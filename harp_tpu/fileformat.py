"""Input formats — Harp L4 (``edu.iu.fileformat``) parity.

Reference parity (SURVEY.md §3.1): Harp jobs use
``MultiFileInputFormat`` (each split = a *list of whole files*, so every
long-running worker gets its file list up front — no record-level
splitting) and ``SingleFileInputFormat`` (each split = exactly one whole
file).  Workers then read their files themselves inside
``mapCollective``; the input format only decides *placement*.

TPU-native design: placement stays a host-side concern — assign whole
files to workers (balanced by byte size, the role YARN's locality-aware
splitter played), have each host read only its workers' files through the
native loader (:mod:`harp_tpu.native.datasource`), then lay shards out for
``WorkerMesh.shard_array``.  Row counts are padded/truncated to equal
per-worker lengths because SPMD sharding needs identical shard shapes —
the analogue of Harp's fixed-size resource arrays.
"""

from __future__ import annotations

import glob as _glob
import os
from typing import Callable, Sequence

import numpy as np

from harp_tpu.native import datasource


def _record_skew(phase: str, work, *, unit: str,
                 padded_total: int | None = None, units=None) -> None:
    """Ingest-side skew record (utils/skew.py): per-shard real rows /
    nonzeros / bytes and the padding fraction, at partition time — host
    arithmetic over arrays the splitter already built.  Lazy import +
    enabled() gate keep the readers zero-cost when telemetry is off."""
    from harp_tpu.utils import skew, telemetry

    if telemetry.enabled():
        skew.record_partition(phase, work, unit=unit,
                              padded_total=padded_total, units=units)


def list_files(pattern_or_dir: str) -> list[str]:
    """Expand a glob pattern or directory into a sorted file list."""
    if os.path.isdir(pattern_or_dir):
        names = [os.path.join(pattern_or_dir, n)
                 for n in sorted(os.listdir(pattern_or_dir))]
        return [p for p in names if os.path.isfile(p)]
    return sorted(_glob.glob(pattern_or_dir))


def multi_file_splits(paths: Sequence[str], num_workers: int,
                      by_size: bool = True) -> list[list[str]]:
    """Assign whole files to workers — ``MultiFileInputFormat`` splits.

    Greedy longest-processing-time balancing on file size (``by_size``),
    else round-robin by position.  Every worker appears in the result
    (possibly with an empty list, as in Harp when files < workers).
    """
    if num_workers <= 0:
        raise ValueError("num_workers must be positive")
    splits: list[list[str]] = [[] for _ in range(num_workers)]
    if by_size:
        loads = [0] * num_workers
        sized = sorted(paths, key=lambda p: -os.path.getsize(p))
        for p in sized:
            w = loads.index(min(loads))
            splits[w].append(p)
            loads[w] += os.path.getsize(p)
        for s in splits:
            s.sort()  # deterministic per-worker order
    else:
        for i, p in enumerate(paths):
            splits[i % num_workers].append(p)
    from harp_tpu.utils import telemetry

    if telemetry.enabled():
        # movable units = whole files: suggest_rebalance can then emit a
        # whole-file plan that schedule.apply_rebalance replays
        units = [[(p, os.path.getsize(p)) for p in s] for s in splits]
        _record_skew("fileformat.multi_file_splits",
                     [sum(sz for _, sz in u) for u in units],
                     unit="bytes", units=units)
    return splits


def single_file_splits(paths: Sequence[str], num_workers: int) -> list[list[str]]:
    """One whole file per split — ``SingleFileInputFormat``.

    Requires ``len(paths) == num_workers`` (Harp launches one mapper per
    file; here worker count is fixed by the mesh, so the counts must agree).
    """
    if len(paths) != num_workers:
        raise ValueError(
            f"SingleFileInputFormat needs exactly one file per worker: "
            f"{len(paths)} files vs {num_workers} workers")
    return [[p] for p in paths]


def _pad_rows(a: np.ndarray, n_rows: int) -> np.ndarray:
    if a.shape[0] == n_rows:
        return a
    pad = np.zeros((n_rows - a.shape[0],) + a.shape[1:], a.dtype)
    return np.concatenate([a, pad], axis=0)


def load_sharded_csv(pattern_or_paths, num_workers: int,
                     loader: Callable[[str], np.ndarray] = datasource.load_csv,
                     pad_value: float = 0.0):
    """Read a multi-file dense dataset into equal per-worker row shards.

    Returns ``(stacked, row_counts)``: ``stacked`` is
    ``[num_workers * rows_pad, cols]`` ready for ``mesh.shard_array``, and
    ``row_counts[w]`` is the number of REAL rows in worker *w*'s shard
    (apps mask the padding — e.g. KMeans weights, SVM sample weights).
    """
    paths = (list_files(pattern_or_paths) if isinstance(pattern_or_paths, str)
             else list(pattern_or_paths))
    if not paths:
        raise FileNotFoundError(f"no input files match {pattern_or_paths!r}")
    splits = multi_file_splits(paths, num_workers)
    # per-file loads ride the shared ingest pipeline (PR 8): files are
    # random-access units, so two reader threads parse file j+1 while
    # file j's rows are being stacked; results come back in submission
    # order, so the per-worker concatenation — and the output — is
    # bit-identical to the old serial loop.  compiles=0 under the
    # warn-mode budget: a loader that silently traces a program would
    # be a relay trap at ingest time.
    flat = [(w, p) for w, files in enumerate(splits) for p in files]
    loaded: list = [None] * len(flat)
    if flat:
        from harp_tpu.ingest import IngestPipeline
        from harp_tpu.utils import telemetry

        with IngestPipeline(lambda j: loader(flat[j][1]), depth=4,
                            read_threads=2,
                            tag="fileformat.load_sharded_csv") as pipe, \
                telemetry.budget(compiles=0, action="warn",
                                 tag="fileformat.load_sharded_csv"):
            for j, arr in enumerate(pipe.stream(len(flat))):
                loaded[j] = arr
    shards: list[np.ndarray] = []
    cols = None
    for w, files in enumerate(splits):
        parts = [loaded[j] for j, (fw, _) in enumerate(flat) if fw == w]
        if parts:
            shard = np.concatenate(parts, axis=0)
            cols = shard.shape[1] if cols is None else cols
        else:
            shard = None
        shards.append(shard)
    if cols is None:
        raise ValueError("all splits empty")
    shards = [s if s is not None else np.zeros((0, cols), np.float32)
              for s in shards]
    counts = np.asarray([s.shape[0] for s in shards], np.int64)
    rows_pad = int(counts.max())
    _record_skew("fileformat.load_sharded_csv", counts, unit="rows",
                 padded_total=num_workers * rows_pad)
    stacked = np.concatenate([_pad_rows(s, rows_pad) for s in shards], axis=0)
    if pad_value != 0.0:
        for w, c in enumerate(counts):
            stacked[w * rows_pad + c: (w + 1) * rows_pad] = pad_value
    return stacked, counts


def load_sharded_triples(pattern_or_paths, num_workers: int):
    """Read multi-file ``u i v`` triple data into equal per-worker shards.

    Returns ``((u, i, v), counts)`` with each array
    ``[num_workers * nnz_pad]``; padding entries have ``u = i = -1`` and
    ``v = 0`` so rating/token kernels can mask them the same way the
    models' partitioners mask internal padding.
    """
    paths = (list_files(pattern_or_paths) if isinstance(pattern_or_paths, str)
             else list(pattern_or_paths))
    if not paths:
        raise FileNotFoundError(f"no input files match {pattern_or_paths!r}")
    splits = multi_file_splits(paths, num_workers)
    per_worker: list[tuple[np.ndarray, np.ndarray, np.ndarray]] = []
    for files in splits:
        if files:
            loaded = [datasource.load_triples(p) for p in files]
            u = np.concatenate([t[0] for t in loaded])
            i = np.concatenate([t[1] for t in loaded])
            v = np.concatenate([t[2] for t in loaded])
        else:
            u = np.zeros(0, np.int32)
            i = np.zeros(0, np.int32)
            v = np.zeros(0, np.float32)
        per_worker.append((u, i, v))
    counts = np.asarray([len(t[0]) for t in per_worker], np.int64)
    nnz_pad = int(counts.max())
    if nnz_pad == 0:
        raise ValueError("all splits empty")
    _record_skew("fileformat.load_sharded_triples", counts,
                 unit="nonzeros", padded_total=num_workers * nnz_pad)

    def pad1(a, fill):
        out = np.full(nnz_pad, fill, a.dtype)
        out[: len(a)] = a
        return out

    u = np.concatenate([pad1(t[0], -1) for t in per_worker])
    i = np.concatenate([pad1(t[1], -1) for t in per_worker])
    v = np.concatenate([pad1(t[2], 0) for t in per_worker])
    return (u, i, v), counts
