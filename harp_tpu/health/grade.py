"""Evidence regression — grade fresh measurements, gate the pruning.

The fourth detector family (see :mod:`harp_tpu.health.sentinel`): a
freshly measured bench row is judged against two baselines —

1. the **committed incumbent** (the latest full-shape TPU row for the
   same config in BENCH_local.jsonl, the same filter as
   ``flip_decision.latest_rows``): relative-tolerance verdict per
   metric family — ``regressed`` / ``improved`` outside the ±10% dead
   band (:data:`REL_TOL`, the flip rule's own margin), ``confirmed``
   inside it;
2. the **perfmodel's prediction** (:mod:`harp_tpu.perfmodel`): the
   magnitude band (``grade.MAGNITUDE_TOL``) and — for flip candidates
   with a measured incumbent — the ranking direction.  Either failing
   yields ``model_invalidated``: the model mis-priced real silicon.

``model_invalidated`` is the verdict ROADMAP autotuning item (3) wants
blocking the next sprint pruning: :func:`model_gate` re-runs the
perfmodel's full self-grade against ALL committed evidence and
``measure_all.py --predicted-top`` REFUSES (fail closed) when it fails
— a model invalidated by fresh silicon evidence cannot prune the sprint
that would re-measure it.  ``measure_on_relay.sh`` runs
``python -m harp_tpu health --grade-model`` right after a sprint lands
new rows, so the verdict is committed evidence, not a scrolled warning.
"""

from __future__ import annotations

import os

from harp_tpu.health import sentinel

#: |ratio - 1| at or below this is "confirmed" — the same 10% margin the
#: flip rule and the perfmodel's ranking dead band use.
REL_TOL = 0.10

#: headline metric resolution order (bench.py UNITS keys + serve qps) —
#: the first key present in a row is its metric family.
METRIC_KEYS = ("iters_per_sec", "updates_per_sec_per_chip",
               "tokens_per_sec_per_chip", "samples_per_sec",
               "vertices_per_sec", "trees_per_sec", "points_per_sec",
               "iters_per_sec_ex_gen", "qps")


def headline_metric(row: dict) -> tuple[str | None, float | None]:
    for k in METRIC_KEYS:
        v = row.get(k)
        if v is not None:
            try:
                return k, float(v)
            except (TypeError, ValueError):
                return None, None
    return None, None


def grade_bench_row(row: dict, repo: str, *, bench: dict | None = None,
                    topo=None) -> dict | None:
    """Judge one freshly measured bench row; register and return the
    ``evidence_regression`` finding, or None when there is nothing to
    grade against (no incumbent AND no model — fail-closed rows are the
    flip gate's job, not the grader's).

    Smoke / error / CPU-sim rows are never graded (the same
    CPU-inversion filter as ``flip_decision.latest_rows``).
    """
    from harp_tpu.perfmodel import grade as G
    from harp_tpu.perfmodel import model as M

    cfg = row.get("config")
    if (not cfg or row.get("smoke") or "error" in row
            or row.get("backend") == "cpu"):
        return None
    metric, value = headline_metric(row)
    if metric is None or not value or value <= 0:
        return None
    if bench is None:
        bench = G.latest_tpu_rows(os.path.join(repo, "BENCH_local.jsonl"))

    finding: dict = {"config": cfg, "metric": metric,
                     "measured": round(value, 4)}
    verdict = None

    # 1. vs the committed incumbent (same config, same metric family)
    inc = bench.get(cfg)
    iv = inc.get(metric) if inc is not None else None
    if iv:
        ratio = value / float(iv)
        finding["incumbent"] = round(float(iv), 4)
        finding["ratio_vs_incumbent"] = round(ratio, 4)
        verdict = ("regressed" if ratio < 1.0 - REL_TOL
                   else "improved" if ratio > 1.0 + REL_TOL
                   else "confirmed")

    # 2. vs the model: magnitude band + ranking direction
    if cfg in M.CONFIG_MODELS:
        if topo is None:
            from harp_tpu.plan.topology import single_chip

            topo = single_chip()  # graded evidence is 1x v5e
        p = M.price(cfg, row, topo)
        factor = max(p.predicted_rate / value, value / p.predicted_rate)
        finding["predicted"] = round(p.predicted_rate, 4)
        finding["model_factor"] = round(factor, 2)
        if factor > G.MAGNITUDE_TOL:
            verdict = "model_invalidated"
        pair = G.FAMILY_PAIRS.get(cfg)
        if pair is not None and verdict != "model_invalidated":
            inc_name, pmetric, fb = pair
            irow = bench.get(inc_name)
            miv = G._metric_value(irow, pmetric, fb) if irow else None
            mcv = G._metric_value(row, pmetric, fb)
            if miv and mcv and inc_name in M.CONFIG_MODELS:
                pi = M.price(inc_name, irow, topo)
                measured = mcv / miv
                predicted = pi.predicted_s / p.predicted_s
                finding["measured_speedup"] = round(measured, 4)
                finding["predicted_speedup"] = round(predicted, 4)
                if (abs(measured - 1.0) > G.DEAD_BAND
                        and (measured > 1.0) != (predicted > 1.0)):
                    verdict = "model_invalidated"

    if verdict is None:
        return None
    sev = ("warn" if verdict in ("regressed", "model_invalidated")
           else "info")
    out = sentinel.monitor.upsert("evidence_regression", cfg,
                                  severity=sev)
    out.update(finding)
    out["verdict"] = verdict
    return sentinel._public(out)


def model_gate(repo: str) -> tuple[bool, dict]:
    """ROADMAP autotuning item (3), closed: re-run the perfmodel's full
    self-grade (``perfmodel.grade.grade`` — flip-pair directions, sweep
    rank correlation, magnitude band, all against the COMMITTED
    evidence files, which include any rows a sprint just landed) and
    turn the outcome into an ``evidence_regression`` health finding.

    Returns ``(ok, finding)``.  ``measure_all.py --predicted-top``
    calls this as its preflight and REFUSES to prune when ``ok`` is
    False — the gate re-runs the grade every time, so the refusal lifts
    exactly when the model has been re-calibrated against the evidence
    that invalidated it (no manual ack file to go stale).
    """
    from harp_tpu.perfmodel import grade as G

    report = G.grade(repo)
    ok = bool(report["ok"])
    verdict = "confirmed" if ok else "model_invalidated"
    row = sentinel.monitor.upsert("evidence_regression",
                                  "perfmodel.grade",
                                  severity="info" if ok else "page")
    row.update({
        "tag": "perfmodel.grade", "verdict": verdict,
        "failures": len(report["failures"]),
        # enough detail to act on without re-running (--grade has the
        # full term breakdowns); bounded so the row stays one line
        "detail": [f["what"] for f in report["failures"]][:4],
    })
    return ok, sentinel._public(row)
