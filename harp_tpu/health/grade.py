"""Evidence regression — grade fresh measurements, gate the pruning.

The fourth detector family (see :mod:`harp_tpu.health.sentinel`): a
freshly measured bench row is judged against two baselines —

1. the **committed incumbent** (the latest full-shape TPU row for the
   same config in BENCH_local.jsonl, the same filter as
   ``flip_decision.latest_rows``): relative-tolerance verdict per
   metric family — ``regressed`` / ``improved`` outside the ±10% dead
   band (:data:`REL_TOL`, the flip rule's own margin), ``confirmed``
   inside it;
2. the **perfmodel's prediction** (:mod:`harp_tpu.perfmodel`): the
   magnitude band (``grade.MAGNITUDE_TOL``) and — for flip candidates
   with a measured incumbent — the ranking direction.  Either failing
   yields ``model_invalidated``: the model mis-priced real silicon.

``model_invalidated`` is the verdict ROADMAP autotuning item (3) wants
blocking the next sprint pruning: :func:`model_gate` re-runs the
perfmodel's full self-grade against ALL committed evidence and
``measure_all.py --predicted-top`` REFUSES (fail closed) when it fails
— a model invalidated by fresh silicon evidence cannot prune the sprint
that would re-measure it.  ``measure_on_relay.sh`` runs
``python -m harp_tpu health --grade-model`` right after a sprint lands
new rows, so the verdict is committed evidence, not a scrolled warning.
"""

from __future__ import annotations

import os

from harp_tpu.health import sentinel

#: |ratio - 1| at or below this is "confirmed" — the same 10% margin the
#: flip rule and the perfmodel's ranking dead band use.
REL_TOL = 0.10

#: headline metric resolution order (bench.py UNITS keys + serve qps) —
#: the first key present in a row is its metric family.
METRIC_KEYS = ("iters_per_sec", "updates_per_sec_per_chip",
               "tokens_per_sec_per_chip", "samples_per_sec",
               "vertices_per_sec", "trees_per_sec", "points_per_sec",
               "iters_per_sec_ex_gen", "qps")


def headline_metric(row: dict) -> tuple[str | None, float | None]:
    for k in METRIC_KEYS:
        v = row.get(k)
        if v is not None:
            try:
                return k, float(v)
            except (TypeError, ValueError):
                return None, None
    return None, None


def grade_bench_row(row: dict, repo: str, *, bench: dict | None = None,
                    topo=None) -> dict | None:
    """Judge one freshly measured bench row; register and return the
    ``evidence_regression`` finding, or None when there is nothing to
    grade against (no incumbent AND no model — fail-closed rows are the
    flip gate's job, not the grader's).

    Smoke / error / CPU-sim rows are never graded (the same
    CPU-inversion filter as ``flip_decision.latest_rows``).
    """
    from harp_tpu.perfmodel import grade as G
    from harp_tpu.perfmodel import model as M

    cfg = row.get("config")
    if (not cfg or row.get("smoke") or "error" in row
            or row.get("backend") == "cpu"):
        return None
    metric, value = headline_metric(row)
    if metric is None or not value or value <= 0:
        return None
    if bench is None:
        bench = G.latest_tpu_rows(os.path.join(repo, "BENCH_local.jsonl"))

    finding: dict = {"config": cfg, "metric": metric,
                     "measured": round(value, 4)}
    verdict = None

    # 1. vs the committed incumbent (same config, same metric family)
    inc = bench.get(cfg)
    iv = inc.get(metric) if inc is not None else None
    if iv:
        ratio = value / float(iv)
        finding["incumbent"] = round(float(iv), 4)
        finding["ratio_vs_incumbent"] = round(ratio, 4)
        verdict = ("regressed" if ratio < 1.0 - REL_TOL
                   else "improved" if ratio > 1.0 + REL_TOL
                   else "confirmed")

    # 2. vs the model: magnitude band + ranking direction
    if cfg in M.CONFIG_MODELS:
        if topo is None:
            from harp_tpu.plan.topology import single_chip

            topo = single_chip()  # graded evidence is 1x v5e
        p = M.price(cfg, row, topo)
        factor = max(p.predicted_rate / value, value / p.predicted_rate)
        finding["predicted"] = round(p.predicted_rate, 4)
        finding["model_factor"] = round(factor, 2)
        if factor > G.MAGNITUDE_TOL:
            verdict = "model_invalidated"
        pair = G.FAMILY_PAIRS.get(cfg)
        if pair is not None and verdict != "model_invalidated":
            inc_name, pmetric, fb = pair
            irow = bench.get(inc_name)
            miv = G._metric_value(irow, pmetric, fb) if irow else None
            mcv = G._metric_value(row, pmetric, fb)
            if miv and mcv and inc_name in M.CONFIG_MODELS:
                pi = M.price(inc_name, irow, topo)
                measured = mcv / miv
                predicted = pi.predicted_s / p.predicted_s
                finding["measured_speedup"] = round(measured, 4)
                finding["predicted_speedup"] = round(predicted, 4)
                if (abs(measured - 1.0) > G.DEAD_BAND
                        and (measured > 1.0) != (predicted > 1.0)):
                    verdict = "model_invalidated"

    if verdict is None:
        return None
    sev = ("warn" if verdict in ("regressed", "model_invalidated")
           else "info")
    out = sentinel.monitor.upsert("evidence_regression", cfg,
                                  severity=sev)
    out.update(finding)
    out["verdict"] = verdict
    return sentinel._public(out)


#: bucket-share drift (absolute points of the wall) at or above which a
#: fresh profile row's attribution is a ``profile_drift`` warn — the
#: same 10-point margin as :data:`REL_TOL`, applied to shares.
PROFILE_SHARE_DRIFT = 0.10


def committed_profiles(repo: str) -> dict[str, dict]:
    """Latest committed ``kind:"profile"`` row per app
    (PROFILE_attrib.jsonl — the PR-16 attribution baseline)."""
    import json

    out: dict[str, dict] = {}
    path = os.path.join(repo, "PROFILE_attrib.jsonl")
    try:
        lines = open(path).read().splitlines()
    except OSError:
        return out
    for line in lines:
        try:
            row = json.loads(line)
        except ValueError:
            continue
        if isinstance(row, dict) and row.get("kind") == "profile" \
                and row.get("app"):
            out[row["app"]] = row
    return out


def _bucket_shares(row: dict) -> dict[str, float] | None:
    wall = row.get("wall_s")
    terms = row.get("terms")
    if not isinstance(terms, dict) or not wall:
        return None
    try:
        return {k: float(v) / float(wall) for k, v in terms.items()}
    except (TypeError, ValueError, ZeroDivisionError):
        return None


def grade_profile_row(row: dict, repo: str, *,
                      committed: dict | None = None) -> dict | None:
    """Judge one fresh ``kind:"profile"`` attribution row against the
    committed baseline for its app; register and return a
    ``profile_drift`` finding when the mechanism mix moved, or None
    when there is no baseline or nothing drifted.

    Drift = the ``bound`` (largest bucket) flipped, or any bucket's
    share of the wall moved more than :data:`PROFILE_SHARE_DRIFT`
    points.  Either means the perfmodel terms calibrated against the
    old attribution are describing a program this repo no longer runs.
    Unreconciled rows are never graded (invariant 15 already fails
    them — grading a broken capture would attribute the breakage).
    """
    app = row.get("app")
    if not app or row.get("reconciled") is not True:
        return None
    if committed is None:
        committed = committed_profiles(repo)
    base = committed.get(app)
    if base is None or base is row:
        return None
    shares, base_shares = _bucket_shares(row), _bucket_shares(base)
    if shares is None or base_shares is None:
        return None
    deltas = {k: shares.get(k, 0.0) - base_shares.get(k, 0.0)
              for k in set(shares) | set(base_shares)}
    worst = max(deltas, key=lambda k: abs(deltas[k]))
    bound_flipped = (row.get("bound") != base.get("bound"))
    if not bound_flipped and abs(deltas[worst]) <= PROFILE_SHARE_DRIFT:
        return None
    out = sentinel.monitor.upsert("profile_drift", app, severity="warn")
    out.update({
        "app": app, "bound": row.get("bound"),
        "committed_bound": base.get("bound"),
        "bound_flipped": bound_flipped,
        "worst_bucket": worst.removesuffix("_s"),
        "share_delta": round(abs(deltas[worst]), 4),
        "wall_s": row.get("wall_s"),
        "committed_wall_s": base.get("wall_s"),
    })
    return sentinel._public(out)


def model_gate(repo: str) -> tuple[bool, dict]:
    """ROADMAP autotuning item (3), closed: re-run the perfmodel's full
    self-grade (``perfmodel.grade.grade`` — flip-pair directions, sweep
    rank correlation, magnitude band, all against the COMMITTED
    evidence files, which include any rows a sprint just landed) and
    turn the outcome into an ``evidence_regression`` health finding.

    Returns ``(ok, finding)``.  ``measure_all.py --predicted-top``
    calls this as its preflight and REFUSES to prune when ``ok`` is
    False — the gate re-runs the grade every time, so the refusal lifts
    exactly when the model has been re-calibrated against the evidence
    that invalidated it (no manual ack file to go stale).
    """
    from harp_tpu.perfmodel import grade as G

    report = G.grade(repo)
    ok = bool(report["ok"])
    verdict = "confirmed" if ok else "model_invalidated"
    row = sentinel.monitor.upsert("evidence_regression",
                                  "perfmodel.grade",
                                  severity="info" if ok else "page")
    row.update({
        "tag": "perfmodel.grade", "verdict": verdict,
        "failures": len(report["failures"]),
        # enough detail to act on without re-running (--grade has the
        # full term breakdowns); bounded so the row stays one line
        "detail": [f["what"] for f in report["failures"]][:4],
    })
    return ok, sentinel._public(row)
