"""Health sentinel — the sixth, *derived* telemetry spine (see
:mod:`harp_tpu.health.sentinel` for the design docstring).

This package import stays light (vocabularies + the sentinel; no jax,
no perfmodel): the skew/flightrec hooks import it lazily on their hot
paths.  The evidence-regression grader (:mod:`harp_tpu.health.grade`)
pulls the perfmodel import cascade, so it is NOT imported here — the
CLI and the measure_all pruning gate import it directly.
"""

from harp_tpu.health.sentinel import (  # noqa: F401
    DETECTORS, SEVERITIES, VERDICTS, FAST_BURN_MIN, PAGE_BURN,
    SLO_ERROR_BUDGET, SLOW_BURN_MIN, TRIGGER_SUPERSTEPS,
    WASTED_FRAC_TRIGGER, HealthMonitor, SLOBurn, export_jsonl, monitor,
    reset, summarize_rows)
