"""``python -m harp_tpu health`` — the sentinel's offline half.

Two modes, both CPU-only (like the lint/plan/predict CLIs, a health
check must never touch — or hang on — the relay):

- ``health run.jsonl [--json]``: read a JSONL file (a telemetry export,
  a sprint's BENCH output, or a committed evidence file), summarize its
  ``kind:"health"`` rows, and GRADE the freshest bench row per config
  against the committed incumbents + the perfmodel
  (:func:`harp_tpu.health.grade.grade_bench_row`; ``--no-grade-bench``
  skips).  Exit 0 healthy, 1 actionable findings (severity warn/page or
  a regressed/model_invalidated verdict), 2 unreadable input.
- ``health --grade-model``: run the fail-closed pruning gate
  (:func:`harp_tpu.health.grade.model_gate`) and print ONE
  provenance-stamped ``kind:"health"`` row — ``measure_on_relay.sh``
  tees this into the evidence file right after a sprint lands new rows
  (ROADMAP autotuning item 3).  Exit 0 confirmed, 1 model_invalidated.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from harp_tpu.health import sentinel


def _stamped(row: dict) -> dict:
    from harp_tpu.utils.flightrec import provenance_stamp

    return {**row, **provenance_stamp()}


def _render(rows: list[dict], summary: dict) -> str:
    lines = ["== harp-tpu health =="]
    lines.append(
        f"{summary['findings']} finding(s), "
        f"{summary['actionable']} actionable"
        + (f", worst severity {summary['worst_severity']}"
           if summary.get("worst_severity") else ""))
    for r in rows:
        det, sev = r.get("detector", "?"), r.get("severity", "?")
        who = r.get("tag") or r.get("phase") or r.get("config") or "?"
        bits = []
        if det == "slo_burn":
            bits.append(f"burn fast {r.get('fast_burn')} / slow "
                        f"{r.get('slow_burn')}; offered "
                        f"{r.get('offered')} = {r.get('served')} served"
                        f" + {r.get('shed')} shed + {r.get('failed')} "
                        f"failed ({r.get('deadline_missed')} missed "
                        "deadline)")
        elif det == "skew_trigger":
            plan = r.get("plan") or {}
            bits.append(f"wasted_frac {r.get('wasted_frac')} for "
                        f"{r.get('consecutive')} superstep(s); inline "
                        f"plan: {len(plan.get('moves') or [])} move(s), "
                        f"ratio {plan.get('ratio_before')} -> "
                        f"{plan.get('ratio_after')}")
        elif det == "budget_drift":
            bits.append(f"{r.get('violations')} violation(s); worst: "
                        f"{r.get('worst')}")
        elif det == "evidence_regression":
            bits.append(f"verdict {r.get('verdict')}"
                        + (f" (measured {r.get('measured')} vs "
                           f"incumbent {r.get('incumbent')})"
                           if r.get("incumbent") is not None else "")
                        + (f" [model factor {r.get('model_factor')}x]"
                           if r.get("model_factor") is not None else ""))
        elif det == "profile_drift":
            who = r.get("app") or who
            bits.append(
                ("bound FLIPPED "
                 f"{r.get('committed_bound')} -> {r.get('bound')}; "
                 if r.get("bound_flipped") else
                 f"bound {r.get('bound')} unchanged; ")
                + f"worst bucket {r.get('worst_bucket')} moved "
                  f"{r.get('share_delta')} of the wall vs committed "
                  "attribution")
        lines.append(f"  [{sev:<4s}] {det:<20s} {who}: "
                     + "; ".join(bits))
    if not rows:
        lines.append("  no findings — healthy")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m harp_tpu health",
        description="health sentinel, offline: summarize kind:'health' "
                    "rows, grade fresh bench rows against the committed "
                    "incumbents + the perfmodel, and run the "
                    "fail-closed --predicted-top model gate")
    p.add_argument("jsonl", nargs="?", default=None,
                   help="JSONL to check (telemetry export / sprint "
                        "output / committed evidence file)")
    p.add_argument("--json", action="store_true",
                   help="print one machine-readable summary line")
    p.add_argument("--grade-model", action="store_true",
                   help="run the perfmodel self-grade gate and print "
                        "one kind:'health' row (exit 1 on "
                        "model_invalidated)")
    p.add_argument("--no-grade-bench", action="store_true",
                   help="only summarize health rows; skip grading the "
                        "file's bench rows against the incumbents")
    p.add_argument("--repo", default=None,
                   help="repo root for the committed evidence files "
                        "(default: cwd)")
    args = p.parse_args(argv)

    from harp_tpu.analysis.cli import _force_cpu_backend

    _force_cpu_backend()
    repo = args.repo or os.getcwd()

    if args.grade_model:
        from harp_tpu.health import grade as HG

        ok, row = HG.model_gate(repo)
        print(json.dumps(_stamped(row)), flush=True)
        if not ok:
            print("health: perfmodel INVALIDATED by committed evidence "
                  "— measure_all --predicted-top will refuse until the "
                  "model is re-calibrated (python -m harp_tpu predict "
                  "--grade for the term breakdowns)", file=sys.stderr)
            return 1
        return 0

    if not args.jsonl:
        p.error("need a JSONL file (or --grade-model)")
    try:
        lines = open(args.jsonl).read().splitlines()
    except OSError as e:
        print(f"health: cannot read {args.jsonl}: {e}", file=sys.stderr)
        return 2

    health_rows: list[dict] = []
    latest_bench: dict[str, dict] = {}
    latest_profile: dict[str, dict] = {}
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            row = json.loads(line)
        except ValueError:
            continue  # check_jsonl owns parseability; summarize the rest
        if not isinstance(row, dict):
            continue
        if row.get("kind") == "health":
            health_rows.append(row)
        elif row.get("kind") == "profile" and row.get("app"):
            latest_profile[row["app"]] = row  # last row per app wins
        elif "config" in row:
            latest_bench[row["config"]] = row  # last row per config wins

    graded: list[dict] = []
    if (latest_bench or latest_profile) and not args.no_grade_bench:
        from harp_tpu.health import grade as HG

        for cfg in sorted(latest_bench):
            f = HG.grade_bench_row(latest_bench[cfg], repo)
            if f is not None:
                graded.append(f)
        committed = HG.committed_profiles(repo) if latest_profile else {}
        for app in sorted(latest_profile):
            f = HG.grade_profile_row(latest_profile[app], repo,
                                     committed=committed)
            if f is not None:
                graded.append(f)

    rows = health_rows + graded
    summary = sentinel.summarize_rows(rows)
    summary["graded_configs"] = len(graded)
    if args.json:
        from harp_tpu.utils.metrics import benchmark_json

        print(benchmark_json("health", summary))
    else:
        print(_render(rows, summary))
    return 1 if summary["actionable"] else 0


if __name__ == "__main__":  # pragma: no cover - python -m harp_tpu health
    sys.exit(main())
