"""Streaming health sentinel — SLO burn, skew trigger, budget drift.

Reference parity (SURVEY.md §6): Harp has no monitoring layer at all —
degradation is visible only when a human greps container logs after the
job.  harp-tpu's five telemetry spines (CommLedger/SpanTracer, flight
recorder, SkewLedger, ReqTracer) record everything but *watch* nothing.
HARP (arXiv:2509.24859, PAPERS.md) makes the modern case that
orchestration decisions — rebalance, degrade, re-plan — should be driven
by continuously monitored runtime signals, not post-hoc reports.  This
module is that monitoring layer: the sixth, **derived** spine.  It
consumes the existing spines at runtime and emits provenance-stamped
``kind:"health"`` rows (scripts/check_jsonl.py invariant 13; frozen
:data:`DETECTORS` / :data:`SEVERITIES` / :data:`VERDICTS` vocabularies,
sync-pinned by tests/test_check_jsonl.py).

Four detector families, each grounded in a landed mechanism:

- **SLO burn** (:class:`SLOBurn`) — multi-window error-budget burn-rate
  tracking (the classic fast-window + slow-window pattern: the fast
  window catches cliffs quickly, the slow window filters blips) over the
  serve plane's request outcomes — the PR-10 degraded-mode events
  (shed / failed / deadline-missed) and optionally a latency objective.
  Lives on the :class:`~harp_tpu.serve.server.ContinuousRunner`
  (``runner.health``), surfaces on the TCP ``stats`` line and the
  ``benchmark_sustained`` row (``health_*`` fields); breach rows carry
  the most recent bad requests' ReqTracer trace ids (``recent_reqs``)
  so a page resolves to per-request timelines.
- **skew trigger** (:meth:`HealthMonitor.observe_skew`) — when a phase's
  SkewLedger ``wasted_frac`` exceeds :data:`WASTED_FRAC_TRIGGER` for
  :data:`TRIGGER_SUPERSTEPS` consecutive records, the finding carries
  the ``suggest_rebalance()`` plan INLINE.  Advisory-only in this PR —
  but the payload is exactly ``schedule.apply_rebalance``-shaped (and
  tested as such), so it is the hook the ROADMAP elastic-execution item
  will later act on mid-run.
- **budget drift** (:meth:`HealthMonitor.observe_budget`) — flight-
  recorder WARN-mode budget violations (``flightrec.budget`` /
  ``SteadyState``, the bench/production action) aggregate into one row
  per site (violation count + worst offender) instead of scrolling past
  as RuntimeWarnings — a relay trap that fires mid-sprint finally
  leaves committed evidence.
- **evidence regression** (:mod:`harp_tpu.health.grade`) — fresh bench
  rows judged against the committed incumbent and the perfmodel's
  prediction; ``model_invalidated`` is the verdict that fails the next
  ``measure_all --predicted-top`` pruning closed (ROADMAP autotuning
  item 3).

Zero-cost when disabled (the PR-3 contract): every observe entry point
returns before touching state unless telemetry is enabled
(``HARP_TELEMETRY=1`` / :func:`harp_tpu.utils.telemetry.enable`), the
module never imports jax and never touches a traced program, so the
flagship budgets (1 dispatch / 1 readback / 0 steady compiles) are
bit-identical with the sentinel armed or telemetry off — pinned in
tests/test_health.py.  Collection is host-side O(1) per event while on.
"""

from __future__ import annotations

import json
from typing import Any

from harp_tpu.utils import telemetry

#: frozen detector vocabulary — check_jsonl KNOWN_HEALTH_DETECTORS
#: mirrors this tuple (drift fails tier-1).  ``profile_drift`` (PR 16)
#: grades fresh ``kind:"profile"`` attribution rows against the
#: committed PROFILE_attrib.jsonl: a flipped ``bound`` or a bucket
#: share moving more than :data:`harp_tpu.health.grade.
#: PROFILE_SHARE_DRIFT` points is a warn — the mechanism mix changed,
#: so every perfmodel term calibrated against the old mix is suspect.
#: ``memory_pressure`` (PR 19) rides the memrec spine: the run's peak
#: HBM watermark eating into the topology's declared capacity past
#: :data:`HEADROOM_WARN_FRAC` remaining, or the watermark drifting more
#: than :data:`MEM_DRIFT_FRAC` above a committed baseline peak, warns —
#: the multi-tenant admission controller's "does tenant N fit" signal.
DETECTORS = ("slo_burn", "skew_trigger", "budget_drift",
             "evidence_regression", "profile_drift", "memory_pressure")

#: frozen severity vocabulary, mildest first.  ``info`` = recorded, no
#: action; ``warn`` = degradation that needs a look; ``page`` = the SLO
#: is burning fast enough to exhaust its error budget within the window.
SEVERITIES = ("info", "warn", "page")

#: frozen evidence-regression verdicts (see harp_tpu.health.grade):
#: ``model_invalidated`` is the one that blocks --predicted-top pruning.
VERDICTS = ("confirmed", "improved", "regressed", "model_invalidated")

_SEV_RANK = {s: i for i, s in enumerate(SEVERITIES)}

# -- SLO burn thresholds ------------------------------------------------------

#: default error budget: the allowed fraction of offered requests that
#: may go bad (shed / hard-failed / deadline-missed / over the latency
#: objective) — 1%, the serve plane's degraded-mode tolerance.
SLO_ERROR_BUDGET = 0.01

#: burn-rate floor on the FAST window (the newest sub-window).  Burn
#: rate = bad_fraction / error_budget; >= 2 means the newest sub-window
#: alone is spending budget at least twice as fast as sustainable.
FAST_BURN_MIN = 2.0

#: burn-rate floor on the SLOW window (the whole ring).  Both floors
#: must be crossed to breach — the classic multi-window rule: the fast
#: window alone pages on blips, the slow window alone pages too late.
SLOW_BURN_MIN = 1.0

#: slow-window burn at or above this escalates the breach to ``page``
#: (budget exhausted ~6x faster than sustainable).
PAGE_BURN = 6.0

# -- skew trigger thresholds --------------------------------------------------

#: ``wasted_frac`` (SkewLedger imbalance model: the fraction of total
#: chip-time idle-waiting at the superstep barrier) at or above this is
#: a trigger-eligible superstep.
WASTED_FRAC_TRIGGER = 0.25

#: consecutive trigger-eligible records of one phase before the finding
#: fires (a single skewed superstep is noise; K in a row is a workload).
TRIGGER_SUPERSTEPS = 3

# -- memory pressure thresholds ----------------------------------------------

#: remaining-HBM fraction below which the memrec watermark is a warn:
#: a run whose peak leaves <10% headroom has no room for a second
#: tenant's executables, a donated depth-2 pipeline's second buffer, or
#: a restage-after-shrink — the admission margin, not an OOM predictor.
HEADROOM_WARN_FRAC = 0.10

#: fractional growth of the peak watermark over a committed baseline
#: peak at or above which memory_pressure warns (the profile_drift
#: analogue for bytes: the footprint mix changed, re-price admission).
MEM_DRIFT_FRAC = 0.10


class HealthMonitor:
    """The findings ledger — one upserted row per (detector, subject).

    Rows are plain dicts mutated in place as a run progresses, so the
    exported row always carries the run's FINAL cumulative counts and
    reconciles exactly with the invariant-9/11 ledgers (the acceptance
    pin in tests/test_health.py).  ``mark()``/``since()`` let a bench
    delimit "findings new to this run" without resetting the monitor
    (bench.py's monotone-counter contract).
    """

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        self._rows: dict[Any, dict] = {}
        self._skew: dict[str, dict] = {}
        self._seq = 0

    # -- row lifecycle ------------------------------------------------------
    def mark(self) -> int:
        """Sequence watermark: findings created after this mark are
        "new" to the caller's run (see :meth:`since`)."""
        return self._seq

    def since(self, mark: int) -> list[dict]:
        return [r for r in self.findings() if r["_seq"] > mark]

    def upsert(self, detector: str, key: Any, *,
               severity: str = "warn") -> dict:
        """Get-or-create the (detector, key) row; severity only ever
        escalates (a page never demotes back to warn)."""
        if detector not in DETECTORS:
            raise ValueError(f"detector {detector!r} not in {DETECTORS}")
        if severity not in SEVERITIES:
            raise ValueError(f"severity {severity!r} not in {SEVERITIES}")
        k = (detector, key)
        row = self._rows.get(k)
        if row is None:
            self._seq += 1
            row = {"kind": "health", "detector": detector,
                   "severity": severity, "_seq": self._seq}
            self._rows[k] = row
            # the superstep timeline's health mark (PR 18) — one mark
            # per NEW finding only (updates mutate the row in place)
            from harp_tpu.utils import steptrace

            if steptrace.tracer._run is not None:
                steptrace.tracer.on_health(detector, key)
        elif _SEV_RANK[severity] > _SEV_RANK[row["severity"]]:
            row["severity"] = severity
        return row

    def findings(self) -> list[dict]:
        """Every finding, oldest first (``_seq`` retained for
        :meth:`since`; export strips private fields)."""
        return sorted(self._rows.values(), key=lambda r: r["_seq"])

    # -- skew trigger -------------------------------------------------------
    def observe_skew(self, phase: str, ledger) -> None:
        """One SkewLedger record for ``phase`` landed (the module-level
        ``skew.record_execution``/``record_partition`` hooks call this).
        Fires after :data:`TRIGGER_SUPERSTEPS` consecutive records with
        ``wasted_frac >= WASTED_FRAC_TRIGGER``, carrying the
        ``suggest_rebalance`` plan inline; latched until the phase
        recovers below the threshold (no per-superstep re-fire spam)."""
        if not telemetry.enabled():
            return
        rec = ledger._phases.get(phase)
        if rec is None:
            return
        from harp_tpu.utils.skew import SkewLedger

        imb = SkewLedger._imbalance(rec)
        wf = imb.get("wasted_frac")
        st = self._skew.setdefault(
            phase, {"consec": 0, "supersteps": 0, "latched": False,
                    "consumed": False})
        st["supersteps"] += 1
        if wf is None or wf < WASTED_FRAC_TRIGGER:
            st["consec"] = 0
            st["latched"] = False
            # the latch release re-arms the handshake: a LATER re-fire
            # hands a fresh plan to consume_skew_trigger
            st["consumed"] = False
            return
        st["consec"] += 1
        if st["consec"] < TRIGGER_SUPERSTEPS or st["latched"]:
            return
        st["latched"] = True
        row = self.upsert("skew_trigger", phase, severity="warn")
        row.update({
            "phase": phase, "wasted_frac": wf,
            "max_mean_ratio": imb.get("max_mean_ratio"),
            "supersteps": st["supersteps"],
            "consecutive": st["consec"],
            # the elastic-execution handoff: apply_rebalance-shaped;
            # PR 15's drivers consume it between supersteps via
            # :meth:`consume_skew_trigger` (harp_tpu.elastic replays it
            # through schedule.apply_rebalance)
            "plan": ledger.suggest_rebalance(phase),
        })

    def consume_skew_trigger(self, phase: str) -> dict | None:
        """The sentinel↔driver handshake (PR 15): hand the latched
        ``skew_trigger`` finding for ``phase`` to the elastic driver
        EXACTLY ONCE.

        Returns the finding row (inline ``plan`` included) the first
        time a driver asks after the trigger fired; every later call
        returns None until the phase recovers below the threshold (the
        latch release) and a NEW trigger fires — so one fired plan can
        never be applied twice, and a still-skewed phase cannot spam
        re-application of a stale plan.  No-op (None) while telemetry
        is off: the zero-cost contract extends to the acting half.
        """
        if not telemetry.enabled():
            return None
        st = self._skew.get(phase)
        if st is None or not st.get("latched") or st.get("consumed"):
            return None
        st["consumed"] = True
        row = self._rows.get(("skew_trigger", phase))
        if row is not None:
            row["consumed"] = True  # visible in the exported evidence
        from harp_tpu.utils import steptrace

        if steptrace.tracer._run is not None:
            # actuation mark (PR 18): the handshake firing lands on the
            # superstep timeline next to the rebalance it triggers
            steptrace.tracer.on_skew_consume(phase)
        return row

    # -- budget drift -------------------------------------------------------
    def observe_budget(self, tag: str,
                       over: list[tuple[str, Any, Any]]) -> None:
        """One WARN-mode flight-budget violation at ``tag`` (flightrec
        calls this next to its RuntimeWarning).  ``over`` is the
        violation list as (counter, spent, bound) triples; the row keeps
        the per-site count and the worst offender by overspend ratio."""
        if not telemetry.enabled():
            return
        row = self.upsert("budget_drift", tag, severity="warn")
        row["tag"] = tag
        row["violations"] = row.get("violations", 0) + 1

        def ratio(t):
            name, spent, bound = t
            return (float(spent) - float(bound)) / max(abs(float(bound)),
                                                       1.0)

        worst = max(over, key=ratio)
        if ratio(worst) > row.get("_worst_ratio", float("-inf")):
            row["_worst_ratio"] = ratio(worst)
            row["worst"] = (f"{worst[0]} used {worst[1]} > "
                            f"budget {worst[2]}")

    # -- memory pressure ----------------------------------------------------
    def observe_memory(self, tag: str, peak_bytes: int, hbm_bytes: int,
                       *, baseline_peak: int | None = None) -> None:
        """One memrec watermark observation at ``tag`` (memrec fires
        this the first time a run's peak crosses the headroom line;
        graders pass ``baseline_peak`` to check drift against committed
        evidence).  Warns when remaining headroom drops below
        :data:`HEADROOM_WARN_FRAC` or the peak grew more than
        :data:`MEM_DRIFT_FRAC` over the baseline."""
        if not telemetry.enabled():
            return
        if hbm_bytes <= 0:
            return
        headroom = max(0.0, 1.0 - peak_bytes / hbm_bytes)
        drift = (None if not baseline_peak
                 else (peak_bytes - baseline_peak) / baseline_peak)
        pressed = headroom < HEADROOM_WARN_FRAC
        drifted = drift is not None and drift >= MEM_DRIFT_FRAC
        if not (pressed or drifted):
            return
        row = self.upsert("memory_pressure", tag, severity="warn")
        row["tag"] = tag
        row["peak_hbm_bytes"] = int(peak_bytes)
        row["hbm_bytes"] = int(hbm_bytes)
        row["headroom_frac"] = round(headroom, 6)
        if drift is not None:
            row["peak_drift_frac"] = round(drift, 6)

    # -- reading / export ---------------------------------------------------
    def summary(self) -> dict:
        """Machine summary for the report's ``health`` section."""
        rows = [_public(r) for r in self.findings()]
        return summarize_rows(rows) | {"rows": rows}

    def export_jsonl(self, fh, stamp: dict | None = None) -> None:
        """One provenance-stamped row per finding (``kind: "health"``)
        — the shape scripts/check_jsonl.py invariant 13 validates."""
        for row in self.findings():
            fh.write(json.dumps({**_public(row), **(stamp or {})}) + "\n")


def _public(row: dict) -> dict:
    return {k: v for k, v in row.items() if not k.startswith("_")}


def summarize_rows(rows: list[dict]) -> dict:
    """Summarize loaded ``kind:"health"`` rows (CLI + report core).

    ``actionable`` counts findings a clean run must not have: severity
    warn/page, or an evidence verdict in {regressed, model_invalidated}
    — the health CLI's exit-1 condition.
    """
    by_det: dict[str, int] = {}
    worst = None
    actionable = 0
    for r in rows:
        det = r.get("detector", "?")
        by_det[det] = by_det.get(det, 0) + 1
        sev = r.get("severity")
        if sev in _SEV_RANK and (worst is None
                                 or _SEV_RANK[sev] > _SEV_RANK[worst]):
            worst = sev
        if sev in ("warn", "page") or r.get("verdict") in (
                "regressed", "model_invalidated"):
            actionable += 1
    return {"findings": len(rows), "by_detector": by_det,
            "worst_severity": worst, "actionable": actionable}


# ---------------------------------------------------------------------------
# SLO burn
# ---------------------------------------------------------------------------

class SLOBurn:
    """Multi-window burn-rate tracking over one serving plane's outcomes.

    Error-budget semantics: of the requests offered in a window, at most
    ``error_budget`` may go *bad* (not served, deadline-missed, or over
    the optional ``latency_slo_ms`` objective).  Burn rate is
    ``bad_fraction / error_budget``; 1.0 spends the budget exactly at
    the sustainable rate.  A breach needs the FAST window (newest
    sub-window, a cliff detector) at :data:`FAST_BURN_MIN` AND the SLOW
    window (the whole ring) at :data:`SLOW_BURN_MIN` — the classic
    two-window rule.  Breaches latch until the slow burn recovers below
    1.0, so a sustained outage is one finding, not one per request.

    The ring reuses :class:`~harp_tpu.utils.reqtrace.RollingWindow`'s
    epoch-keyed slot scheme (stale slots detected by epoch, never
    scanned or cleared on the hot path); memory is ``subwindows`` tiny
    count pairs no matter how long the server runs.  Cumulative outcome
    counters (``counts``) reconcile exactly with the invariant-9 ledger
    and the ReqTracer outcome counts — the acceptance pin.
    """

    def __init__(self, tag: str, *, window_s: float = 60.0,
                 subwindows: int = 6,
                 error_budget: float = SLO_ERROR_BUDGET,
                 latency_slo_ms: float | None = None):
        if window_s <= 0 or subwindows < 2:
            raise ValueError(f"need window_s > 0 and >= 2 subwindows, "
                             f"got {window_s}/{subwindows}")
        if not 0.0 < error_budget <= 1.0:
            raise ValueError(f"error_budget {error_budget} must be in "
                             "(0, 1]")
        self.tag = tag
        self.window_s = float(window_s)
        self.sub_s = self.window_s / int(subwindows)
        self.k = int(subwindows)
        self.error_budget = float(error_budget)
        self.latency_slo_ms = latency_slo_ms
        # ring slot -> [epoch, offered, bad]
        self._ring: list[list | None] = [None] * self.k
        self.counts = {"offered": 0, "served": 0, "shed": 0, "failed": 0,
                       "deadline_missed": 0}
        self.breaches = 0
        self.peak_fast = 0.0
        self.peak_slow = 0.0
        self._latched = False
        self._recent_bad: list[int] = []
        self._row: dict | None = None

    # -- the one entry point ------------------------------------------------
    def observe(self, now: float, outcome: str, *,
                latency_ms: float | None = None,
                deadline_missed: bool = False,
                rid: int | None = None) -> None:
        """One terminal request outcome on the runner's clock.  No-op
        while telemetry is off (the zero-cost contract)."""
        if not telemetry.enabled():
            return
        c = self.counts
        c["offered"] += 1
        c[outcome] += 1
        if deadline_missed:
            c["deadline_missed"] += 1
        bad = (outcome != "served" or deadline_missed
               or (self.latency_slo_ms is not None
                   and latency_ms is not None
                   and latency_ms > self.latency_slo_ms))
        epoch = int(now / self.sub_s)
        i = epoch % self.k
        cur = self._ring[i]
        if cur is None or cur[0] != epoch:
            cur = [epoch, 0, 0]
            self._ring[i] = cur
        cur[1] += 1
        if bad:
            cur[2] += 1
            if rid is not None:
                self._recent_bad.append(rid)
                del self._recent_bad[:-8]
        self._check(now, epoch)
        if self._row is not None:  # keep the exported row's counts FINAL
            self._row.update(c)
            self._row["breaches"] = self.breaches
            self._row["fast_burn"] = round(self.peak_fast, 3)
            self._row["slow_burn"] = round(self.peak_slow, 3)
            self._row["recent_reqs"] = list(self._recent_bad)

    def burn(self, now: float) -> tuple[float, float]:
        """(fast, slow) burn rates at ``now`` (0.0 before any sample)."""
        epoch = int(now / self.sub_s)
        fo = fb = so = sb = 0
        for cur in self._ring:
            if cur is None or epoch - cur[0] >= self.k:
                continue
            so += cur[1]
            sb += cur[2]
            if cur[0] == epoch:
                fo, fb = cur[1], cur[2]
        fast = (fb / fo / self.error_budget) if fo else 0.0
        slow = (sb / so / self.error_budget) if so else 0.0
        return fast, slow

    def _check(self, now: float, epoch: int) -> None:
        fast, slow = self.burn(now)
        self.peak_fast = max(self.peak_fast, fast)
        self.peak_slow = max(self.peak_slow, slow)
        if fast >= FAST_BURN_MIN and slow >= SLOW_BURN_MIN:
            if not self._latched:
                self._latched = True
                self.breaches += 1
            sev = "page" if slow >= PAGE_BURN else "warn"
            # keyed by the instance, not the tag: two runs of the same
            # app in one process each get their own run-scoped row
            self._row = monitor.upsert("slo_burn", self, severity=sev)
            self._row.setdefault("tag", self.tag)
            self._row["error_budget"] = self.error_budget
            self._row["window_s"] = self.window_s
        elif slow < SLOW_BURN_MIN:
            self._latched = False  # hysteresis: re-arm on recovery

    def snapshot(self, now: float) -> dict:
        """Live view for stats lines (works with telemetry off: zeros)."""
        fast, slow = self.burn(now)
        return {**self.counts, "fast_burn": round(fast, 3),
                "slow_burn": round(slow, 3), "breaches": self.breaches,
                "error_budget": self.error_budget}


# ---------------------------------------------------------------------------
# Module singleton + export
# ---------------------------------------------------------------------------

monitor = HealthMonitor()


def reset() -> None:
    """Clear the monitor (telemetry.scope does this on entry)."""
    monitor.reset()


def export_jsonl(fh) -> None:
    """Append health rows (telemetry.export calls this); stamped with
    the flight recorder's provenance triple — a CPU-sim finding must
    never read as relay evidence (the invariant-4 inversion guard)."""
    if not monitor._rows:
        return
    from harp_tpu.utils import flightrec

    monitor.export_jsonl(fh, flightrec.provenance_stamp())
