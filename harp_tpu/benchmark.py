"""Collective micro-benchmarks — ``edu.iu.benchmark`` parity.

The reference app sweeps message sizes through bcast/reduce/allgather/
allreduce and prints per-size timings (SURVEY.md §3.4, §5).  Here every verb
is a standalone jitted shard_map program (``collective.host_op``); sizes
sweep powers of two; output is one line per (verb, size) with achieved
GB/s and latency — run it to see what the ICI/DCN fabric actually delivers,
the way the reference app characterized its socket fan-outs.
"""

from __future__ import annotations

import argparse
import json
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from harp_tpu.parallel import collective as C
from harp_tpu.parallel.mesh import WorkerMesh, current_mesh
from harp_tpu.utils import flightrec, telemetry
from harp_tpu.utils.timing import device_sync

VERBS = {
    # name: (fn, kwargs, out_dim, bytes_on_wire_factor(nw))
    "allreduce": (C.allreduce, {}, None, lambda nw: 2.0),
    "allgather": (C.allgather, {}, None, lambda nw: 1.0),
    "broadcast": (C.broadcast, {}, None, lambda nw: 1.0),
    "reduce": (C.reduce, {}, 0, lambda nw: 1.0),
    "regroup": (C.regroup, {}, 0, lambda nw: 1.0),
    "rotate": (C.rotate, {}, 0, lambda nw: 1.0),
    "push": (C.push, {}, 0, lambda nw: 1.0),
    "pull": (C.pull, {}, None, lambda nw: 1.0),
    # quantized wires move half/quarter the bytes of allreduce's f32 wire
    "allreduce_bf16": (C.allreduce_quantized, {"wire_dtype": jnp.bfloat16},
                       None, lambda nw: 1.0),
    "allreduce_int8": (C.allreduce_quantized, {"wire_dtype": jnp.int8},
                       None, lambda nw: 0.5),
    # quantized data movement (rotate/regroup have an f32 factor of 1.0,
    # so the narrow wires are 0.5/0.25 — the bytes the chunked rotation
    # pipeline puts on the ring per hop under rotate_wire=bf16/int8)
    "rotate_bf16": (C.rotate_quantized, {"wire_dtype": jnp.bfloat16},
                    0, lambda nw: 0.5),
    "rotate_int8": (C.rotate_quantized, {"wire_dtype": jnp.int8},
                    0, lambda nw: 0.25),
    "regroup_bf16": (C.regroup_quantized, {"wire_dtype": jnp.bfloat16},
                     0, lambda nw: 0.5),
    "regroup_int8": (C.regroup_quantized, {"wire_dtype": jnp.int8},
                     0, lambda nw: 0.25),
}


def bench_verb(name, mesh: WorkerMesh, size_bytes: int, reps: int = 20):
    fn, kwargs, out_dim, wire = VERBS[name]
    nw = mesh.num_workers
    # regroup (all_to_all) and push (psum_scatter) additionally split each
    # worker's shard by nw, so rows must be a multiple of nw²
    mult = nw * nw if name.startswith(("regroup", "push")) else nw
    n_rows = max(mult, size_bytes // (4 * 128) // mult * mult)
    x = np.random.default_rng(0).normal(size=(n_rows, 128)).astype(np.float32)
    # flightrec.track: each invocation is one dispatch round trip in the
    # flight record (reps+1 with the warmup), so the report can show
    # dispatch overhead next to the achieved GB/s
    op = flightrec.track(
        C.host_op(mesh, fn, in_dim=0, out_dim=out_dim, **kwargs),
        f"bench.{name}")
    # telemetry: the warmup call traces the verb's comm site; the timed
    # loop re-invokes the cached executable reps times — the ledger's
    # execution counter is what turns one traced byte sheet into volume
    with telemetry.ledger.run(f"bench.{name}", steps=1):
        out = op(x)
    device_sync(out)
    t0 = time.perf_counter()
    with telemetry.span(f"bench.{name}", bytes=x.nbytes, reps=reps), \
            telemetry.ledger.run(f"bench.{name}", steps=reps):
        for _ in range(reps):
            out = op(x)
        device_sync(out)
    dt = (time.perf_counter() - t0) / reps
    payload = x.nbytes * wire(nw)
    return {"verb": name, "bytes": x.nbytes, "sec": dt,
            "gb_per_sec": payload / dt / 1e9, "num_workers": nw}


SPARSE_VERBS = ("pull_sparse", "push_sparse")


def bench_sparse(name, mesh: WorkerMesh, size_bytes: int, reps: int = 20):
    """Characterize the request/serve sparse row exchange
    (table.pull_rows_sparse / push_rows_sparse): ``size_bytes`` is the
    GLOBAL requested-row payload (bench_verb's convention); the table is
    sized 4× past it, which must NOT change the timing — that is the
    verbs' point.  Requests spread evenly over owners so
    ``capacity == m/nw`` exactly: every wire slot carries a real row and
    the accounted payload equals the bytes the fabric moves."""
    from harp_tpu.table import pull_rows_sparse, push_rows_sparse

    nw = mesh.num_workers
    d = 128
    # m requested rows per worker, an exact multiple of nw
    m = max(nw, size_bytes // (4 * d * nw) // nw * nw)
    cap = m // nw
    rows_local = max(4 * m, 128)            # table >> requests
    rng = np.random.default_rng(0)
    table = rng.normal(size=(nw * rows_local, d)).astype(np.float32)
    # worker w requests rows cap*[0..cap) from EVERY owner: zero drops,
    # zero padding slots in the [nw, cap] exchange buffers
    ids = np.concatenate([
        np.concatenate([o * rows_local + np.arange(cap, dtype=np.int32)
                        for o in range(nw)])
        for _ in range(nw)])
    # device-resident inputs: re-uploading host arrays per rep would time
    # the transfer of the deliberately-oversized table, not the exchange
    table_d = mesh.shard_array(table, 0)
    ids_d = mesh.shard_array(ids, 0)
    if name == "pull_sparse":
        fn = jax.jit(mesh.shard_map(
            lambda t, i: pull_rows_sparse(t, i, capacity=cap)[0],
            in_specs=(mesh.spec(0), mesh.spec(0)), out_specs=mesh.spec(0)))
        run = lambda: fn(table_d, ids_d)  # noqa: E731
    else:
        deltas_d = mesh.shard_array(
            rng.normal(size=(nw * m, d)).astype(np.float32), 0)
        fn = jax.jit(mesh.shard_map(
            lambda t, i, dv: push_rows_sparse(t, i, dv, capacity=cap)[0],
            in_specs=(mesh.spec(0),) * 3, out_specs=mesh.spec(0)))
        run = lambda: fn(table_d, ids_d, deltas_d)  # noqa: E731
    device_sync(run())
    t0 = time.perf_counter()
    for _ in range(reps):
        out = run()
    device_sync(out)
    dt = (time.perf_counter() - t0) / reps
    payload = nw * m * d * 4  # global row bytes == actual wire slots
    return {"verb": name, "bytes": payload, "sec": dt,
            "gb_per_sec": payload / dt / 1e9, "num_workers": nw,
            "table_rows": nw * rows_local, "requested_rows_per_worker": m}


def sweep_sparse_capacity(mesh: WorkerMesh, m: int = 4096, d: int = 128,
                          reps: int = 5, zipf_a: float = 1.1,
                          caps=(1 / 64, 1 / 16, 1 / 4, 1 / 2, 1.0)):
    """Capacity-vs-(drops, wire, time) under realistic skew — THE sizing
    question for the sparse verbs (VERDICT r2 weak #4): wire is
    O(nw·capacity) *buffer slots* whether or not slots carry rows, and
    real corpora are Zipf — the hot owner ("the", "of") receives most
    requests, so the even-spread micro-bench's ``cap = m/nw`` is the
    best case, not the typical one.

    Three request distributions per capacity point (caps are fractions of
    the per-worker request count ``m``; cap = m ⇒ zero drops by
    construction):

    - ``even``   — round-robin owners (the old bench's regime);
    - ``zipf``   — ids ~ Zipf(``zipf_a``) over the table, row 0 hottest
      (frequency-sorted vocab ⇒ owner 0 is the hot owner);
    - ``zipf_dedup`` — the same draw with duplicate ids collapsed via the
      ``valid`` mask (one slot per DISTINCT row, the LDA
      ``dedup_pulls`` strategy) — quantifies how much dedup shrinks the
      capacity a skewed workload needs.

    Yields one record per (dist, capacity): ``drop_rate`` is dropped /
    issued requests (global), ``wire_mb`` the all_to_all buffer payload
    both ways (nw·cap row slots + id slots, per worker, × nw workers).
    """
    from harp_tpu.table import pull_rows_sparse

    nw = mesh.num_workers
    rows_local = max(128, 2 * m)
    rng = np.random.default_rng(0)
    table_d = mesh.shard_array(
        rng.normal(size=(nw * rows_local, d)).astype(np.float32), 0)

    # ONE Zipf draw shared by "zipf" and "zipf_dedup": the dedup-vs-raw
    # comparison must mask the SAME ids, not draw two independent corpora
    zipf_ids = (rng.zipf(zipf_a, size=m).astype(np.int64) - 1) \
        % (nw * rows_local)

    def ids_for(dist):
        if dist == "even":
            per = np.arange(m, dtype=np.int64)
            ids = (per % nw) * rows_local + (per // nw) % rows_local
            valid = np.ones(m, bool)
        else:
            ids = zipf_ids
            valid = np.ones(m, bool)
            if dist == "zipf_dedup":
                # one request per DISTINCT row: duplicates keep their
                # slot in the [m] layout but are masked out of the wire
                order = np.argsort(ids, kind="stable")
                first = np.ones(m, bool)
                first[order[1:]] = ids[order[1:]] != ids[order[:-1]]
                valid = first
        # every worker issues the same draw: tile [m] → global [nw*m]
        return (np.tile(ids.astype(np.int32), nw), np.tile(valid, nw),
                int(valid.sum()))

    for dist in ("even", "zipf", "zipf_dedup"):
        ids_np, valid_np, issued = ids_for(dist)  # issued = PER WORKER
        ids_d = mesh.shard_array(ids_np, 0)
        valid_d = mesh.shard_array(valid_np, 0)
        for frac in caps:
            cap = max(1, int(m * frac))
            fn = jax.jit(mesh.shard_map(
                lambda t, i, v: pull_rows_sparse(t, i, capacity=cap,
                                                 valid=v),
                in_specs=(mesh.spec(0),) * 3,
                out_specs=(mesh.spec(0), mesh.spec(0), P())))
            rows, ok, dropped = fn(table_d, ids_d, valid_d)
            device_sync(ok)
            t0 = time.perf_counter()
            for _ in range(reps):
                rows, ok, dropped = fn(table_d, ids_d, valid_d)
            device_sync(ok)
            dt = (time.perf_counter() - t0) / reps
            wire = nw * (nw * cap) * (d * 4 + 4) * 2  # rows+ids, both ways
            yield {"verb": "pull_sparse_sweep", "dist": dist,
                   "capacity": cap, "cap_frac": frac,
                   "requests_per_worker": issued,
                   "drop_rate": float(dropped) / max(1, issued * nw),
                   "wire_mb": wire / 1e6, "sec": dt,
                   "num_workers": nw, "zipf_a": zipf_a}


def main(argv=None):
    p = argparse.ArgumentParser(description="harp-tpu collective micro-benchmarks")
    p.add_argument("--verbs", nargs="*",
                   default=sorted(VERBS) + list(SPARSE_VERBS))
    p.add_argument("--min-kb", type=int, default=64)
    p.add_argument("--max-mb", type=int, default=64)
    p.add_argument("--reps", type=int, default=20)
    p.add_argument("--sparse-capacity-sweep", action="store_true",
                   help="instead of the size sweep: capacity vs (drop "
                        "rate, wire, time) for the sparse verbs under "
                        "even / Zipf-1.1 / Zipf-deduped request "
                        "distributions (the pull_cap sizing table)")
    args = p.parse_args(argv)
    mesh = current_mesh()
    if args.sparse_capacity_sweep:
        for rec in sweep_sparse_capacity(mesh, reps=args.reps):
            print(json.dumps({k: (round(v, 5) if isinstance(v, float)
                                  else v) for k, v in rec.items()}))
        return
    size = args.min_kb * 1024
    sizes = []
    while size <= args.max_mb * 1024 * 1024:
        sizes.append(size)
        size *= 4
    for verb in args.verbs:
        bench = bench_sparse if verb in SPARSE_VERBS else bench_verb
        for s in sizes:
            print(json.dumps(bench(verb, mesh, s, args.reps)))
    from harp_tpu.report import maybe_emit

    maybe_emit("bench")


if __name__ == "__main__":
    main()
