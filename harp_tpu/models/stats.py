"""Classic analytics suite — Harp-DAAL's map + reduce algorithms.

Reference parity (SURVEY.md §3.4): the ``ml/daal`` apps ``edu.iu.daal_pca``,
``daal_cov``, ``daal_mom``, ``daal_naive``, ``daal_linreg``, ``daal_ridgereg``,
``daal_qr``, ``daal_svd``, ``daal_als``: each worker computes a DAAL partial
result on its HDFS shard, partials are combined to master with Harp
``reduce``/``allreduce``/``allgather``, and the master finalizes.

TPU-native design: every algorithm is "local sufficient statistics →
``allreduce`` → closed-form finalize", jitted end-to-end.  The sufficient
statistics are all matmul-shaped (Gram matrices, moment sums), so the MXU
does the heavy lifting and the collective moves O(d²) — exactly why the
map-reduce formulation scales.  Distributed QR/SVD use the TSQR trick:
local QR, allgather the small R factors, QR again (communication-optimal
tall-skinny factorization).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from harp_tpu.parallel import collective as C
from harp_tpu.parallel.mesh import WorkerMesh, current_mesh
from harp_tpu.utils import flightrec


def _spmd(mesh, fn, n_in=1, out_spec=None):
    return jax.jit(mesh.shard_map(
        fn, in_specs=(mesh.spec(0),) * n_in,
        out_specs=out_spec if out_spec is not None else P(),
    ))


def _shard_rows(mesh, *arrays):
    """Pad row-aligned arrays to a worker multiple and shard them.

    Returns ``(*sharded_arrays, sharded_weights)`` where weights are 1 for
    real rows, 0 for padding — the one shared pad+shard idiom every
    row-parallel algorithm here uses (svm/naive-bayes included).
    """
    arrays = [np.asarray(a) for a in arrays]
    nw = mesh.num_workers
    n = arrays[0].shape[0]
    n_pad = -(-n // nw) * nw
    w = np.ones(n, np.float32)
    out = []
    for a in arrays:
        a = a.astype(np.float32) if a.dtype.kind == "f" else a
        if n_pad > n:
            a = np.concatenate([a, np.zeros((n_pad - n,) + a.shape[1:], a.dtype)])
        out.append(mesh.shard_array(a, 0))
    if n_pad > n:
        w = np.concatenate([w, np.zeros(n_pad - n, np.float32)])
    out.append(mesh.shard_array(w, 0))
    return tuple(out)


# ---------------------------------------------------------------------------
# Moments & covariance (edu.iu.daal_mom, edu.iu.daal_cov)
# ---------------------------------------------------------------------------

def moments(x, mesh: WorkerMesh | None = None):
    """Low-order moments per feature: min/max/sum/mean/variance/std."""
    mesh = mesh or current_mesh()
    xd, wd = _shard_rows(mesh, x)

    def prog(x, w):
        big = jnp.float32(3.4e38)
        masked_min = jnp.where(w[:, None] > 0, x, big).min(0)
        masked_max = jnp.where(w[:, None] > 0, x, -big).max(0)
        stats = {
            "n": C.allreduce(w.sum()),
            "sum": C.allreduce((x * w[:, None]).sum(0)),
            "min": C.allreduce(masked_min, C.Combiner.MIN),
            "max": C.allreduce(masked_max, C.Combiner.MAX),
        }
        mean = stats["sum"] / stats["n"]
        # centered second pass: E[x²]−mean² cancels catastrophically in f32
        # when |mean| ≫ std; one extra allreduce buys exactness
        cx = (x - mean[None, :]) * w[:, None]
        stats["centered_sum2"] = C.allreduce((cx * cx).sum(0))
        stats["mean"] = mean
        stats["variance"] = jnp.maximum(stats["centered_sum2"] / stats["n"], 0)
        stats["std"] = jnp.sqrt(stats["variance"])
        return stats

    return {k: np.asarray(v) for k, v in _spmd(mesh, prog, 2)(xd, wd).items()}


def covariance(x, mesh: WorkerMesh | None = None):
    """Covariance matrix (and mean) via one allreduce of (n, Σx, ΣxxT)."""
    mesh = mesh or current_mesh()
    xd, wd = _shard_rows(mesh, x)

    def prog(x, w):
        xw = x * w[:, None]
        n, s = C.allreduce((w.sum(), xw.sum(0)))
        mean = s / n
        # centered Gram (second pass): avoids f32 cancellation at large means
        cx = (x - mean[None, :]) * w[:, None]
        g = C.allreduce(jax.lax.dot_general(
            cx, x - mean[None, :], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32))
        return mean, g / n

    mean, cov = _spmd(mesh, prog, 2)(xd, wd)
    return np.asarray(mean), np.asarray(cov)


# ---------------------------------------------------------------------------
# PCA (edu.iu.daal_pca: correlation method)
# ---------------------------------------------------------------------------

def pca(x, n_components=None, mesh: WorkerMesh | None = None):
    """PCA via the covariance/correlation method (DAAL's distributed mode).

    Returns (components [k, d], explained_variance [k]), sorted descending.
    The eigendecomposition of the d×d covariance runs on device after one
    allreduce — the O(n) part never leaves the workers.
    """
    mean, cov = covariance(x, mesh)
    evals, evecs = np.linalg.eigh(cov)
    order = np.argsort(evals)[::-1]
    k = n_components or cov.shape[0]
    return evecs[:, order[:k]].T, evals[order[:k]]


# ---------------------------------------------------------------------------
# Naive Bayes (edu.iu.daal_naive: multinomial)
# ---------------------------------------------------------------------------

def naive_bayes_fit(x, y, n_classes, alpha=1.0, mesh: WorkerMesh | None = None):
    """Multinomial naive Bayes: per-class feature sums → allreduce → log probs."""
    mesh = mesh or current_mesh()
    xd, yd, wd = _shard_rows(mesh, x, np.asarray(y, np.int32))

    def prog(x, w, y):
        oh = jax.nn.one_hot(y, n_classes, dtype=jnp.float32) * w[:, None]
        feat = C.allreduce(jax.lax.dot_general(
            oh, x, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32))
        cls = C.allreduce(oh.sum(0))
        return feat, cls

    feat, cls = _spmd(mesh, prog, 3)(xd, wd, yd)
    feat, cls = np.asarray(feat), np.asarray(cls)
    log_prior = np.log((cls + alpha) / (cls.sum() + alpha * n_classes))
    log_lik = np.log((feat + alpha) / (feat.sum(1, keepdims=True) + alpha * feat.shape[1]))
    return {"log_prior": log_prior, "log_likelihood": log_lik}


def naive_bayes_predict(model, x):
    scores = np.asarray(x) @ model["log_likelihood"].T + model["log_prior"]
    return scores.argmax(1).astype(np.int32)


# ---------------------------------------------------------------------------
# Linear / ridge regression (edu.iu.daal_linreg, daal_ridgereg)
# ---------------------------------------------------------------------------

def linear_regression(x, y, l2=0.0, fit_intercept=True,
                      mesh: WorkerMesh | None = None):
    """Normal equations: allreduce (XᵀX, Xᵀy), solve on device.

    y may be [n] or [n, t] (DAAL supports multiple dependent variables).
    """
    mesh = mesh or current_mesh()
    x = np.asarray(x, np.float32)
    y2 = np.asarray(y, np.float32)
    y2 = y2[:, None] if y2.ndim == 1 else y2
    if fit_intercept:
        x = np.concatenate([x, np.ones((x.shape[0], 1), np.float32)], 1)
    xd, wd = _shard_rows(mesh, x)
    yd, _ = _shard_rows(mesh, y2)

    d = x.shape[1]
    reg = np.zeros((d, d), np.float32)
    reg[np.arange(d), np.arange(d)] = l2
    if fit_intercept:
        reg[-1, -1] = 0.0  # never regularize the intercept

    def prog(x, w, y):
        xw = x * w[:, None]
        xtx, xty = C.allreduce((
            jax.lax.dot_general(xw, x, (((0,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32),
            jax.lax.dot_general(xw, y, (((0,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32),
        ))
        return jnp.linalg.solve(xtx + reg, xty)

    beta = np.asarray(_spmd(mesh, prog, 3)(xd, wd, yd))
    if fit_intercept:
        return beta[:-1].squeeze(-1) if np.asarray(y).ndim == 1 else beta[:-1], \
               beta[-1].squeeze(-1) if np.asarray(y).ndim == 1 else beta[-1]
    return (beta.squeeze(-1) if np.asarray(y).ndim == 1 else beta), None


def ridge_regression(x, y, l2=1.0, fit_intercept=True, mesh=None):
    return linear_regression(x, y, l2=l2, fit_intercept=fit_intercept, mesh=mesh)


# ---------------------------------------------------------------------------
# QR & SVD (edu.iu.daal_qr, daal_svd): communication-optimal TSQR
# ---------------------------------------------------------------------------

def tsqr(x, mesh: WorkerMesh | None = None):
    """Tall-skinny QR: local QR → allgather R's → QR of stack → fix-up.

    Returns (Q [n, d] sharded rows as input, R [d, d]).  This is the
    distributed QR DAAL implements (step1 local / step2 master / step3
    local), with the master step replaced by a replicated small QR.
    """
    mesh = mesh or current_mesh()
    x = np.asarray(x, np.float32)
    n, d = x.shape
    nw = mesh.num_workers
    n_pad = -(-n // nw) * nw
    if n_pad // nw < d:
        raise ValueError(
            f"tsqr needs a tall-skinny local block: {n} rows / {nw} workers "
            f"= {n_pad // nw} per worker < {d} columns")
    if n_pad > n:
        # zero rows factor exactly: [X; 0] = [Q; 0] R
        x = np.concatenate([x, np.zeros((n_pad - n, d), np.float32)])
    xd = mesh.shard_array(x, 0)

    def prog(x):
        q1, r1 = jnp.linalg.qr(x)                    # local [n_loc, d], [d, d]
        rs = C.allgather(r1)                         # [nw*d, d] everywhere
        q2, r = jnp.linalg.qr(rs)                    # combine step
        # this worker's block of q2 lifts its local Q
        me = jax.lax.axis_index("workers")
        q2_block = jax.lax.dynamic_slice_in_dim(q2, me * d, d, 0)
        return q1 @ q2_block, r

    q, r = flightrec.track(jax.jit(mesh.shard_map(
        prog, in_specs=(mesh.spec(0),), out_specs=(mesh.spec(0), P()),
    )), "stats.tsqr")(xd)
    return np.asarray(q)[:n], np.asarray(r)


def svd(x, mesh: WorkerMesh | None = None):
    """Tall-skinny SVD via TSQR: X = QR, R = UΣVᵀ → X = (QU)ΣVᵀ."""
    q, r = tsqr(x, mesh)
    u_r, s, vt = np.linalg.svd(r)
    return q @ u_r, s, vt


# ---------------------------------------------------------------------------
# ALS (edu.iu.daal_als): alternating least squares for ratings
# ---------------------------------------------------------------------------

def als(users, items, vals, n_users, n_items, rank=16, reg=0.1, iters=10,
        mesh: WorkerMesh | None = None, seed=0):
    """Explicit-feedback ALS: users sharded, item factors replicated.

    W step: per-user normal equations over its (padded) item list, batched
    with vmap.  H step: per-item Grams accumulated with one-hot matmuls and
    combined with allreduce (the DAAL partial-result exchange).  Returns
    (W [n_users, rank], H [n_items, rank], rmse_history).
    """
    mesh = mesh or current_mesh()
    nw = mesh.num_workers
    users = np.asarray(users); items = np.asarray(items)
    vals = np.asarray(vals, np.float32)
    u_bound = -(-n_users // nw)

    # per-user padded item lists (host prep, like HarpDAALDataSource)
    order = np.argsort(users, kind="stable")
    su, si, sv = users[order], items[order], vals[order]
    starts = np.searchsorted(su, np.arange(n_users))
    counts = np.diff(np.append(starts, len(su)))
    m = max(int(counts.max()), 1)
    ui = np.zeros((u_bound * nw, m), np.int32)
    uv = np.zeros((u_bound * nw, m), np.float32)
    um = np.zeros((u_bound * nw, m), np.float32)
    for u in range(n_users):
        c = counts[u]
        ui[u, :c] = si[starts[u]:starts[u] + c]
        uv[u, :c] = sv[starts[u]:starts[u] + c]
        um[u, :c] = 1.0

    rng = np.random.default_rng(seed)
    H = rng.normal(size=(n_items, rank)).astype(np.float32) / np.sqrt(rank)
    uid, uvd, umd = (mesh.shard_array(a, 0) for a in (ui, uv, um))
    eye = reg * np.eye(rank, dtype=np.float32)

    def w_step(H, ui, uv, um):
        def solve_user(idx, v, msk):
            h = H[idx] * msk[:, None]                  # [m, r]
            A = h.T @ h + eye
            b = h.T @ (v * msk)
            return jnp.linalg.solve(A, b)

        return jax.vmap(solve_user)(ui, uv, um)        # [u_loc, r]

    def h_step(W, ui, uv, um):
        # per-item Gram/vec accumulated over the worker's ratings via
        # segment sums (a dense one-hot would be [nnz, n_items] — GBs)
        flat_i = ui.reshape(-1)
        flat_m = um.reshape(-1)
        flat_v = uv.reshape(-1)
        w_rep = jnp.repeat(W, ui.shape[1], axis=0) * flat_m[:, None]  # [nnz_loc, r]
        WW = w_rep[:, :, None] * w_rep[:, None, :]     # [nnz_loc, r, r]
        A = jax.ops.segment_sum(WW, flat_i, num_segments=n_items)
        b = jax.ops.segment_sum(w_rep * flat_v[:, None], flat_i,
                                num_segments=n_items)
        A, b = C.allreduce((A, b))
        return jax.vmap(lambda Ai, bi: jnp.linalg.solve(Ai + eye, bi))(A, b)

    def epoch(H, ui, uv, um):
        W = w_step(H, ui, uv, um)
        H = h_step(W, ui, uv, um)
        pred = (W[:, None, :] * H[ui]).sum(-1)
        se = C.allreduce((((pred - uv) * um) ** 2).sum())
        cnt = C.allreduce(um.sum())
        return W, H, jnp.sqrt(se / jnp.maximum(cnt, 1))

    fn = flightrec.track(jax.jit(mesh.shard_map(
        epoch, in_specs=(P(), mesh.spec(0), mesh.spec(0), mesh.spec(0)),
        out_specs=(mesh.spec(0), P(), P()),
    )), "stats.als")
    Hd = jax.device_put(jnp.asarray(H), mesh.replicated())
    hist = []
    for _ in range(iters):
        W, Hd, rmse = fn(Hd, uid, uvd, umd)
        hist.append(float(np.asarray(rmse)))
    return np.asarray(W)[:n_users], np.asarray(Hd), hist


def main(argv=None):
    """Launcher for the classic-stats suite — the ``daal_{pca,cov,...}``
    per-app launchers collapsed into one (`python -m harp_tpu stats <algo>`)."""
    import argparse

    from harp_tpu.utils.metrics import benchmark_json

    p = argparse.ArgumentParser(
        description="harp-tpu classic analytics (edu.iu.daal_* parity)")
    p.add_argument("algo", choices=["pca", "cov", "moments", "naive",
                                    "linreg", "ridge", "qr", "svd", "als"])
    p.add_argument("--n", type=int, default=100_000)
    p.add_argument("--d", type=int, default=64)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--input", default=None, metavar="FILE_OR_GLOB",
                   help="CSV shards (the Harp daal apps' HDFS input) instead "
                        "of synthetic data; for naive/linreg/ridge the LAST "
                        "column is the label/target, for als rows are "
                        "'user item rating' triples")
    args = p.parse_args(argv)

    rng = np.random.default_rng(args.seed)
    y_file = None
    if args.input and args.algo == "als":
        from harp_tpu.native.datasource import load_triples_glob

        try:
            u_in, i_in, v_in, has_vals = load_triples_glob(args.input)
        except ValueError as e:
            raise SystemExit(str(e))
        if not has_vals:
            raise SystemExit(f"{args.input}: als needs 'user item rating' rows")
        if int(u_in.min()) < 0 or int(i_in.min()) < 0:
            raise SystemExit(
                f"{args.input}: negative user/item ids (ids index factor "
                "rows; JAX would silently wrap them to wrong rows)")
        x = None
    elif args.input:
        from harp_tpu.native.datasource import load_csv_glob

        try:
            x = load_csv_glob(args.input)
        except ValueError as e:
            raise SystemExit(str(e))
        if x.ndim != 2 or x.shape[1] < 1:
            raise SystemExit(f"{args.input}: need a 2-D CSV matrix")
        if args.algo in ("naive", "linreg", "ridge"):
            if x.shape[1] < 2:
                raise SystemExit(
                    f"{args.input}: {args.algo} needs >= 2 columns "
                    "(features..., label)")
            y_file, x = x[:, -1], x[:, :-1].copy()
    else:
        x = rng.normal(size=(args.n, args.d)).astype(np.float32)
    if args.algo == "pca":
        _, evals = pca(x)
        print(benchmark_json("stats_cli", {"algo": "pca", "top5_evals": np.asarray(evals)[:5].tolist()}))
    elif args.algo == "cov":
        _, c = covariance(x)
        print(benchmark_json("stats_cli", {"algo": "cov", "trace": float(np.trace(np.asarray(c)))}))
    elif args.algo == "moments":
        m = moments(x)
        print(benchmark_json("stats_cli", {"algo": "moments",
               "mean_norm": float(np.linalg.norm(np.asarray(m["mean"]))),
               "var_mean": float(np.mean(np.asarray(m["variance"])))}))
    elif args.algo == "naive":
        if y_file is not None:
            if not np.all(y_file == np.round(y_file)):
                raise SystemExit(
                    "naive: labels (last column) must be integers — "
                    "fractional values would silently truncate to wrong "
                    "classes")
            y = y_file.astype(np.int64)
            if y.min() < 0:
                raise SystemExit("naive: labels (last column) must be >= 0")
            n_classes = int(y.max()) + 1
            if n_classes > 10_000:
                raise SystemExit(
                    f"naive: {n_classes} classes from the label column — "
                    "is this a regression target? (refusing to allocate "
                    "count tables that size)")
        else:
            # class-dependent feature PATTERNS: random labels on
            # unrelated features read as chance-level train_acc (0.26 on
            # the round-5 TPU smoke) and look like a broken model — and
            # multinomial NB (the DAAL-parity formulation) is blind to
            # uniform shifts, so each class boosts its own d/4 feature
            # slice instead.  The sklearn-golden tests, not this demo,
            # are the correctness evidence.
            y, n_classes = rng.integers(0, 4, args.n), 4
            x = x + 3.0 * (np.arange(x.shape[1])[None, :] % 4
                           == y[:, None])
        model = naive_bayes_fit(np.abs(x), y, n_classes=n_classes)
        acc = float((naive_bayes_predict(model, np.abs(x)) == y).mean())
        print(benchmark_json("stats_cli", {"algo": "naive_bayes", "train_acc": acc}))
    elif args.algo in ("linreg", "ridge"):
        if y_file is not None:
            y = y_file
        else:
            w_true = rng.normal(size=x.shape[1]).astype(np.float32)
            y = x @ w_true + 0.01 * rng.normal(size=len(x)).astype(np.float32)
        fit = linear_regression if args.algo == "linreg" else ridge_regression
        coef, _intercept = fit(x, y)
        pred = x @ np.asarray(coef) + float(np.asarray(_intercept))
        rmse = float(np.sqrt(np.mean((pred - y) ** 2)))
        print(benchmark_json("stats_cli", {"algo": args.algo, "fit_rmse": rmse}))
    elif args.algo == "qr":
        q, r = tsqr(x)
        resid = float(np.linalg.norm(np.asarray(q) @ np.asarray(r) - x) /
                      np.linalg.norm(x))
        print(benchmark_json("stats_cli", {"algo": "tsqr", "rel_resid": resid}))
    elif args.algo == "svd":
        u, s, vt = svd(x)
        print(benchmark_json("stats_cli", {"algo": "svd", "top5_sv": np.asarray(s)[:5].tolist()}))
    elif args.algo == "als":
        if args.input:
            users, items, vals = u_in, i_in, v_in
            nu, ni = int(users.max()) + 1, int(items.max()) + 1
        else:
            nnz = min(args.n, 200_000)
            users = rng.integers(0, 1000, nnz).astype(np.int32)
            items = rng.integers(0, 500, nnz).astype(np.int32)
            vals = rng.normal(size=nnz).astype(np.float32)
            nu, ni = 1000, 500
        _, _, hist = als(users, items, vals, nu, ni, rank=8, iters=3)
        print(benchmark_json("stats_cli", {"algo": "als", "rmse_history": [round(h, 4) for h in hist]}))


if __name__ == "__main__":
    main()
