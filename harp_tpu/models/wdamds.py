"""WDA-MDS — weighted multidimensional scaling by SMACOF, allreduce.

Reference parity (SURVEY.md §3.4): Harp's ``edu.iu.wdamds`` implements
WDA-SMACOF (Ruan & Qiu): embed N points in d dimensions from a (weighted)
dissimilarity matrix by iterating the SMACOF majorization
``X ← V⁺ B(X) X``, with the Δ matrix row-partitioned across workers and an
allreduce of the stress and of the updated coordinates every iteration.

TPU-native design: rows of Δ sharded over workers; one iteration is a
jitted program: local distance block [n_loc, N] (matmul-shaped), local
``B(X)·X`` row block, then ``allgather`` of the new coordinate block and
``allreduce`` of the stress.  Unweighted case uses the closed form
``V⁺ = (1/N)(I − 11ᵀ/N)`` folded into the update (standard SMACOF); the
weighted case runs a few CG steps against V, each one allreduce.
"""

from __future__ import annotations

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from harp_tpu.parallel import collective as C
from harp_tpu.parallel.mesh import WorkerMesh, current_mesh
from harp_tpu.utils.timing import device_sync


@dataclasses.dataclass
class MDSConfig:
    dim: int = 2
    iters: int = 50
    eps: float = 1e-9
    # weighted path: CG steps per SMACOF iteration solving V X = B(Z) Z
    # (the reference's DA-SMACOF uses the same inner CG; V is the weight
    # Laplacian, singular along translations — centering handles the null
    # space).  10 matched full solves to ~1e-5 relative on test problems.
    cg_iters: int = 10
    # the per-iteration coordinate exchange's wire (PR 12: last per-app
    # wire with no planner byte sheet, with svm — ROADMAP item).  The
    # unweighted Guttman update's X block exchange rides
    # collective.reshard blocked(0)→replicated; "bf16"/"int8" narrow
    # the [N, dim] payload per iteration at one rounding per hop.
    # UNWEIGHTED path only: the weighted CG solve applies V through its
    # exchanges, and a quantized operator inside CG breaks the residual
    # recurrence — that path stays exact by design.  Flip candidates
    # wdamds_coord_bf16/_int8 gate on final_stress (flip_decision.py);
    # default stays exact until a relay window measures them.
    coord_wire: str = "exact"
    # dtype the n² dissimilarity matrix is STAGED in (PR 16: the profile
    # pass found the committed wdamds_cli wall is relay-H2D-staging-bound
    # at ~30 MB/s and Δ is the dominant staged buffer — flip candidate
    # wdamds_delta_bf16).  Arithmetic promotes back to f32 (only the
    # stored δ precision changes); final_stress gates the flip.  Default
    # stays f32 until a relay window measures it.
    delta_dtype: str = "f32"
    # Guttman-step schedule (PR 17), UNWEIGHTED path only: "xla" = the
    # reference body (D and ratio round-trip HBM between fusions);
    # "pallas" = the fused distance + B·X row-block kernel
    # (ops/wdamds_kernel.py) — D/ratio never leave VMEM, composing with
    # delta_dtype (a bf16-staged δ streams half the tile bytes).
    # perfmodel.presize picked a 128-row tile at the graded n=4096
    # shape (2026-08-06, predicted only — NOT yet measured; flip
    # candidate wdamds_dist_pallas gates on final_stress).  Falls back
    # to the XLA body when n_pad is not a 128 multiple; the weighted CG
    # path and the final stress pass always run XLA.
    algo: str = "xla"

    def __post_init__(self):
        if self.coord_wire not in ("exact", "bf16", "int8"):
            raise ValueError(f"coord_wire must be exact|bf16|int8, got "
                             f"{self.coord_wire!r}")
        if self.delta_dtype not in ("f32", "bf16"):
            raise ValueError(f"delta_dtype must be f32|bf16, got "
                             f"{self.delta_dtype!r}")
        if self.algo not in ("xla", "pallas"):
            raise ValueError(f"algo must be xla|pallas, got {self.algo!r}")


def make_smacof_fn(mesh: WorkerMesh, cfg: MDSConfig, n_pad: int):
    """One jitted run of SMACOF over the row-sharded Δ (unweighted)."""
    # the fused kernel needs the replicated axis to be a whole number of
    # lane registers; odd n_pad falls back to the (bitwise-equivalent in
    # outcome, slower in schedule) XLA body rather than erroring
    use_pallas = cfg.algo == "pallas" and n_pad % 128 == 0
    if use_pallas:
        from harp_tpu.ops.pallas_compat import interpret_default

        interp = interpret_default()

    def run(delta_rows, row_mask, X0, n_real):
        # delta_rows: [n_loc, N]; row_mask: [n_loc] (0 for padded rows);
        # X0: [N, d] replicated; n_real: scalar count of live points.
        me0 = jax.lax.axis_index("workers") * delta_rows.shape[0]

        def dist_block(X):
            Xl = jax.lax.dynamic_slice_in_dim(X, me0, delta_rows.shape[0], 0)
            x2 = (Xl ** 2).sum(-1)[:, None]
            y2 = (X ** 2).sum(-1)[None, :]
            d2 = x2 - 2.0 * (Xl @ X.T) + y2
            return jnp.sqrt(jnp.maximum(d2, 0.0)), Xl

        def body(X, _):
            if use_pallas:
                from harp_tpu.ops import wdamds_kernel

                Xl = jax.lax.dynamic_slice_in_dim(
                    X, me0, delta_rows.shape[0], 0)
                Xl_new = wdamds_kernel.smacof_bx(
                    delta_rows, row_mask, Xl, X, n_real, eps=cfg.eps,
                    interpret=interp)
            else:
                D, Xl = dist_block(X)                       # [n_loc, N]
                live = row_mask[:, None] * jnp.where(
                    jnp.arange(n_pad)[None, :] < n_real, 1.0, 0.0)
                # B entries: -δ/d off-diagonal (guarded), diagonal fixes
                # row sum 0
                ratio = jnp.where(
                    D > cfg.eps, delta_rows / jnp.maximum(D, cfg.eps), 0.0)
                ratio = ratio * live
                off = -ratio
                diag_fix = ratio.sum(1)                 # so rows sum to zero
                BX_rows = off @ X + diag_fix[:, None] * Xl  # [n_loc, d]
                # Guttman transform (unweighted): X ← B(X) X / n_real
                Xl_new = BX_rows / jnp.maximum(n_real, 1.0)
            # coordinate exchange via the general reshard verb
            # (blocked→replicated = the same tiled all_gather the old
            # C.allgather emitted, bit-exact on the exact wire) so
            # cfg.coord_wire can narrow it and the planner prices the
            # site (analysis/drivers.py "wdamds.smacof")
            X_new = C.reshard(Xl_new, C.ShardSpec.blocked(0),
                              C.ShardSpec.replicated(),
                              wire=cfg.coord_wire)     # [N, d] everywhere
            return X_new, None

        X, _ = jax.lax.scan(body, X0, None, length=cfg.iters)
        # final stress: Σ_{i<j} (δ − d)²  (counted once via upper mask)
        D, _ = dist_block(X)
        live = row_mask[:, None] * jnp.where(
            jnp.arange(n_pad)[None, :] < n_real, 1.0, 0.0)
        upper = (jnp.arange(n_pad)[None, :] > (me0 + jnp.arange(delta_rows.shape[0]))[:, None])
        se = ((delta_rows - D) ** 2 * live * upper).sum()
        stress = C.allreduce(se)
        return X, stress

    return jax.jit(mesh.shard_map(
        run, in_specs=(mesh.spec(0), mesh.spec(0), P(), P()),
        out_specs=(P(), P()),
    ))


def make_wsmacof_fn(mesh: WorkerMesh, cfg: MDSConfig, n_pad: int):
    """Weighted SMACOF: ``X ← CG-solve(V, B(X) X)`` with the weight
    Laplacian V applied row-sharded (one allgather per CG step) — the
    WDA-SMACOF iteration proper (weights 0 drop a dissimilarity from the
    objective; the unweighted closed form is :func:`make_smacof_fn`)."""

    def run(delta_rows, w_rows, row_mask, X0, n_real):
        me0 = jax.lax.axis_index("workers") * delta_rows.shape[0]
        n_loc = delta_rows.shape[0]

        def live_mask():
            return row_mask[:, None] * jnp.where(
                jnp.arange(n_pad)[None, :] < n_real, 1.0, 0.0)

        def dist_block(X):
            Xl = jax.lax.dynamic_slice_in_dim(X, me0, n_loc, 0)
            x2 = (Xl ** 2).sum(-1)[:, None]
            y2 = (X ** 2).sum(-1)[None, :]
            d2 = x2 - 2.0 * (Xl @ X.T) + y2
            return jnp.sqrt(jnp.maximum(d2, 0.0)), Xl

        def center(X):
            # kill V's translation null space: center over live rows
            m = jnp.where(jnp.arange(n_pad) < n_real, 1.0, 0.0)[:, None]
            return (X - (X * m).sum(0) / jnp.maximum(n_real, 1.0)) * m

        def v_apply(Y, w_live, vdiag):
            # (V Y) rows = vdiag ⊙ Y_local − W_block @ Y, assembled globally
            Yl = jax.lax.dynamic_slice_in_dim(Y, me0, n_loc, 0)
            rows = vdiag[:, None] * Yl - w_live @ Y
            return C.allgather(rows)

        def body(X, _):
            D, Xl = dist_block(X)
            lm = live_mask()
            w_live = w_rows * lm
            vdiag = w_live.sum(1)
            ratio = jnp.where(D > cfg.eps,
                              w_live * delta_rows / jnp.maximum(D, cfg.eps),
                              0.0)
            bz_rows = ratio.sum(1)[:, None] * Xl - ratio @ X
            rhs = center(C.allgather(bz_rows))

            # CG on the replicated [N, dim] system (V is PSD on the
            # centered subspace; all vectors stay replicated, the only
            # distributed op is v_apply's row block + allgather)
            x = center(X)
            r = rhs - v_apply(x, w_live, vdiag)
            p = r
            rs = (r * r).sum()
            rs0 = rs
            rhs_sq = (rhs * rhs).sum()

            def cg_step(st, _):
                x, r, p, rs = st
                # freeze once converged: on the singular system (zero
                # weights enlarge V's null space beyond translations, and
                # can even disconnect the weight graph), iterating past
                # convergence divides f32 noise by f32 noise and explodes.
                # Two guards: a relative one vs the initial residual AND an
                # absolute floor vs |rhs|² (rs0 itself can already be f32
                # noise when the solve starts at convergence); plus a
                # curvature gate — on a direction with ~0/negative p·Vp the
                # step is meaningless, so take alpha = 0 and restart p ← r.
                vp = v_apply(p, w_live, vdiag)
                pvp = (p * vp).sum()
                step_ok = ((rs > 1e-12 * rs0 + 1e-30)
                           & (rs > 1e-10 * rhs_sq + 1e-30)
                           & (pvp > 1e-12 * (p * p).sum()))
                alpha = jnp.where(step_ok,
                                  rs / jnp.maximum(pvp, 1e-30), 0.0)
                x = x + alpha * p
                r = r - alpha * vp
                rs_new = (r * r).sum()
                beta = jnp.where(step_ok,
                                 rs_new / jnp.maximum(rs, 1e-30), 0.0)
                p = r + beta * p
                return (x, r, p, rs_new), None

            (x, _, _, _), _ = jax.lax.scan(
                cg_step, (x, r, p, rs), None, length=cfg.cg_iters)
            return center(x), None

        X, _ = jax.lax.scan(body, X0, None, length=cfg.iters)
        # weighted final stress: Σ_{i<j} w (δ − d)²
        D, _ = dist_block(X)
        lm = live_mask()
        upper = (jnp.arange(n_pad)[None, :]
                 > (me0 + jnp.arange(n_loc))[:, None])
        se = ((delta_rows - D) ** 2 * w_rows * lm * upper).sum()
        return X, C.allreduce(se)

    return jax.jit(mesh.shard_map(
        run, in_specs=(mesh.spec(0), mesh.spec(0), mesh.spec(0), P(), P()),
        out_specs=(P(), P()),
    ))


def mds(delta, cfg: MDSConfig | None = None, mesh: WorkerMesh | None = None,
        seed=0, weights=None):
    """Embed points from dissimilarity matrix delta [n, n] → [n, dim].

    ``weights`` (optional [n, n], symmetric, nonnegative): per-pair
    importance; 0 removes a dissimilarity from the objective (the "W" in
    WDA-MDS — e.g. for missing/unreliable δ entries).  None uses the
    unweighted closed-form V⁺."""
    mesh = mesh or current_mesh()
    cfg = cfg or MDSConfig()
    delta = np.asarray(delta, np.float32)
    n = delta.shape[0]
    nw = mesh.num_workers
    n_pad = -(-n // nw) * nw
    rows = np.zeros((n_pad, n_pad), np.float32)
    rows[:n, :n] = delta
    if cfg.delta_dtype == "bf16":
        # cast BEFORE sharding so the staged H2D bytes halve (the point
        # of the knob); jnp.bfloat16 is a real numpy dtype here, and the
        # in-program arithmetic promotes δ back to f32
        rows = rows.astype(jnp.bfloat16)
    mask = np.zeros(n_pad, np.float32)
    mask[:n] = 1.0
    X0 = np.random.default_rng(seed).normal(size=(n_pad, cfg.dim)).astype(np.float32)

    if weights is None:
        fn = make_smacof_fn(mesh, cfg, n_pad)
        X, stress = fn(mesh.shard_array(rows, 0), mesh.shard_array(mask, 0),
                       jax.device_put(jnp.asarray(X0), mesh.replicated()),
                       jnp.float32(n))
        return np.asarray(X)[:n], float(np.asarray(stress))
    w = np.asarray(weights, np.float32)
    if w.shape != delta.shape:
        raise ValueError(f"weights shape {w.shape} != delta shape {delta.shape}")
    if (w < 0).any():
        raise ValueError("weights must be nonnegative")
    w_rows = np.zeros((n_pad, n_pad), np.float32)
    w_rows[:n, :n] = w
    np.fill_diagonal(w_rows, 0.0)  # self-pairs never contribute
    fn = make_wsmacof_fn(mesh, cfg, n_pad)
    X, stress = fn(mesh.shard_array(rows, 0), mesh.shard_array(w_rows, 0),
                   mesh.shard_array(mask, 0),
                   jax.device_put(jnp.asarray(X0), mesh.replicated()),
                   jnp.float32(n))
    return np.asarray(X)[:n], float(np.asarray(stress))


def benchmark(n=4096, mesh=None, seed=0, coord_wire="exact",
              delta_dtype="f32", algo="xla"):
    rng = np.random.default_rng(seed)
    # 4-D points embedded into dim=3: genuinely LOSSY, so final_stress
    # is bounded away from 0 and the coord_wire flip gate's 2% relative
    # tolerance grades a real number — a perfectly-embeddable benchmark
    # (3-D into 3-D) converges to stress ~0 and a relative quality gate
    # against ~0 refuses every wire unconditionally (vacuous gate)
    pts = rng.normal(size=(n, 4)).astype(np.float32)
    delta = np.sqrt(((pts[:, None] - pts[None]) ** 2).sum(-1))
    cfg = MDSConfig(dim=3, iters=30, coord_wire=coord_wire,
                    delta_dtype=delta_dtype, algo=algo)
    mds(delta, cfg, mesh, seed)  # warmup/compile
    t0 = time.perf_counter()
    X, stress = mds(delta, cfg, mesh, seed)
    dt = time.perf_counter() - t0
    return {"sec_total": dt, "iters_per_sec": cfg.iters / dt,
            "final_stress": stress, "n": n, "coord_wire": coord_wire,
            "delta_dtype": delta_dtype, "algo": algo}


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser(description="harp-tpu WDA-MDS (edu.iu.wdamds parity)")
    p.add_argument("--n", type=int, default=4096)
    p.add_argument("--algo", choices=("xla", "pallas"), default="xla",
                   help="Guttman-step schedule (pallas = the fused "
                        "distance + B·X kernel, flip candidate "
                        "wdamds_dist_pallas; unweighted path only)")
    args = p.parse_args(argv)
    from harp_tpu.utils.metrics import benchmark_json

    print(benchmark_json("wdamds_cli", benchmark(args.n, algo=args.algo)))


if __name__ == "__main__":
    main()
