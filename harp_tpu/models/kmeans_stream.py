"""Streaming / blocked-epoch KMeans — the 1B-point north-star path.

Reference parity (SURVEY.md §1, §7): the north-star metric is "KMeans
iter/sec (1B pts, k=1k)".  1B×300 f32 is 1.2 TB (int8: 300 GB) — it
cannot be device-resident on one chip (v5e: 16 GB HBM), and Harp never
needed it resident either: each mapper streamed its HDFS file split
through memory.  The TPU-native equivalent keeps ONLY the centroids
[k, d] and the partial accumulators [k, d]+[k] device-resident and
streams the points through HBM in fixed-shape chunks:

- **Real data** (:func:`fit_streaming`): host chunks (numpy / np.memmap,
  so the source may be a disk file far larger than RAM) are padded to one
  static shape, double-buffered onto the mesh with ``jax.device_put``
  (async dispatch overlaps the transfer of chunk j+1 with the compute of
  chunk j), and accumulated per-worker on device.  One ``allreduce`` per
  epoch — not per chunk — merges the partials, exactly Harp's
  regroup+allgather phase at epoch granularity.  ``quantize="int8"``
  streams int8 chunks (¼ the host→HBM bytes; scales from one chunked
  host pre-pass).
- **Synthetic at full scale** (:func:`benchmark_streaming`): the whole
  multi-epoch run is ONE jitted program; chunk j is regenerated on device
  from a PRNG keyed by j alone (every epoch revisits the same points —
  regeneration is the stand-in for re-reading a file split, it never
  touches the relay), so the 1B×300 k=1000 config is *formulable* on a
  single chip in bounded HBM and trivially shards over a pod mesh.

Peak HBM per worker ≈ chunk_rows × (d + k) × 4 bytes for the points
block + score matrix (the [chunk, k] scores dominate at k=1000), plus
the [k, d] state — the ``chunk_points`` knob bounds it explicitly.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from harp_tpu.ingest import IngestPipeline
from harp_tpu.parallel import collective as C
from harp_tpu.parallel.mesh import WorkerMesh, current_mesh
from harp_tpu.utils import prng, telemetry
from harp_tpu.utils.timing import device_sync

from harp_tpu.models.kmeans import (  # shared MXU partials formulation
    _INT8_SUM_ROW_LIMIT,
    _check_int8_chunk_rows,
    _clip_round_int8,
    _normalize_centroids,
    _partials_block,
    _partials_block_int8,
    kmeanspp_init,
)


@dataclasses.dataclass
class StreamConfig:
    # epoch counts are runtime arguments (fit_streaming(iters=...) /
    # run_fn(..., n_iters)), never config state: the synthetic program
    # traces n_iters as a scalar so changing it can't recompile
    k: int = 1000
    # rows per streamed chunk (across the whole mesh; rounded up to a
    # multiple of num_workers).  Bounds peak HBM: the dominant buffers are
    # the [chunk/nw, d] points block and [chunk/nw, k] score matrix —
    # 262144×(300+1000)×4 ≈ 1.4 GB at the north-star shapes.
    chunk_points: int = 262_144
    dtype: Any = jnp.float32
    quantize: str | None = None  # None | "int8" (host-quantized chunks)

    def __post_init__(self):
        if self.k < 1:
            raise ValueError(f"k must be >= 1, got {self.k}")
        if self.chunk_points < 1:
            raise ValueError(f"chunk_points must be >= 1, got {self.chunk_points}")
        if self.quantize not in (None, "int8"):
            raise ValueError(f"quantize must be None or 'int8', got {self.quantize!r}")


def _make_accum_fn(mesh: WorkerMesh, cfg: StreamConfig):
    """Per-chunk accumulate: NO collective inside — partials land in a
    per-worker accumulator ([nw, k, d] sharded on dim 0); the epoch-end
    :func:`_make_finish_fn` does the one allreduce."""

    def accum(pts, mask, centroids, sums, counts, inertia):
        # per-worker views: pts [chunk/nw, d], sums [1, k, d], counts
        # [1, k], inertia [1]; centroids replicated
        c2 = (centroids.astype(jnp.float32) ** 2).sum(-1)
        if cfg.quantize == "int8":
            pts_q, col_scale = pts
            s, c, i = _partials_block_int8(pts_q, col_scale, centroids, c2,
                                           mask=mask)
        else:
            # chunks may arrive in a narrow wire dtype (f16 disk data
            # ships as f16 — half the H2D bytes); the widening cast is
            # exact, so this is bit-identical to casting on the host
            s, c, i = _partials_block(pts.astype(cfg.dtype), centroids,
                                      c2, mask=mask)
        return sums + s[None], counts + c[None], inertia + i[None]

    pts_spec = ((mesh.spec(0), P()) if cfg.quantize == "int8"
                else mesh.spec(0))
    sh = mesh.spec(0)
    return jax.jit(mesh.shard_map(
        accum,
        in_specs=(pts_spec, mesh.spec(0), P(), sh, sh, sh),
        out_specs=(sh, sh, sh),
    ))


def _make_finish_fn(mesh: WorkerMesh):
    """Epoch tail: allreduce the per-worker partials, normalize, keep old
    centroid on empty clusters (same rule as kmeans.fit)."""

    def finish(sums, counts, inertia, centroids):
        s, c, i = C.allreduce((sums[0], counts[0], inertia[0]))
        return _normalize_centroids(s, c, centroids), i

    sh = mesh.spec(0)
    return jax.jit(mesh.shard_map(
        finish, in_specs=(sh, sh, sh, P()), out_specs=(P(), P())))


def _validate_explicit_init(init, k, d):
    """The ONE explicit-``[k, d]``-init check, shared by every fit
    variant — k AND the feature dim, so a mismatch fails here with a
    plain message, not inside a jitted matmul."""
    arr = np.asarray(init, np.float32)
    if arr.ndim != 2 or arr.shape[0] != k or arr.shape[1] != d:
        raise ValueError(f"explicit init must be [k={k}, d={d}], "
                         f"got shape {arr.shape}")
    return arr


def _topup_rows(rows, count, rng):
    """Pad ``rows`` to exactly ``count`` by UNIFORM resampling (equal
    allgather shapes across processes; no positional bias)."""
    if rows.shape[0] >= count:
        return rows[:count]
    extra = rng.choice(rows.shape[0], size=count - rows.shape[0])
    return np.concatenate([rows, rows[np.sort(extra)]], 0)


def _init_centroids(points, n, k, seed, init):
    """Same seeding contract as kmeans.fit, but memmap-safe: only the
    selected rows are ever materialized.  ``init`` may also be an
    explicit ``[k, d]`` array (warm start / cross-variant comparisons)."""
    if not isinstance(init, str):  # explicit centroids
        return _validate_explicit_init(init, k, points.shape[1])
    if init == "kmeans++":
        rng = np.random.default_rng(0 if seed is None else seed)
        idx = np.sort(rng.choice(n, size=min(n, 50_000), replace=False))
        return kmeanspp_init(np.asarray(points[idx], np.float32), k,
                             seed=0 if seed is None else seed)
    if init != "random":
        raise ValueError(f"init must be 'random' or 'kmeans++', got {init!r}")
    if seed is None:
        idx = np.arange(k)
    else:
        idx = np.sort(np.random.default_rng(seed).choice(n, size=k,
                                                         replace=False))
    return np.asarray(points[idx], np.float32)


def _int8_amax(points, n, chunk):
    """Per-feature |max| over a source in one chunked host pass (a
    memmap never loads more than one chunk)."""
    amax = np.zeros(points.shape[1], np.float32)
    for lo in range(0, n, chunk):
        blk = np.asarray(points[lo:lo + chunk], np.float32)
        np.maximum(amax, np.abs(blk).max(0), out=amax)
    return amax


def _amax_to_scales(amax):
    """THE int8 scale rule — one place, so the single-source and
    sharded-ingest paths can never disagree on it."""
    return np.maximum(amax, 1e-30) / 127.0


def _int8_scales(points, n, chunk):
    return _amax_to_scales(_int8_amax(points, n, chunk))


# wire-dtype codes for the cross-process agreement allgather (0 = "ship
# the compute dtype"); only narrow FLOAT formats are worth a code — int
# sources upcast host-side as before
_WIRE_CODES = {"float16": 1, "bfloat16": 2}
_WIRE_FROM_CODE = {1: "float16", 2: "bfloat16"}


def _resolve_wire_dtype(wire, np_dtype, src_dtype):
    """H2D payload dtype for the float chunk-streaming paths.

    ``wire="auto"`` (the default) ships the SOURCE dtype when it is a
    narrower float than the compute dtype — f16 disk data crosses
    host→device as f16 and widens on device, which is bit-identical to
    the host-side cast (widening is exact) at half the transfer bytes;
    the relay/PCIe link is the streaming bottleneck, not HBM
    (BASELINE.md real-ingest rows).  Anything else — f32 sources, int
    sources, mixed-file sets (``src_dtype=None``) — ships the compute
    dtype unchanged.  An explicit dtype forces the wire format;
    narrower than the source is a LOSSY opt-in compression (e.g.
    ``wire_dtype=jnp.bfloat16`` on f32 data).  ``wire=None`` restores
    the legacy ship-compute-dtype behavior.

    Multi-host: every process must resolve the SAME wire dtype or the
    per-host chunk programs compile differently and the job deadlocks —
    "auto" allgathers a dtype code and falls back to the compute dtype
    unless all processes agree.
    """
    if wire is None:
        return np_dtype
    if isinstance(wire, str) and wire == "auto":
        name = np.dtype(src_dtype).name if src_dtype is not None else None
        code = _WIRE_CODES.get(name, 0)
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils as mh

            codes = np.atleast_1d(np.asarray(
                mh.process_allgather(np.int64(code))))
            code = int(codes[0]) if (codes == codes[0]).all() else 0
        wire_np = (np.dtype(_WIRE_FROM_CODE[code]) if code else np_dtype)
        return wire_np if wire_np.itemsize < np_dtype.itemsize else np_dtype
    w = np.dtype(jnp.dtype(wire).name)
    if w.name not in ("float16", "bfloat16", "float32", "float64"):
        raise ValueError(f"wire_dtype must be a float dtype, got {w.name}")
    return w


def fit_streaming(points, k=1000, iters=10, chunk_points=262_144,
                  mesh: WorkerMesh | None = None, seed=0,
                  dtype=jnp.float32, quantize=None, init="random",
                  return_history=False, ckpt_dir=None, ckpt_every=5,
                  max_restarts=3, fault=None, instrument=None,
                  wire_dtype="auto", prefetch=2):
    """Blocked-epoch Lloyd over a source too large for HBM.

    ``wire_dtype``: H2D payload format (:func:`_resolve_wire_dtype`) —
    "auto" ships narrow-float sources (f16 disk) in their own dtype and
    widens on device: bit-identical results, half the transfer bytes.

    ``prefetch``: host-pipeline work-ahead depth
    (:class:`harp_tpu.ingest.IngestPipeline`, PR 8).  ``>= 2`` (default
    2) runs read/parse and pad/quantize on background threads so chunk
    j+1's host stages overlap chunk j's transfer AND compute; masks ship
    once and memmap sources ride a single-copy chain (device_put reads
    the mapped pages directly).  ``1`` runs the same staged chain inline
    (serial); ``0`` selects the pre-pipeline serial loop verbatim — the
    measured A/B incumbent in scripts/bench_ingest.py: the staged chain
    sustains 1.7-2.2× the legacy loop's host byte rate at the smoke A/B
    shape (1-core CPU host, 2026-08-04; BENCH_local
    kmeans_ingest_ab_smoke).  Every depth is bit-exact: the stages are
    deterministic per chunk and chunks are consumed in order.

    ``points``: [n, d] numpy array, ``np.memmap``, or any sequential
    source honoring the slice contract (``harp_tpu.native.CSVPoints``).
    Semantics are identical to ``kmeans.fit`` — one epoch assigns EVERY
    point against the epoch-start centroids, so the result is full-batch
    Lloyd, not minibatch — only the execution is chunked.  One deliberate
    seeding divergence: ``init="kmeans++"`` runs the D² seeding on a
    uniform subsample of at most 50 000 rows (``_init_centroids``), not
    the full source — exact kmeans++ needs k full passes over the data
    (k=1000 → 1000 sweeps of a 1.2 TB file); the subsample keeps seeding
    O(1) while Lloyd itself remains exact full-batch.  Returns
    ``(centroids [k, d], inertia)`` (+ per-epoch inertia history with
    ``return_history=True``; the history is read back in one stacked
    transfer at the end — never per epoch, per the relay dispatch trap).

    ``ckpt_dir`` enables checkpoint/resume with the same recovery
    contract as the other model ``fit``\\ s (utils.fault.fit_epochs):
    a 1B-point run is exactly the multi-hour job that needs to survive a
    preemption.  Epochs are deterministic given the centroids (the data
    is re-read each sweep), so centroids + completed history are the
    whole state.

    ``instrument``: pass an empty dict to collect per-epoch pipeline
    timing under key ``"epochs"``: ``host_s`` (time blocked in
    ``put_chunk`` — disk read/parse + pad + H2D dispatch; the part device
    compute is supposed to hide behind), ``sync_s`` (device tail NOT
    hidden: blocking wait on the epoch result after the last chunk), and
    ``epoch_s`` (wall).  Instrumented runs deliberately pay ONE extra
    device sync per epoch (a relay round trip, 20–150 ms — negligible
    against multi-second epochs, but don't instrument micro-runs you
    intend to time).  Consumed by :func:`benchmark_ingest`.
    """
    mesh = mesh or current_mesh()
    n, d = points.shape
    nw = mesh.num_workers
    cfg = StreamConfig(k=k, chunk_points=chunk_points,
                       dtype=dtype, quantize=quantize)
    chunk = -(-min(cfg.chunk_points, n) // nw) * nw  # static chunk shape

    init_c = _init_centroids(points, n, k, seed, init)
    centroids = jax.device_put(jnp.asarray(init_c, dtype=dtype),
                               mesh.replicated())
    np_dtype = np.dtype(jnp.dtype(dtype).name)
    wire_np = _resolve_wire_dtype(wire_dtype, np_dtype,
                                  getattr(points, "dtype", None))
    scale_dev = None
    if quantize == "int8":
        # same exact-int32 accumulation bound as kmeans.fit — here it
        # applies PER CHUNK (cross-chunk accumulation is f32); the limit
        # resolves at call time so tests can shrink it
        _check_int8_chunk_rows(chunk // nw, _INT8_SUM_ROW_LIMIT)
        scales = _int8_scales(points, n, chunk)
        scale_dev = jax.device_put(jnp.asarray(scales), mesh.replicated())

    if iters == 0:  # same contract as kmeans.fit(iters=0)
        return (np.asarray(init_c, np.float32), 0.0, np.zeros(0, np.float32)
                ) if return_history else (np.asarray(init_c, np.float32), 0.0)
    offsets = list(range(0, n, chunk))
    pipe, h2d_epoch = _make_source_pipeline(
        mesh, points, offsets, chunk, n, d, quantize,
        scales if quantize == "int8" else None, scale_dev, wire_np,
        prefetch)
    return _stream_train(mesh, cfg, pipe, len(offsets), centroids, iters,
                         dtype, return_history, ckpt_dir, ckpt_every,
                         max_restarts, fault, instrument,
                         epoch_h2d_bytes=h2d_epoch)


def _legacy_put_chunk(mesh, points, chunk, n, d, quantize, scales,
                      scale_dev, wire_np):
    """The pre-PR-8 serial host chain, verbatim: materialize the slice,
    build + upload a fresh mask per chunk, pad, cast, ship.  Kept as the
    runnable INCUMBENT arm of the bench_ingest A/B (``prefetch=0``) —
    the committed pipeline-speedup row needs the loop it beat to stay
    measurable; numerics are identical to the staged chain."""

    def put_chunk(lo):
        hi = min(lo + chunk, n)
        blk = np.asarray(points[lo:hi])
        m = np.zeros(chunk, np.float32)
        m[:hi - lo] = 1.0
        if hi - lo < chunk:  # pad the tail to the one static shape
            pad = np.zeros((chunk - (hi - lo), d), blk.dtype)
            blk = np.concatenate([blk, pad], 0)
        if quantize == "int8":
            q = _clip_round_int8(blk.astype(np.float32), scales)
            return ((mesh.shard_array(q, 0), scale_dev),
                    mesh.shard_array(m, 0))
        return (mesh.shard_array(blk.astype(wire_np, copy=False), 0),
                mesh.shard_array(m, 0))

    return put_chunk


def _make_source_pipeline(mesh, points, offsets, chunk, n, d, quantize,
                          scales, scale_dev, wire_np, prefetch):
    """(:class:`IngestPipeline`, exact per-epoch H2D bytes) for a
    sliceable source (ndarray / np.memmap / CSVPoints).

    The staged chain does strictly less host work than the legacy loop:
    masks are j-independent (all-ones for full chunks, ONE tail shape)
    and epoch-independent, so they ship once here and the device arrays
    are reused every chunk — and ``read`` hands the raw slice through
    (np.memmap slices stay lazy views; the single data copy happens
    inside ``shard_array``'s device_put, which reads the mapped pages
    directly, instead of materialize-then-ship).  ``prep`` pads the
    tail, quantizes, or casts to the wire dtype — the CPU-bound stage
    the background threads overlap with transfer + compute when
    ``prefetch >= 2``.  ``prefetch=0`` returns the legacy chain."""
    n_chunks = len(offsets)
    if prefetch == 0:
        legacy = _legacy_put_chunk(mesh, points, chunk, n, d, quantize,
                                   scales, scale_dev, wire_np)
        itemsize = 1 if quantize == "int8" else wire_np.itemsize
        pipe = IngestPipeline(lambda j: legacy(offsets[j]), depth=1,
                              tag="kmeans_stream.legacy", stall_warn=None)
        return pipe, n_chunks * chunk * (d * itemsize + 4)

    tail = n - offsets[-1]
    mask_full = mask_tail = None
    if n_chunks > 1 or tail == chunk:
        mask_full = mesh.shard_array(np.ones(chunk, np.float32), 0)
    if tail < chunk:
        m = np.zeros(chunk, np.float32)
        m[:tail] = 1.0
        mask_tail = mesh.shard_array(m, 0)

    def read(j):
        lo = offsets[j]
        return points[lo:min(lo + chunk, n)]

    def prep(blk):
        rows = blk.shape[0]
        if rows < chunk:
            pad = np.zeros((chunk - rows, d), blk.dtype)
            blk = np.concatenate([np.asarray(blk), pad], 0)
        if quantize == "int8":
            return _clip_round_int8(np.asarray(blk, np.float32),
                                    scales), rows
        # no copy when the source already holds the wire dtype — the
        # widening/narrowing cast (when any) is the only transform
        return np.asarray(blk, wire_np), rows

    def ship(prepped):
        blk, rows = prepped
        m = mask_full if rows == chunk else mask_tail
        data = mesh.shard_array(blk, 0)
        if quantize == "int8":
            return (data, scale_dev), m
        return data, m

    pipe = IngestPipeline(read, prep, ship, depth=max(1, prefetch),
                          tag="kmeans_stream.ingest")
    itemsize = 1 if quantize == "int8" else wire_np.itemsize
    return pipe, n_chunks * chunk * d * itemsize


def _stream_train(mesh, cfg, pipe, n_chunks, centroids, iters, dtype,
                  return_history, ckpt_dir, ckpt_every, max_restarts,
                  fault, instrument, epoch_h2d_bytes=None,
                  epoch_reset=None):
    """The shared blocked-epoch driver behind every ``fit_streaming*``
    variant: prefetch-pipelined chunk loop (:class:`IngestPipeline`,
    PR 8), one allreduce per epoch, checkpoint/resume, optional pipeline
    timing.  ``pipe.stream(n_chunks)`` yields the epoch's device chunk
    inputs in order; ``epoch_reset`` (file-split sources) rewinds the
    readers before each sweep.  Each epoch's chunk loop runs under a
    warn-mode flight budget — exactly ``epoch_h2d_bytes`` on the wire
    and zero recompiles once the first epoch owns the accum compile —
    so the relay transfer traps fail loudly on CPU, not on silicon."""
    nw = mesh.num_workers
    k = cfg.k
    d = int(centroids.shape[-1])
    accum_fn = _make_accum_fn(mesh, cfg)
    finish_fn = _make_finish_fn(mesh)
    zeros = lambda: (
        jax.device_put(jnp.zeros((nw, k, d), jnp.float32), mesh.sharding(mesh.spec(0))),
        jax.device_put(jnp.zeros((nw, k), jnp.float32), mesh.sharding(mesh.spec(0))),
        jax.device_put(jnp.zeros((nw,), jnp.float32), mesh.sharding(mesh.spec(0))),
    )
    history: list = []
    epoch_idx = 0

    def train_one():
        nonlocal centroids, epoch_idx
        ep0 = time.perf_counter()
        sums, counts, inertia = zeros()
        if epoch_reset is not None:
            epoch_reset()
        with telemetry.budget(h2d_bytes=epoch_h2d_bytes,
                              compiles=None if epoch_idx == 0 else 0,
                              action="warn", tag="kmeans_stream.ingest"):
            for cur in pipe.stream(n_chunks):
                sums, counts, inertia = accum_fn(cur[0], cur[1], centroids,
                                                 sums, counts, inertia)
        epoch_idx += 1
        new_c, ep_inertia = finish_fn(sums, counts, inertia, centroids)
        centroids = new_c
        history.append(ep_inertia)
        if instrument is not None:  # one deliberate sync/epoch (docstring)
            t = time.perf_counter()
            device_sync(ep_inertia)
            instrument.setdefault("epochs", []).append({
                # blocked_s is the comparable of the old "time in
                # put_chunk": caller time spent inside the ingest path
                "host_s": pipe.stats.blocked_s,
                "sync_s": time.perf_counter() - t,
                "epoch_s": time.perf_counter() - ep0,
                "pipeline": pipe.stats.as_dict(),
            })

    def get_state():
        # LIVE objects, zero syncs: fit_epochs calls this every epoch (not
        # just at checkpoints) and CheckpointManager.save materializes at
        # save time itself; a per-epoch jnp.stack+readback here would cost
        # two relay round trips per sweep and break the double buffer
        return {"centroids": centroids, "hist": list(history)}

    def set_state(state):
        nonlocal centroids, history
        check_restored_shapes([("centroids", state["centroids"], centroids)])
        c = state["centroids"]
        if isinstance(c, jax.Array):      # normal step-to-step flow
            centroids = c
            history = list(state["hist"])
        else:                             # numpy from a fresh restore
            centroids = jax.device_put(
                jnp.asarray(np.asarray(c), dtype=dtype), mesh.replicated())
            history = [np.float32(v) for v in state["hist"]]

    from harp_tpu.utils.fault import check_restored_shapes, fit_epochs

    try:
        fit_epochs(train_one, get_state, set_state, iters, ckpt_dir,
                   ckpt_every=ckpt_every, max_restarts=max_restarts,
                   fault=fault, phase="kmeans_stream.iters")
    finally:
        pipe.close()  # reap the stage threads on every exit path
    final = np.asarray(jnp.stack(history))  # ONE readback for all epochs
    c_host = np.asarray(centroids)
    if return_history:
        return c_host, float(final[-1]), final
    return c_host, float(final[-1])


def fit_streaming_local(points_local, k=1000, iters=10,
                        chunk_points=262_144, mesh: WorkerMesh | None = None,
                        seed=0, dtype=jnp.float32, quantize=None,
                        init="random", return_history=False, ckpt_dir=None,
                        ckpt_every=5, max_restarts=3, fault=None,
                        instrument=None, wire_dtype="auto", prefetch=2):
    """Multi-host blocked-epoch Lloyd where EACH PROCESS streams only its
    own split — Harp's HDFS-split ingest (SURVEY.md §4.2 "load points
    shard"): no host ever reads or materializes the whole dataset, so
    the measured ~14 GB/s single-host ingest floor (BASELINE.md) divides
    by the process count.

    ``points_local``: this process's ``[n_local, d]`` slice (ndarray or
    ``np.memmap``; a random-slicing source — the per-epoch access walks
    each local worker's sub-slice, not one ascending scan, so
    ``CSVPoints`` is not supported here).  The global row order is
    process-major (process p's rows precede p+1's), each process's rows
    block-partitioned over its local devices.  Semantics match
    :func:`fit_streaming`: full-batch Lloyd, every point visited once
    per epoch against epoch-start centroids — with an explicit ``init``
    array the two produce the same clustering up to partial-sum rounding
    (tested in tests/multiproc_worker.py).  Single-process it is simply
    ``fit_streaming`` with a different chunk layout.

    ``init``: "random" (each process contributes ⌈k/nproc⌉ seed rows,
    allgathered, first k kept), "kmeans++" (D² seeding on an allgathered
    ≤50k-row subsample, ⌈50k/nproc⌉ per process), or an explicit
    ``[k, d]`` array.  ``quantize="int8"`` works across hosts: each
    process takes the per-feature |max| over ITS split (one chunked
    pass) and the scales are the allgathered elementwise max — identical
    to the single-source scales on the same global data.  Other knobs —
    checkpoint/resume, ``instrument`` — behave as in
    :func:`fit_streaming`.
    """
    mesh = mesh or current_mesh()
    nw = mesh.num_workers
    nproc = jax.process_count()
    if nw % nproc:
        raise ValueError(f"{nw} workers do not divide over {nproc} processes")
    ldev = nw // nproc               # workers (devices) on this process
    n_local, d = points_local.shape
    if n_local == 0:
        raise ValueError("every process must hold at least one row "
                         "(this one has an empty split)")
    cfg = StreamConfig(k=k, chunk_points=chunk_points, dtype=dtype,
                       quantize=quantize)
    np_dtype = np.dtype(jnp.dtype(dtype).name)
    # resolved BEFORE any other collective: "auto" allgathers a dtype
    # code, and collective order must match across processes
    wire_np = _resolve_wire_dtype(wire_dtype, np_dtype,
                                  getattr(points_local, "dtype", None))

    from jax.experimental import multihost_utils as mh

    n_all = np.atleast_1d(np.asarray(
        mh.process_allgather(np.int64(n_local))))          # [nproc]
    npw = -(-n_local // ldev)        # rows per LOCAL worker (this process)
    npw_all = -(-n_all // ldev)      # the same, per process
    # chunk rows per worker: derived from GLOBAL info so every process
    # builds the same static [nw*cl] chunk shape; per-process shortfall
    # is padding (mask 0)
    cl = max(1, min(-(-cfg.chunk_points // nw), int(npw_all.max())))
    # every process loops the global max chunk count (late ones all-pad)
    n_chunks = int((-(-npw_all // cl)).max())
    scale_dev = scales = None
    if quantize == "int8":
        _check_int8_chunk_rows(cl, _INT8_SUM_ROW_LIMIT)
        # global per-feature scales = allgathered max of LOCAL |max|es:
        # same amax pass + scale rule as the single-source _int8_scales
        amax = np.asarray(mh.process_allgather(
            _int8_amax(points_local, n_local, ldev * cl))
        ).reshape(-1, d).max(0)
        scales = _amax_to_scales(amax)
        scale_dev = jax.device_put(jnp.asarray(scales), mesh.replicated())

    def local_seed_rows(count, rng_seed):
        """``count`` rows of this split (equal shape on every process for
        the allgather).  A split shorter than ``count`` is topped up by
        UNIFORM resampling — no positional bias, unlike a cyclic pad."""
        rng = np.random.default_rng(0 if rng_seed is None else rng_seed)
        if n_local >= count:
            idx = (np.arange(count) if rng_seed is None
                   else rng.choice(n_local, size=count, replace=False))
        else:
            idx = np.concatenate([np.arange(n_local),
                                  rng.choice(n_local, count - n_local)])
        return np.asarray(points_local[np.sort(idx)], np.float32)

    if not isinstance(init, str):
        init_c = _init_centroids(points_local, n_local, k, seed, init)
    elif init == "random":
        per = -(-k // nproc)
        if n_local < per:
            # resampled rows would be exact DUPLICATE centroids —
            # permanently-empty clusters that silently degrade the fit
            # (fit_streaming's n < k case raises too); seed explicitly
            raise ValueError(
                f"init='random' needs >= ceil(k/nproc) = {per} rows per "
                f"process split, this one has {n_local}; pass an explicit "
                "[k, d] init array instead")
        mine = local_seed_rows(per, None if seed is None else seed)
        init_c = np.asarray(mh.process_allgather(mine)).reshape(-1, d)[:k]
    elif init == "kmeans++":
        # subsample sized by the GLOBAL row count (matching fit_streaming's
        # min(n, 50k) contract), split evenly across processes
        per = -(-min(50_000, int(n_all.sum())) // nproc)
        sub = np.asarray(mh.process_allgather(
            local_seed_rows(per, 0 if seed is None else seed))).reshape(-1, d)
        init_c = kmeanspp_init(sub, k, seed=0 if seed is None else seed)
    else:
        raise ValueError(f"init must be 'random', 'kmeans++' or a [k, d] "
                         f"array, got {init!r}")
    centroids = jax.device_put(jnp.asarray(init_c, dtype=dtype),
                               mesh.replicated())

    def read(j):
        # stage 1: assemble this process's per-worker raw rows into the
        # one static local chunk shape (the disk/page-cache reads)
        asm_dtype = np.float32 if quantize == "int8" else wire_np
        blk = np.zeros((ldev * cl, d), asm_dtype)
        msk = np.zeros(ldev * cl, np.float32)
        for w in range(ldev):
            w_end = min((w + 1) * npw, n_local)
            lo = w * npw + j * cl
            hi = min(lo + cl, w_end)
            if hi > lo:
                blk[w * cl: w * cl + hi - lo] = np.asarray(
                    points_local[lo:hi]).astype(asm_dtype, copy=False)
                msk[w * cl: w * cl + hi - lo] = 1.0
        return blk, msk

    def prep(t):
        blk, msk = t
        if quantize == "int8":
            return _clip_round_int8(blk, scales), msk
        return blk, msk

    def ship(t):
        blk, msk = t
        data = mesh.shard_array_local(blk, nw * cl)
        if quantize == "int8":
            return (data, scale_dev), mesh.shard_array_local(msk, nw * cl)
        return data, mesh.shard_array_local(msk, nw * cl)

    if iters == 0:
        return (np.asarray(init_c, np.float32), 0.0, np.zeros(0, np.float32)
                ) if return_history else (np.asarray(init_c, np.float32), 0.0)
    pipe = IngestPipeline(read, prep, ship, depth=max(1, prefetch),
                          tag="kmeans_stream.local")
    item = 1 if quantize == "int8" else wire_np.itemsize
    h2d_epoch = n_chunks * ldev * cl * (d * item + 4)  # this process
    return _stream_train(mesh, cfg, pipe, n_chunks, centroids, iters,
                         dtype, return_history, ckpt_dir, ckpt_every,
                         max_restarts, fault, instrument,
                         epoch_h2d_bytes=h2d_epoch)


def fit_streaming_files(paths, k=1000, iters=10, chunk_points=262_144,
                        mesh: WorkerMesh | None = None, seed=0,
                        dtype=jnp.float32, quantize=None, init="random",
                        return_history=False, ckpt_dir=None, ckpt_every=5,
                        max_restarts=3, fault=None, instrument=None,
                        reader_chunk_rows=65_536, info=None,
                        wire_dtype="auto", prefetch=2):
    """Blocked-epoch Lloyd over a DIRECTORY of file splits — Harp's real
    input shape (SURVEY.md §4.2): files are dealt to workers by the
    size-balanced ``multi_file_splits`` rule and each worker streams
    ONLY its own files (npy memmap or text via the native
    double-buffered parser), so in a multi-host job every file is read
    by exactly one process and the host ingest floor divides by the
    host count, file-granular like HDFS splits.

    ``paths``: resolved file list (use ``harp_tpu.fileformat.list_files``
    for a glob/dir; the list is sorted here for a deterministic
    assignment).  ``info``: pass a dict to receive ``n_total`` / ``d``
    (the CLI reports them; no other way to learn the global row count
    without a second counting pass).  ``quantize="int8"`` streams int8
    chunks with the shared scale rule — each process's
    ``FileSplits.amax`` pass (one extra streaming sweep of its files)
    feeds the allgathered global max.  Semantics are full-batch Lloyd, identical to
    :func:`fit_streaming` on the same rows (the row ORDER differs —
    worker-major over file assignments — which Lloyd does not see:
    epochs are order-independent given the same init; tested).  Workers
    may own zero files (more workers than files: their chunks are all
    padding); a whole PROCESS with zero rows works with an explicit
    ``init`` array (string seeding has nothing to sample there and
    raises).  ``init`` as in :func:`fit_streaming_local`, seeded by
    ``FileSplits.sample`` — random rows across this process's files.
    """
    from harp_tpu.native.datasource import FileSplits

    mesh = mesh or current_mesh()
    nw = mesh.num_workers
    nproc = jax.process_count()
    if nw % nproc:
        raise ValueError(f"{nw} workers do not divide over {nproc} processes")
    ldev = nw // nproc
    pid = jax.process_index()
    local_workers = range(pid * ldev, (pid + 1) * ldev)
    fs = FileSplits(sorted(paths), nw, local_workers,
                    chunk_rows=reader_chunk_rows)
    try:
        return _fit_streaming_files(fs, paths, k, iters, chunk_points,
                                    mesh, nproc, ldev, pid, local_workers,
                                    seed, dtype, quantize, init,
                                    return_history, ckpt_dir, ckpt_every,
                                    max_restarts, fault, instrument, info,
                                    wire_dtype, prefetch)
    finally:
        fs.close()  # also on iters==0 and validation raises: no fd leaks


def _fit_streaming_files(fs, paths, k, iters, chunk_points, mesh, nproc,
                         ldev, pid, local_workers, seed, dtype, quantize,
                         init, return_history, ckpt_dir, ckpt_every,
                         max_restarts, fault, instrument, info=None,
                         wire_dtype="auto", prefetch=2):
    nw = mesh.num_workers
    cfg = StreamConfig(k=k, chunk_points=chunk_points, dtype=dtype,
                       quantize=quantize)
    np_dtype = np.dtype(jnp.dtype(dtype).name)
    # before the other allgathers: collective order must match per-process
    wire_np = _resolve_wire_dtype(wire_dtype, np_dtype, fs.dtype)

    from jax.experimental import multihost_utils as mh

    n_per_worker = np.zeros(nw, np.int64)
    for w in local_workers:
        n_per_worker[w] = fs.rows(w)
    n_per_worker = np.asarray(
        mh.process_allgather(n_per_worker)).reshape(-1, nw).max(0)
    n_total = int(n_per_worker.sum())
    if n_total == 0:
        raise ValueError(f"{len(paths)} input files contain no rows")
    # feature dim must agree ACROSS processes too (each FileSplits only
    # sees its own files); a process with no files adopts the global d
    d_all = np.atleast_1d(np.asarray(
        mh.process_allgather(np.int64(fs.cols))))
    d = int(d_all.max())
    if np.any((d_all != 0) & (d_all != d)):
        raise ValueError(
            f"input files disagree on column count across processes "
            f"({sorted(set(int(v) for v in d_all if v))}) — a ragged mix "
            "would silently misalign features")
    rows_per_proc = n_per_worker.reshape(nproc, ldev).sum(1)
    cl = max(1, min(-(-cfg.chunk_points // nw), int(n_per_worker.max())))
    n_chunks = int((-(-n_per_worker // cl)).max())
    if info is not None:
        info.update({"n_total": n_total, "d": d})
    scale_dev = scales = None
    if quantize == "int8":
        _check_int8_chunk_rows(cl, _INT8_SUM_ROW_LIMIT)
        local_amax = fs.amax()
        if local_amax.shape[0] != d:   # a no-file process: contribute 0s
            local_amax = np.zeros(d, np.float32)
        amax = np.asarray(mh.process_allgather(local_amax)
                          ).reshape(-1, d).max(0)
        scales = _amax_to_scales(amax)
        scale_dev = jax.device_put(jnp.asarray(scales), mesh.replicated())

    if not isinstance(init, str):
        init_c = _validate_explicit_init(init, k, d)
    elif init in ("random", "kmeans++"):
        if (rows_per_proc == 0).any():
            raise ValueError(
                f"process(es) {np.flatnonzero(rows_per_proc == 0).tolist()}"
                " own no rows under the file assignment — string seeding "
                "has nothing to sample there; pass an explicit [k, d] "
                "init array (or use fewer workers)")
        per = -(-(k if init == "random" else min(50_000, n_total)) // nproc)
        if init == "random" and (rows_per_proc < per).any():
            # SYMMETRIC check (rows_per_proc is globally replicated): a
            # one-sided raise would leave the other processes hanging in
            # the allgather below
            short = np.flatnonzero(rows_per_proc < per).tolist()
            raise ValueError(
                f"init='random' needs >= ceil(k/nproc) = {per} rows per "
                f"process; process(es) {short} hold fewer — pass an "
                "explicit [k, d] init array instead")
        rng = np.random.default_rng((0 if seed is None else seed, pid))
        mine = _topup_rows(fs.sample(per, rng=rng), per, rng)
        gathered = np.asarray(mh.process_allgather(mine)).reshape(-1, d)
        init_c = (gathered[:k] if init == "random" else
                  kmeanspp_init(gathered, k, seed=0 if seed is None else seed))
    else:
        raise ValueError(f"init must be 'random', 'kmeans++' or a [k, d] "
                         f"array, got {init!r}")
    centroids = jax.device_put(jnp.asarray(init_c, dtype=dtype),
                               mesh.replicated())

    def read(j):
        # stateful sequential source: the pipeline's read stage runs on
        # ONE thread in submission order (IngestPipeline default), so
        # the per-worker file cursors advance exactly as the serial
        # loop's did; fs.reset() runs as _stream_train's epoch_reset
        # before each sweep's stream starts
        asm_dtype = np.float32 if quantize == "int8" else wire_np
        blk = np.zeros((ldev * cl, d), asm_dtype)
        msk = np.zeros(ldev * cl, np.float32)
        for li, w in enumerate(local_workers):
            rows = fs.next_block(w, cl)
            t = rows.shape[0]
            if t:
                blk[li * cl: li * cl + t] = rows.astype(asm_dtype,
                                                        copy=False)
                msk[li * cl: li * cl + t] = 1.0
        return blk, msk

    def prep(t):
        blk, msk = t
        if quantize == "int8":
            return _clip_round_int8(blk, scales), msk
        return blk, msk

    def ship(t):
        blk, msk = t
        data = mesh.shard_array_local(blk, nw * cl)
        if quantize == "int8":
            return (data, scale_dev), mesh.shard_array_local(msk, nw * cl)
        return data, mesh.shard_array_local(msk, nw * cl)

    if iters == 0:
        return (np.asarray(init_c, np.float32), 0.0, np.zeros(0, np.float32)
                ) if return_history else (np.asarray(init_c, np.float32), 0.0)
    pipe = IngestPipeline(read, prep, ship, depth=max(1, prefetch),
                          tag="kmeans_stream.files")
    item = 1 if quantize == "int8" else wire_np.itemsize
    h2d_epoch = n_chunks * ldev * cl * (d * item + 4)  # this process
    return _stream_train(mesh, cfg, pipe, n_chunks, centroids, iters,
                         dtype, return_history, ckpt_dir, ckpt_every,
                         max_restarts, fault, instrument,
                         epoch_h2d_bytes=h2d_epoch, epoch_reset=fs.reset)


def _make_chunk_gen(key, rows: int, d: int, dtype):
    """THE chunk generator — shared by the real synthetic program and its
    gen-only calibration twin so the two can never time different RNG
    schemes.  ``key`` is the worker's (pre-split) key; chunk j is a
    deterministic function of (worker, j), identical across epochs."""

    def gen(j):
        return jax.random.normal(jax.random.fold_in(key[0], j), (rows, d),
                                 dtype)

    return gen


def make_synthetic_run_fn(mesh: WorkerMesh, cfg: StreamConfig, d: int,
                          n_chunks: int):
    """The fully-fused formulation: fori_loop(epochs) × scan(chunks), all
    on device.  The ``key`` argument is pre-split per worker (sharded over
    the mesh); chunk j's points come from ``fold_in(worker_key, j)`` — a
    deterministic function of (worker, j) alone, so every epoch sees the
    same dataset (regeneration ≡ re-reading a file split).
    This is what makes the 1B-point config runnable on ONE chip: live HBM
    is one [chunk/nw, d] block + [chunk/nw, k] scores + the [k, d] state,
    never the dataset."""
    rows = cfg.chunk_points // mesh.num_workers

    # device-side int8 twin: the synthetic stream is N(0,1) per feature,
    # so a STATIC 5σ amax covers all but ~3e-7 of draws (clipped) — no
    # calibration pass, same _amax_to_scales rule as the ingest path
    col_scale = (jax.device_put(_amax_to_scales(np.full(d, 5.0, np.float32)))
                 if cfg.quantize == "int8" else None)
    if cfg.quantize == "int8":
        # same exact-int32 accumulation guard as every host int8 path
        _check_int8_chunk_rows(rows, _INT8_SUM_ROW_LIMIT)

    def run(key, centroids, n_iters):
        gen = _make_chunk_gen(key, rows, d, cfg.dtype)

        def epoch(i, st):
            c, _ = st
            c2 = (c.astype(jnp.float32) ** 2).sum(-1)

            def chunk_body(acc, j):
                if cfg.quantize == "int8":
                    q = _clip_round_int8(gen(j), col_scale[None, :], xp=jnp)
                    s, cnt, it = _partials_block_int8(q, col_scale, c, c2)
                else:
                    s, cnt, it = _partials_block(gen(j), c, c2)
                return (acc[0] + s, acc[1] + cnt, acc[2] + it), None

            acc0 = (jnp.zeros((cfg.k, d), jnp.float32),
                    jnp.zeros((cfg.k,), jnp.float32), jnp.float32(0.0))
            (sums, counts, inertia), _ = lax.scan(
                chunk_body, acc0, jnp.arange(n_chunks))
            sums, counts, inertia = C.allreduce((sums, counts, inertia))
            return _normalize_centroids(sums, counts, c), inertia

        return lax.fori_loop(0, n_iters, epoch, (centroids, jnp.float32(0.0)))

    return jax.jit(mesh.shard_map(
        run, in_specs=(mesh.spec(0), P(), P()), out_specs=(P(), P())))


def make_gen_only_fn(mesh: WorkerMesh, cfg: StreamConfig, d: int,
                     n_chunks: int):
    """Calibration twin of :func:`make_synthetic_run_fn`: the same
    fori_loop × scan × PRNG generation, but the per-chunk work is a
    trivial running sum instead of the Lloyd partials — timing it
    isolates the data-regeneration overhead that a real ingest pipeline
    would not pay (its data arrives from disk/HBM, not a PRNG)."""
    rows = cfg.chunk_points // mesh.num_workers

    def run(key, n_iters):
        gen = _make_chunk_gen(key, rows, d, cfg.dtype)

        def epoch(i, acc):
            def chunk_body(a, j):
                # touch every generated value so XLA can't elide the RNG
                return a + gen(j).astype(jnp.float32).sum(), None

            acc, _ = lax.scan(chunk_body, acc, jnp.arange(n_chunks))
            return acc

        return C.allreduce(lax.fori_loop(0, n_iters, epoch,
                                         jnp.float32(0.0)))

    return jax.jit(mesh.shard_map(
        run, in_specs=(mesh.spec(0), P()), out_specs=P()))


def benchmark_streaming(n=100_000_000, d=300, k=1000, iters=3,
                        chunk_points=262_144, mesh=None, seed=0,
                        dtype=jnp.float32, warmup=1, calibrate_gen=False,
                        quantize=None):
    """iter/s of the blocked-epoch formulation at north-star scale.

    The dataset is device-regenerated (see :func:`make_synthetic_run_fn`)
    so ``n`` is bounded by FLOPs, not HBM or host RAM: n=1_000_000_000
    with k=1000 runs in ~1.4 GB of live HBM per chip.  Warmup reuses the
    SAME compiled program (n_iters is a traced scalar) per the relay
    recompile trap.

    ``calibrate_gen`` (opt-in: a second full-scale compile + timed run):
    also time a generation-only twin of the program and report
    ``gen_sec_per_iter`` + ``iters_per_sec_ex_gen`` — the RNG
    regeneration is measurement scaffolding a real ingest pipeline would
    not pay.  The raw rate stays the headline; the ex-gen rate is an
    UPPER estimate of the compute rate (in the fused real program the
    RNG partially overlaps the Lloyd matmuls, so standalone gen time can
    over-subtract), and when the calibration is not credible (gen time
    ≥ 90% of the total — overlap/relay noise) ``iters_per_sec_ex_gen``
    is reported as None rather than an inflated number.
    """
    mesh = mesh or current_mesh()
    nw = mesh.num_workers
    # chunk never exceeds n: a small-n request must not silently measure a
    # 262144-point epoch (the dict reports the points actually processed)
    cfg = StreamConfig(k=k,
                       chunk_points=-(-min(chunk_points, n) // nw) * nw,
                       dtype=dtype, quantize=quantize)
    n_chunks = max(1, n // cfg.chunk_points)
    n_eff = n_chunks * cfg.chunk_points  # actual points per epoch
    run_fn = make_synthetic_run_fn(mesh, cfg, d, n_chunks)

    keys = jax.device_put(
        jax.random.split(jnp.asarray(prng.key_bits(seed)), nw),
        mesh.sharding(mesh.spec(0)))
    centroids = jax.device_put(
        jax.random.normal(jnp.asarray(prng.key_bits(seed + 1)), (k, d),
                          dtype=dtype),
        mesh.replicated())
    _, w_in = run_fn(keys, centroids, jnp.int32(max(warmup, 1)))
    device_sync(w_in)
    t0 = time.perf_counter()
    c_new, inertia = run_fn(keys, centroids, jnp.int32(iters))
    inertia_val = device_sync(inertia)
    dt = time.perf_counter() - t0
    out = {
        "iters_per_sec": iters / dt,
        "points_per_sec": n_eff * iters / dt,
        "sec_per_iter": dt / iters,
        "inertia": inertia_val,
        "n": n_eff, "d": d, "k": k, "chunk_points": cfg.chunk_points,
        "n_chunks": n_chunks, "num_workers": nw,
        "dtype": str(jnp.dtype(dtype).name), "quantize": quantize,
    }
    if calibrate_gen:
        gen_fn = make_gen_only_fn(mesh, cfg, d, n_chunks)
        device_sync(gen_fn(keys, jnp.int32(max(warmup, 1))))
        t0 = time.perf_counter()
        device_sync(gen_fn(keys, jnp.int32(iters)))
        gen_dt = time.perf_counter() - t0
        out.update(_ex_gen_fields(dt, gen_dt, iters))
    return out


def _ex_gen_fields(dt: float, gen_dt: float, iters: int) -> dict:
    """Calibration post-processing, factored for direct testing: a gen
    time that eats (nearly) the whole run means the subtraction is noise
    or overlap, and an "ex-gen" rate computed from it would be absurd —
    report None instead of a number that could land in BASELINE.md."""
    fields = {"gen_sec_per_iter": gen_dt / iters}
    if gen_dt >= 0.9 * dt:
        fields["iters_per_sec_ex_gen"] = None
        fields["gen_calibration"] = ("invalid: gen time >= 90% of total "
                                     "(RNG overlaps compute, or timing noise)")
    else:
        fields["iters_per_sec_ex_gen"] = iters / (dt - gen_dt)
    return fields


def benchmark_ingest(points, k=1000, iters=2, chunk_points=262_144,
                     mesh=None, dtype=jnp.float32, quantize=None, seed=0,
                     disk_bytes=None, compare_synthetic=False,
                     wire_dtype="auto", prefetch=2):
    """End-to-end rate of :func:`fit_streaming` on a REAL disk source —
    the honest half of the 1B-point story (SURVEY.md §1 north-star, §4.2
    "load points shard" phase).  :func:`benchmark_streaming` measures the
    compute *formulation* with device-regenerated data; this measures the
    ingest-bound *reality*: disk read + host parse/pad + H2D transfer,
    with device compute double-buffered behind it.

    ``points`` is any ``fit_streaming`` source (``np.memmap``,
    ``CSVPoints``, ndarray).  ``disk_bytes``: actual on-disk bytes per
    epoch (file size) — defaults to ``n*d*itemsize`` when the source
    exposes a dtype, else the f32 logical size; float16/int8 sources and
    text files should pass the real file size so GB/s is honest.

    Reported fields:

    - ``points_per_sec`` — end-to-end, total points × epochs / wall
      (includes centroid init and compile; the per-epoch fields exclude
      them).
    - ``host_sec_per_epoch`` / ``host_gb_per_sec`` — time blocked in the
      host half (read+parse+pad+dispatch) and the disk-byte rate over it.
      This is the pipeline's hard floor: device speed cannot fix it.
    - ``sync_sec_per_epoch`` — device tail NOT hidden behind host work
      (blocking wait after the last chunk).
    - ``overlap_efficiency`` — the HOST PIPELINE's stage-overlap score
      (:class:`harp_tpu.ingest.IngestStats`, PR 8) ∈ [0, 1]:
      consumer_s / (consumer_s + wait_s) — of the dispatch loop's time,
      the fraction spent computing rather than waiting on the pipeline;
      1.0 also when nothing needed hiding (an idle consumer or a serial
      run — no stalls is a clean score).
    - ``device_hidden_fraction`` — the pre-PR-8 "overlap_efficiency":
      host_s / (host_s + sync_s) ∈ (0, 1] — 1.0 means device compute is
      fully hidden behind ingest (purely ingest-bound); lower means the
      device is the straggler.  Renamed because the pipeline makes the
      host side fast, which legitimately LOWERS this ratio.
    - ``ingest_bound_fraction`` — host_s / epoch_s: the share of epoch
      wall spent in the host half (the remainder is dispatch overhead +
      the unhidden device tail).
    - with ``compare_synthetic=True``: ``synthetic_sec_per_epoch`` — the
      device-regenerated formulation at the SAME shapes/chunking (a
      second compile + timed run); ``epoch_s`` ≈ max(host, synthetic)
      when the double buffer overlaps perfectly.
    """
    mesh = mesh or current_mesh()
    n, d = points.shape
    np_dtype = np.dtype(jnp.dtype(dtype).name)
    wire_np = _resolve_wire_dtype(wire_dtype, np_dtype,
                                  getattr(points, "dtype", None))
    inst: dict = {}
    t0 = time.perf_counter()
    _, inertia = fit_streaming(points, k=k, iters=iters,
                               chunk_points=chunk_points, mesh=mesh,
                               seed=seed, dtype=dtype, quantize=quantize,
                               instrument=inst, wire_dtype=wire_dtype,
                               prefetch=prefetch)
    wall = time.perf_counter() - t0
    eps = inst["epochs"]
    host = sum(e["host_s"] for e in eps) / len(eps)
    sync = sum(e["sync_s"] for e in eps) / len(eps)
    epoch = sum(e["epoch_s"] for e in eps) / len(eps)
    if disk_bytes is None:
        itemsize = getattr(getattr(points, "dtype", None), "itemsize", 4)
        disk_bytes = n * d * itemsize
    out = {
        "points_per_sec": n * iters / wall,
        "epoch_sec": epoch,
        "host_sec_per_epoch": host,
        "host_gb_per_sec": disk_bytes / 1e9 / host if host else None,
        "sync_sec_per_epoch": sync,
        "overlap_efficiency": (eps[-1]["pipeline"]["overlap_efficiency"]
                               if eps[-1].get("pipeline") else None),
        "device_hidden_fraction": (host / (host + sync)
                                   if host + sync else None),
        "ingest_bound_fraction": host / epoch if epoch else None,
        "disk_gb_per_epoch": disk_bytes / 1e9,
        "inertia": float(inertia),
        "n": n, "d": d, "k": k, "iters": iters,
        "chunk_points": chunk_points, "quantize": quantize,
        # the H2D payload format + bytes actually crossing the link per
        # epoch ("int8" when quantized): the wire, not the disk, is the
        # relay/PCIe-bound half of the pipeline
        "wire_dtype": "int8" if quantize == "int8" else wire_np.name,
        "wire_gb_per_epoch": n * d * (1 if quantize == "int8"
                                      else wire_np.itemsize) / 1e9,
        "num_workers": mesh.num_workers,
        "source": type(points).__name__,
        # PR 8: rows are typed ingest evidence (check_jsonl invariant 8)
        # and carry the host-pipeline account (harp_tpu.ingest): depth 0
        # is the pre-pipeline serial chain, >=2 the prefetch pipeline
        "kind": "ingest",
        "prefetch_depth": prefetch,
        "pipeline": eps[-1].get("pipeline"),
    }
    if compare_synthetic:
        syn = benchmark_streaming(n=n, d=d, k=k, iters=iters,
                                  chunk_points=chunk_points, mesh=mesh,
                                  dtype=dtype, seed=seed)
        out["synthetic_sec_per_epoch"] = syn["sec_per_iter"]
        out["synthetic_points_per_sec"] = syn["points_per_sec"]
    return out


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser(
        description="harp-tpu streaming KMeans (north-star 1B-point path)")
    p.add_argument("--n", type=int, default=100_000_000)
    p.add_argument("--d", type=int, default=300)
    p.add_argument("--k", type=int, default=1000)
    p.add_argument("--iters", type=int, default=3)
    p.add_argument("--chunk", type=int, default=262_144)
    p.add_argument("--dtype", default="float32", choices=["float32", "bfloat16"])
    p.add_argument("--input", default=None, metavar="NPY_PARQUET_CSV_OR_GLOB",
                   help="stream a .npy file (np.memmap), a CSV/text file "
                        "(native prefetch-threaded reader, bounded "
                        "memory), or a glob/directory of split files — "
                        "dealt to workers size-balanced, each streaming "
                        "only its own (the HDFS-split input shape) — "
                        "instead of the device-synthetic benchmark")
    p.add_argument("--quantize", choices=["int8"], default=None)
    p.add_argument("--wire-dtype", default="auto",
                   choices=["auto", "none", "float16", "bfloat16",
                            "float32"],
                   help="H2D payload format for --input streaming: auto "
                        "ships narrow-float sources as-is (f16 disk → "
                        "half the transfer bytes, bit-identical); "
                        "none = legacy ship-compute-dtype; an explicit "
                        "dtype forces the wire (narrower than the "
                        "source is lossy, opt-in)")
    p.add_argument("--init", choices=["random", "kmeans++"], default="random")
    p.add_argument("--prefetch", type=int, default=2,
                   help="ingest pipeline work-ahead depth for --input "
                        "streaming (harp_tpu.ingest): >=2 overlaps "
                        "read/quantize/ship, 1 = staged serial, 0 = the "
                        "pre-pipeline legacy loop (A/B incumbent)")
    p.add_argument("--ckpt-dir", default=None,
                   help="checkpoint/resume for long runs (rerunning with "
                        "the same dir resumes from the latest epoch)")
    p.add_argument("--ckpt-every", type=int, default=5)
    p.add_argument("--elastic", action="store_true",
                   help="elastic Lloyd (PR 15): consume mid-run "
                        "skew_trigger findings between sweeps (rebalance "
                        "point packs; masked pads keep the math exact) "
                        "and checkpoint mesh-independent centroids")
    p.add_argument("--max-worker-loss", type=int, default=0,
                   help="elastic: survive up to N permanent worker "
                        "losses by shrinking to the survivors and "
                        "replaying the repartition plan from the last "
                        "checkpoint (implies --elastic; needs --ckpt-dir "
                        "to actually resume)")
    args = p.parse_args(argv)
    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    wire = {"auto": "auto", "none": None}.get(args.wire_dtype,
                                              args.wire_dtype)

    if args.elastic or args.max_worker_loss:
        # elastic mode materializes the corpus (the repartition relabels
        # rows), so it pairs with host-sized --n, not the 1B-point path
        from harp_tpu.elastic.apps import kmeans_stream_elastic_fit
        from harp_tpu.utils.metrics import benchmark_json

        if args.input:
            raise SystemExit(
                "--elastic currently pairs with the synthetic corpus; "
                "use --n/--d (file inputs ride the non-elastic "
                "streaming fit)")
        rng = np.random.default_rng(0)
        pts = rng.normal(size=(args.n, args.d)).astype(np.float32)
        ad = kmeans_stream_elastic_fit(
            pts, k=args.k, iters=args.iters, ckpt_dir=args.ckpt_dir,
            ckpt_every=args.ckpt_every,
            max_worker_loss=max(args.max_worker_loss, 0))
        print(benchmark_json("kmeans_stream_elastic_cli", {
            "k": args.k, "iters": args.iters, "n": args.n, "d": args.d,
            "inertia": ad.metric(), "n_workers": ad.mesh.num_workers,
            "worker_losses": ad.losses, "ckpt_dir": args.ckpt_dir}))
        return

    if args.input:
        from harp_tpu.fileformat import list_files

        # a literal path wins over glob expansion: 'data[v2].npy' is a
        # real file, not a character class
        paths = ([args.input] if os.path.isfile(args.input)
                 else list_files(args.input))
        if not paths:
            raise SystemExit(f"{args.input}: no input files matched")
        if len(paths) > 1:  # split directory: per-worker file streams
            split_info: dict = {}
            c, inertia = fit_streaming_files(
                paths, args.k, args.iters, args.chunk, dtype=dtype,
                quantize=args.quantize, init=args.init,
                ckpt_dir=args.ckpt_dir, ckpt_every=args.ckpt_every,
                info=split_info, wire_dtype=wire, prefetch=args.prefetch)
            n_rows, d_cols = split_info["n_total"], split_info["d"]
        else:
            if paths[0].endswith(".npy"):
                pts = np.load(paths[0], mmap_mode="r")
            elif paths[0].endswith((".parquet", ".pq")):
                from harp_tpu.native.datasource import ParquetPoints

                pts = ParquetPoints(paths[0], chunk_rows=args.chunk)
            else:  # text: native streaming reader, never materialized
                from harp_tpu.native.datasource import CSVPoints

                pts = CSVPoints(paths[0], chunk_rows=args.chunk)
            c, inertia = fit_streaming(pts, args.k, args.iters, args.chunk,
                                       dtype=dtype, quantize=args.quantize,
                                       init=args.init,
                                       ckpt_dir=args.ckpt_dir,
                                       ckpt_every=args.ckpt_every,
                                       wire_dtype=wire,
                                       prefetch=args.prefetch)
            n_rows, d_cols = int(pts.shape[0]), int(pts.shape[1])
        # JSON, not dict repr: measure_on_relay.sh tees this into a .jsonl
        from harp_tpu.utils.metrics import benchmark_json

        print(benchmark_json("kmeans_stream_fit_cli",
                             {"k": args.k, "iters": args.iters,
                              "n": n_rows, "d": d_cols,
                              "files": len(paths),
                              "inertia": float(inertia)}))
    else:
        from harp_tpu.utils.metrics import benchmark_json

        print(benchmark_json("kmeans_stream_cli", benchmark_streaming(
            args.n, args.d, args.k, args.iters, args.chunk, dtype=dtype,
            quantize=args.quantize)))


if __name__ == "__main__":
    main()
