"""Random Forest — graded config #5b: data-parallel ensemble, allgather.

Reference parity (SURVEY.md §3.4): Harp's ``edu.iu.rf`` trains decision
trees on bootstrap samples of each worker's local shard (javaml/weka-style
sequential tree induction), then ``allgather``s the trees so every worker
holds the full forest; prediction is majority vote.

TPU-native design: tree induction is re-formulated as **vectorized
histogram-based level-wise growth** (the XGBoost/LightGBM layout, which is
also how a systolic machine wants it):

- features are quantile-binned once (static [n, f] uint8 bin ids);
- a whole *level* of every tree grows at once: per (tree, node, feature,
  bin, class) label histograms via one-hot matmuls on the MXU, Gini
  impurity from cumulative histogram sums, best (feature, threshold)
  per node by argmin;
- all trees of a worker grow in lockstep via ``vmap`` over the tree axis
  (bootstrap sampling = per-tree example-weight vectors, so "sampling"
  is a weighted histogram, not a gather);
- the forest "allgather" is the same verb apps always use; prediction
  routes every sample down all trees with gather-free arithmetic on the
  dense node arrays.

The per-worker forest shards stay local until ``allgather_forest`` — the
same lifecycle as Harp's local tree lists.
"""

from __future__ import annotations

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from harp_tpu.ingest import IngestPipeline
from harp_tpu.parallel import collective as C
from harp_tpu.parallel.mesh import WorkerMesh, current_mesh
from harp_tpu.utils import flightrec, telemetry


@dataclasses.dataclass
class RFConfig:
    n_trees: int = 32          # total across workers (Harp: trees per worker × N)
    max_depth: int = 6
    n_bins: int = 32
    n_classes: int = 2
    feature_fraction: float = 1.0  # per-(tree,node) feature subsampling
    # "dense" = one-hot int8 MXU matmul histogram (the default since
    # 2026-07-30 — XLA scatter of small rows runs ~25 GB/s on v5e, see
    # CLAUDE.md); "scatter" = the scatter-add arm kept for the A/B
    # (bit-identical int32 counts, tests/test_rf.py).  PR 16 flip
    # candidate pair: rf_dense_hist vs rf_scatter_hist.  "pallas"
    # (PR 17) = the same dense math as a real kernel with on-chip bin
    # accumulation (ops/rf_kernel.py) — the per-level [n, node·C]
    # one-hot never round-trips HBM; counts stay BIT-identical to
    # "dense".  perfmodel.presize picked a 2048-sample tile at the
    # graded 200k×64 shape (2026-08-06, predicted only — NOT yet
    # measured; flip candidate rf_hist_pallas).  Falls back to "dense"
    # when f·n_bins is not a 128 multiple.
    hist_algo: str = "dense"
    seed: int = 0

    def __post_init__(self):
        if self.hist_algo not in ("dense", "scatter", "pallas"):
            raise ValueError(
                f"hist_algo must be 'dense', 'scatter' or 'pallas', got "
                f"{self.hist_algo!r}")


def quantile_bins(x, n_bins):
    """Per-feature quantile bin edges [f, n_bins-1] from a sample."""
    qs = np.linspace(0, 1, n_bins + 1)[1:-1]
    return np.quantile(np.asarray(x), qs, axis=0).T.astype(np.float32)


def binize(x, edges):
    """x [n, f] → bin ids [n, f] int32 via the precomputed edges.

    Per-feature searchsorted keeps the transient at [n] (a broadcast
    comparison would materialize [n, f, n_bins-1] — hundreds of MB at
    benchmark scale).
    """
    x = np.asarray(x)
    out = np.empty(x.shape, np.int32)
    for j in range(x.shape[1]):
        out[:, j] = np.searchsorted(edges[j], x[:, j], side="left")
    return out


def binize_chunked(x, edges, chunk_rows=65_536, prefetch=2):
    """:func:`binize` through the shared ingest pipeline (PR 8):
    bit-identical output (per-row searchsorted is row-independent) with
    the work chunked — the read stage hands zero-copy row views and,
    with ``prefetch >= 2``, chunk j+1 bins on a worker thread while
    chunk j's result writes back.  Each chunk's output slice is
    disjoint, so the side-effecting prep stage is thread-safe by
    construction."""
    x = np.asarray(x)
    n = x.shape[0]
    out = np.empty(x.shape, np.int32)
    n_chunks = max(1, -(-n // chunk_rows))

    def read(j):
        lo = j * chunk_rows
        return lo, x[lo:lo + chunk_rows]

    def prep(t):
        lo, blk = t
        out[lo:lo + blk.shape[0]] = binize(blk, edges)

    with IngestPipeline(read, prep, None, depth=max(1, prefetch),
                        tag="rf.binize") as pipe:
        for _ in pipe.stream(n_chunks):
            pass
    return out


def bins_onehot(bins, n_bins):
    """Precompute the flattened bin one-hot BO int8 [n, f*B] — shared by
    every tree and level (bins never change during a fit), so the big
    one-hot is built ONCE instead of per (tree, level, feature).
    Built per feature column to avoid a [n, f, f*B] transient."""
    n, f = bins.shape

    def one_col(bins_f):
        return jax.nn.one_hot(bins_f, n_bins, dtype=jnp.int8)  # [n, B]

    cols = lax.map(one_col, bins.T)                 # [f, n, B]
    return jnp.moveaxis(cols, 0, 1).reshape(n, f * n_bins)


def _grow_level(BO, bins, y, weights, node_id, level, feat_mask, cfg):
    """Grow one level of one tree: returns (split_feat, split_bin,
    new_node_id) for the 2^level nodes of this level.

    BO: [n, f*B] int8 precomputed bin one-hots (see :func:`bins_onehot`);
    y: [n] int32 labels; weights: [n] bootstrap weights (small ints);
    node_id: [n] current node of each sample (within this level's frame);
    feat_mask: [f] 0/1 feature subsample for this tree.

    The full histogram[node, f, bin, class] is ONE int8 matmul: the lhs
    one-hot folds (node, class, weight) into a single [n, nodeC] int8
    matrix (Poisson(1) weights are tiny ints, exact in int8; counts
    accumulate in int32, exact — asserted against a numpy scatter-add
    histogram in tests/test_rf.py).  Compared to the previous per-feature
    f32 outer-product formulation this removes the [n, B*C] transient per
    (tree, level, feature), the fit's dominant HBM traffic by op-level
    accounting (~205 GB/fit at the graded 200k×64 32-tree config vs ~9 GB
    of BO reads).  TPU wall-clock pending: the relay was hung when this
    landed (2026-07-30, see CLAUDE.md gotchas; prior formulation measured
    7.07 trees/s on 2026-07-29, 1× v5e) — measure and record in BASELINE.md
    at next relay availability.
    """
    n = BO.shape[0]
    C_ = cfg.n_classes
    B = cfg.n_bins
    f = BO.shape[1] // B
    n_nodes = 2 ** level

    if cfg.hist_algo == "scatter":
        # the 25 GB/s-wall arm (A/B partner of the dense default): one
        # scatter-add of weight w at [node*C + y, feat*B + bin] per
        # (sample, feature) — bit-identical int32 counts by construction
        w = jnp.clip(weights, 0, 127).astype(jnp.int32)
        rows = node_id * C_ + y                          # [n]
        cols = jnp.arange(f, dtype=jnp.int32)[None, :] * B + bins  # [n, f]
        hist = jnp.zeros((n_nodes * C_, f * B), jnp.int32).at[
            jnp.broadcast_to(rows[:, None], cols.shape), cols].add(
            jnp.broadcast_to(w[:, None], cols.shape))
    elif cfg.hist_algo == "pallas" and (f * B) % 128 == 0:
        # the dense arm as a real kernel (ops/rf_kernel.py): same int8
        # MXU products accumulated in int32 on-chip — bit-identical
        # counts, so the Gini/split/route below sees the same numbers.
        # The kernel runs under the tree vmap (batching adds a leading
        # grid dimension); odd f·B shapes fall through to dense.
        from harp_tpu.ops import rf_kernel
        from harp_tpu.ops.pallas_compat import interpret_default

        hist = rf_kernel.hist_bins(
            BO, node_id * C_ + y, jnp.clip(weights, 0, 127).astype(jnp.int32),
            n_nodes * C_, interpret=interpret_default())
    else:
        nc = jax.nn.one_hot(node_id * C_ + y, n_nodes * C_, dtype=jnp.int8)
        nc = nc * jnp.clip(weights, 0, 127).astype(jnp.int8)[:, None]
        hist = lax.dot_general(
            nc, BO, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )                                           # [node*C, f*B]
    hist = hist.reshape(n_nodes, C_, f, B).transpose(0, 2, 3, 1)
    hist = hist.astype(jnp.float32)                 # [n_nodes, f, B, C]

    # left counts for threshold "≤ bin b" = cumsum over bins (exclusive of
    # nothing: splitting at b sends bins ≤ b left)
    left = jnp.cumsum(hist, axis=2)              # [node, f, B, C]
    total = left[:, :, -1:, :]                   # [node, f, 1, C]
    right = total - left

    def gini_side(cnt):  # [.., C] → impurity * size
        sz = cnt.sum(-1)
        p = cnt / jnp.maximum(sz[..., None], 1e-9)
        return sz * (1.0 - (p * p).sum(-1))

    score = gini_side(left) + gini_side(right)   # [node, f, B]
    # forbid: last bin (empty right), masked-out features
    score = score.at[:, :, -1].set(jnp.inf)
    score = jnp.where(feat_mask[None, :, None] > 0, score, jnp.inf)

    flat = score.reshape(n_nodes, f * B)
    best = jnp.argmin(flat, axis=1)
    split_feat = (best // B).astype(jnp.int32)           # [node]
    split_bin = (best % B).astype(jnp.int32)             # [node]

    # route samples: go right if bin > split_bin of their node
    sf = split_feat[node_id]                              # [n]
    sb = split_bin[node_id]
    sample_bin = jnp.take_along_axis(bins, sf[:, None], axis=1)[:, 0]
    go_right = (sample_bin > sb).astype(jnp.int32)
    new_node_id = node_id * 2 + go_right
    return split_feat, split_bin, new_node_id


def _leaf_stats(y_onehot, weights, node_id, n_leaves):
    node_oh = jax.nn.one_hot(node_id, n_leaves, dtype=jnp.float32) * weights[:, None]
    hist = node_oh.T @ y_onehot            # [leaves, C]
    return jnp.argmax(hist, axis=1).astype(jnp.int32)


def make_train_fn(mesh: WorkerMesh, cfg: RFConfig, n_features: int):
    """Compile per-worker forest training (trees_per_worker via vmap)."""

    def train_one_tree(BO, bins, y, y_onehot, key):
        k1, k2 = jax.random.split(key)
        n = bins.shape[0]
        # bootstrap: Poisson(1) weights ≈ sampling with replacement
        weights = jax.random.poisson(k1, 1.0, (n,)).astype(jnp.float32)
        feat_mask = (
            jax.random.uniform(k2, (n_features,)) < cfg.feature_fraction
        ).astype(jnp.float32)
        # never mask every feature out
        feat_mask = jnp.where(feat_mask.sum() > 0, feat_mask,
                              jnp.ones_like(feat_mask))

        node_id = jnp.zeros((n,), jnp.int32)
        feats, bins_out = [], []
        for level in range(cfg.max_depth):
            sf, sb, node_id = _grow_level(
                BO, bins, y, weights, node_id, level, feat_mask, cfg
            )
            feats.append(sf)
            bins_out.append(sb)
        leaves = _leaf_stats(y_onehot, weights, node_id, 2 ** cfg.max_depth)
        # pack level arrays into flat [2^depth - 1] heap order
        return (
            jnp.concatenate(feats),      # node k at offset 2^l - 1 + k
            jnp.concatenate(bins_out),
            leaves,
        )

    def train_shard(bins, y, keys):
        y_onehot = jax.nn.one_hot(y, cfg.n_classes, dtype=jnp.float32)
        BO = bins_onehot(bins, cfg.n_bins)  # shared by all trees/levels
        return jax.vmap(
            lambda k: train_one_tree(BO, bins, y, y_onehot, k))(keys)

    def prog(bins, y, keys):
        feats, thresh, leaves = train_shard(bins, y, keys[0])
        # Harp step: allgather local trees → full forest everywhere
        return C.allgather((feats, thresh, leaves))

    return jax.jit(
        mesh.shard_map(
            prog,
            in_specs=(mesh.spec(0), mesh.spec(0), mesh.spec(0)),
            out_specs=P(),
        )
    )


def predict_forest(forest, bins, max_depth, n_classes):
    """Majority vote over all trees. bins: [n, f] int32 (same binning)."""
    feats, thresh, leaves = forest  # [T, 2^d - 1], [T, 2^d - 1], [T, 2^d]

    def one_tree(tf, tb, tl):
        n = bins.shape[0]
        node = jnp.zeros((n,), jnp.int32)  # level-frame index
        offset = 0
        for level in range(max_depth):
            heap = offset + node
            sf = tf[heap]
            sb = tb[heap]
            sample_bin = jnp.take_along_axis(bins, sf[:, None], axis=1)[:, 0]
            node = node * 2 + (sample_bin > sb).astype(jnp.int32)
            offset += 2 ** level
        return tl[node]  # [n]

    votes = jax.vmap(one_tree)(feats, thresh, leaves)  # [T, n]
    votes_oh = jax.nn.one_hot(votes, n_classes, dtype=jnp.float32)
    return jnp.argmax(votes_oh.sum(0), axis=-1)


class RandomForest:
    """Host driver (the mapCollective residue for edu.iu.rf)."""

    def __init__(self, cfg: RFConfig | None = None, mesh: WorkerMesh | None = None):
        self.mesh = mesh or current_mesh()
        self.cfg = cfg or RFConfig()
        nw = self.mesh.num_workers
        if self.cfg.n_trees % nw:
            raise ValueError(
                f"n_trees={self.cfg.n_trees} must be divisible by {nw} workers")
        self.trees_per_worker = self.cfg.n_trees // nw
        self.forest = None
        self.edges = None
        self._predict_fn = None
        self._train_fn = None

    def fit(self, x, y):
        cfg = self.cfg
        nw = self.mesh.num_workers
        x, y = np.asarray(x, np.float32), np.asarray(y, np.int32)
        if y.max() >= cfg.n_classes or y.min() < 0:
            raise ValueError(
                f"labels must be in [0, {cfg.n_classes}); got range "
                f"[{y.min()}, {y.max()}] — set RFConfig(n_classes=...)")
        n = (x.shape[0] // nw) * nw
        x, y = x[:n], y[:n]
        from harp_tpu.utils import skew, telemetry

        if telemetry.enabled():
            # ingest skew record (utils/skew.py): rows shard evenly by
            # construction (the truncation above), so this pins the
            # balanced baseline the report compares other phases against
            skew.record_partition("rf.partition", np.full(nw, n // nw),
                                  unit="rows", padded_total=n)
        self.edges = quantile_bins(x, cfg.n_bins)
        if self._train_fn is None:
            self._train_fn = make_train_fn(self.mesh, cfg, x.shape[1])
        train = self._train_fn
        from harp_tpu.utils import prng

        keys = np.asarray(
            jax.random.split(jnp.asarray(prng.key_bits(cfg.seed)),
                             nw * self.trees_per_worker)
        ).reshape(nw, self.trees_per_worker, 2)
        # binize + ship through the shared ingest pipeline (PR 8), under
        # the standard warn-mode flight budget: exactly the bins/labels/
        # keys bytes cross the wire and the host half compiles nothing
        with telemetry.budget(compiles=0,
                              h2d_bytes=(x.size * 4 + y.nbytes
                                         + keys.nbytes),
                              action="warn", tag="rf.ingest"):
            bins = binize_chunked(x, self.edges)
            bins_dev = self.mesh.shard_array(bins, 0)
            y_dev = self.mesh.shard_array(y, 0)
            keys_dev = self.mesh.shard_array(keys, 0)
        self.forest = jax.tree.map(np.asarray, train(
            bins_dev, y_dev, keys_dev))
        return self

    def predict(self, x):
        if self.forest is None:
            raise RuntimeError("call fit() before predict()")
        if self._predict_fn is None:
            self._predict_fn = flightrec.track(jax.jit(
                lambda forest, bins: predict_forest(
                    forest, bins, self.cfg.max_depth, self.cfg.n_classes)
            ), "rf.predict")
        # device_put, not jnp.asarray: host bins ride the counted H2D
        # path instead of risking a compile-time literal (HL003)
        bins = jax.device_put(binize(np.asarray(x, np.float32), self.edges))
        return np.asarray(self._predict_fn(
            jax.tree.map(jnp.asarray, self.forest), bins))

    def accuracy(self, x, y):
        return float((self.predict(x) == np.asarray(y)).mean())


def synthetic_classification(n=100_000, f=64, classes=2, seed=0):
    """Axis-aligned-structure task a depth-6 forest can learn."""
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, f)).astype(np.float32)
    # XOR of two axis-aligned thresholds: exactly representable at depth 2,
    # invisible to any single split (so it actually tests tree growth)
    y = ((x[:, 0] > 0).astype(int) ^ (x[:, 1] > 0.5).astype(int)) % classes
    return x, y.astype(np.int32)


def benchmark(n=200_000, f=64, n_trees=32, max_depth=6, mesh=None, seed=0,
              hist_algo="dense"):
    """Trees/sec + samples/sec (graded config #5b)."""
    mesh = mesh or current_mesh()
    cfg = RFConfig(n_trees=n_trees, max_depth=max_depth, seed=seed,
                   hist_algo=hist_algo)
    x, y = synthetic_classification(n, f, seed=seed)
    model = RandomForest(cfg, mesh)
    model.fit(x, y)  # warmup/compile
    t0 = time.perf_counter()
    model.fit(x, y)
    fit_dt = time.perf_counter() - t0
    t0 = time.perf_counter()
    acc = model.accuracy(x[:20_000], y[:20_000])
    pred_dt = time.perf_counter() - t0
    return {
        "trees_per_sec": n_trees / fit_dt,
        "fit_sec": fit_dt,
        "predict_sec_20k": pred_dt,
        "train_acc": acc,
        "n": n, "features": f, "n_trees": n_trees, "depth": max_depth,
        "num_workers": mesh.num_workers, "hist_algo": hist_algo,
    }


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser(description="harp-tpu random forest (edu.iu.rf parity)")
    p.add_argument("--n", type=int, default=200_000)
    p.add_argument("--features", type=int, default=64)
    p.add_argument("--trees", type=int, default=32)
    p.add_argument("--depth", type=int, default=6)
    p.add_argument("--hist-algo", choices=("dense", "scatter", "pallas"),
                   default="dense",
                   help="histogram formulation (pallas = the on-chip "
                        "one-hot kernel, flip candidate rf_hist_pallas; "
                        "bit-identical counts)")
    args = p.parse_args(argv)
    from harp_tpu.utils.metrics import benchmark_json

    print(benchmark_json("rf_cli", benchmark(
        args.n, args.features, args.trees, args.depth,
        hist_algo=args.hist_algo)))


if __name__ == "__main__":
    main()
