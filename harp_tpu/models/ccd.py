"""CCD++ matrix factorization — coordinate descent with column allreduce.

Reference parity (SURVEY.md §3.4): Harp's ``edu.iu.ccd`` implements CCD++
(Yu et al.): rank coordinates get closed-form updates
``w_uf ← Σ_i R̂_ui h_if / (λ + Σ_i h_if²)`` (symmetrically for H), cycling
through coordinates, with the model exchanged through Harp's collective
machinery.

TPU-native design: users (and their ratings) are range-partitioned so each
worker holds **all** ratings of its users; the item factor matrix H is
replicated (items × rank is small).  One coordinate update is then exact:

- W column: per-user segment-sums over local ratings — no communication
  (user data is complete locally);
- H column: per-item partial (num, den) segment-sums over *global* item
  ids, combined with one ``allreduce`` of two [n_items] vectors — the
  TPU translation of Harp's per-coordinate model exchange, exact and
  cheaper than rotating full slices (O(items) on the wire per coordinate
  instead of O(items × rank)).

Per-rating predictions are maintained incrementally across coordinate
updates (the role of CCD++'s explicit residual array), so each epoch costs
O(nnz · rank) like the reference.  The epoch is one jitted SPMD program.
"""

from __future__ import annotations

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from harp_tpu.parallel import collective as C
from harp_tpu.parallel.mesh import WorkerMesh, current_mesh
from harp_tpu.utils import prng
from harp_tpu.utils.timing import device_sync


@dataclasses.dataclass
class CCDConfig:
    rank: int = 32
    reg: float = 0.1
    sweeps: int = 1  # coordinate cycles per epoch


def _epoch_device_fn(mesh: WorkerMesh, cfg: CCDConfig, n_items: int):
    def epoch(W, H, bu, bi, bv, bm):
        # bu: [B] user ids local to this worker's range; bi: [B] GLOBAL
        # item ids; H replicated [n_items, r].
        u_size = W.shape[0]
        pred = (jnp.take(W, bu, axis=0) * jnp.take(H, bi, axis=0)).sum(-1)

        def coord_body(st, f):
            W, H, pred = st
            wf = jnp.take(W[:, f], bu)          # [B]
            hf = jnp.take(H[:, f], bi)
            rhat = bm * (bv - pred + wf * hf)

            # exact W-column update (all of each user's ratings are local)
            num_u = jax.ops.segment_sum(rhat * hf, bu, num_segments=u_size)
            den_u = jax.ops.segment_sum(bm * hf * hf, bu, num_segments=u_size)
            w_new_col = jnp.where(den_u > 0,
                                  num_u / (cfg.reg + den_u), W[:, f])
            W = W.at[:, f].set(w_new_col)
            wf_new = jnp.take(w_new_col, bu)
            pred = pred + bm * (wf_new - wf) * hf

            # H-column update: partial per-item stats → allreduce (exact)
            rhat = bm * (bv - pred + wf_new * hf)
            num_i = jax.ops.segment_sum(rhat * wf_new, bi, num_segments=n_items)
            den_i = jax.ops.segment_sum(bm * wf_new * wf_new, bi,
                                        num_segments=n_items)
            num_i, den_i = C.allreduce((num_i, den_i))
            h_new_col = jnp.where(den_i > 0,
                                  num_i / (cfg.reg + den_i), H[:, f])
            H = H.at[:, f].set(h_new_col)
            hf_new = jnp.take(h_new_col, bi)
            pred = pred + bm * wf_new * (hf_new - hf)
            return (W, H, pred), None

        coords = jnp.tile(jnp.arange(cfg.rank), cfg.sweeps)
        (W, H, pred), _ = lax.scan(coord_body, (W, H, pred), coords)

        err = bm * (bv - pred)
        se, cnt = C.allreduce(((err * err).sum(), bm.sum()))
        return W, H, se, cnt

    return epoch


_IN_SPECS = lambda mesh: (mesh.spec(0), P(), mesh.spec(0), mesh.spec(0),  # noqa: E731
                          mesh.spec(0), mesh.spec(0))


def make_epoch_fn(mesh: WorkerMesh, cfg: CCDConfig, n_items: int):
    return jax.jit(mesh.shard_map(
        _epoch_device_fn(mesh, cfg, n_items),
        in_specs=_IN_SPECS(mesh),
        out_specs=(mesh.spec(0), P(), P(), P()),
    ))


def make_multi_epoch_fn(mesh: WorkerMesh, cfg: CCDConfig, n_items: int,
                        epochs: int):
    """``epochs`` coordinate-descent epochs as ONE device program — the
    same dispatch amortization as mfsgd/lda (per-call round trips cost
    ~20–150 ms on the relay-attached v5e, 2026-07-30).  Returns per-epoch
    (se[epochs], cnt[epochs])."""
    inner = _epoch_device_fn(mesh, cfg, n_items)

    def many(W, H, bu, bi, bv, bm):
        def body(carry, _):
            W, H = carry
            W, H, se, cnt = inner(W, H, bu, bi, bv, bm)
            return (W, H), (se, cnt)

        (W, H), (ses, cnts) = lax.scan(body, (W, H), None, length=epochs)
        return W, H, ses, cnts

    return jax.jit(mesh.shard_map(
        many,
        in_specs=_IN_SPECS(mesh),
        out_specs=(mesh.spec(0), P(), P(), P()),
    ))


class CCD:
    """Host driver (the mapCollective residue for edu.iu.ccd)."""

    def __init__(self, n_users, n_items, cfg: CCDConfig | None = None,
                 mesh: WorkerMesh | None = None, seed=0):
        self.mesh = mesh or current_mesh()
        self.cfg = cfg or CCDConfig()
        self.n_users, self.n_items = n_users, n_items
        n = self.mesh.num_workers
        self.u_bound = -(-n_users // n)
        # raw key bits (utils.prng): a fresh seed must not cost a fresh
        # (remote) compile -- CLAUDE.md PRNGKey-specialization trap
        k1, k2 = jax.random.split(jnp.asarray(prng.key_bits(seed)))
        s = 1.0 / np.sqrt(self.cfg.rank)
        self.W = self.mesh.shard_array(np.asarray(
            jax.random.uniform(k1, (self.u_bound * n, self.cfg.rank),
                               jnp.float32, 0, s)), 0)
        self.H = jax.device_put(
            jax.random.uniform(k2, (n_items, self.cfg.rank), jnp.float32, 0, s),
            self.mesh.replicated())
        self._epoch_fn = make_epoch_fn(self.mesh, self.cfg, n_items)
        self._multi_fns: dict = {}
        self._blocks = None

    def set_ratings(self, users, items, vals):
        """Partition by user range; items stay global (H is replicated)."""
        n = self.mesh.num_workers
        users = np.asarray(users); items = np.asarray(items)
        vals = np.asarray(vals, np.float32)
        wid = users // self.u_bound
        order = np.argsort(wid, kind="stable")
        su, si, sv, sw = users[order], items[order], vals[order], wid[order]
        counts = np.bincount(sw, minlength=n)
        B = int(counts.max())
        bu = np.zeros((n, B), np.int32)
        bi = np.zeros((n, B), np.int32)
        bv = np.zeros((n, B), np.float32)
        bm = np.zeros((n, B), np.float32)
        starts = np.zeros(n, np.int64)
        starts[1:] = counts.cumsum()[:-1]
        for w in range(n):
            c = counts[w]
            sl = slice(starts[w], starts[w] + c)
            bu[w, :c] = su[sl] - w * self.u_bound
            bi[w, :c] = si[sl]
            bv[w, :c] = sv[sl]
            bm[w, :c] = 1.0
        self._blocks = tuple(self.mesh.shard_array(a.reshape(n * B) if a.ndim == 2 else a, 0)
                             for a in (bu, bi, bv, bm))
        self._multi_fns.clear()  # compiled executables bind to block shapes

    def train_epoch(self):
        if self._blocks is None:
            raise RuntimeError("call set_ratings() before train_epoch()")
        self.W, self.H, se, cnt = self._epoch_fn(self.W, self.H, *self._blocks)
        return float(np.sqrt(max(device_sync(se), 0.0) /
                             max(device_sync(cnt), 1.0)))

    def compile_epochs(self, epochs: int):
        """AOT-compile the ``epochs``-epoch program WITHOUT training (same
        contract as the mfsgd/lda drivers: benchmark warmup must not
        secretly run extra epochs)."""
        if self._blocks is None:
            raise RuntimeError("call set_ratings() before compile_epochs()")
        fn = self._multi_fns.get(epochs)
        if fn is None:
            jitted = make_multi_epoch_fn(
                self.mesh, self.cfg, self.n_items, epochs)
            fn = self._multi_fns[epochs] = jitted.lower(
                self.W, self.H, *self._blocks).compile()
        return fn

    def train_epochs(self, epochs: int):
        """Run ``epochs`` epochs as one device program; per-epoch RMSEs."""
        fn = self.compile_epochs(epochs)
        self.W, self.H, ses, cnts = fn(self.W, self.H, *self._blocks)
        stats = np.asarray(jnp.stack([ses, cnts]))  # one readback
        return [float(np.sqrt(max(s, 0.0) / max(c, 1.0)))
                for s, c in zip(stats[0], stats[1])]

    def fit(self, epochs: int, ckpt_dir: str | None = None, *,
            ckpt_every: int = 5, max_restarts: int = 3, fault=None):
        """Train with optional checkpoint/resume — the same recovery
        contract as MF-SGD/LDA/MLP ``fit`` (SURVEY.md §6): with
        ``ckpt_dir`` set, a crashed run (or a rerun pointing at the same
        dir) resumes from the latest saved epoch, and a checkpoint from a
        different rank/shape config refuses to restore.  Returns the
        per-epoch RMSEs this call actually ran."""
        from harp_tpu.utils.fault import factor_state_io, fit_epochs

        rmses: list[float] = []
        get_state, set_state = factor_state_io(self, {
            "W": lambda a: self.mesh.shard_array(a, 0),
            # device_put directly (no jnp.asarray detour: the relay ships
            # big compile-time literals — CLAUDE.md trap — and H can be
            # hundreds of MB at graded scale)
            "H": lambda a: jax.device_put(a, self.mesh.replicated()),
        })
        fit_epochs(
            lambda: rmses.append(self.train_epoch()),
            get_state, set_state,
            epochs, ckpt_dir, ckpt_every=ckpt_every,
            max_restarts=max_restarts, fault=fault,
            phase="ccd.epochs",
        )
        return rmses


def benchmark(n_users=50_000, n_items=20_000, nnz=2_000_000, rank=32,
              epochs=2, mesh=None, seed=0):
    from harp_tpu.models.mfsgd import synthetic_ratings

    mesh = mesh or current_mesh()
    model = CCD(n_users, n_items, CCDConfig(rank=rank), mesh, seed)
    u, i, v = synthetic_ratings(n_users, n_items, nnz, seed=seed)
    model.set_ratings(u, i, v)
    r0 = model.train_epoch()     # warmup + single-epoch compile
    model.compile_epochs(epochs)  # AOT, off-clock, does NOT train
    t0 = time.perf_counter()
    r = model.train_epochs(epochs)[-1]
    dt = time.perf_counter() - t0
    return {"coord_updates_per_sec": nnz * rank * epochs / dt,
            "sec_per_epoch": dt / epochs, "rmse_first": r0, "rmse_final": r,
            "rank": rank, "nnz": nnz, "num_workers": mesh.num_workers}


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser(description="harp-tpu CCD++ (edu.iu.ccd parity)")
    p.add_argument("--nnz", type=int, default=2_000_000)
    p.add_argument("--rank", type=int, default=32)
    p.add_argument("--epochs", type=int, default=2)
    args = p.parse_args(argv)
    from harp_tpu.utils.metrics import benchmark_json

    print(benchmark_json("ccd_cli", benchmark(
        nnz=args.nnz, rank=args.rank, epochs=args.epochs)))


if __name__ == "__main__":
    main()
