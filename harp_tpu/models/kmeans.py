"""KMeans — graded config #1: k=100 on 1M×300 dense (allreduce pattern).

Reference parity (SURVEY.md §3.4, §4.2): Harp's ``edu.iu.kmeans.*`` (variants
``regroupallgather``, ``allreduce``) and ``edu.iu.daal_kmeans``.  Each Harp
iteration: workers assign their point shard to nearest centroids (DAAL/MKL
compute), produce partial centroid sums+counts, then ``regroup`` + ``allgather``
(or ``allreduce``) merges partials so every worker starts the next iteration
with the new centroids.

TPU-native design: the whole iteration is ONE jitted SPMD program —
``argmin(dists) → unsorted_segment_sum → psum`` — with centroids replicated
in HBM and all T iterations inside a ``fori_loop``; zero host round-trips in
the hot loop (the reference crosses JNI + sockets every iteration).  The
distance matrix is computed as ``x@cᵀ`` so the FLOPs land on the MXU; only
the cross-term depends on both x and c (||x||² is assignment-invariant and
dropped from the argmin).
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from harp_tpu.ops.pallas_compat import interpret_default
from harp_tpu.parallel import collective as C
from harp_tpu.parallel.mesh import WorkerMesh, current_mesh
from harp_tpu.utils import flightrec, prng, skew, steptrace, telemetry
from harp_tpu.utils.timing import device_sync


@dataclasses.dataclass
class KMeansConfig:
    """Harp knob parity: numMapTasks→mesh size, pointsPerFile→shard size."""

    k: int = 100
    iters: int = 10
    dtype: Any = jnp.float32  # bf16 points keep f32 accumulation (MXU-friendly)
    block_points: int = 0  # >0: process points in blocks to bound the [n,k] dist matrix
    # Harp's two app variants (edu.iu.kmeans.allreduce / .regroupallgather):
    # "allreduce" = one psum; "regroupallgather" = reduce-scatter the
    # partials so each worker owns and normalizes a centroid block, then
    # allgather the new centroids — Harp's headline variant, kept for
    # parity/explicitness.  Identical results AND identical wire traffic:
    # XLA's ring psum already lowers to reduce-scatter+allgather, so this
    # is not a performance knob.
    variant: str = "allreduce"
    # Single-pass Pallas kernel.  None = auto per path, exactly the
    # measured verdicts (FLIP_DECISIONS.jsonl): ON for quantize="int8"
    # — FLIPPED 2026-08-01, 555.1 iter/s vs 486.9 XLA int8 = 1.14× at
    # equal inertia on the graded 1M×300 k=100 shape (the VMEM-budget
    # tile chooser unlocked it: 8000-row tiles vs the old 2000 cap,
    # see ops/kmeans_kernel._tile_rows_int8) — and OFF for f32, where
    # the XLA path measured equal-or-faster (kernel 2.83 ms vs XLA
    # ~2.5 ms, ops/kmeans_kernel.py).  Resolved at READ time
    # (:func:`_use_pallas`) so dataclasses.replace keeps auto tracking.
    use_pallas: bool | None = None
    # opt-in int8 point quantization: per-feature symmetric scales, distances
    # and partial sums as int8 MXU matmuls with exact int32 accumulation —
    # quarter the per-iteration HBM traffic of f32 points.  Accuracy
    # contract (measured on CPU sim, 2026-07-30): near-equidistant
    # assignments may flip within the ~1/127 relative distance resolution;
    # from a non-degenerate init the result matches f32 to 5 digits of
    # inertia, but a degenerate random init (duplicate-cluster seeds) can
    # select a different Lloyd basin — the same sensitivity any metric
    # perturbation has.  TPU wall-clock pending (relay outage, BASELINE.md).
    quantize: str | None = None
    # PR 11 (collective planner): the per-iteration partials allreduce's
    # schedule.  "one_shot" (default — today's single fused psum, bit-
    # identical to every committed row) or "hier" (the planner's
    # hierarchical two-stage psum, collective.allreduce_hier: the
    # payload crosses the inter-host link class once per host group
    # instead of once per worker — a win only on multi-host meshes, and
    # ~2x the bytes on a flat ring, which is why it FAILS CLOSED as flip
    # candidate `kmeans_hier_psum` until relay-measured; float partials
    # reassociate across the two stages, gated on inertia like the int8
    # candidates).  Ignored by variant="regroupallgather" (that schedule
    # already two-phases through push+pull).
    psum_schedule: str = "one_shot"

    def __post_init__(self):
        if self.k < 1:
            raise ValueError(f"k must be >= 1, got {self.k}")
        if self.quantize not in (None, "int8"):
            raise ValueError(f"quantize must be None or 'int8', got {self.quantize!r}")
        if self.quantize and self.block_points:
            raise ValueError("quantize='int8' is incompatible with "
                             "block_points (the int8 paths are single-"
                             "block; use_pallas selects the fused kernel)")
        if self.variant not in ("allreduce", "regroupallgather"):
            raise ValueError(
                f"variant must be 'allreduce' or 'regroupallgather', "
                f"got {self.variant!r}")
        if self.psum_schedule not in ("one_shot", "hier"):
            raise ValueError(
                f"psum_schedule must be 'one_shot' or 'hier', "
                f"got {self.psum_schedule!r}")


def _partials_block(points, centroids, c2, mask=None):
    """Per-block partials: (sums [k,d], counts [k], inertia scalar).

    Everything routes through the MXU: the score matrix comes from
    ``x @ cᵀ`` and the per-cluster sums from ``one_hotᵀ @ x`` — no scatter,
    no gather (both are pathological on TPU; measured 180 ms/iter vs
    5.7 ms/iter fused on the 1M×300 k=100 config, 2026-07-29, 1× v5e).
    ||x||² is dropped from
    the argmin (assignment-invariant) and re-added only to the inertia.

    ``mask`` (optional [b], 0/1): rows with mask 0 contribute nothing —
    the streaming path pads its tail chunk to a fixed shape with these.
    """
    dots = jax.lax.dot_general(
        points, centroids.T, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [b, k]
    scores = c2[None, :] - 2.0 * dots
    assign = jnp.argmin(scores, axis=1)
    onehot = jax.nn.one_hot(assign, c2.shape[0], dtype=points.dtype)
    if mask is None:
        x2 = (points.astype(jnp.float32) ** 2).sum()
        inertia = x2 + scores.min(axis=1).sum()
    else:
        w = mask.astype(jnp.float32)
        x2 = ((points.astype(jnp.float32) ** 2).sum(1) * w).sum()
        inertia = x2 + (scores.min(axis=1) * w).sum()
        onehot = onehot * mask.astype(onehot.dtype)[:, None]
    sums = jax.lax.dot_general(
        onehot, points, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [k, d]
    counts = onehot.sum(0).astype(jnp.float32)
    return sums, counts, inertia


# one worker-local cluster may sum at most 2^31/127 int8 contributions
# before the exact int32 accumulator could wrap
_INT8_SUM_ROW_LIMIT = (1 << 31) // 127


def _clip_round_int8(values, scale, xp=np):
    """THE int8 rounding rule — every quantized-points path (device
    resident, streaming, sharded-ingest, file-split, and the traced
    synthetic twin via ``xp=jnp``) shares this one expression so the
    variants can never disagree on it."""
    return xp.clip(xp.round(values / scale), -127, 127).astype(xp.int8)


def _check_int8_chunk_rows(rows_per_worker, limit):
    """The shared exact-int32 accumulation guard for streamed chunks.
    ``limit`` is REQUIRED: callers resolve their module's
    _INT8_SUM_ROW_LIMIT at call time (tests shrink it to exercise the
    guard) — a default here would silently bypass that."""
    if rows_per_worker > limit:
        raise ValueError(
            f"quantize='int8': {rows_per_worker} chunk rows/worker "
            f"exceeds the {limit} exact-int32 accumulation "
            "bound — use a smaller chunk_points")


def quantize_points_int8(points):
    """Per-feature symmetric int8 quantization: (q int8 [n, d], scale [d]).

    ``points ≈ q * scale[None, :]`` with per-entry error ≤ scale/2.
    Pure numpy (same formula as :func:`collective.quantize_to_int8`): the
    graded-scale matrix must not detour through one device — sharding
    happens after, in ``fit``."""
    points = np.asarray(points, np.float32)
    scale = np.maximum(np.abs(points).max(0), 1e-30) / 127.0
    return _clip_round_int8(points, scale), scale.astype(np.float32)


def _quantize_centroids(centroids, col_scale):
    """Per-iteration centroid requantization shared by the XLA int8 path
    and the fused Pallas kernel (ops/kmeans_kernel.kmeans_partials_int8):
    centroids enter the quantized-feature coordinate system
    (``cs = c · col_scale``), each ROW gets its own symmetric scale, and
    ``c2`` stays in the original space for the score decomposition.
    Returns (c_q [k, d] int8, c_scale [k] f32, c2 [k] f32)."""
    cs = centroids.astype(jnp.float32) * col_scale[None, :]      # [k, d]
    c_q, c_scale_col = C.quantize_to_int8(cs, jnp.abs(cs).max(1, keepdims=True))
    c2 = (centroids.astype(jnp.float32) ** 2).sum(-1)            # [k]
    return c_q, c_scale_col[:, 0], c2


def _partials_block_int8(pts_q, col_scale, centroids, c2, mask=None,
                         x2=None):
    """Quantized twin of :func:`_partials_block`: both matmuls run int8 on
    the MXU (v5e: 2× the bf16 rate, ¼ the f32 bytes); accumulation is
    exact int32, dequantized once per [k, d]/[k] output.  The centroid
    operand requantizes per iteration with a per-centroid scale, so the
    only approximation is the two int8 roundings inside the argmin.
    ``mask`` as in :func:`_partials_block` (int8 0/1 keeps the sums
    matmul int8; a padded row contributes exact zeros).  ``x2``: the
    iteration-invariant ``Σ‖x‖²`` — pass the hoisted value to skip this
    block's full re-read of the point stream (maskless callers only;
    the masked/streaming path sees different rows per chunk)."""
    k = centroids.shape[0]
    c_q, c_scale, _ = _quantize_centroids(centroids, col_scale)
    dots_i = jax.lax.dot_general(
        pts_q, c_q, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)                        # [n, k]
    dots = dots_i.astype(jnp.float32) * c_scale[None, :]
    scores = c2[None, :] - 2.0 * dots
    assign = jnp.argmin(scores, axis=1)
    onehot = jax.nn.one_hot(assign, k, dtype=jnp.int8)
    if mask is None:
        if x2 is None:
            x2 = ((pts_q.astype(jnp.float32) * col_scale[None, :]) ** 2
                  ).sum()
        inertia = x2 + scores.min(axis=1).sum()
    else:
        assert x2 is None, "x2 hoisting is a maskless-path optimization"
        w = mask.astype(jnp.float32)
        x2 = (((pts_q.astype(jnp.float32) * col_scale[None, :]) ** 2).sum(1)
              * w).sum()
        inertia = x2 + (scores.min(axis=1) * w).sum()
        onehot = onehot * mask.astype(jnp.int8)[:, None]
    sums_i = jax.lax.dot_general(
        onehot, pts_q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)                        # [k, d]
    sums = sums_i.astype(jnp.float32) * col_scale[None, :]
    counts = jnp.sum(onehot, axis=0, dtype=jnp.int32).astype(jnp.float32)
    return sums, counts, inertia


def kmeans_kernel_supported(n: int) -> bool:
    """use_pallas falls back to the XLA path when no tile divides the shard."""
    from harp_tpu.ops import kmeans_kernel

    return kmeans_kernel.supported(n)


def _use_pallas(cfg: KMeansConfig) -> bool:
    """Resolved use_pallas — None means auto per path (the 2026-08-01
    verdicts: fused kernel ON for int8 — 1.14× at equal inertia — OFF
    for f32 where XLA measured equal-or-faster)."""
    if cfg.use_pallas is None:
        return cfg.quantize == "int8"
    return cfg.use_pallas


def kmeans_step(points, centroids, cfg: KMeansConfig, x2=None):
    """One Lloyd iteration (device view, per-worker shard).

    Returns (new_centroids, inertia).  The partial-sums → allreduce is
    exactly Harp's regroup+allgather phase, fused to one psum.  ``x2``:
    optional hoisted ``Σ‖x‖²`` (int8 paths; iteration-invariant, see
    make_fit_fn).
    """
    if cfg.quantize == "int8":
        from harp_tpu.ops import kmeans_kernel

        pts_q, col_scale = points  # (int8 [n, d], f32 [d]) — see fit()
        # the gate consults the int8 kernel's OWN supportability (tile
        # within the VMEM budget AND d inside the exact-accumulation
        # bound) and falls back to the XLA path — the auto default must
        # not make previously-working shapes raise
        if _use_pallas(cfg) and kmeans_kernel.int8_supported(
                pts_q.shape[0], pts_q.shape[1], cfg.k):
            # fused single-pass kernel: the XLA int8 path materializes
            # ~2 GB/iter of [n, k] intermediates at the graded shape and
            # clocks the same 2.5 ms/iter as f32 (1M×300 k=100, 1× v5e,
            # 2026-07-31); the kernel reads only the int8 stream.  x2 is
            # required: the fused path never re-reads points for it.
            assert x2 is not None, "fused int8 path needs the hoisted x2"
            c_q, c_scale, c2 = _quantize_centroids(centroids, col_scale)
            sums, counts, best_sum = kmeans_kernel.kmeans_partials_int8(
                pts_q, c_q, c_scale, c2, col_scale,
                interpret=interpret_default())
            partial_inertia = best_sum + x2
        else:
            c2 = (centroids.astype(jnp.float32) ** 2).sum(-1)
            sums, counts, partial_inertia = _partials_block_int8(
                pts_q, col_scale, centroids, c2, x2=x2)
        nw = lax.axis_size(C.WORKER_AXIS)
        return _combine_partials(sums, counts, partial_inertia, centroids,
                                 cfg, nw)
    n = points.shape[0]
    block = cfg.block_points
    if _use_pallas(cfg) and kmeans_kernel_supported(n):
        from harp_tpu.ops import kmeans_kernel

        if block:
            raise ValueError("block_points has no effect with use_pallas "
                             "(the kernel picks its own tile size)")
        sums, counts, partial_inertia = kmeans_kernel.kmeans_partials(
            points, centroids, interpret=interpret_default())
    elif block <= 0 or block >= n:
        c2 = (centroids.astype(jnp.float32) ** 2).sum(-1)  # [k]
        sums, counts, partial_inertia = _partials_block(points, centroids, c2)
    else:
        assert n % block == 0, "block_points must divide the local shard size"
        c2 = (centroids.astype(jnp.float32) ** 2).sum(-1)  # [k]
        blocks = points.reshape(n // block, block, points.shape[1])
        sums, counts, partial_inertia = lax.map(
            lambda b: _partials_block(b, centroids, c2), blocks
        )
        sums, counts = sums.sum(0), counts.sum(0)
        partial_inertia = partial_inertia.sum()

    nw = lax.axis_size(C.WORKER_AXIS)
    return _combine_partials(sums, counts, partial_inertia, centroids, cfg, nw)


def _normalize_centroids(sums, counts, old):
    """Empty cluster keeps its old centroid — the ONE empty-cluster policy,
    shared by every path (both fit variants AND the streaming module); a
    change here, e.g. reseeding, must apply to all of them identically."""
    return jnp.where(
        counts[:, None] > 0, sums / jnp.maximum(counts[:, None], 1.0), old
    ).astype(old.dtype)


def _combine_partials(sums, counts, partial_inertia, centroids, cfg, nw):
    """The collective+normalize tail every partials formulation shares."""
    normalize = _normalize_centroids

    if cfg.variant == "regroupallgather" and sums.shape[0] % nw == 0:
        # Harp's regroup+allgather: reduce-scatter the partials so worker w
        # owns centroid block w (the regroup/push phase), normalize locally,
        # allgather the normalized blocks.  Falls back to allreduce when
        # k isn't divisible (Harp's partitioner would round-robin uneven
        # blocks; one fused psum is the degenerate equivalent).
        my_sums, my_counts = C.push((sums, counts))
        kb = sums.shape[0] // nw
        me = lax.axis_index(C.WORKER_AXIS)
        cent_blk = lax.dynamic_slice_in_dim(centroids, me * kb, kb, 0)
        new_centroids = C.pull(normalize(my_sums, my_counts, cent_blk))
        inertia = C.allreduce(partial_inertia)
        return new_centroids, inertia

    if cfg.psum_schedule == "hier":
        # the planner's hierarchical two-stage psum (fail-closed flip
        # candidate kmeans_hier_psum; see KMeansConfig.psum_schedule)
        sums, counts, inertia = C.allreduce_hier(
            (sums, counts, partial_inertia))
    else:
        sums, counts, inertia = C.allreduce((sums, counts, partial_inertia))
    return normalize(sums, counts, centroids), inertia


def _effective_variant(variant: str, k: int, num_workers: int) -> str:
    """The variant that will actually run — the two-phase form needs
    ``k % num_workers == 0`` and falls back to allreduce (loudly)."""
    if variant == "regroupallgather" and k % num_workers != 0:
        import logging

        logging.getLogger("harp_tpu").warning(
            "kmeans: k=%d not divisible by %d workers — regroupallgather "
            "falls back to the (equivalent) allreduce path", k, num_workers)
        return "allreduce"
    return variant


def make_fit_fn(mesh: WorkerMesh, cfg: KMeansConfig):
    """Compile the full T-iteration KMeans run as one SPMD program."""

    def run(points, centroids):
        x2 = None
        if cfg.quantize == "int8":
            # Σ‖x‖² is iteration-invariant: one pass here instead of one
            # per Lloyd iteration (the fori_loop body would re-read the
            # whole point stream for it every iteration otherwise)
            pts_q, col_scale = points
            x2 = ((pts_q.astype(jnp.float32) * col_scale[None, :]) ** 2
                  ).sum()

        def body(i, state):
            c, _ = state
            return kmeans_step(points, c, cfg, x2=x2)

        centroids, inertia = lax.fori_loop(
            0, cfg.iters, body, (centroids, jnp.float32(0.0)))
        # per-worker active-row count folded NEXT TO the inertia — the
        # skew spine's execution counter (utils/skew.py) rides the same
        # [nw, 2] stats readback; no collective is added (the
        # out-sharding concatenates), so the hand-computed comm byte
        # sheet (tests/test_telemetry.py) and the pinned flight budgets
        # (compiles=1, dispatches=1, readbacks=2) are untouched
        rows = (points[0] if cfg.quantize == "int8" else points).shape[0]
        stats = jnp.stack([jnp.float32(rows), inertia])[None]  # [1, 2]
        return centroids, stats

    pts_spec = ((mesh.spec(0), P()) if cfg.quantize == "int8"
                else mesh.spec(0))  # (q shards, replicated col scales)
    return jax.jit(
        mesh.shard_map(run, in_specs=(pts_spec, P()),
                       out_specs=(P(), mesh.spec(0)))
    )


def kmeanspp_init(points, k, seed=0, sample=50_000):
    """k-means++ seeding (Arthur & Vassilvitskii) on a host subsample.

    Beyond-reference robustness: Harp seeds with random rows, which can
    pick duplicate-cluster seeds and strand Lloyd in a bad basin (measured:
    2× worse true inertia on separated clusters, see tests).  Runs on a
    ``sample``-row subsample so graded-scale inputs stay O(sample·k·d)."""
    pts = np.asarray(points, np.float32)
    rng = np.random.default_rng(seed)
    if len(pts) > sample:
        pts = pts[rng.choice(len(pts), size=sample, replace=False)]
    centers = [pts[rng.integers(len(pts))]]
    d2 = ((pts - centers[0]) ** 2).sum(1)
    for _ in range(k - 1):
        # float64 so the probabilities pass numpy's sum-to-one check even
        # when one entry dominates (f32 rounding can exceed the tolerance)
        d2_64 = d2.astype(np.float64)
        total = float(d2_64.sum())
        if total <= 0.0:
            # fewer than k distinct rows: every point already coincides
            # with a center — fall back to uniform picks (Lloyd's
            # keep-old-centroid rule handles the resulting empty clusters)
            nxt = pts[rng.integers(len(pts))]
        else:
            nxt = pts[rng.choice(len(pts), p=d2_64 / total)]
        centers.append(nxt)
        d2 = np.minimum(d2, ((pts - nxt) ** 2).sum(1))
    return np.stack(centers)


def fit(points, k=100, iters=10, mesh: WorkerMesh | None = None, seed=0,
        dtype=jnp.float32, block_points=0, use_pallas=None,
        variant="allreduce", quantize=None, init="random",
        psum_schedule="one_shot",
        ckpt_dir: str | None = None, ckpt_every: int = 5,
        max_restarts: int = 3, fault=None):
    """Host driver — the ``mapCollective`` residue (SURVEY.md §4.2).

    ``points``: [n, d] host or device array; sharded over workers on dim 0.
    Initialization (``init``): "random" (Harp's scheme) picks k distinct
    random rows with the integer ``seed``, or the first k points when
    ``seed=None`` — deterministic, so results match a numpy Lloyd
    reference exactly (the golden tests use this mode); "kmeans++" uses
    :func:`kmeanspp_init` (beyond-reference, far less init-sensitive).

    Checkpoint/resume (PR 10, the SURVEY.md §6 driver contract the other
    graded apps already carry): with ``ckpt_dir`` set, the T iterations
    run as ``ckpt_every``-iteration device programs with the centroids
    checkpointed between chunks through
    :class:`~harp_tpu.utils.checkpoint.CheckpointManager`; a crashed run
    (or a rerun pointing at the same dir — the CLI ``--resume``) resumes
    from the latest saved chunk instead of iteration 0.  The chunked
    schedule replays bit-identically on resume: each chunk is the same
    compiled program over the same operands, and restored centroids
    round-trip host-side exactly (f32 in, f32 out).
    """
    mesh = mesh or current_mesh()
    variant = _effective_variant(variant, k, mesh.num_workers)
    cfg = KMeansConfig(k=k, iters=iters, dtype=dtype, block_points=block_points,
                       use_pallas=use_pallas, variant=variant, quantize=quantize,
                       psum_schedule=psum_schedule)
    n = points.shape[0]
    if init == "kmeans++":
        init_c = kmeanspp_init(points, k, seed=0 if seed is None else seed)
    elif init == "random":
        if seed is None:
            init_idx = np.arange(k)
        else:
            init_idx = np.random.default_rng(seed).choice(n, size=k,
                                                          replace=False)
        init_c = np.asarray(points[np.sort(init_idx)])
    else:
        raise ValueError(f"init must be 'random' or 'kmeans++', got {init!r}")
    centroids = jnp.asarray(init_c, dtype=dtype)
    if quantize == "int8":
        if -(-n // mesh.num_workers) > _INT8_SUM_ROW_LIMIT:
            raise ValueError(
                f"quantize='int8': {n} points over {mesh.num_workers} workers "
                f"exceeds the {_INT8_SUM_ROW_LIMIT} rows/worker exact-int32 "
                "accumulation bound — use more workers or the f32 path")
        q, scale = quantize_points_int8(points)
        pts = (mesh.shard_array(q, 0),
               jax.device_put(jnp.asarray(scale), mesh.replicated()))
    else:
        pts = mesh.shard_array(
            np.asarray(points, dtype=np.dtype(jnp.dtype(dtype).name)), 0)
    centroids = jax.device_put(centroids, mesh.replicated())
    if ckpt_dir is not None:
        return _fit_ckpt(mesh, cfg, pts, centroids, iters,
                         ckpt_dir, ckpt_every=ckpt_every,
                         max_restarts=max_restarts, fault=fault)
    if fault is not None:
        raise ValueError(
            "fault injection requires ckpt_dir (recovery restarts from "
            "checkpoints; without one the injector would be silently "
            "ignored)")
    fit_fn = flightrec.track(make_fit_fn(mesh, cfg), "kmeans.fit")
    # telemetry: the T iterations run inside ONE dispatch, so the traced
    # per-iteration comm sites execute cfg.iters times per invocation;
    # the flight recorder sees that one dispatch plus exactly two
    # readbacks (inertia scalar + final centroids)
    # steptrace (PR 18): the whole-run dispatch is ONE superstep — the
    # timeline shows the single-dispatch discipline literally (one span,
    # flight {dispatches: 1})
    with telemetry.span("kmeans.fit", iters=cfg.iters, k=k), \
            telemetry.ledger.run("kmeans.fit", steps=cfg.iters), \
            steptrace.run("kmeans.fit"), \
            steptrace.superstep("kmeans.fit", 0):
        t0 = time.perf_counter()
        new_c, stats = fit_fn(pts, centroids)
        st = flightrec.readback(stats)  # [nw, 2]: per-worker rows, inertia
        inertia = float(st[0, 1])
        skew.record_execution("kmeans.fit", st[:, 0], unit="points",
                              wall_s=time.perf_counter() - t0)
        return flightrec.readback(new_c), inertia


def _fit_ckpt(mesh, cfg, pts, centroids, iters, ckpt_dir, *,
              ckpt_every=5, max_restarts=3, fault=None):
    """The recovery-looped fit: ``ckpt_every``-iteration device chunks
    under :func:`harp_tpu.utils.fault.run_with_recovery`, centroids (+
    the last chunk's stats, so a no-work resume still reports inertia)
    checkpointed between chunks.  One compiled program per distinct
    chunk length (at most two: the full chunk and a ragged tail)."""
    from harp_tpu.utils.checkpoint import CheckpointManager
    from harp_tpu.utils.fault import run_with_recovery

    mgr = CheckpointManager(ckpt_dir)
    lens = [min(ckpt_every, iters - s) for s in range(0, iters, ckpt_every)]
    fns: dict[int, Any] = {}

    def chunk_fn(n_it):
        fn = fns.get(n_it)
        if fn is None:
            fn = fns[n_it] = flightrec.track(
                make_fit_fn(mesh, dataclasses.replace(cfg, iters=n_it)),
                "kmeans.fit_ckpt")
        return fn

    nw = mesh.num_workers

    def place(c):
        return jax.device_put(jnp.asarray(np.asarray(c), dtype=cfg.dtype),
                              mesh.replicated())

    def make_state():
        return {"centroids": centroids,
                "stats": jnp.zeros((nw, 2), jnp.float32)}

    def step(ci, state):
        with steptrace.superstep("kmeans.fit_ckpt", ci):
            c = state["centroids"]
            if not isinstance(c, jax.Array):  # numpy from a fresh restore
                c = place(c)
            new_c, stats = chunk_fn(lens[ci])(pts, c)
            return {"centroids": new_c, "stats": stats}

    with telemetry.span("kmeans.fit_ckpt", iters=iters, k=cfg.k), \
            steptrace.run("kmeans.fit_ckpt"):
        final = run_with_recovery(make_state, step, len(lens), mgr,
                                  ckpt_every=1, max_restarts=max_restarts,
                                  fault=fault)
    st = np.asarray(final["stats"])
    return np.asarray(final["centroids"]), float(st[0, 1])


def benchmark(n=1_000_000, d=300, k=100, iters=10, mesh=None, dtype=jnp.float32,
              warmup=2, seed=0, use_pallas=None, variant="allreduce",
              quantize=None, psum_schedule="one_shot"):
    """Measure iter/sec on the graded 1M×300 k=100 config (north-star metric)."""
    mesh = mesh or current_mesh()
    variant = _effective_variant(variant, k, mesh.num_workers)
    cfg = KMeansConfig(k=k, iters=1, dtype=dtype, use_pallas=use_pallas,
                       variant=variant, quantize=quantize,
                       psum_schedule=psum_schedule)
    nw = mesh.num_workers
    n = (n // nw) * nw  # actual points generated/processed (and reported)

    # Generate the shard on-device (no host→HBM transfer of 1.2 GB).
    def gen(key):
        return jax.random.normal(key, (n // nw, d), dtype=dtype)

    # raw key bits (utils.prng): a fresh seed must not cost a fresh
    # (remote) compile — CLAUDE.md PRNGKey-specialization trap
    keys = jax.random.split(jnp.asarray(prng.key_bits(seed)), nw)
    points = flightrec.track(jax.jit(
        mesh.shard_map(lambda ks: gen(ks[0]), in_specs=(mesh.spec(0),),
                       out_specs=mesh.spec(0))
    ), "kmeans.datagen")(keys)
    if quantize == "int8":
        if n // nw > _INT8_SUM_ROW_LIMIT:
            raise ValueError(
                f"quantize='int8': {n // nw} rows/worker exceeds the "
                f"{_INT8_SUM_ROW_LIMIT} exact-int32 accumulation bound")
        # on-device quantization: per-feature |max| needs a cross-shard pmax
        def quant(x):
            amax = C.allreduce(jnp.abs(x).max(0), C.Combiner.MAX)
            return C.quantize_to_int8(x, amax)  # scale [d] broadcasts

        points = flightrec.track(jax.jit(mesh.shard_map(
            quant, in_specs=(mesh.spec(0),),
            out_specs=(mesh.spec(0), P()))), "kmeans.quantize")(points)
    centroids = jax.device_put(
        jax.random.normal(jnp.asarray(prng.key_bits(seed + 1)), (k, d),
                          dtype=dtype),
        mesh.replicated(),
    )

    # All iterations inside ONE jitted program: the relay's ~4 ms/dispatch
    # overhead and unreliable block_until_ready (see utils.timing) both
    # disappear; sync is a scalar readback, which cannot complete early.
    # n_iters is a traced scalar so warmup and the timed run share one
    # compilation (recompiling inside the timed region once cost 4x).
    def run(points, centroids, n_iters):
        x2 = None
        if quantize == "int8":  # hoisted Σ‖x‖², as in make_fit_fn
            pts_q, col_scale = points
            x2 = ((pts_q.astype(jnp.float32) * col_scale[None, :]) ** 2
                  ).sum()

        def body(i, st):
            c, _ = st
            return kmeans_step(points, c, cfg, x2=x2)

        return lax.fori_loop(0, n_iters, body, (centroids, jnp.float32(0.0)))

    pts_spec = ((mesh.spec(0), P()) if quantize == "int8" else mesh.spec(0))
    run_fn = flightrec.track(jax.jit(
        mesh.shard_map(
            run, in_specs=(pts_spec, P(), P()), out_specs=(P(), P()),
        )
    ), "kmeans.benchmark")
    # telemetry: n_iters is a traced scalar, so the loop body's comm sites
    # trace once — the host knows the real per-invocation trip count
    with telemetry.ledger.run("kmeans.benchmark", steps=max(warmup, 1)):
        c_w, inertia = run_fn(points, centroids, jnp.int32(max(warmup, 1)))
        device_sync(inertia)

    t0 = time.perf_counter()
    with telemetry.span("kmeans.benchmark", iters=iters), \
            telemetry.ledger.run("kmeans.benchmark", steps=iters):
        centroids, inertia = run_fn(points, centroids, jnp.int32(iters))
        inertia_val = device_sync(inertia)
    dt = time.perf_counter() - t0
    return {
        "iters_per_sec": iters / dt,
        "points_per_sec": n * iters / dt,
        "sec_per_iter": dt / iters,
        "inertia": inertia_val,
        "n": n, "d": d, "k": k, "num_workers": nw,
        "dtype": str(jnp.dtype(dtype).name),
        "variant": variant,  # the variant that actually ran (post-fallback)
        "quantize": quantize,
        "psum_schedule": psum_schedule,
    }


def main(argv=None):
    import argparse

    from harp_tpu.utils.metrics import benchmark_json

    p = argparse.ArgumentParser(description="harp-tpu KMeans (edu.iu.kmeans parity)")
    p.add_argument("--n", type=int, default=1_000_000)
    p.add_argument("--d", type=int, default=300)
    p.add_argument("--k", type=int, default=100)
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--dtype", default="float32", choices=["float32", "bfloat16"])
    p.add_argument("--variant", default="allreduce",
                   choices=["allreduce", "regroupallgather"],
                   help="Harp app variant: one fused psum, or the explicit "
                        "regroup(reduce-scatter)+allgather two-phase form")
    p.add_argument("--input", default=None, metavar="FILE_OR_GLOB",
                   help="CSV/whitespace point files (one point per row) — "
                        "the Harp app's HDFS input; default: synthetic")
    p.add_argument("--init", choices=["random", "kmeans++"], default="random",
                   help="centroid seeding: Harp's random rows, or kmeans++ "
                        "(beyond-reference; far less init-sensitive)")
    p.add_argument("--quantize", choices=["int8"], default=None,
                   help="opt-in int8 point quantization (¼ the HBM traffic; "
                        "see KMeansConfig.quantize for the accuracy contract)")
    p.add_argument("--psum-schedule", choices=["one_shot", "hier"],
                   default="one_shot",
                   help="partials-allreduce schedule: one fused psum "
                        "(default) or the planner's hierarchical two-stage "
                        "psum (flip candidate kmeans_hier_psum — see "
                        "KMeansConfig.psum_schedule)")
    p.add_argument("--bench", action="store_true", help="synthetic benchmark mode")
    p.add_argument("--ckpt-dir", default=None,
                   help="fit with checkpoint/resume: iterations run in "
                        "--ckpt-every chunks with centroids checkpointed "
                        "between them; rerunning with the same dir resumes "
                        "from the latest saved chunk")
    p.add_argument("--ckpt-every", type=int, default=5,
                   help="iterations per checkpointed chunk")
    p.add_argument("--resume", action="store_true",
                   help="assert the run RESUMES: --ckpt-dir must already "
                        "hold a checkpoint (a mistyped dir fails loudly "
                        "instead of silently restarting from iteration 0)")
    args = p.parse_args(argv)
    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32

    from harp_tpu.utils.fault import resolve_resume

    resumed_from = resolve_resume(args.ckpt_dir, args.resume)

    from harp_tpu.report import maybe_emit

    if args.bench:
        out = benchmark(args.n, args.d, args.k, args.iters, dtype=dtype,
                        variant=args.variant, quantize=args.quantize,
                        psum_schedule=args.psum_schedule)
        print(out)
        maybe_emit("kmeans_bench")
    else:
        if args.input:
            from harp_tpu.native.datasource import load_csv_glob

            try:
                pts = load_csv_glob(args.input)
            except ValueError as e:
                raise SystemExit(str(e))
        else:
            rng = np.random.default_rng(0)
            pts = rng.normal(size=(args.n, args.d)).astype(np.float32)
        c, inertia = fit(pts, args.k, args.iters, dtype=dtype,
                         variant=args.variant, quantize=args.quantize,
                         init=args.init, psum_schedule=args.psum_schedule,
                         ckpt_dir=args.ckpt_dir,
                         ckpt_every=args.ckpt_every)
        print(benchmark_json("kmeans_cli", {"k": args.k, "iters": args.iters, "n": pts.shape[0],
               "d": pts.shape[1], "inertia": inertia,
               "ckpt_dir": args.ckpt_dir, "resumed_from": resumed_from}))
        maybe_emit("kmeans")


if __name__ == "__main__":
    main()
