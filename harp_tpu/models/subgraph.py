"""Subgraph counting via color-coding — graded config #5a (irregular).

Reference parity (SURVEY.md §3.4): Harp's ``edu.iu.subgraph`` (and
``edu.iu.daal_subgraph``) counts tree-shaped templates (u3-1, u5-x, u7-x …)
in a large graph with the color-coding dynamic program: randomly color
vertices with s colors (s = template size), count *colorful* embeddings
(all colors distinct) by DP over a rooted decomposition of the template,
then unbias by the colorfulness probability ``s!/sˢ``.  Harp parallelizes
by vertex partition and exchanges per-vertex count tables with
``allgather``/``regroup`` each DP level — the "irregular" workload.

TPU-native design: the per-vertex count table for a partial absorbing j
template vertices is stored **compactly over the C(k, j) size-j color
subsets** (a colorful partial uses exactly j distinct colors — every
other bitmask column is identically zero), so each DP level becomes

  ``counts_t[v, S] = Σ_{S₁⊎S₂=S} counts_{t₁}[v, S₁] · (A @ counts_{t₂})[v, S₂]``

— a sparse-neighbor aggregation (padded-CSR gather + mask over the
compact columns) followed by a subset convolution through static
position maps.  The distributed step is one ``allgather`` of the compact
partner table per DP level, matching Harp's communication pattern
verb-for-verb at the C(k, j)/2ᵏ fraction of the naive dense wire
(u5-tree: 5–10 of 32 columns per level; u7-tree ≤ 35 of 128).

Round-3 compact-table measurements (8-worker CPU sim, 2026-07-31,
bit-identical counts): u5-tree 100k-vertex power-law 284.4k vertices/s
(130.4k before the column work on the smoke A/B — ~2.4×); u7-tree
50k-vertex power-law 171.6k vertices/s (122.9k with dense tables and
sliced exchanges — a further 1.4× from compact storage).  TPU
re-measure rides the relay sprint (BASELINE.md candidates table).
"""

from __future__ import annotations

import dataclasses
import math
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from harp_tpu.parallel import collective as C
from harp_tpu.parallel.mesh import WorkerMesh, current_mesh
from harp_tpu.utils import flightrec


# ---------------------------------------------------------------------------
# Templates: rooted trees given as parent lists; decomposition into
# (root-keeps-child-subtree) partial templates, exactly the color-coding DP.
# ---------------------------------------------------------------------------

TEMPLATES = {
    # name: parent list (parent[i] < i, parent[0] = -1 root)
    "u3-path": [-1, 0, 1],          # path on 3 vertices
    "u3-star": [-1, 0, 0],          # star (same graph, different rooting)
    "u5-path": [-1, 0, 1, 2, 3],
    "u5-star": [-1, 0, 0, 0, 0],
    "u5-tree": [-1, 0, 0, 1, 1],    # balanced binary-ish tree
    "u7-tree": [-1, 0, 0, 1, 1, 2, 2],
    # the deep end of the reference's template ladder (upstream shipped
    # 10-15-vertex trees): DP table width is 2^k subset columns, so
    # u10 = 1024 and u12 = 4096 columns — the compact C(k, j) storage
    # keeps memory at the size-j support only
    "u10-tree": [-1, 0, 0, 1, 1, 2, 2, 3, 3, 4],
    "u12-tree": [-1, 0, 0, 1, 1, 2, 2, 3, 3, 4, 4, 5],
}


def template_size(tpl) -> int:
    return len(tpl)


def _children(tpl):
    ch = [[] for _ in tpl]
    for i, p in enumerate(tpl):
        if p >= 0:
            ch[p].append(i)
    return ch


def _subtree_sizes(tpl):
    ch = _children(tpl)
    size = [1] * len(tpl)
    for i in reversed(range(len(tpl))):
        for c in ch[i]:
            size[i] += size[c]
    return size


_FN_CACHE: dict = {}


def make_colorful_count_fn(tpl, k, mesh: WorkerMesh,
                           overflow_algo: str = "segment",
                           row_tile: int = 512):
    """Compile the color-coding DP:
    (nbr [n, deg], msk [n, deg], *overflow, colors [trial_chunk, n]) →
    [trial_chunk] colorful rooted counts — a chunk of trials per program
    (vmap over colorings; the driver chunks, see
    SubgraphConfig.trial_chunk).  ``overflow_algo`` picks the exact tail
    for past-max_degree adjacency (see SubgraphConfig): "segment" takes
    the 3 flattened arrays of :func:`_partition_overflow`, "onehot" the
    4 tiled arrays of :func:`_partition_overflow_tiles`.

    Counts maps φ: template→graph with all image colors distinct (hence
    injective), rooted at template vertex 0 — the quantity Harp's DP
    levels accumulate before unbiasing.  Compiled fns are cached per
    (template, colors, mesh, overflow formulation); jit re-specializes
    per trials count.
    """
    # key on the underlying jax Mesh (hashable, identity-stable), not the
    # WorkerMesh wrapper, whose id could be reused after collection;
    # row_tile only shapes the onehot trace — keying it under "segment"
    # would cache duplicate byte-identical programs
    cache_key = (tuple(tpl), k, mesh.mesh, overflow_algo,
                 row_tile if overflow_algo == "onehot" else None)
    if cache_key in _FN_CACHE:
        return _FN_CACHE[cache_key]
    s = template_size(tpl)
    ch = _children(tpl)
    sizes = _subtree_sizes(tpl)
    combos = _dp_subset_tables(tpl, k)
    n_subsets = 1 << k
    n_ovf_args = 3 if overflow_algo == "segment" else 4

    def spmv_gather(full_counts, nbr, msk, *ovf):
        # Σ_{u∈N(v)} counts[u, :]: padded CSR for the low-degree mass
        # (dense gather, MXU-friendly) + an EXACT tail for entries past
        # max_degree — no adjacency is ever dropped (round-1 VERDICT
        # weak #4: power-law hubs)
        g = jnp.take(full_counts, nbr, axis=0)      # [n_loc, deg, S]
        out = (g * msk[:, :, None]).sum(1)
        if overflow_algo == "segment":
            o_nbr, o_row, o_msk = ovf
            og = jnp.take(full_counts, o_nbr, axis=0) * o_msk[:, None]
            # _partition_overflow emits o_row ascending (padding id 0
            # first), so the sorted segment-sum lowering applies — the
            # cheap mitigant for the v5e ~25 GB/s small-row scatter
            # floor (CLAUDE.md)
            return out + jax.ops.segment_sum(og, o_row,
                                             num_segments=out.shape[0],
                                             indices_are_sorted=True)
        # "onehot": no scatter at all — each (entry × row-window) tile is
        # one one-hot MXU matmul into a dynamic-sliced block (the
        # mfsgd/lda pattern); acc is padded by row_tile so the last
        # window's slice stays in bounds
        t_nbr, t_loc, t_msk, t_lo = ovf
        acc = jnp.concatenate(
            [out, jnp.zeros((row_tile, out.shape[1]), out.dtype)], 0)

        def body(a, tile):
            nb, lc, mk, lo = tile
            og = jnp.take(full_counts, nb, axis=0) * mk[:, None]  # [TE, S]
            oh = jax.nn.one_hot(lc, row_tile, dtype=og.dtype)     # [TE, R]
            contrib = jax.lax.dot_general(  # ohᵀ @ og → [R, S], MXU
                oh, og, (((0,), (0,)), ((), ())))
            blk = jax.lax.dynamic_slice_in_dim(a, lo, row_tile, 0)
            return jax.lax.dynamic_update_slice_in_dim(
                a, blk + contrib, lo, 0), None

        acc, _ = jax.lax.scan(body, acc, (t_nbr, t_loc, t_msk, t_lo))
        return acc[: out.shape[0]]

    # Colorful counting: a partial rooted at i with j template vertices
    # absorbed uses EXACTLY j distinct colors, so its table is supported
    # on the C(k, j) size-j subsets alone.  Tables therefore live
    # COMPACTLY over that support (round 3 session 2) — u5-tree keeps
    # 5–10 columns instead of 2^5 everywhere: the per-level allgather
    # wire, the neighbor gathers (the dominant cost), the overflow
    # tails, the subset-convolution scatter and the vmapped HBM
    # footprint all shrink by the support ratio.  Counts are
    # bit-identical: the dropped columns were identically zero.
    supp = {sz: [m for m in range(n_subsets)
                 if bin(m).count("1") == sz] for sz in range(k + 1)}
    pos = {sz: {m: j for j, m in enumerate(cols)}
           for sz, cols in supp.items()}

    def one_trial(nbr, msk, ovf, colors_shard):
        # compact singleton: supp[1] is [1<<0, 1<<1, ...] ascending, so
        # the position of color c's mask is c — a plain one-hot
        singleton = jax.nn.one_hot(colors_shard, k, dtype=jnp.float32)

        # post-order DP: table[i] = counts for subtree rooted at i
        tables = [None] * len(tpl)
        for i in reversed(range(len(tpl))):
            acc = singleton  # root-of-subtree alone
            acc_size = 1
            for c in ch[i]:
                triples = combos(acc_size, sizes[c])
                new_size = acc_size + sizes[c]
                p1 = jnp.asarray([pos[acc_size][t[1]] for t in triples],
                                 jnp.int32)
                p2 = jnp.asarray([pos[sizes[c]][t[2]] for t in triples],
                                 jnp.int32)
                pS = jnp.asarray([pos[new_size][t[0]] for t in triples],
                                 jnp.int32)
                child_full = C.allgather(tables[c])  # compact Harp step
                nbr_counts = spmv_gather(child_full, nbr, msk, *ovf)
                contrib = acc[:, p1] * nbr_counts[:, p2]  # [n_loc, T]
                acc = jnp.zeros(
                    (acc.shape[0], len(supp[new_size])), acc.dtype
                ).at[:, pS].add(contrib)
                acc_size = new_size
            tables[i] = acc

        # the root table's support IS the size-s subsets (one column when
        # k == s): summing the compact table covers both cases
        return tables[0].sum(-1).sum()

    def prog(nbr, msk, *rest):
        # colors_shard [trial_chunk, n_loc]: a chunk of trials per program —
        # each dispatch+readback round trip costs ~20–150 ms (1× v5e relay,
        # 2026-07-30, BASELINE.md row 4), so a per-trial host loop would
        # dominate multi-trial estimates; chunking (not all-trials-vmap)
        # bounds the compact [chunk, n_loc, C(k, j)] DP tables' HBM
        # footprint (≤ C(k, floor(k/2)) columns — 10 for u5, 35 for u7)
        ovf, colors_shard = rest[:-1], rest[-1]
        rooted = jax.vmap(
            lambda cs: one_trial(nbr, msk, ovf, cs)
        )(colors_shard)
        return C.allreduce(rooted)  # [trial_chunk], replicated

    fn = flightrec.track(jax.jit(mesh.shard_map(
        prog,
        in_specs=(mesh.spec(0),) * (2 + n_ovf_args) + (mesh.spec(1),),
        out_specs=P(),
    )), "subgraph.count")
    _FN_CACHE[cache_key] = fn
    return fn


@dataclasses.dataclass
class SubgraphConfig:
    template: str = "u5-tree"
    n_colors: int = 0        # 0 → template size (standard color-coding)
    n_trials: int = 1        # average over colorings (variance reduction)
    # trials per device program: chunking bounds the DP tables' HBM use at
    # [trial_chunk, n, C(k, j)] floats (compact support — at most
    # C(k, floor(k/2)) columns, e.g. 10 for u5 / 35 for u7, NOT 2^k)
    # while still amortizing the per-dispatch round trip over a chunk
    # (vmapping ALL trials would OOM large graphs at high n_trials)
    trial_chunk: int = 8
    max_degree: int = 64     # padded-CSR width
    seed: int = 0
    # The exact tail for adjacency past max_degree, two formulations
    # (bitwise-equal keeps per tile/segment ordering aside; tested):
    # "segment" — sorted segment-sum over the overflow edge list (the
    # shipped default; v5e scatters small rows at ~25 GB/s, the sorted
    # lowering is the cheap mitigant);
    # "onehot"  — the mfsgd/lda pattern: overflow entries grouped into
    # (entry_tile × row_tile) tiles, each applied as ONE one-hot MXU
    # matmul into a dynamic-sliced block (trades ~2·TE·R·S flops per
    # tile for no scatter at all).  Which wins on TPU is the profile
    # question queued since round 2 (BASELINE.md "Pallas headroom") —
    # both are resident so the answer is one --overflow-algo flag away.
    overflow_algo: str = "segment"
    overflow_row_tile: int = 512    # onehot: rows per tile block
    overflow_entry_tile: int = 2048  # onehot: max entries per tile

    def __post_init__(self):
        if self.overflow_algo not in ("segment", "onehot"):
            raise ValueError(f"overflow_algo must be 'segment' or "
                             f"'onehot', got {self.overflow_algo!r}")


def pad_csr(edges, n_vertices, max_degree):
    """Edge list → padded neighbor table [n, max_degree] + mask + overflow.

    Adjacency entries past ``max_degree`` are returned as an
    ``overflow [m, 2]`` array of (vertex, neighbor) rows — handled
    EXACTLY by the DP's segment-sum side path, never dropped (Harp's
    irregular memory reuse becomes a static-shape pad + exact tail).
    """
    e = np.asarray(edges, dtype=np.int64).reshape(-1, 2)
    src = np.concatenate([e[:, 0], e[:, 1]])
    dst = np.concatenate([e[:, 1], e[:, 0]])
    order = np.argsort(src, kind="stable")
    src, dst = src[order], dst[order]
    # position of each entry within its source-vertex run
    starts = np.searchsorted(src, np.arange(n_vertices))
    pos = np.arange(len(src)) - starts[src]
    keep = pos < max_degree
    nbr = np.zeros((n_vertices, max_degree), np.int32)
    msk = np.zeros((n_vertices, max_degree), np.float32)
    nbr[src[keep], pos[keep]] = dst[keep]
    msk[src[keep], pos[keep]] = 1.0
    overflow = np.stack([src[~keep], dst[~keep]], 1).astype(np.int64)
    return nbr, msk, overflow


def _partition_overflow(overflow, n_pad, nw):
    """Overflow edges → per-worker padded arrays, sharded like the rows.

    Worker w owns padded vertex rows [w·loc, (w+1)·loc); its overflow
    entries land in its block, padded to the max per-worker count (≥ 1 so
    shapes stay static even with no overflow).  Returns flattened
    ``(o_nbr [nw·m], o_row [nw·m] worker-LOCAL rows, o_msk [nw·m])``.
    """
    loc = n_pad // nw
    rows, nbrs = overflow[:, 0], overflow[:, 1]
    owner = rows // loc
    counts = np.bincount(owner, minlength=nw) if len(rows) else np.zeros(nw, int)
    m_pad = max(1, int(counts.max()))
    o_nbr = np.zeros((nw, m_pad), np.int32)
    o_row = np.zeros((nw, m_pad), np.int32)
    o_msk = np.zeros((nw, m_pad), np.float32)
    for w in range(nw):
        idx = np.flatnonzero(owner == w)
        t = len(idx)
        # padding FIRST (id 0), then rows ascending: the device side
        # relies on this to use the sorted segment-sum lowering
        order = np.argsort(rows[idx], kind="stable")
        o_row[w, m_pad - t:] = rows[idx][order] - w * loc
        o_nbr[w, m_pad - t:] = nbrs[idx][order]
        o_msk[w, m_pad - t:] = 1.0
    return o_nbr.reshape(-1), o_row.reshape(-1), o_msk.reshape(-1)


def _partition_overflow_tiles(overflow, n_pad, nw, row_tile, entry_tile):
    """Overflow edges → per-worker (entry × row-window) tiles for the
    one-hot MXU tail: each tile holds ≤ ``entry_tile`` entries whose
    LOCAL rows all lie in one ``[lo, lo + row_tile)`` window (entries
    arrive row-ascending, so tiles are contiguous windows).  Returns
    ``(t_nbr [nw·NT, TE], t_loc [nw·NT, TE]`` — row offsets within the
    window, ``row_tile`` for padding (one-hot maps it to a zero row),
    ``t_msk [nw·NT, TE], t_lo [nw·NT])`` with NT the max per-worker tile
    count (≥ 1) and TE ≤ entry_tile sublane-rounded to the max fill.
    """
    loc = n_pad // nw
    rows, nbrs = overflow[:, 0], overflow[:, 1]
    owner = rows // loc if len(rows) else np.zeros(0, np.int64)
    per_w = []
    for w in range(nw):
        idx = np.flatnonzero(owner == w)
        order = np.argsort(rows[idx], kind="stable")
        r = (rows[idx][order] - w * loc).astype(np.int64)
        nb = nbrs[idx][order].astype(np.int32)
        tiles = []
        i = 0
        while i < len(r):
            lo = int(r[i])
            j = i
            while j < len(r) and j - i < entry_tile and r[j] < lo + row_tile:
                j += 1
            tiles.append((lo, (r[i:j] - lo).astype(np.int32), nb[i:j]))
            i = j
        per_w.append(tiles)
    NT = max(1, max((len(t) for t in per_w), default=1))
    max_e = max((len(locs) for tiles in per_w for _, locs, _ in tiles),
                default=0)
    TE = min(entry_tile, max(8, -(-max_e // 8) * 8))
    t_nbr = np.zeros((nw, NT, TE), np.int32)
    t_loc = np.full((nw, NT, TE), row_tile, np.int32)
    t_msk = np.zeros((nw, NT, TE), np.float32)
    t_lo = np.zeros((nw, NT), np.int32)
    for w, tiles in enumerate(per_w):
        for t, (lo, locs, nb) in enumerate(tiles):
            e = len(locs)
            t_lo[w, t] = lo
            t_nbr[w, t, :e] = nb
            t_loc[w, t, :e] = locs
            t_msk[w, t, :e] = 1.0
    return (t_nbr.reshape(nw * NT, TE), t_loc.reshape(nw * NT, TE),
            t_msk.reshape(nw * NT, TE), t_lo.reshape(nw * NT))


def _dp_subset_tables(tpl, n_colors):
    """Static DP plan: for each template vertex i (post-order), the list of
    (S, S1, S2) bitmask triples combining the partial at i with a child
    subtree, restricted to |S| == accumulated size.  Returns per-combine
    dense index arrays for a one-hot 'subset convolution' on device."""
    s = n_colors
    masks = list(range(1 << s))
    popcnt = [bin(m).count("1") for m in masks]

    def combos(sz1, sz2):
        out = []
        for S1 in masks:
            if popcnt[S1] != sz1:
                continue
            for S2 in masks:
                if popcnt[S2] != sz2 or (S1 & S2):
                    continue
                out.append((S1 | S2, S1, S2))
        return out

    return combos


def count_template(edges, n_vertices, cfg: SubgraphConfig,
                   mesh: WorkerMesh | None = None):
    """Estimate the number of (unrooted) embeddings of the template.

    Returns ``(estimate, per_trial_estimates, overflow_edges)`` —
    ``overflow_edges`` counts adjacency entries past ``cfg.max_degree``,
    which are handled EXACTLY by the segment-sum side path (nothing is
    dropped; the count is a perf diagnostic — a large value suggests
    raising ``max_degree``).  The estimate is the colorful rooted count
    divided by the colorfulness probability and by |Aut(template)| (the
    rooted DP counts each unrooted embedding once per automorphism).
    """
    tpl = TEMPLATES[cfg.template] if isinstance(cfg.template, str) else cfg.template
    s = template_size(tpl)
    k = cfg.n_colors or s
    if k < s:
        raise ValueError(
            f"n_colors={k} must be >= template size {s} for color-coding")
    mesh = mesh or current_mesh()
    nw = mesh.num_workers
    n_pad = -(-n_vertices // nw) * nw

    nbr, msk, overflow = pad_csr(edges, n_vertices, cfg.max_degree)
    if n_pad > n_vertices:
        nbr = np.concatenate([nbr, np.zeros((n_pad - n_vertices, cfg.max_degree), np.int32)])
        msk = np.concatenate([msk, np.zeros((n_pad - n_vertices, cfg.max_degree), np.float32)])

    from harp_tpu.utils import skew, telemetry

    if telemetry.enabled():
        # ingest skew record (utils/skew.py): real adjacency entries per
        # vertex-partition worker vs its padded slots — powerlaw graphs
        # are exactly where "one worker holds the hub" shows up
        loc = n_pad // nw
        skew.record_partition(
            "subgraph.partition",
            msk.reshape(nw, loc * cfg.max_degree).sum(1),
            unit="edges", padded_total=msk.size)

    nbr_d = mesh.shard_array(nbr, 0)
    msk_d = mesh.shard_array(msk, 0)
    if cfg.overflow_algo == "onehot":
        ovf = _partition_overflow_tiles(overflow, n_pad, nw,
                                        cfg.overflow_row_tile,
                                        cfg.overflow_entry_tile)
    else:
        ovf = _partition_overflow(overflow, n_pad, nw)
    ovf_d = tuple(mesh.shard_array(a, 0) for a in ovf)
    fn = make_colorful_count_fn(tpl, k, mesh, cfg.overflow_algo,
                                cfg.overflow_row_tile)

    rng = np.random.default_rng(cfg.seed)
    p_colorful = math.factorial(s) / (s ** s) if k == s else (
        math.factorial(k) / (math.factorial(k - s) * k ** s))
    n_auto = _count_automorphism_roots(tpl)
    chunk = max(1, min(cfg.n_trials, cfg.trial_chunk))
    t_pad = -(-cfg.n_trials // chunk) * chunk  # equal chunks: one compile
    colors = rng.integers(0, k, (t_pad, n_pad)).astype(np.int32)
    outs = [fn(nbr_d, msk_d, *ovf_d,
               mesh.shard_array(colors[lo:lo + chunk], 1))
            for lo in range(0, t_pad, chunk)]  # async; ONE readback below
    rooted = np.asarray(jnp.concatenate(outs))[: cfg.n_trials]
    estimates = [float(r) / p_colorful / n_auto for r in rooted]
    return float(np.mean(estimates)), estimates, len(overflow)


def _count_automorphism_roots(tpl):
    """Number of automorphisms of the template tree (each unrooted colorful
    embedding is counted once per automorphism by the rooted DP)."""
    ch = _children(tpl)

    def canon(i):
        return "(" + "".join(sorted(canon(c) for c in ch[i])) + ")"

    def autos(i):
        subs = [canon(c) for c in ch[i]]
        a = 1
        for c in ch[i]:
            a *= autos(c)
        from collections import Counter

        for cnt in Counter(subs).values():
            a *= math.factorial(cnt)
        return a

    # rooted automorphisms of the tree as rooted at 0, times the number of
    # vertices whose rooted canonical form equals the root's (root orbit)
    root_form = canon(0)
    # re-root at each vertex to find the root orbit size
    orbit = 0
    n = len(tpl)
    adj = [[] for _ in range(n)]
    for i, p in enumerate(tpl):
        if p >= 0:
            adj[i].append(p)
            adj[p].append(i)

    def canon_rerooted(v, parent):
        return "(" + "".join(
            sorted(canon_rerooted(u, v) for u in adj[v] if u != parent)
        ) + ")"

    for v in range(n):
        if canon_rerooted(v, -1) == root_form:
            orbit += 1
    return autos(0) * orbit


def benchmark(n_vertices=100_000, avg_degree=16, template="u5-tree",
              mesh=None, seed=0, max_degree=64, graph="uniform",
              overflow_algo="segment"):
    """Vertices/sec through one color-coding trial (graded config #5a).

    ``graph="powerlaw"`` draws edge sources zipf-1.3 (hub-heavy, the
    realistic web/social degree distribution) so the exact overflow
    segment-sum path carries real mass — the graded-scale regime where
    a truncating implementation would be silently biased; the reported
    ``overflow_share`` is the fraction of adjacency entries riding it.
    """
    rng = np.random.default_rng(seed)
    n_edges = n_vertices * avg_degree // 2
    if graph == "powerlaw":
        src = (rng.zipf(1.3, n_edges).astype(np.int64) - 1) % n_vertices
        dst = rng.integers(0, n_vertices, n_edges)
        edges = np.stack([src, dst], 1)
    elif graph == "uniform":
        edges = np.stack([
            rng.integers(0, n_vertices, n_edges),
            rng.integers(0, n_vertices, n_edges),
        ], 1)
    else:
        raise ValueError(f"graph must be 'uniform' or 'powerlaw', got {graph!r}")
    cfg = SubgraphConfig(template=template, seed=seed, max_degree=max_degree,
                         overflow_algo=overflow_algo)
    count_template(edges, n_vertices, cfg, mesh)  # warmup: compile + CSR
    t0 = time.perf_counter()
    est, trials, overflow = count_template(edges, n_vertices, cfg, mesh)
    dt = time.perf_counter() - t0
    return {
        "vertices_per_sec": n_vertices / dt,
        "estimate": est,
        "sec_per_trial": dt,
        "overflow_edges": overflow,  # handled exactly; 0 edges dropped
        "overflow_share": overflow / (2 * n_edges),
        "dropped_edges": 0,
        "template": template,
        "n_vertices": n_vertices,
        "graph": graph,
        "overflow_algo": overflow_algo,
    }


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser(description="harp-tpu subgraph counting (edu.iu.subgraph parity)")
    p.add_argument("--vertices", type=int, default=100_000)
    p.add_argument("--avg-degree", type=int, default=16)
    p.add_argument("--template", default="u5-tree", choices=sorted(TEMPLATES))
    p.add_argument("--max-degree", type=int, default=64)
    p.add_argument("--graph", choices=["uniform", "powerlaw"],
                   default="uniform")
    p.add_argument("--overflow-algo", choices=["segment", "onehot"],
                   default="segment",
                   help="exact tail for adjacency past max-degree: "
                        "sorted segment-sum (default) or tiled one-hot "
                        "MXU matmuls — same counts, different hardware "
                        "path (profile on TPU to pick)")
    args = p.parse_args(argv)
    # JSON, not dict-repr: the relay sprint tees this into BENCH_local.jsonl
    import json

    print(json.dumps({"config": "subgraph_cli",
                      **benchmark(args.vertices, args.avg_degree,
                                  args.template, max_degree=args.max_degree,
                                  graph=args.graph,
                                  overflow_algo=args.overflow_algo)}))


if __name__ == "__main__":
    main()
