"""LDA via Collapsed Gibbs Sampling — graded config #3: rotate + push/pull.

Reference parity (SURVEY.md §3.4, §4.4): Harp's ``edu.iu.lda`` samples
topics for a sharded token corpus with the word-topic count table partitioned
across workers; workers either ``pull`` needed rows / ``push`` deltas, or
(rotation variant) rotate word-topic blocks around the ring while a dynamic
scheduler samples the tokens whose words are resident.  Parallel CGS is
*approximate* by construction — workers sample concurrently against slightly
stale counts (Harp's threads do too); convergence is judged by likelihood,
not bitwise equivalence.

TPU-native design:
- tokens pre-partitioned into the (doc-range × word-slice) grid of
  :func:`harp_tpu.models.mfsgd.partition_ratings`-style blocks (2 half-
  slices per worker, pipelined rotation exactly like MF-SGD);
- a rotation step samples all resident tokens in batches: gather doc-topic
  and word-topic count rows, form the CGS posterior
  ``(N_dk+α)(N_wk+β)/(N_k+Vβ)``, sample via Gumbel-argmax (on-device
  ``jax.random``), apply count deltas.  Two delta-application algorithms
  (``LDAConfig.algo``): "dense" one-hot MXU matmuls into dynamic-sliced
  tile blocks (default; 6.3M vs 3.3M tokens/s/chip on the graded config —
  XLA scatter of K-wide rows was 2.2 s of the 2.87 s epoch) and the
  "scatter" reference;
- the global topic-totals vector ``N_k`` is synchronized with an
  ``allreduce`` of deltas every rotation step — the push/pull residue
  (dense K-vector, so psum ≡ push+pull at once);
- chromatic note: within a chunk all tokens sample against the same count
  snapshot (blocked Gibbs); chunk boundaries refresh counts, mirroring the
  granularity Harp gets from its timer-bounded scheduler.
"""

from __future__ import annotations

import dataclasses
import functools
import glob
import os
import re
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from harp_tpu.ops.pallas_compat import interpret_default
from harp_tpu.parallel import collective as C
from harp_tpu.parallel.mesh import WorkerMesh, current_mesh
from harp_tpu.parallel.rotate import (ROTATE_WIRES, resident_chunk_index,
                                      rotate_pipeline)
from harp_tpu.models.mfsgd import (
    _ceil_div,
    _dense_bounds,
    algo_kwargs,
    carry_tile_switch,
    partition_ratings,
    partition_ratings_tiles,
    rotate_chunks_resolved,
)
from harp_tpu.utils import flightrec, prng, skew


@dataclasses.dataclass
class LDAConfig:
    n_topics: int = 100
    alpha: float = 0.1  # doc-topic Dirichlet prior
    beta: float = 0.01  # word-topic Dirichlet prior
    # Count-update algorithm.  "dense" (default) groups tokens into
    # (d_tile × w_tile) sub-tiles and applies count deltas as one-hot MXU
    # matmuls into dynamic-sliced table blocks — no XLA scatter.  Profiled
    # on the graded config (1k topics, 10M tokens, 1× v5e, 2026-07-30):
    # the two scatters were 2.2 s of the 2.87 s epoch (~25 GB/s scatter
    # floor), while the take-gathers cost only 0.23 s and stay as takes.
    # "scatter" keeps the direct formulation as the readable reference.
    # "pushpull" is Harp's OTHER edu.iu.lda variant (SURVEY.md §4.4):
    # the word-topic table stays row-sharded (never rotated, never
    # materialized); each chunk pulls the word rows its tokens touch
    # (table.pull_rows_sparse), samples, and pushes the deltas back
    # (push_rows_sparse).  The exchange travels in [nw, pull_cap, K]
    # capacity buffers, so wire is O(nw·pull_cap) per chunk — independent
    # of TABLE size (the point: the right variant when the word-topic
    # table outgrows one chip's HBM), but nw× the touched rows at the
    # zero-drop default cap; size pull_cap ≈ chunk/nw when drops are
    # acceptable.
    # Delta matmuls are EXACT in bf16 (operands are 0/±1; f32 accumulate),
    # so counts remain integers on all paths.
    # FLIPPED to "pallas" 2026-08-01 (1× v5e, FLIP_DECISIONS.jsonl):
    # fused kernel + exprace + rbg + carry_db measured 10.50M
    # tok/s/chip vs 6.46M dense gumbel = 1.63× at equal likelihood
    # (−12.0815 vs −12.0824, tol 0.05) at the 100k-doc × 1k-topic
    # sweep shape; the plain kernel alone is 7.92M = 1.23×.
    algo: str = "pallas"
    d_tile: int = 512   # dense: doc-topic tile rows
    w_tile: int = 512   # dense: word-topic tile rows
    entry_cap: int = 2048  # dense/pallas: max tokens per tile entry —
    # 2048 measured best on the kernel+carry stack (2026-08-01, 1× v5e:
    # 10.5M tok/s vs 10.17M @1024 / 10.30M @4096)
    chunk: int = 8192   # scatter/pushpull: tokens sampled per count-snapshot
    # pushpull: row-request slots per (worker, owner) pair and chunk.  The
    # default (= chunk) guarantees zero drops (a chunk can never request
    # more rows than it has tokens); lower caps shrink the all_to_all
    # buffers ([nw·cap, K] each way) at the cost of counted drops —
    # dropped tokens simply keep their topic that sweep (still a valid
    # Gibbs chain: skipping a site preserves the stationary distribution).
    # SIZING (VERDICT r2 item 5): with dedup_pulls the exact zero-drop cap
    # is the max count of DISTINCT word rows per (chunk, owner) —
    # :func:`suggest_pull_cap` computes it from the loaded corpus (Zipf
    # corpora: far below chunk, because every repeat of a hot word shares
    # one slot); without dedup it is the max TOKEN count per (chunk,
    # owner), which a frequency-sorted Zipf vocabulary pushes toward the
    # whole chunk on the hot owner.
    pull_cap: int | None = None
    # pushpull: collapse duplicate word rows within a chunk to ONE wire
    # request/push slot (duplicates of "the" share a slot; deltas are
    # pre-summed host→owner).  Bit-identical to the non-dedup exchange at
    # zero drops (pulled values equal; delta sums are exact ±1 integers in
    # f32) and strictly fewer drops under any cap, so the default is on.
    # Measured (8-worker CPU sim, Zipf-1.1 ids over m=4096 requests,
    # 2026-07-30, benchmark.sweep_sparse_capacity): the raw stream still
    # drops 41% at cap = m/4 and needs cap = m for zero drops; the
    # deduped stream reaches ZERO drops at cap = m/4 — 4× smaller
    # exchange buffers at equal fidelity.
    dedup_pulls: bool = True
    # Tiled algos (dense/pallas): carry the doc-topic tile across its
    # od-run instead of slice+DUS per entry.  Entries are od-major
    # (partition_ratings_tiles sorts tiles u-major), so one od's ~25
    # entries at enwiki shapes (512 docs x 100 tok / 2048-token entries)
    # currently pay 25x the [K, d_tile] in+out HBM traffic; the carry
    # pays it once per run (a lax.cond flushes/loads ONLY on od change —
    # correct under any entry order: the switch always flushes before a
    # region can be re-sliced).  Default OFF until TPU-measured: the
    # cond+DUS-on-carry interaction is exactly the CLAUDE.md
    # whole-table-copy trap's neighborhood (a round-3 regrouping
    # prototype was reverted there), so the sweep configs lda_carry /
    # lda_pallas_carry measure it and the flip gate decides (VERDICT r3
    # item 2's queued decision, now one flag).  FLIPPED ON 2026-08-01
    # for the pallas stack: lda_pallas_carry measured 10.50M tok/s =
    # 1.33× over the plain kernel (1.63× over dense) on 1× v5e — the
    # trace shows the carry removing the dominant [K, d_tile] DUS
    # write-back; chain bit-identical (silicon kernel_equiv_check) and
    # no whole-table copies in the HLO.  The DENSE-stack arm
    # (`lda_carry`, 1.13×) was VETOED by the conditional gate, so the
    # auto default stays off there.
    # None = "auto per algo", STORED as None and resolved at READ time by
    # :func:`carry_db_resolved` (mirrors MFSGDConfig.tiles() /
    # KMeansConfig._use_pallas — a __post_init__ resolution froze the
    # auto value, so ``dataclasses.replace(LDAConfig(), algo='scatter')``
    # raised and ``replace(..., algo='dense')`` silently enabled the
    # VETOED dense-carry arm; ADVICE r5).  An explicit True on a
    # non-tiled algo still raises.
    carry_db: bool | None = None
    # algo="pallas" only: exact base-256-plane count gathers (ADVICE r3 —
    # single-dot bf16 gathers round counts > 256, perturbing the posterior
    # ~0.4% at enwiki hot-word counts).  Default ON: correctness first.
    # False = single-dot gathers (+0/-2 MXU dots per tile); the
    # lda_pallas_approx sweep config measures whether approx buys ≥10% at
    # equal chain likelihood (flip_decision gate) before this may flip.
    pallas_exact_gathers: bool = True
    # Doc-topic table dtype.  "int16" halves the Ndk HBM footprint — the
    # graded enwiki-1M × 1k-topics config needs 4 GB in f32 vs 2 GB in
    # int16 (VERDICT r1 item 5) — and is EXACT: a doc-topic count is
    # bounded by the doc's token count (≪ 32767), and every delta is ±1.
    # Sampling is bit-identical to f32 (tests pin this).  Nwk stays f32:
    # corpus-frequent words exceed the int16 range.
    ndk_dtype: str = "float32"
    # Topic draw.  "gumbel" (default): log-posterior + Gumbel noise,
    # argmax — 5 transcendentals per [token, K] element (3 logs + the 2
    # inside the Gumbel transform).  "exprace": competing exponentials —
    # argmin E_k·(nk+Vβ) / ((ndk+α)(nwk+β)) with E_k ~ Exp(1) — draws
    # from the IDENTICAL distribution (the winner of an exponential race
    # at rates p_k is k with probability p_k/Σp) with 1 log + 2 mul +
    # 1 div per element, ~5× fewer transcendentals on the VPU.  Same
    # chain statistics, different random stream.  FLIPPED 2026-08-01
    # with the pallas algo (its required stack; the lda_fast A/B alone
    # measured exprace+rbg 1.24× over gumbel+threefry at equal LL,
    # while exprace+threefry was 0.98× — the noise TENSOR, not the
    # transcendentals, was the wall).
    sampler: str = "exprace"
    # Random-bit source for the per-[token, K] draws.  "threefry"
    # (default): JAX's counter-based PRNG — splittable, reproducible
    # across backends, but ~15 VPU ops per element; at 1k topics the
    # noise tensor is K× the token count, so bit generation is a real
    # share of the epoch.  "rbg": XLA's RngBitGenerator — the TPU
    # hardware generator, near-free, still deterministic per key but a
    # different (backend-dependent) stream.  Chain statistics unaffected
    # (any iid uniform source is a valid Gibbs draw).  FLIPPED
    # 2026-08-01 with the pallas algo (see sampler above — rbg is where
    # the lda_fast 1.24× comes from).
    rng_impl: str = "rbg"
    # Rotation pipeline knobs (rotation algos only — pushpull never
    # rotates).  Same contract as MFSGDConfig: rotate_chunks None = auto
    # 2 (the historical two-halves schedule, resolved read-time by
    # mfsgd.rotate_chunks_resolved); rotate_wire "exact" | "bf16" |
    # "int8" picks the in-flight chunk's ring payload.  The int8 wire
    # dequantizes counts lossily, so the chain samples against slightly
    # perturbed word-topic counts — a valid approximate-CGS trade (the
    # whole parallel sampler is approximate), gated by the
    # `lda_rotate_int8` log-likelihood flip candidate before it may
    # become a default.
    rotate_chunks: int | None = None
    rotate_wire: str = "exact"

    def __post_init__(self):
        if self.ndk_dtype not in ("float32", "int16"):
            raise ValueError(
                f"ndk_dtype must be 'float32' or 'int16', got {self.ndk_dtype!r}")
        if self.algo not in ("dense", "scatter", "pushpull", "pallas"):
            raise ValueError(
                f"algo must be 'dense', 'scatter', 'pushpull' or "
                f"'pallas', got {self.algo!r}")
        if self.algo == "pallas" and (self.sampler != "exprace"
                                      or self.rng_impl != "rbg"):
            # the fused kernel IS the exprace + hardware-bits stack (see
            # ops/lda_kernel.py) — require the matching knobs so a config
            # never claims a sampler the kernel doesn't run
            raise ValueError(
                "algo='pallas' fuses the exprace draw over hardware "
                "random bits; pass sampler='exprace', rng_impl='rbg'")
        if self.sampler not in ("gumbel", "exprace"):
            raise ValueError(
                f"sampler must be 'gumbel' or 'exprace', got {self.sampler!r}")
        if self.rng_impl not in ("threefry", "rbg"):
            raise ValueError(
                f"rng_impl must be 'threefry' or 'rbg', got {self.rng_impl!r}")
        if self.pull_cap is not None and self.algo != "pushpull":
            raise ValueError("pull_cap only applies to algo='pushpull'")
        # carry_db=None stays None here — :func:`carry_db_resolved` reads
        # it as "on for the pallas stack only" (exactly the 2026-08-01
        # verdict: `lda_pallas_carry` FLIPPED, the dense arm `lda_carry`
        # was VETOED); only an EXPLICIT True is validated
        if self.carry_db and self.algo not in _TILED_ALGOS:
            raise ValueError("carry_db applies to the tiled algos "
                             f"{_TILED_ALGOS}, not algo={self.algo!r}")
        if self.rotate_chunks is not None and self.rotate_chunks < 1:
            raise ValueError(
                f"rotate_chunks must be >= 1, got {self.rotate_chunks}")
        if self.rotate_wire not in ROTATE_WIRES:
            raise ValueError(
                f"rotate_wire must be one of {ROTATE_WIRES}, "
                f"got {self.rotate_wire!r}")
        if self.algo == "pushpull" and (self.rotate_chunks is not None
                                        or self.rotate_wire != "exact"):
            raise ValueError(
                "rotate_chunks/rotate_wire apply to the rotation algos; "
                "algo='pushpull' never rotates (a silently-ignored "
                "tuning flag wastes benchmark sweeps)")
        if self.pull_cap is not None and self.pull_cap < 1:
            raise ValueError(
                f"pull_cap must be >= 1, got {self.pull_cap} (0 would "
                "silently fall back to the full-chunk default)")


def carry_db_resolved(cfg: LDAConfig) -> bool:
    """Resolved doc-tile carry — ``None`` means "on for the pallas stack
    only" (the 2026-08-01 verdict: `lda_pallas_carry` FLIPPED at 1.33×,
    the dense arm `lda_carry` was VETOED by the conditional gate, so only
    the kernel stack may default the carry on).  Read-time resolution
    (mirroring :func:`harp_tpu.models.mfsgd.tiles`) keeps
    ``dataclasses.replace(cfg, algo=...)`` tracking the new algo instead
    of freezing the old algo's resolved value (ADVICE r5)."""
    return cfg.carry_db if cfg.carry_db is not None else cfg.algo == "pallas"


def _cgs_resample(ndk, nwk, nk, z, mask, key, cfg: LDAConfig, vocab_size):
    """The ONE CGS posterior + Gumbel-argmax draw, shared by all three
    algos — a change here (clamps, priors, denominator) applies to
    dense, scatter and pushpull identically."""
    a = jnp.maximum(ndk + cfg.alpha, 1e-10)
    b = jnp.maximum(nwk + cfg.beta, 1e-10)
    c = jnp.maximum(nk + vocab_size * cfg.beta, 1e-10)
    if cfg.rng_impl == "rbg":
        # rebuild the (split-derived, chunk-unique) threefry key as an RBG
        # key: bits then come from the TPU hardware generator instead of
        # ~15 VPU ops/element of counter hashing (see LDAConfig.rng_impl)
        kd = key if key.dtype == jnp.uint32 else jax.random.key_data(key)
        key = jax.random.wrap_key_data(jnp.concatenate([kd, kd]),
                                       impl="rbg")
    if cfg.sampler == "exprace":
        # competing exponentials: argmin_k E_k/p_k lands on k with
        # probability p_k/Σp — the same draw as Gumbel-argmax at ~1/5th
        # the transcendental count (see LDAConfig.sampler)
        e = jax.random.exponential(key, a.shape, a.dtype)
        z_new = jnp.argmin(e * c / (a * b), axis=-1).astype(jnp.int32)
    else:
        logp = jnp.log(a) + jnp.log(b) - jnp.log(c)
        gumbel = jax.random.gumbel(key, logp.shape, logp.dtype)
        z_new = jnp.argmax(logp + gumbel, axis=-1).astype(jnp.int32)
    return jnp.where(mask > 0, z_new, z)


def _sample_chunk(Ndk, Nwk, Nk, z, chunk, key, cfg: LDAConfig, vocab_size):
    """Blocked-Gibbs resample of one token chunk against a count snapshot."""
    d, w, m = chunk  # local doc ids, local word ids, valid mask  [c]
    K = cfg.n_topics

    # remove current assignments from the counts the posterior sees
    # (Ndk may be int16 — see LDAConfig.ndk_dtype; the posterior math is
    # f32 either way and the ±1 delta casts back exactly)
    oh_old = jax.nn.one_hot(z, K, dtype=jnp.float32) * m[:, None]
    ndk = jnp.take(Ndk, d, axis=0).astype(jnp.float32) - oh_old  # [c, K]
    nwk = jnp.take(Nwk, w, axis=0) - oh_old          # [c, K]
    nk = Nk[None, :] - oh_old                        # [c, K]

    z_new = _cgs_resample(ndk, nwk, nk, z, m, key, cfg, vocab_size)

    # apply count deltas (scatter; chunk-granular like Harp's schedulers)
    oh_new = jax.nn.one_hot(z_new, K, dtype=jnp.float32) * m[:, None]
    delta = oh_new - oh_old
    Ndk = Ndk.at[d].add(delta.astype(Ndk.dtype), mode="drop")
    Nwk = Nwk.at[w].add(delta, mode="drop")
    dNk = delta.sum(0)
    return Ndk, Nwk, dNk, z_new


def _sample_chunk_pushpull(Ndk, Nwk_shard, Nk, z, chunk, key,
                           cfg: LDAConfig, vocab_size):
    """Pull → sample → push for one token chunk (Harp's edu.iu.lda
    pull/push variant, SURVEY.md §4.4).

    ``Nwk_shard`` is this worker's row block of the GLOBAL word-topic
    table; the chunk's word rows arrive via ``pull_rows_sparse`` (wire =
    touched rows, the table itself never moves) and the deltas return via
    ``push_rows_sparse``.  A capacity-dropped token keeps its topic this
    sweep — skipping a Gibbs site preserves the stationary distribution —
    and pull-drop ⇒ its delta is zero, so the matching push slot (same
    ids, same bucket order) carries nothing.

    With ``cfg.dedup_pulls`` duplicate word rows in the chunk collapse to
    one request/push slot via :func:`harp_tpu.table.pull_rows_sparse_dedup`
    / ``push_rows_sparse_dedup`` — the Zipf-skew mitigation: per-owner
    capacity need becomes DISTINCT rows touched, not tokens (deltas are
    ±1 integers, so the pre-summed push is bit-identical).  The returned
    drop count is TOKENS skipped this chunk (globally summed), identical
    in meaning across both paths.
    """
    from harp_tpu.table import (pull_rows_sparse, pull_rows_sparse_dedup,
                                push_rows_sparse, push_rows_sparse_dedup)

    d, w, m = chunk  # worker-local doc rows, GLOBAL word ids, valid mask
    K = cfg.n_topics
    cap = cfg.pull_cap if cfg.pull_cap is not None else d.shape[0]
    pull = pull_rows_sparse_dedup if cfg.dedup_pulls else pull_rows_sparse
    push = push_rows_sparse_dedup if cfg.dedup_pulls else push_rows_sparse

    # padding tokens (m == 0) issue no request and take no capacity slot
    rows, ok, _ = pull(Nwk_shard, w, capacity=cap, valid=m > 0)
    # tokens skipped this sweep (drop semantics identical across paths)
    tok_drop = C.allreduce(jnp.sum((m > 0) & ~ok).astype(jnp.int32))

    mm = m * ok.astype(m.dtype)
    oh_old = jax.nn.one_hot(z, K, dtype=jnp.float32) * mm[:, None]
    ndk = jnp.take(Ndk, d, axis=0).astype(jnp.float32) - oh_old
    nwk = rows - oh_old
    nk = Nk[None, :] - oh_old

    z_new = _cgs_resample(ndk, nwk, nk, z, mm, key, cfg, vocab_size)

    oh_new = jax.nn.one_hot(z_new, K, dtype=jnp.float32) * mm[:, None]
    delta = oh_new - oh_old
    Ndk = Ndk.at[d].add(delta.astype(Ndk.dtype), mode="drop")
    # push with the SAME valid mask as the pull (m, not m·ok): the two
    # dedup plans are then identical expressions XLA can CSE into one
    # sort, and the difference is immaterial — a pull-dropped token's
    # delta is zero, so its slot (dropped again, same plan) carries
    # nothing either way
    Nwk_shard, _ = push(Nwk_shard, w, delta, capacity=cap, valid=m > 0)
    dNk = delta.sum(0)
    return Ndk, Nwk_shard, dNk, z_new, tok_drop


def _sample_entry_tiles(Db, Wb, Nk_eff, z, cd, cw, key, cfg: LDAConfig,
                        vocab_size):
    """Tile-level core of :func:`_sample_entry`: resample one entry's
    tokens against pre-sliced ``Db [d_tile, K]`` / ``Wb [w_tile, K]``
    blocks and return the updated blocks — no table slicing here, so the
    ``carry_db`` epoch path can keep a doc block resident across its
    od-run (slicing strategy is the CALLER's concern; the math is shared
    so carry and non-carry chains are bit-identical)."""
    K = cfg.n_topics
    DR, WR = cfg.d_tile, cfg.w_tile
    m = (cd < DR).astype(jnp.float32)
    oh_old = jax.nn.one_hot(z, K, dtype=jnp.float32) * m[:, None]
    ndk = jnp.take(Db, jnp.minimum(cd, DR - 1), axis=0).astype(
        jnp.float32) - oh_old
    nwk = jnp.take(Wb, jnp.minimum(cw, WR - 1), axis=0) - oh_old
    nk = Nk_eff[None, :] - oh_old

    z_new = _cgs_resample(ndk, nwk, nk, z, m, key, cfg, vocab_size)

    oh_new = jax.nn.one_hot(z_new, K, dtype=jnp.float32) * m[:, None]
    delta = (oh_new - oh_old).astype(jnp.bfloat16)  # entries ∈ {-1,0,1}: exact
    ohd = jax.nn.one_hot(cd, DR, dtype=jnp.bfloat16)  # pad rows all-zero
    ohw = jax.nn.one_hot(cw, WR, dtype=jnp.bfloat16)
    dot = lambda a, b: lax.dot_general(  # noqa: E731 — contract dim 0 with 0
        a, b, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    Db = (Db.astype(jnp.float32) + dot(ohd, delta)).astype(Db.dtype)
    Wb = Wb + dot(ohw, delta)
    dNk = delta.astype(jnp.float32).sum(0)
    return Db, Wb, dNk, z_new


def _sample_entry(Ndk, Nwk, Nk, z, entry, key, cfg: LDAConfig, vocab_size):
    """Dense-tile resample of one (d_tile × w_tile) token entry.

    Gathers stay ``jnp.take`` (profiled cheap); the count-delta scatters
    become one-hot matmuls accumulated into dynamic-sliced table blocks
    and written back with ``dynamic_update_slice`` — no XLA scatter.  The
    matmuls are exact (0/±1 operands in bf16, f32 accumulation), so the
    count tables stay integer-valued like the scatter path's.
    """
    cd, cw, od, ow = entry  # tile-local ids + tile offsets
    DR, WR = cfg.d_tile, cfg.w_tile

    # Slice the tile blocks FIRST and gather from them (ids are tile-local):
    # gathering straight from the scan-carried tables while also
    # dynamic-update-slicing them makes XLA insert a full-table copy per
    # entry (profiled: 20 s of a 29 s epoch).  Blocks in, blocks out keeps
    # the tables update-in-place.
    Db = lax.dynamic_slice_in_dim(Ndk, od, DR, 0)
    Wb = lax.dynamic_slice_in_dim(Nwk, ow, WR, 0)
    Db, Wb, dNk, z_new = _sample_entry_tiles(Db, Wb, Nk, z, cd, cw, key,
                                             cfg, vocab_size)
    Ndk = lax.dynamic_update_slice_in_dim(Ndk, Db, od, 0)
    Nwk = lax.dynamic_update_slice_in_dim(Nwk, Wb, ow, 0)
    return Ndk, Nwk, dNk, z_new


def _sample_tiles_pallas(DbT, WbT, nk, z, cd, cw, key2, cfg: LDAConfig,
                         vocab_size, count_bounds=(None, None)):
    """Tile-level core of :func:`_sample_entry_pallas` (topic-major
    blocks in/out) — the fused-kernel twin of
    :func:`_sample_entry_tiles`, shared by the carry and slice-per-entry
    epoch paths."""
    from harp_tpu.ops.lda_kernel import cgs_entry_update

    DbT, WbT, z_new, dNk = cgs_entry_update(
        DbT, WbT, nk, z, cd, cw, key2,
        alpha=cfg.alpha, beta=cfg.beta, vbeta=vocab_size * cfg.beta,
        interpret=interpret_default(),
        exact_gathers=cfg.pallas_exact_gathers,
        ndk_count_bound=count_bounds[0], nwk_count_bound=count_bounds[1])
    return DbT, WbT, dNk, z_new


def _sample_entry_pallas(NdkT, NwkT, nk, z, entry, key2, cfg: LDAConfig,
                         vocab_size, count_bounds=(None, None)):
    """Fused-kernel twin of :func:`_sample_entry` on TOPIC-MAJOR tables
    (ops/lda_kernel.py): tiles slice along lanes, the whole [C, K] chain
    stays in VMEM.  Chunk-granular snapshots (fresher than the XLA
    entry snapshot); exprace draw over hardware bits by construction."""
    cd, cw, od, ow = entry
    DR, WR = cfg.d_tile, cfg.w_tile
    DbT = lax.dynamic_slice_in_dim(NdkT, od, DR, 1)
    WbT = lax.dynamic_slice_in_dim(NwkT, ow, WR, 1)
    DbT, WbT, dNk, z_new = _sample_tiles_pallas(DbT, WbT, nk, z, cd, cw,
                                                key2, cfg, vocab_size,
                                                count_bounds)
    NdkT = lax.dynamic_update_slice_in_dim(NdkT, DbT, od, 1)
    NwkT = lax.dynamic_update_slice_in_dim(NwkT, WbT, ow, 1)
    return NdkT, NwkT, dNk, z_new


#: algos that consume the dense (d_tile × w_tile) entry layout
_TILED_ALGOS = ("dense", "pallas")

#: pallas prep: entry width must be a multiple of the kernel chunk
_PALLAS_C = 256

#: benchmark pack-cache format version — bump when pack_tokens/partitioner
#: layout changes so stale cached packs can never be installed
_PACK_VERSION = 1


def _epoch_device_fn(mesh: WorkerMesh, cfg: LDAConfig, vocab_size: int,
                     count_bounds=(None, None)):
    """Device-view epoch body: every token resampled once.

    Chunked rotation pipeline identical to MF-SGD's (see
    harp_tpu.models.mfsgd._epoch_device_fn): the word-slice splits into
    ``rotate_chunks_resolved(cfg)`` sub-slices — compute on the resident
    chunk while the previously-sampled one is in flight
    (:func:`rotate_pipeline`; the 2-chunk default is the former bespoke
    half-slice schedule, and ``cfg.rotate_wire`` narrows the ring
    payload).  The per-step token pass dispatches on ``cfg.algo``: scan
    over dense tile entries, or over fixed-size scatter chunks (see
    :func:`_sample_entry` / :func:`_sample_chunk`).
    """
    nc = rotate_chunks_resolved(cfg)
    tiled = cfg.algo in _TILED_ALGOS
    pallas = cfg.algo == "pallas"
    carry_db = carry_db_resolved(cfg)

    def epoch(Ndk, Nwk_slice, Nk, z_grid, *token_args):
        key = token_args[-1][0]
        tokens = token_args[:-1]
        # per-worker tokens touched this sweep — the skew spine's
        # execution counter (utils/skew.py), folded into the epoch
        # outputs so the driver's ONE readback carries it (flight
        # budgets stay 1 dispatch / 1 readback, tests/test_flightrec.py).
        # Unconditional: a telemetry-gated output would make the traced
        # program differ with the flag (zero-cost contract).
        valid = ((tokens[0] < cfg.d_tile) if tiled
                 else (tokens[2] > 0)).sum()
        work_w = C.allgather(valid.astype(jnp.float32)[None])
        if pallas:
            # the fused kernel is topic-major: transpose once per epoch
            # (~10 GB/epoch of HBM at enwiki scale — noise vs the epoch);
            # the pipeline then chunks (and rotates) along axis 1
            Ndk, Nwk_slice = Ndk.T, Nwk_slice.T

        def step(st, computing, t):
            Ndk, Nk, z_grid, key = st
            chunk_idx = resident_chunk_index(t, nc)
            blk = jax.tree.map(lambda a: a[chunk_idx], tokens)
            z_blk = z_grid[chunk_idx]
            key, sub = jax.random.split(key)

            if tiled:
                ed, ew, od, ow = blk  # [NE, C], [NE]
                entry_keys = jax.random.split(sub, ed.shape[0])
                if pallas:
                    entry_keys = lax.bitcast_convert_type(
                        entry_keys, jnp.int32)

                if carry_db:
                    # Carry the doc tile across its od-run (entries are
                    # od-major): flush/load rides a lax.cond so an
                    # unchanged od pays ZERO doc-tile HBM traffic.  The
                    # switch always flushes the old region before any
                    # region can be re-sliced, so this is exact under any
                    # entry order — pad entries jumping back to od 0
                    # included.  Same tile cores as the non-carry path:
                    # chains are bit-identical (tested).
                    ax = 1 if pallas else 0
                    DR = cfg.d_tile
                    core = (functools.partial(_sample_tiles_pallas,
                                              count_bounds=count_bounds)
                            if pallas else _sample_entry_tiles)

                    def entry_body(st, inp):
                        Ndk, Nwk, dNk_acc, db, cur_od = st
                        cd, cw, zc, eo, wo, k = inp

                        Ndk, db, cur_od = carry_tile_switch(
                            Ndk, db, cur_od, eo, DR, ax)
                        Wb = lax.dynamic_slice_in_dim(
                            Nwk, wo, cfg.w_tile, ax)
                        db, Wb, dNk, z_new = core(
                            db, Wb, Nk + dNk_acc, zc, cd, cw, k,
                            cfg, vocab_size)
                        Nwk = lax.dynamic_update_slice_in_dim(
                            Nwk, Wb, wo, ax)
                        return (Ndk, Nwk, dNk_acc + dNk, db, cur_od), z_new

                    od0 = od[0]
                    db0 = lax.dynamic_slice_in_dim(Ndk, od0, DR, ax)
                    (Ndk, computing, dNk, db_f, od_f), z_new = lax.scan(
                        entry_body,
                        (Ndk, computing, jnp.zeros_like(Nk), db0, od0),
                        (ed, ew, z_blk, od, ow, entry_keys),
                    )
                    # final flush: the last run's tile is still in carry
                    Ndk = lax.dynamic_update_slice_in_dim(
                        Ndk, db_f, od_f, ax)
                else:
                    sample = (functools.partial(_sample_entry_pallas,
                                                count_bounds=count_bounds)
                              if pallas else _sample_entry)

                    def entry_body(st, inp):
                        Ndk, Nwk, dNk_acc = st
                        cd, cw, zc, eo, wo, k = inp
                        Ndk, Nwk, dNk, z_new = sample(
                            Ndk, Nwk, Nk + dNk_acc, zc, (cd, cw, eo, wo),
                            k, cfg, vocab_size)
                        return (Ndk, Nwk, dNk_acc + dNk), z_new

                    (Ndk, computing, dNk), z_new = lax.scan(
                        entry_body, (Ndk, computing, jnp.zeros_like(Nk)),
                        (ed, ew, z_blk, od, ow, entry_keys),
                    )
            else:
                d_blk, w_blk, m_blk = blk
                # clamp to the static block width (blocks narrower than
                # cfg.chunk arise on small corpora — see partition_ratings)
                c = min(cfg.chunk, d_blk.shape[0])
                nchunk = d_blk.shape[0] // c
                chunk_keys = jax.random.split(sub, nchunk)

                def chunk_body(st, inp):
                    Ndk, Nwk, dNk_acc = st
                    d, w, m, zc, k = inp
                    Ndk, Nwk, dNk, z_new = _sample_chunk(
                        Ndk, Nwk, Nk + dNk_acc, zc, (d, w, m), k, cfg,
                        vocab_size)
                    return (Ndk, Nwk, dNk_acc + dNk), z_new

                (Ndk, computing, dNk), z_new = lax.scan(
                    chunk_body, (Ndk, computing, jnp.zeros_like(Nk)),
                    (d_blk.reshape(nchunk, c), w_blk.reshape(nchunk, c),
                     m_blk.reshape(nchunk, c), z_blk.reshape(nchunk, c),
                     chunk_keys),
                )
                z_new = z_new.reshape(-1)
            # push/pull residue: topic totals sync via psum of deltas
            Nk = Nk + C.allreduce(dNk)
            z_grid = z_grid.at[chunk_idx].set(z_new)
            return (Ndk, Nk, z_grid, key), computing

        (Ndk, Nk, z_grid, key), Nwk_slice = rotate_pipeline(
            step, (Ndk, Nk, z_grid, key), Nwk_slice,
            n_chunks=nc, wire=cfg.rotate_wire,
            chunk_axis=1 if pallas else 0)
        if pallas:
            Ndk, Nwk_slice = Ndk.T, Nwk_slice.T
        return Ndk, Nwk_slice, Nk, z_grid, work_w

    return epoch


def _pushpull_epoch_device_fn(mesh: WorkerMesh, cfg: LDAConfig,
                              vocab_size: int):
    """Device-view epoch for ``algo="pushpull"``: no rotation — the
    word-topic table stays row-sharded; each chunk is one
    pull → sample → push round plus a psum of the topic-total deltas
    (Harp's per-iteration pull/push granularity, SURVEY.md §4.4)."""

    def epoch(Ndk, Nwk_shard, Nk, z, d, w, m, keys):
        key = keys[0]
        T = d.shape[0]
        c = min(cfg.chunk, T)
        nchunk = T // c
        chunk_keys = jax.random.split(key, nchunk)

        def body(st, inp):
            Ndk, Nwk_shard, Nk, drop = st
            dc, wc, mc, zc, k = inp
            Ndk, Nwk_shard, dNk, z_new, d_chunk = _sample_chunk_pushpull(
                Ndk, Nwk_shard, Nk, zc, (dc, wc, mc), k, cfg, vocab_size)
            Nk = Nk + C.allreduce(dNk)
            return (Ndk, Nwk_shard, Nk, drop + d_chunk), z_new

        (Ndk, Nwk_shard, Nk, drop), z_new = lax.scan(
            body, (Ndk, Nwk_shard, Nk, jnp.int32(0)),
            (d.reshape(nchunk, c), w.reshape(nchunk, c),
             m.reshape(nchunk, c), z.reshape(nchunk, c), chunk_keys))
        # per-worker valid tokens (the skew execution counter; drops are
        # reported separately and already globally summed)
        work_w = C.allgather(jnp.sum(m > 0).astype(jnp.float32)[None])
        return Ndk, Nwk_shard, Nk, z_new.reshape(-1), drop, work_w

    return epoch


def _device_epoch_fn(mesh: WorkerMesh, cfg: LDAConfig, vocab_size: int,
                     count_bounds=(None, None)):
    """Pick the epoch body for ``cfg.algo`` (rotation vs pull/push)."""
    if cfg.algo == "pushpull":
        return _pushpull_epoch_device_fn(mesh, cfg, vocab_size)
    return _epoch_device_fn(mesh, cfg, vocab_size, count_bounds)


def _n_token_args(cfg: LDAConfig) -> int:
    return 5 if cfg.algo in _TILED_ALGOS else 4  # (+ keys)


def _epoch_out_specs(mesh, cfg):
    """Pushpull epochs also return the global drop counter (replicated);
    every algo appends the replicated per-worker work vector (skew)."""
    base = (mesh.spec(0), mesh.spec(0), P(), mesh.spec(0))
    return base + ((P(),) if cfg.algo == "pushpull" else ()) + (P(),)


def make_epoch_fn(mesh: WorkerMesh, cfg: LDAConfig, vocab_size: int,
                  count_bounds=(None, None)):
    """Compile one epoch — see :func:`_epoch_device_fn` (rotation algos)
    and :func:`_pushpull_epoch_device_fn`.

    ``count_bounds``: static (max doc-topic, max word-topic) count bounds
    the pallas kernel uses to pick its exact-gather plane counts — chain
    invariants derived by ``LDA._install_pack`` from the initial tables.
    """
    return jax.jit(
        mesh.shard_map(
            _device_epoch_fn(mesh, cfg, vocab_size, count_bounds),
            in_specs=(mesh.spec(0), mesh.spec(0), P(), mesh.spec(0))
            + (mesh.spec(0),) * _n_token_args(cfg),
            out_specs=_epoch_out_specs(mesh, cfg),
        )
    )


def make_multi_epoch_fn(mesh: WorkerMesh, cfg: LDAConfig, vocab_size: int,
                        epochs: int, count_bounds=(None, None)):
    """Compile ``epochs`` Gibbs sweeps as ONE device program.

    Same dispatch-amortization as mfsgd.make_multi_epoch_fn (round trips
    cost ~20–150 ms on the relay-attached v5e, 2026-07-30).  Each sweep's
    RNG key is derived on device by folding the epoch index into the
    worker's base key, so the chain is identical to per-epoch dispatches
    with the same derivation.
    """
    inner = _device_epoch_fn(mesh, cfg, vocab_size, count_bounds)

    pp = cfg.algo == "pushpull"

    def many(Ndk, Nwk_slice, Nk, z_grid, *token_args):
        tokens = token_args[:-1]
        base = jax.random.wrap_key_data(token_args[-1][0])

        def body(carry, e):
            st = carry[:4]
            k = jax.random.key_data(jax.random.fold_in(base, e))[None]
            out = inner(*st, *tokens, k)
            if pp:  # accumulate the drop counter across sweeps
                out = out[:4] + (carry[4] + out[4], out[5])
            return out, None

        # trailing zeros: the per-worker work vector's carry slot (the
        # per-sweep counts are identical, so the last sweep's suffice)
        init = (Ndk, Nwk_slice, Nk, z_grid) \
            + ((jnp.int32(0),) if pp else ()) \
            + (jnp.zeros((mesh.num_workers,), jnp.float32),)
        out, _ = lax.scan(body, init, jnp.arange(epochs))
        return out

    return jax.jit(
        mesh.shard_map(
            many,
            in_specs=(mesh.spec(0), mesh.spec(0), P(), mesh.spec(0))
            + (mesh.spec(0),) * _n_token_args(cfg),
            out_specs=_epoch_out_specs(mesh, cfg),
        )
    )


def partition_tokens_by_doc(doc_ids, word_ids, z0, n_docs, n_workers,
                            chunk):
    """Partition tokens to their doc-owning worker (pushpull layout).

    Docs are block-partitioned: worker w owns docs [w·d_bound, (w+1)·
    d_bound).  Returns ``(d [n, T_pad] worker-LOCAL doc rows, w [n, T_pad]
    GLOBAL word ids, z [n, T_pad], m [n, T_pad] mask, d_bound)`` with
    T_pad a common multiple of ``min(chunk, T_pad)`` so the epoch scan
    has static chunk shapes.  Padding slots use doc/word 0 with mask 0.
    """
    d_bound = -(-n_docs // n_workers)
    owner = np.asarray(doc_ids) // d_bound
    per = [np.flatnonzero(owner == wk) for wk in range(n_workers)]
    t_max = max((len(p) for p in per), default=0)
    T_pad = max(chunk, -(-t_max // chunk) * chunk) if t_max else chunk
    d = np.zeros((n_workers, T_pad), np.int32)
    w = np.zeros((n_workers, T_pad), np.int32)
    z = np.zeros((n_workers, T_pad), np.int32)
    m = np.zeros((n_workers, T_pad), np.float32)
    for wk, idx in enumerate(per):
        t = len(idx)
        d[wk, :t] = np.asarray(doc_ids)[idx] - wk * d_bound
        w[wk, :t] = np.asarray(word_ids)[idx]
        z[wk, :t] = np.asarray(z0)[idx]
        m[wk, :t] = 1.0
    return d, w, z, m, d_bound


def suggest_pull_cap(word_ids, mask, n_workers, chunk, vocab_size,
                     dedup=True):
    """EXACT zero-drop ``pull_cap`` for a partitioned pushpull layout.

    One host pass over the corpus (load-time, O(T)): for every (worker,
    chunk) slice of the :func:`partition_tokens_by_doc` layout, count the
    requests each owner would receive — DISTINCT word rows when ``dedup``
    (the ``LDAConfig.dedup_pulls`` wire), raw tokens otherwise — and
    return the max.  Sampling with this cap drops nothing; anything
    smaller trades counted drops for smaller [nw·cap, K] buffers.
    The answer is the sizing rule VERDICT r2 item 5 asked for: under
    Zipf word frequencies the deduped cap sits far below ``chunk``
    while the raw cap approaches it (every repeat of a hot word bills
    the hot owner a slot).
    """
    w = np.asarray(word_ids).reshape(n_workers, -1)
    m = np.asarray(mask).reshape(n_workers, -1) > 0
    rows_local = _ceil_div(vocab_size, n_workers)
    T = w.shape[1]
    c = min(chunk, T)
    cap = 1
    for wk in range(n_workers):
        ww = w[wk].reshape(-1, c)
        mm = m[wk].reshape(-1, c)
        for j in range(ww.shape[0]):
            ids = ww[j][mm[j]]
            if dedup:
                ids = np.unique(ids)
            if ids.size:
                cap = max(cap, int(np.bincount(ids // rows_local,
                                               minlength=n_workers).max()))
    return cap


def epoch_arg_shapes(n_workers, n_docs, vocab_size, cfg: LDAConfig,
                     n_tokens=0, entries_per_row=None, entry_width=None):
    """Shape/dtype of every compiled-epoch argument at a given scale,
    WITHOUT building a corpus — ``[(shape, dtype), ...]`` in
    :func:`make_epoch_fn` argument order (Ndk, Nwk, Nk, z, *tokens, keys).

    This is the memory-budget model for graded shapes: the enwiki-1M
    lowering proof (tests/test_lda_scale.py, mirroring the 1B-point
    KMeans proof of tests/test_kmeans_stream.py) feeds these into
    ``jax.ShapeDtypeStruct`` + ``make_multi_epoch_fn(...).lower`` so the
    1M-doc × 1k-topic program is *traced at its true shapes* with zero
    host memory.  SURVEY.md §3.4 #3; VERDICT r2 item 3.

    Corpus-dependent token-layout dims are modeled for an EVENLY
    distributed corpus (the partitioners pad every (worker, slice) block
    to the max-loaded one, so even fill is exact for balanced synthetic
    corpora and a lower bound under skew):

    - scatter/pushpull: per-worker token count pads to a ``cfg.chunk``
      multiple (mirrors :func:`partition_tokens_by_doc` /
      :func:`harp_tpu.models.mfsgd.partition_ratings` exactly);
    - dense: entry width ``entry_width`` defaults to ``cfg.entry_cap``
      (a corpus whose hot tiles fill their caps — enwiki's Zipf vocab
      does; the partitioner shrinks C below the cap only when every tile
      is small) and ``entries_per_row`` defaults to
      ``ceil(tokens_per_grid_row / C)`` — tight packing.  Pass the real
      partitioner's NE/C to model a specific corpus.
    """
    n, K = n_workers, cfg.n_topics
    ns = rotate_chunks_resolved(cfg) * n  # chunk-slices (pushpull: unused)
    i32, f32 = np.dtype(np.int32), np.dtype(np.float32)
    ndk_dt = np.dtype(cfg.ndk_dtype)
    keys = ((n, 2), np.dtype(np.uint32))
    nk = ((K,), f32)
    if cfg.algo == "pushpull":
        d_bound = _ceil_div(n_docs, n)
        w_own = _ceil_div(vocab_size, n)
        t_max = _ceil_div(n_tokens, n)
        T_pad = max(cfg.chunk, _ceil_div(t_max, cfg.chunk) * cfg.chunk) \
            if t_max else cfg.chunk
        flat = ((n * T_pad,), i32)
        return [((d_bound * n, K), ndk_dt), ((w_own * n, K), f32), nk,
                flat, flat, flat, ((n * T_pad,), f32), keys]
    if cfg.algo in _TILED_ALGOS:
        d_own, w_own, d_bound, ib2 = _dense_bounds(
            n_docs, vocab_size, n, ns, cfg.d_tile, cfg.w_tile)
        C = entry_width or cfg.entry_cap
        # NE comes from the REAL entry capacity — pallas C-padding adds
        # masked slots, not token capacity (set_tokens pads after packing)
        NE = entries_per_row or max(1, _ceil_div(_ceil_div(n_tokens, n * ns),
                                                 C))
        if cfg.algo == "pallas":
            C = _PALLAS_C * _ceil_div(C, _PALLAS_C)
        ec, eo = ((n * ns, NE, C), i32), ((n * ns, NE), i32)
        return [((d_bound * n, K), ndk_dt), ((2 * ib2 * n, K), f32), nk,
                ec, ec, ec, eo, eo, keys]
    # scatter: mirrors partition_ratings' B rule
    d_bound = _ceil_div(n_docs, n)
    wb2 = _ceil_div(vocab_size, ns)
    bmax = _ceil_div(n_tokens, n * ns)
    if bmax >= cfg.chunk:
        B = _ceil_div(bmax, cfg.chunk) * cfg.chunk
    else:
        B = min(cfg.chunk, max(8, _ceil_div(bmax, 8) * 8))
    blk = ((n * ns, B), i32)
    return [((d_bound * n, K), ndk_dt), ((2 * wb2 * n, K), f32), nk,
            blk, blk, blk, ((n * ns, B), f32), keys]


class LDA:
    """Host driver (the mapCollective residue for edu.iu.lda)."""

    def __init__(self, n_docs, vocab_size, cfg: LDAConfig | None = None,
                 mesh: WorkerMesh | None = None, seed=0):
        self.mesh = mesh or current_mesh()
        self.cfg = cfg or LDAConfig()
        self.n_docs, self.vocab_size = n_docs, vocab_size
        n = self.mesh.num_workers
        nc = rotate_chunks_resolved(self.cfg)
        # rotate_chunks chunk-slices per worker (rotation algos)
        self._n_slices = nc * n
        if self.cfg.algo in _TILED_ALGOS:
            self.d_own, self.w_own, self.d_bound, wbc = _dense_bounds(
                n_docs, vocab_size, n, self._n_slices,
                self.cfg.d_tile, self.cfg.w_tile)
            self.w_bound = nc * wbc
        elif self.cfg.algo == "pushpull":
            self.d_bound = self.d_own = -(-n_docs // n)
            # word-topic rows this worker OWNS (row-sharded global table)
            self.w_bound = self.w_own = -(-vocab_size // n)
        else:
            self.d_bound = self.d_own = -(-n_docs // n)
            self.w_bound = nc * (-(-vocab_size // self._n_slices))
            self.w_own = self.w_bound // nc
        # (max doc-topic, max word-topic) static count bounds — derived
        # per corpus in _install_pack (pallas only); (None, None) = the
        # kernel falls back to dtype-based gather plane counts
        self._count_bounds = (None, None)
        self._epoch_fn = flightrec.track(
            make_epoch_fn(self.mesh, self.cfg, vocab_size), "lda.epoch")
        self._multi_fns: dict = {}
        self._seed = seed
        self._tokens = None
        # pushpull only: TOKENS skipped by pull_cap capacity drops in the
        # most recent sample_epoch/sample_epochs call (0 = none skipped)
        self.last_dropped = 0
        # per-worker tokens touched in the most recent sweep (numpy [nw];
        # the skew spine's execution counter — see utils/skew.py)
        self.last_work = None
        # movable pack grains for the skew execution records (PR 15):
        # the elastic driver sets per-worker [(pack_id, load)] lists so
        # the sentinel's skew_trigger plan is whole-unit replayable
        self.skew_units = None

    def suggest_pull_cap(self, apply=False):
        """Exact zero-drop ``pull_cap`` for the LOADED corpus (pushpull
        only; see module-level :func:`suggest_pull_cap`).  ``apply=True``
        installs it: the epoch program is rebuilt so the next sample
        traces with the new capacity (call between ``set_tokens`` and
        the first sample to avoid a second compile)."""
        if self.cfg.algo != "pushpull":
            raise ValueError("suggest_pull_cap applies to algo='pushpull'")
        if self._tokens is None:
            raise RuntimeError("call set_tokens() before suggest_pull_cap()")
        _, pw, pm = self._tokens
        cap = suggest_pull_cap(pw, pm, self.mesh.num_workers,
                               self.cfg.chunk, self.vocab_size,
                               dedup=self.cfg.dedup_pulls)
        if apply:
            self.cfg.pull_cap = cap
            self._epoch_fn = flightrec.track(
                make_epoch_fn(self.mesh, self.cfg, self.vocab_size),
                "lda.epoch")
            self._multi_fns.clear()
        return cap

    def set_tokens(self, doc_ids, word_ids):
        """Load the token corpus (one entry per token occurrence)."""
        self._install_pack(self.pack_tokens(doc_ids, word_ids))

    def pack_tokens(self, doc_ids, word_ids, z0=None) -> dict:
        """Host-side half of :meth:`set_tokens`: partition the corpus into
        this config's device layout and build the initial count tables —
        a plain dict of numpy arrays, so callers can CACHE it
        (``lda.benchmark``'s ``pack_cache``: the enwiki-1M pack costs
        ~675 s on a 1-core host and is identical across sweep variants
        that share a tiling).  ``_install_pack`` ships it to devices.

        ``z0`` (PR 15): explicit per-token topic assignments instead of
        the seeded random init — the elastic repartition extracts the
        live chain (:meth:`token_state`), remaps doc ids, and repacks
        WITHOUT resetting it; counts rebuild exactly from ``z0``, so
        the move itself is chain-preserving."""
        n = self.mesh.num_workers
        K = self.cfg.n_topics
        if self.cfg.ndk_dtype == "int16":
            # a doc-topic count is bounded by the doc's token count; wrap
            # past int16 would corrupt counts SILENTLY (the posterior
            # clamp hides negatives), so fail loudly here instead
            longest = int(np.bincount(np.asarray(doc_ids)).max()) \
                if len(doc_ids) else 0
            if longest > np.iinfo(np.int16).max:
                raise ValueError(
                    f"ndk_dtype='int16': longest document has {longest} "
                    f"tokens > {np.iinfo(np.int16).max} — counts would "
                    "wrap; use ndk_dtype='float32' or split the document")
        # reuse the MF-SGD grid partitioners: "rating value" carries the
        # initial topic assignment
        if z0 is None:
            rng = np.random.default_rng(self._seed)
            z0 = rng.integers(0, K, len(doc_ids)).astype(np.float32)
        else:
            z0 = np.asarray(z0, np.float32)
            if z0.shape != np.shape(doc_ids):
                raise ValueError(
                    f"z0 has shape {z0.shape} but the corpus has "
                    f"{len(doc_ids)} tokens")
        nc = rotate_chunks_resolved(self.cfg)
        if self.cfg.algo in _TILED_ALGOS:
            ed, ew, ez, od, ow, do, wo, db, wbc = partition_ratings_tiles(
                doc_ids, word_ids, z0, self.n_docs, self.vocab_size, n,
                self.cfg.d_tile, self.cfg.w_tile, self.cfg.entry_cap,
                n_slices=self._n_slices,
            )
            assert (do, wo, db, nc * wbc) == (
                self.d_own, self.w_own, self.d_bound, self.w_bound)
            if self.cfg.algo == "pallas":
                # kernel chunks C in _PALLAS_C slices: pad entry width up
                # (pad slots: d id = tile width -> masked out in-kernel)
                Cw = ed.shape[-1]
                Cp = _PALLAS_C * _ceil_div(Cw, _PALLAS_C)
                if Cp != Cw:
                    pad = ((0, 0), (0, 0), (0, Cp - Cw))
                    ed = np.pad(ed, pad, constant_values=self.cfg.d_tile)
                    ew = np.pad(ew, pad, constant_values=self.cfg.w_tile)
                    ez = np.pad(ez, pad, constant_values=0.0)
            z_grid = ez.astype(np.int32)
            tokens = (ed, ew, od, ow)
        elif self.cfg.algo == "pushpull":
            pd, pw, pz, pm, db = partition_tokens_by_doc(
                doc_ids, word_ids, z0, self.n_docs, n, self.cfg.chunk)
            assert db == self.d_bound
            z_grid = pz.reshape(-1)
            tokens = (pd.reshape(-1), pw.reshape(-1), pm.reshape(-1))
        else:
            bd, bw, bz, bm, db, wbc = partition_ratings(
                doc_ids, word_ids, z0, self.n_docs, self.vocab_size, n,
                self.cfg.chunk, n_slices=self._n_slices,
            )
            assert (db, nc * wbc) == (self.d_bound, self.w_bound)
            z_grid = bz.astype(np.int32)
            tokens = (bd, bw, bm)

        # initial count tables from the assignments (host, exact)
        Ndk = np.zeros((self.d_bound * n, K), np.dtype(self.cfg.ndk_dtype))
        Nwk = np.zeros((self.w_bound * n, K), np.float32)
        gd, gw, gm = self._global_token_ids(tokens)
        gz = z_grid.reshape(-1)
        np.add.at(Ndk, (gd[gm], gz[gm]), 1)  # int literal: Ndk may be int16
        np.add.at(Nwk, (gw[gm], gz[gm]), 1.0)
        Nk = Nwk.sum(0)
        return {"tokens": tuple(tokens), "z_grid": z_grid, "Ndk": Ndk,
                "Nwk": Nwk, "Nk": Nk, "n_tokens": int(gm.sum())}

    def _install_pack(self, pack: dict) -> None:
        """Device half of :meth:`set_tokens`: shard a
        :meth:`pack_tokens` dict onto the mesh."""
        n = self.mesh.num_workers
        sh = self.mesh.shard_array
        if self.cfg.algo == "pallas":
            # static count bounds for the kernel's exact gathers (chain
            # invariants: a doc-topic count ≤ its doc length, a
            # word-topic count ≤ its word frequency — Gibbs preserves
            # both row sums).  Enwiki-shape corpora have doc lengths
            # ≤ 256, so the Db gather usually needs ONE bf16 dot instead
            # of 2-3 digit planes.  Epoch program rebuilt: the bounds are
            # trace-time statics.
            # int64 accumulator on the stored dtype — no 2x table copy
            # (an f32 astype of the enwiki int16 Ndk would be 4 GB)
            self._count_bounds = (
                int(np.asarray(pack["Ndk"]).sum(1, dtype=np.int64).max()),
                int(np.asarray(pack["Nwk"]).sum(1, dtype=np.int64).max()))
            self._epoch_fn = flightrec.track(
                make_epoch_fn(self.mesh, self.cfg, self.vocab_size,
                              self._count_bounds), "lda.epoch")
        from harp_tpu.utils import telemetry

        if telemetry.enabled():
            # ingest-side skew record (host arithmetic over the pack —
            # also fires for cached packs, which skip pack_tokens)
            _, _, gm = self._global_token_ids(pack["tokens"])
            per = gm.reshape(n, -1).sum(1)
            skew.record_partition("lda.partition", per, unit="tokens",
                                  padded_total=gm.size)
        self.Ndk, self.Nwk = sh(pack["Ndk"], 0), sh(pack["Nwk"], 0)
        self.Nk = jax.device_put(jnp.asarray(pack["Nk"]),
                                 self.mesh.replicated())
        self.z_grid = sh(np.asarray(pack["z_grid"], np.int32), 0)
        self._tokens = tuple(sh(a, 0) for a in pack["tokens"])
        self._multi_fns.clear()  # compiled programs bind to token shapes
        self.n_tokens = int(pack["n_tokens"])
        # raw key bits (utils.prng): bit-identical to split(PRNGKey(seed))
        # without the per-seed PRNGKey compile (CLAUDE.md relay trap)
        self._keys = prng.split_keys(self._seed, n)

    def _global_token_ids(self, tokens):
        """Grid-local → global STORAGE (doc, word) row ids + valid mask.

        Grid row r belongs to worker ``r // ns`` (doc range) and word
        slice ``r % ns`` (``ns = rotate_chunks · n`` chunk-slices).
        "Storage" rows: the dense layout pads each range to a tile
        multiple, so storage row ≠ external id there (use
        :meth:`doc_topic_table` / :meth:`word_topic_table` for external
        views).
        """
        n = self.mesh.num_workers
        if self.cfg.algo == "pushpull":
            pd, pw, pm = (np.asarray(a) for a in tokens)
            t_pad = pd.shape[0] // n
            gd = pd + (np.arange(n).repeat(t_pad) * self.d_bound)
            return gd, pw, pm > 0  # word ids are already global
        ns = self._n_slices
        db, wbc = self.d_bound, self.w_bound // rotate_chunks_resolved(self.cfg)
        rows = np.arange(n * ns)
        if self.cfg.algo in _TILED_ALGOS:
            ed, ew, od, ow = (np.asarray(a) for a in tokens)
            gm = (ed < self.cfg.d_tile).reshape(-1)
            ld = np.minimum(ed, self.cfg.d_tile - 1) + od[:, :, None]
            lw = np.minimum(ew, self.cfg.w_tile - 1) + ow[:, :, None]
            gd = (ld + (rows // ns * db)[:, None, None]).reshape(-1)
            gw = (lw + (rows % ns * wbc)[:, None, None]).reshape(-1)
            return gd, gw, gm
        bd, bw, bm = (np.asarray(a) for a in tokens)
        gd = (bd + (rows // ns * db)[:, None]).reshape(-1)
        gw = (bw + (rows % ns * wbc)[:, None]).reshape(-1)
        gm = bm.reshape(-1) > 0
        return gd, gw, gm

    def doc_topic_table(self):
        """[n_docs, K] doc-topic counts with storage padding stripped."""
        n = self.mesh.num_workers
        Ndk = np.asarray(self.Ndk)
        if self.cfg.algo in _TILED_ALGOS:
            K = Ndk.shape[-1]
            Ndk = Ndk.reshape(n, self.d_bound, K)[:, : self.d_own].reshape(-1, K)
        return Ndk[: self.n_docs]

    def word_topic_table(self):
        """[vocab_size, K] word-topic counts with storage padding stripped."""
        Nwk = np.asarray(self.Nwk)
        if self.cfg.algo in _TILED_ALGOS:
            K = Nwk.shape[-1]
            wbc = self.w_bound // rotate_chunks_resolved(self.cfg)
            Nwk = Nwk.reshape(self._n_slices, wbc, K)[:, : self.w_own] \
                .reshape(-1, K)
        return Nwk[: self.vocab_size]

    def token_state(self):
        """Current chain state as EXTERNAL ``(doc, word, z)`` token
        triples (PR 15).

        A collapsed-Gibbs chain IS the token-assignment multiset — both
        count tables derive exactly from it — so these triples are the
        complete, layout-independent chain state: the elastic
        repartition extracts them, remaps doc ids, and repacks with
        ``pack_tokens(..., z0=z)``, and the rebuilt counts equal the
        live ones bit-for-bit.  Storage row ids (grid padding included)
        are translated back to external doc/word ids here.
        """
        if self._tokens is None:
            raise RuntimeError("call set_tokens() before token_state()")
        gd, gw, gm = self._global_token_ids(self._tokens)
        gz = np.asarray(self.z_grid).reshape(-1)
        d_st, w_st, z = gd[gm], gw[gm], gz[gm]
        if self.cfg.algo == "pushpull":
            # doc storage is unpadded (d_bound == d_own) and word ids
            # are already global external
            return d_st, w_st, z
        wbc = self.w_bound // rotate_chunks_resolved(self.cfg)
        d_ext = (d_st // self.d_bound) * self.d_own + d_st % self.d_bound
        w_ext = (w_st // wbc) * self.w_own + w_st % wbc
        return d_ext, w_ext, z

    def compile_epochs(self, epochs: int):
        """AOT-compile the ``epochs``-sweep program WITHOUT sampling —
        benchmark warmup must not double the workload (same contract as
        :meth:`harp_tpu.models.mfsgd.MFSGD.compile_epochs`).  The compiled
        executable is cached and reused by :meth:`sample_epochs`."""
        if self._tokens is None:
            raise RuntimeError("call set_tokens() before compile_epochs()")
        fn = self._multi_fns.get(epochs)
        if fn is None:
            from harp_tpu.utils import telemetry

            jitted = make_multi_epoch_fn(
                self.mesh, self.cfg, self.vocab_size, epochs,
                self._count_bounds)
            keys = self.mesh.shard_array(self._keys, 0)
            # steps=0: lowering traces the sweep's comm sites under the
            # execution tag without counting an execution
            with telemetry.ledger.run("lda.epochs", steps=0):
                fn = self._multi_fns[epochs] = flightrec.track(
                    jitted.lower(
                        self.Ndk, self.Nwk, self.Nk, self.z_grid,
                        *self._tokens, keys).compile(), "lda.epochs")
        return fn

    def _install_epoch_out(self, out):
        self.Ndk, self.Nwk, self.Nk, self.z_grid = out[:4]
        if self.cfg.algo == "pushpull":
            # drop counter (the "counted, never silently wrong" half of
            # the capacity contract) + per-worker work vector in ONE
            # stacked readback; reading it back doubles as the device sync
            stats = flightrec.readback(jnp.concatenate(
                [out[4].reshape(1).astype(jnp.float32), out[5]]))
            self.last_dropped = int(stats[0])
            self.last_work = np.asarray(stats[1:])
        else:
            # the per-worker work vector rides the epoch outputs; reading
            # it back IS the device sync (replaces the old Nk scalar sync)
            self.last_work = np.asarray(flightrec.readback(out[4]))

    def sample_epochs(self, epochs: int):
        """Run ``epochs`` Gibbs sweeps as one device program (one dispatch,
        one sync) — see :func:`make_multi_epoch_fn`.  Use :meth:`fit` when
        checkpointing between sweeps."""
        from harp_tpu.utils import telemetry

        fn = self.compile_epochs(epochs)
        keys = self.mesh.shard_array(self._keys, 0)
        # the scan body's traced comm sites execute once per Gibbs sweep
        with telemetry.span("lda.epochs", epochs=epochs), \
                telemetry.ledger.run("lda.epochs", steps=epochs):
            t0 = time.perf_counter()
            out = fn(self.Ndk, self.Nwk, self.Nk, self.z_grid,
                     *self._tokens, keys)
            self._advance_keys()
            self._install_epoch_out(out)
            skew.record_execution("lda.epochs", self.last_work,
                                  unit="tokens",
                                  wall_s=time.perf_counter() - t0,
                                  units=self.skew_units)

    def sample_epoch(self):
        if self._tokens is None:
            raise RuntimeError("call set_tokens() before sample_epoch()")
        from harp_tpu.utils import telemetry

        keys = self.mesh.shard_array(self._keys, 0)
        with telemetry.span("lda.epoch"), \
                telemetry.ledger.run("lda.epochs", steps=1):
            t0 = time.perf_counter()
            out = self._epoch_fn(
                self.Ndk, self.Nwk, self.Nk, self.z_grid, *self._tokens,
                keys
            )
            self._advance_keys()
            self._install_epoch_out(out)
            skew.record_execution("lda.epochs", self.last_work,
                                  unit="tokens",
                                  wall_s=time.perf_counter() - t0,
                                  units=self.skew_units)

    def _advance_keys(self):
        # prng.split_keys builds the base key's bits on host — a fresh
        # derived seed per epoch never costs a (remote) compile, unlike
        # split(PRNGKey(int)) which specialized per distinct int
        # (CLAUDE.md relay trap; the bits are identical, so checkpointed
        # chains resume unchanged)
        self._keys = prng.split_keys(int(self._keys[0][0]) ^ 0x9E37,
                                     self.mesh.num_workers)

    def fit(self, epochs: int, ckpt_dir: str | None = None, *,
            ckpt_every: int = 5, max_restarts: int = 3, fault=None):
        """Sample ``epochs`` Gibbs sweeps with optional checkpoint/resume.

        Same recovery contract as :meth:`harp_tpu.models.mfsgd.MFSGD.fit`
        (restart-from-entry-state before the first checkpoint; resume
        installs the restored counts; fault without ckpt_dir is refused).
        The RNG keys are part of the checkpoint, so a recovered run samples
        the same chain it would have without the crash.
        """
        from harp_tpu.utils.fault import check_restored_shapes, fit_epochs

        def get_state():
            return {"Ndk": self.Ndk, "Nwk": self.Nwk, "Nk": self.Nk,
                    "z": self.z_grid, "keys": np.asarray(self._keys)}

        def set_state(state):
            check_restored_shapes([("Ndk", state["Ndk"], self.Ndk),
                                   ("Nwk", state["Nwk"], self.Nwk),
                                   ("z", state["z"], self.z_grid)])
            if not isinstance(state["Ndk"], jax.Array):  # numpy from restore
                sh = self.mesh.shard_array
                # restore casts to the configured dtype (counts are exact
                # integers in either, so f32↔int16 round-trips losslessly)
                self.Ndk = sh(np.asarray(state["Ndk"]).astype(
                    np.dtype(self.cfg.ndk_dtype)), 0)
                self.Nwk = sh(np.asarray(state["Nwk"]), 0)
                self.z_grid = sh(np.asarray(state["z"]), 0)
                self.Nk = jax.device_put(jnp.asarray(np.asarray(state["Nk"])),
                                         self.mesh.replicated())
            else:
                self.Ndk, self.Nwk = state["Ndk"], state["Nwk"]
                self.Nk, self.z_grid = state["Nk"], state["z"]
            self._keys = np.asarray(state["keys"])

        fit_epochs(self.sample_epoch, get_state, set_state, epochs,
                   ckpt_dir, ckpt_every=ckpt_every,
                   max_restarts=max_restarts, fault=fault,
                   phase="lda.epochs")

    def log_likelihood(self):
        """Mean per-token predictive log-likelihood of current assignments."""
        if self._tokens is None:
            raise RuntimeError("call set_tokens() before log_likelihood()")
        Ndk = np.asarray(self.Ndk)
        Nwk = np.asarray(self.Nwk)
        Nk = np.asarray(self.Nk)
        cfg = self.cfg
        gd, gw, gm = self._global_token_ids(self._tokens)
        gz = np.asarray(self.z_grid).reshape(-1)
        d, w, zz = gd[gm], gw[gm], gz[gm]
        nd = Ndk.sum(1)
        theta = (Ndk[d, zz] + cfg.alpha) / (nd[d] + cfg.n_topics * cfg.alpha)
        phi = (Nwk[w, zz] + cfg.beta) / (Nk[zz] + self.vocab_size * cfg.beta)
        return float(np.mean(np.log(np.maximum(theta * phi, 1e-12))))


def synthetic_corpus(n_docs, vocab_size, n_topics_true, tokens_per_doc, seed=0):
    """Documents generated from a true LDA model (peaked topics)."""
    rng = np.random.default_rng(seed)
    # each true topic owns a disjoint vocabulary band (easy to recover)
    band = vocab_size // n_topics_true
    doc_ids, word_ids = [], []
    for d in range(n_docs):
        topics = rng.dirichlet(np.full(n_topics_true, 0.2))
        zs = rng.choice(n_topics_true, size=tokens_per_doc, p=topics)
        ws = (zs * band + rng.integers(0, band, tokens_per_doc)) % vocab_size
        doc_ids += [d] * tokens_per_doc
        word_ids += ws.tolist()
    return np.asarray(doc_ids, np.int32), np.asarray(word_ids, np.int32)


def _make_cfg(n_topics, algo="dense", chunk=None, d_tile=None, w_tile=None,
              entry_cap=None, pull_cap=None, ndk_dtype="float32",
              dedup_pulls=None, sampler=None, rng_impl=None,
              pallas_exact_gathers=None, carry_db=None,
              rotate_chunks=None, rotate_wire=None):
    """None inherits LDAConfig's defaults; algo-specific knobs raise when
    combined with a non-owning algo (shared contract: mfsgd.algo_kwargs)."""
    # None = "caller didn't say": resolves to the LDAConfig defaults,
    # except algo="pallas" whose fused kernel IS the exprace +
    # hardware-bits stack (an EXPLICIT gumbel/threefry request passes
    # through and errors in LDAConfig's validation)
    if sampler is None:
        sampler = "exprace" if algo == "pallas" else "gumbel"
    if rng_impl is None:
        rng_impl = "rbg" if algo == "pallas" else "threefry"
    # benchmark/sweep identity is per-NAME: the `_carry` configs own the
    # carry knob, so an unstated carry_db pins to OFF here even though
    # the user-facing LDAConfig default flipped ON (2026-08-01) — else
    # the flip would silently turn `lda`/`lda_pallas` sweep rows into
    # carry rows and the A/B would compare a config against itself
    # (owning algos only — a pinned False would trip algo_kwargs's
    # non-owning-knob check for scatter/pushpull)
    if carry_db is None and algo in _TILED_ALGOS:
        carry_db = False
    return LDAConfig(n_topics=n_topics, ndk_dtype=ndk_dtype, sampler=sampler,
                     rng_impl=rng_impl,
                     **algo_kwargs(algo, {
        ("scatter", "pushpull"): {"chunk": chunk},
        _TILED_ALGOS: {"d_tile": d_tile, "w_tile": w_tile,
                       "entry_cap": entry_cap, "carry_db": carry_db},
        "pushpull": {"pull_cap": pull_cap, "dedup_pulls": dedup_pulls},
        "pallas": {"pallas_exact_gathers": pallas_exact_gathers},
        # rotation pipeline knobs: every rotation algo owns them;
        # pushpull (which never rotates) rejects a non-None value here
        ("dense", "scatter", "pallas"): {"rotate_chunks": rotate_chunks,
                                         "rotate_wire": rotate_wire},
    }))


def _load_pack(path: str) -> dict:
    """Read a cached :meth:`LDA.pack_tokens` npz back into a pack dict."""
    with np.load(path) as z:
        nt = len([k for k in z.files if k.startswith("tok")])
        return {"tokens": tuple(z[f"tok{i}"] for i in range(nt)),
                "z_grid": z["z_grid"], "Ndk": z["Ndk"],
                "Nwk": z["Nwk"], "Nk": z["Nk"],
                "n_tokens": int(z["n_tokens"])}


def _save_pack(path: str, pack: dict) -> None:
    """Write a pack dict as npz — temp + atomic rename, because the
    sprint is routinely killed mid-config (relay hangs, watchdogs) and a
    truncated npz at the final path would poison every later cache hit.
    The tmp name is per-process so a manual prewarm racing a watcher-fired
    sprint can't interleave writes into one tmp file (ADVICE r4); stale
    tmp siblings from killed writers are swept first so watchdog kills
    don't accumulate orphaned multi-hundred-MB partials."""
    # legacy constant-name orphans (pre-ADVICE-r4 writers) have no owner
    # pid: always stale, sweep unconditionally
    for stale in (path + ".tmp", path + ".tmp.npz"):
        try:
            os.unlink(stale)
        except OSError:
            pass
    for stale in glob.glob(glob.escape(path) + ".*.tmp*"):
        m = re.search(r"\.(\d+)\.tmp", stale)
        try:
            if m and int(m.group(1)) != os.getpid():
                os.kill(int(m.group(1)), 0)  # raises if writer is dead
        except ProcessLookupError:
            try:
                os.unlink(stale)
            except OSError:
                pass
        except OSError:
            pass  # can't signal (perms): assume live, leave it
    tmp_path = f"{path}.{os.getpid()}.tmp"
    np.savez(tmp_path, z_grid=pack["z_grid"], Ndk=pack["Ndk"],
             Nwk=pack["Nwk"], Nk=pack["Nk"], n_tokens=pack["n_tokens"],
             **{f"tok{i}": a for i, a in enumerate(pack["tokens"])})
    # np.savez appends .npz to names without it
    os.replace(tmp_path if os.path.exists(tmp_path) else tmp_path + ".npz",
               path)


def benchmark_corpus(n_docs, vocab_size, tokens_per_doc, seed):
    """The deterministic i.i.d. synthetic corpus :func:`benchmark` times
    (structure irrelevant to cost).  ONE definition, shared with
    scripts/prewarm_bench_cache.py — the pack-cache key assumes both
    build identical corpora, so a second construction would let them
    drift apart silently (same key, different bytes)."""
    rng = np.random.default_rng(seed)
    n_tok = n_docs * tokens_per_doc
    d_ids = np.repeat(np.arange(n_docs, dtype=np.int32), tokens_per_doc)
    w_ids = rng.integers(0, vocab_size, n_tok).astype(np.int32)
    return d_ids, w_ids


def _pack_cache_path(pack_cache, cfg: LDAConfig, num_workers, n_docs,
                     vocab_size, n_topics, tokens_per_doc, seed) -> str:
    """Cache path for a :func:`benchmark` corpus pack — layout-relevant
    knobs ONLY, keyed by the EXACT algo: dense/pallas pack differently
    (pallas pads C to _PALLAS_C), and scatter vs pushpull use different
    partitioners entirely (partition_ratings grid vs
    partition_tokens_by_doc), so they must never share a pack.  Shared
    with scripts/prewarm_bench_cache.py so an offline prewarm writes the
    same keys the sprint reads."""
    import hashlib

    layout = (cfg.algo, cfg.algo == "pallas", cfg.d_tile, cfg.w_tile,
              cfg.entry_cap, cfg.chunk, cfg.ndk_dtype)
    # rotate_chunks changes n_slices and therefore the whole pack layout;
    # appended only when non-incumbent so every existing 2-chunk cache
    # key (675 s enwiki packs) stays valid
    if rotate_chunks_resolved(cfg) != 2:
        layout += (rotate_chunks_resolved(cfg),)
    sig = repr((_PACK_VERSION, n_docs, vocab_size, n_topics,
                tokens_per_doc, seed, num_workers, layout))
    key = hashlib.sha1(sig.encode()).hexdigest()[:16]
    os.makedirs(pack_cache, exist_ok=True)
    return os.path.join(pack_cache, f"lda_pack_{key}.npz")


def benchmark(n_docs=100_000, vocab_size=50_000, n_topics=1000,
              tokens_per_doc=100, epochs=2, mesh=None, chunk=None, seed=0,
              algo="dense", d_tile=None, w_tile=None, entry_cap=None,
              pull_cap=None, ndk_dtype="float32", dedup_pulls=None,
              sampler=None, rng_impl=None, pallas_exact_gathers=None,
              carry_db=None, rotate_chunks=None, rotate_wire=None,
              pack_cache=None):
    """Tokens/sec/chip on an enwiki-1M-scaled config (graded config #3).

    (Full enwiki-1M docs needs a multi-chip pod for the 1M×1k doc-topic
    table; this keeps per-chip load representative.)

    ``pack_cache``: directory for cached :meth:`LDA.pack_tokens` results.
    The corpus here is deterministic in the arguments, and the pack is
    identical across sweep variants sharing a tiling (sampler/rng/carry
    knobs don't touch the layout), so the sprint pays the host packing —
    675 s at enwiki-1M on this 1-core host — once per tiling instead of
    once per config.  The key hashes every layout-relevant argument plus
    ``_PACK_VERSION`` (bump it when packing code changes).
    """
    mesh = mesh or current_mesh()
    cfg = _make_cfg(n_topics, algo, chunk, d_tile, w_tile, entry_cap,
                    pull_cap, ndk_dtype, dedup_pulls, sampler, rng_impl,
                    pallas_exact_gathers, carry_db, rotate_chunks,
                    rotate_wire)
    model = LDA(n_docs, vocab_size, cfg, mesh, seed)
    n_tok = n_docs * tokens_per_doc
    d_ids, w_ids = benchmark_corpus(n_docs, vocab_size, tokens_per_doc, seed)
    t0 = time.perf_counter()
    pack_path = (None if pack_cache is None else _pack_cache_path(
        pack_cache, cfg, mesh.num_workers, n_docs, vocab_size, n_topics,
        tokens_per_doc, seed))
    if pack_path is not None and os.path.exists(pack_path):
        model._install_pack(_load_pack(pack_path))
    else:
        pack = model.pack_tokens(d_ids, w_ids)
        model._install_pack(pack)
        if pack_path is not None:
            _save_pack(pack_path, pack)
    prep = time.perf_counter() - t0

    model.sample_epoch()         # warmup + single-epoch compile
    model.compile_epochs(epochs)  # AOT, off-clock, does NOT sample
    t0 = time.perf_counter()
    model.sample_epochs(epochs)  # ONE dispatch + sync for all epochs
    dt = time.perf_counter() - t0
    out = {
        "tokens_per_sec_per_chip": n_tok * epochs / dt / mesh.num_workers,
        "sec_per_epoch": dt / epochs,
        "n_tokens": n_tok, "n_topics": n_topics,
        "prep_sec": prep, "num_workers": mesh.num_workers,
    }
    # Quality field for the flip gate (VERDICT r3 item 6): sampler/kernel
    # candidates must show equal chain quality before becoming defaults.
    # Host-side (numpy over all tokens + the full Ndk pull), so skipped at
    # ladder scale — 100M tokens would add minutes of host time and a
    # multi-GB relay pull to a timing run; the candidate configs that need
    # the gate all run at the 10M-token default shape.
    if n_tok <= 20_000_000:
        out["log_likelihood"] = model.log_likelihood()
    if algo == "pushpull":
        out["dropped_tokens"] = model.last_dropped  # pull_cap overflow
    return out


def main(argv=None):
    import argparse

    from harp_tpu.utils.metrics import benchmark_json

    p = argparse.ArgumentParser(description="harp-tpu LDA-CGS (edu.iu.lda parity)")
    p.add_argument("--docs", type=int, default=None,
                   help="default: 100000, or max doc id + 1 with --input")
    p.add_argument("--vocab", type=int, default=None,
                   help="default: 50000, or max word id + 1 with --input")
    p.add_argument("--topics", type=int, default=1000)
    p.add_argument("--tokens-per-doc", type=int, default=100)
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--algo",
                   choices=["dense", "scatter", "pushpull", "pallas"],
                   default="dense",
                   help="dense: one-hot MXU count updates (fastest, "
                        "default); scatter: direct scatter-add reference; "
                        "pushpull: row-sharded word-topic table, sparse "
                        "pull/push of touched rows (Harp's other edu.iu.lda "
                        "variant; for tables beyond one chip's HBM)")
    p.add_argument("--chunk", type=int, default=None,
                   help="scatter/pushpull: tokens per count-snapshot "
                        "(default 8192); errors under --algo dense")
    p.add_argument("--pull-cap", type=int, default=None,
                   help="pushpull-only: row-request slots per (worker, "
                        "owner) pair (default: chunk — zero drops; "
                        "LDA.suggest_pull_cap computes the exact "
                        "zero-drop cap for a loaded corpus)")
    p.add_argument("--no-dedup-pulls", action="store_true",
                   help="pushpull-only: disable collapsing duplicate "
                        "word rows to one wire slot per chunk (dedup is "
                        "on by default — Zipf corpora need far smaller "
                        "pull_cap with it)")
    p.add_argument("--sampler", choices=["gumbel", "exprace"],
                   default=None,
                   help="topic draw: gumbel (log-posterior + Gumbel "
                        "argmax, default) or exprace (exponential race — "
                        "identical distribution, ~5x fewer VPU "
                        "transcendentals; opt-in until TPU-measured)")
    p.add_argument("--rng-impl", choices=["threefry", "rbg"],
                   default=None,
                   help="random bits for the [token, K] draws: threefry "
                        "(default, splittable counter PRNG) or rbg (TPU "
                        "hardware generator, near-free; opt-in until "
                        "TPU-measured)")
    p.add_argument("--ndk-dtype", choices=["float32", "int16"],
                   default="float32",
                   help="doc-topic table dtype: int16 halves its HBM "
                        "(exact — counts bounded by doc length; the "
                        "enwiki-1M graded config needs 2 GB vs 4 GB)")
    p.add_argument("--d-tile", type=int, default=None,
                   help="dense-only: doc-topic tile rows (default 512)")
    p.add_argument("--w-tile", type=int, default=None,
                   help="dense-only: word-topic tile rows (default 512)")
    p.add_argument("--entry-cap", type=int, default=None,
                   help="dense-only: max tokens per tile entry (default 2048)")
    p.add_argument("--rotate-chunks", type=int, default=None,
                   help="rotation algos: word-slice chunks per worker in "
                        "the chunked rotation pipeline (default 2 — the "
                        "double-buffered two-halves schedule)")
    p.add_argument("--rotate-wire", choices=["exact", "bf16", "int8"],
                   default=None,
                   help="rotation algos: ring payload for in-flight "
                        "chunks (default exact; bf16/int8 halve/quarter "
                        "the rotate bytes, one rounding per hop)")
    p.add_argument("--ckpt-dir", default=None,
                   help="sample with checkpoint/resume instead of "
                        "benchmarking; rerunning with the same dir resumes "
                        "the chain from the latest saved epoch")
    p.add_argument("--ckpt-every", type=int, default=5)
    p.add_argument("--resume", action="store_true",
                   help="assert the run RESUMES from --ckpt-dir: fails "
                        "loudly when the dir holds no checkpoint (a "
                        "mistyped dir must not silently restart the "
                        "chain from epoch 0)")
    p.add_argument("--input", default=None, metavar="FILE_OR_GLOB",
                   help="token files ('doc word [count]' rows) — the Harp "
                        "app's HDFS input; implies sampling mode. --docs/"
                        "--vocab are raised to max id + 1 as needed")
    p.add_argument("--elastic", action="store_true",
                   help="elastic sampling (PR 15): consume mid-run "
                        "skew_trigger findings between sweeps (rebalance "
                        "doc packs, chain preserved) and checkpoint "
                        "mesh-independent state")
    p.add_argument("--max-worker-loss", type=int, default=0,
                   help="elastic: survive up to N permanent worker "
                        "losses by shrinking to the survivors and "
                        "replaying the repartition plan from the last "
                        "checkpoint (implies --elastic; needs --ckpt-dir "
                        "to actually resume)")
    args = p.parse_args(argv)
    from harp_tpu.utils.fault import resolve_resume

    resumed_from = resolve_resume(args.ckpt_dir, args.resume)
    if args.elastic or args.max_worker_loss:
        if args.input:
            raise SystemExit(
                "--elastic currently pairs with the synthetic corpus; "
                "use --docs/--vocab/--tokens-per-doc (file inputs ride "
                "the non-elastic fit)")
        from harp_tpu.elastic.apps import lda_elastic_fit

        n_docs, vocab = args.docs or 100_000, args.vocab or 50_000
        d_ids, w_ids = synthetic_corpus(n_docs, vocab,
                                        max(2, args.topics // 8),
                                        args.tokens_per_doc)
        ad = lda_elastic_fit(
            d_ids, w_ids, n_docs=n_docs, vocab_size=vocab,
            cfg=_make_cfg(args.topics, args.algo, args.chunk,
                          args.d_tile, args.w_tile, args.entry_cap,
                          args.pull_cap, args.ndk_dtype,
                          False if args.no_dedup_pulls else None,
                          args.sampler, args.rng_impl,
                          rotate_chunks=args.rotate_chunks,
                          rotate_wire=args.rotate_wire),
            epochs=args.epochs, ckpt_dir=args.ckpt_dir,
            ckpt_every=args.ckpt_every,
            max_worker_loss=max(args.max_worker_loss, 0))
        print(benchmark_json("lda_elastic_cli", {
            "epochs": args.epochs,
            "log_likelihood": round(ad.metric(), 4),
            "n_workers": ad.mesh.num_workers,
            "worker_losses": ad.losses, "ckpt_dir": args.ckpt_dir}))
        from harp_tpu.report import maybe_emit

        maybe_emit("lda")
        return
    if args.input or args.ckpt_dir:
        if args.input:
            from harp_tpu.native.datasource import load_triples_glob

            try:
                d_ids, w_ids, counts, has_counts = load_triples_glob(args.input)
            except ValueError as e:
                raise SystemExit(str(e))
            if int(d_ids.min()) < 0 or int(w_ids.min()) < 0:
                raise SystemExit(f"{args.input}: negative doc/word ids")
            if has_counts:
                # explicit count column: 0 means "absent" — drop, don't clamp
                reps = np.maximum(counts.astype(np.int64), 0)
            else:
                reps = np.ones(len(d_ids), np.int64)  # bare pair = one token
            d_ids = np.repeat(d_ids, reps)
            w_ids = np.repeat(w_ids, reps)
            if len(d_ids) == 0:
                raise SystemExit(f"{args.input}: all token counts are zero")
            # explicit sizes are raised to fit the data (as the help says)
            n_docs = max(args.docs or 0, int(d_ids.max()) + 1)
            vocab = max(args.vocab or 0, int(w_ids.max()) + 1)
        else:
            n_docs, vocab = args.docs or 100_000, args.vocab or 50_000
            d_ids, w_ids = synthetic_corpus(n_docs, vocab,
                                            max(2, args.topics // 8),
                                            args.tokens_per_doc)
        model = LDA(n_docs, vocab,
                    _make_cfg(args.topics, args.algo, args.chunk,
                              args.d_tile, args.w_tile, args.entry_cap,
                              args.pull_cap, args.ndk_dtype,
                              False if args.no_dedup_pulls else None,
                              args.sampler, args.rng_impl,
                              rotate_chunks=args.rotate_chunks,
                              rotate_wire=args.rotate_wire))
        model.set_tokens(d_ids, w_ids)
        model.fit(args.epochs, args.ckpt_dir, ckpt_every=args.ckpt_every)
        print(benchmark_json("lda_fit_cli", {
            "epochs": args.epochs, "ckpt_dir": args.ckpt_dir,
            "resumed_from": resumed_from,
            "log_likelihood": round(model.log_likelihood(), 4)}))
    else:
        print(benchmark_json("lda_cli", benchmark(
            args.docs or 100_000, args.vocab or 50_000, args.topics,
            args.tokens_per_doc, args.epochs, chunk=args.chunk,
            algo=args.algo, d_tile=args.d_tile,
            w_tile=args.w_tile, entry_cap=args.entry_cap,
            pull_cap=args.pull_cap, ndk_dtype=args.ndk_dtype,
            dedup_pulls=(False if args.no_dedup_pulls
                         else None), sampler=args.sampler,
            rng_impl=args.rng_impl,
            rotate_chunks=args.rotate_chunks,
            rotate_wire=args.rotate_wire)))
    from harp_tpu.report import maybe_emit

    maybe_emit("lda")


if __name__ == "__main__":
    main()
