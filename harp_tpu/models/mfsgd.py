"""MF-SGD (matrix factorization) — graded config #2: MovieLens-20M, rotate.

Reference parity (SURVEY.md §3.4, §4.3): Harp's ``edu.iu.sgd`` (and DAAL
variant ``edu.iu.daal_sgd``) factorizes the ratings matrix R ≈ W·Hᵀ with the
signature model-rotation pattern: each worker owns a user-range of R and W;
H is split into one slice per worker; slices travel the ring (``rotate``)
while ``edu.iu.dymoro.Rotator`` prefetches and a timer-bounded
``DynamicScheduler`` runs Hogwild-style SGD threads on the resident slice.

TPU-native design:
- Host preprocessing partitions the rating triples into an N×N grid of
  (user-range, item-slice) blocks, padded to a common size — the TPU
  analogue of Harp's per-worker rating store (static shapes for XLA).
- One epoch = ``rotate_pipeline`` over the H slices; at rotation step t a
  worker trains on the block matching its resident slice
  (``resident_slice_index``) — every rating is visited exactly once per
  epoch, just like Harp.
- Hogwild async updates become deterministic *mini-batched* SGD
  (SURVEY.md §8 hard parts).  Two formulations, selected by
  ``MFSGDConfig.algo``:

  * ``"dense"`` (default): each block re-tiles into (u_tile × i_tile)
    sub-tiles; row gathers AND duplicate-summing scatters are one-hot
    matmuls over ``dynamic_slice``\\ d W/H tiles — four MXU dots per entry,
    no XLA scatter anywhere.  TPU scatter of rank-64 rows moves ~25 GB/s;
    the same permutations as matmuls measured 84–102M updates/s/chip vs
    26.3M (ML-20M config, 1× v5e, 2026-07-30).
  * ``"scatter"``: direct ``lax.scan`` over fixed-size chunks with
    gather / scatter-add — the readable reference implementation, and the
    exact-equivalence target for the numpy golden tests.

  Convergence is validated by loss curve, not bitwise (the reference is
  nondeterministic anyway).
- The timer-bound lockstep is free: SPMD workers advance together.
"""

from __future__ import annotations

import dataclasses
import time
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from harp_tpu.ops.pallas_compat import interpret_default
from harp_tpu.parallel import collective as C
from harp_tpu.parallel.mesh import WorkerMesh, current_mesh
from harp_tpu.parallel.rotate import (ROTATE_WIRES, resident_chunk_index,
                                      rotate_pipeline)
from harp_tpu.utils import flightrec, prng, skew


@dataclasses.dataclass
class MFSGDConfig:
    rank: int = 64
    lr: float = 0.01
    reg: float = 0.05  # λ, applied to touched rows only (as SGD does)
    # Update algorithm.  "dense" (default) re-tiles each rating block into
    # (u_tile × i_tile) sub-tiles and runs every gather/scatter as a one-hot
    # MXU matmul over dynamic-sliced W/H tiles — no XLA scatter anywhere.
    # "scatter" is the direct gather/scatter-add formulation, kept as the
    # readable reference and for exact-equivalence tests.  Measured on the
    # ML-20M graded config (rank 64, 1× v5e, 2026-07-30): dense 84–102M
    # updates/s/chip vs scatter 26.3M — TPU scatter of 256 B rows runs at
    # ~25 GB/s while the same permutation as matmuls rides the MXU.
    # "pallas" fuses the dense entry update into one VMEM-resident kernel
    # (ops/mfsgd_kernel.py) — same data layout and update order as "dense",
    # minus the HBM round trips between XLA fusions; needs 128-multiple
    # tiles and rank % 8 == 0 on TPU.  FLIPPED to "pallas" 2026-08-01
    # (1× v5e, FLIP_DECISIONS.jsonl): 246.5M ups/s/chip at the swept
    # 256×256 auto-tiles vs 83.1M dense = 2.97× at identical rmse_final
    # (0.366, silicon-equivalence-gated; 188.1M = 2.26× pre-sweep at
    # 512 tiles); the trace shows the kernel absorbing the one-hot
    # operand traffic that made dense memory-bound at ~11% of HBM peak.
    algo: str = "pallas"
    # Tiling, auto per algo (None).  dense: 512×512 measured best on v5e
    # (84–102M ups vs 60–80M at 1024/2048 — one-hot traffic grows with
    # tile width and dominates before scan-step overhead does).  pallas:
    # 256×256 measured best 2026-08-01 (SWEEP_pallas.jsonl, 1× v5e,
    # ML-20M shapes, identical rmse_final 0.366): 250.2M ups/s vs
    # 195.5M at 512 and 147.3M at 128 — the kernel keeps one-hots in
    # VMEM, so smaller W/H tiles (less slice traffic per entry) win
    # until grid overhead bites.
    # None = auto, resolved at READ time by :func:`tiles` — not baked in
    # at construction, so ``dataclasses.replace(cfg, algo=...)`` keeps
    # the auto default tracking the new algo instead of freezing the
    # old algo's resolved value (review finding, round 5).
    u_tile: int | None = None
    i_tile: int | None = None
    # max ratings per dense entry; overfull tiles split into several entries
    # (keeps padding bounded under power-law item skew)
    entry_cap: int = 2048
    # dense matmul operand dtype: bf16 is MXU-native (gather/scatter one-hots
    # are exact 0/1 either way; W/H operands round to bf16 — noise well under
    # SGD's own stochasticity, validated by the convergence tests).  Golden
    # tests pin float32 to match numpy bit-for-bit on CPU.
    compute_dtype: Any = jnp.bfloat16
    # scatter algo: minibatch size inside a block; 32768 measured best on
    # 1× v5e (26.3M vs 14.4M ups/chip at 8192, identical RMSE).  Small
    # datasets are safe: blocks narrower than this clamp themselves
    # (partition_ratings pads only to the real max block size).
    chunk: int = 32768
    # algo="dense" only: carry the W tile across its tou-run instead of
    # slice+DUS per entry (the LDA carry_db lever — entries are u-major,
    # so a hot W block's entries currently re-pay the [u_tile, r] in+out
    # per entry).  The pallas kernel already keeps W resident across its
    # block runs, so this applies to the XLA path alone.  MEASURED
    # 2026-08-01 (1× v5e): 1.01× vs dense — no win (the analytic 20%
    # byte saving is hidden behind other traffic) — and the kernel flip
    # supersedes the slot anyway; stays OFF.
    carry_w: bool = False
    # Rotation pipeline knobs (the chunked double-buffered rotator,
    # parallel/rotate.py).  rotate_chunks: H sub-slices per worker that
    # alternate compute/in-flight roles — None = auto (2: the historical
    # two-halves schedule; the generic pipeline at 2 chunks is
    # equivalence-pinned against it by tests/test_rotate_chunked.py).
    # More chunks shrink each ring transfer and expose finer overlap at
    # the cost of more scan steps — flip candidate `mfsgd_chunked_rotate`
    # measures 4 on the relay; default stays 2 until flip_decision says
    # FLIP.  None = auto, resolved at READ time by
    # :func:`rotate_chunks_resolved` (same contract as :func:`tiles`).
    rotate_chunks: int | None = None
    # Ring payload for the in-flight chunk: "exact" (default — bit-exact
    # f32 ppermute), "bf16" or "int8" (collective.rotate_quantized: one
    # rounding per hop, ring-size-independent — noise of the same order
    # as SGD's own stochasticity, but the default stays exact until a
    # relay measurement flips it).
    rotate_wire: str = "exact"

    def __post_init__(self):
        if self.algo not in ("dense", "scatter", "pallas"):
            raise ValueError(
                f"algo must be 'dense', 'scatter' or 'pallas', got {self.algo!r}")
        if self.carry_w and self.algo != "dense":
            raise ValueError(
                "carry_w applies to algo='dense' only (the pallas kernel "
                "already keeps W resident across its block runs; scatter "
                "has no tile slicing to amortize)")
        if self.rotate_chunks is not None and self.rotate_chunks < 1:
            raise ValueError(
                f"rotate_chunks must be >= 1, got {self.rotate_chunks}")
        if self.rotate_wire not in ROTATE_WIRES:
            raise ValueError(
                f"rotate_wire must be one of {ROTATE_WIRES}, "
                f"got {self.rotate_wire!r}")


def tiles(cfg: MFSGDConfig) -> tuple[int, int]:
    """Resolved ``(u_tile, i_tile)`` — None means auto per algo.

    pallas: 256×256 (measured best 2026-08-01, SWEEP_pallas.jsonl, 1×
    v5e ML-20M: 250.2M ups/s vs 195.5M@512 / 163.3M@1024 / 147.3M@128,
    identical rmse — smaller tiles win inside the kernel because the
    one-hots never leave VMEM, until grid overhead bites).  dense: 512
    (measured best vs 1024/2048, 2026-07-30).
    """
    auto = 256 if cfg.algo == "pallas" else 512
    return (cfg.u_tile if cfg.u_tile is not None else auto,
            cfg.i_tile if cfg.i_tile is not None else auto)


def rotate_chunks_resolved(cfg) -> int:
    """Resolved rotation chunk count — ``None`` means the incumbent 2
    (the two-halves schedule both rotation models shipped with).  Read-time
    resolution (not ``__post_init__``) so ``dataclasses.replace`` keeps the
    auto default, mirroring :func:`tiles`; shared with
    :class:`harp_tpu.models.lda.LDAConfig` (same field, same contract)."""
    return cfg.rotate_chunks if cfg.rotate_chunks is not None else 2


# ---------------------------------------------------------------------------
# Host preprocessing: triples → N×N padded block grid.
# ---------------------------------------------------------------------------

def partition_ratings(users, items, vals, n_users, n_items, n_workers, chunk,
                      n_slices: int | None = None):
    """Partition rating triples into the (user-range × item-slice) grid.

    ``n_slices`` defaults to ``2 * n_workers`` — two half-slices per worker,
    the incumbent double-buffer depth; the chunked epoch passes
    ``rotate_chunks * n_workers`` (one slice per rotation chunk).

    Returns per-worker arrays ``u[S, B], i[S, B], v[S, B], mask[S, B]`` with
    user/item ids **local** to their range/slice, stacked worker-major so
    dim 0 shards over the mesh (worker w's row is its ``[n_slices, B]``
    grid).  B is the global max block size rounded up to ``chunk``.

    (Harp stores the same thing as per-worker rating lists keyed by the H
    partition id; padding replaces the dynamic per-block sizes because XLA
    needs static shapes.)
    """
    users = np.asarray(users)
    items = np.asarray(items)
    vals = np.asarray(vals, dtype=np.float32)
    n = n_workers
    ns = n_slices if n_slices is not None else 2 * n
    u_bound = -(-n_users // n)  # users per range (ceil)
    i_bound = -(-n_items // ns)  # items per slice

    wid = users // u_bound  # owning worker (user range)
    sid = items // i_bound  # item slice

    # bucket sort triples by (worker, slice)
    order = np.lexsort((items, sid, wid))
    users, items, vals, wid, sid = (
        a[order] for a in (users, items, vals, wid, sid)
    )
    counts = np.zeros((n, ns), np.int64)
    np.add.at(counts, (wid, sid), 1)
    bmax = int(counts.max())
    if bmax >= chunk:
        B = -(-bmax // chunk) * chunk  # pad to chunk multiple
    else:
        # small data: don't pad every block up to a full chunk (400× waste
        # at the tuned 32768 default on 10k-rating datasets) — one
        # sublane-aligned sub-chunk suffices; the device side clamps its
        # scan chunk to the block width (see _block_update).  Cap at chunk:
        # sublane alignment may otherwise overshoot it when chunk % 8 != 0,
        # and the device reshape needs B % min(chunk, B) == 0.
        B = min(chunk, max(8, -(-bmax // 8) * 8))

    u = np.zeros((n, ns, B), np.int32)
    i = np.zeros((n, ns, B), np.int32)
    v = np.zeros((n, ns, B), np.float32)
    m = np.zeros((n, ns, B), np.float32)
    starts = np.zeros((n, ns), np.int64)
    starts.flat[1:] = counts.cumsum()[:-1]
    for w in range(n):
        for s in range(ns):
            lo, c = starts[w, s], counts[w, s]
            sl = slice(lo, lo + c)
            u[w, s, :c] = users[sl] - w * u_bound
            i[w, s, :c] = items[sl] - s * i_bound
            v[w, s, :c] = vals[sl]
            m[w, s, :c] = 1.0
    return (
        u.reshape(n * ns, B), i.reshape(n * ns, B),
        v.reshape(n * ns, B), m.reshape(n * ns, B),
        u_bound, i_bound,
    )


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


def _dense_bounds(n_users, n_items, n_workers, n_slices, u_tile, i_tile):
    """Bounds for the dense algo, shared by partitioner and driver.

    Ownership (``u_own``/``i_own``) stays UNROUNDED — the same balanced
    ``id // ceil(size/N)`` placement Harp's partitioner and the scatter
    algo use; rounding ownership to tile multiples would dump every row
    on worker 0 whenever ``ceil(size/N) < tile``.  Storage per worker
    (``u_bound``/``ib2``) rounds up to tile multiples so dynamic slices
    are always full-size; the pad rows own no ids and stay untrained.
    """
    u_own = _ceil_div(n_users, n_workers)
    i_own = _ceil_div(n_items, n_slices)
    u_bound = u_tile * _ceil_div(u_own, u_tile)
    ib2 = i_tile * _ceil_div(i_own, i_tile)
    return u_own, i_own, u_bound, ib2


def partition_ratings_tiles(users, items, vals, n_users, n_items, n_workers,
                            u_tile, i_tile, entry_cap, n_slices=None):
    """Partition triples into dense (u_tile × i_tile) sub-tiles per
    (worker, half-slice) block — the layout the "dense" algo consumes.

    Each *entry* is up to ``entry_cap`` ratings of one sub-tile (overfull
    tiles split into several entries, so power-law item skew cannot blow up
    the padding).  Returns worker-major stacked arrays

    ``eu/ei/ev [n*ns, NE, C]`` — ids local to their tile (pad id = tile
    width, which one-hot maps to an all-zero row), values;
    ``ou/oi [n*ns, NE]`` — tile row offsets into the worker's W range /
    the resident half-slice;
    plus ``(u_own, i_own, u_bound, ib2)`` from :func:`_dense_bounds`
    (balanced ownership sizes + tile-rounded storage sizes).
    """
    users = np.asarray(users)
    items = np.asarray(items)
    vals = np.asarray(vals, dtype=np.float32)
    n = n_workers
    ns = n_slices if n_slices is not None else 2 * n
    u_own, i_own, u_bound, ib2 = _dense_bounds(
        n_users, n_items, n, ns, u_tile, i_tile)

    wid = users // u_own
    sid = items // i_own
    lu = users - wid * u_own
    li = items - sid * i_own
    tu = lu // u_tile
    ti = li // i_tile
    ntu, nti = u_bound // u_tile, ib2 // i_tile

    # global tile id, sorted so each (worker, slice) lists tiles u-major
    gtile = ((wid * ns + sid) * ntu + tu) * nti + ti
    order = np.argsort(gtile, kind="stable")
    lu, li, vals, gtile = lu[order], li[order], vals[order], gtile[order]

    n_tiles = n * ns * ntu * nti
    counts = np.bincount(gtile, minlength=n_tiles)
    C = int(min(entry_cap, max(8, 8 * _ceil_div(int(counts.max(initial=0)), 8))))
    ent_per_tile = _ceil_div(counts, C)  # elementwise ceil; 0 for empty tiles
    ws_of_tile = np.arange(n_tiles) // (ntu * nti)
    NE = max(1, int(np.bincount(ws_of_tile, weights=ent_per_tile,
                                minlength=n * ns).max()))

    eu = np.full((n * ns, NE, C), u_tile, np.int32)
    ei = np.full((n * ns, NE, C), i_tile, np.int32)
    ev = np.zeros((n * ns, NE, C), np.float32)
    ou = np.zeros((n * ns, NE), np.int32)
    oi = np.zeros((n * ns, NE), np.int32)
    starts = np.zeros(n_tiles, np.int64)
    starts[1:] = counts.cumsum()[:-1]
    e_next = np.zeros(n * ns, np.int64)
    # Deliberately a per-entry loop: it copies CONTIGUOUS slices of the
    # tile-sorted data (memcpy-speed, ~15k iterations at ML-20M).  A fully
    # vectorized fancy-index formulation measured 2× SLOWER (12.6 s vs
    # 6.3 s, 2026-07-30) — five 20M-element bounds-checked scatters beat
    # no Python loop but lose to 15k memcpys.
    for t in np.nonzero(counts)[0]:
        ws = t // (ntu * nti)
        t_u = (t // nti) % ntu
        t_i = t % nti
        lo, cnt = int(starts[t]), int(counts[t])
        for off in range(0, cnt, C):
            e = int(e_next[ws])
            e_next[ws] = e + 1
            c = min(C, cnt - off)
            sl = slice(lo + off, lo + off + c)
            eu[ws, e, :c] = lu[sl] - t_u * u_tile
            ei[ws, e, :c] = li[sl] - t_i * i_tile
            ev[ws, e, :c] = vals[sl]
            ou[ws, e] = t_u * u_tile
            oi[ws, e] = t_i * i_tile
    return eu, ei, ev, ou, oi, u_own, i_own, u_bound, ib2


# ---------------------------------------------------------------------------
# Device compute.
# ---------------------------------------------------------------------------

def _chunk_update(W, H, batch, cfg: MFSGDConfig):
    """One deterministic minibatch SGD step on (W, H-slice).

    Gradients of ½Σ m(r − w·h)² + ½λΣ(‖w‖²+‖h‖²) over the chunk; duplicate
    rows get summed gradients (scatter-add), the batched stand-in for
    Harp's sequential Hogwild updates.
    """
    bu, bi, bv, bm = batch
    wu = jnp.take(W, bu, axis=0)          # [c, r]
    hi = jnp.take(H, bi, axis=0)          # [c, r]
    err = bm * (bv - (wu * hi).sum(-1))   # [c]
    gw = err[:, None] * hi - cfg.reg * bm[:, None] * wu
    gh = err[:, None] * wu - cfg.reg * bm[:, None] * hi
    W = W.at[bu].add(cfg.lr * gw, mode="drop")
    H = H.at[bi].add(cfg.lr * gh, mode="drop")
    return W, H, (err * err).sum(), bm.sum()


def _block_update(W, H, block, cfg: MFSGDConfig):
    """Scan minibatch chunks over one (user-range × item-slice) block.

    The effective chunk is clamped to the (static) block width — small
    datasets produce blocks narrower than ``cfg.chunk`` (see
    ``partition_ratings``), which then run as a single minibatch.
    """
    bu, bi, bv, bm = block
    c = min(cfg.chunk, bu.shape[0])
    nchunk = bu.shape[0] // c
    chunks = jax.tree.map(lambda a: a.reshape(nchunk, c), (bu, bi, bv, bm))

    def body(carry, chunk):
        W, H, se, cnt = carry
        W, H, dse, dcnt = _chunk_update(W, H, chunk, cfg)
        return (W, H, se + dse, cnt + dcnt), None

    (W, H, se, cnt), _ = lax.scan(
        body, (W, H, jnp.float32(0.0), jnp.float32(0.0)), chunks
    )
    return W, H, se, cnt


def _entry_tiles_update(Wb, Hb, cu, ci, cv, cfg: MFSGDConfig):
    """Tile-level core of :func:`_tile_block_update`: one entry's update on
    pre-sliced ``Wb [u_tile, r]`` / ``Hb [i_tile, r]`` — no table slicing
    here, so the ``carry_w`` path can keep a W tile resident across its
    u-run (slicing strategy is the caller's concern; shared math keeps
    carry and non-carry chains bit-identical)."""
    UR, IR = tiles(cfg)
    cd = cfg.compute_dtype
    dot = partial(lax.dot_general, preferred_element_type=jnp.float32)
    ohu = jax.nn.one_hot(cu, UR, dtype=cd)          # [C, UR]
    ohi = jax.nn.one_hot(ci, IR, dtype=cd)          # [C, IR]
    wu = dot(ohu, Wb.astype(cd), (((1,), (0,)), ((), ())))  # gather
    hi = dot(ohi, Hb.astype(cd), (((1,), (0,)), ((), ())))
    cm = (cu < UR).astype(jnp.float32)
    err = cm * (cv - (wu * hi).sum(-1))
    gw = (err[:, None] * hi - cfg.reg * cm[:, None] * wu).astype(cd)
    gh = (err[:, None] * wu - cfg.reg * cm[:, None] * hi).astype(cd)
    gW = dot(ohu, gw, (((0,), (0,)), ((), ())))     # scatter-add
    gH = dot(ohi, gh, (((0,), (0,)), ((), ())))
    return (Wb + cfg.lr * gW, Hb + cfg.lr * gH,
            (err * err).sum(), cm.sum())


def carry_tile_switch(table, tile, cur, new_off, size, ax):
    """Run-carry tile switch shared by MF-SGD ``carry_w`` and LDA
    ``carry_db``: on an offset change, flush the carried tile back into
    the table BEFORE slicing the new region, so the result equals the
    slice-per-entry path even for overlapping (non-tile-aligned) offsets
    — not just the aligned ones current partitioners emit (ADVICE r4;
    overlap pinned by test_carry_w_exact_for_overlapping_tile_offsets).
    An unchanged offset pays zero tile HBM traffic via the ``lax.cond``.
    """
    def switch(opr):
        table, tile, cur = opr
        table = lax.dynamic_update_slice_in_dim(table, tile, cur, ax)
        new = lax.dynamic_slice_in_dim(table, new_off, size, ax)
        return table, new, new_off

    return lax.cond(new_off != cur, switch, lambda opr: opr,
                    (table, tile, cur))


def _tile_block_update(W, H, block, cfg: MFSGDConfig):
    """Scan dense-tile entries of one (user-range × item-half-slice) block.

    Per entry (≤ entry_cap ratings, all inside one u_tile × i_tile sub-tile):
    gather W/H tile rows by ``dynamic_slice``, run BOTH the row gather and
    the duplicate-summing scatter as one-hot matmuls — four MXU dots, zero
    XLA scatters.  Pad ids equal the tile width, so their one-hot rows are
    all-zero and they drop out of every product.

    ``cfg.carry_w``: entries are u-major (partition_ratings_tiles), so the
    W tile is carried across its tou-run and flushed/loaded only on a
    tou-change ``lax.cond`` — the LDA ``carry_db`` lever applied here
    (the switch always flushes before a region can be re-sliced, so this
    is exact under any entry order; bit-identical chains tested).
    """
    eu, ei, ev, ou, oi = block
    UR, IR = tiles(cfg)

    if cfg.carry_w:
        def body(carry, xs):
            W, H, se, cnt, wb, cur = carry
            cu, ci, cv, tou, toi = xs

            W, wb, cur = carry_tile_switch(W, wb, cur, tou, UR, 0)
            Hb = lax.dynamic_slice_in_dim(H, toi, IR, 0)
            wb, Hb, dse, dcnt = _entry_tiles_update(wb, Hb, cu, ci, cv, cfg)
            H = lax.dynamic_update_slice_in_dim(H, Hb, toi, 0)
            return (W, H, se + dse, cnt + dcnt, wb, cur), None

        wb0 = lax.dynamic_slice_in_dim(W, ou[0], UR, 0)
        (W, H, se, cnt, wb_f, cur_f), _ = lax.scan(
            body, (W, H, jnp.float32(0.0), jnp.float32(0.0), wb0, ou[0]),
            (eu, ei, ev, ou, oi))
        W = lax.dynamic_update_slice_in_dim(W, wb_f, cur_f, 0)
        return W, H, se, cnt

    def body(carry, xs):
        W, H, se, cnt = carry
        cu, ci, cv, tou, toi = xs
        Wb = lax.dynamic_slice_in_dim(W, tou, UR, 0)
        Hb = lax.dynamic_slice_in_dim(H, toi, IR, 0)
        Wb, Hb, dse, dcnt = _entry_tiles_update(Wb, Hb, cu, ci, cv, cfg)
        W = lax.dynamic_update_slice_in_dim(W, Wb, tou, 0)
        H = lax.dynamic_update_slice_in_dim(H, Hb, toi, 0)
        return (W, H, se + dse, cnt + dcnt), None

    (W, H, se, cnt), _ = lax.scan(
        body, (W, H, jnp.float32(0.0), jnp.float32(0.0)), (eu, ei, ev, ou, oi)
    )
    return W, H, se, cnt


def _pallas_tile_block_update(W, H, block, cfg: MFSGDConfig):
    """Fused-kernel twin of :func:`_tile_block_update` (same entries, same
    order — see ops/mfsgd_kernel.py).  Factors transpose to rank-major at
    the block boundary; ~0.3 ms/epoch of HBM traffic at ML-20M scale."""
    from harp_tpu.ops.mfsgd_kernel import sgd_tile_update

    eu, ei, ev, ou, oi = block
    Wt, Ht, se, cnt = sgd_tile_update(
        W.T, H.T, eu, ei, ev, ou, oi,
        lr=cfg.lr, reg=cfg.reg, u_tile=tiles(cfg)[0], i_tile=tiles(cfg)[1],
        compute_dtype=cfg.compute_dtype,
        interpret=interpret_default())
    return Wt.T, Ht.T, se, cnt


_UPDATERS = {"dense": _tile_block_update, "scatter": _block_update,
             "pallas": _pallas_tile_block_update}

#: algos that consume the dense (u_tile × i_tile) entry layout
_DENSE_ALGOS = ("dense", "pallas")


def _epoch_device_fn(mesh: WorkerMesh, cfg: MFSGDConfig):
    """Build the device-view epoch callable (every rating visited once).

    This is the dymoro pipeline done the XLA way (SURVEY.md §4.3), on the
    generic chunked rotator: each worker's H slice splits into
    ``rotate_chunks_resolved(cfg)`` sub-slices that alternate compute /
    in-flight roles inside :func:`rotate_pipeline` — the chunk updated at
    step t-1 rides a ``ppermute`` with no data dependency on step t's
    compute, so XLA's async scheduler overlaps transfer with compute,
    while a whole-slice rotation would serialize (a mutated slice cannot
    leave before its update finishes — the constraint Harp's Rotator also
    has, which is why dymoro prefetches *next* slices rather than sending
    current ones).  The 2-chunk default IS the former bespoke two-halves
    schedule (n workers, 2n half-slices, 2n steps/epoch; equivalence
    pinned by the numpy goldens + tests/test_rotate_chunked.py);
    ``cfg.rotate_wire`` narrows the ring payload.
    """
    nc = rotate_chunks_resolved(cfg)
    update = _UPDATERS[cfg.algo]

    def epoch(W, H_slice, *blocks):
        # block arrays arrive as this worker's [nc·n chunk-slices, ...] row
        def step(st, chunk, t):
            W, se, cnt = st
            block = jax.tree.map(
                lambda a: a[resident_chunk_index(t, nc)], blocks)
            W, chunk, dse, dcnt = update(W, chunk, block, cfg)
            return (W, se + dse, cnt + dcnt), chunk

        (W, se, cnt), H_slice = rotate_pipeline(
            step, (W, jnp.float32(0.0), jnp.float32(0.0)), H_slice,
            n_chunks=nc, wire=cfg.rotate_wire)
        # per-worker visited-rating count BEFORE the psum — the skew
        # spine's execution counter (utils/skew.py), folded into the
        # epoch outputs so the driver's ONE stacked readback carries it
        # (flight budgets unchanged, tests/test_flightrec.py).
        work_w = C.allgather(cnt[None])
        # loss partials are per-worker; combine before leaving SPMD (the
        # optional end-of-epoch allreduce-RMSE in Harp's MF-SGD loop)
        se, cnt = C.allreduce((se, cnt))
        return W, H_slice, se, cnt, work_w

    return epoch


def _n_block_args(cfg: MFSGDConfig) -> int:
    return 5 if cfg.algo in _DENSE_ALGOS else 4


def make_epoch_fn(mesh: WorkerMesh, cfg: MFSGDConfig):
    """Compile one full rotation epoch — see :func:`_epoch_device_fn`."""
    return jax.jit(
        mesh.shard_map(
            _epoch_device_fn(mesh, cfg),
            in_specs=(mesh.spec(0),) * (2 + _n_block_args(cfg)),
            out_specs=(mesh.spec(0), mesh.spec(0), P(), P(), P()),
        )
    )


def make_multi_epoch_fn(mesh: WorkerMesh, cfg: MFSGDConfig, epochs: int):
    """Compile ``epochs`` rotation epochs as ONE device program.

    A single dispatch instead of one per epoch: host→device dispatch on a
    relay-attached chip costs ~150 ms/call (measured 2026-07-30, v5e),
    which at 186 ms of device time per ML-20M epoch nearly halves the
    apparent throughput of per-epoch calls.  Returns per-epoch
    ``(se[epochs], cnt[epochs])`` alongside the final W/H.
    """
    inner = _epoch_device_fn(mesh, cfg)

    def many(W, H_slice, *blocks):
        def body(carry, _):
            W, H = carry
            W, H, se, cnt, work = inner(W, H, *blocks)
            return (W, H), (se, cnt, work)

        (W, H_slice), (ses, cnts, works) = lax.scan(
            body, (W, H_slice), None, length=epochs)
        # per-sweep work vectors are identical — the last one suffices
        return W, H_slice, ses, cnts, works[-1]

    return jax.jit(
        mesh.shard_map(
            many,
            in_specs=(mesh.spec(0),) * (2 + _n_block_args(cfg)),
            out_specs=(mesh.spec(0), mesh.spec(0), P(), P(), P()),
        )
    )


class MFSGD:
    """Host driver (the ``mapCollective`` residue for edu.iu.sgd)."""

    def __init__(self, n_users, n_items, cfg: MFSGDConfig | None = None,
                 mesh: WorkerMesh | None = None, seed=0):
        self.mesh = mesh or current_mesh()
        self.cfg = cfg or MFSGDConfig()
        self.n_users, self.n_items = n_users, n_items
        n = self.mesh.num_workers
        nc = rotate_chunks_resolved(self.cfg)
        # rotate_chunks chunk-slices per worker (pipelined rotation)
        self._n_slices = nc * n
        if self.cfg.algo in _DENSE_ALGOS:
            self.u_own, self.i_own, self.u_bound, ibc = _dense_bounds(
                n_users, n_items, n, self._n_slices, *tiles(self.cfg))
            self.i_bound = nc * ibc
        else:
            self.u_bound = self.u_own = _ceil_div(n_users, n)
            self.i_bound = nc * _ceil_div(n_items, self._n_slices)
            self.i_own = self.i_bound // nc
        # raw key bits (utils.prng): a fresh seed must not cost a fresh
        # (remote) compile — CLAUDE.md PRNGKey-specialization trap
        k1, k2 = jax.random.split(jnp.asarray(prng.key_bits(seed)))
        scale = 1.0 / np.sqrt(self.cfg.rank)
        self.W = self.mesh.shard_array(
            np.asarray(jax.random.uniform(k1, (self.u_bound * n, self.cfg.rank),
                                          jnp.float32, 0, scale)), 0)
        self.H = self.mesh.shard_array(
            np.asarray(jax.random.uniform(k2, (self.i_bound * n, self.cfg.rank),
                                          jnp.float32, 0, scale)), 0)
        self._epoch_fn = flightrec.track(make_epoch_fn(self.mesh, self.cfg),
                                         "mfsgd.epoch")
        self._multi_fns: dict[int, Any] = {}
        self._blocks = None
        # movable pack grains for the skew spine's execution records
        # (PR 15): the elastic driver sets per-worker [(pack_id, load)]
        # lists here so the health sentinel's skew_trigger carries a
        # whole-unit, apply_rebalance-replayable plan.  None (default)
        # keeps the PR-4 per-worker-only records.
        self.skew_units = None

    def set_ratings(self, users, items, vals):
        from harp_tpu.utils import telemetry

        n = self.mesh.num_workers
        nc = rotate_chunks_resolved(self.cfg)
        if self.cfg.algo in _DENSE_ALGOS:
            eu, ei, ev, ou, oi, uo, io, ub, ibc = partition_ratings_tiles(
                users, items, vals, self.n_users, self.n_items, n,
                *tiles(self.cfg), self.cfg.entry_cap,
                n_slices=self._n_slices,
            )
            assert (uo, io) == (self.u_own, self.i_own)
            if telemetry.enabled():
                # ingest skew record from the REAL ratings (before the
                # pallas coverage entries, which carry no rating mass)
                valid = eu < tiles(self.cfg)[0]
                skew.record_partition(
                    "mfsgd.partition", valid.reshape(n, -1).sum(1),
                    unit="ratings", padded_total=valid.size)
            if self.cfg.algo == "pallas":
                from harp_tpu.ops.mfsgd_kernel import insert_coverage_entries

                eu, ei, ev, ou, oi = insert_coverage_entries(
                    eu, ei, ev, ou, oi, ub, tiles(self.cfg)[0])
            blocks = (eu, ei, ev, ou, oi)
        else:
            bu, bi, bv, bm, ub, ibc = partition_ratings(
                users, items, vals, self.n_users, self.n_items, n,
                self.cfg.chunk, n_slices=self._n_slices,
            )
            if telemetry.enabled():
                skew.record_partition(
                    "mfsgd.partition", (bm > 0).reshape(n, -1).sum(1),
                    unit="ratings", padded_total=bm.size)
            blocks = (bu, bi, bv, bm)
        assert (ub, nc * ibc) == (self.u_bound, self.i_bound)
        self._blocks = tuple(self.mesh.shard_array(a, 0) for a in blocks)
        self._multi_fns.clear()  # compiled executables bind to block shapes
        self.nnz = len(np.asarray(vals))

    def train_epoch(self):
        """One rotation epoch; returns training RMSE over visited ratings."""
        if self._blocks is None:
            raise RuntimeError("call set_ratings() before train_epoch()")
        from harp_tpu.utils import telemetry

        with telemetry.span("mfsgd.epoch"), \
                telemetry.ledger.run("mfsgd.epochs", steps=1):
            t0 = time.perf_counter()
            self.W, self.H, se, cnt, work_w = self._epoch_fn(
                self.W, self.H, *self._blocks)
            # one stacked readback, not one per scalar (readbacks
            # budget); the per-worker work vector rides the same fetch
            stats = flightrec.readback(
                jnp.concatenate([jnp.stack([se, cnt]), work_w]))
            skew.record_execution("mfsgd.epochs", stats[2:],
                                  unit="ratings",
                                  wall_s=time.perf_counter() - t0,
                                  units=self.skew_units)
            return float(np.sqrt(max(float(stats[0]), 0.0)
                                 / max(float(stats[1]), 1.0)))

    def compile_epochs(self, epochs: int):
        """AOT-compile the ``epochs``-epoch program WITHOUT running it.

        ``.lower().compile()`` is side-effect-free — benchmark warmup must
        not secretly train extra epochs, or the reported RMSE describes a
        different model than the epoch count claims.  The compiled
        executable is cached and reused by :meth:`train_epochs`.
        """
        if self._blocks is None:
            raise RuntimeError("call set_ratings() before compile_epochs()")
        fn = self._multi_fns.get(epochs)
        if fn is None:
            from harp_tpu.utils import telemetry

            jitted = make_multi_epoch_fn(self.mesh, self.cfg, epochs)
            # steps=0: lowering traces the comm sites (attributed to the
            # same tag the executions count under) without executing them
            with telemetry.ledger.run("mfsgd.epochs", steps=0):
                fn = self._multi_fns[epochs] = flightrec.track(
                    jitted.lower(self.W, self.H, *self._blocks).compile(),
                    "mfsgd.epochs")
        return fn

    def train_epochs(self, epochs: int):
        """Run ``epochs`` epochs as one device program; returns per-epoch RMSEs.

        One host→device dispatch instead of ``epochs`` (~150 ms/call saved
        on the relay-attached v5e, measured 2026-07-30 — see
        :func:`make_multi_epoch_fn`).  Use
        ``fit()`` instead when checkpointing between epochs.
        """
        from harp_tpu.utils import telemetry

        fn = self.compile_epochs(epochs)
        # the scan body's traced comm sites execute once per epoch
        with telemetry.span("mfsgd.epochs", epochs=epochs), \
                telemetry.ledger.run("mfsgd.epochs", steps=epochs):
            t0 = time.perf_counter()
            self.W, self.H, ses, cnts, work_w = fn(self.W, self.H,
                                                   *self._blocks)
            # ONE stacked readback for all epochs' stats (the ccd.py
            # idiom) — the flight-recorder budget for this loop pins
            # readbacks=1 per run, not one per stat array; the
            # per-worker work vector rides the same fetch (skew spine)
            stats = flightrec.readback(
                jnp.concatenate([ses, cnts, work_w]))
            skew.record_execution("mfsgd.epochs", stats[2 * epochs:],
                                  unit="ratings",
                                  wall_s=time.perf_counter() - t0,
                                  units=self.skew_units)
            ses, cnts = stats[:epochs], stats[epochs:2 * epochs]
        return [float(np.sqrt(max(s, 0.0) / max(c, 1.0)))
                for s, c in zip(ses, cnts)]

    def fit(self, epochs: int, ckpt_dir: str | None = None, *,
            ckpt_every: int = 5, max_restarts: int = 3, fault=None):
        """Train with optional checkpoint/resume — the SURVEY.md §6 driver.

        With ``ckpt_dir`` set, epochs checkpoint every ``ckpt_every`` and a
        crashed run (or a rerun pointing at the same dir) resumes from the
        latest saved epoch instead of epoch 0 — Harp's YARN whole-job retry,
        upgraded.  Returns the per-epoch RMSE list for the epochs this call
        actually ran.
        """
        from harp_tpu.utils.fault import factor_state_io, fit_epochs

        rmses: list[float] = []
        get_state, set_state = factor_state_io(self, {
            "W": lambda a: self.mesh.shard_array(a, 0),
            "H": lambda a: self.mesh.shard_array(a, 0),
        })
        fit_epochs(
            lambda: rmses.append(self.train_epoch()),
            get_state, set_state,
            epochs, ckpt_dir, ckpt_every=ckpt_every,
            max_restarts=max_restarts, fault=fault,
            phase="mfsgd.epochs",
        )
        return rmses

    def factors(self):
        """Global (W, H) with storage padding stripped.

        Dense storage pads each worker's W range (and each half-slice's H
        range) to a tile multiple; user ``g`` lives at row
        ``(g // u_own) * u_bound + g % u_own``, so the pad rows must be cut
        per range, not just at the tail.
        """
        n = self.mesh.num_workers
        W = np.asarray(self.W)
        H = np.asarray(self.H)
        if self.cfg.algo in _DENSE_ALGOS:
            nc = rotate_chunks_resolved(self.cfg)
            r = W.shape[-1]
            W = W.reshape(n, self.u_bound, r)[:, : self.u_own].reshape(-1, r)
            ibc = self.i_bound // nc
            H = H.reshape(nc * n, ibc, r)[:, : self.i_own].reshape(-1, r)
        return W[: self.n_users], H[: self.n_items]

    def predict_rmse(self, users, items, vals):
        W, H = self.factors()
        pred = (W[np.asarray(users)] * H[np.asarray(items)]).sum(-1)
        return float(np.sqrt(np.mean((pred - np.asarray(vals)) ** 2)))


# ---------------------------------------------------------------------------
# Synthetic MovieLens-20M-shaped data + benchmark.
# ---------------------------------------------------------------------------

def synthetic_ratings(n_users, n_items, nnz, rank=8, noise=0.1, seed=0):
    """Low-rank ground truth + noise, uniform random (u, i) pairs."""
    rng = np.random.default_rng(seed)
    Wt = rng.normal(size=(n_users, rank)) / np.sqrt(rank)
    Ht = rng.normal(size=(n_items, rank)) / np.sqrt(rank)
    u = rng.integers(0, n_users, nnz)
    i = rng.integers(0, n_items, nnz)
    v = (Wt[u] * Ht[i]).sum(-1) + noise * rng.normal(size=nnz)
    return u.astype(np.int32), i.astype(np.int32), v.astype(np.float32)


def algo_kwargs(algo: str, groups: dict) -> dict:
    """Validated algo-specific config kwargs (shared by mfsgd and lda).

    ``groups``: ``{owner_algo(s): {knob: value}}`` — the key is one algo
    name or a tuple of them (a knob like lda's ``chunk`` can belong to
    several).  ``None`` values inherit the config defaults; a non-None
    knob combined with a non-owning algo raises — a silently-ignored
    tuning flag wastes benchmark sweeps."""
    kw: dict[str, Any] = {"algo": algo}
    for owners, knobs in groups.items():
        owners_t = (owners,) if isinstance(owners, str) else tuple(owners)
        for name, val in knobs.items():
            if val is None:
                continue
            if algo not in owners_t:
                raise ValueError(
                    f"{name} is {'/'.join(owners_t)}-only; pass one of "
                    f"those algos or tune the {algo!r} knobs instead")
            kw[name] = val
    return kw


def _make_config(rank: int, chunk: int | None, algo: str = "dense",
                 u_tile: int | None = None, i_tile: int | None = None,
                 entry_cap: int | None = None,
                 carry_w: bool | None = None,
                 rotate_chunks: int | None = None,
                 rotate_wire: str | None = None) -> MFSGDConfig:
    return MFSGDConfig(rank=rank, **algo_kwargs(algo, {
        "scatter": {"chunk": chunk},
        _DENSE_ALGOS: {"u_tile": u_tile, "i_tile": i_tile,
                       "entry_cap": entry_cap},
        "dense": {"carry_w": carry_w},
        # every MF-SGD algo rotates, so the pipeline knobs have no
        # non-owning algo to reject — they still ride algo_kwargs for
        # the uniform None-inherits-default contract
        ("dense", "scatter", "pallas"): {"rotate_chunks": rotate_chunks,
                                         "rotate_wire": rotate_wire},
    }))


def benchmark(n_users=138_493, n_items=26_744, nnz=20_000_000, rank=64,
              epochs=3, mesh=None, seed=0, chunk=None, algo="dense",
              u_tile=None, i_tile=None, entry_cap=None, carry_w=None,
              rotate_chunks=None, rotate_wire=None):
    """updates/sec/chip on MovieLens-20M shapes (north-star metric #2).

    One 'update' = one rating visit (one (w_u, h_i) SGD update pair),
    matching Harp-DAAL's MF-SGD throughput accounting.

    Measured on this config (1× v5e): algo="dense" (default) — see the
    MFSGDConfig.algo comment and BASELINE.md for the dense-vs-scatter
    numbers.  For algo="scatter", chunk=None inherits the tuned 32768
    (2026-07-29: 26.3M ups/chip vs 14.4M at 8192; 65536 within noise;
    131072 hit an XLA scatter compile pathology (>9 min, killed) — do not
    default past 64k).
    """
    mesh = mesh or current_mesh()
    cfg = _make_config(rank, chunk, algo, u_tile, i_tile, entry_cap,
                       carry_w, rotate_chunks, rotate_wire)
    model = MFSGD(n_users, n_items, cfg, mesh, seed)
    u, i, v = synthetic_ratings(n_users, n_items, nnz, seed=seed)
    t0 = time.perf_counter()
    model.set_ratings(u, i, v)
    prep = time.perf_counter() - t0

    rmse0 = model.train_epoch()    # warmup (includes single-epoch compile)
    model.compile_epochs(epochs)   # AOT, off-clock, does NOT train
    t0 = time.perf_counter()
    rmse = model.train_epochs(epochs)[-1]
    dt = time.perf_counter() - t0
    ups = nnz * epochs / dt / mesh.num_workers
    return {
        "updates_per_sec_per_chip": ups,
        "sec_per_epoch": dt / epochs,
        "rmse_first_epoch": rmse0,
        "rmse_final": rmse,
        "prep_sec": prep,
        "nnz": nnz, "rank": rank, "num_workers": mesh.num_workers,
        "algo": algo,
    }


def main(argv=None):
    import argparse

    from harp_tpu.utils.metrics import benchmark_json

    p = argparse.ArgumentParser(description="harp-tpu MF-SGD (edu.iu.sgd parity)")
    p.add_argument("--users", type=int, default=None,
                   help="default: 138493 (ML-20M); with --input, raised to "
                        "max id + 1 as needed")
    p.add_argument("--items", type=int, default=None,
                   help="default: 26744 (ML-20M); with --input, raised to "
                        "max id + 1 as needed")
    p.add_argument("--nnz", type=int, default=20_000_000)
    p.add_argument("--rank", type=int, default=64)
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--algo", choices=["dense", "scatter", "pallas"],
                   default="dense",
                   help="dense: one-hot MXU tiles (default); pallas: the "
                        "same update fused into one VMEM kernel; scatter: "
                        "direct gather/scatter-add reference")
    p.add_argument("--chunk", type=int, default=None,
                   help="scatter-only: minibatch size (default: tuned 32768); "
                        "errors under --algo dense instead of silently "
                        "doing nothing")
    p.add_argument("--u-tile", type=int, default=None,
                   help="dense/pallas: W tile rows (default 512)")
    p.add_argument("--i-tile", type=int, default=None,
                   help="dense/pallas: H tile rows (default 512)")
    p.add_argument("--entry-cap", type=int, default=None,
                   help="dense/pallas: max ratings per tile entry (default 2048)")
    p.add_argument("--rotate-chunks", type=int, default=None,
                   help="H sub-slices per worker in the chunked rotation "
                        "pipeline (default 2 — the double-buffered "
                        "two-halves schedule)")
    p.add_argument("--rotate-wire", choices=["exact", "bf16", "int8"],
                   default=None,
                   help="ring payload for in-flight chunks (default exact; "
                        "bf16/int8 halve/quarter the rotate bytes with one "
                        "rounding per hop)")
    p.add_argument("--ckpt-dir", default=None,
                   help="train with checkpoint/resume instead of benchmarking; "
                        "rerunning with the same dir resumes from the latest "
                        "saved epoch")
    p.add_argument("--ckpt-every", type=int, default=5)
    p.add_argument("--resume", action="store_true",
                   help="assert the run RESUMES from --ckpt-dir: fails "
                        "loudly when the dir holds no checkpoint (a "
                        "mistyped dir must not silently retrain from "
                        "epoch 0)")
    p.add_argument("--input", default=None, metavar="FILE_OR_GLOB",
                   help="rating triple files ('user item rating' rows, e.g. "
                        "MovieLens) — the Harp app's HDFS input; implies "
                        "training mode. --users/--items default to max id + 1")
    p.add_argument("--elastic", action="store_true",
                   help="elastic training (PR 15): consume mid-run "
                        "skew_trigger findings between epochs (rebalance "
                        "user packs over the reshard wire) and checkpoint "
                        "mesh-independent state")
    p.add_argument("--max-worker-loss", type=int, default=0,
                   help="elastic: survive up to N permanent worker "
                        "losses by shrinking to the survivors and "
                        "replaying the repartition plan from the last "
                        "checkpoint (implies --elastic; needs --ckpt-dir "
                        "to actually resume)")
    args = p.parse_args(argv)
    from harp_tpu.utils.fault import resolve_resume

    resumed_from = resolve_resume(args.ckpt_dir, args.resume)
    if args.elastic or args.max_worker_loss:
        if args.input:
            raise SystemExit(
                "--elastic currently pairs with the synthetic corpus; "
                "use --users/--items/--nnz (file inputs ride the "
                "non-elastic fit)")
        from harp_tpu.elastic.apps import mfsgd_elastic_fit

        n_users = args.users or 138_493
        n_items = args.items or 26_744
        u, i, v = synthetic_ratings(n_users, n_items, args.nnz)
        ad = mfsgd_elastic_fit(
            u, i, v, n_users=n_users, n_items=n_items,
            cfg=_make_config(args.rank, args.chunk, args.algo,
                             args.u_tile, args.i_tile, args.entry_cap,
                             rotate_chunks=args.rotate_chunks,
                             rotate_wire=args.rotate_wire),
            epochs=args.epochs, ckpt_dir=args.ckpt_dir,
            ckpt_every=args.ckpt_every,
            max_worker_loss=max(args.max_worker_loss, 0))
        print(benchmark_json("mfsgd_elastic_cli", {
            "epochs": args.epochs, "rmse_final": ad.metric(),
            "n_workers": ad.mesh.num_workers,
            "worker_losses": ad.losses, "ckpt_dir": args.ckpt_dir}))
        from harp_tpu.report import maybe_emit

        maybe_emit("mfsgd")
        return
    if args.input or args.ckpt_dir:
        if args.input:
            from harp_tpu.native.datasource import load_triples_glob

            try:
                u, i, v, has_rating = load_triples_glob(args.input)
            except ValueError as e:
                raise SystemExit(str(e))
            if not has_rating:
                raise SystemExit(
                    f"{args.input}: rows have no rating column — MF-SGD "
                    "needs 'user item rating' triples (training on the "
                    "implied zeros would silently fit nothing)")
            if int(u.min()) < 0 or int(i.min()) < 0:
                raise SystemExit(
                    f"{args.input}: negative user/item ids (ids index model "
                    "rows; JAX would silently clamp them to wrong rows)")
            # explicit sizes are raised to fit the data (out-of-range ids
            # would crash the partitioner deep inside otherwise)
            n_users = max(args.users or 0, int(u.max()) + 1)
            n_items = max(args.items or 0, int(i.max()) + 1)
        else:
            n_users = args.users or 138_493
            n_items = args.items or 26_744
            u, i, v = synthetic_ratings(n_users, n_items, args.nnz)
        model = MFSGD(n_users, n_items,
                      _make_config(args.rank, args.chunk, args.algo,
                                   args.u_tile, args.i_tile, args.entry_cap,
                                   rotate_chunks=args.rotate_chunks,
                                   rotate_wire=args.rotate_wire))
        model.set_ratings(u, i, v)
        rmses = model.fit(args.epochs, args.ckpt_dir,
                          ckpt_every=args.ckpt_every)
        print(benchmark_json("mfsgd_fit_cli", {"epochs_run": len(rmses),
               "rmse_final": rmses[-1] if rmses else None,
               "nnz": len(u), "users": n_users, "items": n_items,
               "ckpt_dir": args.ckpt_dir, "resumed_from": resumed_from}))
    else:
        print(benchmark_json("mfsgd_cli", benchmark(
            args.users or 138_493, args.items or 26_744,
            args.nnz, args.rank, args.epochs, chunk=args.chunk,
            algo=args.algo, u_tile=args.u_tile,
            i_tile=args.i_tile, entry_cap=args.entry_cap,
            rotate_chunks=args.rotate_chunks,
            rotate_wire=args.rotate_wire)))
    from harp_tpu.report import maybe_emit

    maybe_emit("mfsgd")


if __name__ == "__main__":
    main()
