"""MF-SGD (matrix factorization) — graded config #2: MovieLens-20M, rotate.

Reference parity (SURVEY.md §3.4, §4.3): Harp's ``edu.iu.sgd`` (and DAAL
variant ``edu.iu.daal_sgd``) factorizes the ratings matrix R ≈ W·Hᵀ with the
signature model-rotation pattern: each worker owns a user-range of R and W;
H is split into one slice per worker; slices travel the ring (``rotate``)
while ``edu.iu.dymoro.Rotator`` prefetches and a timer-bounded
``DynamicScheduler`` runs Hogwild-style SGD threads on the resident slice.

TPU-native design:
- Host preprocessing partitions the rating triples into an N×N grid of
  (user-range, item-slice) blocks, padded to a common size — the TPU
  analogue of Harp's per-worker rating store (static shapes for XLA).
- One epoch = ``rotate_pipeline`` over the H slices; at rotation step t a
  worker trains on the block matching its resident slice
  (``resident_slice_index``) — every rating is visited exactly once per
  epoch, just like Harp.
- Hogwild async updates become deterministic *mini-batched* SGD
  (SURVEY.md §8 hard parts): a ``lax.scan`` over fixed-size chunks;
  within a chunk, gradients for duplicate users/items are summed via
  segment-sum semantics of scatter-add.  Convergence is validated by loss
  curve, not bitwise (the reference is nondeterministic anyway).
- The timer-bound lockstep is free: SPMD workers advance together.
"""

from __future__ import annotations

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from harp_tpu.parallel import collective as C
from harp_tpu.parallel.mesh import WorkerMesh, current_mesh, num_workers, worker_id
from harp_tpu.utils.timing import device_sync


@dataclasses.dataclass
class MFSGDConfig:
    rank: int = 64
    lr: float = 0.01
    reg: float = 0.05  # λ, applied to touched rows only (as SGD does)
    # minibatch size inside a block; 32768 measured best on 1× v5e
    # (26.3M vs 14.4M ups/chip at 8192, identical RMSE — see benchmark()).
    # Small datasets are safe: blocks narrower than this clamp themselves
    # (partition_ratings pads only to the real max block size).
    chunk: int = 32768


# ---------------------------------------------------------------------------
# Host preprocessing: triples → N×N padded block grid.
# ---------------------------------------------------------------------------

def partition_ratings(users, items, vals, n_users, n_items, n_workers, chunk,
                      n_slices: int | None = None):
    """Partition rating triples into the (user-range × item-slice) grid.

    ``n_slices`` defaults to ``2 * n_workers`` — two half-slices per worker,
    which the pipelined epoch needs to overlap rotation with compute.

    Returns per-worker arrays ``u[S, B], i[S, B], v[S, B], mask[S, B]`` with
    user/item ids **local** to their range/slice, stacked worker-major so
    dim 0 shards over the mesh (worker w's row is its ``[n_slices, B]``
    grid).  B is the global max block size rounded up to ``chunk``.

    (Harp stores the same thing as per-worker rating lists keyed by the H
    partition id; padding replaces the dynamic per-block sizes because XLA
    needs static shapes.)
    """
    users = np.asarray(users)
    items = np.asarray(items)
    vals = np.asarray(vals, dtype=np.float32)
    n = n_workers
    ns = n_slices if n_slices is not None else 2 * n
    u_bound = -(-n_users // n)  # users per range (ceil)
    i_bound = -(-n_items // ns)  # items per slice

    wid = users // u_bound  # owning worker (user range)
    sid = items // i_bound  # item slice

    # bucket sort triples by (worker, slice)
    order = np.lexsort((items, sid, wid))
    users, items, vals, wid, sid = (
        a[order] for a in (users, items, vals, wid, sid)
    )
    counts = np.zeros((n, ns), np.int64)
    np.add.at(counts, (wid, sid), 1)
    bmax = int(counts.max())
    if bmax >= chunk:
        B = -(-bmax // chunk) * chunk  # pad to chunk multiple
    else:
        # small data: don't pad every block up to a full chunk (400× waste
        # at the tuned 32768 default on 10k-rating datasets) — one
        # sublane-aligned sub-chunk suffices; the device side clamps its
        # scan chunk to the block width (see _block_update).  Cap at chunk:
        # sublane alignment may otherwise overshoot it when chunk % 8 != 0,
        # and the device reshape needs B % min(chunk, B) == 0.
        B = min(chunk, max(8, -(-bmax // 8) * 8))

    u = np.zeros((n, ns, B), np.int32)
    i = np.zeros((n, ns, B), np.int32)
    v = np.zeros((n, ns, B), np.float32)
    m = np.zeros((n, ns, B), np.float32)
    starts = np.zeros((n, ns), np.int64)
    starts.flat[1:] = counts.cumsum()[:-1]
    for w in range(n):
        for s in range(ns):
            lo, c = starts[w, s], counts[w, s]
            sl = slice(lo, lo + c)
            u[w, s, :c] = users[sl] - w * u_bound
            i[w, s, :c] = items[sl] - s * i_bound
            v[w, s, :c] = vals[sl]
            m[w, s, :c] = 1.0
    return (
        u.reshape(n * ns, B), i.reshape(n * ns, B),
        v.reshape(n * ns, B), m.reshape(n * ns, B),
        u_bound, i_bound,
    )


# ---------------------------------------------------------------------------
# Device compute.
# ---------------------------------------------------------------------------

def _chunk_update(W, H, batch, cfg: MFSGDConfig):
    """One deterministic minibatch SGD step on (W, H-slice).

    Gradients of ½Σ m(r − w·h)² + ½λΣ(‖w‖²+‖h‖²) over the chunk; duplicate
    rows get summed gradients (scatter-add), the batched stand-in for
    Harp's sequential Hogwild updates.
    """
    bu, bi, bv, bm = batch
    wu = jnp.take(W, bu, axis=0)          # [c, r]
    hi = jnp.take(H, bi, axis=0)          # [c, r]
    err = bm * (bv - (wu * hi).sum(-1))   # [c]
    gw = err[:, None] * hi - cfg.reg * bm[:, None] * wu
    gh = err[:, None] * wu - cfg.reg * bm[:, None] * hi
    W = W.at[bu].add(cfg.lr * gw, mode="drop")
    H = H.at[bi].add(cfg.lr * gh, mode="drop")
    return W, H, (err * err).sum(), bm.sum()


def _block_update(W, H, block, cfg: MFSGDConfig):
    """Scan minibatch chunks over one (user-range × item-slice) block.

    The effective chunk is clamped to the (static) block width — small
    datasets produce blocks narrower than ``cfg.chunk`` (see
    ``partition_ratings``), which then run as a single minibatch.
    """
    bu, bi, bv, bm = block
    c = min(cfg.chunk, bu.shape[0])
    nchunk = bu.shape[0] // c
    chunks = jax.tree.map(lambda a: a.reshape(nchunk, c), (bu, bi, bv, bm))

    def body(carry, chunk):
        W, H, se, cnt = carry
        W, H, dse, dcnt = _chunk_update(W, H, chunk, cfg)
        return (W, H, se + dse, cnt + dcnt), None

    (W, H, se, cnt), _ = lax.scan(
        body, (W, H, jnp.float32(0.0), jnp.float32(0.0)), chunks
    )
    return W, H, se, cnt


def make_epoch_fn(mesh: WorkerMesh, cfg: MFSGDConfig):
    """Compile one full rotation epoch (every rating visited exactly once).

    This is the dymoro pipeline done the XLA way (SURVEY.md §4.3): each
    worker's H slice is **split into two halves** that alternate roles —
    while the SGD kernel updates one half, the other (updated on the
    previous step) is in flight to the ring neighbor.  The ``ppermute`` has
    no data dependency on the current step's compute, so XLA's async
    scheduler overlaps transfer with compute; a whole-slice rotation would
    serialize, because a mutated slice cannot leave before its update
    finishes (the constraint Harp's Rotator also has, which is why dymoro
    prefetches *next* slices rather than sending current ones).

    Schedule (n workers, 2n half-slices, 2n steps/epoch): at step t worker
    w computes half ``2*((w - t//2) % n)`` (t even) or
    ``2*((w - t//2 - 1) % n) + 1`` (t odd); after 2n steps both halves are
    back home and every (worker, half) pair has met exactly once.
    """
    two_n = 2 * mesh.num_workers

    def epoch(W, H_slice, bu, bi, bv, bm):
        # bu… arrive as this worker's [2n_half_slices, B] block row; the
        # resident H rows split into an even (front) and odd (back) half.
        ib2 = H_slice.shape[0] // 2
        computing, inflight = H_slice[:ib2], H_slice[ib2:]

        def body(carry, t):
            W, computing, inflight, se, cnt = carry
            received = C.rotate(inflight)  # overlaps with the update below
            half_idx = jnp.where(
                t % 2 == 0,
                2 * ((worker_id() - t // 2) % num_workers()),
                2 * ((worker_id() - t // 2 - 1) % num_workers()) + 1,
            )
            block = jax.tree.map(
                lambda a: a[half_idx], (bu, bi, bv, bm)
            )
            W, computing, dse, dcnt = _block_update(W, computing, block, cfg)
            return (W, received, computing, se + dse, cnt + dcnt), None

        (W, computing, inflight, se, cnt), _ = lax.scan(
            body,
            (W, computing, inflight, jnp.float32(0.0), jnp.float32(0.0)),
            jnp.arange(two_n),
        )
        # After 2n steps the even half sits in `computing`, odd in `inflight`,
        # both back on their home worker.
        H_slice = jnp.concatenate([computing, inflight], axis=0)
        # loss partials are per-worker; combine before leaving SPMD (the
        # optional end-of-epoch allreduce-RMSE in Harp's MF-SGD loop)
        se, cnt = C.allreduce((se, cnt))
        return W, H_slice, se, cnt

    return jax.jit(
        mesh.shard_map(
            epoch,
            in_specs=(mesh.spec(0),) * 6,
            out_specs=(mesh.spec(0), mesh.spec(0), P(), P()),
        )
    )


class MFSGD:
    """Host driver (the ``mapCollective`` residue for edu.iu.sgd)."""

    def __init__(self, n_users, n_items, cfg: MFSGDConfig | None = None,
                 mesh: WorkerMesh | None = None, seed=0):
        self.mesh = mesh or current_mesh()
        self.cfg = cfg or MFSGDConfig()
        self.n_users, self.n_items = n_users, n_items
        n = self.mesh.num_workers
        self.u_bound = -(-n_users // n)
        # two half-slices per worker (pipelined rotation) → per-worker H rows
        self.i_bound = 2 * (-(-n_items // (2 * n)))
        k1, k2 = jax.random.split(jax.random.key(seed))
        scale = 1.0 / np.sqrt(self.cfg.rank)
        self.W = self.mesh.shard_array(
            np.asarray(jax.random.uniform(k1, (self.u_bound * n, self.cfg.rank),
                                          jnp.float32, 0, scale)), 0)
        self.H = self.mesh.shard_array(
            np.asarray(jax.random.uniform(k2, (self.i_bound * n, self.cfg.rank),
                                          jnp.float32, 0, scale)), 0)
        self._epoch_fn = make_epoch_fn(self.mesh, self.cfg)
        self._blocks = None

    def set_ratings(self, users, items, vals):
        n = self.mesh.num_workers
        bu, bi, bv, bm, ub, ib2 = partition_ratings(
            users, items, vals, self.n_users, self.n_items, n, self.cfg.chunk
        )
        assert (ub, 2 * ib2) == (self.u_bound, self.i_bound)
        self._blocks = tuple(self.mesh.shard_array(a, 0) for a in (bu, bi, bv, bm))
        self.nnz = len(np.asarray(vals))

    def train_epoch(self):
        """One rotation epoch; returns training RMSE over visited ratings."""
        if self._blocks is None:
            raise RuntimeError("call set_ratings() before train_epoch()")
        self.W, self.H, se, cnt = self._epoch_fn(self.W, self.H, *self._blocks)
        return float(np.sqrt(max(device_sync(se), 0.0) / max(device_sync(cnt), 1.0)))

    def fit(self, epochs: int, ckpt_dir: str | None = None, *,
            ckpt_every: int = 5, max_restarts: int = 3, fault=None):
        """Train with optional checkpoint/resume — the SURVEY.md §6 driver.

        With ``ckpt_dir`` set, epochs checkpoint every ``ckpt_every`` and a
        crashed run (or a rerun pointing at the same dir) resumes from the
        latest saved epoch instead of epoch 0 — Harp's YARN whole-job retry,
        upgraded.  Returns the per-epoch RMSE list for the epochs this call
        actually ran.
        """
        from harp_tpu.utils.fault import fit_epochs

        rmses: list[float] = []

        def set_state(state):
            if not isinstance(state["W"], jax.Array):  # numpy from restore
                self.W = self.mesh.shard_array(np.asarray(state["W"]), 0)
                self.H = self.mesh.shard_array(np.asarray(state["H"]), 0)
            else:
                self.W, self.H = state["W"], state["H"]

        fit_epochs(
            lambda: rmses.append(self.train_epoch()),
            lambda: {"W": self.W, "H": self.H},
            set_state,
            epochs, ckpt_dir, ckpt_every=ckpt_every,
            max_restarts=max_restarts, fault=fault,
        )
        return rmses

    def factors(self):
        return np.asarray(self.W)[: self.n_users], np.asarray(self.H)[: self.n_items]

    def predict_rmse(self, users, items, vals):
        W, H = self.factors()
        pred = (W[np.asarray(users)] * H[np.asarray(items)]).sum(-1)
        return float(np.sqrt(np.mean((pred - np.asarray(vals)) ** 2)))


# ---------------------------------------------------------------------------
# Synthetic MovieLens-20M-shaped data + benchmark.
# ---------------------------------------------------------------------------

def synthetic_ratings(n_users, n_items, nnz, rank=8, noise=0.1, seed=0):
    """Low-rank ground truth + noise, uniform random (u, i) pairs."""
    rng = np.random.default_rng(seed)
    Wt = rng.normal(size=(n_users, rank)) / np.sqrt(rank)
    Ht = rng.normal(size=(n_items, rank)) / np.sqrt(rank)
    u = rng.integers(0, n_users, nnz)
    i = rng.integers(0, n_items, nnz)
    v = (Wt[u] * Ht[i]).sum(-1) + noise * rng.normal(size=nnz)
    return u.astype(np.int32), i.astype(np.int32), v.astype(np.float32)


def _make_config(rank: int, chunk: int | None) -> MFSGDConfig:
    """chunk=None inherits MFSGDConfig's tuned default."""
    return MFSGDConfig(rank=rank) if chunk is None else \
        MFSGDConfig(rank=rank, chunk=chunk)


def benchmark(n_users=138_493, n_items=26_744, nnz=20_000_000, rank=64,
              epochs=3, mesh=None, seed=0, chunk=None):
    """updates/sec/chip on MovieLens-20M shapes (north-star metric #2).

    One 'update' = one rating visit (one (w_u, h_i) SGD update pair),
    matching Harp-DAAL's MF-SGD throughput accounting.

    chunk=None inherits MFSGDConfig's tuned default (32768, measured on
    1× v5e 2026-07-29: 26.3M ups/chip vs 14.4M at 8192 — scatter dispatch
    amortizes; RMSE identical to 4 decimal places).  65536 is within noise
    of 32768; 131072 hit an XLA scatter compile/runtime pathology (>9 min,
    killed) — do not default past 64k.
    """
    mesh = mesh or current_mesh()
    cfg = _make_config(rank, chunk)
    model = MFSGD(n_users, n_items, cfg, mesh, seed)
    u, i, v = synthetic_ratings(n_users, n_items, nnz, seed=seed)
    t0 = time.perf_counter()
    model.set_ratings(u, i, v)
    prep = time.perf_counter() - t0

    rmse0 = model.train_epoch()  # warmup (includes compile)
    t0 = time.perf_counter()
    rmse = 0.0
    for _ in range(epochs):
        rmse = model.train_epoch()
    dt = time.perf_counter() - t0
    ups = nnz * epochs / dt / mesh.num_workers
    return {
        "updates_per_sec_per_chip": ups,
        "sec_per_epoch": dt / epochs,
        "rmse_first_epoch": rmse0,
        "rmse_final": rmse,
        "prep_sec": prep,
        "nnz": nnz, "rank": rank, "num_workers": mesh.num_workers,
    }


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser(description="harp-tpu MF-SGD (edu.iu.sgd parity)")
    p.add_argument("--users", type=int, default=None,
                   help="default: 138493 (ML-20M); with --input, raised to "
                        "max id + 1 as needed")
    p.add_argument("--items", type=int, default=None,
                   help="default: 26744 (ML-20M); with --input, raised to "
                        "max id + 1 as needed")
    p.add_argument("--nnz", type=int, default=20_000_000)
    p.add_argument("--rank", type=int, default=64)
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--chunk", type=int, default=None,
                   help="minibatch size (default: MFSGDConfig's tuned value)")
    p.add_argument("--ckpt-dir", default=None,
                   help="train with checkpoint/resume instead of benchmarking; "
                        "rerunning with the same dir resumes from the latest "
                        "saved epoch")
    p.add_argument("--ckpt-every", type=int, default=5)
    p.add_argument("--input", default=None, metavar="FILE_OR_GLOB",
                   help="rating triple files ('user item rating' rows, e.g. "
                        "MovieLens) — the Harp app's HDFS input; implies "
                        "training mode. --users/--items default to max id + 1")
    args = p.parse_args(argv)
    if args.input or args.ckpt_dir:
        if args.input:
            from harp_tpu.native.datasource import load_triples_glob

            try:
                u, i, v, has_rating = load_triples_glob(args.input)
            except ValueError as e:
                raise SystemExit(str(e))
            if not has_rating:
                raise SystemExit(
                    f"{args.input}: rows have no rating column — MF-SGD "
                    "needs 'user item rating' triples (training on the "
                    "implied zeros would silently fit nothing)")
            if int(u.min()) < 0 or int(i.min()) < 0:
                raise SystemExit(
                    f"{args.input}: negative user/item ids (ids index model "
                    "rows; JAX would silently clamp them to wrong rows)")
            # explicit sizes are raised to fit the data (out-of-range ids
            # would crash the partitioner deep inside otherwise)
            n_users = max(args.users or 0, int(u.max()) + 1)
            n_items = max(args.items or 0, int(i.max()) + 1)
        else:
            n_users = args.users or 138_493
            n_items = args.items or 26_744
            u, i, v = synthetic_ratings(n_users, n_items, args.nnz)
        model = MFSGD(n_users, n_items, _make_config(args.rank, args.chunk))
        model.set_ratings(u, i, v)
        rmses = model.fit(args.epochs, args.ckpt_dir,
                          ckpt_every=args.ckpt_every)
        print({"epochs_run": len(rmses),
               "rmse_final": rmses[-1] if rmses else None,
               "nnz": len(u), "users": n_users, "items": n_items,
               "ckpt_dir": args.ckpt_dir})
    else:
        print(benchmark(args.users or 138_493, args.items or 26_744,
                        args.nnz, args.rank, args.epochs, chunk=args.chunk))


if __name__ == "__main__":
    main()
