"""ML applications — the Harp L7 capability surface (SURVEY.md §3.4).

Each app mirrors one Harp application family (``ml/java`` pure-Java apps and
``ml/daal`` Harp-DAAL apps): a jitted step function built on the collective
verbs, a ``fit``-style host driver, and a CLI launcher replacing
``hadoop jar harp-<app>.jar edu.iu....Launcher``.

Graded configs (BASELINE.json):
  kmeans   — KMeans k=100 on 1M×300 dense     (allreduce pattern)
  mfsgd    — MF-SGD on MovieLens-20M           (rotate pattern)
  lda      — LDA-CGS 1k topics, enwiki-1M docs (rotate + push/pull)
  mlp      — neural-net / MLP on MNIST         (gradient allreduce)
  subgraph — subgraph counting                 (allgather/regroup, irregular)
  rf       — random forest                     (allgather)

Additional reference apps: ccd (CCD++ MF), svm, wdamds (WDA-MDS/SMACOF),
and the DAAL classic-stats suite (pca, covariance, moments, naive Bayes,
linear/ridge regression, QR, SVD, ALS) in :mod:`harp_tpu.models.stats`.
"""
