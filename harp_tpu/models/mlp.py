"""Neural net / MLP — graded config #4: MNIST, gradient allreduce.

Reference parity (SURVEY.md §3.4): Harp-DAAL's ``edu.iu.daal_nn`` trains a
DAAL neural-net (MLP) data-parallel: each worker computes gradients on its
shard through DAAL's native layers, then a Harp ``allreduce`` combines
gradients before the synchronized weight update.

TPU-native design: the training step is one jitted SPMD program —
``jax.value_and_grad`` through the MLP, gradients averaged with the same
:func:`harp_tpu.parallel.collective.allreduce` verb every other app uses
(demonstrating the DP path is app-level API, not a special case), then an
optax update applied identically on every worker (weights stay replicated,
like Harp's model tables after allreduce).  MXU notes: batch and hidden
dims padded to 128 keep the matmuls on full tiles; bf16 activations with
f32 params/optimizer is the standard mixed-precision recipe.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

from harp_tpu.ingest import IngestPipeline
from harp_tpu.parallel import collective as C
from harp_tpu.parallel.mesh import WorkerMesh, current_mesh
from harp_tpu.utils import flightrec, prng, telemetry
from harp_tpu.utils.timing import device_sync


@dataclasses.dataclass
class MLPConfig:
    sizes: Sequence[int] = (784, 512, 256, 10)  # MNIST default (daal_nn MLP)
    lr: float = 0.01
    optimizer: str = "sgd"  # sgd | momentum | adam
    half_precision: bool = False  # bf16 activations, f32 params
    # gradient allreduce wire format: "f32" (exact, default) | "bf16" |
    # "int8" — quantized wire (collective.allreduce_quantized, EQuARX-style)
    # halves/quarters ICI/DCN gradient bytes on real pods; loss/acc metrics
    # always reduce exactly
    grad_wire: str = "f32"
    # ZeRO-1 optimizer-state sharding (beyond-reference, like TP/PP/EP):
    # instead of allreduce(grads) + a replicated optax update, the step
    # PUSHes gradient shards to their owners (psum_scatter — Harp's push
    # verb applied to the optimizer), updates only the local 1/nw slice of
    # the optimizer state, and PULLs the updated parameter shards back
    # (all_gather — Harp's pull).  Optimizer memory per chip drops nw×
    # (adam: 2× params replicated → 2×/nw), comm volume stays 2×params/
    # step like allreduce (reduce_scatter + all_gather IS ring allreduce).
    # Identical math for elementwise optimizers (sgd/momentum/adam) —
    # tests pin step-for-step equality with the replicated path.
    zero1: bool = False

    def __post_init__(self):
        if self.grad_wire not in ("f32", "bf16", "int8"):
            raise ValueError(
                f"grad_wire must be f32|bf16|int8, got {self.grad_wire!r}")


def init_params(cfg: MLPConfig, key):
    params = []
    keys = jax.random.split(key, len(cfg.sizes) - 1)
    for k, (fan_in, fan_out) in zip(keys, zip(cfg.sizes[:-1], cfg.sizes[1:])):
        w = jax.random.normal(k, (fan_in, fan_out), jnp.float32)
        params.append({
            "w": w * jnp.sqrt(2.0 / fan_in),  # He init (ReLU net)
            "b": jnp.zeros((fan_out,), jnp.float32),
        })
    return params


def forward(params, x, cfg: MLPConfig):
    h = x.astype(jnp.bfloat16) if cfg.half_precision else x
    for layer in params[:-1]:
        w = layer["w"].astype(h.dtype)
        h = jax.nn.relu(h @ w + layer["b"].astype(h.dtype))
    last = params[-1]
    logits = h @ last["w"].astype(h.dtype) + last["b"].astype(h.dtype)
    return logits.astype(jnp.float32)


def loss_fn(params, x, y, cfg: MLPConfig):
    logits = forward(params, x, cfg)
    ce = optax.softmax_cross_entropy_with_integer_labels(logits, y)
    return ce.mean(), logits


def make_optimizer(cfg: MLPConfig):
    if cfg.optimizer == "sgd":
        return optax.sgd(cfg.lr)
    if cfg.optimizer == "momentum":
        return optax.sgd(cfg.lr, momentum=0.9)
    if cfg.optimizer == "adam":
        return optax.adam(cfg.lr)
    raise ValueError(f"unknown optimizer {cfg.optimizer!r}")


def _step_body(tx, cfg: MLPConfig, combine):
    """The one train-step body both trainers share: value_and_grad →
    ``combine`` (the DP gradient allreduce; identity under GSPMD where XLA
    inserts the collectives) → optax update.  A change here (e.g. grad
    clipping) applies to DP and TP identically — the equivalence tests
    depend on that."""

    def step(params, opt_state, x, y):
        (loss, logits), grads = jax.value_and_grad(
            lambda p: loss_fn(p, x, y, cfg), has_aux=True
        )(params)
        acc = (jnp.argmax(logits, -1) == y).mean()
        grads, loss, acc = combine((grads, loss, acc))
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss, acc

    return step


def _grad_combine(cfg: MLPConfig):
    """The DP gradient-allreduce, honoring the configured wire format.

    Gradients may ride a quantized wire; the scalar loss/acc metrics always
    reduce exactly (they are what the user reads).
    """
    if cfg.grad_wire == "f32":
        return lambda t: C.allreduce(t, C.Combiner.AVG)
    # unknown values already rejected by MLPConfig.__post_init__
    wire = {"bf16": jnp.bfloat16, "int8": jnp.int8}[cfg.grad_wire]

    def combine(tree):
        grads, loss, acc = tree
        n = lax.axis_size(C.WORKER_AXIS)
        grads = jax.tree.map(
            lambda g: g / n, C.allreduce_quantized(grads, wire_dtype=wire))
        loss, acc = C.allreduce((loss, acc), C.Combiner.AVG)
        return grads, loss, acc

    return combine


def param_count(cfg: MLPConfig) -> int:
    return sum(fi * fo + fo for fi, fo in zip(cfg.sizes[:-1], cfg.sizes[1:]))


def zero1_shard_len(cfg: MLPConfig, n_workers: int) -> int:
    """Per-worker slice of the flattened parameter vector (ceil-padded)."""
    return -(-param_count(cfg) // n_workers)


def _zero1_grad_shard(grads, cfg: MLPConfig, nw: int, pad: int):
    """Average-reduce the gradient pytree to this worker's flat [L] slice.

    f32: one exact push (psum_scatter, AVG).  bf16: the flat quantized
    scatter.  int8: quantized PER LEAF before flattening — the same
    per-layer scale granularity :func:`allreduce_quantized` gives the
    replicated path (one global scale would zero out small-magnitude
    layers' gradients); the int32 scatter stays exact, and the dequant
    scale for each position rides a segment vector sliced to this
    worker's range.
    """
    from jax.flatten_util import ravel_pytree

    from harp_tpu.parallel.collective import quantize_to_int8

    if cfg.grad_wire == "f32":
        flat_g, _ = ravel_pytree(grads)
        return C.push(jnp.pad(flat_g, (0, pad)), C.Combiner.AVG)
    if cfg.grad_wire == "bf16":
        flat_g, _ = ravel_pytree(grads)
        return C.push_quantized(jnp.pad(flat_g, (0, pad)),
                                wire_dtype=jnp.bfloat16) / nw
    leaves = jax.tree.leaves(grads)
    # MAX-allreduce through the verb layer (one stacked collective for
    # every leaf's scale), so the ledger sees the scale exchange too
    amax = C.allreduce(jnp.stack([jnp.max(jnp.abs(g)).astype(jnp.float32)
                                  for g in leaves]), C.Combiner.MAX)
    qs, scale_segs = [], []
    for i, g in enumerate(leaves):
        q, scale = quantize_to_int8(g.reshape(-1), amax[i])
        qs.append(q)
        scale_segs.append(jnp.full((g.size,), scale, jnp.float32))
    flat_q = jnp.pad(jnp.concatenate(qs), (0, pad))
    total = C.push(flat_q.astype(jnp.int32), C.Combiner.ADD)     # exact
    scale_flat = jnp.pad(jnp.concatenate(scale_segs), (0, pad))
    L = total.shape[0]
    w = lax.axis_index(C.WORKER_AXIS)
    my_scale = lax.dynamic_slice_in_dim(scale_flat, w * L, L)
    return total.astype(jnp.float32) * my_scale / nw


def _zero1_step_body(tx, cfg: MLPConfig, nw: int):
    """ZeRO-1 twin of :func:`_step_body`: same (params, opt_state, x, y)
    → (params, opt_state, loss, acc) contract, but ``opt_state`` is this
    worker's 1/nw shard over the flattened parameter vector.  The
    gradient exchange is push (psum_scatter) + pull (all_gather) — the
    same bytes as allreduce, with the optax update sharded between them.
    """
    from jax.flatten_util import ravel_pytree

    def step(params, opt_state, x, y):
        (loss, logits), grads = jax.value_and_grad(
            lambda p: loss_fn(p, x, y, cfg), has_aux=True
        )(params)
        acc = (jnp.argmax(logits, -1) == y).mean()
        loss, acc = C.allreduce((loss, acc), C.Combiner.AVG)

        flat_p, unravel = ravel_pytree(params)
        total = flat_p.shape[0]
        L = -(-total // nw)
        pad = nw * L - total
        gsh = _zero1_grad_shard(grads, cfg, nw, pad)             # [L]
        w = lax.axis_index(C.WORKER_AXIS)
        psh = lax.dynamic_slice_in_dim(jnp.pad(flat_p, (0, pad)), w * L, L)
        updates, opt_state = tx.update(gsh, opt_state, psh)
        psh = optax.apply_updates(psh, updates)
        params = unravel(C.pull(psh)[:total])                    # [nw·L]
        return params, opt_state, loss, acc

    return step


def _opt_state_setup(mesh: WorkerMesh, cfg: MLPConfig, tx, params):
    """(initial opt_state, its shard_map spec tree) for either layout.

    Replicated (default): optax state over the full param pytree, P().
    zero1: state over a [L]-vector per worker — vector leaves live as
    [nw·L] arrays sharded on dim 0, scalar leaves (adam's count)
    replicated.  Vector leaves are built as fresh zeros: every supported
    optimizer (the make_optimizer allowlist) zero-initializes its state,
    so no device readback is needed to check.
    """
    if not cfg.zero1:
        state = jax.device_put(tx.init(params), mesh.replicated())
        return state, P()
    nw = mesh.num_workers
    L = zero1_shard_len(cfg, nw)
    local = tx.init(jnp.zeros((L,), jnp.float32))

    def globalize(leaf):
        if leaf.ndim == 0:
            return jax.device_put(leaf, mesh.replicated())
        return mesh.shard_array(
            np.zeros((nw * L,) + leaf.shape[1:], np.dtype(leaf.dtype)), 0)

    state = jax.tree.map(globalize, local)
    return state, _opt_specs_for(mesh, cfg)


def _opt_specs_for(mesh: WorkerMesh, cfg: MLPConfig):
    """shard_map specs for the optimizer state — derived from cfg alone,
    so make_train_step/make_epoch_fn can never be handed mismatched
    specs for a zero1 config."""
    if not cfg.zero1:
        return P()
    local = jax.eval_shape(  # structure only — no device work
        make_optimizer(cfg).init,
        jax.ShapeDtypeStruct((zero1_shard_len(cfg, mesh.num_workers),),
                             jnp.float32))
    return jax.tree.map(lambda a: P() if a.ndim == 0 else mesh.spec(0),
                        local)


def _pick_step_body(mesh: WorkerMesh, cfg: MLPConfig, tx):
    if cfg.zero1:
        return _zero1_step_body(tx, cfg, mesh.num_workers)
    # the graded pattern: gradient allreduce through the app-level verb
    return _step_body(tx, cfg, _grad_combine(cfg))


def make_train_step(mesh: WorkerMesh, cfg: MLPConfig):
    """Compile the data-parallel training step (the daal_nn hot loop).

    The optimizer-state placement follows ``cfg.zero1`` automatically
    (specs derived internally — callers cannot hand mismatched ones);
    pair with :func:`_opt_state_setup` for the matching initial state.
    """
    tx = make_optimizer(cfg)
    step = _pick_step_body(mesh, cfg, tx)
    opt_specs = _opt_specs_for(mesh, cfg)
    return jax.jit(
        mesh.shard_map(
            step,
            in_specs=(P(), opt_specs, mesh.spec(0), mesh.spec(0)),
            out_specs=(P(), opt_specs, P(), P()),
        )
    ), tx


def make_epoch_fn(mesh: WorkerMesh, cfg: MLPConfig, batch_per_worker: int,
                  n_batches: int, epochs: int = 1):
    """Compile ``epochs`` epochs over a device-RESIDENT shard as ONE program.

    Harp-DAAL NN iterates minibatches of an in-memory NumericTable; the
    TPU analogue keeps the shard in HBM and scans batch steps (and epochs)
    on device — one dispatch and one readback for the whole run.  On the
    relay-attached v5e each dispatch/readback round trip costs a variable
    ~20–150 ms, which dwarfs the ~3 ms device epoch: the host-loop path
    measured 2.8–5.2M samples/s vs 21.2M fully on-device (MNIST shapes,
    batch 8192, 1× v5e, 2026-07-30).
    Batch order reshuffles each epoch by folding the epoch index into the
    passed RNG key (replicated, so workers visit their shards in step).
    Returns per-epoch (last-batch loss, acc) arrays.
    """
    tx = make_optimizer(cfg)
    step = _pick_step_body(mesh, cfg, tx)
    opt_specs = _opt_specs_for(mesh, cfg)

    def run(params, opt_state, xs, ys, key):
        base = jax.random.wrap_key_data(key)

        def epoch(carry, e):
            params, opt_state = carry
            order = jax.random.permutation(
                jax.random.fold_in(base, e), n_batches)

            def body(c, i):
                p, o = c
                xb = lax.dynamic_slice_in_dim(
                    xs, i * batch_per_worker, batch_per_worker, 0)
                yb = lax.dynamic_slice_in_dim(
                    ys, i * batch_per_worker, batch_per_worker, 0)
                p, o, loss, acc = step(p, o, xb, yb)
                return (p, o), (loss, acc)

            (params, opt_state), (losses, accs) = lax.scan(
                body, (params, opt_state), order)
            return (params, opt_state), (losses[-1], accs[-1])

        (params, opt_state), (losses, accs) = lax.scan(
            epoch, (params, opt_state), jnp.arange(epochs))
        return params, opt_state, losses, accs

    return jax.jit(
        mesh.shard_map(
            run,
            in_specs=(P(), opt_specs, mesh.spec(0), mesh.spec(0), P()),
            out_specs=(P(), opt_specs, P(), P()),
        )
    ), tx


def _effective_batch(batch_size: int, n: int, n_workers: int) -> int:
    """Batch size actually used: capped at n, rounded down to a worker
    multiple, floored at one sample per worker.  Shared by fit and
    load_resident so both paths train with the same effective batch for
    the same argument."""
    return max(n_workers, (min(batch_size, n) // n_workers) * n_workers)


def _batch_reader(x, y, batch_size, order):
    """Stage-1 reader for the shared ingest pipeline (PR 8): contiguous
    ZERO-COPY views of the caller's arrays.  Shuffling permutes BATCH
    indices (``order``, re-drawn per epoch by the caller), never rows —
    the pre-PR loop gathered ``x[perm]`` batch by batch, a full
    fancy-index copy of the dataset every epoch; a view costs nothing
    and the cast/H2D stages downstream touch only one batch at a time
    (pinned by tests/test_ingest.py: the reader output shares memory
    with the input)."""

    def read(j):
        lo = int(order[j]) * batch_size
        return x[lo:lo + batch_size], y[lo:lo + batch_size]

    return read


class MLPTrainer:
    """Host driver (the mapCollective residue for edu.iu.daal_nn)."""

    def __init__(self, cfg: MLPConfig | None = None, mesh: WorkerMesh | None = None,
                 seed=0):
        self.mesh = mesh or current_mesh()
        self.cfg = cfg or MLPConfig()
        self.params = jax.device_put(
            init_params(self.cfg, jax.random.key(seed)), self.mesh.replicated()
        )
        tx = make_optimizer(self.cfg)
        self.opt_state, self._opt_specs = _opt_state_setup(
            self.mesh, self.cfg, tx, self.params)
        self._step, _ = make_train_step(self.mesh, self.cfg)
        self._forward = flightrec.track(
            jax.jit(lambda p, v: forward(p, v, self.cfg)), "mlp.forward")
        self._epoch_fns: dict = {}
        self._shuffle_counter = 0

    def train_batch(self, x, y):
        """x: [b, features], y: [b] int labels; b divisible by num_workers."""
        x = self.mesh.shard_array(np.asarray(x, np.float32), 0)
        y = self.mesh.shard_array(np.asarray(y, np.int32), 0)
        self.params, self.opt_state, loss, acc = self._step(
            self.params, self.opt_state, x, y
        )
        return float(device_sync(loss)), float(device_sync(acc))

    def load_resident(self, x, y, batch_size=8192, seed=0):
        """Stage the dataset in HBM for :meth:`fit_resident`.

        Rows stage in input order; when the batch-divisibility trim must
        drop rows it drops a uniform random subset (``seed``), so the
        trim stays unbiased without the pre-PR-8 full-row host reshuffle
        (a whole extra dataset copy).  Batch ORDER still reshuffles on
        device every epoch (:func:`make_epoch_fn`).  The host→device
        transfer happens here, once, not inside the training loop.
        Returns the usable sample count.
        """
        n = x.shape[0]
        nw = self.mesh.num_workers
        if n < nw:
            raise ValueError(f"need at least {nw} samples (one per worker), got {n}")
        batch_size = _effective_batch(batch_size, n, nw)
        usable = (n // batch_size) * batch_size
        # rows stage in INPUT order (zero extra host copies when x is
        # already f32) — the pre-PR ``x[order]`` gather re-materialized
        # the whole dataset just to randomize an order the on-device
        # per-epoch batch shuffle already randomizes.  Only the
        # divisibility trim still samples: the dropped rows are a
        # uniform random subset (order preserved), so the trim stays
        # unbiased without a full-row reshuffle.
        xs_host = np.asarray(x, np.float32)
        ys_host = np.asarray(y, np.int32)
        if usable < n:
            rng = np.random.default_rng(seed)
            keep = np.sort(rng.choice(n, size=usable, replace=False))
            xs_host, ys_host = xs_host[keep], ys_host[keep]
        xs = self.mesh.shard_array(xs_host, 0)
        ys = self.mesh.shard_array(ys_host, 0)
        self._resident = (xs, ys, batch_size // nw, usable // batch_size)
        return usable

    def fit_resident(self, epochs=1, seed=0):
        """Train on the :meth:`load_resident`-staged data — ALL epochs as
        one device program (see :func:`make_epoch_fn`), batch order
        reshuffled on device each epoch.  Returns [(last_loss, last_acc)]
        per epoch.
        """
        if getattr(self, "_resident", None) is None:
            raise RuntimeError("call load_resident() before fit_resident()")
        xs, ys, bpw, nb = self._resident
        fn = self._epoch_fns.get((bpw, nb, epochs))
        if fn is None:
            fn, _ = make_epoch_fn(self.mesh, self.cfg, bpw, nb, epochs)
            self._epoch_fns[(bpw, nb, epochs)] = fn
        # raw threefry key bits built on host: jax.random.PRNGKey(int)
        # specializes on the Python int, so distinct seeds would each
        # trigger a (remote) compile.  The call counter advances the key so
        # sequential fit_resident calls (natural when reusing a compiled
        # epoch count) keep reshuffling instead of repeating one order.
        s = seed + 1 + self._shuffle_counter
        self._shuffle_counter += epochs
        key = prng.key_bits(s)
        self.params, self.opt_state, losses, accs = fn(
            self.params, self.opt_state, xs, ys, key)
        stats = np.asarray(jnp.stack([losses, accs], axis=1))  # one readback
        return [(float(l), float(a)) for l, a in stats]

    def fit_ckpt(self, x, y, epochs, ckpt_dir=None, *, batch_size=8192,
                 ckpt_every=5, max_restarts=3, fault=None, seed=0):
        """Epoch training with checkpoint/resume — the same recovery
        contract as MF-SGD/LDA ``fit()`` (SURVEY.md §6: restart-from-entry
        before the first checkpoint, resume installs restored state, fault
        without ckpt_dir refused).  One epoch = one resident device program
        (:meth:`fit_resident`); params AND optimizer state checkpoint, so a
        resumed adam/momentum run continues the same trajectory.  Returns
        [(last_loss, last_acc)] for the epochs this call ran.
        """
        from harp_tpu.utils.fault import check_restored_shapes, fit_epochs

        self.load_resident(x, y, batch_size=batch_size, seed=seed)
        history: list = []

        def set_state(state):
            # opt_state too: matching params but a different optimizer
            # (sgd vs adam) would otherwise die inside tree.unflatten with
            # an obscure structure error instead of this clear refusal
            check_restored_shapes([
                ("params", state["params"], self.params),
                ("opt_state", state["opt_state"], self.opt_state),
            ])
            if not isinstance(jax.tree.leaves(state["params"])[0], jax.Array):
                # a checkpoint restore yields plain containers; rebuild on
                # the LIVE treedefs so optax's named-tuple states survive
                def put_like(template, restored, spec_tree=None):
                    leaves = [np.asarray(v) for v in jax.tree.leaves(restored)]
                    tdef = jax.tree.structure(template)
                    if spec_tree is None:
                        return jax.device_put(jax.tree.unflatten(tdef, leaves),
                                              self.mesh.replicated())
                    # zero1: restore each leaf to ITS sharding — replicating
                    # the [nw·L] state on every chip would transiently cost
                    # the nw× memory zero1 exists to avoid (the spec tree is
                    # leaf-aligned with the state by construction)
                    specs = jax.tree.leaves(
                        spec_tree, is_leaf=lambda s: isinstance(s, P))
                    assert len(specs) == len(leaves), (specs, len(leaves))
                    placed = [jax.device_put(l, self.mesh.sharding(sp))
                              for l, sp in zip(leaves, specs)]
                    return jax.tree.unflatten(tdef, placed)

                self.params = put_like(self.params, state["params"])
                self.opt_state = put_like(
                    self.opt_state, state["opt_state"],
                    None if self._opt_specs == P() else self._opt_specs)
            else:
                self.params = state["params"]
                self.opt_state = state["opt_state"]
            self._shuffle_counter = int(np.asarray(state["shuffle"]))

        fit_epochs(
            lambda: history.append(self.fit_resident(epochs=1, seed=seed)[0]),
            lambda: {"params": self.params, "opt_state": self.opt_state,
                     "shuffle": np.int64(self._shuffle_counter)},
            set_state,
            epochs, ckpt_dir, ckpt_every=ckpt_every,
            max_restarts=max_restarts, fault=fault,
            phase="mlp.epochs",
        )
        return history

    def fit(self, x, y, batch_size=8192, epochs=1, shuffle_seed=0,
            prefetch=2):
        """Host-streamed epoch training through the shared ingest
        pipeline (:mod:`harp_tpu.ingest`, PR 8): batches are contiguous
        zero-copy views of ``x``/``y``, the per-epoch shuffle permutes
        BATCH indices, and with ``prefetch >= 2`` batch j+1's f32/int32
        cast and H2D overlap batch j's step.  The pre-PR loop gathered
        ``x[perm]`` per batch — a full fancy-index copy of the dataset
        every epoch.  (Batch COMPOSITION is now fixed contiguous blocks
        in shuffled order — the same fixed-composition property the
        resident path has after staging.)  Each epoch's loop runs under
        a warn-mode flight budget: exactly the batch bytes on the wire,
        zero recompiles after the first epoch."""
        n = x.shape[0]
        nw = self.mesh.num_workers
        if n < nw:
            raise ValueError(f"need at least {nw} samples (one per worker), got {n}")
        batch_size = _effective_batch(batch_size, n, nw)
        usable = (n // batch_size) * batch_size
        n_batches = usable // batch_size
        x = np.asarray(x)
        y = np.asarray(y)
        rng = np.random.default_rng(shuffle_seed)
        order = np.arange(n_batches)  # re-permuted in place per epoch

        def prep(batch):
            xb, yb = batch
            return np.asarray(xb, np.float32), np.asarray(yb, np.int32)

        def ship(batch):
            xb, yb = batch
            return (self.mesh.shard_array(xb, 0),
                    self.mesh.shard_array(yb, 0))

        epoch_bytes = usable * (x.shape[1] * 4 + 4)  # f32 rows + i32 labels
        history = []
        with IngestPipeline(_batch_reader(x, y, batch_size, order), prep,
                            ship, depth=max(1, prefetch),
                            tag="mlp.fit") as pipe:
            for e in range(epochs):
                order[:] = rng.permutation(n_batches)
                with telemetry.budget(h2d_bytes=epoch_bytes,
                                      compiles=None if e == 0 else 0,
                                      action="warn", tag="mlp.fit.ingest"):
                    for xb, yb in pipe.stream(n_batches):
                        self.params, self.opt_state, loss, acc = self._step(
                            self.params, self.opt_state, xb, yb)
                        history.append((float(device_sync(loss)),
                                        float(device_sync(acc))))
        return history

    def predict(self, x):
        # device_put, not jnp.asarray: host data must ride the counted
        # H2D path, never risk baking in as a compile-time literal (HL003)
        xs = jax.device_put(np.asarray(x, np.float32))
        return np.asarray(self._forward(self.params, xs))

    def accuracy(self, x, y):
        return float((self.predict(x).argmax(-1) == np.asarray(y)).mean())


class TPMLPTrainer:
    """Tensor-parallel MLP on a 2-D (data × model) mesh — GSPMD style.

    Beyond-reference extension (Harp has no TP — SURVEY.md §3.5): layers
    alternate Megatron-style column-parallel (w sharded on the output dim)
    and row-parallel (input dim), the batch shards over the data axis, and
    XLA inserts every collective from the sharding annotations alone — no
    ``shard_map``, no explicit verbs.  Numerics match the DP trainer (same
    global mean loss/grads), asserted in tests.
    """

    def __init__(self, cfg: MLPConfig | None = None, mesh=None, seed=0):
        from jax.sharding import NamedSharding

        from harp_tpu.parallel.mesh import mesh_2d

        self.cfg = cfg or MLPConfig()
        if self.cfg.zero1:
            raise ValueError(
                "zero1 is DP-only: the TP trainer's optimizer state follows "
                "the GSPMD param shardings; silently replicating it would "
                "betray the memory contract zero1 promises")
        if self.cfg.grad_wire != "f32":
            raise ValueError(
                f"grad_wire={self.cfg.grad_wire!r} is DP-only: under GSPMD "
                "XLA inserts the TP collectives from sharding annotations, "
                "so there is no explicit allreduce to quantize — use "
                "MLPTrainer for a quantized gradient wire")
        if mesh is None:
            # largest model axis that divides every SHARDED layer dim (the
            # output dim of even layers, input dim of odd ones) AND the
            # device count — so the no-arg constructor works on any host
            import math

            sizes = self.cfg.sizes
            sharded_dims = [sizes[i + 1] if i % 2 == 0 else sizes[i]
                            for i in range(len(sizes) - 1)]
            g = math.gcd(*sharded_dims)
            n_dev = len(jax.devices())
            n_model = max(d for d in range(1, min(g, n_dev) + 1)
                          if g % d == 0 and n_dev % d == 0)
            mesh = mesh_2d(n_dev // n_model, n_model)
        self.mesh = mesh
        data_ax, model_ax = self.mesh.axis_names
        n_model = self.mesh.shape[model_ax]
        self._n_data = self.mesh.shape[data_ax]
        sizes = self.cfg.sizes
        for i, (fan_in, fan_out) in enumerate(zip(sizes[:-1], sizes[1:])):
            sharded_dim = fan_out if i % 2 == 0 else fan_in
            if sharded_dim % n_model != 0:
                raise ValueError(
                    f"TP needs layer {i}'s "
                    f"{'output' if i % 2 == 0 else 'input'} dim "
                    f"({sharded_dim}) divisible by the model axis "
                    f"({n_model}); adjust MLPConfig.sizes or the mesh")
        params = init_params(self.cfg, jax.random.key(seed))
        sharded = []
        for i, layer in enumerate(params):
            if i % 2 == 0:  # column-parallel: shard the output dim
                w_s, b_s = P(None, model_ax), P(model_ax)
            else:           # row-parallel: shard the input dim
                w_s, b_s = P(model_ax, None), P()
            sharded.append({
                "w": jax.device_put(layer["w"], NamedSharding(self.mesh, w_s)),
                "b": jax.device_put(layer["b"], NamedSharding(self.mesh, b_s)),
            })
        self.params = sharded
        tx = make_optimizer(self.cfg)
        self.opt_state = tx.init(self.params)
        self._batch_sharding = NamedSharding(self.mesh, P(data_ax))
        # same body as the DP trainer; GSPMD inserts the collectives, so
        # the combine step is the identity
        self._step = flightrec.track(
            jax.jit(_step_body(tx, self.cfg, lambda t: t),
                    donate_argnums=(0, 1)), "mlp.tp_step")

    def train_batch(self, x, y):
        """x: [b, features], y: [b]; b must be divisible by the data axis."""
        if len(x) % self._n_data != 0:
            raise ValueError(
                f"batch size {len(x)} not divisible by the data axis "
                f"({self._n_data}) — round the batch like MLPTrainer.fit does")
        x = jax.device_put(np.asarray(x, np.float32), self._batch_sharding)
        y = jax.device_put(np.asarray(y, np.int32), self._batch_sharding)
        self.params, self.opt_state, loss, acc = self._step(
            self.params, self.opt_state, x, y)
        return float(device_sync(loss)), float(device_sync(acc))


def synthetic_mnist(n=60_000, d=784, classes=10, seed=0, noise=0.8):
    """MNIST-shaped synthetic task (no network access in this environment):
    images are class-prototype + noise, so a real decision boundary exists."""
    rng = np.random.default_rng(seed)
    protos = rng.normal(size=(classes, d)).astype(np.float32)
    y = rng.integers(0, classes, n).astype(np.int32)
    x = 0.5 * protos[y] + rng.normal(size=(n, d)).astype(np.float32) * noise
    return x, y


def benchmark(n=60_000, batch=8192, steps=50, mesh=None, cfg=None, warmup=5):
    """Samples/sec through the DP training step on MNIST shapes.

    Headline is the device-resident epoch path (``fit_resident`` — data in
    HBM, one dispatch per epoch, like DAAL iterating an in-memory
    NumericTable); ``samples_per_sec_hostloop`` times the per-batch host
    dispatch loop (a host input pipeline) for comparison.  Measured 1× v5e
    2026-07-30: 21.2M resident vs 2.8–5.2M host-loop.
    """
    mesh = mesh or current_mesh()
    cfg = cfg or MLPConfig()
    trainer = MLPTrainer(cfg, mesh)
    x, y = synthetic_mnist(n=max(n, batch), d=cfg.sizes[0],
                           classes=cfg.sizes[-1])
    xb = trainer.mesh.shard_array(x[:batch], 0)
    yb = trainer.mesh.shard_array(y[:batch], 0)

    # host-loop path: the jitted per-batch step, dispatched per batch
    trainer.train_batch(x[:batch], y[:batch])  # compile
    t0 = time.perf_counter()
    for _ in range(steps):
        trainer.params, trainer.opt_state, loss, acc = trainer._step(
            trainer.params, trainer.opt_state, xb, yb
        )
    device_sync(loss)
    dt_host = time.perf_counter() - t0

    # resident path: whole shard staged in HBM once, scan batches per epoch.
    # Enough epochs that the one end-of-call readback (~0.1 s relay round
    # trip) is amortized, not measured.
    usable = trainer.load_resident(x, y, batch_size=batch)
    epochs = max(8, (steps * batch) // usable) * 8
    # warm with the SAME epoch count: the compiled program is keyed on it,
    # so a different count would put the compile inside the timed region
    trainer.fit_resident(epochs=epochs)
    t0 = time.perf_counter()
    hist = trainer.fit_resident(epochs=epochs)
    dt_res = time.perf_counter() - t0
    return {
        "samples_per_sec": usable * epochs / dt_res,
        "samples_per_sec_hostloop": batch * steps / dt_host,
        "steps_per_sec": usable * epochs / batch / dt_res,
        "loss": hist[-1][0],
        "acc": hist[-1][1],
        # the quantized-gradient-wire flip gate's quality field (PR 8:
        # mlp_grad_bf16/int8 candidates in measure_all + flip_decision —
        # a degraded wire must refuse on train_acc, not win on speed)
        "train_acc": hist[-1][1],
        "grad_wire": cfg.grad_wire,
        "batch": batch,
        "num_workers": mesh.num_workers,
        "half_precision": cfg.half_precision,
    }


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser(description="harp-tpu MLP (edu.iu.daal_nn parity)")
    p.add_argument("--batch", type=int, default=8192)
    p.add_argument("--steps", type=int, default=50)
    p.add_argument("--optimizer", default="sgd")
    p.add_argument("--bf16", action="store_true")
    p.add_argument("--train", action="store_true", help="2-epoch training demo")
    args = p.parse_args(argv)
    cfg = MLPConfig(optimizer=args.optimizer, half_precision=args.bf16)
    from harp_tpu.utils.metrics import benchmark_json

    if args.train:
        x, y = synthetic_mnist()
        tr = MLPTrainer(cfg)
        hist = tr.fit(x, y, batch_size=args.batch, epochs=2)
        # one-line JSON like every other CLI branch, so a teed line is a
        # parseable BENCH_local.jsonl row (ADVICE r4)
        print(benchmark_json("mlp_fit_cli", {
            "first_loss": float(hist[0][0]), "last_loss": float(hist[-1][0]),
            "train_acc": float(tr.accuracy(x[:10000], y[:10000]))}))
    else:
        print(benchmark_json("mlp_cli", benchmark(
            batch=args.batch, steps=args.steps, cfg=cfg)))


if __name__ == "__main__":
    main()
