"""Parallel SVM — allgather of support vectors, iterate.

Reference parity (SURVEY.md §3.4): Harp's ``edu.iu.svm`` wraps libsvm:
each worker trains on (local shard ∪ current global support vectors),
the support vectors are ``allgather``ed, and the loop repeats until the
SV set stabilizes — an ensemble/cascade scheme that converges to a model
close to the centralized SVM.

TPU-native design: the local solver is a linear SVM trained by batched
sub-gradient descent on the hinge loss (Pegasos-style, jitted, MXU
matmuls).  "Support vectors" = margin violators (y·f(x) < 1), exchanged
by allgather with a fixed-size top-k cap so shapes stay static (the k
closest-to-margin violators stand in for libsvm's SV list).
"""

from __future__ import annotations

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from harp_tpu.parallel import collective as C
from harp_tpu.parallel.mesh import WorkerMesh, current_mesh
from harp_tpu.utils.timing import device_sync


@dataclasses.dataclass
class SVMConfig:
    l2: float = 1e-3
    lr: float = 0.1
    inner_steps: int = 200    # pegasos steps per outer round
    outer_rounds: int = 5     # allgather-SV rounds
    sv_per_worker: int = 256  # top-k margin violators exchanged


def _pegasos(w, b, x, y, sample_w, cfg: SVMConfig):
    """Batched hinge-loss subgradient descent on (x, y) with weights."""

    def step(carry, t):
        w, b = carry
        margin = y * (x @ w + b)
        viol = (margin < 1.0).astype(jnp.float32) * sample_w
        lr = cfg.lr / (1.0 + 0.01 * t)
        gw = cfg.l2 * w - (viol * y) @ x / jnp.maximum(sample_w.sum(), 1.0)
        gb = -(viol * y).sum() / jnp.maximum(sample_w.sum(), 1.0)
        return (w - lr * gw, b - lr * gb), None

    (w, b), _ = jax.lax.scan(step, (w, b), jnp.arange(cfg.inner_steps))
    return w, b


def make_train_fn(mesh: WorkerMesh, cfg: SVMConfig, d: int, n_loc: int):
    k = min(cfg.sv_per_worker, n_loc)  # top_k needs k <= local shard size

    def prog(x, y, sample_w):
        n_loc = x.shape[0]
        w = jnp.zeros((d,), jnp.float32)
        b = jnp.float32(0.0)
        # augmented set: local shard + gathered SVs from all workers
        nw = jax.lax.axis_size("workers")
        sv_x = jnp.zeros((nw * k, d), jnp.float32)
        sv_y = jnp.zeros((nw * k,), jnp.float32)
        sv_m = jnp.zeros((nw * k,), jnp.float32)

        def round_body(carry, _):
            w, b, sv_x, sv_y, sv_m = carry
            ax = jnp.concatenate([x, sv_x], 0)
            ay = jnp.concatenate([y, sv_y], 0)
            am = jnp.concatenate([sample_w, sv_m], 0)
            w, b = _pegasos(w, b, ax, ay, am, cfg)
            # margin violators of the LOCAL shard → top-k by closeness
            margin = y * (x @ w + b)
            score = jnp.where(sample_w > 0, margin, jnp.inf)
            _, idx = jax.lax.top_k(-score, k)       # most-violating k
            cand_m = (score[idx] < 1.0).astype(jnp.float32)
            # Harp step: allgather the SV lists
            sv_x, sv_y, sv_m = C.allgather(
                (x[idx], y[idx], cand_m))
            return (w, b, sv_x, sv_y, sv_m), None

        (w, b, *_), _ = jax.lax.scan(
            round_body, (w, b, sv_x, sv_y, sv_m), None,
            length=cfg.outer_rounds)
        # final consensus: average the (identical-input-fed) models — with
        # gathered SVs shared, worker models already agree up to local data;
        # averaging matches Harp's final ensemble vote in expectation
        w = C.allreduce(w, C.Combiner.AVG)
        b = C.allreduce(b, C.Combiner.AVG)
        return w, b

    return jax.jit(mesh.shard_map(
        prog, in_specs=(mesh.spec(0),) * 3, out_specs=(P(), P()),
    ))


class SVM:
    """Host driver (the mapCollective residue for edu.iu.svm). Binary, y∈{-1,+1}."""

    def __init__(self, cfg: SVMConfig | None = None, mesh: WorkerMesh | None = None):
        self.mesh = mesh or current_mesh()
        self.cfg = cfg or SVMConfig()
        self.w = None
        self.b = None

    def fit(self, x, y):
        from harp_tpu.models.stats import _shard_rows

        x = np.asarray(x, np.float32)
        y = np.asarray(y, np.float32)
        assert set(np.unique(y)) <= {-1.0, 1.0}, "labels must be ±1"
        # padded rows get y=0 with weight 0: zero hinge gradient, never
        # selected as SVs (their margin is masked to +inf)
        xd, yd, sample_wd = _shard_rows(self.mesh, x, y)
        n_loc = xd.shape[0] // self.mesh.num_workers
        fn = make_train_fn(self.mesh, self.cfg, x.shape[1], n_loc)
        w, b = fn(xd, yd, sample_wd)
        self.w, self.b = np.asarray(w), float(np.asarray(b))
        return self

    def decision_function(self, x):
        return np.asarray(x, np.float32) @ self.w + self.b

    def predict(self, x):
        return np.sign(self.decision_function(x))

    def accuracy(self, x, y):
        return float((self.predict(x) == np.asarray(y)).mean())


def benchmark(n=500_000, d=128, mesh=None, seed=0):
    rng = np.random.default_rng(seed)
    true_w = rng.normal(size=d).astype(np.float32)
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = np.sign(x @ true_w + 0.1 * rng.normal(size=n)).astype(np.float32)
    model = SVM(mesh=mesh)
    model.fit(x, y)  # warmup: compile at full shape
    t0 = time.perf_counter()
    model.fit(x, y)
    dt = time.perf_counter() - t0
    return {"fit_sec": dt, "samples_per_sec": n / dt,
            "train_acc": model.accuracy(x[:50_000], y[:50_000]),
            "n": n, "d": d}


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser(description="harp-tpu SVM (edu.iu.svm parity)")
    p.add_argument("--n", type=int, default=500_000)
    p.add_argument("--d", type=int, default=128)
    args = p.parse_args(argv)
    print(benchmark(args.n, args.d))


if __name__ == "__main__":
    main()
