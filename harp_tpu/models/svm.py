"""Parallel SVM — allgather of support vectors, iterate.

Reference parity (SURVEY.md §3.4): Harp's ``edu.iu.svm`` wraps libsvm:
each worker trains on (local shard ∪ current global support vectors),
the support vectors are ``allgather``ed, and the loop repeats until the
SV set stabilizes — an ensemble/cascade scheme that converges to a model
close to the centralized SVM.

TPU-native design: the local solver is a linear SVM trained by batched
sub-gradient descent on the hinge loss (Pegasos-style, jitted, MXU
matmuls).  "Support vectors" = margin violators (y·f(x) < 1), exchanged
by allgather with a fixed-size top-k cap so shapes stay static (the k
closest-to-margin violators stand in for libsvm's SV list).
"""

from __future__ import annotations

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from harp_tpu.parallel import collective as C
from harp_tpu.parallel.mesh import WorkerMesh, current_mesh
from harp_tpu.utils.timing import device_sync


@dataclasses.dataclass
class SVMConfig:
    l2: float = 1e-3
    lr: float = 0.1
    inner_steps: int = 200    # pegasos steps per outer round
    outer_rounds: int = 5     # allgather-SV rounds
    sv_per_worker: int = 256  # top-k margin violators exchanged
    # the per-round SV exchange's wire (PR 12: the last per-app wire
    # with no planner byte sheet, with wdamds — ROADMAP item).  The
    # exchange rides collective.reshard blocked(0)→replicated, so
    # "bf16"/"int8" halve/quarter the [nw*k, d] SV rows per round at
    # ONE rounding per exchange (labels/masks ride exact — reshard
    # narrows float leaves only).  Flip candidates svm_sv_bf16/_int8
    # gate on train_acc (flip_decision.py); default stays exact until
    # a relay window measures them.
    sv_wire: str = "exact"
    # dtype the [n, d] feature matrix is STAGED in (PR 16: the profile
    # pass found the committed svm_cli wall is relay-H2D-staging-bound
    # at ~30 MB/s, so halving staged bytes is the model's top-ranked
    # lever — flip candidate svm_x_bf16).  Dots promote back to f32, so
    # only the stored feature precision changes; train_acc gates the
    # flip.  Default stays f32 until a relay window measures it.
    x_dtype: str = "f32"
    # inner-solve schedule (PR 17): "xla" = the 2-pass _pegasos scan;
    # "pallas" = the fused single-pass hinge-gradient kernel
    # (ops/svm_kernel.py) — one feature read per step instead of two,
    # composing with x_dtype (a bf16-staged x streams half the tile
    # bytes through the same kernel).  perfmodel.presize picked an
    # 8192-sample tile at the graded 500k×128 shape (2026-08-06,
    # predicted only — NOT yet measured; flip candidate
    # svm_kernel_pallas gates on train_acc).  Dense rows only: the
    # ELL sparse path always solves via XLA.
    algo: str = "xla"

    def __post_init__(self):
        if self.sv_wire not in ("exact", "bf16", "int8"):
            raise ValueError(
                f"sv_wire must be exact|bf16|int8, got {self.sv_wire!r}")
        if self.x_dtype not in ("f32", "bf16"):
            raise ValueError(
                f"x_dtype must be f32|bf16, got {self.x_dtype!r}")
        if self.algo not in ("xla", "pallas"):
            raise ValueError(
                f"algo must be xla|pallas, got {self.algo!r}")


def _pegasos(w, b, x, y, sample_w, cfg: SVMConfig):
    """Batched hinge-loss subgradient descent on (x, y) with weights."""

    def step(carry, t):
        w, b = carry
        margin = y * (x @ w + b)
        viol = (margin < 1.0).astype(jnp.float32) * sample_w
        lr = cfg.lr / (1.0 + 0.01 * t)
        gw = cfg.l2 * w - (viol * y) @ x / jnp.maximum(sample_w.sum(), 1.0)
        gb = -(viol * y).sum() / jnp.maximum(sample_w.sum(), 1.0)
        return (w - lr * gw, b - lr * gb), None

    (w, b), _ = jax.lax.scan(step, (w, b), jnp.arange(cfg.inner_steps))
    return w, b


def _pegasos_pallas(w, b, x, y, sample_w, cfg: SVMConfig):
    """:func:`_pegasos` on the fused Pallas kernel (ops/svm_kernel.py):
    the margin pass and the gradient contraction read each feature tile
    ONCE per step instead of XLA's two passes.  Same update sequence —
    matches the XLA arm to accumulation-order rounding (tests/
    test_svm_kernel.py pins it at rtol 1e-4).  Padding (d → 128-lane
    multiple, n → tile multiple with sw = 0) is invisible: pad features
    start at w = 0 and receive zero gradient, pad samples carry zero
    weight."""
    from harp_tpu.ops import svm_kernel
    from harp_tpu.ops.pallas_compat import interpret_default

    n, d = x.shape
    interp = interpret_default()
    dp = 128 * -(-d // 128)
    xsize = jnp.dtype(x.dtype).itemsize
    tn = svm_kernel.pick_tile(n, d, xsize)
    n_pad = tn * -(-n // tn)
    # transpose ONCE per outer round (x is scan-invariant inside the
    # inner solve); the kernel streams [dp, tn] tiles off this layout
    xT = jnp.pad(x, ((0, n_pad - n), (0, dp - d))).T        # [dp, n_pad]
    yp = jnp.pad(y, (0, n_pad - n))
    swp = jnp.pad(sample_w, (0, n_pad - n))
    denom = jnp.maximum(sample_w.sum(), 1.0)
    cd = jnp.bfloat16 if x.dtype == jnp.bfloat16 else jnp.float32
    wp0 = jnp.pad(w, (0, dp - d))

    def step(carry, t):
        wp, b = carry
        gw, gs = svm_kernel.pegasos_grad(
            wp, b, xT, yp, swp, tn=tn, compute_dtype=cd, interpret=interp)
        lr = cfg.lr / (1.0 + 0.01 * t)
        # identical to _pegasos: gw here is Σ coef·x (un-normalised) and
        # gs = Σ coef = −denom·gb
        wp = wp - lr * (cfg.l2 * wp - gw / denom)
        b = b + lr * gs / denom
        return (wp, b), None

    (wp, b), _ = jax.lax.scan(step, (wp0, b), jnp.arange(cfg.inner_steps))
    return wp[:d], b


def _pegasos_ell(w, b, ids, vals, msk, y, sample_w, cfg: SVMConfig):
    """Hinge subgradient descent on padded-ELL sparse rows.

    ids/vals/msk: [n, width] (see ``csr_to_ell``) — f(x) is a gather-dot,
    the gradient a segment-sum scatter; memory stays O(nnz), never O(n·d).
    """
    d = w.shape[0]

    def step(carry, t):
        w, b = carry
        fx = (vals * jnp.take(w, ids) * msk).sum(1) + b
        margin = y * fx
        viol = (margin < 1.0).astype(jnp.float32) * sample_w
        denom = jnp.maximum(sample_w.sum(), 1.0)
        coef = (viol * y) / denom                     # [n]
        gw_data = jax.ops.segment_sum(
            (coef[:, None] * vals * msk).ravel(), ids.ravel(), num_segments=d)
        lr = cfg.lr / (1.0 + 0.01 * t)
        return (w - lr * (cfg.l2 * w - gw_data), b + lr * coef.sum()), None

    (w, b), _ = jax.lax.scan(step, (w, b), jnp.arange(cfg.inner_steps))
    return w, b


def _make_train_prog(cfg: SVMConfig, d: int, k: int, sparse: bool):
    """Shared outer loop: local solve → top-k margin violators → allgather.

    ``sparse`` switches the row representation: dense [n, d] x vs ELL
    (ids, vals, msk) triples.  The SV exchange gathers rows the same way
    in both (fixed-size top-k keeps shapes static).
    """

    def prog(rows, y, sample_w):
        w = jnp.zeros((d,), jnp.float32)
        b = jnp.float32(0.0)
        nw = jax.lax.axis_size("workers")

        def fwd(rows, w, b):
            if sparse:
                ids, vals, msk = rows
                return (vals * jnp.take(w, ids) * msk).sum(1) + b
            return rows @ w + b

        def take_rows(rows, idx):
            return jax.tree.map(lambda a: a[idx], rows)

        sv_rows = jax.tree.map(
            lambda a: jnp.zeros((nw * k,) + a.shape[1:], a.dtype), rows)
        sv_y = jnp.zeros((nw * k,), jnp.float32)
        sv_m = jnp.zeros((nw * k,), jnp.float32)

        def round_body(carry, _):
            w, b, sv_rows, sv_y, sv_m = carry
            arows = jax.tree.map(
                lambda a, s: jnp.concatenate([a, s], 0), rows, sv_rows)
            ay = jnp.concatenate([y, sv_y], 0)
            am = jnp.concatenate([sample_w, sv_m], 0)
            if sparse:
                w, b = _pegasos_ell(w, b, *arows, ay, am, cfg)
            elif cfg.algo == "pallas":
                w, b = _pegasos_pallas(w, b, arows, ay, am, cfg)
            else:
                w, b = _pegasos(w, b, arows, ay, am, cfg)
            # margin violators of the LOCAL shard → top-k by closeness
            score = jnp.where(sample_w > 0, y * fwd(rows, w, b), jnp.inf)
            _, idx = jax.lax.top_k(-score, k)       # most-violating k
            cand_m = (score[idx] < 1.0).astype(jnp.float32)
            # Harp step: exchange the SV lists — the general reshard
            # verb (blocked→replicated lowers to the same tiled
            # all_gather the old C.allgather call emitted, bit-exact on
            # the exact wire), so cfg.sv_wire can narrow the rows and
            # the planner prices this site off its byte sheet
            # (analysis/drivers.py "svm.train")
            sv_rows, sv_y, sv_m = C.reshard(
                (take_rows(rows, idx), y[idx], cand_m),
                C.ShardSpec.blocked(0), C.ShardSpec.replicated(),
                wire=cfg.sv_wire)
            return (w, b, sv_rows, sv_y, sv_m), None

        (w, b, *_), _ = jax.lax.scan(
            round_body, (w, b, sv_rows, sv_y, sv_m), None,
            length=cfg.outer_rounds)
        # final consensus: average the (identical-input-fed) models — with
        # gathered SVs shared, worker models already agree up to local data;
        # averaging matches Harp's final ensemble vote in expectation
        w = C.allreduce(w, C.Combiner.AVG)
        b = C.allreduce(b, C.Combiner.AVG)
        return w, b

    return prog


def make_train_fn(mesh: WorkerMesh, cfg: SVMConfig, d: int, n_loc: int):
    k = min(cfg.sv_per_worker, n_loc)  # top_k needs k <= local shard size
    prog = _make_train_prog(cfg, d, k, sparse=False)
    return jax.jit(mesh.shard_map(
        prog, in_specs=(mesh.spec(0),) * 3, out_specs=(P(), P()),
    ))


def make_train_fn_ell(mesh: WorkerMesh, cfg: SVMConfig, d: int, n_loc: int):
    k = min(cfg.sv_per_worker, n_loc)
    prog = _make_train_prog(cfg, d, k, sparse=True)
    return jax.jit(mesh.shard_map(
        prog,
        in_specs=((mesh.spec(0),) * 3, mesh.spec(0), mesh.spec(0)),
        out_specs=(P(), P()),
    ))


class SVM:
    """Host driver (the mapCollective residue for edu.iu.svm). Binary, y∈{-1,+1}."""

    def __init__(self, cfg: SVMConfig | None = None, mesh: WorkerMesh | None = None):
        self.mesh = mesh or current_mesh()
        self.cfg = cfg or SVMConfig()
        self.w = None
        self.b = None

    def fit(self, x, y):
        from harp_tpu.models.stats import _shard_rows

        x = np.asarray(x, np.float32)
        y = np.asarray(y, np.float32)
        assert set(np.unique(y)) <= {-1.0, 1.0}, "labels must be ±1"
        if self.cfg.x_dtype == "bf16":
            # cast BEFORE sharding so the staged H2D bytes halve (the
            # point of the knob — the wall is the staging wire, not the
            # MXU); jnp.bfloat16 is a real numpy dtype here
            x = x.astype(jnp.bfloat16)
        # padded rows get y=0 with weight 0: zero hinge gradient, never
        # selected as SVs (their margin is masked to +inf)
        xd, yd, sample_wd = _shard_rows(self.mesh, x, y)
        n_loc = xd.shape[0] // self.mesh.num_workers
        fn = make_train_fn(self.mesh, self.cfg, x.shape[1], n_loc)
        w, b = fn(xd, yd, sample_wd)
        self.w, self.b = np.asarray(w), float(np.asarray(b))
        return self

    def fit_sparse(self, ids, vals, mask, y, n_features: int):
        """Train on padded-ELL sparse rows (``csr_to_ell`` output) —
        memory stays O(nnz) end to end, never densifying [n, d]."""
        from harp_tpu.models.stats import _shard_rows

        y = np.asarray(y, np.float32)
        assert set(np.unique(y)) <= {-1.0, 1.0}, "labels must be ±1"
        idd, vd, md, yd, sample_wd = _shard_rows(self.mesh, ids, vals, mask, y)
        n_loc = yd.shape[0] // self.mesh.num_workers
        fn = make_train_fn_ell(self.mesh, self.cfg, n_features, n_loc)
        w, b = fn((idd, vd, md), yd, sample_wd)
        self.w, self.b = np.asarray(w), float(np.asarray(b))
        return self

    def decision_function(self, x):
        return np.asarray(x, np.float32) @ self.w + self.b

    def predict(self, x):
        return np.sign(self.decision_function(x))

    def accuracy(self, x, y):
        return float((self.predict(x) == np.asarray(y)).mean())


def benchmark(n=500_000, d=128, mesh=None, seed=0, sv_wire="exact",
              x_dtype="f32", algo="xla"):
    rng = np.random.default_rng(seed)
    true_w = rng.normal(size=d).astype(np.float32)
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = np.sign(x @ true_w + 0.1 * rng.normal(size=n)).astype(np.float32)
    model = SVM(SVMConfig(sv_wire=sv_wire, x_dtype=x_dtype, algo=algo),
                mesh=mesh)
    model.fit(x, y)  # warmup: compile at full shape
    t0 = time.perf_counter()
    model.fit(x, y)
    dt = time.perf_counter() - t0
    return {"fit_sec": dt, "samples_per_sec": n / dt,
            "train_acc": model.accuracy(x[:50_000], y[:50_000]),
            "n": n, "d": d, "sv_wire": sv_wire, "x_dtype": x_dtype,
            "algo": algo}


def main(argv=None):
    import argparse

    from harp_tpu.utils.metrics import benchmark_json

    p = argparse.ArgumentParser(description="harp-tpu SVM (edu.iu.svm parity)")
    p.add_argument("--n", type=int, default=500_000)
    p.add_argument("--d", type=int, default=128)
    p.add_argument("--libsvm", default=None, metavar="FILE",
                   help="train on a libsvm-format file (the reference's "
                        "native input format) instead of synthetic data")
    p.add_argument("--zero-based", action="store_true",
                   help="file indices start at 0 (default: 1-based)")
    p.add_argument("--algo", choices=("xla", "pallas"), default="xla",
                   help="inner-solve schedule (pallas = the fused "
                        "hinge-gradient kernel, flip candidate "
                        "svm_kernel_pallas; dense rows only)")
    args = p.parse_args(argv)
    if args.libsvm:
        from harp_tpu.native.datasource import csr_to_ell, load_libsvm

        try:
            labels, indptr, indices, values, nf = load_libsvm(
                args.libsvm, zero_based=args.zero_based)
        except ValueError as e:  # e.g. a 0-based file without --zero-based
            raise SystemExit(str(e))
        classes = np.unique(labels)
        if len(classes) != 2:
            raise SystemExit(
                f"{args.libsvm}: need exactly 2 label values, got "
                f"{classes.tolist()} (binary SVM)")
        y = np.where(labels == classes[1], 1.0, -1.0).astype(np.float32)
        ids, vals, mask = csr_to_ell(indptr, indices, values)
        model = SVM().fit_sparse(ids, vals, mask, y, nf)
        fx = (vals * model.w[ids] * mask).sum(1) + model.b
        acc = float((np.sign(fx) == y).mean())
        print(benchmark_json("svm_fit_cli", {"file": args.libsvm, "n": len(labels), "d": nf,
               "classes": classes.tolist(), "train_acc": acc}))
    else:
        print(benchmark_json("svm_cli",
                             benchmark(args.n, args.d, algo=args.algo)))


if __name__ == "__main__":
    main()
