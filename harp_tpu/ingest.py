"""Prefetch-pipelined host→device ingest — THE shared streaming fast path.

Reference parity (SURVEY.md §4.2 "load points shard"): Harp mappers
streamed their HDFS split through memory while the previous block was
being consumed; the TPU-native equivalent is a bounded multi-stage host
pipeline in front of the device.  Before this module each data-bound app
owned a bespoke loop (`kmeans_stream`'s double buffer; rf/mlp/fileformat
shipped whole arrays synchronously), and the measured 1B-point walls were
host-side: relay H2D ≈ 30-40 MB/s and kmeans_ingest at 66.4k points/s
with ingest_bound_fraction 0.89 (relay v5e, 2026-08-01, BASELINE.md) —
the device was already hidden, so the remaining speed lives entirely in
the serial host read→parse→pad→quantize→device_put chain.  DrJAX
(arXiv:2403.07128) is the reference shape for reusable sharded data
movement; EQuARX (arXiv:2506.17615) motivates the int8/bf16 wire the
pipeline carries for its quantizing users.

:class:`IngestPipeline` runs the host stages as a bounded pipeline:

- **read** (thread pool, submission order): disk slice / file block /
  parse.  With ``read_threads=1`` (default) calls execute strictly in
  order on one thread, so stateful sequential sources
  (``FileSplits.next_block``) are safe; raise it only for random-access
  sources.  A reader may return a lazy view (np.memmap slice) and defer
  the actual copy to the ship stage — that is the single-copy fast path.
- **prep** (thread pool): pad / quantize / cast — the CPU-bound
  transform that used to serialize inside the dispatch loop.
- **ship** (caller thread): ``device_put``/``shard_array``.  Dispatch is
  async, and with ``depth >= 2`` finished chunks are shipped AHEAD of
  consumption, so chunk j+1's H2D overlaps chunk j's compute.

``depth`` bounds how many chunks exist beyond the one being consumed
(bounded memory, like Harp's fixed-size resource pools).  ``depth=1``
runs every stage inline on the caller thread — the same serial order as
the pre-pipeline loops, kept as the bit-exact anchor (all depths are
bit-exact: the stages are deterministic per chunk and consumption is
in order; only the overlap changes).

**Overlap accounting / stall detector.**  The pipeline times each stage,
the caller's blocked time, and the caller's busy time between chunks.
``overlap_efficiency`` = consumer_s / (consumer_s + wait_s) — of the
caller's loop time, the fraction spent computing rather than waiting on
the pipeline: 1.0 means every chunk was ready when asked; 0.5 means the
caller waited as long as it computed; a pipeline that cannot work ahead
of consumption (the canonical dead pipeline: each read gated on the
previous chunk's consumption) scores well below that despite ``depth >=
2``.  When the consumer granted no meaningful compute windows to hide
under (an idle consumer, a serial run) the score is vacuously 1.0 — no
stall can be claimed where nothing was hideable.  With ``stall_warn``
set, a sub-threshold score emits a ``RuntimeWarning`` so a dead
pipeline cannot silently measure as a working one.  The warning is
OPT-IN because on a single-core host CPU-bound stages cannot overlap by
physics (measured 2026-08-04 on this 1-core CPU host: two threaded
numpy casts take 2.04× one thread's wall), so a low score there is the
hardware, not a bug; the score is always computed and exported either
way.

Every pipeline loop in the repo wraps itself in a flight-recorder
budget (``telemetry.budget(h2d_bytes=…, compiles=0)``, warn mode) so
the relay transfer traps fail tier-1 instead of burning a window.
"""

from __future__ import annotations

import dataclasses
import threading
import time
import warnings
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable


@dataclasses.dataclass
class IngestStats:
    """One :meth:`IngestPipeline.stream` run's timing account.

    ``read_s``/``prep_s`` are stage busy sums (across their threads),
    ``ship_s`` is caller-thread device_put dispatch time, ``wait_s`` is
    caller time blocked on background stages, ``blocked_s`` is TOTAL
    caller time inside the pipeline (the comparable of the old loops'
    "host_s"), ``consumer_s`` is caller busy time between chunks (the
    compute the pipeline hides behind), ``wall_s`` the whole stream.
    """

    chunks: int = 0
    read_s: float = 0.0
    prep_s: float = 0.0
    ship_s: float = 0.0
    wait_s: float = 0.0
    blocked_s: float = 0.0
    consumer_s: float = 0.0
    wall_s: float = 0.0
    depth: int = 1
    stalls: int = 0
    overlap_efficiency: float = 1.0

    def as_dict(self) -> dict:
        return {k: (round(v, 6) if isinstance(v, float) else v)
                for k, v in dataclasses.asdict(self).items()}


class IngestPipeline:
    """Bounded multi-stage host→device chunk pipeline (module doc).

    ``read(j)`` → raw chunk j; ``prep(raw)`` → host arrays (None =
    identity); ``ship(host)`` → device arrays (None = host-only
    pipeline).  :meth:`stream` yields chunk 0..n-1 in order; ``stats``
    holds the latest run's :class:`IngestStats`.  Reusable across
    epochs (thread pools persist); use as a context manager or call
    :meth:`close` to reap the pools.
    """

    def __init__(self, read: Callable[[int], Any],
                 prep: Callable[[Any], Any] | None = None,
                 ship: Callable[[Any], Any] | None = None, *,
                 depth: int = 2, read_threads: int = 1,
                 prep_threads: int = 1, tag: str = "ingest",
                 stall_warn: float | None = None,
                 stall_min_hideable_s: float = 0.005):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        if read_threads < 1 or prep_threads < 1:
            raise ValueError("read_threads/prep_threads must be >= 1")
        self._read, self._prep, self._ship = read, prep, ship
        self.depth = int(depth)
        self.tag = tag
        self._read_threads = int(read_threads)
        self._prep_threads = int(prep_threads)
        self._stall_warn = stall_warn
        self._stall_min_s = float(stall_min_hideable_s)
        self._read_pool: ThreadPoolExecutor | None = None
        self._prep_pool: ThreadPoolExecutor | None = None
        self._lock = threading.Lock()
        self.stats = IngestStats(depth=self.depth)

    # -- lifecycle ----------------------------------------------------

    def __enter__(self) -> "IngestPipeline":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def close(self) -> None:
        """Reap the stage thread pools (idempotent)."""
        for pool in (self._read_pool, self._prep_pool):
            if pool is not None:
                pool.shutdown(wait=False, cancel_futures=True)
        self._read_pool = self._prep_pool = None

    def _pools(self):
        if self._read_pool is None:
            self._read_pool = ThreadPoolExecutor(
                self._read_threads, thread_name_prefix=f"{self.tag}-read")
        if self._prep_pool is None and self._prep is not None:
            self._prep_pool = ThreadPoolExecutor(
                self._prep_threads, thread_name_prefix=f"{self.tag}-prep")
        return self._read_pool, self._prep_pool

    # -- streaming ----------------------------------------------------

    def stream(self, n_chunks: int):
        """Yield device (or host) chunks 0..n_chunks-1 in order."""
        self.stats = IngestStats(depth=self.depth)
        if self.depth <= 1:
            return self._stream_serial(n_chunks)
        return self._stream_threaded(n_chunks)

    def _timed_ship(self, x):
        if self._ship is None:
            return x
        t0 = time.perf_counter()
        out = self._ship(x)
        self.stats.ship_s += time.perf_counter() - t0
        return out

    def _stream_serial(self, n: int):
        """depth=1: every stage inline, caller order — the serial-stage
        anchor (bit-exact with the threaded modes by construction)."""
        st = self.stats
        t_wall = time.perf_counter()
        last_out = None
        try:
            for j in range(n):
                t_in = time.perf_counter()
                if last_out is not None:
                    st.consumer_s += t_in - last_out
                t0 = time.perf_counter()
                cur = self._read(j)
                # lock ONLY the accumulation, never the read/prep work
                # itself: the pool paths take self._lock for these same
                # counters, and holding it across a stage (or a ship
                # dispatch) would be harplint HL404
                with self._lock:
                    st.read_s += time.perf_counter() - t0
                if self._prep is not None:
                    t0 = time.perf_counter()
                    cur = self._prep(cur)
                    with self._lock:
                        st.prep_s += time.perf_counter() - t0
                cur = self._timed_ship(cur)
                st.chunks += 1
                last_out = time.perf_counter()
                st.blocked_s += last_out - t_in
                yield cur
        finally:
            st.wall_s = time.perf_counter() - t_wall
            self._finalize(st)

    def _stream_threaded(self, n: int):
        st = self.stats
        read_pool, prep_pool = self._pools()
        pending: deque = deque()   # background futures, submission order
        shipped: deque = deque()   # device chunks staged ahead
        submitted = 0
        consumed = 0

        def timed_read(j):
            t0 = time.perf_counter()
            out = self._read(j)
            with self._lock:
                st.read_s += time.perf_counter() - t0
            return out

        def chained_prep(rf):
            def run():
                raw = rf.result()   # stage handoff; not counted as busy
                t0 = time.perf_counter()
                out = self._prep(raw)
                with self._lock:
                    st.prep_s += time.perf_counter() - t0
                return out
            return run

        def pump():
            nonlocal submitted
            while submitted < n and submitted - consumed < self.depth:
                rf = read_pool.submit(timed_read, submitted)
                pending.append(prep_pool.submit(chained_prep(rf))
                               if self._prep is not None else rf)
                submitted += 1

        t_wall = time.perf_counter()
        last_out = None
        try:
            for j in range(n):
                t_in = time.perf_counter()
                if last_out is not None:
                    st.consumer_s += t_in - last_out
                pump()
                if shipped:
                    cur = shipped.popleft()
                else:
                    f = pending.popleft()
                    t0 = time.perf_counter()
                    raw = f.result()
                    st.wait_s += time.perf_counter() - t0
                    cur = self._timed_ship(raw)
                # ship-ahead: start the async H2D of already-prepped
                # chunks so their transfer rides under the consumer's
                # compute (depth bounds the staged device memory)
                while (pending and pending[0].done()
                       and len(shipped) < self.depth - 1):
                    shipped.append(self._timed_ship(
                        pending.popleft().result()))
                st.chunks += 1
                consumed += 1
                pump()
                last_out = time.perf_counter()
                st.blocked_s += last_out - t_in
                yield cur
        finally:
            st.wall_s = time.perf_counter() - t_wall
            self._finalize(st)

    # -- overlap accounting -------------------------------------------

    def _finalize(self, st: IngestStats) -> None:
        if self.depth >= 2 and st.consumer_s > self._stall_min_s:
            st.overlap_efficiency = max(0.0, min(1.0, (
                st.consumer_s / (st.consumer_s + st.wait_s))))
        else:
            # nothing to hide under (idle consumer, serial mode, or a
            # trivial stream): vacuously efficient, never a stall
            st.overlap_efficiency = 1.0
        if (self._stall_warn is not None
                and st.overlap_efficiency < self._stall_warn):
            st.stalls += 1
            warnings.warn(
                f"ingest pipeline stalled [{self.tag}]: the consumer "
                f"waited {st.wait_s:.3f}s against {st.consumer_s:.3f}s of "
                f"its own compute (overlap_efficiency "
                f"{st.overlap_efficiency:.0%}) — the pipeline is not "
                "working ahead of consumption",
                RuntimeWarning, stacklevel=3)
