"""Table / Partition data model, TPU-native.

Reference parity (SURVEY.md §3.1): ``edu.iu.harp.partition`` defines
``Table`` (map ``partitionID → Partition``), ``PartitionCombiner`` (what
happens when two partitions with the same ID meet — the reduction
semantics), and ``Partitioner`` (partition ID → owning worker, default
``id % numWorkers``); ``edu.iu.harp.keyval`` layers typed KV tables with
``ValCombiner`` on top.  Underneath, ``edu.iu.harp.resource`` pools
primitive arrays to avoid GC churn.

TPU-native design (SURVEY.md §8): a model "table" is an array (or pytree)
with a sharding; the combiner is the reduction op passed to the collective;
the partitioner is the sharding spec.  The resource pool has no equivalent —
XLA owns buffers and donation (``jax.jit(..., donate_argnums)``) covers
reuse.  This module keeps a thin, host-side ``Table`` for apps that want
Harp-flavored partition bookkeeping (irregular apps: subgraph counting,
random forest), plus device-side helpers for the KV/combine-by-key pattern.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Iterator

import jax
import jax.numpy as jnp
import numpy as np

from harp_tpu.parallel.collective import Combiner
from harp_tpu.parallel.mesh import WORKER_AXIS, WorkerMesh


@dataclasses.dataclass
class Partition:
    """One partition: an ID plus its payload array — ``edu.iu.harp.partition.Partition``."""

    id: int
    data: Any  # np/jnp array (Harp: one resource array or KV struct)


def modulo_partitioner(num_workers: int) -> Callable[[int], int]:
    """Harp's default ``Partitioner``: partition ID → ``id % numWorkers``."""

    def owner(pid: int) -> int:
        return pid % num_workers

    return owner


class Table:
    """Host-side table of partitions with Harp combiner semantics.

    ``addPartition`` on an existing ID invokes the combiner, exactly like
    Harp's ``Table.addPartition`` → ``PartitionCombiner.combine``.  Device
    computation should not iterate a ``Table``; instead :meth:`to_stacked`
    produces a dense ``[num_partitions, ...]`` array to shard over the mesh,
    and :meth:`from_stacked` reconstitutes the table after a host sync.
    """

    def __init__(self, combiner: Combiner | str = Combiner.ADD):
        self.combiner = combiner if isinstance(combiner, Combiner) else Combiner(combiner)
        self._parts: dict[int, Any] = {}
        self._counts: dict[int, int] = {}  # contributions per ID (for AVG)

    # -- Harp Table API -----------------------------------------------------
    def add_partition(self, pid: int, data: Any) -> None:
        # running mean over ALL contributions for AVG, matching
        # allreduce(AVG) and combine_by_key(AVG) — not a pairwise (a+b)/2.
        # data is stored verbatim on first insert (np/jnp array or any
        # pytree); only collisions force array arithmetic.
        _accumulate(self._parts, self._counts, pid, data, self.combiner)

    def get_partition(self, pid: int) -> Any:
        return self._parts[pid]

    def partition_ids(self) -> list[int]:
        return sorted(self._parts)

    @property
    def num_partitions(self) -> int:
        return len(self._parts)

    def __iter__(self) -> Iterator[Partition]:
        for pid in self.partition_ids():
            yield Partition(pid, self._parts[pid])

    def __contains__(self, pid: int) -> bool:
        return pid in self._parts

    # -- device bridge ------------------------------------------------------
    def to_stacked(self) -> tuple[np.ndarray, np.ndarray]:
        """Dense ``(ids, stack)`` view: stack[i] is partition ids[i]'s data.

        Partition shapes must match (pad irregular partitions first — the
        TPU analogue of Harp's fixed-size resource arrays).
        """
        if not self._parts:
            raise ValueError(
                "Table has no partitions; to_stacked()/shard() need at least "
                "one (irregular apps should pad empty workers explicitly)"
            )
        ids = np.asarray(self.partition_ids(), dtype=np.int32)
        stack = np.stack([np.asarray(self._parts[i]) for i in ids])
        return ids, stack

    @classmethod
    def from_stacked(cls, ids, stack, combiner: Combiner | str = Combiner.ADD) -> "Table":
        t = cls(combiner)
        for pid, row in zip(np.asarray(ids).tolist(), np.asarray(stack)):
            t.add_partition(int(pid), row)
        return t

    def shard(self, mesh: WorkerMesh):
        """Place the stacked table on the mesh, partitions split over workers."""
        ids, stack = self.to_stacked()
        return mesh.shard_array(ids, 0), mesh.shard_array(stack, 0)


def _combine_host(comb: Combiner, a, b):
    a, b = np.asarray(a), np.asarray(b)
    if comb is Combiner.ADD:
        return a + b
    if comb is Combiner.MAX:
        return np.maximum(a, b)
    if comb is Combiner.MIN:
        return np.minimum(a, b)
    if comb is Combiner.AVG:
        raise AssertionError(
            "AVG is handled by Table.add_partition's running mean; a pairwise "
            "(a+b)/2 here would disagree with allreduce/combine_by_key AVG"
        )
    if comb is Combiner.MULTIPLY:
        return a * b
    raise AssertionError(comb)


# ---------------------------------------------------------------------------
# KV tables — edu.iu.harp.keyval equivalent.
#
# Harp layers typed key-value tables (Int2IntKVTable, Long2DoubleKVTable, …)
# over partitions: keys hash to partitions (key % numPartitions), and a
# ValCombiner resolves collisions as entries are added, so collectives can
# move whole key-partitions and merge them without app code.  Host-side
# bookkeeping stays a dict here; device compute goes through to_arrays() /
# combine_by_key (the segment-reduce form XLA vectorizes).
# ---------------------------------------------------------------------------


def _accumulate(store: dict, counts: dict, key: int, value, combiner: Combiner,
                weight: int = 1) -> None:
    """Fold one contribution into a keyed store — the one ValCombiner kernel.

    Shared by ``Table.add_partition``, ``KVTable.add`` and ``KVTable.merge``
    so AVG semantics (a true running mean over ALL contributions, matching
    ``allreduce(AVG)`` / ``combine_by_key(AVG)``) live in exactly one place.
    ``weight`` is how many raw contributions ``value`` already aggregates
    (used when merging pre-combined tables).
    """
    if key in store:
        if combiner is Combiner.AVG:
            n = counts[key]
            old = np.asarray(store[key])
            store[key] = old + (np.asarray(value) - old) * (weight / (n + weight))
        else:
            store[key] = _combine_host(combiner, store[key], value)
        counts[key] += weight
    else:
        store[key] = value
        counts[key] = weight


class KVTable:
    """Typed key→value table with ValCombiner collision semantics.

    ``add`` on an existing key invokes the combiner (Harp: ``ValCombiner.
    combine``); values may be scalars or fixed-shape arrays.  ``partition``
    buckets keys Harp-style (``key % num_partitions``) for placement; the
    ``merge`` method is what collective exchange uses to fold one worker's
    table into another's.

    AVG caveat: a mean is not closed over integers, so AVG tables store
    float64 values regardless of the typed ``dtype`` (an ``Int2IntKVTable``
    with AVG yields float means — truncating back to int would silently
    diverge from ``combine_by_key(AVG)`` and from merge round-trips).
    """

    def __init__(self, combiner: Combiner | str = Combiner.ADD,
                 num_partitions: int = 1, dtype=None):
        self.combiner = combiner if isinstance(combiner, Combiner) else Combiner(combiner)
        self.num_partitions = int(num_partitions)
        self.dtype = np.float64 if self.combiner is Combiner.AVG and dtype is not None \
            and np.issubdtype(np.dtype(dtype), np.integer) else dtype
        self._kv: dict[int, Any] = {}
        self._counts: dict[int, int] = {}

    # -- Harp KVTable API ---------------------------------------------------
    def add(self, key: int, value: Any) -> None:
        _accumulate(self._kv, self._counts, int(key),
                    np.asarray(value, dtype=self.dtype), self.combiner)

    def get(self, key: int, default: Any = None) -> Any:
        return self._kv.get(int(key), default)

    def keys(self) -> list[int]:
        return sorted(self._kv)

    def items(self) -> Iterator[tuple[int, Any]]:
        for k in self.keys():
            yield k, self._kv[k]

    def __len__(self) -> int:
        return len(self._kv)

    def __contains__(self, key: int) -> bool:
        return int(key) in self._kv

    def partition(self, key: int) -> int:
        """Owning partition for a key — Harp's ``key % numPartitions``."""
        return int(key) % self.num_partitions

    def merge(self, other: "KVTable") -> None:
        """Fold another table in through the combiner (collective merge step).

        Count-weighted: a key that aggregates ``m`` raw contributions in
        ``other`` enters the AVG running mean with weight ``m``, so merging
        pre-combined worker tables equals combining all raw contributions
        directly (parity with ``combine_by_key(AVG)``).
        """
        for k in other.keys():
            _accumulate(self._kv, self._counts, k,
                        np.asarray(other._kv[k], dtype=self.dtype),
                        self.combiner, weight=other._counts[k])

    # -- device bridge ------------------------------------------------------
    def to_arrays(self) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Dense ``(keys [n] int64, values [n, ...], counts [n] int64)`` view.

        Keys ascending; ``counts[i]`` is how many raw contributions
        ``values[i]`` aggregates (needed to merge AVG tables faithfully).
        An empty table yields values of shape ``(0,)`` — the value shape is
        unknowable before the first ``add``.
        """
        ks = self.keys()
        keys = np.asarray(ks, dtype=np.int64)
        counts = np.asarray([self._counts[k] for k in ks], dtype=np.int64)
        if ks:
            vals = np.stack([np.asarray(self._kv[k]) for k in ks])
        else:
            vals = np.zeros((0,), dtype=self.dtype or np.float32)
        return keys, vals, counts

    @classmethod
    def from_arrays(cls, keys, values, combiner: Combiner | str = Combiner.ADD,
                    num_partitions: int = 1, dtype=None, counts=None) -> "KVTable":
        # typed subclasses pin dtype in their __init__ and don't accept it
        t = cls(combiner, num_partitions) if cls is not KVTable \
            else cls(combiner, num_partitions, dtype)
        keys = np.asarray(keys).tolist()
        counts = [1] * len(keys) if counts is None else np.asarray(counts).tolist()
        for k, v, c in zip(keys, np.asarray(values), counts):
            _accumulate(t._kv, t._counts, int(k),
                        np.asarray(v, dtype=t.dtype), t.combiner, weight=int(c))
        return t


# Harp's typed table classes (edu.iu.harp.keyval.*KVTable) — the key is
# always a python int here; the *value* dtype is what the names pin down.
class Int2IntKVTable(KVTable):
    def __init__(self, combiner: Combiner | str = Combiner.ADD, num_partitions: int = 1):
        super().__init__(combiner, num_partitions, dtype=np.int32)


class Int2LongKVTable(KVTable):
    def __init__(self, combiner: Combiner | str = Combiner.ADD, num_partitions: int = 1):
        super().__init__(combiner, num_partitions, dtype=np.int64)


class Int2FloatKVTable(KVTable):
    def __init__(self, combiner: Combiner | str = Combiner.ADD, num_partitions: int = 1):
        super().__init__(combiner, num_partitions, dtype=np.float32)


class Int2DoubleKVTable(KVTable):
    def __init__(self, combiner: Combiner | str = Combiner.ADD, num_partitions: int = 1):
        super().__init__(combiner, num_partitions, dtype=np.float64)


class Long2IntKVTable(KVTable):
    def __init__(self, combiner: Combiner | str = Combiner.ADD, num_partitions: int = 1):
        super().__init__(combiner, num_partitions, dtype=np.int32)


class Long2DoubleKVTable(KVTable):
    def __init__(self, combiner: Combiner | str = Combiner.ADD, num_partitions: int = 1):
        super().__init__(combiner, num_partitions, dtype=np.float64)


def _empty_like(table: KVTable) -> KVTable:
    """Fresh empty table of the same (sub)class, combiner and partitioning."""
    if type(table) is KVTable:
        return KVTable(table.combiner, table.num_partitions, table.dtype)
    return type(table)(table.combiner, table.num_partitions)


def kv_allreduce(table: KVTable, worker_tables: list[KVTable] | None = None):
    """Merge KV tables across workers so every worker holds the union.

    The KV analogue of Harp's table allreduce: the ValCombiner resolves key
    collisions (count-weighted, so AVG matches combining raw contributions).

    Two deployment shapes:
    - single process (this machine, tests): the per-worker tables live in
      one host process — pass them as ``worker_tables``;
    - multi-host (``jax.distributed``): each host passes only its local
      ``table`` and the union is formed over all processes via a host
      allgather of the (keys, values, counts) arrays.

    Device-side dense key spaces should use :func:`combine_by_key` +
    ``allreduce`` instead — this host path serves the irregular apps.
    """
    merged = _empty_like(table)
    merged.merge(table)
    for t in worker_tables or []:
        merged.merge(t)

    if jax.process_count() > 1:
        merged = _kv_process_union(merged)
    return merged


def _kv_process_union(local: KVTable) -> KVTable:
    """Union a KV table across all ``jax.distributed`` processes.

    ``process_allgather`` needs identical shapes/dtypes on every process, so
    the value signature (rank + dims + dtype) and the pad length are agreed
    globally first; a process with an empty table (value shape unknowable
    locally) adopts the gathered signature.  Validity is carried by
    ``counts > 0``, not a key sentinel, so negative keys survive.  All
    payloads travel as raw bytes (uint8 views): ``process_allgather`` moves
    data through JAX device arrays, which with x64 disabled would silently
    downcast int64→int32 / float64→float32 — byte transport is dtype-exact
    by construction.
    """
    from jax.experimental import multihost_utils

    def gather_rows(arr2d: np.ndarray, n_rows_max: int) -> np.ndarray:
        """Allgather a [n, b] byte matrix padded to [n_rows_max, b] → [P, n_rows_max, b]."""
        padded = np.pad(arr2d, ((0, n_rows_max - arr2d.shape[0]), (0, 0)))
        out = np.asarray(multihost_utils.process_allgather(padded))
        # some jax versions omit the leading process axis when P == 1
        return out if out.ndim == 3 else out[None]

    def as_bytes(arr: np.ndarray) -> np.ndarray:
        a = np.ascontiguousarray(arr)
        return a.view(np.uint8).reshape(a.shape[0], -1) if a.size else \
            np.zeros((0, a.itemsize * (int(np.prod(a.shape[1:])) or 1)), np.uint8)

    keys, vals, counts = local.to_arrays()
    vshape = vals.shape[1:]

    # agree on (n_max, value dtype, value rank, value dims) across processes
    _MAXD = 8
    sig = np.full(3 + _MAXD, -1, np.int32)
    sig[0] = len(keys)
    if len(keys):
        sig[1] = np.dtype(vals.dtype).num
        sig[2] = len(vshape)
        sig[3:3 + len(vshape)] = vshape
    # atleast_2d: some jax versions return the bare [11] vector (no leading
    # process axis) from a single-process allgather instead of [1, 11]
    all_sig = np.atleast_2d(np.asarray(multihost_utils.process_allgather(sig)))
    n_max = int(all_sig[:, 0].max())
    nonempty = all_sig[all_sig[:, 0] > 0]
    if n_max == 0:
        return local  # every process is empty
    sigs = {tuple(r[1:]) for r in nonempty.tolist()}
    if len(sigs) > 1:
        raise ValueError(
            f"kv_allreduce: value dtypes/shapes differ across processes: "
            f"{sorted(sigs)}"
        )
    vdtype = _dtype_from_num(int(nonempty[0, 1]))
    rank = int(nonempty[0, 2])
    vshape = tuple(int(x) for x in nonempty[0, 3:3 + rank])

    flat = np.asarray(vals, vdtype).reshape(len(keys), -1) if len(keys) else \
        np.zeros((0, int(np.prod(vshape, dtype=np.int64)) if vshape else 1), vdtype)
    all_keys = gather_rows(as_bytes(keys[:, None]), n_max).view(np.int64)[..., 0]
    all_vals = gather_rows(as_bytes(flat), n_max).view(vdtype)
    all_counts = gather_rows(as_bytes(counts[:, None]), n_max).view(np.int64)[..., 0]

    union = _empty_like(local)
    for p in range(all_keys.shape[0]):
        for k, v, c in zip(all_keys[p], all_vals[p], all_counts[p]):
            if c > 0:
                _accumulate(union._kv, union._counts, int(k),
                            np.asarray(v.reshape(vshape), dtype=union.dtype),
                            union.combiner, weight=int(c))
    return union


_NUMPY_DTYPES_BY_NUM = {np.dtype(t).num: np.dtype(t) for t in
                        (np.int8, np.int16, np.int32, np.int64,
                         np.uint8, np.uint16, np.uint32, np.uint64,
                         np.float16, np.float32, np.float64, np.bool_)}


def _dtype_from_num(num: int) -> np.dtype:
    try:
        return _NUMPY_DTYPES_BY_NUM[num]
    except KeyError:
        raise ValueError(f"kv_allreduce: unsupported value dtype num {num}") from None


# ---------------------------------------------------------------------------
# Device-side KV helpers — edu.iu.harp.keyval equivalent.
# ---------------------------------------------------------------------------

def combine_by_key(keys, values, num_keys: int, op: Combiner | str = Combiner.ADD):
    """Combine values sharing a key — the ``ValCombiner`` reduction, on device.

    Harp's KV tables (``Int2IntKVTable`` …) combine colliding values as
    entries are added; on TPU the idiomatic form is a segment reduction over
    a dense key space.  ``num_keys`` must be static (pad the key space).
    """
    comb = op if isinstance(op, Combiner) else Combiner(op)
    if comb is Combiner.ADD:
        return jax.ops.segment_sum(values, keys, num_segments=num_keys)
    if comb is Combiner.MAX:
        return jax.ops.segment_max(values, keys, num_segments=num_keys)
    if comb is Combiner.MIN:
        return jax.ops.segment_min(values, keys, num_segments=num_keys)
    if comb is Combiner.AVG:
        s = jax.ops.segment_sum(values, keys, num_segments=num_keys)
        n = jax.ops.segment_sum(jnp.ones_like(values), keys, num_segments=num_keys)
        return s / jnp.maximum(n, 1)
    if comb is Combiner.MULTIPLY:
        return jax.ops.segment_prod(values, keys, num_segments=num_keys)
    raise AssertionError(comb)


def regroup_by_key(keys, values, *, capacity: int, axis: str = WORKER_AXIS):
    """Route (key, value) pairs to their owning worker — device-side KV regroup.

    Harp's KV tables repartition by ``key % numWorkers`` (the keyval
    regroup); on TPU that is one ``all_to_all`` over static capacity-bounded
    buckets (same machinery as MoE expert dispatch).  Call inside
    ``shard_map``.

    Args (per worker): ``keys [n] int`` (non-negative), ``values [n, ...]``,
    ``capacity`` = pair slots this worker may send to EACH destination.
    Returns ``(keys_out [nw·capacity], values_out [nw·capacity, ...],
    mask [nw·capacity], dropped)`` — the pairs this worker now owns, plus
    the GLOBAL count of pairs dropped by capacity overflow.  Padding slots
    carry key ``-1`` (and mask 0), which JAX segment ops drop as
    out-of-range — so :func:`combine_by_key` is safe for EVERY combiner
    (AVG/MIN/MAX included), not just value-masked ADD.
    """
    from harp_tpu.parallel.collective import allreduce as _allreduce
    from harp_tpu.parallel.collective import regroup as _regroup
    from harp_tpu.parallel.dispatch import bucket_by_destination

    nw = jax.lax.axis_size(axis)
    dest = keys % nw
    # keys travel shifted by +1 so the dispatch's zero-filled padding
    # becomes key -1 on receipt (a sentinel no valid key can collide with)
    (buf_k1, buf_v, buf_m), _, _, dropped_local = bucket_by_destination(
        dest, (keys + 1, values, jnp.ones(keys.shape[0], jnp.float32)),
        capacity, nw)
    dropped = _allreduce(dropped_local, axis=axis)

    rk1, rv, rm = _regroup((buf_k1, buf_v, buf_m),
                           axis=axis, split_dim=0, concat_dim=0)
    flat = lambda a: a.reshape((nw * capacity,) + a.shape[2:])
    return flat(rk1) - 1, flat(rv), flat(rm), dropped


# ---------------------------------------------------------------------------
# Sparse push/pull on a row-sharded global table (device view).
#
# Harp's LocalGlobalSyncCollective moves only the partitions a worker touches.
# The dense analogues live in collective.push/pull; these row-indexed forms
# serve LDA-style "rows I need" access. They materialize the gathered table
# transiently — fine for model tables that fit HBM; blocked apps (LDA) should
# prefer rotation, which never materializes the full table.
# ---------------------------------------------------------------------------

def pull_rows(global_shard, row_ids, *, axis: str = WORKER_AXIS):
    """Fetch specific rows of a row-sharded global table into local storage.

    O(table) wire: replicates the WHOLE table then takes rows — simple
    and fast when the table fits HBM anyway.  For model tables larger
    than one chip's HBM (or when touched rows ≪ table), use
    :func:`pull_rows_sparse`.  PR 11: the replication is a
    ``reshard(blocked(0) → replicated)`` — the same all_gather lowering
    the ``pull`` verb emitted, now priced by the collective planner like
    every other redistribution (bit-identical; tests/test_reshard.py).
    """
    from harp_tpu.parallel.collective import ShardSpec, reshard

    full = reshard(global_shard, ShardSpec.blocked(0),
                   ShardSpec.replicated(), axis=axis)
    return jnp.take(full, row_ids, axis=0)


def push_rows(global_shard, row_ids, deltas, *, axis: str = WORKER_AXIS):
    """Scatter-add local row deltas back into the row-sharded global table.

    O(table) wire (dense psum_scatter over the full key space); the
    O(pushed rows) form is :func:`push_rows_sparse`.
    """
    from harp_tpu.parallel.collective import push as _push

    n_total = global_shard.shape[0] * jax.lax.axis_size(axis)
    dense = jnp.zeros((n_total,) + global_shard.shape[1:], deltas.dtype)
    dense = dense.at[row_ids].add(deltas)
    return global_shard + _push(dense, axis=axis)


# ---------------------------------------------------------------------------
# True sparse pull/push — request/serve row exchange, O(requested) wire.
#
# Harp's LocalGlobalSyncCollective.pull sends each server only the partition
# ids a worker touches and receives only those partitions back (SURVEY.md
# §3.1); the dense pull_rows above instead materializes the whole table —
# fatal for a model table larger than one chip's HBM (round-1 VERDICT,
# missing #5).  These forms reproduce the partition-granular exchange with
# static shapes: ids are bucketed per owning worker (the same
# bucket_by_destination core MoE dispatch and regroup_by_key use), one
# all_to_all carries the requests, the owner serves rows from its local
# shard, a second all_to_all carries the replies back.  Wire cost is
# nw·capacity ids + nw·capacity rows — independent of the table size.
# ---------------------------------------------------------------------------


def _guard_row_requests(row_ids, valid, n_rows):
    """(requested, oor_local) — the ONE out-of-range guard both sparse
    verbs share: bad ids are excluded from the exchange (they would clamp
    into the last destination's bucket — silent corruption) and counted
    as drops, UNLIKE `valid` padding which is free to skip."""
    in_range = (row_ids >= 0) & (row_ids < n_rows)
    if valid is None:
        return in_range, jnp.sum(~in_range)
    return valid & in_range, jnp.sum(valid & ~in_range)


def pull_rows_sparse(global_shard, row_ids, *, capacity: int,
                     valid=None, axis: str = WORKER_AXIS):
    """Fetch rows of a row-sharded global table without materializing it.

    Call inside ``shard_map``.  The global table has ``nw * rows_local``
    rows, block-partitioned: worker w owns rows ``[w*rows_local,
    (w+1)*rows_local)``.  ``row_ids [m]``: global row indices this worker
    needs (duplicates fine; out-of-range ids come back ``ok=False`` and
    count as dropped — never silently served).  ``capacity``: static slot
    count this worker may request from EACH owner — requests beyond it
    are dropped (counted, never silently wrong).  ``valid`` (optional [m]
    bool): False entries are padding — they issue no request, occupy no
    capacity slot, and come back with ``ok=False``.

    Returns ``(rows [m, ...], ok [m] bool, dropped)`` where ``rows[i]``
    is zeros when ``ok[i]`` is False and ``dropped`` is the GLOBAL count
    of requests not served: capacity overflow PLUS out-of-range ids
    (a nonzero count from in-range ids means raise ``capacity``; from
    bad ids it means fix the caller).
    """
    from harp_tpu.parallel.collective import allreduce as _allreduce
    from harp_tpu.parallel.collective import regroup as _regroup
    from harp_tpu.parallel.dispatch import bucket_by_destination

    nw = jax.lax.axis_size(axis)
    me = jax.lax.axis_index(axis)
    rows_local = global_shard.shape[0]
    row_ids = row_ids.astype(jnp.int32)
    dest = row_ids // rows_local                       # owning worker
    requested, oor_local = _guard_row_requests(row_ids, valid,
                                               nw * rows_local)
    # ids travel +1 so zero-filled padding decodes to the -1 sentinel
    (req,), keep, slot, dropped_local = bucket_by_destination(
        dest, (row_ids + 1,), capacity, nw, requested)  # [nw, capacity]
    dropped = _allreduce(dropped_local + oor_local, axis=axis)

    # request phase: recv[p, j] = row id peer p wants from me (slot j)
    recv = _regroup(req, axis=axis, split_dim=0, concat_dim=0)
    local = recv - 1 - me * rows_local                 # [nw, capacity]
    valid = (recv > 0) & (local >= 0) & (local < rows_local)
    served = jnp.take(global_shard, jnp.clip(local, 0, rows_local - 1),
                      axis=0)                          # [nw, capacity, ...]
    served = served * valid.reshape(valid.shape + (1,) * (served.ndim - 2)
                                    ).astype(served.dtype)

    # reply phase: replies[o, j] = the row owner o served for my slot j
    replies = _regroup(served, axis=axis, split_dim=0, concat_dim=0)
    flat = replies.reshape((nw * capacity,) + replies.shape[2:])
    idx = jnp.where(keep, dest * capacity + slot, 0)
    out = jnp.take(flat, idx, axis=0)
    out = out * keep.reshape(keep.shape + (1,) * (out.ndim - 1)
                             ).astype(out.dtype)
    return out, keep, dropped


def _dedup_plan(row_ids, valid):
    """Shared dedup layout for the *_dedup verbs: stable-sort the ids
    (padding forced last via an INT32_MAX sentinel — ids must be below
    it, which any indexable row id is), mark first occurrences, and map
    every position to its run's representative slot.  Returns
    ``(order, inv, sorted_ids, first, run, firstpos)``; the wire then
    carries ONE slot per distinct id (``valid=first``), the Zipf-skew
    mitigation measured in benchmark.sweep_sparse_capacity."""
    ids = row_ids.astype(jnp.int32)
    sentinel = jnp.int32(jnp.iinfo(jnp.int32).max)
    keyed = ids if valid is None else jnp.where(valid, ids, sentinel)
    order = jnp.argsort(keyed)
    sw = jnp.take(keyed, order)
    first = jnp.concatenate([jnp.ones((1,), bool), sw[1:] != sw[:-1]]) \
        & (sw < sentinel)
    run = jnp.cumsum(first) - 1                 # run id per sorted position
    idx = jnp.arange(ids.shape[0])
    firstpos = jax.lax.associative_scan(jnp.maximum,
                                        jnp.where(first, idx, -1))
    inv = jnp.argsort(order)
    return order, inv, jnp.where(first, sw, 0), first, run, firstpos


def pull_rows_sparse_dedup(global_shard, row_ids, *, capacity: int,
                           valid=None, axis: str = WORKER_AXIS):
    """:func:`pull_rows_sparse` with duplicate ids sharing ONE wire slot.

    Same contract and return shape — ``(rows [m, ...], ok [m], dropped)``
    with every duplicate position receiving its row — but per-owner
    capacity is consumed per DISTINCT id, so Zipf-skewed workloads (hot
    rows requested many times per call) need far smaller capacities:
    measured on the Zipf-1.1 sweep, zero drops at 1/4 the capacity the
    raw wire needs (BASELINE.md, 2026-07-30).  ``dropped`` counts
    distinct rows not served (capacity overflow + out-of-range ids, which
    drop ONCE per distinct bad id here).  Bit-identical results to the
    raw verb when nothing drops.
    """
    order, inv, wire_ids, first, run, firstpos = _dedup_plan(row_ids, valid)
    pulled, ok_p, dropped = pull_rows_sparse(global_shard, wire_ids,
                                             capacity=capacity,
                                             valid=first, axis=axis)
    safe = jnp.maximum(firstpos, 0)
    rows = jnp.take(jnp.take(pulled, safe, axis=0), inv, axis=0)
    ok = jnp.take(jnp.take(ok_p, safe) & (firstpos >= 0), inv)
    if valid is not None:
        ok = ok & valid
    # contract parity with the raw verb: rows are ZEROS wherever ok is
    # False (padding positions would otherwise echo a neighboring run)
    rows = rows * ok.reshape(ok.shape + (1,) * (rows.ndim - 1)
                             ).astype(rows.dtype)
    return rows, ok, dropped


def push_rows_sparse_dedup(global_shard, row_ids, deltas, *,
                           capacity: int, valid=None,
                           axis: str = WORKER_AXIS):
    """:func:`push_rows_sparse` with duplicate ids sharing ONE wire slot:
    deltas for the same row are pre-summed locally (an exact segment-sum
    — note floats sum in sorted-run order, which can differ from the raw
    verb's server-side order by rounding; integer-valued deltas are
    bit-identical) and one slot per distinct id travels.  Same capacity
    economics as :func:`pull_rows_sparse_dedup`; ``dropped`` counts
    distinct rows.  Returns ``(new_shard, dropped)``.
    """
    order, inv, wire_ids, first, run, firstpos = _dedup_plan(row_ids, valid)
    d_sorted = jnp.take(deltas, order, axis=0)
    if valid is not None:
        vz = jnp.take(valid, order)
        d_sorted = d_sorted * vz.reshape(
            vz.shape + (1,) * (d_sorted.ndim - 1)).astype(d_sorted.dtype)
    summed = jax.ops.segment_sum(d_sorted, run,
                                 num_segments=row_ids.shape[0],
                                 indices_are_sorted=True)
    d_push = jnp.take(summed, run, axis=0) * first.reshape(
        first.shape + (1,) * (d_sorted.ndim - 1)).astype(d_sorted.dtype)
    return push_rows_sparse(global_shard, wire_ids, d_push,
                            capacity=capacity, valid=first, axis=axis)


def push_rows_sparse(global_shard, row_ids, deltas, *, capacity: int,
                     valid=None, axis: str = WORKER_AXIS):
    """Scatter-add row deltas into a row-sharded global table, O(pushed) wire.

    Call inside ``shard_map``.  Each (row_id, delta) pair is routed to the
    owning worker (one all_to_all of ``nw * capacity`` rows) and folded in
    with ADD — Harp's ``LocalGlobalSyncCollective.push``.  ``capacity`` =
    static slots per destination; over-capacity pushes AND out-of-range
    ids are dropped and counted (never folded, never clamped into the
    wrong bucket).  ``valid`` as in :func:`pull_rows_sparse` (padding
    pushes nothing, takes no slot, counts as nothing).  Returns
    ``(new_shard, dropped)``.
    """
    from harp_tpu.parallel.collective import allreduce as _allreduce
    from harp_tpu.parallel.collective import regroup as _regroup
    from harp_tpu.parallel.dispatch import bucket_by_destination

    nw = jax.lax.axis_size(axis)
    me = jax.lax.axis_index(axis)
    rows_local = global_shard.shape[0]
    row_ids = row_ids.astype(jnp.int32)
    dest = row_ids // rows_local
    requested, oor_local = _guard_row_requests(row_ids, valid,
                                               nw * rows_local)
    (ids1, dv), keep, _, dropped_local = bucket_by_destination(
        dest, (row_ids + 1, deltas), capacity, nw, requested)
    dropped = _allreduce(dropped_local + oor_local, axis=axis)

    rids1, rdv = _regroup((ids1, dv), axis=axis, split_dim=0, concat_dim=0)
    flat_ids = rids1.reshape(nw * capacity) - 1
    local = jnp.where(flat_ids >= 0, flat_ids - me * rows_local, -1)
    # segment_sum drops out-of-range ids, so padding (-1) vanishes
    add = jax.ops.segment_sum(
        rdv.reshape((nw * capacity,) + rdv.shape[2:]).astype(global_shard.dtype),
        local, num_segments=rows_local)
    return global_shard + add, dropped
