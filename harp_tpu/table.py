"""Table / Partition data model, TPU-native.

Reference parity (SURVEY.md §3.1): ``edu.iu.harp.partition`` defines
``Table`` (map ``partitionID → Partition``), ``PartitionCombiner`` (what
happens when two partitions with the same ID meet — the reduction
semantics), and ``Partitioner`` (partition ID → owning worker, default
``id % numWorkers``); ``edu.iu.harp.keyval`` layers typed KV tables with
``ValCombiner`` on top.  Underneath, ``edu.iu.harp.resource`` pools
primitive arrays to avoid GC churn.

TPU-native design (SURVEY.md §8): a model "table" is an array (or pytree)
with a sharding; the combiner is the reduction op passed to the collective;
the partitioner is the sharding spec.  The resource pool has no equivalent —
XLA owns buffers and donation (``jax.jit(..., donate_argnums)``) covers
reuse.  This module keeps a thin, host-side ``Table`` for apps that want
Harp-flavored partition bookkeeping (irregular apps: subgraph counting,
random forest), plus device-side helpers for the KV/combine-by-key pattern.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Iterator

import jax
import jax.numpy as jnp
import numpy as np

from harp_tpu.parallel.collective import Combiner
from harp_tpu.parallel.mesh import WORKER_AXIS, WorkerMesh


@dataclasses.dataclass
class Partition:
    """One partition: an ID plus its payload array — ``edu.iu.harp.partition.Partition``."""

    id: int
    data: Any  # np/jnp array (Harp: one resource array or KV struct)


def modulo_partitioner(num_workers: int) -> Callable[[int], int]:
    """Harp's default ``Partitioner``: partition ID → ``id % numWorkers``."""

    def owner(pid: int) -> int:
        return pid % num_workers

    return owner


class Table:
    """Host-side table of partitions with Harp combiner semantics.

    ``addPartition`` on an existing ID invokes the combiner, exactly like
    Harp's ``Table.addPartition`` → ``PartitionCombiner.combine``.  Device
    computation should not iterate a ``Table``; instead :meth:`to_stacked`
    produces a dense ``[num_partitions, ...]`` array to shard over the mesh,
    and :meth:`from_stacked` reconstitutes the table after a host sync.
    """

    def __init__(self, combiner: Combiner | str = Combiner.ADD):
        self.combiner = combiner if isinstance(combiner, Combiner) else Combiner(combiner)
        self._parts: dict[int, Any] = {}
        self._counts: dict[int, int] = {}  # contributions per ID (for AVG)

    # -- Harp Table API -----------------------------------------------------
    def add_partition(self, pid: int, data: Any) -> None:
        if pid in self._parts:
            if self.combiner is Combiner.AVG:
                # running mean over ALL contributions, matching allreduce(AVG)
                # and combine_by_key(AVG) — not a pairwise (a+b)/2.
                n = self._counts[pid]
                old = np.asarray(self._parts[pid])
                self._parts[pid] = old + (np.asarray(data) - old) / (n + 1)
            else:
                self._parts[pid] = _combine_host(self.combiner, self._parts[pid], data)
            self._counts[pid] += 1
        else:
            self._parts[pid] = data
            self._counts[pid] = 1

    def get_partition(self, pid: int) -> Any:
        return self._parts[pid]

    def partition_ids(self) -> list[int]:
        return sorted(self._parts)

    @property
    def num_partitions(self) -> int:
        return len(self._parts)

    def __iter__(self) -> Iterator[Partition]:
        for pid in self.partition_ids():
            yield Partition(pid, self._parts[pid])

    def __contains__(self, pid: int) -> bool:
        return pid in self._parts

    # -- device bridge ------------------------------------------------------
    def to_stacked(self) -> tuple[np.ndarray, np.ndarray]:
        """Dense ``(ids, stack)`` view: stack[i] is partition ids[i]'s data.

        Partition shapes must match (pad irregular partitions first — the
        TPU analogue of Harp's fixed-size resource arrays).
        """
        if not self._parts:
            raise ValueError(
                "Table has no partitions; to_stacked()/shard() need at least "
                "one (irregular apps should pad empty workers explicitly)"
            )
        ids = np.asarray(self.partition_ids(), dtype=np.int32)
        stack = np.stack([np.asarray(self._parts[i]) for i in ids])
        return ids, stack

    @classmethod
    def from_stacked(cls, ids, stack, combiner: Combiner | str = Combiner.ADD) -> "Table":
        t = cls(combiner)
        for pid, row in zip(np.asarray(ids).tolist(), np.asarray(stack)):
            t.add_partition(int(pid), row)
        return t

    def shard(self, mesh: WorkerMesh):
        """Place the stacked table on the mesh, partitions split over workers."""
        ids, stack = self.to_stacked()
        return mesh.shard_array(ids, 0), mesh.shard_array(stack, 0)


def _combine_host(comb: Combiner, a, b):
    a, b = np.asarray(a), np.asarray(b)
    if comb is Combiner.ADD:
        return a + b
    if comb is Combiner.MAX:
        return np.maximum(a, b)
    if comb is Combiner.MIN:
        return np.minimum(a, b)
    if comb is Combiner.AVG:
        raise AssertionError(
            "AVG is handled by Table.add_partition's running mean; a pairwise "
            "(a+b)/2 here would disagree with allreduce/combine_by_key AVG"
        )
    if comb is Combiner.MULTIPLY:
        return a * b
    raise AssertionError(comb)


# ---------------------------------------------------------------------------
# Device-side KV helpers — edu.iu.harp.keyval equivalent.
# ---------------------------------------------------------------------------

def combine_by_key(keys, values, num_keys: int, op: Combiner | str = Combiner.ADD):
    """Combine values sharing a key — the ``ValCombiner`` reduction, on device.

    Harp's KV tables (``Int2IntKVTable`` …) combine colliding values as
    entries are added; on TPU the idiomatic form is a segment reduction over
    a dense key space.  ``num_keys`` must be static (pad the key space).
    """
    comb = op if isinstance(op, Combiner) else Combiner(op)
    if comb is Combiner.ADD:
        return jax.ops.segment_sum(values, keys, num_segments=num_keys)
    if comb is Combiner.MAX:
        return jax.ops.segment_max(values, keys, num_segments=num_keys)
    if comb is Combiner.MIN:
        return jax.ops.segment_min(values, keys, num_segments=num_keys)
    if comb is Combiner.AVG:
        s = jax.ops.segment_sum(values, keys, num_segments=num_keys)
        n = jax.ops.segment_sum(jnp.ones_like(values), keys, num_segments=num_keys)
        return s / jnp.maximum(n, 1)
    if comb is Combiner.MULTIPLY:
        return jax.ops.segment_prod(values, keys, num_segments=num_keys)
    raise AssertionError(comb)


# ---------------------------------------------------------------------------
# Sparse push/pull on a row-sharded global table (device view).
#
# Harp's LocalGlobalSyncCollective moves only the partitions a worker touches.
# The dense analogues live in collective.push/pull; these row-indexed forms
# serve LDA-style "rows I need" access. They materialize the gathered table
# transiently — fine for model tables that fit HBM; blocked apps (LDA) should
# prefer rotation, which never materializes the full table.
# ---------------------------------------------------------------------------

def pull_rows(global_shard, row_ids, *, axis: str = WORKER_AXIS):
    """Fetch specific rows of a row-sharded global table into local storage."""
    full = jax.lax.all_gather(global_shard, axis, tiled=True)
    return jnp.take(full, row_ids, axis=0)


def push_rows(global_shard, row_ids, deltas, *, axis: str = WORKER_AXIS):
    """Scatter-add local row deltas back into the row-sharded global table."""
    n_total = global_shard.shape[0] * jax.lax.axis_size(axis)
    dense = jnp.zeros((n_total,) + global_shard.shape[1:], deltas.dtype)
    dense = dense.at[row_ids].add(deltas)
    return global_shard + jax.lax.psum_scatter(dense, axis, scatter_dimension=0, tiled=True)
