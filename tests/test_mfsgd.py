"""MF-SGD golden tests: deterministic equivalence vs a numpy model of the
same rotation schedule, plus convergence on synthetic low-rank data."""

import numpy as np
import pytest

from harp_tpu.models import mfsgd as MF

N = 8


def numpy_rotation_epoch(W, H, blocks, n, chunk, lr, reg):
    """Exact replica of one device epoch (pipelined half-slice schedule):
    at step t worker w trains half-slice 2*((w-t//2)%n) (t even) or
    2*((w-t//2-1)%n)+1 (t odd); computing halves are disjoint across
    workers at every step, so this sequential order equals the parallel one."""
    bu, bi, bv, bm, u_bound, ib2 = blocks
    ns = 2 * n
    bu = bu.reshape(n, ns, -1)
    bi = bi.reshape(n, ns, -1)
    bv = bv.reshape(n, ns, -1)
    bm = bm.reshape(n, ns, -1)
    se = cnt = 0.0
    for t in range(ns):
        for w in range(n):
            if t % 2 == 0:
                s = 2 * ((w - t // 2) % n)
            else:
                s = 2 * ((w - t // 2 - 1) % n) + 1
            Wv = W[w * u_bound:(w + 1) * u_bound]
            Hv = H[s * ib2:(s + 1) * ib2]
            B = bu.shape[-1]
            for lo in range(0, B, chunk):
                sl = slice(lo, lo + chunk)
                u, i, v, m = bu[w, s, sl], bi[w, s, sl], bv[w, s, sl], bm[w, s, sl]
                wu, hi = Wv[u], Hv[i]
                err = m * (v - (wu * hi).sum(-1))
                gw = err[:, None] * hi - reg * m[:, None] * wu
                gh = err[:, None] * wu - reg * m[:, None] * hi
                np.add.at(Wv, u, lr * gw)
                np.add.at(Hv, i, lr * gh)
                se += (err ** 2).sum()
                cnt += m.sum()
    return W, H, np.sqrt(se / max(cnt, 1))


def test_partition_ratings_small_data_does_not_pad_to_chunk(mesh):
    """Blocks narrower than chunk pad to the real max block size, not chunk."""
    rng = np.random.default_rng(1)
    nnz = 200
    u = rng.integers(0, 64, nnz).astype(np.int32)
    i = rng.integers(0, 48, nnz).astype(np.int32)
    v = rng.normal(size=nnz).astype(np.float32)
    bu, *_ = MF.partition_ratings(u, i, v, 64, 48, N, 32768)
    assert bu.shape[1] <= max(8, -(-nnz // 8) * 8)  # not 32768

    # non-multiple-of-8 chunk with bmax just below it: sublane alignment
    # must not overshoot chunk (device reshape needs B % min(chunk, B) == 0)
    u97 = np.zeros(97, np.int32)
    i97 = np.arange(97, dtype=np.int32) % 3
    b97, *_ = MF.partition_ratings(u97, i97, np.ones(97, np.float32),
                                   64, 48, N, 100)
    B = b97.shape[1]
    assert B % min(100, B) == 0

    # and training still works at the clamped width (single sub-chunk scan)
    model = MF.MFSGD(64, 48, MF.MFSGDConfig(rank=4, algo="scatter"), mesh=mesh)
    model.set_ratings(u, i, v)
    r0 = model.train_epoch()
    for _ in range(3):
        r = model.train_epoch()
    assert r < r0  # converging, not corrupted


def test_partition_ratings_roundtrip():
    rng = np.random.default_rng(0)
    nnz, n_users, n_items = 500, 64, 48
    u = rng.integers(0, n_users, nnz).astype(np.int32)
    i = rng.integers(0, n_items, nnz).astype(np.int32)
    v = rng.normal(size=nnz).astype(np.float32)
    bu, bi, bv, bm, ub, ib = MF.partition_ratings(u, i, v, n_users, n_items, N, 32)
    assert bm.sum() == nnz  # every rating lands in exactly one block
    ns = 2 * N
    # reconstruct global ids and check the multiset of triples survives
    bu2 = bu.reshape(N, ns, -1)
    bi2 = bi.reshape(N, ns, -1)
    got = []
    for w in range(N):
        for s in range(ns):
            mask = bm.reshape(N, ns, -1)[w, s] > 0
            got += list(zip(
                (bu2[w, s][mask] + w * ub).tolist(),
                (bi2[w, s][mask] + s * ib).tolist(),
                bv.reshape(N, ns, -1)[w, s][mask].tolist(),
            ))
    expect = sorted(zip(u.tolist(), i.tolist(), v.tolist()))
    assert sorted(got) == expect


def test_epoch_matches_numpy_model(mesh):
    rng = np.random.default_rng(1)
    n_users, n_items, nnz, rank, chunk = 64, 48, 600, 4, 16
    u = rng.integers(0, n_users, nnz).astype(np.int32)
    i = rng.integers(0, n_items, nnz).astype(np.int32)
    v = rng.normal(size=nnz).astype(np.float32)

    cfg = MF.MFSGDConfig(rank=rank, chunk=chunk, lr=0.02, reg=0.01, algo="scatter")
    model = MF.MFSGD(n_users, n_items, cfg, mesh, seed=3)
    W0 = np.asarray(model.W).copy()
    H0 = np.asarray(model.H).copy()
    model.set_ratings(u, i, v)
    rmse = model.train_epoch()

    blocks = MF.partition_ratings(u, i, v, n_users, n_items, N, chunk)
    Wr, Hr, rmse_ref = numpy_rotation_epoch(
        W0.copy(), H0.copy(), blocks, N, chunk, cfg.lr, cfg.reg
    )
    np.testing.assert_allclose(np.asarray(model.W), Wr, rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(model.H), Hr, rtol=2e-4, atol=2e-5)
    assert abs(rmse - rmse_ref) < 1e-3


def test_convergence_on_low_rank(mesh):
    n_users, n_items, nnz = 256, 192, 20_000
    u, i, v = MF.synthetic_ratings(n_users, n_items, nnz, rank=4, noise=0.01, seed=0)
    cfg = MF.MFSGDConfig(rank=8, chunk=512, lr=0.05, reg=0.002, algo="scatter")
    model = MF.MFSGD(n_users, n_items, cfg, mesh, seed=0)
    model.set_ratings(u, i, v)
    first = model.train_epoch()
    last = None
    for _ in range(15):
        last = model.train_epoch()
    assert last < 0.55 * first, (first, last)
    # held-out-ish check: prediction RMSE approaches the noise floor scale
    assert model.predict_rmse(u, i, v) < 0.2


def test_second_epoch_slices_home(mesh):
    """H slices must be back home after each epoch (factors() correctness):
    running two epochs must keep improving, which fails if slices misalign."""
    u, i, v = MF.synthetic_ratings(128, 96, 6_000, rank=4, noise=0.0, seed=2)
    cfg = MF.MFSGDConfig(rank=8, chunk=256, lr=0.05, reg=0.0, algo="scatter")
    model = MF.MFSGD(128, 96, cfg, mesh, seed=1)
    model.set_ratings(u, i, v)
    r1 = model.train_epoch()
    r5 = None
    for _ in range(6):
        r5 = model.train_epoch()
    assert r5 < r1


# -- dense (one-hot MXU tile) algo ------------------------------------------

def numpy_dense_epoch(W, H, tiles, n, u_tile, i_tile, lr, reg):
    """Numpy replica of the dense algo's epoch: same half-slice rotation
    schedule, per-entry batched tile updates with duplicate gradients
    summed (what the one-hot matmuls compute)."""
    eu, ei, ev, ou, oi, u_own, i_own, u_bound, ib2 = tiles
    ns = 2 * n
    NE, C = eu.shape[1], eu.shape[2]
    eu = eu.reshape(n, ns, NE, C); ei = ei.reshape(n, ns, NE, C)
    ev = ev.reshape(n, ns, NE, C)
    ou = ou.reshape(n, ns, NE); oi = oi.reshape(n, ns, NE)
    se = cnt = 0.0
    for t in range(ns):
        for w in range(n):
            s = 2 * ((w - t // 2) % n) if t % 2 == 0 else \
                2 * ((w - t // 2 - 1) % n) + 1
            Wv = W[w * u_bound:(w + 1) * u_bound]
            Hv = H[s * ib2:(s + 1) * ib2]
            for e in range(NE):
                cu, ci, cv = eu[w, s, e], ei[w, s, e], ev[w, s, e]
                m = (cu < u_tile).astype(np.float32)
                Wb = Wv[ou[w, s, e]:ou[w, s, e] + u_tile]
                Hb = Hv[oi[w, s, e]:oi[w, s, e] + i_tile]
                wu = np.where(m[:, None] > 0, Wb[np.minimum(cu, u_tile - 1)], 0.0)
                hi = np.where(m[:, None] > 0, Hb[np.minimum(ci, i_tile - 1)], 0.0)
                err = m * (cv - (wu * hi).sum(-1))
                gw = err[:, None] * hi - reg * m[:, None] * wu
                gh = err[:, None] * wu - reg * m[:, None] * hi
                gW = np.zeros_like(Wb); gH = np.zeros_like(Hb)
                valid = m > 0
                np.add.at(gW, cu[valid], gw[valid])
                np.add.at(gH, ci[valid], gh[valid])
                Wb += lr * gW
                Hb += lr * gH
                se += (err ** 2).sum()
                cnt += m.sum()
    return W, H, np.sqrt(se / max(cnt, 1))


def test_partition_ratings_tiles_roundtrip():
    rng = np.random.default_rng(0)
    nnz, n_users, n_items = 700, 64, 48
    u = rng.integers(0, n_users, nnz).astype(np.int32)
    i = rng.integers(0, n_items, nnz).astype(np.int32)
    v = rng.normal(size=nnz).astype(np.float32)
    eu, ei, ev, ou, oi, uo, io, ub, ib2 = MF.partition_ratings_tiles(
        u, i, v, n_users, n_items, N, u_tile=8, i_tile=8, entry_cap=16)
    ns = 2 * N
    got = []
    for ws in range(N * ns):
        w, s = ws // ns, ws % ns
        for e in range(eu.shape[1]):
            mask = eu[ws, e] < 8
            got += list(zip(
                (eu[ws, e][mask] + ou[ws, e] + w * uo).tolist(),
                (ei[ws, e][mask] + oi[ws, e] + s * io).tolist(),
                ev[ws, e][mask].tolist(),
            ))
    assert sorted(got) == sorted(zip(u.tolist(), i.tolist(), v.tolist()))


def test_dense_epoch_matches_numpy_model(mesh):
    import jax.numpy as jnp

    rng = np.random.default_rng(5)
    n_users, n_items, nnz = 64, 48, 600
    u = rng.integers(0, n_users, nnz).astype(np.int32)
    i = rng.integers(0, n_items, nnz).astype(np.int32)
    v = rng.normal(size=nnz).astype(np.float32)

    cfg = MF.MFSGDConfig(rank=4, algo="dense", u_tile=8, i_tile=8,
                         entry_cap=16, compute_dtype=jnp.float32,
                         lr=0.02, reg=0.01)
    model = MF.MFSGD(n_users, n_items, cfg, mesh, seed=3)
    W0 = np.asarray(model.W).copy()
    H0 = np.asarray(model.H).copy()
    model.set_ratings(u, i, v)
    rmse = model.train_epoch()

    tiles = MF.partition_ratings_tiles(u, i, v, n_users, n_items, N,
                                       u_tile=8, i_tile=8, entry_cap=16)
    Wr, Hr, rmse_ref = numpy_dense_epoch(
        W0.copy(), H0.copy(), tiles, N, 8, 8, cfg.lr, cfg.reg)
    np.testing.assert_allclose(np.asarray(model.W), Wr, rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(model.H), Hr, rtol=2e-4, atol=2e-5)
    assert abs(rmse - rmse_ref) < 1e-3


def test_carry_w_bit_identical_chain(mesh):
    """carry_w=True (the LDA carry_db lever on MF-SGD's dense path)
    shares the entry core with the slice-per-entry path, so the trained
    factors — same ratings, same seed — must be BIT-identical.  More
    users than one u_tile per worker so real tou changes exercise the
    flush/load cond."""
    import jax.numpy as jnp

    rng = np.random.default_rng(8)
    n_users, n_items, nnz = 8 * 24, 48, 2000
    u = rng.integers(0, n_users, nnz).astype(np.int32)
    i = rng.integers(0, n_items, nnz).astype(np.int32)
    v = rng.normal(size=nnz).astype(np.float32)
    out = {}
    for carry in (False, True):
        cfg = MF.MFSGDConfig(rank=4, algo="dense", u_tile=8, i_tile=8,
                             entry_cap=16, compute_dtype=jnp.float32,
                             lr=0.02, reg=0.01, carry_w=carry)
        m = MF.MFSGD(n_users, n_items, cfg, mesh, seed=3)
        m.set_ratings(u, i, v)
        rm = m.train_epochs(3)
        out[carry] = (np.asarray(m.W), np.asarray(m.H), np.asarray(rm))
    np.testing.assert_array_equal(out[True][0], out[False][0])
    np.testing.assert_array_equal(out[True][1], out[False][1])
    np.testing.assert_array_equal(out[True][2], out[False][2])


def test_carry_w_exact_for_overlapping_tile_offsets():
    """Pin the ADVICE r4 fix: the carry switch flushes the old tile BEFORE
    slicing the new region, so carry vs slice-per-entry stays bit-identical
    even for OVERLAPPING (non-tile-aligned) offsets no current partitioner
    emits.  Reverting to slice-before-flush makes offset 4 read rows 4..7
    stale after the offset-0 run updated them, and this fails."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(11)
    UR = IR = 8
    cap = 4
    W0 = rng.normal(size=(24, 3)).astype(np.float32)
    H0 = rng.normal(size=(16, 3)).astype(np.float32)
    # u-runs at offsets 0 → 4 → 0: both transitions overlap the prior tile
    ou = np.array([0, 0, 4, 4, 0], np.int32)
    oi = np.array([0, 8, 0, 8, 0], np.int32)
    eu = rng.integers(0, UR, (5, cap)).astype(np.int32)
    ei = rng.integers(0, IR, (5, cap)).astype(np.int32)
    ev = rng.normal(size=(5, cap)).astype(np.float32)
    block = (jnp.asarray(eu), jnp.asarray(ei), jnp.asarray(ev),
             jnp.asarray(ou), jnp.asarray(oi))
    out = {}
    for carry in (False, True):
        cfg = MF.MFSGDConfig(rank=3, algo="dense", u_tile=UR, i_tile=IR,
                             entry_cap=cap, compute_dtype=jnp.float32,
                             lr=0.05, reg=0.01, carry_w=carry)
        W, H, se, cnt = jax.jit(
            lambda W, H, b: MF._tile_block_update(W, H, b, cfg))(
            jnp.asarray(W0), jnp.asarray(H0), block)
        out[carry] = (np.asarray(W), np.asarray(H),
                      float(se), float(cnt))
    np.testing.assert_array_equal(out[True][0], out[False][0])
    np.testing.assert_array_equal(out[True][1], out[False][1])
    assert out[True][2:] == out[False][2:]


def test_carry_w_rejects_non_dense_algos():
    import pytest

    with pytest.raises(ValueError, match="carry_w"):
        MF.MFSGDConfig(algo="scatter", carry_w=True)
    with pytest.raises(ValueError, match="carry_w"):
        MF.MFSGDConfig(algo="pallas", carry_w=True)


def test_dense_matches_scatter_convergence(mesh):
    """Same data, same seed: both algos must converge to the same ballpark
    (they batch differently, so trajectories differ only slightly)."""
    import jax.numpy as jnp

    u, i, v = MF.synthetic_ratings(200, 150, 8_000, rank=4, noise=0.01, seed=0)
    finals = {}
    for algo in ("dense", "scatter"):
        cfg = MF.MFSGDConfig(rank=8, lr=0.05, reg=0.002, algo=algo,
                             u_tile=16, i_tile=16, entry_cap=64, chunk=64,
                             compute_dtype=jnp.float32)
        m = MF.MFSGD(200, 150, cfg, mesh, seed=0)
        m.set_ratings(u, i, v)
        for _ in range(8):
            r = m.train_epoch()
        finals[algo] = r
    assert abs(finals["dense"] - finals["scatter"]) < 0.05, finals


def test_dense_ownership_stays_balanced():
    """Tile rounding must not change worker placement: with
    ceil(n_users/N) < u_tile every rating would otherwise land on worker 0."""
    rng = np.random.default_rng(2)
    nnz = 4000
    u = rng.integers(0, 512, nnz).astype(np.int32)
    i = rng.integers(0, 256, nnz).astype(np.int32)
    v = rng.normal(size=nnz).astype(np.float32)
    eu, *_ = MF.partition_ratings_tiles(
        u, i, v, 512, 256, N, u_tile=512, i_tile=512, entry_cap=2048)
    per_worker = (eu.reshape(N, -1) < 512).sum(axis=1)
    assert (per_worker > 0).all(), per_worker  # every worker owns ratings
    assert per_worker.max() < 2 * per_worker.min(), per_worker


def test_dense_factors_strip_storage_padding(mesh):
    """factors() must cut the per-range tile padding, not just the tail."""
    import jax.numpy as jnp

    u, i, v = MF.synthetic_ratings(100, 70, 2_000, rank=3, seed=4)
    cfg = MF.MFSGDConfig(rank=4, u_tile=8, i_tile=8, entry_cap=32,
                         compute_dtype=jnp.float32, lr=0.05)
    m = MF.MFSGD(100, 70, cfg, mesh, seed=0)
    m.set_ratings(u, i, v)
    m.train_epoch()
    W, H = m.factors()
    assert W.shape == (100, 4) and H.shape == (70, 4)
    # predict_rmse goes through factors(); a misaligned strip would blow it up
    assert m.predict_rmse(u, i, v) < 2.0


def test_resume_rejects_mismatched_checkpoint_shapes(mesh, tmp_path):
    """A checkpoint from a different algo/tile config must refuse to resume
    (dynamic slices would clamp and silently train wrong rows)."""
    u, i, v = MF.synthetic_ratings(64, 48, 500, rank=2, seed=0)
    ckpt = str(tmp_path / "mf")
    m1 = MF.MFSGD(64, 48, MF.MFSGDConfig(rank=4, algo="scatter"), mesh, seed=0)
    m1.set_ratings(u, i, v)
    m1.fit(2, ckpt, ckpt_every=1)

    m2 = MF.MFSGD(64, 48, MF.MFSGDConfig(rank=4, algo="dense", u_tile=16,
                                         i_tile=16), mesh, seed=0)
    m2.set_ratings(u, i, v)
    with pytest.raises(ValueError, match="checkpoint shapes"):
        m2.fit(2, ckpt, ckpt_every=1)
