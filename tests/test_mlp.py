"""MLP tests: DP-allreduce gradient equivalence + convergence."""

import jax
import numpy as np
import pytest

from harp_tpu.models import mlp as M

N = 8


def test_dp_grads_equal_fullbatch(mesh):
    """N-worker allreduced step must equal a single-worker full-batch step."""
    cfg = M.MLPConfig(sizes=(16, 32, 4), lr=0.1)
    x, y = M.synthetic_mnist(n=64, d=16, classes=4, seed=1)

    t_multi = M.MLPTrainer(cfg, mesh, seed=0)
    l_multi, _ = t_multi.train_batch(x, y)

    from harp_tpu.parallel.mesh import WorkerMesh
    single = WorkerMesh(jax.devices()[:1])
    t_single = M.MLPTrainer(cfg, single, seed=0)
    l_single, _ = t_single.train_batch(x, y)

    assert abs(l_multi - l_single) < 1e-5
    for pm, ps in zip(jax.tree.leaves(t_multi.params), jax.tree.leaves(t_single.params)):
        np.testing.assert_allclose(np.asarray(pm), np.asarray(ps), rtol=2e-5, atol=1e-6)


@pytest.mark.parametrize("opt", ["sgd", "momentum", "adam"])
def test_training_converges(mesh, opt):
    cfg = M.MLPConfig(sizes=(32, 64, 8), lr=0.05 if opt != "adam" else 0.005,
                      optimizer=opt)
    x, y = M.synthetic_mnist(n=2048, d=32, classes=8, seed=0, noise=0.35)
    tr = M.MLPTrainer(cfg, mesh, seed=0)
    hist = tr.fit(x, y, batch_size=256, epochs=3)
    first_losses = np.mean([h[0] for h in hist[:4]])
    last_losses = np.mean([h[0] for h in hist[-4:]])
    assert last_losses < 0.6 * first_losses, (opt, first_losses, last_losses)
    assert tr.accuracy(x, y) > 0.8


def test_bf16_trains(mesh):
    cfg = M.MLPConfig(sizes=(32, 64, 8), lr=0.05, half_precision=True)
    x, y = M.synthetic_mnist(n=1024, d=32, classes=8, seed=0)
    tr = M.MLPTrainer(cfg, mesh, seed=0)
    hist = tr.fit(x, y, batch_size=256, epochs=3)
    assert hist[-1][0] < hist[0][0]
    # params stay f32 (mixed precision contract)
    assert all(p.dtype == np.float32 for p in jax.tree.leaves(
        jax.tree.map(np.asarray, tr.params)))


def test_bad_optimizer_raises(mesh):
    with pytest.raises(ValueError, match="unknown optimizer"):
        M.MLPTrainer(M.MLPConfig(optimizer="lion"), mesh)


def test_tp_matches_dp(mesh):
    """Tensor-parallel (2x4 data x model mesh) == data-parallel trainer.

    Same init seed, same full batch: the TP step's global loss/grads are
    the same math as DP's allreduce(AVG), so params must agree.
    """
    cfg = M.MLPConfig(sizes=(16, 32, 8), lr=0.05)
    x, y = M.synthetic_mnist(n=64, d=16, classes=8, seed=3)

    from harp_tpu.parallel.mesh import mesh_2d

    dp = M.MLPTrainer(cfg, mesh, seed=0)
    tp = M.TPMLPTrainer(cfg, mesh_2d(2, 4), seed=0)
    for _ in range(3):
        dp_loss, _ = dp.train_batch(x, y)
        tp_loss, _ = tp.train_batch(x, y)
    assert abs(dp_loss - tp_loss) < 1e-4
    for pl_dp, pl_tp in zip(dp.params, tp.params):
        np.testing.assert_allclose(np.asarray(pl_dp["w"]),
                                   np.asarray(pl_tp["w"]), rtol=2e-4,
                                   atol=2e-5)


def test_mesh_2d_validates_device_count(mesh):
    from harp_tpu.parallel.mesh import mesh_2d

    with pytest.raises(ValueError, match="needs"):
        mesh_2d(4, 4)  # 16 > 8 simulated devices


def test_tp_default_constructor_works(mesh):
    """TPMLPTrainer() must be instantiable on the default topology: the
    auto-picked model axis divides every sharded layer dim."""
    tp = M.TPMLPTrainer()  # default MNIST sizes (784,512,256,10), 8 devices
    x, y = M.synthetic_mnist(n=64)
    loss, _ = tp.train_batch(x, y)
    assert np.isfinite(loss)


def test_tp_validates_divisibility(mesh):
    from harp_tpu.parallel.mesh import mesh_2d

    # layer 0 is column-parallel: its output dim 10 must divide the model axis
    with pytest.raises(ValueError, match="divisible by the model axis"):
        M.TPMLPTrainer(M.MLPConfig(sizes=(16, 10, 8)), mesh_2d(1, 8))

    tp = M.TPMLPTrainer(M.MLPConfig(sizes=(16, 32, 8)), mesh_2d(2, 4))
    x, y = M.synthetic_mnist(n=63, d=16, classes=8)  # 63 % 2 != 0
    with pytest.raises(ValueError, match="batch size"):
        tp.train_batch(x, y)


def test_fit_resident_trains_and_matches_api_contract(mesh):
    """The single-dispatch resident path converges and returns per-epoch
    stats; staging must be explicit (load_resident before fit_resident)."""
    cfg = M.MLPConfig(sizes=(16, 32, 4), lr=0.1)
    tr = M.MLPTrainer(cfg, mesh, seed=0)
    with pytest.raises(RuntimeError, match="load_resident"):
        tr.fit_resident(epochs=1)

    x, y = M.synthetic_mnist(n=512, d=16, classes=4, seed=2)
    usable = tr.load_resident(x, y, batch_size=64)
    assert usable == 512
    hist = tr.fit_resident(epochs=8)
    assert len(hist) == 8
    losses = [l for l, _ in hist]
    assert losses[-1] < 0.5 * losses[0], losses  # it actually trains
    accs = [a for _, a in hist]
    assert accs[-1] > accs[0]


def test_fit_resident_epoch_shuffle_changes_order(mesh):
    """Different seeds shuffle batch order: training still converges and
    histories differ (the on-device permutation is live, not a no-op)."""
    cfg = M.MLPConfig(sizes=(16, 32, 4), lr=0.05)
    x, y = M.synthetic_mnist(n=256, d=16, classes=4, seed=3)
    hists = []
    for seed in (0, 1):
        tr = M.MLPTrainer(cfg, mesh, seed=0)
        tr.load_resident(x, y, batch_size=32, seed=0)  # same rows
        hists.append(tr.fit_resident(epochs=3, seed=seed))
    assert hists[0] != hists[1]


def test_fit_resident_sequential_calls_keep_reshuffling(mesh):
    """Back-to-back fit_resident calls must not repeat one batch order:
    the call counter advances the shuffle key."""
    cfg = M.MLPConfig(sizes=(16, 32, 4), lr=0.05)
    tr = M.MLPTrainer(cfg, mesh, seed=0)
    x, y = M.synthetic_mnist(n=256, d=16, classes=4, seed=3)
    tr.load_resident(x, y, batch_size=32, seed=0)
    h1 = tr.fit_resident(epochs=2)
    h2 = tr.fit_resident(epochs=2)

    tr2 = M.MLPTrainer(cfg, mesh, seed=0)
    tr2.load_resident(x, y, batch_size=32, seed=0)
    g1 = tr2.fit_resident(epochs=2)
    assert g1 == h1            # same starting state → reproducible
    # a repeat-order bug would make call 2 equal a fresh run's call 1 stats
    # trajectory after manually resetting params — instead simply check the
    # counter actually changed the key path
    assert tr._shuffle_counter == 4 and tr2._shuffle_counter == 2
    assert h2 != h1


@pytest.mark.parametrize("wire", ["bf16", "int8"])
def test_quantized_grad_wire_trains(mesh, wire):
    """Quantized gradient allreduce converges close to the exact wire."""
    x, y = M.synthetic_mnist(n=512, d=16, classes=4, seed=1)
    finals = {}
    for gw in ("f32", wire):
        cfg = M.MLPConfig(sizes=(16, 32, 4), lr=0.1, grad_wire=gw)
        tr = M.MLPTrainer(cfg, mesh, seed=0)
        tr.load_resident(x, y, batch_size=64)
        finals[gw] = tr.fit_resident(epochs=8)[-1][0]
    assert finals[wire] < 1.5 * finals["f32"] + 0.05, finals


def test_bad_grad_wire_raises(mesh):
    with pytest.raises(ValueError, match="grad_wire"):
        M.MLPTrainer(M.MLPConfig(sizes=(16, 32, 4), grad_wire="fp4"), mesh)


def test_tp_rejects_grad_wire(mesh):
    with pytest.raises(ValueError, match="DP-only"):
        M.TPMLPTrainer(M.MLPConfig(sizes=(16, 32, 4), grad_wire="int8"))


def test_fit_ckpt_rejects_mismatched_sizes(mesh, tmp_path):
    x, y = M.synthetic_mnist(n=128, d=16, classes=4, seed=0)
    ck = str(tmp_path / "m")
    M.MLPTrainer(M.MLPConfig(sizes=(16, 64, 4)), mesh, seed=0).fit_ckpt(
        x, y, 2, ck, batch_size=32, ckpt_every=1)
    with pytest.raises(ValueError, match="refusing to resume"):
        M.MLPTrainer(M.MLPConfig(sizes=(16, 32, 4)), mesh, seed=0).fit_ckpt(
            x, y, 4, ck, batch_size=32, ckpt_every=1)


def test_fit_ckpt_rejects_mismatched_optimizer(mesh, tmp_path):
    # same param shapes, different optimizer state (sgd vs adam): must hit
    # the clear shape guard, not an obscure tree.unflatten structure error
    x, y = M.synthetic_mnist(n=128, d=16, classes=4, seed=0)
    ck = str(tmp_path / "m")
    M.MLPTrainer(M.MLPConfig(sizes=(16, 64, 4), optimizer="sgd"),
                 mesh, seed=0).fit_ckpt(x, y, 2, ck, batch_size=32, ckpt_every=1)
    with pytest.raises(ValueError, match="refusing to resume"):
        M.MLPTrainer(M.MLPConfig(sizes=(16, 64, 4), optimizer="adam"),
                     mesh, seed=0).fit_ckpt(x, y, 4, ck, batch_size=32,
                                            ckpt_every=1)


# ---- ZeRO-1 optimizer-state sharding (beyond-reference, round 3) ------

def _flat_params(trainer):
    return np.concatenate([np.asarray(l).ravel()
                           for l in jax.tree.leaves(trainer.params)])


@pytest.mark.parametrize("opt", ["sgd", "momentum", "adam"])
def test_zero1_matches_replicated_stepwise(mesh, opt):
    """push(grads) + sharded optax update + pull(params) must equal the
    replicated allreduce + full update for elementwise optimizers —
    the math is identical; only the placement differs."""
    x, y = M.synthetic_mnist(n=256, d=32, classes=4, seed=0)
    outs = {}
    for z in (False, True):
        cfg = M.MLPConfig(sizes=(32, 48, 4), optimizer=opt, zero1=z)
        t = M.MLPTrainer(cfg, mesh, seed=0)
        losses = [t.train_batch(x, y)[0] for _ in range(3)]
        outs[z] = (losses, _flat_params(t))
    np.testing.assert_allclose(outs[True][0], outs[False][0], rtol=1e-5)
    np.testing.assert_allclose(outs[True][1], outs[False][1],
                               rtol=2e-5, atol=2e-6)


def test_zero1_state_is_actually_sharded(mesh):
    """The point of ZeRO-1: vector optimizer-state leaves live as
    [nw*L] arrays sharded over workers, not replicated copies."""
    cfg = M.MLPConfig(sizes=(32, 48, 4), optimizer="adam", zero1=True)
    t = M.MLPTrainer(cfg, mesh, seed=0)
    L = M.zero1_shard_len(cfg, N)
    vec_leaves = [l for l in jax.tree.leaves(t.opt_state) if l.ndim > 0]
    assert vec_leaves, "adam must have mu/nu vector state"
    for leaf in vec_leaves:
        assert leaf.shape[0] == N * L
        # sharded on the worker axis: each device holds 1/N of the rows
        assert len(leaf.sharding.device_set) == N
        shard_rows = {s.data.shape[0] for s in leaf.addressable_shards}
        assert shard_rows == {L}, shard_rows


def test_zero1_fit_resident_converges(mesh):
    x, y = M.synthetic_mnist(n=512, d=32, classes=4, seed=1)
    cfg = M.MLPConfig(sizes=(32, 64, 4), optimizer="adam", zero1=True)
    t = M.MLPTrainer(cfg, mesh, seed=0)
    t.load_resident(x, y, batch_size=128)
    stats = t.fit_resident(epochs=6)
    assert stats[-1][0] < stats[0][0]  # loss descends
    assert stats[-1][1] > 0.8          # and the net actually learns


@pytest.mark.parametrize("wire", ["bf16", "int8"])
def test_zero1_quantized_wire_trains(mesh, wire):
    """zero1 + narrow gradient wire (push_quantized): converges and stays
    close to the exact-wire trajectory."""
    x, y = M.synthetic_mnist(n=256, d=32, classes=4, seed=3)
    cfg = M.MLPConfig(sizes=(32, 48, 4), optimizer="adam", zero1=True,
                      grad_wire=wire)
    t = M.MLPTrainer(cfg, mesh, seed=0)
    losses = [t.train_batch(x, y)[0] for _ in range(5)]
    assert losses[-1] < losses[0]
    ref = M.MLPTrainer(M.MLPConfig(sizes=(32, 48, 4), optimizer="adam",
                                   zero1=True), mesh, seed=0)
    ref_losses = [ref.train_batch(x, y)[0] for _ in range(5)]
    # quantization noise perturbs, not derails
    assert abs(losses[-1] - ref_losses[-1]) < 0.3, (losses, ref_losses)


def test_zero1_ckpt_resume(mesh, tmp_path):
    """The recovery contract holds with sharded optimizer state — and the
    RESTORED state flows back into training steps with its sharding
    intact (restore must not replicate the [nw·L] leaves)."""
    x, y = M.synthetic_mnist(n=256, d=32, classes=4, seed=2)
    cfg = M.MLPConfig(sizes=(32, 48, 4), optimizer="adam", zero1=True)
    t = M.MLPTrainer(cfg, mesh, seed=0)
    ck = str(tmp_path / "z1")
    t.fit_ckpt(x, y, 2, ck, batch_size=128, ckpt_every=1)
    # a fresh trainer resumes at epoch 2 and trains two MORE epochs from
    # the restored sharded state
    t2 = M.MLPTrainer(cfg, mesh, seed=0)
    out = t2.fit_ckpt(x, y, 4, ck, batch_size=128, ckpt_every=1)
    assert len(out) == 2 and all(np.isfinite(l) for l, _ in out)
    L = M.zero1_shard_len(cfg, N)
    for leaf in jax.tree.leaves(t2.opt_state):
        if leaf.ndim > 0:
            assert {s.data.shape[0] for s in leaf.addressable_shards} == {L}
    # all epochs checkpointed → a rerun is a no-op
    t3 = M.MLPTrainer(cfg, mesh, seed=0)
    assert t3.fit_ckpt(x, y, 4, ck, batch_size=128, ckpt_every=1) == []
    for leaf in jax.tree.leaves(t3.opt_state):
        if leaf.ndim > 0:  # the pure-restore path keeps the sharding too
            assert {s.data.shape[0] for s in leaf.addressable_shards} == {L}


def test_zero1_rejected_by_tp_trainer(mesh):
    with pytest.raises(ValueError, match="DP-only"):
        M.TPMLPTrainer(M.MLPConfig(optimizer="adam", zero1=True), mesh)
