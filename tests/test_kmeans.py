"""KMeans golden tests vs a straight-line numpy Lloyd reference.

(Reference repo has no unit tests for apps — SURVEY.md §5; we hold ourselves
to golden-model equivalence instead.)
"""

import jax.numpy as jnp
import numpy as np
import pytest

from harp_tpu.models import kmeans as KM

N = 8


def numpy_lloyd(points, centroids, iters):
    c = centroids.copy()
    for _ in range(iters):
        d2 = ((points[:, None, :] - c[None]) ** 2).sum(-1)
        assign = d2.argmin(1)
        for j in range(c.shape[0]):
            m = assign == j
            if m.any():
                c[j] = points[m].mean(0)
    return c, assign


def blobs(n_per=64, k=4, d=5, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(k, d)) * 10
    pts = np.concatenate(
        [centers[i] + rng.normal(size=(n_per, d)) for i in range(k)]
    ).astype(np.float32)
    rng.shuffle(pts)
    return pts


def test_kmeans_matches_numpy_lloyd(mesh):
    pts = blobs(n_per=64, k=4)
    init = pts[:4].copy()
    ours, _ = KM.fit(pts, k=4, iters=5, mesh=mesh, seed=None)
    ref, _ = numpy_lloyd(pts, init, 5)
    np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-4)


def test_kmeans_regroupallgather_matches_allreduce(mesh):
    """Harp's two app variants compute identical centroids."""
    pts = blobs(n_per=64, k=8)  # k=8 divisible by the 8 workers
    a, ia = KM.fit(pts, k=8, iters=4, mesh=mesh, seed=None)
    b, ib = KM.fit(pts, k=8, iters=4, mesh=mesh, seed=None,
                   variant="regroupallgather")
    np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)
    assert abs(ia - ib) / max(abs(ia), 1.0) < 1e-5


def test_kmeans_regroupallgather_falls_back_on_indivisible_k(mesh):
    pts = blobs(n_per=32, k=3)  # 3 % 8 != 0 → allreduce fallback, same math
    a, _ = KM.fit(pts, k=3, iters=3, mesh=mesh, seed=None)
    b, _ = KM.fit(pts, k=3, iters=3, mesh=mesh, seed=None,
                  variant="regroupallgather")
    np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)


def test_kmeans_bad_variant_raises():
    import pytest

    with pytest.raises(ValueError, match="variant"):
        KM.KMeansConfig(k=2, variant="nope")


def test_kmeans_blocked_assignment_matches(mesh):
    pts = blobs(n_per=64, k=4)
    ours_full, _ = KM.fit(pts, k=4, iters=3, mesh=mesh, seed=None)
    ours_blk, _ = KM.fit(pts, k=4, iters=3, mesh=mesh, seed=None, block_points=8)
    np.testing.assert_allclose(ours_full, ours_blk, rtol=1e-5)


def test_kmeans_inertia_decreases(mesh):
    pts = blobs(n_per=64, k=4, seed=3)
    _, inertia1 = KM.fit(pts, k=4, iters=1, mesh=mesh, seed=0)
    _, inertia8 = KM.fit(pts, k=4, iters=8, mesh=mesh, seed=0)
    assert inertia8 <= inertia1


def test_kmeans_empty_cluster_keeps_centroid(mesh):
    """A centroid that captures no points must survive unchanged (no NaN)."""
    pts = np.ones((N * 4, 3), np.float32)
    far = np.full((1, 3), 1e6, np.float32)
    init = np.concatenate([np.ones((1, 3), np.float32), far])
    cfg = KM.KMeansConfig(k=2, iters=1)
    import jax
    from jax.sharding import PartitionSpec as P

    step = jax.jit(
        mesh.shard_map(
            lambda p, c: KM.kmeans_step(p, c, cfg),
            in_specs=(mesh.spec(0), P()),
            out_specs=(P(), P()),
        )
    )
    new_c, _ = step(pts, jnp.asarray(init))
    new_c = np.asarray(new_c)
    assert not np.isnan(new_c).any()
    np.testing.assert_allclose(new_c[1], far[0])  # empty cluster untouched
    np.testing.assert_allclose(new_c[0], np.ones(3))


def test_kmeans_empty_cluster_regroupallgather(mesh):
    """The two-phase variant's local-normalize phase also keeps empty
    clusters' centroids (each worker owns one centroid block here)."""
    pts = np.ones((N * 4, 3), np.float32)
    init = np.concatenate(
        [np.ones((1, 3), np.float32),
         np.arange(1, 8, dtype=np.float32)[:, None] * 1e5 * np.ones((7, 3), np.float32)]
    )
    cfg = KM.KMeansConfig(k=8, iters=1, variant="regroupallgather")
    import jax
    from jax.sharding import PartitionSpec as P

    step = jax.jit(
        mesh.shard_map(
            lambda p, c: KM.kmeans_step(p, c, cfg),
            in_specs=(mesh.spec(0), P()),
            out_specs=(P(), P()),
        )
    )
    new_c = np.asarray(step(pts, jnp.asarray(init))[0])
    assert not np.isnan(new_c).any()
    np.testing.assert_allclose(new_c[0], np.ones(3))
    np.testing.assert_allclose(new_c[1:], init[1:])  # 7 empty clusters survive


def test_kmeans_bf16_close_to_f32(mesh):
    pts = blobs(n_per=64, k=4)
    f32, _ = KM.fit(pts, k=4, iters=3, mesh=mesh, seed=None)
    bf16, _ = KM.fit(pts, k=4, iters=3, mesh=mesh, seed=None, dtype=jnp.bfloat16)
    # blobs are well separated; assignments agree so means agree closely
    np.testing.assert_allclose(bf16.astype(np.float32), f32, rtol=0.05, atol=0.05)


def test_quantize_points_int8_error_bound():
    from harp_tpu.models.kmeans import quantize_points_int8

    rng = np.random.default_rng(0)
    x = (rng.normal(size=(200, 6)) * rng.uniform(0.1, 50, 6)).astype(np.float32)
    q, scale = quantize_points_int8(x)
    assert q.dtype == np.int8
    # |err| ≤ scale/2 at exact ties; allow f32 arithmetic slack on top
    bound = np.broadcast_to(scale[None, :] * 0.5001 + 1e-6, x.shape)
    np.testing.assert_array_less(np.abs(q.astype(np.float32) * scale - x),
                                 bound)


def test_int8_quantized_fit_matches_f32_on_separated_clusters(mesh):
    """On well-separated clusters the int8 path finds the same centroids as
    f32 (assignment errors only possible within the quantization step)."""
    from harp_tpu.models.kmeans import fit

    rng = np.random.default_rng(3)
    centers = rng.normal(size=(4, 8)).astype(np.float32) * 10
    pts = np.concatenate([
        centers[i] + 0.1 * rng.normal(size=(64, 8)).astype(np.float32)
        for i in range(4)
    ])
    c_f32, _ = fit(pts, k=4, iters=8, mesh=mesh, seed=0)
    c_q, _ = fit(pts, k=4, iters=8, mesh=mesh, seed=0, quantize="int8",
               use_pallas=False)  # the XLA int8 arm, explicitly
    # same clustering: centroids agree to quantization tolerance
    np.testing.assert_allclose(np.sort(c_q, 0), np.sort(c_f32, 0),
                               rtol=5e-2, atol=0.2)

    # clustering QUALITY matches: true (f32, numpy) inertia of both centroid
    # sets is near-identical (the device-side int8 inertia is documented as
    # approximate — it folds the quantized score matrix)
    def true_inertia(c):
        d2 = ((pts[:, None] - c[None]) ** 2).sum(-1)
        return d2.min(1).sum()

    assert true_inertia(c_q) < 1.05 * true_inertia(c_f32) + 1e-3


def test_quantize_config_validation(mesh):
    from harp_tpu.models.kmeans import KMeansConfig

    with pytest.raises(ValueError, match="quantize must be"):
        KMeansConfig(quantize="fp4")
    with pytest.raises(ValueError, match="incompatible"):
        KMeansConfig(quantize="int8", block_points=128)
    # round 3: use_pallas + int8 is the FUSED kernel path, no longer an error
    KMeansConfig(quantize="int8", use_pallas=True)


def test_kmeanspp_init_rescues_degenerate_seeds(mesh):
    """On well-separated clusters, kmeans++ lands near the optimum for
    seeds where random-row init strands Lloyd in a 2x-worse basin."""
    from harp_tpu.models.kmeans import fit

    rng = np.random.default_rng(0)
    centers = rng.normal(size=(8, 16)).astype(np.float32) * 8
    pts = np.concatenate([
        centers[i] + 0.2 * rng.normal(size=(128, 16)).astype(np.float32)
        for i in range(8)
    ])

    def true_inertia(c):
        return ((pts[:, None] - c[None]) ** 2).sum(-1).min(1).sum()

    # near-optimal reference: Lloyd from the TRUE centers
    c_opt, _ = fit(np.concatenate([centers, pts]), k=8, iters=8, mesh=mesh,
                   seed=None)
    opt = true_inertia(c_opt)
    worst = 0.0
    for seed in range(5):
        cpp, _ = fit(pts, k=8, iters=8, mesh=mesh, seed=seed, init="kmeans++")
        worst = max(worst, true_inertia(cpp))
    # every seed lands within 5% of optimal (random init measured ~2x off
    # on 2 of these 5 seeds)
    assert worst < 1.05 * opt, (worst, opt)


def test_fit_rejects_unknown_init(mesh):
    from harp_tpu.models.kmeans import fit

    with pytest.raises(ValueError, match="init must be"):
        fit(np.zeros((16, 2), np.float32), k=2, mesh=mesh, init="zzz")


def test_kmeanspp_handles_fewer_distinct_rows_than_k(mesh):
    from harp_tpu.models.kmeans import fit, kmeanspp_init

    pts = np.tile(np.array([[1.0, 2.0], [3.0, 4.0]], np.float32), (8, 1))
    c = kmeanspp_init(pts, k=4, seed=0)
    assert c.shape == (4, 2) and np.isfinite(c).all()
    cf, _ = fit(pts, k=4, iters=3, mesh=mesh, init="kmeans++")
    assert np.isfinite(cf).all()


def test_kmeanspp_dominated_distances_never_reject_probabilities():
    """One far outlier makes d2 mass concentrate on a single entry; the
    selection probabilities are computed in float64 so rng.choice's
    sum-to-one check holds regardless of numpy's dtype-dependent
    tolerance policy (f32 division noise is ~6e-8 per entry; the f64
    path keeps the deviation at ~1e-16).  Sweeps seeds as a canary —
    any future revert to f32 probabilities risks intermittent
    'probabilities do not sum to 1' on skewed data."""
    from harp_tpu.models.kmeans import kmeanspp_init

    rng = np.random.default_rng(0)
    pts = (rng.normal(size=(512, 8)) * 1e-3).astype(np.float32)
    pts[0] = 1e4  # dominating outlier
    for seed in range(25):
        c = kmeanspp_init(pts, k=4, seed=seed)
        assert c.shape == (4, 8) and np.isfinite(c).all()
