"""Streaming/blocked-epoch KMeans (north-star 1B-point path).

Golden contract: fit_streaming is full-batch Lloyd — bitwise-close to
the device-resident kmeans.fit on the same data/init — only the
execution is chunked.  SURVEY.md §1 (north-star), VERDICT r1 item 4.
"""

import numpy as np
import pytest

from harp_tpu.models import kmeans as K
from harp_tpu.models import kmeans_stream as KS


def _blobs(n=4096, d=24, c=4, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.normal(size=(n, d)).astype(np.float32)
            + (rng.integers(0, c, size=(n, 1)) * 6).astype(np.float32))


def test_streaming_matches_resident_fit(mesh):
    pts = _blobs()
    c0, i0 = K.fit(pts, k=8, iters=6, mesh=mesh, seed=3)
    # chunk 1000 → padded tail chunk exercises the mask path
    c1, i1 = KS.fit_streaming(pts, k=8, iters=6, chunk_points=1000,
                              mesh=mesh, seed=3)
    assert np.allclose(c0, c1, rtol=1e-4, atol=1e-4)
    assert abs(i0 - i1) < 1e-3 * abs(i0)


def test_streaming_single_chunk_degenerate(mesh):
    # chunk >= n: one (padded) chunk — must still equal resident fit
    pts = _blobs(n=1024)
    c0, i0 = K.fit(pts, k=4, iters=4, mesh=mesh, seed=1)
    c1, i1 = KS.fit_streaming(pts, k=4, iters=4, chunk_points=1 << 20,
                              mesh=mesh, seed=1)
    assert np.allclose(c0, c1, rtol=1e-4, atol=1e-4)
    assert abs(i0 - i1) < 1e-3 * abs(i0)


def test_streaming_history_monotone(mesh):
    pts = _blobs()
    _, _, hist = KS.fit_streaming(pts, k=8, iters=6, chunk_points=512,
                                  mesh=mesh, seed=3, return_history=True)
    assert len(hist) == 6
    assert all(hist[i + 1] <= hist[i] * (1 + 1e-6) for i in range(5))


def test_streaming_int8_close_to_f32(mesh):
    pts = _blobs()
    _, i0 = K.fit(pts, k=8, iters=6, mesh=mesh, seed=3)
    c, i8 = KS.fit_streaming(pts, k=8, iters=6, chunk_points=1000,
                             mesh=mesh, seed=3, quantize="int8")
    assert np.isfinite(c).all()
    assert abs(i8 - i0) < 0.05 * abs(i0)


def test_streaming_memmap_source(mesh, tmp_path):
    # disk-backed source streams without materializing (the 1B-point
    # story: np.memmap slices load per chunk)
    pts = _blobs(n=2048)
    f = tmp_path / "pts.npy"
    np.save(f, pts)
    mm = np.load(f, mmap_mode="r")
    c0, i0 = K.fit(pts, k=4, iters=3, mesh=mesh, seed=2)
    c1, i1 = KS.fit_streaming(mm, k=4, iters=3, chunk_points=700,
                              mesh=mesh, seed=2)
    assert np.allclose(c0, c1, rtol=1e-4, atol=1e-4)
    assert abs(i0 - i1) < 1e-3 * abs(i0)


def test_streaming_kmeanspp_init(mesh):
    pts = _blobs()
    c, inertia = KS.fit_streaming(pts, k=8, iters=4, chunk_points=1000,
                                  mesh=mesh, seed=0, init="kmeans++")
    assert np.isfinite(c).all() and np.isfinite(inertia)


def test_streaming_config_validation():
    with pytest.raises(ValueError, match="quantize"):
        KS.StreamConfig(quantize="fp4")
    with pytest.raises(ValueError, match="k must"):
        KS.StreamConfig(k=0)
    with pytest.raises(ValueError, match="chunk_points"):
        KS.StreamConfig(chunk_points=0)


def test_streaming_int8_rejects_wrap_prone_chunk(mesh, monkeypatch):
    # the exact-int32 accumulation bound applies per chunk (same guard
    # as kmeans.fit; cross-chunk accumulation is f32 so only the chunk
    # row count matters).  The real limit needs ~135M rows to trip, so
    # shrink it — the guard reads the module global at call time.
    monkeypatch.setattr(KS, "_INT8_SUM_ROW_LIMIT", 4)
    pts = _blobs(n=256)
    with pytest.raises(ValueError, match="accumulation bound"):
        KS.fit_streaming(pts, k=4, iters=1, chunk_points=256,
                         mesh=mesh, quantize="int8")


def test_streaming_checkpoint_crash_recovery_equals_clean_run(mesh, tmp_path):
    """Same recovery contract as the other fits: a crash mid-run resumes
    from the checkpoint and produces the identical result (epochs are
    deterministic given centroids — data is re-read each sweep)."""
    from harp_tpu.utils.fault import FaultInjector

    pts = _blobs()
    clean_c, clean_i, clean_h = KS.fit_streaming(
        pts, k=8, iters=6, chunk_points=1000, mesh=mesh, seed=3,
        return_history=True)
    ck = str(tmp_path / "ks")
    c, i, h = KS.fit_streaming(
        pts, k=8, iters=6, chunk_points=1000, mesh=mesh, seed=3,
        return_history=True, ckpt_dir=ck, ckpt_every=2,
        fault=FaultInjector(fail_at=(4,)))
    np.testing.assert_allclose(c, clean_c, rtol=1e-6)
    np.testing.assert_allclose(h, clean_h, rtol=1e-6)


def test_streaming_ckpt_rejects_mismatched_k(mesh, tmp_path):
    pts = _blobs()
    ck = str(tmp_path / "ks")
    KS.fit_streaming(pts, k=8, iters=2, chunk_points=1000, mesh=mesh,
                     seed=3, ckpt_dir=ck, ckpt_every=1)
    with pytest.raises(ValueError, match="refusing to resume"):
        KS.fit_streaming(pts, k=4, iters=4, chunk_points=1000, mesh=mesh,
                         seed=3, ckpt_dir=ck, ckpt_every=1)


def test_synthetic_fused_benchmark_converges(mesh):
    # the ONE-jit full-scale formulation: same dataset every epoch, so
    # inertia must descend across separate calls with more iters
    r1 = KS.benchmark_streaming(n=65536, d=16, k=16, iters=1,
                                chunk_points=8192, mesh=mesh, warmup=1)
    r4 = KS.benchmark_streaming(n=65536, d=16, k=16, iters=6,
                                chunk_points=8192, mesh=mesh, warmup=1)
    assert r4["inertia"] < r1["inertia"]
    assert r1["n_chunks"] == 8 and r1["n"] == 65536
    assert "gen_sec_per_iter" not in r1  # calibration is opt-in


def test_gen_calibration_post_processing():
    # falsifiable unit contract of the calibration arithmetic: a credible
    # gen time subtracts; a gen time eating >= 90% of the run must yield
    # None, never an absurd 1e9x "ex-gen" rate
    ok = KS._ex_gen_fields(dt=10.0, gen_dt=4.0, iters=2)
    assert ok["gen_sec_per_iter"] == 2.0
    np.testing.assert_allclose(ok["iters_per_sec_ex_gen"], 2 / 6.0)
    bad = KS._ex_gen_fields(dt=10.0, gen_dt=9.5, iters=2)
    assert bad["iters_per_sec_ex_gen"] is None
    assert "invalid" in bad["gen_calibration"]
    worse = KS._ex_gen_fields(dt=1.0, gen_dt=2.0, iters=2)  # gen > total
    assert worse["iters_per_sec_ex_gen"] is None


def test_gen_calibration_runs_end_to_end(mesh):
    r = KS.benchmark_streaming(n=65536, d=16, k=16, iters=4,
                               chunk_points=8192, mesh=mesh, warmup=1,
                               calibrate_gen=True)
    assert r["gen_sec_per_iter"] > 0  # the twin really ran the RNG
    # either a credible subtraction or an explicit invalid flag
    assert (r["iters_per_sec_ex_gen"] is None) == ("gen_calibration" in r)


def test_benchmark_ingest_memmap(mesh, tmp_path):
    """Real-ingest harness (VERDICT r2 item 2): disk npy through the
    instrumented fit_streaming — pipeline fields present and coherent."""
    pts = _blobs(n=4096, d=16)
    f = tmp_path / "pts.npy"
    np.save(f, pts.astype(np.float16))  # the 100M-row disk dtype
    mm = np.load(f, mmap_mode="r")
    import os

    r = KS.benchmark_ingest(mm, k=8, iters=2, chunk_points=1024,
                            mesh=mesh, disk_bytes=os.path.getsize(f),
                            compare_synthetic=True)
    assert r["points_per_sec"] > 0
    assert r["host_sec_per_epoch"] > 0 and r["host_gb_per_sec"] > 0
    assert 0 < r["overlap_efficiency"] <= 1.0
    assert 0 < r["ingest_bound_fraction"] <= 1.0
    # host time is a lower bound on epoch wall, never above it
    assert r["host_sec_per_epoch"] <= r["epoch_sec"] + 1e-9
    assert r["synthetic_sec_per_epoch"] > 0
    assert r["source"] == "memmap" and np.isfinite(r["inertia"])


def test_benchmark_ingest_csv_source(mesh, tmp_path):
    from harp_tpu.native.datasource import CSVPoints

    pts = _blobs(n=1500, d=8)
    f = tmp_path / "pts.csv"
    np.savetxt(f, pts, fmt="%.5f", delimiter=",")
    r = KS.benchmark_ingest(CSVPoints(str(f), chunk_rows=512), k=4,
                            iters=2, chunk_points=512, mesh=mesh,
                            disk_bytes=f.stat().st_size)
    assert r["points_per_sec"] > 0 and r["source"] == "CSVPoints"
    assert np.isfinite(r["inertia"])


def test_instrument_hook_epoch_records(mesh):
    inst: dict = {}
    pts = _blobs(n=2048, d=8)
    KS.fit_streaming(pts, k=4, iters=3, chunk_points=512, mesh=mesh,
                     instrument=inst)
    eps = inst["epochs"]
    assert len(eps) == 3
    for e in eps:
        assert e["host_s"] > 0 and e["sync_s"] >= 0
        assert e["epoch_s"] >= e["host_s"]


def test_streaming_local_single_process_matches_global(mesh):
    """fit_streaming_local is fit_streaming with a per-process chunk
    layout: with the same explicit init the clusterings agree (the
    chunk partitioning only regroups the f32 partial sums)."""
    pts = _blobs(n=3100)  # not divisible by workers or chunks: pad paths
    c0 = pts[:8].copy()
    cg, ig = KS.fit_streaming(pts, k=8, iters=5, chunk_points=512,
                              mesh=mesh, init=c0)
    cl, il = KS.fit_streaming_local(pts, k=8, iters=5, chunk_points=512,
                                    mesh=mesh, init=c0)
    assert np.allclose(cg, cl, rtol=1e-4, atol=1e-4)
    assert abs(ig - il) < 1e-3 * abs(ig)


def test_streaming_local_int8_matches_single_source_int8(mesh):
    """int8 across splits: the allgathered-max scales equal the
    single-source scales on the same data (same amax pass, same rule),
    so the two variants quantize identically and the chains agree to
    f32 partial-sum tolerance (the chunk partitioning differs, so
    cross-chunk summation order — and low bits — may)."""
    pts = _blobs(n=2048)
    c0 = pts[:8].copy()
    cg, ig = KS.fit_streaming(pts, k=8, iters=4, chunk_points=512,
                              mesh=mesh, init=c0, quantize="int8")
    cl, il = KS.fit_streaming_local(pts, k=8, iters=4, chunk_points=512,
                                    mesh=mesh, init=c0, quantize="int8")
    assert np.allclose(cg, cl, rtol=1e-4, atol=1e-4)
    # sanity vs f32: same basin, loosely (the tight 5% quality contract
    # is pinned by test_streaming_int8_close_to_f32 on a proper seeded
    # init; this explicit first-rows init is deliberately crude and
    # amplifies quantization error)
    _, if32 = KS.fit_streaming(pts, k=8, iters=4, chunk_points=512,
                               mesh=mesh, init=c0)
    assert abs(il - if32) < 0.2 * abs(if32)


def test_streaming_local_int8_rejects_wrap_prone_chunk(mesh, monkeypatch):
    monkeypatch.setattr(KS, "_INT8_SUM_ROW_LIMIT", 4)
    pts = _blobs(n=512)
    with pytest.raises(ValueError, match="accumulation bound"):
        KS.fit_streaming_local(pts, k=4, iters=1, chunk_points=512,
                               mesh=mesh, quantize="int8",
                               init=pts[:4].copy())


def test_streaming_local_seeding_modes(mesh):
    pts = _blobs(n=2048)
    for init in ("random", "kmeans++"):
        c, inertia = KS.fit_streaming_local(pts, k=8, iters=3,
                                            chunk_points=512, mesh=mesh,
                                            seed=1, init=init)
        assert np.isfinite(c).all() and np.isfinite(inertia)
    with pytest.raises(ValueError, match="init must be"):
        KS.fit_streaming_local(pts, k=8, iters=1, mesh=mesh, init="grid")
    with pytest.raises(ValueError, match="explicit init"):
        KS.fit_streaming_local(pts, k=8, iters=1, mesh=mesh,
                               init=np.zeros((4, pts.shape[1])))
    with pytest.raises(ValueError, match="at least one row"):
        KS.fit_streaming_local(pts[:0], k=8, iters=1, mesh=mesh)
    # a split too short to seed k distinct centroids fails LOUDLY: the
    # resampled alternative would be duplicate seeds = dead clusters
    with pytest.raises(ValueError, match="rows per"):
        KS.fit_streaming_local(pts[:4], k=8, iters=1, mesh=mesh,
                               init="random")


def _write_splits(tmp_path, pts, n_files, fmt="csv"):
    """Split rows across n_files (uneven sizes exercise the balancer)."""
    paths = []
    bounds = np.linspace(0, len(pts), n_files + 1).astype(int)
    for i in range(n_files):
        blk = pts[bounds[i]:bounds[i + 1]]
        p = tmp_path / f"split_{i}.{fmt}"
        if fmt == "npy":
            np.save(p, blk)
        else:
            np.savetxt(p, blk, fmt="%.6f", delimiter=",")
        paths.append(str(p))
    return paths


def test_filesplits_blocks_cover_every_row_once(tmp_path):
    from harp_tpu.native.datasource import FileSplits

    pts = np.arange(23 * 3, dtype=np.float32).reshape(23, 3)
    paths = _write_splits(tmp_path, pts, n_files=4)
    fs = FileSplits(paths, n_workers=3, local_workers=range(3),
                    chunk_rows=8)
    assert fs.cols == 3
    assert sum(fs.rows(w) for w in range(3)) == 23
    for _ in range(2):  # two epochs: reset() really rewinds
        fs.reset()
        seen = []
        for w in range(3):
            while True:
                blk = fs.next_block(w, 5)  # crosses file boundaries
                if blk.shape[0] == 0:
                    break
                seen.append(blk)
        got = np.concatenate(seen, 0)
        assert got.shape == (23, 3)
        # every original row exactly once (order is worker-major)
        np.testing.assert_allclose(
            np.sort(got[:, 0]), np.sort(pts[:, 0]), atol=1e-4)
    # head() probes rows then rewinds
    assert fs.head(7).shape == (7, 3)
    assert fs.next_block(0, 3).shape[0] > 0
    # sample(): random rows from the real set, capped by what exists,
    # cursors untouched
    fs.reset()
    smp = fs.sample(9, rng=3)
    assert smp.shape == (9, 3)
    assert np.isin(smp[:, 0], pts[:, 0]).all()
    assert fs.sample(100).shape == (23, 3)      # cap at total rows
    assert fs.next_block(0, 4).shape[0] > 0     # cursor still at start
    # amax(): exact per-feature |max| over every file, cursors rewound
    fs.reset()
    np.testing.assert_allclose(fs.amax(), np.abs(pts).max(0), atol=1e-4)
    assert fs.next_block(0, 4).shape[0] > 0
    fs.close()


def test_filesplits_rejects_ragged_columns(tmp_path):
    from harp_tpu.native.datasource import FileSplits

    np.savetxt(tmp_path / "a.csv", np.zeros((3, 4)), delimiter=",")
    np.savetxt(tmp_path / "b.csv", np.zeros((3, 5)), delimiter=",")
    with pytest.raises(ValueError, match="column count"):
        FileSplits([str(tmp_path / "a.csv"), str(tmp_path / "b.csv")],
                   n_workers=1, local_workers=[0])


def test_streaming_files_matches_single_source(mesh, tmp_path):
    """The HDFS-split input shape: mixed-size file splits dealt to 8
    workers produce the same clustering as one contiguous source (row
    order differs — full-batch Lloyd does not see it)."""
    pts = _blobs(n=2600, d=10)
    c0 = pts[:6].copy()
    cg, ig = KS.fit_streaming(pts, k=6, iters=4, chunk_points=512,
                              mesh=mesh, init=c0)
    for fmt, n_files in (("csv", 5), ("npy", 3)):
        paths = _write_splits(tmp_path, pts, n_files=n_files, fmt=fmt)
        cf, i_f = KS.fit_streaming_files(paths, k=6, iters=4,
                                         chunk_points=512, mesh=mesh,
                                         init=c0)
        assert np.allclose(cg, cf, rtol=1e-3, atol=1e-3), fmt
        assert abs(ig - i_f) < 1e-3 * abs(ig), fmt


def test_streaming_files_int8_matches_single_source_int8(mesh, tmp_path):
    """File splits + int8: the per-file amax pass allgathers to the SAME
    global scales as the single-source pass, so quantization is
    identical and the chains agree to f32 partial-sum tolerance."""
    pts = _blobs(n=1800, d=8)
    paths = _write_splits(tmp_path, pts, n_files=4, fmt="npy")
    c0 = pts[:5].copy()
    cg, ig = KS.fit_streaming(pts, k=5, iters=3, chunk_points=300,
                              mesh=mesh, init=c0, quantize="int8")
    cf, i_f = KS.fit_streaming_files(paths, k=5, iters=3,
                                     chunk_points=300, mesh=mesh,
                                     init=c0, quantize="int8")
    assert np.allclose(cg, cf, rtol=1e-3, atol=1e-3)
    assert abs(ig - i_f) < 1e-3 * abs(ig)


def test_streaming_files_more_workers_than_files(mesh, tmp_path):
    # 2 files over 8 workers: six workers stream pure padding
    pts = _blobs(n=512, d=6)
    paths = _write_splits(tmp_path, pts, n_files=2, fmt="npy")
    c, inertia = KS.fit_streaming_files(paths, k=4, iters=3,
                                        chunk_points=128, mesh=mesh,
                                        init=pts[:4].copy())
    c0, i0 = KS.fit_streaming(pts, k=4, iters=3, chunk_points=128,
                              mesh=mesh, init=pts[:4].copy())
    assert np.allclose(c, c0, rtol=1e-3, atol=1e-3)


def test_streaming_files_checkpoint_recovery(mesh, tmp_path):
    """The shared epoch driver's recovery contract holds for the
    file-split source too: a crash mid-run resumes from the checkpoint
    (streams rewound by put_chunk(0)) and equals the clean run."""
    from harp_tpu.utils.fault import FaultInjector

    pts = _blobs(n=1024, d=6)
    paths = _write_splits(tmp_path, pts, n_files=3, fmt="npy")
    c0 = pts[:4].copy()
    clean_c, _, clean_h = KS.fit_streaming_files(
        paths, k=4, iters=6, chunk_points=256, mesh=mesh, init=c0,
        return_history=True)
    ck = str(tmp_path / "ckpt")
    c, _, h = KS.fit_streaming_files(
        paths, k=4, iters=6, chunk_points=256, mesh=mesh, init=c0,
        return_history=True, ckpt_dir=ck, ckpt_every=2,
        fault=FaultInjector(fail_at=(4,)))
    np.testing.assert_allclose(c, clean_c, rtol=1e-6)
    np.testing.assert_allclose(h, clean_h, rtol=1e-6)


def test_north_star_1b_program_lowers(mesh):
    """The REAL 1B×300 k=1000 program (3814-chunk scan × fori epochs)
    must trace and lower at its true shapes — proving the north-star
    config is formulable — without executing (that needs the TPU)."""
    import jax
    import jax.numpy as jnp

    cfg = KS.StreamConfig(k=1000, chunk_points=262_144)
    n_chunks = 1_000_000_000 // cfg.chunk_points  # 3814
    fn = KS.make_synthetic_run_fn(mesh, cfg, d=300, n_chunks=n_chunks)
    keys = jax.random.split(jax.random.key(0), mesh.num_workers)
    lowered = fn.lower(
        jax.ShapeDtypeStruct(keys.shape, keys.dtype,
                             sharding=mesh.sharding(mesh.spec(0))),
        jax.ShapeDtypeStruct((1000, 300), jnp.float32,
                             sharding=mesh.replicated()),
        jax.ShapeDtypeStruct((), jnp.int32, sharding=mesh.replicated()))
    text = lowered.as_text()
    assert "while" in text  # the chunk scan is in the program


def test_north_star_1b_int8_program_lowers(mesh):
    """The int8 twin of the 1B program (device-quantized chunks on the
    int8 MXU) lowers at true shapes too — same proof, quantized path."""
    import jax
    import jax.numpy as jnp

    cfg = KS.StreamConfig(k=1000, chunk_points=262_144, quantize="int8")
    n_chunks = 1_000_000_000 // cfg.chunk_points
    fn = KS.make_synthetic_run_fn(mesh, cfg, d=300, n_chunks=n_chunks)
    keys = jax.random.split(jax.random.key(0), mesh.num_workers)
    lowered = fn.lower(
        jax.ShapeDtypeStruct(keys.shape, keys.dtype,
                             sharding=mesh.sharding(mesh.spec(0))),
        jax.ShapeDtypeStruct((1000, 300), jnp.float32,
                             sharding=mesh.replicated()),
        jax.ShapeDtypeStruct((), jnp.int32, sharding=mesh.replicated()))
    assert "i8" in lowered.as_text()  # the int8 stream is in the program


# ---- wire dtype (H2D payload format; round 3) -------------------------

def test_resolve_wire_dtype_rules():
    f32 = np.dtype(np.float32)
    # auto: narrow float sources ship as-is, everything else as compute
    assert KS._resolve_wire_dtype("auto", f32, np.float16) == np.float16
    assert KS._resolve_wire_dtype("auto", f32, np.dtype("bfloat16")).name \
        == "bfloat16"
    assert KS._resolve_wire_dtype("auto", f32, np.float32) == f32
    assert KS._resolve_wire_dtype("auto", f32, np.int16) == f32
    assert KS._resolve_wire_dtype("auto", f32, None) == f32  # mixed/unknown
    # never ship WIDER than compute via auto
    assert KS._resolve_wire_dtype("auto", np.dtype(np.float16),
                                  np.float16) == np.float16
    # None = legacy; explicit forces; non-float rejected
    assert KS._resolve_wire_dtype(None, f32, np.float16) == f32
    assert KS._resolve_wire_dtype(np.float16, f32, np.float32) == np.float16
    with pytest.raises(ValueError, match="float"):
        KS._resolve_wire_dtype(np.int8, f32, np.float32)


def test_f16_source_wire_bit_identical_to_host_cast(mesh):
    # an f16 disk source streamed with the f16 wire (auto) must equal the
    # legacy path (host-cast to f32, f32 wire) BITWISE: widening is exact
    pts16 = _blobs(n=1200, d=12).astype(np.float16)
    c_auto, i_auto = KS.fit_streaming(pts16, k=5, iters=4, chunk_points=512,
                                      mesh=mesh, seed=7)
    c_legacy, i_legacy = KS.fit_streaming(pts16, k=5, iters=4,
                                          chunk_points=512, mesh=mesh,
                                          seed=7, wire_dtype=None)
    np.testing.assert_array_equal(c_auto, c_legacy)
    assert i_auto == i_legacy


def test_f16_wire_program_receives_f16(mesh):
    # the compiled chunk program must see an f16 operand (the wire win is
    # real, not a host-side cast sneaking back in)
    seen = []
    orig = KS._make_accum_fn

    def spy(m, cfg):
        fn = orig(m, cfg)

        def wrapped(pts, *rest):
            seen.append(np.asarray(pts).dtype)
            return fn(pts, *rest)
        return wrapped

    KS._make_accum_fn = spy
    try:
        pts16 = _blobs(n=600, d=8).astype(np.float16)
        KS.fit_streaming(pts16, k=3, iters=1, chunk_points=256, mesh=mesh)
    finally:
        KS._make_accum_fn = orig
    assert seen and all(d == np.float16 for d in seen), seen


def test_streaming_files_f16_splits_use_f16_wire(mesh, tmp_path):
    # uniform f16 .npy splits resolve the f16 wire and match the
    # single-source result bitwise; a mixed f16+csv set falls back to f32
    pts = _blobs(n=900, d=10).astype(np.float16)
    paths = []
    for i in range(3):
        p = tmp_path / f"s{i}.npy"
        np.save(p, pts[i * 300:(i + 1) * 300])
        paths.append(str(p))
    init = pts[:4].astype(np.float32)
    c_f, i_f = KS.fit_streaming_files(paths, k=4, iters=3, chunk_points=256,
                                      mesh=mesh, init=init)
    c_s, i_s = KS.fit_streaming(pts, k=4, iters=3, chunk_points=256,
                                mesh=mesh, init=init)
    assert np.allclose(c_f, c_s, rtol=1e-4, atol=1e-4)

    from harp_tpu.native.datasource import FileSplits

    fs = FileSplits(paths, mesh.num_workers,
                    range(mesh.num_workers))
    assert fs.dtype == np.float16
    fs.close()
    csv = tmp_path / "mix.csv"
    np.savetxt(csv, pts[:8].astype(np.float32), delimiter=",")
    fs2 = FileSplits([paths[0], str(csv)], 2, range(2))
    assert fs2.dtype is None  # mixed → wire falls back to compute dtype
    fs2.close()


def test_synthetic_int8_formulation_matches_f32_clustering(mesh):
    """quantize='int8' on the device-regenerated formulation: same data
    (same keys), quantized on device with the static 5σ scale — inertia
    must land within the quantization tolerance of the f32 run and
    descend with more iters."""
    kw = dict(n=65536, d=16, k=16, chunk_points=8192, mesh=mesh, warmup=1)
    f1 = KS.benchmark_streaming(iters=1, **kw)
    q1 = KS.benchmark_streaming(iters=1, quantize="int8", **kw)
    q6 = KS.benchmark_streaming(iters=6, quantize="int8", **kw)
    assert q1["quantize"] == "int8"
    # int8 rounding perturbs assignments slightly; 5% matches the
    # non-streaming int8 quality bound (tests/test_kmeans.py)
    assert abs(q1["inertia"] - f1["inertia"]) / f1["inertia"] < 0.05
    assert q6["inertia"] < q1["inertia"]
