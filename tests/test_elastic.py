"""Elastic execution (harp_tpu/elastic, PR 15) — acting on the skew
trigger mid-run and surviving permanent worker loss without a restart.

Evidence layers, all on the 8-worker CPU sim:

1. pack/remap machinery: home assignment reproduces the non-elastic
   layout exactly; remaps are bijections; the reshard-wire row move
   equals the host gather bit-for-bit;
2. the sentinel↔driver handshake: a latched ``skew_trigger`` is
   consumed EXACTLY once per fire (no double-apply), re-arms on latch
   release, and no-ops with telemetry off (the PR-3 zero-cost pin);
3. THE skew drill (ISSUE 15): on a deliberately skewed corpus the
   driver consumes the fired trigger, the SkewLedger after-evidence
   drops below the 0.25 trigger threshold, and the final model metric
   stays within the app's flip-decision gate (rmse rel 1% / LL abs
   0.05) vs the non-elastic run — for BOTH flagship rotation apps;
4. THE worker-loss drill (ISSUE 15): an injected permanent fault at a
   seeded ordinal shrinks the mesh to the survivors, the resume
   replays the repartition plan from the last crash-atomic checkpoint,
   training completes, and the result is BIT-identical to an
   uninterrupted survivors-only run from the same checkpoint;
5. the evidence: every drill's ``kind:"elastic"`` rows pass
   scripts/check_jsonl.py invariant 14 inside a full telemetry export.
"""

import os
import sys

import numpy as np
import pytest

from harp_tpu import health
from harp_tpu.elastic import ledger as eledger
from harp_tpu.elastic.rebalance import (IdRemap, Packs, maybe_rebalance,
                                        wasted_frac)
from harp_tpu.utils import skew, telemetry

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "scripts"))

import check_jsonl  # noqa: E402


# ---------------------------------------------------------------------------
# Packs / IdRemap / regather
# ---------------------------------------------------------------------------

def test_packs_home_assignment_is_identity_remap():
    """The home assignment must reproduce the partitioners' block
    layout EXACTLY — elastic mode with no trigger is the plain fit."""
    packs = Packs(64, 8, per_worker=2)
    assert packs.n_packs == 16 and packs.width == 4
    rm = IdRemap(packs, packs.home_assignment(), 8)
    np.testing.assert_array_equal(rm.fwd, np.arange(64))
    np.testing.assert_array_equal(rm.inv, np.arange(64))
    assert rm.new_n == 64


def test_idremap_is_a_bijection_under_any_assignment():
    rng = np.random.default_rng(3)
    packs = Packs(61, 8, per_worker=3)  # ragged id space
    asg = rng.integers(0, 5, packs.n_packs)  # onto FEWER workers
    rm = IdRemap(packs, asg, 5)
    assert (np.sort(rm.inv[rm.fwd]) == np.arange(61)).all()
    # every id lands on its pack's planned owner under block partition
    owner = rm.fwd // rm.bound
    np.testing.assert_array_equal(owner, asg[packs.pack_of(np.arange(61))])


def test_regather_rows_matches_host_gather(mesh):
    """The reshard-wire move is bit-exact vs the host permutation, pads
    (-1) zero-fill, and the CommLedger sees exactly one reshard site."""
    from harp_tpu.elastic.move import regather_rows

    rng = np.random.default_rng(0)
    host = rng.normal(size=(32, 4)).astype(np.float32)
    x = mesh.shard_array(host, 0)
    rows = np.array([5, -1, 0, 31, 7, 7, -1, 2] * 5, np.int64)  # 40 rows
    with telemetry.scope(True):
        out = np.asarray(regather_rows(mesh, x, rows))
        assert telemetry.ledger.bytes_per_execution("elastic.regather") > 0
    ref = np.where((rows >= 0)[:, None], host[np.maximum(rows, 0)], 0.0)
    np.testing.assert_array_equal(out, ref)


def test_regather_rejects_non_worker_multiple(mesh):
    from harp_tpu.elastic.move import regather_rows

    x = mesh.shard_array(np.zeros((16, 2), np.float32), 0)
    with pytest.raises(ValueError, match="multiple"):
        regather_rows(mesh, x, np.arange(9))


# ---------------------------------------------------------------------------
# The sentinel↔driver handshake
# ---------------------------------------------------------------------------

def _fire_trigger(phase="p", units=None):
    for _ in range(health.TRIGGER_SUPERSTEPS):
        skew.record_execution(phase, [10, 2, 2, 2], unit="u",
                              units=units)


def test_consume_skew_trigger_exactly_once_then_rearms():
    with telemetry.scope(True):
        assert health.monitor.consume_skew_trigger("p") is None  # unfired
        _fire_trigger()
        row = health.monitor.consume_skew_trigger("p")
        assert row is not None and row["detector"] == "skew_trigger"
        assert row["consumed"] is True
        # exactly once: a still-latched phase hands nothing more out
        assert health.monitor.consume_skew_trigger("p") is None
        skew.record_execution("p", [10, 2, 2, 2], unit="u")  # still skewed
        assert health.monitor.consume_skew_trigger("p") is None
        # latch release re-arms: a NEW fire hands a fresh plan
        skew.record_execution("p", [4, 4, 4, 4], unit="u")
        _fire_trigger()
        assert health.monitor.consume_skew_trigger("p") is not None
        assert health.monitor.consume_skew_trigger("p") is None


def test_consume_skew_trigger_noop_with_telemetry_off():
    """The zero-cost pin (PR-3 pattern): the acting half no-ops too."""
    with telemetry.scope(True):
        _fire_trigger()
    telemetry.enable(False)
    try:
        assert health.monitor.consume_skew_trigger("p") is None
    finally:
        telemetry.enable(False)


def test_execution_units_make_the_trigger_plan_whole_unit():
    """record_execution(units=...) (PR 15) gives the fired plan 'id'
    moves — the shape apply_rebalance replays; without units the plan
    stays fractional (the PR-14 behavior, unchanged)."""
    from harp_tpu import schedule

    units = [[("a", 6.0), ("b", 4.0)], [("c", 2.0)], [("d", 2.0)],
             [("e", 2.0)]]
    with telemetry.scope(True):
        _fire_trigger("pu", units=units)
        plan = health.monitor.consume_skew_trigger("pu")["plan"]
        assert plan["moves"] and all("id" in m for m in plan["moves"])
        new = schedule.apply_rebalance(
            [["a", "b"], ["c"], ["d"], ["e"]], plan)
        assert sorted(x for lst in new for x in lst) == list("abcde")


# ---------------------------------------------------------------------------
# THE skew drill — Layer 1 acceptance
# ---------------------------------------------------------------------------

def _skewed_ratings(n_users=64, n_items=48, rng=None):
    """Rating rows concentrated on the first two workers' users (the
    powerlaw pattern): worker loads ~[2000, 2000, 160, ...]."""
    rng = rng or np.random.default_rng(0)
    hot = rng.integers(0, 16, 4000)
    cold = rng.integers(16, n_users, 1000)
    users = np.concatenate([hot, cold])
    rng.shuffle(users)
    items = rng.integers(0, n_items, users.shape[0])
    vals = rng.normal(size=users.shape[0]).astype(np.float32)
    return users, items, vals


def test_mfsgd_skew_drill_rebalances_below_threshold(mesh, tmp_path):
    """ISSUE 15 acceptance: trigger fired -> consumed -> wasted_frac
    below the 0.25 threshold in the SkewLedger after-evidence -> final
    rmse within the flip gate (rel 1%) of the non-elastic run -> the
    full export (skew + health + elastic rows) passes the checker."""
    from harp_tpu.elastic.apps import MFSGDElastic, elastic_fit
    from harp_tpu.models.mfsgd import MFSGD, MFSGDConfig

    users, items, vals = _skewed_ratings()
    cfg = MFSGDConfig(rank=4, algo="dense", u_tile=8, i_tile=8,
                      entry_cap=64)
    epochs = 5
    with telemetry.scope(True):
        ad = MFSGDElastic(64, 48, cfg, mesh, 0, users=users, items=items,
                          vals=vals, packs_per_worker=8)
        assert wasted_frac(ad.worker_loads()) > health.WASTED_FRAC_TRIGGER
        elastic_fit(ad, epochs)
        # the trigger fired, was consumed, and the move landed
        rows = [r for r in eledger.ledger.rows if r["event"] == "rebalance"]
        assert len(rows) == 1
        r = rows[0]
        assert r["wasted_frac_before"] > health.WASTED_FRAC_TRIGGER
        assert r["wasted_frac_after"] < health.WASTED_FRAC_TRIGGER
        assert sum(r["loads_after"]) == sum(r["loads_before"])
        # the SkewLedger AFTER-evidence: the post-rebalance supersteps
        # recorded balanced per-worker work
        after = skew.ledger.summary()["mfsgd.epochs"]
        assert after["wasted_frac"] < health.WASTED_FRAC_TRIGGER
        rmse_elastic = ad.metric()
        p = tmp_path / "drill.jsonl"
        telemetry.export(str(p))
    assert check_jsonl.check_file(str(p), provenance=True) == []

    # flip-gate parity vs the non-elastic run (rmse rel 1%)
    m = MFSGD(64, 48, cfg, mesh, 0)
    m.set_ratings(users, items, vals)
    for _ in range(epochs):
        m.train_epoch()
    rmse_plain = m.predict_rmse(users, items, vals)
    assert abs(rmse_elastic - rmse_plain) / rmse_plain < 0.01


def test_lda_skew_drill_rebalances_below_threshold(mesh):
    """The LDA arm of the acceptance drill: powerlaw doc lengths, chain
    preserved across the move (counts rebuild exactly from the token
    multiset), final LL within the flip gate (abs 0.05)."""
    from harp_tpu.elastic.apps import LDAElastic, elastic_fit
    from harp_tpu.models.lda import LDA, LDAConfig

    rng = np.random.default_rng(0)
    n_docs, vocab = 64, 64
    lens = np.where(np.arange(n_docs) < 16, 200, 20)  # 10x-long docs
    d_ids = np.repeat(np.arange(n_docs), lens).astype(np.int32)
    w_ids = rng.integers(0, vocab, d_ids.shape[0]).astype(np.int32)
    cfg = LDAConfig(n_topics=4, algo="dense", d_tile=8, w_tile=8,
                    entry_cap=64, sampler="gumbel", rng_impl="threefry")
    epochs = 5
    with telemetry.scope(True):
        ad = LDAElastic(n_docs, vocab, cfg, mesh, 0, doc_ids=d_ids,
                        word_ids=w_ids, packs_per_worker=8)
        assert wasted_frac(ad.worker_loads()) > health.WASTED_FRAC_TRIGGER
        elastic_fit(ad, epochs)
        rows = [r for r in eledger.ledger.rows if r["event"] == "rebalance"]
        assert len(rows) == 1
        assert rows[0]["wasted_frac_after"] < health.WASTED_FRAC_TRIGGER
        after = skew.ledger.summary()["lda.epochs"]
        assert after["wasted_frac"] < health.WASTED_FRAC_TRIGGER
        ll_elastic = ad.metric()

    m = LDA(n_docs, vocab, cfg, mesh, 0)
    m.set_tokens(d_ids, w_ids)
    for _ in range(epochs):
        m.sample_epoch()
    assert abs(ll_elastic - m.log_likelihood()) < 0.05


def test_rebalance_refused_when_packs_too_coarse(mesh):
    """A plan that cannot improve (one giant indivisible pack) is
    consumed but NOT applied — no thrash, no lying evidence row."""
    from harp_tpu.elastic.apps import MFSGDElastic

    rng = np.random.default_rng(0)
    users = np.concatenate([rng.integers(0, 8, 4000),       # ONE pack
                            rng.integers(8, 64, 200)])
    items = rng.integers(0, 48, users.shape[0])
    vals = rng.normal(size=users.shape[0]).astype(np.float32)
    from harp_tpu.models.mfsgd import MFSGDConfig

    cfg = MFSGDConfig(rank=4, algo="dense", u_tile=8, i_tile=8,
                      entry_cap=64)
    with telemetry.scope(True):
        ad = MFSGDElastic(64, 48, cfg, mesh, 0, users=users, items=items,
                          vals=vals, packs_per_worker=1)
        before = ad.assignment.copy()
        for _ in range(health.TRIGGER_SUPERSTEPS):
            ad.train_one()
        assert maybe_rebalance(ad) is None  # consumed, refused
        np.testing.assert_array_equal(ad.assignment, before)
        assert eledger.ledger.rows == []
        # and the handshake already spent the fire: no double-consume
        assert health.monitor.consume_skew_trigger(ad.phase) is None


def test_elastic_home_layout_matches_plain_fit_bitwise(mesh):
    """With no trigger (balanced corpus), the elastic adapter IS the
    plain driver: identical factors after the same epochs."""
    from harp_tpu.elastic.apps import MFSGDElastic, elastic_fit
    from harp_tpu.models.mfsgd import MFSGD, MFSGDConfig

    rng = np.random.default_rng(5)
    users = rng.integers(0, 64, 1500)
    items = rng.integers(0, 48, 1500)
    vals = rng.normal(size=1500).astype(np.float32)
    cfg = MFSGDConfig(rank=4, algo="dense", u_tile=8, i_tile=8,
                      entry_cap=64)
    ad = MFSGDElastic(64, 48, cfg, mesh, 0, users=users, items=items,
                      vals=vals)
    elastic_fit(ad, 3)
    m = MFSGD(64, 48, cfg, mesh, 0)
    m.set_ratings(users, items, vals)
    for _ in range(3):
        m.train_epoch()
    W_e = ad.canonical_state()["W"]
    W_p, H_p = m.factors()
    np.testing.assert_array_equal(W_e, W_p)
    np.testing.assert_array_equal(ad.canonical_state()["H"], H_p)


# ---------------------------------------------------------------------------
# THE worker-loss drill — Layer 2 acceptance
# ---------------------------------------------------------------------------

def _uniform_ratings(rng):
    users = rng.integers(0, 64, 2000)
    items = rng.integers(0, 48, 2000)
    vals = rng.normal(size=2000).astype(np.float32)
    return users, items, vals


def test_mfsgd_worker_loss_drill_bit_identical(mesh, tmp_path):
    """ISSUE 15 acceptance: permanent fault at a seeded dispatch
    ordinal -> mesh shrinks to the survivors -> resume replays the
    repartition plan from the last crash-atomic checkpoint -> training
    completes BIT-identical (assert_array_equal) to an uninterrupted
    survivors-only run from the same checkpoint; the run's elastic
    rows pass invariant 14."""
    from harp_tpu.elastic.apps import MFSGDElastic, elastic_fit
    from harp_tpu.models.mfsgd import MFSGDConfig
    from harp_tpu.parallel.mesh import WorkerMesh
    from harp_tpu.utils.checkpoint import CheckpointManager
    from harp_tpu.utils.fault import FaultInjector

    users, items, vals = _uniform_ratings(np.random.default_rng(1))
    cfg = MFSGDConfig(rank=4, algo="dense", u_tile=8, i_tile=8,
                      entry_cap=64)
    ck = str(tmp_path / "ck")
    lost = 3
    with telemetry.scope(True):
        inj = FaultInjector(seed=0, permanent={"dispatch": (2,)},
                            lost_worker=lost)
        ad = MFSGDElastic(64, 48, cfg, mesh, 0, users=users, items=items,
                          vals=vals, max_worker_loss=1)
        elastic_fit(ad, 3, ck, ckpt_every=1, fault=inj, rebalance=False)
        assert inj.permanent_fired and ad.losses == 1
        assert ad.mesh.num_workers == mesh.num_workers - 1
        events = [r["event"] for r in eledger.ledger.rows]
        assert events == ["shrink", "resume"]
        shrink = eledger.ledger.rows[0]
        assert shrink["lost_worker"] == lost
        assert shrink["n_workers_after"] == shrink["n_workers_before"] - 1
        resume = eledger.ledger.rows[1]
        assert resume["replayed_plan"] is True
        assert resume["n_workers"] == 7
        st_e = ad.canonical_state()
        p = tmp_path / "loss.jsonl"
        telemetry.export(str(p))
    assert check_jsonl.check_file(str(p), provenance=True) == []

    # the uninterrupted survivors-only run from the SAME checkpoint:
    # the fault fired during epoch 1, so the last checkpoint is step 0
    step, state = CheckpointManager(ck).restore(0)
    assert step == 0
    surv = WorkerMesh([d for i, d in enumerate(mesh.devices)
                       if i != lost])
    ad2 = MFSGDElastic(64, 48, cfg, surv, 0, users=users, items=items,
                       vals=vals)
    ad2.install(state)
    for _ in range(step + 1, 3):
        ad2.train_one()
    st_c = ad2.canonical_state()
    np.testing.assert_array_equal(st_e["W"], st_c["W"])
    np.testing.assert_array_equal(st_e["H"], st_c["H"])


def test_lda_worker_loss_drill_bit_identical(mesh, tmp_path):
    """The LDA arm: the canonical token-multiset state (z + key chain)
    restores onto the survivor mesh and the continued chain is
    bit-identical to the survivors-only continuation."""
    from harp_tpu.elastic.apps import LDAElastic, elastic_fit
    from harp_tpu.models.lda import LDAConfig
    from harp_tpu.parallel.mesh import WorkerMesh
    from harp_tpu.utils.checkpoint import CheckpointManager
    from harp_tpu.utils.fault import FaultInjector

    rng = np.random.default_rng(2)
    d_ids = np.repeat(np.arange(48), 24).astype(np.int32)
    w_ids = rng.integers(0, 48, d_ids.shape[0]).astype(np.int32)
    cfg = LDAConfig(n_topics=4, algo="dense", d_tile=8, w_tile=8,
                    entry_cap=64, sampler="gumbel", rng_impl="threefry")
    ck = str(tmp_path / "ck")
    with telemetry.scope(True):
        inj = FaultInjector(seed=0, permanent={"dispatch": (2,)},
                            lost_worker=5)
        ad = LDAElastic(48, 48, cfg, mesh, 0, doc_ids=d_ids,
                        word_ids=w_ids, max_worker_loss=1)
        elastic_fit(ad, 3, ck, ckpt_every=1, fault=inj, rebalance=False)
        assert inj.permanent_fired and ad.mesh.num_workers == 7
        st_e = ad.canonical_state()

    step, state = CheckpointManager(ck).restore(0)
    surv = WorkerMesh([d for i, d in enumerate(mesh.devices) if i != 5])
    ad2 = LDAElastic(48, 48, cfg, surv, 0, doc_ids=d_ids, word_ids=w_ids)
    ad2.install(state)
    for _ in range(step + 1, 3):
        ad2.train_one()
    st_c = ad2.canonical_state()
    for k in ("d", "w", "z"):
        np.testing.assert_array_equal(st_e[k], st_c[k])
    np.testing.assert_array_equal(ad.model.doc_topic_table(),
                                  ad2.model.doc_topic_table())


def test_worker_loss_budget_exhausted_fails_loudly(mesh, tmp_path):
    """max_worker_loss=0: the handler refuses and the loss propagates —
    elasticity is opt-in capacity, not silent degradation."""
    from harp_tpu.elastic.apps import MFSGDElastic, elastic_fit
    from harp_tpu.models.mfsgd import MFSGDConfig
    from harp_tpu.utils.fault import FaultInjector, PermanentWorkerLoss

    users, items, vals = _uniform_ratings(np.random.default_rng(4))
    ad = MFSGDElastic(64, 48, MFSGDConfig(rank=4, algo="dense", u_tile=8,
                                          i_tile=8, entry_cap=64),
                      mesh, 0, users=users, items=items, vals=vals,
                      max_worker_loss=0)
    inj = FaultInjector(seed=0, permanent={"dispatch": (1,)},
                        lost_worker=0)
    with pytest.raises(PermanentWorkerLoss):
        elastic_fit(ad, 2, str(tmp_path / "ck"), fault=inj,
                    rebalance=False)


def test_elastic_fit_refuses_fault_without_ckpt(mesh):
    from harp_tpu.elastic.apps import KMeansStreamElastic, elastic_fit
    from harp_tpu.utils.fault import FaultInjector

    ad = KMeansStreamElastic(np.zeros((64, 4), np.float32), 2, mesh, 0)
    with pytest.raises(ValueError, match="requires ckpt_dir"):
        elastic_fit(ad, 1, None, fault=FaultInjector())


# ---------------------------------------------------------------------------
# kmeans-stream adapter
# ---------------------------------------------------------------------------

def test_kmeans_stream_elastic_matches_plain_and_survives(mesh, tmp_path):
    """Home layout reproduces fit_streaming exactly; a rebalanced
    (masked, padded) layout still computes exact Lloyd; a permanent
    loss shrinks and finishes."""
    from harp_tpu.elastic.apps import KMeansStreamElastic, elastic_fit
    from harp_tpu.models.kmeans_stream import fit_streaming
    from harp_tpu.utils.fault import FaultInjector

    rng = np.random.default_rng(0)
    pts = rng.normal(size=(512, 8)).astype(np.float32)
    ad = KMeansStreamElastic(pts, 4, mesh, 0)
    elastic_fit(ad, 3)
    _, inertia = fit_streaming(pts, 4, 3, 512, mesh=mesh, seed=0)
    assert ad.metric() == pytest.approx(inertia, rel=1e-6)

    # an arbitrary (uneven) assignment changes nothing numerically:
    # pads carry mask 0, Lloyd sums are permutation-invariant
    ad2 = KMeansStreamElastic(pts, 4, mesh, 0, packs_per_worker=2)
    asg = ad2.packs.home_assignment()
    asg[:3] = 7  # pile three packs onto the last worker
    ad2.apply_assignment(asg)
    for _ in range(3):
        ad2.train_one()
    assert ad2.metric() == pytest.approx(inertia, rel=1e-5)

    inj = FaultInjector(seed=0, permanent={"dispatch": (3,)},
                        lost_worker=2)
    with telemetry.scope(True):
        ad3 = KMeansStreamElastic(pts, 4, mesh, 0, max_worker_loss=1)
        elastic_fit(ad3, 3, str(tmp_path / "ck"), fault=inj)
        assert inj.permanent_fired and ad3.mesh.num_workers == 7
    assert ad3.metric() == pytest.approx(inertia, rel=1e-5)


# ---------------------------------------------------------------------------
# Ledger mechanics
# ---------------------------------------------------------------------------

def test_elastic_ledger_vocab_and_export(tmp_path):
    import harp_tpu.elastic as E

    assert E.EVENTS == check_jsonl.KNOWN_ELASTIC_EVENTS
    eledger.ledger.reset()
    with pytest.raises(ValueError, match="event"):
        eledger.record("grow", "p")
    eledger.record("shrink", "p", lost_worker=1, site="dispatch",
                   ordinal=2, n_workers_before=8, n_workers_after=7,
                   capacity_frac=0.875)
    p = tmp_path / "e.jsonl"
    with open(p, "w") as fh:
        E.export_jsonl(fh)
    assert check_jsonl.check_file(str(p), provenance=True) == []
    eledger.ledger.reset()


def test_report_grows_elastic_section(mesh):
    """The run report carries the elastic actions (the report surface
    of the acting half, mirroring the PR-14 health section)."""
    from harp_tpu import report
    from harp_tpu.elastic.apps import MFSGDElastic, elastic_fit
    from harp_tpu.models.mfsgd import MFSGDConfig

    users, items, vals = _skewed_ratings()
    cfg = MFSGDConfig(rank=4, algo="dense", u_tile=8, i_tile=8,
                      entry_cap=64)
    with telemetry.scope(True):
        ad = MFSGDElastic(64, 48, cfg, mesh, 0, users=users, items=items,
                          vals=vals, packs_per_worker=8)
        elastic_fit(ad, 4)
        row, _ = report.live_report()
        assert row["elastic"]["by_event"] == {"rebalance": 1}
        text = report.render(row)
        assert "elastic (actions)" in text and "[rebalance]" in text
