"""Prefetch-pipelined ingest (harp_tpu/ingest.py, PR 8).

Contract under test: every depth of the shared host pipeline is
BIT-EXACT (stages are deterministic per chunk, consumption is in
order) — only the overlap changes; the flight budgets wrapping the
pipeline loops are exact (chunk bytes on the wire, zero post-warmup
compiles); and the stall detector turns a secretly-serialized pipeline
into a loud RuntimeWarning instead of a silently wrong measurement.
"""

import threading
import time
import warnings

import jax
import numpy as np
import pytest

from harp_tpu import ingest
from harp_tpu.models import kmeans as K
from harp_tpu.models import kmeans_stream as KS
from harp_tpu.models import mlp as M
from harp_tpu.utils import flightrec, telemetry

needs_compile_events = pytest.mark.skipif(
    not flightrec.COMPILE_EVENTS_AVAILABLE,
    reason="this jax lacks the monitoring hook")


def _blobs(n=4096, d=24, c=4, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.normal(size=(n, d)).astype(np.float32)
            + (rng.integers(0, c, size=(n, 1)) * 6).astype(np.float32))


# ---------------------------------------------------------------------------
# the pipeline itself (no jax involved)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("depth,rt,pt", [(1, 1, 1), (2, 1, 1), (4, 2, 2)])
def test_pipeline_preserves_order_and_values(depth, rt, pt):
    with ingest.IngestPipeline(lambda j: j, lambda r: r * 10,
                               lambda r: r + 1, depth=depth,
                               read_threads=rt, prep_threads=pt) as pipe:
        assert list(pipe.stream(13)) == [j * 10 + 1 for j in range(13)]
        assert pipe.stats.chunks == 13
        # a second stream through the SAME pipeline (epoch reuse)
        assert list(pipe.stream(3)) == [1, 11, 21]


def test_pipeline_single_reader_runs_in_order():
    """Stateful sequential sources (FileSplits) depend on read(j)
    executing in submission order on one thread."""
    seen = []

    def read(j):
        seen.append(j)
        time.sleep(0.001 * (3 - j % 3))  # adversarial per-call jitter
        return j

    with ingest.IngestPipeline(read, depth=4) as pipe:
        assert list(pipe.stream(9)) == list(range(9))
    assert seen == list(range(9))


def test_pipeline_propagates_stage_errors():
    def read(j):
        if j == 3:
            raise RuntimeError("disk on fire")
        return j

    with ingest.IngestPipeline(read, depth=2) as pipe:
        with pytest.raises(RuntimeError, match="disk on fire"):
            list(pipe.stream(8))


def test_pipeline_rejects_bad_knobs():
    with pytest.raises(ValueError, match="depth"):
        ingest.IngestPipeline(lambda j: j, depth=0)
    with pytest.raises(ValueError, match="threads"):
        ingest.IngestPipeline(lambda j: j, read_threads=0)


# ---------------------------------------------------------------------------
# the stall detector (satellite: sabotaged overlap must be LOUD)
# ---------------------------------------------------------------------------

def test_stall_detector_fires_on_sabotaged_overlap():
    """The canonical dead pipeline: each read is gated on the PREVIOUS
    chunk's consumption (a shared buffer of size one), so depth-2
    prefetch cannot actually work ahead — the consumer waits a full
    read per chunk despite computing in between, and the detector must
    say so."""
    sem = threading.Semaphore(1)

    def read(j):
        sem.acquire()           # can never run ahead of consumption
        time.sleep(0.02)
        return j

    pipe = ingest.IngestPipeline(read, depth=2, tag="unit.sabotage",
                                 stall_warn=0.5)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        for _ in pipe.stream(8):
            time.sleep(0.01)    # compute the reads SHOULD hide under
            sem.release()
    assert pipe.stats.overlap_efficiency < 0.5, pipe.stats
    assert pipe.stats.stalls == 1
    assert any("stalled" in str(x.message) for x in w), \
        [str(x.message) for x in w]


def test_no_stall_warning_when_overlap_works():
    """Same costs WITHOUT the shared lock: reads hide behind the
    consumer sleep and the detector stays silent."""

    def read(j):
        time.sleep(0.01)
        return j

    pipe = ingest.IngestPipeline(read, depth=2, tag="unit.healthy",
                                 stall_warn=0.5)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        for _ in pipe.stream(8):
            time.sleep(0.01)
    assert not any("stalled" in str(x.message) for x in w), \
        [str(x.message) for x in w]
    assert pipe.stats.overlap_efficiency >= 0.5, pipe.stats


# ---------------------------------------------------------------------------
# kmeans_stream on the pipeline: depth is invisible to the math
# ---------------------------------------------------------------------------

def test_kmeans_stream_depths_bit_exact(mesh):
    """prefetch 0 (legacy chain) / 1 / 2 / 4 produce the IDENTICAL
    clustering — and all match the committed-golden contract vs the
    resident fit."""
    pts = _blobs()
    ref_c, ref_i = K.fit(pts, k=8, iters=5, mesh=mesh, seed=3)
    outs = [KS.fit_streaming(pts, k=8, iters=5, chunk_points=1000,
                             mesh=mesh, seed=3, prefetch=p)
            for p in (0, 1, 2, 4)]
    for c, i in outs[1:]:
        np.testing.assert_array_equal(c, outs[0][0])
        assert i == outs[0][1]
    assert np.allclose(outs[0][0], ref_c, rtol=1e-4, atol=1e-4)
    assert abs(outs[0][1] - ref_i) < 1e-3 * abs(ref_i)


def test_kmeans_stream_int8_gate_rides_pipeline(mesh):
    """quantize='int8' through the pipeline: bit-exact across depths
    (the quantize stage moved threads, not math) and within the
    existing inertia tolerance of f32."""
    pts = _blobs()
    _, i_f32 = KS.fit_streaming(pts, k=8, iters=4, chunk_points=1000,
                                mesh=mesh, seed=3)
    outs = [KS.fit_streaming(pts, k=8, iters=4, chunk_points=1000,
                             mesh=mesh, seed=3, quantize="int8",
                             prefetch=p) for p in (0, 1, 4)]
    for c, i in outs[1:]:
        np.testing.assert_array_equal(c, outs[0][0])
        assert i == outs[0][1]
    assert abs(outs[0][1] - i_f32) < 0.05 * abs(i_f32)


def test_kmeans_stream_files_depths_bit_exact(mesh, tmp_path):
    """The stateful file-split source (sequential cursors + epoch reset)
    is depth-invariant too."""
    pts = _blobs(n=1300, d=10)
    paths = []
    bounds = np.linspace(0, len(pts), 4).astype(int)
    for i in range(3):
        p = tmp_path / f"s{i}.npy"
        np.save(p, pts[bounds[i]:bounds[i + 1]])
        paths.append(str(p))
    init = pts[:5].copy()
    outs = [KS.fit_streaming_files(paths, k=5, iters=3, chunk_points=256,
                                   mesh=mesh, init=init, prefetch=p)
            for p in (1, 3)]
    np.testing.assert_array_equal(outs[0][0], outs[1][0])
    assert outs[0][1] == outs[1][1]


def test_benchmark_ingest_reports_pipeline_fields(mesh, tmp_path):
    pts = _blobs(n=2048, d=16).astype(np.float16)
    f = tmp_path / "pts.npy"
    np.save(f, pts)
    mm = np.load(f, mmap_mode="r")
    import os

    r = KS.benchmark_ingest(mm, k=4, iters=2, chunk_points=512,
                            mesh=mesh, disk_bytes=os.path.getsize(f))
    assert r["kind"] == "ingest" and r["prefetch_depth"] == 2
    assert 0.0 <= r["overlap_efficiency"] <= 1.0
    assert 0.0 < r["device_hidden_fraction"] <= 1.0
    assert r["pipeline"]["chunks"] == 4
    assert r["pipeline"]["blocked_s"] > 0


# ---------------------------------------------------------------------------
# budget pins: exact chunk bytes, zero post-warmup compiles
# ---------------------------------------------------------------------------

def test_kmeans_stream_h2d_budget_exact(mesh):
    """The whole fit ships EXACTLY iters × chunk-data bytes plus the two
    one-time masks — nothing re-uploads, nothing sneaks past the
    counted shard_array path."""
    pts = _blobs(n=2048, d=16)
    chunk = 512                     # divides n: full mask only
    iters = 3
    exact = chunk * 4 + iters * (2048 // chunk) * chunk * 16 * 4
    with telemetry.scope():
        with flightrec.budget(h2d_bytes=exact, tag="unit.ks.h2d") as b:
            KS.fit_streaming(pts, k=4, iters=iters, chunk_points=chunk,
                             mesh=mesh, seed=0, prefetch=2)
        assert b.spent()["h2d_bytes"] == exact


@needs_compile_events
def test_kmeans_stream_zero_postwarmup_compiles(mesh):
    """Epochs after the first compile NOTHING: a 4-epoch fit spends no
    more backend compiles than a 1-epoch fit does for its per-epoch
    machinery (the only delta is the final history stack's shape)."""
    pts = _blobs(n=2048, d=16)
    kw = dict(k=4, chunk_points=512, mesh=mesh, seed=0, prefetch=2)
    with telemetry.scope():
        KS.fit_streaming(pts, iters=4, **kw)   # warms every shape incl.
        base = flightrec.compile_watch.count   # the 4-long stack
        KS.fit_streaming(pts, iters=1, **kw)
        c1 = flightrec.compile_watch.count - base
        with flightrec.budget(compiles=c1, tag="unit.ks.compiles") as b:
            KS.fit_streaming(pts, iters=4, **kw)
        # epochs 2-4 added zero compiles beyond the 1-epoch run's set
        assert b.spent()["compiles"] <= c1


def test_interior_epoch_budget_fires_on_recompiling_chunk_loop(mesh,
                                                               monkeypatch):
    """Liveness of the warn-mode guard inside _stream_train: a chunk fn
    that recompiles per call (the classic relay trap) must trip the
    epoch budget's compiles=0 arm on every post-warmup epoch."""
    if not flightrec.COMPILE_EVENTS_AVAILABLE:
        pytest.skip("this jax lacks the monitoring hook")
    orig = KS._make_accum_fn

    def recompiling(mesh_, cfg_):
        fn = orig(mesh_, cfg_)

        def wrapped(*args):
            return jax.jit(lambda *a: fn(*a))(*args)  # fresh jit per call

        return wrapped

    monkeypatch.setattr(KS, "_make_accum_fn", recompiling)
    pts = _blobs(n=1024, d=8)
    with telemetry.scope():
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            KS.fit_streaming(pts, k=4, iters=2, chunk_points=512,
                             mesh=mesh, seed=0, prefetch=2)
    assert any("kmeans_stream.ingest" in str(x.message)
               and "compiles" in str(x.message) for x in w), \
        [str(x.message) for x in w]


def test_clean_runs_emit_no_budget_warnings(mesh):
    """The shipped loops PASS their own interior budgets: a telemetry-on
    multi-epoch kmeans fit and mlp fit emit zero budget warnings."""
    pts = _blobs(n=2048, d=16)
    x, y = M.synthetic_mnist(n=256, d=16, classes=4, seed=1)
    with telemetry.scope():
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            KS.fit_streaming(pts, k=4, iters=3, chunk_points=500,
                             mesh=mesh, seed=0)  # padded tail chunk too
            tr = M.MLPTrainer(M.MLPConfig(sizes=(16, 32, 4), lr=0.1),
                              mesh, seed=0)
            tr.fit(x, y, batch_size=64, epochs=2)
    budget_warnings = [x for x in w
                       if "budget exceeded" in str(x.message)]
    assert not budget_warnings, [str(x.message) for x in budget_warnings]


# ---------------------------------------------------------------------------
# mlp on the pipeline (satellite: no more per-epoch full-copy reshuffle)
# ---------------------------------------------------------------------------

def test_mlp_fit_depths_bit_exact(mesh):
    cfg = M.MLPConfig(sizes=(16, 32, 4), lr=0.1)
    x, y = M.synthetic_mnist(n=256, d=16, classes=4, seed=1)
    runs = {}
    for p in (1, 2, 4):
        tr = M.MLPTrainer(cfg, mesh, seed=0)
        hist = tr.fit(x, y, batch_size=64, epochs=2, prefetch=p)
        runs[p] = (hist, [np.asarray(l) for l in
                          jax.tree.leaves(tr.params)])
    for p in (2, 4):
        assert runs[p][0] == runs[1][0]
        for a, b in zip(runs[p][1], runs[1][1]):
            np.testing.assert_array_equal(a, b)


def test_mlp_batch_reader_yields_views():
    """THE saved-host-copies pin: the reader hands VIEWS of the caller's
    arrays — the pre-PR ``x[perm]`` gather copied every row, every
    epoch."""
    x = np.arange(64 * 8, dtype=np.float32).reshape(64, 8)
    y = np.zeros(64, np.int32)
    read = M._batch_reader(x, y, 16, np.array([2, 0, 1, 3]))
    xb, yb = read(0)
    assert np.shares_memory(xb, x) and np.shares_memory(yb, y)
    np.testing.assert_array_equal(xb, x[32:48])  # batch index 2
    xb3, _ = read(3)
    np.testing.assert_array_equal(xb3, x[48:64])


def test_mlp_fit_h2d_budget_exact_and_zero_recompiles(mesh):
    """Per epoch the wire carries exactly the batch bytes (f32 rows +
    i32 labels) and a warmed trainer's fit compiles nothing."""
    cfg = M.MLPConfig(sizes=(16, 32, 4), lr=0.1)
    x, y = M.synthetic_mnist(n=256, d=16, classes=4, seed=1)
    tr = M.MLPTrainer(cfg, mesh, seed=0)
    tr.fit(x, y, batch_size=64, epochs=1)  # warm: the step compile
    epochs = 2
    exact = epochs * 256 * (16 * 4 + 4)
    with telemetry.scope():
        with flightrec.budget(compiles=0, h2d_bytes=exact,
                              tag="unit.mlp.fit") as b:
            tr.fit(x, y, batch_size=64, epochs=epochs)
        assert b.spent()["h2d_bytes"] == exact
        assert b.spent()["compiles"] == 0


def test_mlp_load_resident_skips_host_copy_when_aligned(mesh):
    """load_resident with divisible-by-batch f32 input stages WITHOUT
    the pre-PR full-row gather; trimming still drops a uniform random
    subset and keeps row order."""
    cfg = M.MLPConfig(sizes=(16, 32, 4), lr=0.1)
    x, y = M.synthetic_mnist(n=192, d=16, classes=4, seed=2)
    tr = M.MLPTrainer(cfg, mesh, seed=0)
    assert tr.load_resident(x, y, batch_size=64) == 192
    xs, ys, _, _ = tr._resident
    np.testing.assert_array_equal(np.asarray(xs), x)  # input order kept
    np.testing.assert_array_equal(np.asarray(ys), y)
    # trim path: usable < n drops rows but preserves relative order
    assert tr.load_resident(x[:150], y[:150], batch_size=64, seed=7) == 128
    xs2 = np.asarray(tr._resident[0])
    idx = [int(np.flatnonzero((x[:150] == row).all(1))[0]) for row in xs2]
    assert idx == sorted(idx) and len(set(idx)) == 128


# ---------------------------------------------------------------------------
# rf + fileformat on the pipeline
# ---------------------------------------------------------------------------

def test_rf_binize_chunked_bit_exact():
    from harp_tpu.models import rf as R

    rng = np.random.default_rng(0)
    x = rng.normal(size=(5000, 9)).astype(np.float32)
    edges = R.quantile_bins(x, 8)
    ref = R.binize(x, edges)
    for prefetch in (1, 2):
        np.testing.assert_array_equal(
            R.binize_chunked(x, edges, chunk_rows=1024,
                             prefetch=prefetch), ref)


def test_load_sharded_csv_matches_serial_loader_order(mesh, tmp_path):
    """The threaded per-file loads reassemble in submission order: the
    stacked output is bit-identical to loading each split serially."""
    from harp_tpu import fileformat as FF

    rng = np.random.default_rng(3)
    paths = []
    for i in range(5):
        p = tmp_path / f"f{i}.csv"
        np.savetxt(p, rng.normal(size=(20 + 11 * i, 4)), fmt="%.5f",
                   delimiter=",")
        paths.append(str(p))
    stacked, counts = FF.load_sharded_csv(paths, 3)
    splits = FF.multi_file_splits(paths, 3)
    from harp_tpu.native import datasource as DS

    rows_pad = stacked.shape[0] // 3
    for w, files in enumerate(splits):
        parts = [DS.load_csv(p) for p in files]
        ref = (np.concatenate(parts, 0) if parts
               else np.zeros((0, 4), np.float32))
        got = stacked[w * rows_pad: w * rows_pad + counts[w]]
        np.testing.assert_array_equal(got, ref)
