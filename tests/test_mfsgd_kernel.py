"""Fused Pallas MF-SGD kernel (ops/mfsgd_kernel.py) vs the XLA dense algo.

The kernel promises the SAME update order as ``algo="dense"`` — these
tests pin equivalence through the full rotation epoch on the 8-worker
mesh (interpret mode on CPU), plus the host-prep contract the kernel's
W-block streaming depends on.
"""

import numpy as np
import pytest

from harp_tpu.models import mfsgd as MF
from harp_tpu.ops import mfsgd_kernel as MF_K
from harp_tpu.ops.mfsgd_kernel import insert_coverage_entries

N = 8


def _cfg(algo, **kw):
    import jax.numpy as jnp

    base = dict(rank=4, u_tile=8, i_tile=8, entry_cap=16,
                compute_dtype=jnp.float32, lr=0.02, reg=0.01)
    base.update(kw)
    return MF.MFSGDConfig(algo=algo, **base)


def _run_epochs(mesh, algo, u, i, v, n_users, n_items, epochs=1, **kw):
    m = MF.MFSGD(n_users, n_items, _cfg(algo, **kw), mesh, seed=3)
    m.set_ratings(u, i, v)
    rmses = [m.train_epoch() for _ in range(epochs)]
    return np.asarray(m.W), np.asarray(m.H), rmses


def test_pallas_epoch_matches_dense(mesh):
    rng = np.random.default_rng(5)
    n_users, n_items, nnz = 64, 48, 600
    u = rng.integers(0, n_users, nnz).astype(np.int32)
    i = rng.integers(0, n_items, nnz).astype(np.int32)
    v = rng.normal(size=nnz).astype(np.float32)

    Wd, Hd, rd = _run_epochs(mesh, "dense", u, i, v, n_users, n_items, 2)
    Wp, Hp, rp = _run_epochs(mesh, "pallas", u, i, v, n_users, n_items, 2)
    np.testing.assert_allclose(Wp, Wd, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(Hp, Hd, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(rp, rd, rtol=1e-5)


def test_pallas_multi_epoch_program_matches_dense(mesh):
    """train_epochs (one scanned device program) through the kernel."""
    u, i, v = MF.synthetic_ratings(96, 64, 3000, rank=4, noise=0.05, seed=1)
    out = {}
    for algo in ("dense", "pallas"):
        m = MF.MFSGD(96, 64, _cfg(algo), mesh, seed=0)
        m.set_ratings(u, i, v)
        out[algo] = (m.train_epochs(3), np.asarray(m.W))
    np.testing.assert_allclose(out["pallas"][0], out["dense"][0], rtol=1e-4)
    np.testing.assert_allclose(out["pallas"][1], out["dense"][1],
                               rtol=1e-4, atol=1e-5)


def test_pallas_multi_chunk_entries_match_dense(mesh):
    """C > chunk_c=512 drives the chunk axis of the kernel's 2-D grid
    through multiple steps — the path the full-scale ML-20M config
    (C=2048) runs; a chunk-slicing bug passes the small-entry tests but
    corrupts factors only at scale."""
    rng = np.random.default_rng(11)
    # all ratings in ONE (worker, slice, tile) cell (n_items=128 → 8 items
    # per half-slice, so i<8 is slice 0 / tile 0) → one entry holding 600
    # ratings, padded to C=1024 by insert_coverage_entries → 2 chunks
    n_users, n_items, nnz = 8 * 8, 128, 600
    u = rng.integers(0, 8, nnz).astype(np.int32)  # worker 0, tile 0
    i = rng.integers(0, 8, nnz).astype(np.int32)
    v = rng.normal(size=nnz).astype(np.float32)

    kw = dict(entry_cap=1024)
    Wd, Hd, rd = _run_epochs(mesh, "dense", u, i, v, n_users, n_items, **kw)
    Wp, Hp, rp = _run_epochs(mesh, "pallas", u, i, v, n_users, n_items, **kw)
    # the prep must actually have produced a multi-chunk entry
    eu, ei, ev, ou, oi, *_ = MF.partition_ratings_tiles(
        u, i, v, n_users, n_items, N, 8, 8, 1024)
    assert insert_coverage_entries(eu, ei, ev, ou, oi, 8, 8)[0].shape[-1] \
        > 512
    np.testing.assert_allclose(Wp, Wd, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(Hp, Hd, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(rp, rd, rtol=1e-5)


def test_pallas_unvisited_w_blocks_pass_through(mesh):
    """W blocks with zero ratings must come out bit-identical, not garbage
    (the kernel writes every output block only because host prep inserts
    coverage entries — this is the test that breaks if that contract
    does)."""
    rng = np.random.default_rng(7)
    n_users, n_items, nnz = 128, 16, 200
    u = rng.integers(0, 8, nnz).astype(np.int32)  # only block 0 per worker
    i = rng.integers(0, n_items, nnz).astype(np.int32)
    v = rng.normal(size=nnz).astype(np.float32)

    m = MF.MFSGD(n_users, n_items, _cfg("pallas"), mesh, seed=9)
    W0 = np.asarray(m.W).copy()
    m.set_ratings(u, i, v)
    m.train_epoch()
    W1 = np.asarray(m.W)
    u_bound = m.u_bound
    touched = np.zeros(len(W1), bool)
    for w in range(N):
        lo = w * u_bound
        touched[lo:lo + 8] = True  # block 0 of each worker's range
    np.testing.assert_array_equal(W1[~touched], W0[~touched])
    assert not np.allclose(W1[:8], W0[:8])  # block 0 did train


def test_insert_coverage_entries_contract():
    rng = np.random.default_rng(3)
    nnz, n_users, n_items, u_tile, i_tile = 400, 64, 48, 8, 8
    u = rng.integers(0, 16, nnz).astype(np.int32)  # leaves blocks empty
    i = rng.integers(0, n_items, nnz).astype(np.int32)
    v = rng.normal(size=nnz).astype(np.float32)
    eu, ei, ev, ou, oi, uo, io, ub, ib2 = MF.partition_ratings_tiles(
        u, i, v, n_users, n_items, N, u_tile, i_tile, 16)
    eu2, ei2, ev2, ou2, oi2 = insert_coverage_entries(
        eu, ei, ev, ou, oi, ub, u_tile)

    nblk = ub // u_tile
    for w in range(eu2.shape[0]):
        blks = ou2[w] // u_tile
        # coverage: every W block appears
        assert set(range(nblk)) <= set(blks.tolist())
        # contiguity: each block id is one contiguous run
        change = np.flatnonzero(np.diff(blks) != 0)
        assert len(set(blks.tolist())) == len(change) + 1
        # the real ratings survive with their values
        real2 = ev2[w][eu2[w] < u_tile]
        real1 = ev[w][eu[w] < u_tile]
        np.testing.assert_array_equal(np.sort(real2), np.sort(real1))


def test_insert_coverage_pads_c_to_chunk_multiple():
    rng = np.random.default_rng(4)
    eu = rng.integers(0, 8, (2, 3, 520)).astype(np.int32)
    ei = rng.integers(0, 8, (2, 3, 520)).astype(np.int32)
    ev = rng.normal(size=(2, 3, 520)).astype(np.float32)
    ou = np.zeros((2, 3), np.int32)
    oi = np.zeros((2, 3), np.int32)
    eu2, *_ = insert_coverage_entries(eu, ei, ev, ou, oi, 8, 8, chunk_c=512)
    assert eu2.shape[-1] % 512 == 0


def test_pallas_rejects_oversized_resident_h():
    import jax.numpy as jnp

    from harp_tpu.ops.mfsgd_kernel import sgd_tile_update

    Wt = jnp.zeros((8, 128), jnp.float32)
    Ht = jnp.zeros((8, 1 << 19), jnp.float32)  # 16 MB half-slice
    e = jnp.zeros((1, 8), jnp.int32)
    with pytest.raises(ValueError, match="VMEM budget"):
        sgd_tile_update(Wt, Ht, e, e, e.astype(jnp.float32),
                        jnp.zeros(1, jnp.int32), jnp.zeros(1, jnp.int32),
                        lr=0.1, reg=0.0, u_tile=128, i_tile=128,
                        interpret=True)


@pytest.mark.parametrize("shape", [
    # (R, UB, IB, NE, C, tile) — graded ML-20M tiling, the REAL smoke
    # shapes the driver bench compiles FIRST on real TPU (captured from
    # the smoke bench: C=200 pads to 256 by insert_coverage_entries'
    # 128-multiple rule), and the 8-worker-sim smoke shape
    (64, 2048, 13440, 8, 2048, 256),  # DEFAULT tiles since the
                                      # 2026-08-01 sweep (250.2M@256)
    (64, 2048, 13440, 8, 2048, 512),  # explicit 512 stays supported
    (8, 512, 128, 2, 256, 128),    # 1-worker TPU smoke (u_bound=512)
    (8, 128, 128, 1, 256, 128),    # 8-worker sim smoke (u_bound=128)
])
def test_kernel_lowers_for_tpu(shape):
    """Cross-platform lowering runs the Pallas->Mosaic verification
    (layouts, block shapes, casts) without hardware — the check that
    caught the [1, C]-block constraint before any relay time was spent."""
    import functools

    import jax
    import jax.numpy as jnp

    R, UB, IB, NE, C, tile = shape
    f = functools.partial(MF_K.sgd_tile_update, lr=0.01, reg=0.05,
                          u_tile=tile, i_tile=tile, interpret=False)
    lowered = jax.jit(f).trace(
        jnp.zeros((R, UB)), jnp.zeros((R, IB)),
        jnp.zeros((NE, C), jnp.int32), jnp.zeros((NE, C), jnp.int32),
        jnp.zeros((NE, C)), jnp.zeros(NE, jnp.int32),
        jnp.zeros(NE, jnp.int32)).lower(lowering_platforms=("tpu",))
    assert "tpu_custom_call" in lowered.as_text()


def test_ml20m_pallas_epoch_lowers_for_tpu(mesh, monkeypatch):
    """The fused-kernel ML-20M epoch (138,493×26,744 grid, rank 64,
    the auto-resolved default tiles — 256×256 since the 2026-08-01
    sweep — 8-way mesh), MOSAIC-compiled, lowers for TPU on this CPU
    host — transposes, rotation scan, scalar-prefetch grids and the
    kernel itself at the true graded shapes."""
    import jax
    import jax.numpy as jnp

    monkeypatch.setenv("HARP_PALLAS_FORCE_MOSAIC", "1")
    cfg = MF.MFSGDConfig(rank=64, algo="pallas")
    n, ns = 8, 16
    _, _, u_bound, ib2 = MF._dense_bounds(
        138_493, 26_744, n, ns, *MF.tiles(cfg))
    NE, C = 96, 2048  # ~20M ratings / (n·ns) rows at C=2048 + coverage
    i32, f32 = jnp.int32, jnp.float32
    shapes = [((u_bound * n, 64), f32), ((2 * ib2 * n, 64), f32),
              ((n * ns, NE, C), i32), ((n * ns, NE, C), i32),
              ((n * ns, NE, C), f32), ((n * ns, NE), i32),
              ((n * ns, NE), i32)]
    sds = [jax.ShapeDtypeStruct(s, d, sharding=mesh.sharding(mesh.spec(0)))
           for s, d in shapes]
    fn = MF.make_multi_epoch_fn(mesh, cfg, epochs=2)
    text = fn.trace(*sds).lower(lowering_platforms=("tpu",)).as_text()
    assert "tpu_custom_call" in text  # the Mosaic kernel is in the program


# hypothesis is optional in some images: without it only this property
# test skips — a bare module-level import would fail the whole module's
# collection and take the deterministic kernel tests above down with it
try:
    from hypothesis import given, settings, strategies as st  # noqa: E402
except ImportError:  # pragma: no cover
    given = None


def _property_case(fn):
    if given is None:  # pragma: no cover
        return pytest.mark.skip(reason="hypothesis not installed")(fn)
    return settings(max_examples=40, deadline=None)(given(
        nnz=st.integers(1, 300),
        n_users=st.sampled_from([16, 40, 64]),
        n_items=st.sampled_from([16, 48]),
        u_tile=st.sampled_from([8, 16]),
        entry_cap=st.sampled_from([8, 16, 64]),
        seed=st.integers(0, 2**31 - 1),
    )(fn))


@_property_case
def test_insert_coverage_entries_properties(nnz, n_users, n_items,
                                            u_tile, entry_cap, seed):
    """The kernel's streaming correctness rests on this host prep: for
    ANY rating set — coverage (every W block appears), contiguity (one
    run per block), value preservation (real ratings survive exactly
    once), C a 128-multiple (the Mosaic lane gate), and in-bounds
    offsets."""
    rng = np.random.default_rng(seed)
    u = rng.integers(0, n_users, nnz).astype(np.int32)
    i = rng.integers(0, n_items, nnz).astype(np.int32)
    v = rng.normal(size=nnz).astype(np.float32)
    eu, ei, ev, ou, oi, uo, io, ub, ib2 = MF.partition_ratings_tiles(
        u, i, v, n_users, n_items, N, u_tile, u_tile, entry_cap)
    eu2, ei2, ev2, ou2, oi2 = insert_coverage_entries(
        eu, ei, ev, ou, oi, ub, u_tile)

    nblk = ub // u_tile
    assert eu2.shape[-1] % 128 == 0          # Mosaic lane gate, any size
    for w in range(eu2.shape[0]):
        blks = ou2[w] // u_tile
        assert set(range(nblk)) <= set(blks.tolist())          # coverage
        change = np.flatnonzero(np.diff(blks) != 0)
        assert len(set(blks.tolist())) == len(change) + 1      # contiguity
        assert (ou2[w] >= 0).all() and (ou2[w] + u_tile <= ub).all()
        assert (oi2[w] >= 0).all() and (oi2[w] + u_tile <= ib2).all()
        # every real rating survives exactly once, with its value
        real2 = np.sort(ev2[w][eu2[w] < u_tile])
        real1 = np.sort(ev[w][eu[w] < u_tile])
        np.testing.assert_array_equal(real2, real1)
