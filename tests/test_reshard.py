"""reshard — the general redistribution verb (PR 11).

Contract under test:

1. Every (src_spec, dst_spec) pair over the 8-sim-worker mesh is
   BIT-identical to the naive all_gather+slice reference
   (``collective.reshard_reference``) — identity, local slice, ppermute
   rotation, all_to_all, gather, and the gather+slice fallback all take
   different fast paths and must agree exactly.
2. The quantized wires keep the one-rounding ``_quantized_move``
   contract (bf16 one cast each way; int8 error ≤ global_max/254
   against the worker-shared stacked-pmax scale; non-float leaves ride
   exact), and the chunked ppermute pipeline lowering is bit-exact with
   the one-hop rotation.
3. The equivalence-pinned shims: the rotate pipeline's ring hop and
   ``table.pull_rows`` now route through reshard and must reproduce the
   direct verbs bit-for-bit; the flagship kmeans hier-psum schedule
   reproduces the one-shot fit within float-reassociation tolerance
   (and exactly on integer payloads).
4. Flight-budget pins: each comm lowering is ONE dispatch and ZERO
   post-warmup compiles (the CLAUDE.md relay traps, machine-checked).
5. The CommLedger sees every wire: verb "reshard", payload at wire
   width, chunk-sized for the chunked lowering.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from harp_tpu.parallel import collective as C
from harp_tpu.parallel.collective import ShardSpec
from harp_tpu.utils import flightrec, telemetry

S = ShardSpec

#: every layout the 2-D test array can take over the 8-worker ring —
#: the full pair matrix is 6×6 = 36 lowerings, covering every kind
SPECS = {
    "R": S.replicated(),
    "S0": S.blocked(0),
    "S0s1": S.blocked(0, 1),
    "S0s3": S.blocked(0, 3),
    "S1": S.blocked(1),
    "S1s2": S.blocked(1, 2),
}


def _global_array(nw):
    # rows 8·nw (divides by nw), cols nw (divides by nw): every spec legal
    return np.arange(nw * 8 * nw, dtype=np.float32).reshape(nw * 8, nw)


def _host_layout(x, spec, nw):
    """Pre-roll the host array so sharding dim-`spec.dim` over the mesh
    realizes the spec (worker w holds global block (w - shift) % nw)."""
    if spec.dim is None:
        return x
    if spec.shift % nw:
        bs = x.shape[spec.dim] // nw
        return np.roll(x, (spec.shift % nw) * bs, axis=spec.dim)
    return x


def _dev_spec(mesh, spec):
    return P() if spec.dim is None else mesh.spec(spec.dim, ndim=2)


def _run_pair(mesh, src, dst, **kw):
    nw = mesh.num_workers
    x = _global_array(nw)

    def prog(a):
        return (C.reshard(a, src, dst, **kw),
                C.reshard_reference(a, src, dst))

    fn = jax.jit(mesh.shard_map(
        prog, in_specs=(_dev_spec(mesh, src),),
        out_specs=(_dev_spec(mesh, dst),) * 2))
    staged = mesh.shard_array(_host_layout(x, src, nw), src.dim)
    got, ref = fn(staged)
    return np.asarray(got), np.asarray(ref)


@pytest.mark.parametrize("src_name", sorted(SPECS))
@pytest.mark.parametrize("dst_name", sorted(SPECS))
def test_every_pair_bit_exact_vs_naive_reference(mesh, src_name, dst_name):
    got, ref = _run_pair(mesh, SPECS[src_name], SPECS[dst_name])
    np.testing.assert_array_equal(got, ref)


def test_rotation_lowers_like_the_rotate_verb(mesh):
    """The ring-hop shim's pin: reshard between ring-shifted layouts is
    BIT-identical to the direct rotate verb for every shift (including
    negative and > ring size) — the lowering emits the same ppermute."""
    nw = mesh.num_workers
    x = np.random.default_rng(0).normal(size=(nw * 4, 16)).astype(np.float32)
    for shift in (1, 3, -1, nw + 2):
        def prog(a, s=shift):
            return (C.reshard(a, S.blocked(0), S.blocked(0, s)),
                    C.rotate(a, shift=s))

        fn = jax.jit(mesh.shard_map(prog, in_specs=(mesh.spec(0),),
                                    out_specs=(mesh.spec(0),) * 2))
        got, ref = fn(mesh.shard_array(x, 0))
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_pipeline_ring_hop_is_the_reshard_shim(mesh):
    """rotate_pipeline's wire resolver (the mfsgd/lda/ccd ring) emits
    reshard: ledger verb 'reshard' at the pipeline site, and a 2-chunk
    epoch reproduces the pre-shim two-halves schedule bit-for-bit (the
    slice updated at t-1 lands exactly one worker on)."""
    from harp_tpu.parallel.rotate import rotate_pipeline

    nw = mesh.num_workers
    sl = np.arange(nw * 4.0, dtype=np.float32).reshape(nw * 4, 1)

    def epoch(acc, s):
        def step(c, chunk, t):
            return c + chunk.sum(), chunk * 2.0

        return rotate_pipeline(step, acc, s, n_chunks=2)

    fn = jax.jit(mesh.shard_map(
        epoch, in_specs=(P(), mesh.spec(0)), out_specs=(P(), mesh.spec(0))))
    with telemetry.scope(True):
        with telemetry.ledger.run("pipe", steps=1):
            acc, out = fn(jnp.float32(0.0), mesh.shard_array(sl, 0))
        verbs = {s["verb"]
                 for s in telemetry.ledger.summary()["pipe"]["sites"]}
    assert "reshard" in verbs
    # every chunk visited every worker once: doubled 2n times... each
    # chunk is doubled once per visit, n visits -> x * 2^n, home order
    np.testing.assert_array_equal(
        np.asarray(out), sl * 2.0 ** nw)
    assert float(acc) > 0.0


def test_wire_validation_matches_rotate_pipeline_contract(mesh):
    from harp_tpu.parallel.rotate import _wire_rotate

    with pytest.raises(ValueError, match="wire must be one of"):
        _wire_rotate("fp8", 1, "workers")
    with pytest.raises(ValueError, match="wire must be one of"):
        C.reshard(jnp.zeros(8), S.blocked(0), S.blocked(0, 1), wire="fp8")


def test_quantized_wires_round_once(mesh):
    """bf16/int8 reshard wires: single-rounding error bounds on the
    rotation AND the gather lowering; int leaves ride exact."""
    nw = mesh.num_workers
    rng = np.random.default_rng(7)
    x = rng.normal(size=(nw * 4, 8)).astype(np.float32) * 3.0
    xi = np.arange(nw * 2, dtype=np.int32).reshape(nw * 2, 1)

    def prog(a, b):
        r8 = C.reshard(a, S.blocked(0), S.blocked(0, 1), wire="int8")
        rb = C.reshard(a, S.blocked(0), S.blocked(0, 1), wire="bf16")
        g8 = C.reshard(a, S.blocked(0), S.replicated(), wire="int8")
        i8 = C.reshard(b, S.blocked(0), S.blocked(0, 1), wire="int8")
        return r8, rb, g8, i8

    fn = jax.jit(mesh.shard_map(
        prog, in_specs=(mesh.spec(0),) * 2,
        out_specs=(mesh.spec(0), mesh.spec(0), P(), mesh.spec(0))))
    r8, rb, g8, i8 = fn(mesh.shard_array(x, 0), mesh.shard_array(xi, 0))
    exact = np.roll(x, x.shape[0] // nw, axis=0)
    bound8 = np.abs(x).max() / 254 + 1e-6
    assert np.abs(np.asarray(r8) - exact).max() <= bound8
    assert np.abs(np.asarray(g8) - x).max() <= bound8
    # bf16: one cast each way
    assert np.abs(np.asarray(rb) - exact).max() <= \
        np.abs(x).max() * 2.0 ** -8 + 1e-6
    np.testing.assert_array_equal(
        np.asarray(i8), np.roll(xi, xi.shape[0] // nw, axis=0))


def test_chunked_pipeline_lowering_bit_exact_and_gated(mesh):
    """n_chunks splits the rotation into a scan of sub-chunk hops —
    bit-exact with the one-hop move; non-divisible chunk counts and
    non-rotation lowerings refuse loudly."""
    nw = mesh.num_workers
    x = np.random.default_rng(3).normal(size=(nw * 8, 4)).astype(np.float32)

    def prog(a):
        one = C.reshard(a, S.blocked(0), S.blocked(0, 1))
        four = C.reshard(a, S.blocked(0), S.blocked(0, 1), n_chunks=4)
        return one, four

    fn = jax.jit(mesh.shard_map(prog, in_specs=(mesh.spec(0),),
                                out_specs=(mesh.spec(0),) * 2))
    one, four = fn(mesh.shard_array(x, 0))
    np.testing.assert_array_equal(np.asarray(one), np.asarray(four))

    with pytest.raises(ValueError, match="does not divide"):
        jax.jit(mesh.shard_map(
            lambda a: C.reshard(a, S.blocked(0), S.blocked(0, 1),
                                n_chunks=3),
            in_specs=(mesh.spec(0),), out_specs=mesh.spec(0)))(
            mesh.shard_array(x, 0))
    with pytest.raises(ValueError, match="ring rotations only"):
        jax.jit(mesh.shard_map(
            lambda a: C.reshard(a, S.blocked(0), S.replicated(),
                                n_chunks=2),
            in_specs=(mesh.spec(0),), out_specs=P()))(
            mesh.shard_array(x, 0))


def test_spec_validation(mesh):
    with pytest.raises(ValueError, match="no ring shift"):
        S(dim=None, shift=1)
    x = np.zeros((mesh.num_workers * 2, 3), np.float32)
    # dim out of range and non-divisible sizes refuse at trace time
    with pytest.raises(ValueError, match="out of range"):
        jax.jit(mesh.shard_map(
            lambda a: C.reshard(a, S.blocked(0), S.blocked(5)),
            in_specs=(mesh.spec(0),), out_specs=mesh.spec(0)))(
            mesh.shard_array(x, 0))
    with pytest.raises(ValueError, match="does not split"):
        jax.jit(mesh.shard_map(
            lambda a: C.reshard(a, S.blocked(0), S.blocked(1)),
            in_specs=(mesh.spec(0),), out_specs=mesh.spec(1, ndim=2)))(
            mesh.shard_array(x, 0))


def test_match_reshard_rules(mesh):
    tree = {"model": {"W": np.zeros((8, 4)), "H": np.zeros((8, 4))},
            "lr": np.float32(0.1), "step": np.zeros(())}
    rules = [("model/W", S.blocked(0)), ("model/H", S.blocked(0, 1)),
             (".*", S.replicated())]
    specs = C.match_reshard_rules(rules, tree)
    assert specs["model"]["W"] == S.blocked(0)
    assert specs["model"]["H"] == S.blocked(0, 1)
    assert specs["lr"] == S.replicated()      # scalar: never partitioned
    assert specs["step"] == S.replicated()
    with pytest.raises(ValueError, match="no reshard rule"):
        C.match_reshard_rules([("W", S.blocked(0))],
                              {"other": np.zeros((4, 4))})


def test_reshard_pytree_with_per_leaf_specs(mesh):
    """A rule-matched spec tree reshards each leaf independently in one
    verb call (one ledger record, mixed lowerings)."""
    nw = mesh.num_workers
    tree = {"W": np.arange(nw * 4.0, dtype=np.float32).reshape(nw * 4, 1),
            "H": np.arange(nw * 2.0, dtype=np.float32).reshape(nw * 2, 1)}
    src = C.match_reshard_rules([("W", S.blocked(0)),
                                 ("H", S.blocked(0))], tree)
    dst = C.match_reshard_rules([("W", S.blocked(0, 1)),
                                 ("H", S.replicated())], tree)

    def prog(t):
        return C.reshard(t, src, dst)

    fn = jax.jit(mesh.shard_map(
        prog, in_specs=({"W": mesh.spec(0), "H": mesh.spec(0)},),
        out_specs={"W": mesh.spec(0), "H": P()}))
    out = fn({k: mesh.shard_array(v, 0) for k, v in tree.items()})
    np.testing.assert_array_equal(
        np.asarray(out["W"]), np.roll(tree["W"], 4, axis=0))
    np.testing.assert_array_equal(np.asarray(out["H"]), tree["H"])


# -- the shimmed call sites --------------------------------------------------

def test_pull_rows_shim_unchanged(mesh):
    """table.pull_rows rides reshard(blocked->replicated) now — same
    rows, bit-for-bit, as the raw all_gather+take reference."""
    from harp_tpu.table import pull_rows

    nw = mesh.num_workers
    tb = np.arange(nw * 4 * 3, dtype=np.float32).reshape(nw * 4, 3)
    ids = np.tile(np.arange(nw * 4, dtype=np.int32)[::-1][:4], nw)

    def prog(t, i):
        got = pull_rows(t, i)
        ref = jnp.take(jax.lax.all_gather(t, "workers", tiled=True), i,
                       axis=0)
        return got, ref

    fn = jax.jit(mesh.shard_map(prog, in_specs=(mesh.spec(0),) * 2,
                                out_specs=(mesh.spec(0),) * 2))
    got, ref = fn(mesh.shard_array(tb, 0), mesh.shard_array(ids, 0))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_kmeans_hier_psum_matches_one_shot(mesh):
    """The flagship planner schedule: psum_schedule='hier' reproduces
    the one-shot fit to float-reassociation tolerance on the same seed
    (the flip gate's 1% inertia tolerance is ~1e4x looser than this)."""
    from harp_tpu.models.kmeans import fit

    rng = np.random.default_rng(0)
    pts = rng.normal(size=(mesh.num_workers * 64, 16)).astype(np.float32)
    c1, i1 = fit(pts, k=8, iters=5, mesh=mesh, seed=3)
    c2, i2 = fit(pts, k=8, iters=5, mesh=mesh, seed=3,
                 psum_schedule="hier")
    assert abs(i1 - i2) / abs(i1) < 1e-5
    np.testing.assert_allclose(np.asarray(c1), np.asarray(c2),
                               rtol=1e-5, atol=1e-5)


def test_allreduce_hier_exact_on_ints_any_group(mesh):
    nw = mesh.num_workers
    y = np.arange(nw * 5, dtype=np.int32).reshape(nw, 5)
    for gs in (None, 1, 2, 4, nw):
        op = C.host_op(mesh, lambda t, gs=gs, **kw: C.allreduce_hier(
            t, group_size=gs, **kw), in_dim=0, out_dim=0)
        np.testing.assert_array_equal(np.asarray(op(y)),
                                      np.tile(y.sum(0), (nw, 1)))
    with pytest.raises(ValueError, match="must divide"):
        C.host_op(mesh, lambda t, **kw: C.allreduce_hier(
            t, group_size=3, **kw), in_dim=0, out_dim=0)(y)


# -- flight budgets + ledger -------------------------------------------------

def _budget_pinned(mesh, build_prog, in_specs, out_specs, args):
    """One warmup, then one invocation under the pinned budget: ONE
    dispatch, ONE stacked readback, ZERO compiles (a reshard lowering
    must never hide a re-trace or a per-leaf dispatch)."""
    fn = flightrec.track(
        jax.jit(mesh.shard_map(build_prog, in_specs=in_specs,
                               out_specs=out_specs)), "reshard.pin")
    with telemetry.scope(True):
        out = fn(*args)                      # warmup (compile here)
        jax.block_until_ready(out)
        with flightrec.budget(compiles=0, dispatches=1, readbacks=1,
                              tag="reshard.pin"):
            out = fn(*args)
            flightrec.readback(jax.tree.leaves(out)[0])


@pytest.mark.parametrize("dst_name,wire,chunks", [
    ("S0s1", "exact", 1),     # ppermute
    ("S0s1", "exact", 4),     # chunked pipeline
    ("S0s1", "int8", 1),      # quantized ring hop
    ("S1", "exact", 1),       # all_to_all
    ("R", "exact", 1),        # all_gather
    ("S1s2", "exact", 1),     # gather+slice fallback
])
def test_flight_budget_one_dispatch_zero_recompiles(mesh, dst_name, wire,
                                                    chunks):
    nw = mesh.num_workers
    x = _global_array(nw)
    dst = SPECS[dst_name]
    _budget_pinned(
        mesh,
        lambda a: C.reshard(a, S.blocked(0), dst, wire=wire,
                            n_chunks=chunks),
        (mesh.spec(0, ndim=2),), _dev_spec(mesh, dst),
        (mesh.shard_array(x, 0),))


def test_ledger_accounts_reshard_at_wire_width(mesh):
    """The CommLedger pin: exact rotation records the full payload,
    the 4-chunk pipeline records the chunk-sized hop, int8 records at
    1 B/element — the byte sheet the planner prices is the wire that
    ships (HL302's cross-check, unit-sized)."""
    nw = mesh.num_workers
    x = np.zeros((nw * 8, 4), np.float32)
    per_shard = 8 * 4 * 4  # worker's [8, 4] f32 block

    def payloads(**kw):
        with telemetry.scope(True):
            with telemetry.ledger.run("probe", steps=0):
                jax.jit(mesh.shard_map(
                    lambda a: C.reshard(a, S.blocked(0), S.blocked(0, 1),
                                        **kw),
                    in_specs=(mesh.spec(0),),
                    out_specs=mesh.spec(0))).lower(mesh.shard_array(x, 0))
            sites = telemetry.ledger.summary()["probe"]["sites"]
            return {s["verb"]: s["payload_bytes"] for s in sites}

    assert payloads()["reshard"] == per_shard
    assert payloads(n_chunks=4)["reshard"] == per_shard // 4
    assert payloads(wire="int8")["reshard"] == per_shard // 4  # 1 B/elem
    assert payloads(wire="bf16")["reshard"] == per_shard // 2
