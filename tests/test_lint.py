"""harplint (harp_tpu/analysis) — golden fixtures for every layer.

One synthetic module per Layer-1 rule that must trip it, the pre-fix LDA
scan-carry gather+DUS pattern pinned as a Layer-2 positive (and the
fixed tile-local form as a negative), a 3-seed-word ``prng_seed`` toy
kernel the Mosaic audit must flag WITHOUT hardware, the Layer-4
CommGraph fixtures (kmeans' hand-computed byte sheet as the HL302
cross-check, an unledgered psum for HL301, a sabotaged donated-buffer
re-read for HL303, a loop-invariant allgather for HL304), the Layer-5
thread-root fixtures (one sabotaged synthetic plane per HL401–HL405
plus its clean twin, driven through ``threadgraph.analyze_sources``),
and the repo-wide tier-1 gate: zero unallowlisted violations at HEAD.
"""

import contextlib
import json
import os
import sys
import textwrap

import numpy as np
import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "scripts"))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax import lax  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

import harp_tpu.utils.telemetry as T  # noqa: E402
from harp_tpu.analysis import commgraph  # noqa: E402
from harp_tpu.analysis import rule_ids  # noqa: E402
from harp_tpu.analysis import allowlist as allowlist_mod  # noqa: E402
from harp_tpu.analysis.astlints import lint_source  # noqa: E402
from harp_tpu.analysis.jaxpr_checks import (  # noqa: E402
    find_large_constants, find_scan_copy_traps)
from harp_tpu.analysis.mosaic_audit import (  # noqa: E402
    audit_kernel, check_kernel_jaxpr)
from harp_tpu.analysis import cli  # noqa: E402


def _rules(violations):
    return sorted({v.rule for v in violations})


# ---------------------------------------------------------------------------
# Layer 1 — one synthetic module per rule
# ---------------------------------------------------------------------------

def test_hl001_raw_collective_trips():
    src = textwrap.dedent("""
        from jax import lax
        def step(x):
            return lax.psum(x, "workers")
    """)
    vs = lint_source("harp_tpu/models/fake.py", src)
    assert _rules(vs) == ["HL001"]


def test_hl001_exempt_inside_verb_layer():
    src = "from jax import lax\ndef f(x):\n    return lax.psum(x, 'w')\n"
    assert lint_source("harp_tpu/parallel/collective.py", src) == []
    assert lint_source("harp_tpu/parallel/rotate.py", src) == []


def test_hl001_axis_queries_stay_legal():
    src = ("from jax import lax\n"
           "def f():\n"
           "    return lax.axis_index('w') + lax.axis_size('w')\n")
    assert lint_source("harp_tpu/models/fake.py", src) == []


def test_hl002_prngkey_trips():
    src = ("import jax\n"
           "def seed_me(s):\n"
           "    return jax.random.PRNGKey(s)\n")
    vs = lint_source("harp_tpu/models/fake.py", src)
    assert _rules(vs) == ["HL002"]
    # the helper that wraps the trap is exempt
    assert lint_source("harp_tpu/utils/prng.py", src) == []


def test_hl003_asarray_on_numpy_trips():
    src = ("import jax.numpy as jnp, numpy as np\n"
           "def ingest(x):\n"
           "    return jnp.asarray(np.asarray(x, np.float32))\n")
    vs = lint_source("harp_tpu/models/fake.py", src)
    assert _rules(vs) == ["HL003"]


def test_hl003_device_put_wrapper_is_clean():
    src = ("import jax, jax.numpy as jnp, numpy as np\n"
           "def ingest(x):\n"
           "    return jax.device_put(jnp.asarray(np.asarray(x)))\n")
    assert lint_source("harp_tpu/models/fake.py", src) == []


def test_hl004_untracked_jit_trips_only_in_models():
    src = ("import jax\n"
           "def driver():\n"
           "    step = jax.jit(lambda x: x)\n"
           "    return step\n")
    assert _rules(lint_source("harp_tpu/models/fake.py", src)) == ["HL004"]
    assert lint_source("harp_tpu/utils/fake.py", src) == []


def test_hl004_factory_return_and_track_are_clean():
    src = ("import jax\n"
           "from harp_tpu.utils import flightrec\n"
           "def make_step_fn():\n"
           "    return jax.jit(lambda x: x)\n"
           "def driver():\n"
           "    return flightrec.track(jax.jit(lambda x: x), 'd.step')\n")
    assert lint_source("harp_tpu/models/fake.py", src) == []


def test_hl005_undated_perf_claim_trips():
    src = ('def fast():\n'
           '    """Runs at 246.5M ups/s on the graded shape."""\n')
    vs = lint_source("harp_tpu/models/fake.py", src)
    assert _rules(vs) == ["HL005"]
    # date + chip in the documented form passes
    src_ok = ('def fast():\n'
              '    """246.5M ups/s (2026-08-01, 1x v5e)."""\n')
    assert lint_source("harp_tpu/models/fake.py", src_ok) == []


def test_hl000_syntax_error_is_loud():
    assert _rules(lint_source("harp_tpu/models/fake.py",
                              "def broken(:\n")) == ["HL000"]


# ---------------------------------------------------------------------------
# Layer 2 — the LDA copy-trap regression, pinned
# ---------------------------------------------------------------------------

def _prefix_lda_pattern(table, idxs, upds):
    """The PRE-FIX shape of the LDA epoch: the scan body gathers from the
    carried table AND dynamic_update_slice's it (cost 20 s of a 29 s
    epoch before the tile-local fix)."""

    def body(tbl, x):
        i, u = x
        vals = jnp.take(tbl, i, axis=0)              # gather from carry
        tbl = lax.dynamic_update_slice(tbl, u, (i[0], 0))
        return tbl, vals.sum()

    return lax.scan(body, table, (idxs, upds))


def _fixed_lda_pattern(table, idxs, upds):
    """The FIXED form: dynamic_slice the tile first, gather tile-locally
    — the gather operand is the slice result, not the carry."""

    def body(tbl, x):
        i, u = x
        tile = lax.dynamic_slice(tbl, (0, 0), (4, tbl.shape[1]))
        vals = jnp.take(tile, i % 4, axis=0)
        tbl = lax.dynamic_update_slice(tbl, u, (i[0], 0))
        return tbl, vals.sum()

    return lax.scan(body, table, (idxs, upds))


_SCAN_ARGS = (jnp.zeros((16, 8)), jnp.zeros((3, 2), jnp.int32),
              jnp.zeros((3, 1, 8)))


def test_scan_copy_trap_positive():
    closed = jax.jit(_prefix_lda_pattern).trace(*_SCAN_ARGS).jaxpr
    vs = find_scan_copy_traps(closed, "fixture")
    assert _rules(vs) == ["HL101"]
    assert "copy the whole" in vs[0].message.lower()


def test_scan_copy_trap_fixed_form_negative():
    closed = jax.jit(_fixed_lda_pattern).trace(*_SCAN_ARGS).jaxpr
    assert find_scan_copy_traps(closed, "fixture") == []


def test_scan_copy_trap_sees_fori_loop():
    def bad_fori(table, idxs, upds):
        def body(t, tbl):
            vals = jnp.take(tbl, idxs[t], axis=0)
            return lax.dynamic_update_slice(
                tbl, upds[t] + vals.sum(), (idxs[t][0], 0))
        return lax.fori_loop(0, 3, body, table)

    closed = jax.jit(bad_fori).trace(*_SCAN_ARGS).jaxpr
    assert _rules(find_scan_copy_traps(closed, "f")) == ["HL101"]


def test_large_constant_detector():
    big = np.ones((1 << 18,), np.float32)            # 1 MiB exactly

    def closes_over(x):
        return x + jnp.asarray(big)

    closed = jax.jit(closes_over).trace(jnp.zeros(1 << 18)).jaxpr
    # over a small threshold: flagged; at the default 1 MiB: exactly at
    # the boundary (not >), so clean
    assert _rules(find_large_constants(closed, "f", 1 << 16)) == ["HL102"]
    assert find_large_constants(closed, "f", 1 << 20) == []


def test_driver_registry_is_clean():
    """The registered flagship driver programs (kmeans fit, ring
    attention, mfsgd epoch) carry no copy trap and no oversized
    literal."""
    from harp_tpu.analysis.drivers import DRIVERS
    from harp_tpu.analysis.jaxpr_checks import analyze_program

    assert set(DRIVERS) >= {"kmeans.fit", "ring_attention", "mfsgd.epoch"}
    for name, build in DRIVERS.items():
        fn, args = build()
        assert analyze_program(fn, args, f"driver:{name}") == []


# ---------------------------------------------------------------------------
# Layer 3 — Mosaic audit, no hardware
# ---------------------------------------------------------------------------

def _toy_seed_kernel(n_words: int):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kern(seed_ref, o_ref):
        pltpu.prng_seed(*(seed_ref[i] for i in range(n_words)))
        bits = pltpu.prng_random_bits(o_ref.shape)
        o_ref[...] = lax.shift_right_logical(bits, 8).astype(jnp.float32)

    def f(seed):
        # seed words ride SMEM so seed_ref[i] reads scalars, as the real
        # lda kernel's scalar-prefetch grid does
        return pl.pallas_call(
            kern,
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)],
            out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32)
        )(seed)

    return f, (jnp.zeros(max(n_words, 1), jnp.int32),)


def test_mosaic_audit_flags_3_seed_words():
    """The 2026-08-01 in-window silicon failure, caught on CPU: a 3-word
    prng_seed must trip HL202 from the jaxpr alone."""
    fn, args = _toy_seed_kernel(3)
    closed = jax.jit(fn).trace(*args).jaxpr
    vs = check_kernel_jaxpr(closed, "kernel:toy3")
    assert "HL202" in _rules(vs)
    assert "2 " in vs[0].message or "TWO" in vs[0].message


def test_mosaic_audit_2_seed_words_clean():
    fn, args = _toy_seed_kernel(2)
    vs = audit_kernel("toy2", fn, args)
    assert vs == [], [v.message for v in vs]


def test_mosaic_audit_flags_uint32_float_cast():
    from jax.experimental import pallas as pl

    def kern(x_ref, o_ref):
        o_ref[...] = x_ref[...].astype(jnp.float32)

    def f(x):
        return pl.pallas_call(
            kern, out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32)
        )(x)

    vs = audit_kernel("toyu32", f, (jnp.zeros((8, 128), jnp.uint32),))
    # the silicon limit local lowering does NOT enforce: HL203 must fire
    # even though the local Mosaic pass stays green
    assert "HL203" in _rules(vs)


def test_mosaic_audit_flags_unaligned_block_dim():
    from jax.experimental import pallas as pl

    def kern(x_ref, o_ref):
        o_ref[...] = x_ref[...]

    def f(x):
        return pl.pallas_call(
            kern, grid=(4,),
            in_specs=[pl.BlockSpec((4, 128), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((4, 128), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((16, 128), jnp.float32))(x)

    vs = audit_kernel("toyblk", f, (jnp.zeros((16, 128), jnp.float32),))
    assert "HL204" in _rules(vs)


def test_kernel_registry_audit_is_clean():
    """Every registered ops/ kernel lowers for TPU on this CPU host and
    passes the silicon-limit checks (the audit that caught
    flash_attention's is_finite, which had only ever run in interpret
    mode)."""
    from harp_tpu.analysis.mosaic_audit import audit_registry, \
        registered_kernels

    assert set(registered_kernels()) >= {
        "kmeans.partials", "kmeans.partials_int8", "lda.cgs_entry_update",
        "mfsgd.sgd_tile_update", "flash_attention"}
    vs = audit_registry()
    assert vs == [], [v.format() for v in vs]


# ---------------------------------------------------------------------------
# Layer 4 — CommGraph (static communication audit)
# ---------------------------------------------------------------------------

AX = "workers"


def _wmesh():
    from harp_tpu.parallel.mesh import WorkerMesh

    return WorkerMesh()


def _sharded(mesh, shape, dtype=jnp.float32):
    return jax.ShapeDtypeStruct(shape, dtype,
                                sharding=mesh.sharding(mesh.spec(0)))


def test_commgraph_kmeans_sheet_matches_hand_computed():
    """THE acceptance fixture: the static byte sheet for kmeans.fit
    equals the hand-computed (k·d·4 + k·4 + 4) per-iteration allreduce
    sheet (sums + counts + inertia — the same sheet
    tests/test_telemetry.py pins at runtime), amplified by the fori trip
    count, and matches the CommLedger's trace-time bytes EXACTLY."""
    from harp_tpu.analysis.drivers import DRIVERS

    fn, args = DRIVERS["kmeans.fit"]()
    vs, graph = commgraph.analyze_program("kmeans.fit", fn, args)
    assert vs == [], [v.format() for v in vs]
    (site,) = graph.sites
    k, d, iters = 8, 32, 2  # the registry's driver shapes
    per_iter = k * d * 4 + k * 4 + 4
    assert site.primitive == "psum" and site.verb == "allreduce"
    assert site.site.startswith("kmeans.py:")
    assert site.calls_per_trace == 3          # sums, counts, inertia
    assert site.per_shard_bytes == per_iter
    assert site.amplification == iters and not site.dynamic
    sheet = graph.sheet()
    assert sheet["bytes_per_trace"] == per_iter
    assert sheet["amplified_bytes"] == per_iter * iters
    # static == ledger, to the byte (the HL302 contract)
    ledger_total = sum(r["payload_bytes"]
                       for recs in graph.ledger_sites.values()
                       for r in recs)
    assert ledger_total == per_iter


def test_hl301_unledgered_collective_fires():
    """A raw lax.psum inside shard_map leaves no CommLedger record —
    the untracked wire HL301 exists for."""
    mesh = _wmesh()

    def raw(x):
        return lax.psum(x, AX)

    fn = jax.jit(mesh.shard_map(raw, in_specs=(mesh.spec(0),),
                                out_specs=P()))
    vs, graph = commgraph.analyze_program(
        "fix301", fn, (_sharded(mesh, (8, 4)),))
    assert _rules(vs) == ["HL301"]
    assert "untracked wire" in vs[0].message
    assert graph.sites and graph.sites[0].verb is None


def test_hl302_lying_byte_sheet_fires():
    """A verb that records a SMALLER tree than it reduces (record_comm
    and the psum share one source line, so both sides key the same call
    site) must trip the static-vs-ledger byte cross-check."""
    mesh = _wmesh()

    def lying(x):
        return T.record_comm("allreduce", x[0, 0], axis=AX) or lax.psum(x, AX)  # noqa: E501

    fn = jax.jit(mesh.shard_map(lying, in_specs=(mesh.spec(0),),
                                out_specs=P()))
    vs, _ = commgraph.analyze_program("fix302", fn,
                                      (_sharded(mesh, (8, 4)),))
    assert _rules(vs) == ["HL302"]
    assert "disagrees" in vs[0].message


def test_hl302_quantized_wire_is_exempt():
    """The int8 wire accounts 1 B/elem logically while the lowering
    accumulates in int32 — a documented divergence the byte cross-check
    must NOT flag (and the extra stacked-scale pmax at the same site
    must not read as an untracked wire either)."""
    from harp_tpu.parallel import collective as C

    mesh = _wmesh()

    def q(x):
        return C.allreduce_quantized(x, wire_dtype=jnp.int8)

    fn = jax.jit(mesh.shard_map(q, in_specs=(mesh.spec(0),),
                                out_specs=P()))
    vs, graph = commgraph.analyze_program("fixq", fn,
                                          (_sharded(mesh, (8, 4)),))
    assert vs == [], [v.format() for v in vs]
    assert any(s.ledger_wire == "int8" for s in graph.sites)


def test_hl304_loop_invariant_collective_fires():
    """An allgather of a scan CONST re-ships identical bytes every
    iteration — hoistable, and the sheet must show the wasted
    amplification."""
    from harp_tpu.parallel import collective as C

    mesh = _wmesh()

    def prog(x):
        def body(c, _):
            return c + C.allgather(x).sum(), None

        out, _ = lax.scan(body, jnp.float32(0.0), None, length=4)
        return out

    fn = jax.jit(mesh.shard_map(prog, in_specs=(mesh.spec(0),),
                                out_specs=P()))
    vs, graph = commgraph.analyze_program("fix304", fn,
                                          (_sharded(mesh, (8, 4)),))
    assert _rules(vs) == ["HL304"]
    assert "hoist" in vs[0].message
    (site,) = graph.sites
    assert site.amplification == 4 and site.loop_invariant


def test_hl304_carry_dependent_collective_is_clean():
    """The same allgather on the CARRY is real per-iteration traffic —
    no hoist finding (ring attention / rotate_pipeline shape)."""
    from harp_tpu.parallel import collective as C

    mesh = _wmesh()

    def prog(x):
        def body(c, _):
            return C.allgather(c)[: c.shape[0]] * 0.5 + c, None

        out, _ = lax.scan(body, x, None, length=4)
        return out

    fn = jax.jit(mesh.shard_map(prog, in_specs=(mesh.spec(0),),
                                out_specs=mesh.spec(0)))
    vs, _ = commgraph.analyze_program("fix304n", fn,
                                      (_sharded(mesh, (8, 4)),))
    assert vs == [], [v.format() for v in vs]


def test_hl303_sabotaged_donated_reread_and_redispatch_fire():
    """The violation fixture: a buffer donated to a dispatch is read
    back AND re-dispatched.  On this CPU backend the re-use may also
    raise jax's own 'Array has been deleted' — the audit must have
    recorded the violation BEFORE the crash (on TPU there is no crash,
    just garbage — which is the whole point of the lint)."""
    from harp_tpu.utils import flightrec

    exe = jax.jit(lambda s, b: s + b, donate_argnums=(1,))
    s = jax.device_put(np.ones((4,), np.float32))
    audit = commgraph.DonationAudit("protocol:sabotage")
    with audit:
        w = audit.wrap(exe, (1,), "toy.step")
        buf = jax.device_put(np.ones((4,), np.float32))
        w(s, buf)
        with contextlib.suppress(RuntimeError):
            flightrec.readback(buf)        # use-after-donate: host read
        with contextlib.suppress(RuntimeError, ValueError):
            w(s, buf)                      # use-after-donate: re-dispatch
        fresh = jax.device_put(np.ones((4,), np.float32))
        w(s, fresh)                        # correct discipline: clean
    assert [v.rule for v in audit.violations] == ["HL303", "HL303"]
    assert "host read" in audit.violations[0].message
    assert "re-dispatched" in audit.violations[1].message


def test_hl303_continuous_runner_discipline_is_clean():
    """The clean fixture: the REAL serve ContinuousRunner depth-2
    in-flight pipeline (fresh staged buffer per batch, donated exactly
    once) passes the donation audit — the registered lint-time
    protocols drive exactly this."""
    from harp_tpu.analysis.drivers import PROTOCOLS

    assert set(PROTOCOLS) >= {"serve.kmeans_continuous",
                              "serve.mfsgd_continuous"}
    drive = PROTOCOLS["serve.kmeans_continuous"]()
    vs = commgraph.audit_protocol("serve.kmeans_continuous", drive)
    assert vs == [], [v.format() for v in vs]


def test_hl303_retry_restage_protocol_is_clean_and_non_vacuous():
    """The PR-10 retry protocol: an injector-killed dispatch retried
    through a FRESHLY staged buffer passes the donation audit — and the
    drive itself asserts the fault fired, so the protocol can never go
    vacuously green."""
    from harp_tpu.analysis.drivers import PROTOCOLS

    assert "serve.retry_restage" in PROTOCOLS
    drive = PROTOCOLS["serve.retry_restage"]()
    vs = commgraph.audit_protocol("serve.retry_restage", drive)
    assert vs == [], [v.format() for v in vs]


def test_hl303_sabotaged_retry_redispatching_donated_buffer_fires(mesh):
    """The sabotaged twin of serve.retry_restage: a retry loop that
    re-dispatches the SAME staged buffer after the failed attempt (the
    'obvious' retry) is exactly the use-after-donate HL303 exists for —
    the CPU sim would pass it silently."""
    from harp_tpu.serve.engines import ENGINES
    from harp_tpu.serve.server import Server
    from harp_tpu.utils.fault import FaultInjector, InjectedFault

    rng = np.random.default_rng(0)
    srv = Server("kmeans",
                 state=ENGINES["kmeans"].synthetic_state(rng, k=4, d=8),
                 mesh=mesh, ladder=(1, 4))
    srv.startup()
    n_state = len(srv.engine.state_args())
    audit = commgraph.DonationAudit("protocol:sabotaged_retry")
    with audit:
        srv.wrap_executables(
            lambda rung, exe: audit.wrap(exe, (n_state,), f"b{rung}"))
        staged = srv.engine.put_input(
            srv.engine.make_input(
                rng.normal(size=(2, 8)).astype(np.float32), 4))
        inj = FaultInjector(fail={"dispatch": (1,)})
        with inj.arm():
            with contextlib.suppress(InjectedFault):
                srv._exec[4](*srv.engine.state_args(), staged)
            # the sabotage: retry WITHOUT restaging
            with contextlib.suppress(RuntimeError, ValueError):
                srv._exec[4](*srv.engine.state_args(), staged)
    assert any(v.rule == "HL303" and "re-dispatched" in v.message
               for v in audit.violations)


def test_hl303_elastic_rebalance_restage_protocol_is_clean():
    """The PR-15 elastic survival protocol: a permanent worker loss
    mid-loop, shrink to survivors, every post-shrink dispatch through a
    FRESHLY restaged buffer — clean under the donation audit, and the
    drive asserts the loss fired (never vacuously green)."""
    from harp_tpu.analysis.drivers import PROTOCOLS

    assert "elastic.rebalance_restage" in PROTOCOLS
    drive = PROTOCOLS["elastic.rebalance_restage"]()
    vs = commgraph.audit_protocol("elastic.rebalance_restage", drive)
    assert vs == [], [v.format() for v in vs]


def test_hl303_sabotaged_shrink_reusing_preloss_buffer_fires(mesh):
    """The sabotaged twin of elastic.rebalance_restage: after the
    permanent loss, the 'obvious' continuation re-dispatches the
    PRE-SHRINK staged buffer on the survivor mesh — but that buffer was
    already donated to the dead dispatch (and lives on a mesh that no
    longer exists).  The CPU sim passes it silently; HL303 must not."""
    import jax
    import jax.numpy as jnp

    from harp_tpu.parallel.mesh import WorkerMesh
    from harp_tpu.utils import flightrec
    from harp_tpu.utils.fault import FaultInjector, PermanentWorkerLoss

    audit = commgraph.DonationAudit("protocol:sabotaged_shrink")

    def build(m, tag):
        fn = jax.jit(lambda c, x: (c + x.sum(), x * 2.0),
                     donate_argnums=(1,))
        return audit.wrap(flightrec.track(fn, tag), (1,), tag)

    rng = np.random.default_rng(0)
    exe = build(mesh, "b_full")
    carry = jax.device_put(jnp.float32(0.0), mesh.replicated())
    inj = FaultInjector(seed=0, permanent={"dispatch": (1,)},
                        lost_worker=mesh.num_workers - 1)
    with audit, inj.arm():
        staged = mesh.shard_array(
            rng.normal(size=(56, 4)).astype(np.float32), 0)
        with contextlib.suppress(PermanentWorkerLoss):
            exe(carry, staged)  # donated here, then the loss fires
        surv = WorkerMesh(mesh.devices[:-1])
        exe2 = build(surv, "b_surv")
        carry2 = jax.device_put(jnp.float32(0.0), surv.replicated())
        # the sabotage: continue on the survivors WITHOUT restaging
        with contextlib.suppress(Exception):
            exe2(carry2, staged)
    assert any(v.rule == "HL303" and "already donated" in v.message
               for v in audit.violations), \
        [v.format() for v in audit.violations]


def test_commgraph_registry_is_clean_and_covers_the_surface():
    """Every registered driver extracts a clean CommGraph (no untracked
    wire, no lying sheet, no hoistable collective), the registry covers
    >= 10 programs (all six serve engines + rotate pipeline + ingest
    pair), and the serve engines' donated batch arg is visible in the
    aliasing info."""
    from harp_tpu.analysis.drivers import DRIVERS

    assert len(DRIVERS) >= 10
    assert {"serve.kmeans_assign", "serve.mfsgd_topk", "serve.lda_infer",
            "serve.mlp_logits", "serve.rf_vote", "serve.svm_scores",
            "rotate.pipeline_chunked", "ingest.accum_chunk",
            "ingest.finish_epoch"} <= set(DRIVERS)
    for name, build in DRIVERS.items():
        fn, args = build()
        vs, graph = commgraph.analyze_program(name, fn, args)
        assert vs == [], (name, [v.format() for v in vs])
        if name.startswith("serve."):
            assert graph.donated_args, name  # the batch buffer donates
    # the chunked rotate pipeline's ring traffic carries the full
    # n_chunks * ring-size amplification
    fn, args = DRIVERS["rotate.pipeline_chunked"]()
    _, graph = commgraph.analyze_program("rotate.pipeline_chunked", fn,
                                         args)
    (site,) = graph.sites
    assert site.primitive == "ppermute" and site.amplification == 16


def test_check_jsonl_commgraph_sets_in_sync():
    """check_jsonl freezes the byte-sheet vocabulary (standalone
    script); drift from the live registries fails here."""
    import check_jsonl

    from harp_tpu.analysis.drivers import DRIVERS
    from harp_tpu.parallel.collective import PRIMITIVE_VERBS

    assert tuple(sorted(DRIVERS)) == check_jsonl.KNOWN_LINT_PROGRAMS
    assert tuple(sorted(PRIMITIVE_VERBS)) == \
        check_jsonl.KNOWN_COMM_PRIMITIVES
    all_verbs = set().union(*PRIMITIVE_VERBS.values())
    assert tuple(sorted(all_verbs)) == check_jsonl.KNOWN_COMM_VERBS


# ---------------------------------------------------------------------------
# Allowlist + registry + CLI
# ---------------------------------------------------------------------------

def test_allowlist_requires_reason(tmp_path):
    p = tmp_path / "allow.toml"
    p.write_text('[[allow]]\nrule = "HL001"\npath = "x.py"\n')
    with pytest.raises(allowlist_mod.AllowlistError):
        allowlist_mod.load(str(p))


def test_allowlist_suppresses_and_reports_stale(tmp_path):
    from harp_tpu.analysis import Violation

    p = tmp_path / "allow.toml"
    p.write_text(textwrap.dedent("""
        [[allow]]
        rule = "HL001"
        path = "a.py"
        reason = "legit"
        [[allow]]
        rule = "HL002"
        path = "never.py"
        reason = "stale"
    """))
    entries = allowlist_mod.load(str(p))
    vs = [Violation("HL001", "a.py", 1, "m"),
          Violation("HL001", "b.py", 1, "m")]
    kept, suppressed, stale = allowlist_mod.apply(vs, entries)
    assert [v.path for v in kept] == ["b.py"]
    assert [v.path for v in suppressed] == ["a.py"]
    assert [e["path"] for e in stale] == ["never.py"]


def test_check_jsonl_rule_set_in_sync():
    """scripts/check_jsonl.py invariant 6 hardcodes the rule ids (the
    script stays standalone); drift from the registry fails here."""
    import check_jsonl

    assert tuple(rule_ids()) == check_jsonl.KNOWN_LINT_RULES


def test_cli_fixture_path_exits_nonzero(tmp_path, capsys):
    bad = tmp_path / "bad_module.py"
    bad.write_text("import jax\n"
                   "def f(s):\n"
                   "    return jax.random.PRNGKey(s)\n")
    rc = cli.main([str(bad), "--json"])
    out = capsys.readouterr().out.strip().splitlines()[-1]
    row = json.loads(out)
    assert rc == 1
    assert row["kind"] == "lint" and row["violations"] == 1
    assert row["per_rule"] == {"HL002": 1}
    # provenance stamp rides the line (check_jsonl invariant 6)
    assert all(k in row for k in ("backend", "date", "commit"))


def test_cli_audit_module_trips_jaxpr_and_mosaic_layers(tmp_path, capsys):
    fixture = tmp_path / "fixture_mod.py"
    fixture.write_text(textwrap.dedent("""
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        def _bad_scan():
            def f(table, idxs, upds):
                def body(tbl, x):
                    i, u = x
                    vals = jnp.take(tbl, i, axis=0)
                    tbl = lax.dynamic_update_slice(tbl, u, (i[0], 0))
                    return tbl, vals.sum()
                return lax.scan(body, table, (idxs, upds))
            return f, (jnp.zeros((16, 8)), jnp.zeros((3, 2), jnp.int32),
                       jnp.zeros((3, 1, 8)))

        def _bad_kernel():
            def kern(seed_ref, o_ref):
                pltpu.prng_seed(seed_ref[0], seed_ref[1], seed_ref[2])
                bits = pltpu.prng_random_bits(o_ref.shape)
                o_ref[...] = lax.shift_right_logical(
                    bits, 8).astype(jnp.float32)
            def f(seed):
                return pl.pallas_call(kern, out_shape=jax.ShapeDtypeStruct(
                    (8, 128), jnp.float32))(seed)
            return f, (jnp.zeros(3, jnp.int32),)

        HARPLINT_DRIVERS = {"bad_scan": _bad_scan}
        HARPLINT_KERNELS = {"bad_seed": _bad_kernel}
    """))
    rc = cli.main(["--audit-module", str(fixture), "--json"])
    row = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 1
    assert "HL101" in row["per_rule"] and "HL202" in row["per_rule"]


def test_cli_audit_module_trips_commgraph_layer(tmp_path, capsys):
    """Layer-4 exit codes through the CLI: an unledgered psum (HL301),
    a loop-invariant allgather (HL304), and a sabotaged donation
    protocol (HL303) in one fixture module must all land in per_rule
    and flip the exit code."""
    fixture = tmp_path / "fixture_cg.py"
    fixture.write_text(textwrap.dedent("""
        import contextlib

        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax import lax
        from jax.sharding import PartitionSpec as P

        from harp_tpu.parallel import collective as C
        from harp_tpu.parallel.mesh import WorkerMesh
        from harp_tpu.utils import flightrec


        def _mesh():
            return WorkerMesh()


        def _x(mesh):
            return jax.ShapeDtypeStruct(
                (8, 4), jnp.float32,
                sharding=mesh.sharding(mesh.spec(0)))


        def _raw_psum():
            mesh = _mesh()
            fn = jax.jit(mesh.shard_map(
                lambda x: lax.psum(x, "workers"),
                in_specs=(mesh.spec(0),), out_specs=P()))
            return fn, (_x(mesh),)


        def _hoistable():
            mesh = _mesh()

            def prog(x):
                def body(c, _):
                    return c + C.allgather(x).sum(), None
                out, _ = lax.scan(body, jnp.float32(0.0), None,
                                  length=4)
                return out

            fn = jax.jit(mesh.shard_map(
                prog, in_specs=(mesh.spec(0),), out_specs=P()))
            return fn, (_x(mesh),)


        def _sabotage():
            def drive(audit):
                exe = jax.jit(lambda s, b: s + b, donate_argnums=(1,))
                w = audit.wrap(exe, (1,), "toy.step")
                s = jax.device_put(np.ones((4,), np.float32))
                buf = jax.device_put(np.ones((4,), np.float32))
                w(s, buf)
                with contextlib.suppress(RuntimeError):
                    flightrec.readback(buf)
            return drive


        HARPLINT_DRIVERS = {"raw_psum": _raw_psum,
                            "hoistable": _hoistable}
        HARPLINT_PROTOCOLS = {"sabotage": _sabotage}
    """))
    rc = cli.main(["--audit-module", str(fixture), "--json"])
    row = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 1
    assert {"HL301", "HL303", "HL304"} <= set(row["per_rule"])
    # fixture rows never ship byte sheets: sheet program names are
    # pinned to the drivers registry by check_jsonl invariant 6
    assert "byte_sheets" not in row


def test_cli_stale_allowlist_entry_fails(tmp_path, capsys):
    """Satellite: a stale allowlist entry is a HARD failure, not a
    report line — same exit as an unallowlisted violation (AST-layer
    full-repo run; the committed entries are all AST-rule entries, so
    the control run stays green)."""
    committed = open(os.path.join(ROOT, "harp_tpu", "analysis",
                                  "allowlist.toml")).read()
    ok = tmp_path / "ok.toml"
    ok.write_text(committed)
    rc = cli.main(["--json", "--layer", "ast", "--allowlist", str(ok)])
    capsys.readouterr()
    assert rc == 0
    stale = tmp_path / "stale.toml"
    stale.write_text(committed + textwrap.dedent("""
        [[allow]]
        rule = "HL002"
        path = "harp_tpu/models/never_existed.py"
        reason = "synthetic stale entry for the hard-fail test"
    """))
    rc = cli.main(["--json", "--layer", "ast", "--allowlist",
                   str(stale)])
    row = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 1
    assert row["stale_allowlist"] == 1
    assert row["clean"] is True  # no violations — the ENTRY is the rot


def test_cli_changed_mode_scopes_the_ast_layer(monkeypatch, capsys):
    """--changed lints only the git-changed files in the AST layer (the
    ~2 s dev loop); staleness reporting is disabled because an unswept
    file cannot prove an entry dead."""
    monkeypatch.setattr(cli, "_changed_paths",
                        lambda repo: ["harp_tpu/utils/timing.py"])
    rc = cli.main(["--changed", "--json", "--layer", "ast"])
    row = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0, row
    assert row["files_scanned"] == 1
    assert row["stale_allowlist"] == 0


def test_changed_paths_subset_of_sweep():
    """_changed_paths returns repo-relative paths drawn from the same
    set the full sweep lints (deleted files never error)."""
    from harp_tpu.analysis.astlints import iter_python_files

    repo = cli.repo_root()
    changed = cli._changed_paths(repo)
    assert isinstance(changed, list)
    assert set(changed) <= set(iter_python_files(repo))


def test_cli_repo_run_is_clean(capsys):
    """THE tier-1 gate: zero unallowlisted violations at HEAD, all five
    layers, and the machine line passes check_jsonl invariant 6 — with
    the Layer-4 byte sheets riding the row (>= 10 programs; kmeans.fit
    matching the hand-computed sheet exactly)."""
    import check_jsonl

    rc = cli.main(["--json"])
    out = capsys.readouterr().out.strip().splitlines()[-1]
    row = json.loads(out)
    assert rc == 0, row
    assert row["clean"] is True and row["violations"] == 0
    assert row["stale_allowlist"] == 0
    assert check_jsonl._check_lint_row("stdout", 1, row) == []
    sheets = row["byte_sheets"]
    assert len(sheets) >= 10
    km = sheets["kmeans.fit"]
    assert km["bytes_per_trace"] == 8 * 32 * 4 + 8 * 4 + 4
    assert km["amplified_bytes"] == 2 * km["bytes_per_trace"]
    assert km["collectives"][0]["verb"] == "allreduce"


# ---------------------------------------------------------------------------
# Layer 5 — thread-root graph (HL4xx): one sabotaged plane per rule
# ---------------------------------------------------------------------------

from harp_tpu.analysis import threadgraph  # noqa: E402


def _plane(owners=("main",), name="fix"):
    return threadgraph.PlaneSpec(name, ("fix.py",), tuple(owners))


def _analyze(src, owners=("main",), spine_locked=None):
    return threadgraph.analyze_sources(
        _plane(owners), {"fix.py": textwrap.dedent(src)},
        spine_locked=spine_locked)


_HL401_SRC = """
    import threading

    import jax.numpy as jnp

    class Worker:
        def start(self):
            t = threading.Thread(target=self._work, daemon=True,
                                 name="fix-worker")
            t.start()

        def _work(self):
            return jnp.zeros((4,))
"""


def test_hl401_jax_from_non_owner_thread_fires():
    """The sabotaged twin: a named worker thread whose entry reaches a
    jax call on a plane where only main owns jax."""
    vs = _analyze(_HL401_SRC)
    assert _rules(vs) == ["HL401"]
    assert "thread:_work" in vs[0].message
    assert "jnp.zeros" in vs[0].source


def test_hl401_designated_owner_is_clean():
    """The transport-dispatcher shape: the SAME source is clean once the
    plane declares the thread root a jax owner (serve's
    thread:_dispatch_loop is the pinned real case)."""
    assert _analyze(_HL401_SRC, owners=("main", "thread:_work")) == []


_HL402_SRC = """
    import time

    class FrontEnd:
        async def _run(self):
            while True:
                self._drain()

        def _drain(self):
            time.sleep(0.1)
            self._done.wait()
"""


def test_hl402_blocking_call_in_event_loop_fires():
    """time.sleep and an unbounded Event.wait both reachable from the
    coroutine root freeze every socket the loop owns."""
    vs = _analyze(_HL402_SRC)
    assert _rules(vs) == ["HL402"] and len(vs) == 2
    assert any("time.sleep" in v.message for v in vs)
    assert any("wait" in v.source for v in vs)


def test_hl402_bounded_and_awaited_are_clean():
    vs = _analyze("""
        import asyncio

        class FrontEnd:
            async def _run(self):
                await asyncio.sleep(0.1)
                self._done.wait(0.5)
    """)
    assert vs == []


_HL403_SPINE_SRC = """
    import threading

    from harp_tpu.utils import reqtrace

    class Pump:
        def start(self):
            t = threading.Thread(target=self._pump, daemon=True,
                                 name="fix-pump")
            t.start()

        def serve_one(self):
            rid = reqtrace.tracer.begin(0.0)

        def _pump(self):
            reqtrace.tracer.event("r1", "deliver")
"""


def test_hl403_spine_written_from_two_roots_unlocked_fires():
    """The single-writer contract: main and a pump thread both hit the
    reqtrace spine, whose mutators are NOT verified locked."""
    vs = _analyze(_HL403_SPINE_SRC, spine_locked={"reqtrace": False})
    assert _rules(vs) == ["HL403"]
    assert "reqtrace" in vs[0].message
    assert "single-writer" in vs[0].message


def test_hl403_verified_locked_spine_is_clean():
    """Same two-root writes, but the spine's own mutators verified as
    internally locked (the PR-20 reqtrace RLock) — no violation."""
    assert _analyze(_HL403_SPINE_SRC,
                    spine_locked={"reqtrace": True}) == []


_HL403_ATTR_TMPL = """
    import threading

    class Counter:
        def __init__(self):
            self.n = 0
            self._lock = threading.Lock()

        def start(self):
            t = threading.Thread(target=self._bump, daemon=True,
                                 name="fix-bump")
            t.start()

        def bump_from_main(self):
            {main_write}

        def _bump(self):
            {thread_write}
"""


def test_hl403_shared_attr_two_roots_no_lock_fires():
    vs = _analyze(_HL403_ATTR_TMPL.format(
        main_write="self.n += 1", thread_write="self.n += 1"))
    assert _rules(vs) == ["HL403"]
    assert "'n'" in vs[0].message and "no common lock" in vs[0].message


def test_hl403_shared_attr_common_lock_is_clean():
    """Both write paths under self._lock: the lock sets intersect, and
    __init__ writes are exempt (construction happens-before start)."""
    vs = _analyze(_HL403_ATTR_TMPL.format(
        main_write="with self._lock:\n                self.n += 1",
        thread_write="with self._lock:\n                self.n += 1"))
    assert vs == []


def test_hl404_dispatch_under_lock_fires():
    """A tracked-executable dispatch AND a jax call inside a with-lock
    body: 20-150 ms relay round trips while holding the lock."""
    vs = _analyze("""
        class Runner:
            def flush(self, batch):
                with self._lock:
                    out = self._exec[0](batch)
                return out

            def stage(self, a, b):
                import jax.numpy as jnp
                with self._lock:
                    return jnp.dot(a, b)
    """)
    assert _rules(vs) == ["HL404"] and len(vs) == 2
    assert all("holding" in v.message for v in vs)


def test_hl404_dispatch_after_lock_release_is_clean():
    vs = _analyze("""
        class Runner:
            def flush(self):
                with self._lock:
                    batch = self._q.popleft()
                return self._exec[0](batch)
    """)
    assert vs == []


def test_hl405_unjoinable_thread_fires():
    vs = _analyze("""
        import threading

        def spawn(fn):
            t = threading.Thread(target=fn, name="fix-zombie")
            t.start()
            return t
    """)
    assert _rules(vs) == ["HL405"]
    assert "daemon" in vs[0].message


def test_hl405_daemon_or_bounded_join_is_clean():
    assert _analyze("""
        import threading

        def spawn_daemon(fn):
            t = threading.Thread(target=fn, daemon=True)
            t.start()

        def spawn_joined(fn):
            t = threading.Thread(target=fn)
            t.start()
            t.join(5.0)
    """) == []


def test_threads_layer_repo_at_head_only_allowlisted_findings():
    """The Layer-5 HEAD gate at the API level: every finding over the
    real planes is HL403 and matched by a committed allowlist entry
    (with its reviewed reason) — nothing unallowlisted, nothing stale
    among the HL4xx entries."""
    vs = threadgraph.analyze_repo(ROOT)
    assert vs, "the four reviewed HL403 findings should exist at HEAD"
    assert _rules(vs) == ["HL403"]
    entries = allowlist_mod.load()
    kept, suppressed, stale = allowlist_mod.apply(vs, entries)
    assert kept == []
    assert len(suppressed) == len(vs)
    assert not any(e["rule"].startswith("HL4") for e in stale)


def test_cli_threads_layer_scoped_run_is_clean(capsys):
    """`lint --layer threads` (the scoped run `--changed` uses): exit 0,
    every finding allowlisted, and staleness judged ONLY against
    threads-layer entries (an AST entry can't be proven dead here)."""
    rc = cli.main(["--json", "--layer", "threads"])
    row = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0, row
    assert row["clean"] is True and row["violations"] == 0
    assert row["allowlisted"] >= 4
    assert row["stale_allowlist"] == 0


def test_planes_for_paths_scopes_changed_runs():
    """--changed scoping: a plane module maps to its plane; a spine
    module re-runs every plane (lock verdicts feed all of them); an
    unrelated file runs none."""
    assert threadgraph.planes_for_paths(["harp_tpu/ingest.py"]) == \
        ["ingest"]
    allp = [p.name for p in threadgraph.PLANES]
    assert threadgraph.planes_for_paths(
        ["harp_tpu/utils/reqtrace.py"]) == allp
    assert threadgraph.planes_for_paths(["harp_tpu/models/kmeans.py"]) \
        == []


def test_spine_lock_verification_reads_the_mutator_bodies():
    """The verdict is derived from the spine SOURCE, not asserted: a
    twin ReqTracer with one unlocked mutator flips to False."""
    spec = next(s for s in threadgraph.SPINES if s.name == "reqtrace")
    locked = textwrap.dedent("""
        class ReqTracer:
            def begin(self, t):
                with self._lock:
                    return 1
            def event(self, rid, name):
                with self._lock:
                    pass
            def end(self, rid, outcome, t):
                with self._lock:
                    pass
            def mark(self, name):
                with self._lock:
                    pass
    """)
    assert threadgraph._spine_locked_from_source(spec, locked) is True
    sabotaged = locked.replace(
        "def mark(self, name):\n        with self._lock:\n            pass",
        "def mark(self, name):\n        self.rows.append(name)")
    assert threadgraph._spine_locked_from_source(spec, sabotaged) is False
    # the REAL reqtrace at HEAD carries the PR-20 RLock
    verdicts = threadgraph.spine_lock_verdicts(ROOT)
    assert verdicts["reqtrace"] is True


def test_ownership_map_is_generated_from_the_static_graph():
    """The runtime twin's contract: forbidden patterns are exactly the
    named non-owner roots the graph discovered (watchdog, scheduler
    workers, the TCP accept loop) — and the serve dispatcher, a
    designated owner, is NOT forbidden."""
    import fnmatch

    omap = threadgraph.ownership_map(ROOT)
    pats = omap["forbidden_thread_patterns"]
    assert "harp-watchdog" in pats
    assert "harp-serve-tcp" in pats
    assert any(p.startswith("harp-sched-static-") for p in pats)
    assert any(p.startswith("harp-sched-dyn-") for p in pats)
    assert not any(fnmatch.fnmatch("harp-serve-dispatch", p)
                   for p in pats)
    assert set(omap["spines"]) == {sp.name for sp in threadgraph.SPINES}
    assert omap["spines"]["reqtrace"]["locked"] is True
    for name, plane in omap["planes"].items():
        assert set(plane["forbidden_thread_patterns"]) <= set(pats)
        assert "main" in plane["jax_owners"]
