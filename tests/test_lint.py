"""harplint (harp_tpu/analysis) — golden fixtures for every layer.

One synthetic module per Layer-1 rule that must trip it, the pre-fix LDA
scan-carry gather+DUS pattern pinned as a Layer-2 positive (and the
fixed tile-local form as a negative), a 3-seed-word ``prng_seed`` toy
kernel the Mosaic audit must flag WITHOUT hardware, and the repo-wide
tier-1 gate: zero unallowlisted violations at HEAD.
"""

import json
import os
import sys
import textwrap

import numpy as np
import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "scripts"))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax import lax  # noqa: E402

from harp_tpu.analysis import rule_ids  # noqa: E402
from harp_tpu.analysis import allowlist as allowlist_mod  # noqa: E402
from harp_tpu.analysis.astlints import lint_source  # noqa: E402
from harp_tpu.analysis.jaxpr_checks import (  # noqa: E402
    find_large_constants, find_scan_copy_traps)
from harp_tpu.analysis.mosaic_audit import (  # noqa: E402
    audit_kernel, check_kernel_jaxpr)
from harp_tpu.analysis import cli  # noqa: E402


def _rules(violations):
    return sorted({v.rule for v in violations})


# ---------------------------------------------------------------------------
# Layer 1 — one synthetic module per rule
# ---------------------------------------------------------------------------

def test_hl001_raw_collective_trips():
    src = textwrap.dedent("""
        from jax import lax
        def step(x):
            return lax.psum(x, "workers")
    """)
    vs = lint_source("harp_tpu/models/fake.py", src)
    assert _rules(vs) == ["HL001"]


def test_hl001_exempt_inside_verb_layer():
    src = "from jax import lax\ndef f(x):\n    return lax.psum(x, 'w')\n"
    assert lint_source("harp_tpu/parallel/collective.py", src) == []
    assert lint_source("harp_tpu/parallel/rotate.py", src) == []


def test_hl001_axis_queries_stay_legal():
    src = ("from jax import lax\n"
           "def f():\n"
           "    return lax.axis_index('w') + lax.axis_size('w')\n")
    assert lint_source("harp_tpu/models/fake.py", src) == []


def test_hl002_prngkey_trips():
    src = ("import jax\n"
           "def seed_me(s):\n"
           "    return jax.random.PRNGKey(s)\n")
    vs = lint_source("harp_tpu/models/fake.py", src)
    assert _rules(vs) == ["HL002"]
    # the helper that wraps the trap is exempt
    assert lint_source("harp_tpu/utils/prng.py", src) == []


def test_hl003_asarray_on_numpy_trips():
    src = ("import jax.numpy as jnp, numpy as np\n"
           "def ingest(x):\n"
           "    return jnp.asarray(np.asarray(x, np.float32))\n")
    vs = lint_source("harp_tpu/models/fake.py", src)
    assert _rules(vs) == ["HL003"]


def test_hl003_device_put_wrapper_is_clean():
    src = ("import jax, jax.numpy as jnp, numpy as np\n"
           "def ingest(x):\n"
           "    return jax.device_put(jnp.asarray(np.asarray(x)))\n")
    assert lint_source("harp_tpu/models/fake.py", src) == []


def test_hl004_untracked_jit_trips_only_in_models():
    src = ("import jax\n"
           "def driver():\n"
           "    step = jax.jit(lambda x: x)\n"
           "    return step\n")
    assert _rules(lint_source("harp_tpu/models/fake.py", src)) == ["HL004"]
    assert lint_source("harp_tpu/utils/fake.py", src) == []


def test_hl004_factory_return_and_track_are_clean():
    src = ("import jax\n"
           "from harp_tpu.utils import flightrec\n"
           "def make_step_fn():\n"
           "    return jax.jit(lambda x: x)\n"
           "def driver():\n"
           "    return flightrec.track(jax.jit(lambda x: x), 'd.step')\n")
    assert lint_source("harp_tpu/models/fake.py", src) == []


def test_hl005_undated_perf_claim_trips():
    src = ('def fast():\n'
           '    """Runs at 246.5M ups/s on the graded shape."""\n')
    vs = lint_source("harp_tpu/models/fake.py", src)
    assert _rules(vs) == ["HL005"]
    # date + chip in the documented form passes
    src_ok = ('def fast():\n'
              '    """246.5M ups/s (2026-08-01, 1x v5e)."""\n')
    assert lint_source("harp_tpu/models/fake.py", src_ok) == []


def test_hl000_syntax_error_is_loud():
    assert _rules(lint_source("harp_tpu/models/fake.py",
                              "def broken(:\n")) == ["HL000"]


# ---------------------------------------------------------------------------
# Layer 2 — the LDA copy-trap regression, pinned
# ---------------------------------------------------------------------------

def _prefix_lda_pattern(table, idxs, upds):
    """The PRE-FIX shape of the LDA epoch: the scan body gathers from the
    carried table AND dynamic_update_slice's it (cost 20 s of a 29 s
    epoch before the tile-local fix)."""

    def body(tbl, x):
        i, u = x
        vals = jnp.take(tbl, i, axis=0)              # gather from carry
        tbl = lax.dynamic_update_slice(tbl, u, (i[0], 0))
        return tbl, vals.sum()

    return lax.scan(body, table, (idxs, upds))


def _fixed_lda_pattern(table, idxs, upds):
    """The FIXED form: dynamic_slice the tile first, gather tile-locally
    — the gather operand is the slice result, not the carry."""

    def body(tbl, x):
        i, u = x
        tile = lax.dynamic_slice(tbl, (0, 0), (4, tbl.shape[1]))
        vals = jnp.take(tile, i % 4, axis=0)
        tbl = lax.dynamic_update_slice(tbl, u, (i[0], 0))
        return tbl, vals.sum()

    return lax.scan(body, table, (idxs, upds))


_SCAN_ARGS = (jnp.zeros((16, 8)), jnp.zeros((3, 2), jnp.int32),
              jnp.zeros((3, 1, 8)))


def test_scan_copy_trap_positive():
    closed = jax.jit(_prefix_lda_pattern).trace(*_SCAN_ARGS).jaxpr
    vs = find_scan_copy_traps(closed, "fixture")
    assert _rules(vs) == ["HL101"]
    assert "copy the whole" in vs[0].message.lower()


def test_scan_copy_trap_fixed_form_negative():
    closed = jax.jit(_fixed_lda_pattern).trace(*_SCAN_ARGS).jaxpr
    assert find_scan_copy_traps(closed, "fixture") == []


def test_scan_copy_trap_sees_fori_loop():
    def bad_fori(table, idxs, upds):
        def body(t, tbl):
            vals = jnp.take(tbl, idxs[t], axis=0)
            return lax.dynamic_update_slice(
                tbl, upds[t] + vals.sum(), (idxs[t][0], 0))
        return lax.fori_loop(0, 3, body, table)

    closed = jax.jit(bad_fori).trace(*_SCAN_ARGS).jaxpr
    assert _rules(find_scan_copy_traps(closed, "f")) == ["HL101"]


def test_large_constant_detector():
    big = np.ones((1 << 18,), np.float32)            # 1 MiB exactly

    def closes_over(x):
        return x + jnp.asarray(big)

    closed = jax.jit(closes_over).trace(jnp.zeros(1 << 18)).jaxpr
    # over a small threshold: flagged; at the default 1 MiB: exactly at
    # the boundary (not >), so clean
    assert _rules(find_large_constants(closed, "f", 1 << 16)) == ["HL102"]
    assert find_large_constants(closed, "f", 1 << 20) == []


def test_driver_registry_is_clean():
    """The registered flagship driver programs (kmeans fit, ring
    attention, mfsgd epoch) carry no copy trap and no oversized
    literal."""
    from harp_tpu.analysis.drivers import DRIVERS
    from harp_tpu.analysis.jaxpr_checks import analyze_program

    assert set(DRIVERS) >= {"kmeans.fit", "ring_attention", "mfsgd.epoch"}
    for name, build in DRIVERS.items():
        fn, args = build()
        assert analyze_program(fn, args, f"driver:{name}") == []


# ---------------------------------------------------------------------------
# Layer 3 — Mosaic audit, no hardware
# ---------------------------------------------------------------------------

def _toy_seed_kernel(n_words: int):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kern(seed_ref, o_ref):
        pltpu.prng_seed(*(seed_ref[i] for i in range(n_words)))
        bits = pltpu.prng_random_bits(o_ref.shape)
        o_ref[...] = lax.shift_right_logical(bits, 8).astype(jnp.float32)

    def f(seed):
        # seed words ride SMEM so seed_ref[i] reads scalars, as the real
        # lda kernel's scalar-prefetch grid does
        return pl.pallas_call(
            kern,
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)],
            out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32)
        )(seed)

    return f, (jnp.zeros(max(n_words, 1), jnp.int32),)


def test_mosaic_audit_flags_3_seed_words():
    """The 2026-08-01 in-window silicon failure, caught on CPU: a 3-word
    prng_seed must trip HL202 from the jaxpr alone."""
    fn, args = _toy_seed_kernel(3)
    closed = jax.jit(fn).trace(*args).jaxpr
    vs = check_kernel_jaxpr(closed, "kernel:toy3")
    assert "HL202" in _rules(vs)
    assert "2 " in vs[0].message or "TWO" in vs[0].message


def test_mosaic_audit_2_seed_words_clean():
    fn, args = _toy_seed_kernel(2)
    vs = audit_kernel("toy2", fn, args)
    assert vs == [], [v.message for v in vs]


def test_mosaic_audit_flags_uint32_float_cast():
    from jax.experimental import pallas as pl

    def kern(x_ref, o_ref):
        o_ref[...] = x_ref[...].astype(jnp.float32)

    def f(x):
        return pl.pallas_call(
            kern, out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32)
        )(x)

    vs = audit_kernel("toyu32", f, (jnp.zeros((8, 128), jnp.uint32),))
    # the silicon limit local lowering does NOT enforce: HL203 must fire
    # even though the local Mosaic pass stays green
    assert "HL203" in _rules(vs)


def test_mosaic_audit_flags_unaligned_block_dim():
    from jax.experimental import pallas as pl

    def kern(x_ref, o_ref):
        o_ref[...] = x_ref[...]

    def f(x):
        return pl.pallas_call(
            kern, grid=(4,),
            in_specs=[pl.BlockSpec((4, 128), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((4, 128), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((16, 128), jnp.float32))(x)

    vs = audit_kernel("toyblk", f, (jnp.zeros((16, 128), jnp.float32),))
    assert "HL204" in _rules(vs)


def test_kernel_registry_audit_is_clean():
    """Every registered ops/ kernel lowers for TPU on this CPU host and
    passes the silicon-limit checks (the audit that caught
    flash_attention's is_finite, which had only ever run in interpret
    mode)."""
    from harp_tpu.analysis.mosaic_audit import audit_registry, \
        registered_kernels

    assert set(registered_kernels()) >= {
        "kmeans.partials", "kmeans.partials_int8", "lda.cgs_entry_update",
        "mfsgd.sgd_tile_update", "flash_attention"}
    vs = audit_registry()
    assert vs == [], [v.format() for v in vs]


# ---------------------------------------------------------------------------
# Allowlist + registry + CLI
# ---------------------------------------------------------------------------

def test_allowlist_requires_reason(tmp_path):
    p = tmp_path / "allow.toml"
    p.write_text('[[allow]]\nrule = "HL001"\npath = "x.py"\n')
    with pytest.raises(allowlist_mod.AllowlistError):
        allowlist_mod.load(str(p))


def test_allowlist_suppresses_and_reports_stale(tmp_path):
    from harp_tpu.analysis import Violation

    p = tmp_path / "allow.toml"
    p.write_text(textwrap.dedent("""
        [[allow]]
        rule = "HL001"
        path = "a.py"
        reason = "legit"
        [[allow]]
        rule = "HL002"
        path = "never.py"
        reason = "stale"
    """))
    entries = allowlist_mod.load(str(p))
    vs = [Violation("HL001", "a.py", 1, "m"),
          Violation("HL001", "b.py", 1, "m")]
    kept, suppressed, stale = allowlist_mod.apply(vs, entries)
    assert [v.path for v in kept] == ["b.py"]
    assert [v.path for v in suppressed] == ["a.py"]
    assert [e["path"] for e in stale] == ["never.py"]


def test_check_jsonl_rule_set_in_sync():
    """scripts/check_jsonl.py invariant 6 hardcodes the rule ids (the
    script stays standalone); drift from the registry fails here."""
    import check_jsonl

    assert tuple(rule_ids()) == check_jsonl.KNOWN_LINT_RULES


def test_cli_fixture_path_exits_nonzero(tmp_path, capsys):
    bad = tmp_path / "bad_module.py"
    bad.write_text("import jax\n"
                   "def f(s):\n"
                   "    return jax.random.PRNGKey(s)\n")
    rc = cli.main([str(bad), "--json"])
    out = capsys.readouterr().out.strip().splitlines()[-1]
    row = json.loads(out)
    assert rc == 1
    assert row["kind"] == "lint" and row["violations"] == 1
    assert row["per_rule"] == {"HL002": 1}
    # provenance stamp rides the line (check_jsonl invariant 6)
    assert all(k in row for k in ("backend", "date", "commit"))


def test_cli_audit_module_trips_jaxpr_and_mosaic_layers(tmp_path, capsys):
    fixture = tmp_path / "fixture_mod.py"
    fixture.write_text(textwrap.dedent("""
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        def _bad_scan():
            def f(table, idxs, upds):
                def body(tbl, x):
                    i, u = x
                    vals = jnp.take(tbl, i, axis=0)
                    tbl = lax.dynamic_update_slice(tbl, u, (i[0], 0))
                    return tbl, vals.sum()
                return lax.scan(body, table, (idxs, upds))
            return f, (jnp.zeros((16, 8)), jnp.zeros((3, 2), jnp.int32),
                       jnp.zeros((3, 1, 8)))

        def _bad_kernel():
            def kern(seed_ref, o_ref):
                pltpu.prng_seed(seed_ref[0], seed_ref[1], seed_ref[2])
                bits = pltpu.prng_random_bits(o_ref.shape)
                o_ref[...] = lax.shift_right_logical(
                    bits, 8).astype(jnp.float32)
            def f(seed):
                return pl.pallas_call(kern, out_shape=jax.ShapeDtypeStruct(
                    (8, 128), jnp.float32))(seed)
            return f, (jnp.zeros(3, jnp.int32),)

        HARPLINT_DRIVERS = {"bad_scan": _bad_scan}
        HARPLINT_KERNELS = {"bad_seed": _bad_kernel}
    """))
    rc = cli.main(["--audit-module", str(fixture), "--json"])
    row = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 1
    assert "HL101" in row["per_rule"] and "HL202" in row["per_rule"]


def test_cli_repo_run_is_clean(capsys):
    """THE tier-1 gate: zero unallowlisted violations at HEAD, all three
    layers, and the machine line passes check_jsonl invariant 6."""
    import check_jsonl

    rc = cli.main(["--json"])
    out = capsys.readouterr().out.strip().splitlines()[-1]
    row = json.loads(out)
    assert rc == 0, row
    assert row["clean"] is True and row["violations"] == 0
    assert row["stale_allowlist"] == 0
    assert check_jsonl._check_lint_row("stdout", 1, row) == []
