"""Health sentinel (harp_tpu/health) — the sixth, derived telemetry spine.

Evidence layers, all on the 8-worker CPU sim:

1. SLO-burn math: multi-window burn rates, the two-floor breach rule,
   severity escalation, latch/hysteresis;
2. THE chaos acceptance pin (ISSUE 14): a seeded-chaos
   ``benchmark_sustained`` run fires SLO-burn AND budget-drift health
   rows whose counts reconcile EXACTLY with the invariant-9 ledger and
   the invariant-11 trace counts — and the full export (trace + health
   + the stamped bench row) passes scripts/check_jsonl.py as one file —
   while the identical healthy control run emits zero findings;
3. skew trigger: fires only after K consecutive over-threshold
   supersteps, carries the ``suggest_rebalance`` plan inline, and that
   plan replays through ``schedule.apply_rebalance`` (the
   elastic-execution handoff shape, pinned);
4. budget drift: warn-mode flightrec violations aggregate (count +
   worst offender per site); raise-mode stays loud-and-unrecorded;
5. zero-cost contract: every detector no-ops with telemetry off, the
   traced serve program is jaxpr-identical with the sentinel armed, and
   the flagship serve budgets (0 compiles / exact dispatch+readback
   totals) hold UNCHANGED with it armed;
6. evidence regression: tolerance verdicts vs a committed incumbent,
   model_invalidated on a magnitude-band breach, and the fail-closed
   ``measure_all --predicted-top`` model gate (refusal + real-repo
   pass).
"""

import io
import json
import os
import sys
import warnings

import numpy as np
import pytest

from harp_tpu import health
from harp_tpu.utils import flightrec, skew, telemetry

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "scripts"))

import check_jsonl  # noqa: E402


# ---------------------------------------------------------------------------
# SLO burn math
# ---------------------------------------------------------------------------

def test_slo_burn_two_floor_rule_and_severity():
    """Burn = bad_frac / budget; a breach needs fast >= 2 AND slow >= 1;
    slow >= PAGE_BURN escalates to page; recovery re-arms the latch."""
    with telemetry.scope(True):
        slo = health.SLOBurn("t", window_s=6.0, subwindows=6,
                             error_budget=0.10)
        # 9 good + 1 bad in one sub-window: fast burn = 0.1/0.1 = 1.0
        # (under the fast floor) -> no breach
        for _ in range(9):
            slo.observe(0.1, "served", latency_ms=1.0)
        slo.observe(0.1, "shed")
        assert slo.burn(0.1) == (pytest.approx(1.0), pytest.approx(1.0))
        assert slo.breaches == 0
        # next sub-window goes 50% bad: fast 5.0, slow ~2.3 -> breach,
        # but below PAGE_BURN -> warn
        for i in range(8):
            slo.observe(1.1, "served" if i % 2 else "failed")
        assert slo.breaches == 1
        row = health.monitor.findings()[-1]
        assert row["detector"] == "slo_burn" and row["severity"] == "warn"
        # an all-bad window pushes the slow burn past PAGE_BURN ->
        # severity escalates on the SAME row (one breach episode)
        for _ in range(30):
            slo.observe(2.1, "failed")
        assert health.monitor.findings()[-1]["severity"] == "page"
        # cumulative counts stay exact on the exported row
        assert row["offered"] == slo.counts["offered"] == 48
        assert row["failed"] == slo.counts["failed"]


def test_slo_burn_latency_objective_counts_slow_requests():
    with telemetry.scope(True):
        slo = health.SLOBurn("t", window_s=6.0, subwindows=6,
                             error_budget=0.5, latency_slo_ms=10.0)
        slo.observe(0.1, "served", latency_ms=5.0)    # good
        slo.observe(0.1, "served", latency_ms=50.0)   # over the SLO: bad
        fast, slow = slo.burn(0.1)
        assert fast == pytest.approx(1.0)  # 0.5 bad frac / 0.5 budget
        assert slo.counts["served"] == 2   # outcome counting unchanged


def test_slo_burn_zero_cost_when_disabled():
    slo = health.SLOBurn("t")
    slo.observe(0.0, "failed")
    slo.observe(0.0, "shed")
    assert slo.counts["offered"] == 0
    assert slo.snapshot(0.0)["fast_burn"] == 0.0
    assert health.monitor.findings() == []


# ---------------------------------------------------------------------------
# THE chaos acceptance pin
# ---------------------------------------------------------------------------

_CHAOS = dict(app="kmeans", n_requests=48, rows_per_request=1,
              burst_admit=8, ladder=(8,), offered_qps=1e5,
              state_shape={"k": 4, "d": 8})


def test_chaos_sustained_fires_and_reconciles(mesh, tmp_path):
    """Seeded chaos (exact dispatch ordinal + a bounded queue at 2x+
    offered load) fires SLO-burn + budget-drift rows that reconcile
    EXACTLY with the invariant-9 ledger and invariant-11 trace counts;
    the whole export passes the checker as one file."""
    from harp_tpu.serve.bench import benchmark_sustained
    from harp_tpu.utils import reqtrace
    from harp_tpu.utils.metrics import benchmark_json

    with telemetry.scope(True):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            res = benchmark_sustained(**_CHAOS, max_queue_rows=16,
                                      max_retries=2, fault_ordinals=(2,),
                                      mesh=mesh)
        # chaos actually ran, deterministically: dispatch event #2 fired
        assert res["faults_injected"] == 1
        assert res["fault_retries"] == 1
        assert res["shed_requests"] > 0

        rows = {r["detector"]: r for r in health.monitor.findings()}
        # (a) SLO burn fired and its cumulative counts ARE the ledger
        slo = rows["slo_burn"]
        assert slo["offered"] == res["offered_requests"]
        assert slo["served"] == res["served_requests"]
        assert slo["shed"] == res["shed_requests"]
        assert slo["failed"] == res["failed_requests"]
        # ... and the invariant-11 trace counts
        assert reqtrace.tracer.counts == {
            "served": slo["served"], "shed": slo["shed"],
            "failed": slo["failed"]}
        # (b) budget drift: exactly the retried window, worst offender
        # names the double staging
        bd = rows["budget_drift"]
        assert bd["violations"] == res["fault_retries"] == 1
        assert "h2d_calls used 2 > budget 1" in bd["worst"]
        assert res["health_budget_drift"] == 1
        # (c) the bench row's health fields summarize the findings
        assert res["health_findings"] == 2
        assert res["health_worst_severity"] == "page"
        assert res["health_breaches"] >= 1
        assert res["health_fast_burn"] >= health.FAST_BURN_MIN

        # (d) one file: trace + health export + the stamped bench row
        # passes EVERY checker invariant (9, 11, 13) together
        p = tmp_path / "chaos_run.jsonl"
        telemetry.export(str(p))
        with open(p, "a") as fh:
            fh.write(benchmark_json("serve_kmeans_sustained", res) + "\n")
    errs = check_jsonl.check_file(str(p), provenance=True)
    assert errs == [], errs


def test_healthy_control_run_emits_zero_findings(mesh):
    """The identical trace with the degradation knobs off: no faults,
    no bounds -> zero findings, zero burns, zero drift."""
    from harp_tpu.serve.bench import benchmark_sustained

    with telemetry.scope(True):
        res = benchmark_sustained(**{**_CHAOS, "offered_qps": 500.0},
                                  mesh=mesh)
        assert res["served_requests"] == res["offered_requests"]
        assert res["health_findings"] == 0
        assert res["health_worst_severity"] is None
        assert res["health_fast_burn"] == 0.0
        assert res["health_breaches"] == 0
        assert res["health_budget_drift"] == 0
        assert health.monitor.findings() == []


# ---------------------------------------------------------------------------
# Skew trigger -> the elastic-execution handoff
# ---------------------------------------------------------------------------

def test_skew_trigger_needs_k_consecutive_and_carries_plan():
    with telemetry.scope(True):
        for i in range(health.TRIGGER_SUPERSTEPS - 1):
            skew.record_execution("p", [10, 2, 2, 2], unit="u")
        assert health.monitor.findings() == []  # K-1 is not enough
        # a balanced superstep resets the consecutive counter
        skew.record_execution("p", [4, 4, 4, 4], unit="u")
        for i in range(health.TRIGGER_SUPERSTEPS - 1):
            skew.record_execution("p", [10, 2, 2, 2], unit="u")
        assert health.monitor.findings() == []
        skew.record_execution("p", [10, 2, 2, 2], unit="u")  # the K-th
        rows = health.monitor.findings()
        assert len(rows) == 1
        r = rows[0]
        assert r["detector"] == "skew_trigger" and r["phase"] == "p"
        assert r["wasted_frac"] == pytest.approx(0.6)
        assert r["consecutive"] == health.TRIGGER_SUPERSTEPS
        plan = r["plan"]
        assert plan["ratio_before"] == pytest.approx(2.5)
        assert plan["ratio_after"] == pytest.approx(1.0)
        # latched: further skewed supersteps do not spam new findings
        skew.record_execution("p", [10, 2, 2, 2], unit="u")
        assert len(health.monitor.findings()) == 1


def test_skew_trigger_plan_replays_through_apply_rebalance(mesh):
    """The acceptance pin for the handoff: the INLINE plan (recorded
    with movable units on the PR-4 skewed-corpus pattern) must be
    exactly what schedule.apply_rebalance accepts — the elastic
    execution PR acts on this payload, so its shape is contract."""
    from harp_tpu import schedule

    with telemetry.scope(True):
        for _ in range(health.TRIGGER_SUPERSTEPS):
            skew.record_partition(
                "files", [10, 1, 0, 1], unit="bytes",
                units=[[("a", 6), ("b", 4)], [("c", 1)], [], [("d", 1)]])
        r = health.monitor.findings()[0]
        assert r["detector"] == "skew_trigger"
        plan = r["plan"]
        assert all("id" in m for m in plan["moves"])
        new = schedule.apply_rebalance([["a", "b"], ["c"], [], ["d"]],
                                       plan)
        assert sorted(map(sorted, new)) == [["a"], ["b"], ["c"], ["d"]]
        # and the row round-trips the invariant-13 plan checks
        stamp = {"backend": "cpu", "date": "2026-08-05", "commit": "x"}
        assert check_jsonl._check_health_row("t", 1, {**r, **stamp}) == []


# ---------------------------------------------------------------------------
# Budget drift
# ---------------------------------------------------------------------------

def test_budget_drift_aggregates_warn_violations():
    with telemetry.scope(True):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            with flightrec.budget(readbacks=0, action="warn", tag="s"):
                flightrec.record_readback(4)
            with flightrec.budget(readbacks=1, h2d_bytes=0,
                                  action="warn", tag="s"):
                flightrec.record_readback(4)
                flightrec.record_readback(4)
                flightrec.record_h2d(1 << 20)
        rows = health.monitor.findings()
        assert len(rows) == 1  # one row per site, violations aggregated
        r = rows[0]
        assert r["detector"] == "budget_drift" and r["tag"] == "s"
        assert r["violations"] == 2
        # worst offender by overspend ratio: the 1 MiB h2d over budget 0
        assert "h2d_bytes" in r["worst"]


def test_budget_drift_raise_mode_stays_loud_not_recorded():
    with telemetry.scope(True):
        with pytest.raises(flightrec.BudgetExceeded):
            with flightrec.budget(readbacks=0, tag="s"):
                flightrec.record_readback(4)
        assert health.monitor.findings() == []


# ---------------------------------------------------------------------------
# Zero-cost contract
# ---------------------------------------------------------------------------

def test_detectors_noop_with_telemetry_off():
    telemetry.enable(False)
    try:
        health.monitor.reset()
        skew.record_execution("p", [10, 0, 0, 0], unit="u")
        health.monitor.observe_budget("t", [("readbacks", 2, 1)])
        health.monitor.observe_skew("p", skew.ledger)
        assert health.monitor.findings() == []
    finally:
        telemetry.enable(False)  # conftest default stays off


def test_serve_program_jaxpr_identical_with_sentinel_armed(mesh, tmp_path):
    """The PR-3 contract: arming the sentinel never touches a traced
    program — the serve engine's jaxpr is bit-identical with telemetry
    off vs on-with-the-sentinel-observing."""
    import jax

    from harp_tpu.serve.engines import make_engine

    rng = np.random.default_rng(0)
    from harp_tpu.serve.engines import ENGINES

    state = ENGINES["kmeans"].synthetic_state(rng, k=4, d=8)

    def trace():
        eng = make_engine("kmeans", state, mesh)
        return str(jax.make_jaxpr(eng.jitted().__wrapped__
                                  if hasattr(eng.jitted(), "__wrapped__")
                                  else eng.jitted())(
            *eng.trace_args(8)))

    telemetry.enable(False)
    off = trace()
    with telemetry.scope(True):
        slo = health.SLOBurn("t")
        slo.observe(0.0, "failed")  # sentinel actively observing
        on = trace()
    assert off == on


def test_flagship_serve_budget_unchanged_with_sentinel_armed(mesh,
                                                             tmp_path):
    """The acceptance pin: with the sentinel armed (it always is on the
    runner) and telemetry ON, the continuous plane still proves EXACT
    totals — one dispatch + one readback per batch, zero steady
    compiles — and a clean run records zero violations and findings."""
    from harp_tpu.serve.engines import ENGINES
    from harp_tpu.serve.server import Server

    rng = np.random.default_rng(7)
    with telemetry.scope(True):
        srv = Server("kmeans",
                     state=ENGINES["kmeans"].synthetic_state(rng, k=4,
                                                             d=8),
                     mesh=mesh, ladder=(1, 8),
                     cache_dir=str(tmp_path / "aot"))
        srv.startup()
        srv.process([srv.engine.synthetic_request(rng, n)
                     for n in (1, 8)])  # warm every rung
        srv.steady.reset()
        srv.steady.limits["h2d_calls"] = 1  # the staging discipline
        runner = srv.make_runner(clock=lambda: 0.0)
        for i in range(8):
            runner.submit(i, srv.engine.synthetic_request(rng, 3),
                          now=0.0)
            runner.step(0.0)
        runner.drain(0.0)
        runner.verify_exact()  # raises on any inexactness
        assert srv.steady.violations == 0
        assert runner.health.counts["served"] == 8
        assert runner.health.breaches == 0
        assert health.monitor.findings() == []
        # the sentinel is ON the stats surface
        assert runner.stats()["health"]["offered"] == 8


# ---------------------------------------------------------------------------
# Evidence regression + the fail-closed model gate
# ---------------------------------------------------------------------------

def _repo_with_incumbent(tmp_path, config, metric, value):
    row = {"config": config, metric: value, "backend": "tpu",
           "date": "2026-08-01", "commit": "abc1234"}
    (tmp_path / "BENCH_local.jsonl").write_text(json.dumps(row) + "\n")
    return str(tmp_path)


def test_grade_bench_row_tolerance_verdicts(tmp_path):
    """rf has deliberately no cost model (ROADMAP), so the verdict is
    the pure incumbent comparison at the +-10% dead band."""
    from harp_tpu.health import grade as HG

    repo = _repo_with_incumbent(tmp_path, "rf", "trees_per_sec", 10.0)
    health.monitor.reset()

    def fresh(v):
        return {"config": "rf", "trees_per_sec": v, "backend": "tpu",
                "date": "2026-08-05", "commit": "def5678"}

    assert HG.grade_bench_row(fresh(8.0), repo)["verdict"] == "regressed"
    assert HG.grade_bench_row(fresh(12.0), repo)["verdict"] == "improved"
    assert HG.grade_bench_row(fresh(10.2), repo)["verdict"] == "confirmed"
    # severity: regressions warn, the rest inform — but the upserted row
    # keeps the worst severity seen
    r = health.monitor.findings()[0]
    assert r["detector"] == "evidence_regression"
    assert r["severity"] == "warn"
    # smoke / CPU / error rows are never graded (CPU-inversion filter)
    assert HG.grade_bench_row({**fresh(1.0), "backend": "cpu"},
                              repo) is None
    assert HG.grade_bench_row({**fresh(1.0), "smoke": True},
                              repo) is None
    health.monitor.reset()


def test_grade_bench_row_magnitude_breach_invalidates_model(tmp_path):
    from harp_tpu.health import grade as HG

    repo = _repo_with_incumbent(tmp_path, "kmeans", "iters_per_sec",
                                381.2)
    health.monitor.reset()
    # a "measured" rate 6 orders of magnitude off the model's prediction
    # is outside MAGNITUDE_TOL: the model no longer describes this
    # hardware -> model_invalidated regardless of the incumbent verdict
    f = HG.grade_bench_row(
        {"config": "kmeans", "iters_per_sec": 1e-3, "n": 1_000_000,
         "d": 300, "k": 100, "backend": "tpu", "date": "2026-08-05",
         "commit": "def5678"}, repo)
    assert f["verdict"] == "model_invalidated"
    assert f["model_factor"] > 50.0
    health.monitor.reset()


def test_model_gate_passes_on_committed_evidence():
    """The real repo's committed evidence grades clean (tier-1 already
    pins perfmodel.grade ok), so the gate ALLOWS pruning and emits a
    confirmed info row that passes invariant 13."""
    from harp_tpu.health import grade as HG

    health.monitor.reset()
    ok, finding = HG.model_gate(ROOT)
    assert ok is True
    assert finding["verdict"] == "confirmed"
    assert finding["failures"] == 0
    stamp = {"backend": "cpu", "date": "2026-08-05", "commit": "x"}
    assert check_jsonl._check_health_row("t", 1,
                                         {**finding, **stamp}) == []
    health.monitor.reset()


def test_predicted_top_refuses_when_model_invalidated(monkeypatch):
    """ROADMAP autotuning item (3), the gate pin: an invalidated model
    must not choose what the next relay window measures — measure_all
    --predicted-top exits 1 BEFORE computing any selection."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "measure_all_gate", os.path.join(ROOT, "scripts",
                                         "measure_all.py"))
    ma = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ma)

    from harp_tpu.health import grade as HG

    monkeypatch.setattr(
        HG, "model_gate",
        lambda repo: (False, {"verdict": "model_invalidated",
                              "failures": 2, "detail": ["x", "y"]}))
    with pytest.raises(SystemExit) as ei:
        ma.predicted_only(3, "v4_32")
    assert "REFUSED" in str(ei.value)
    # ... and through the CLI surface, --dry-run included (the refusal
    # must come before any selection is printed)
    with pytest.raises(SystemExit) as ei:
        ma.main(["--predicted-top", "2", "--dry-run"])
    assert "REFUSED" in str(ei.value)
    # gate open -> the selection machinery runs as before
    monkeypatch.setattr(HG, "model_gate",
                        lambda repo: (True, {"verdict": "confirmed"}))
    only, ranked, _ = ma.predicted_only(2, "v4_32")
    assert only and set(c for c, _ in ranked[:2]) <= set(only)


# ---------------------------------------------------------------------------
# Monitor mechanics + vocab
# ---------------------------------------------------------------------------

def test_monitor_upsert_escalates_severity_and_marks():
    health.monitor.reset()
    mark0 = health.monitor.mark()
    r = health.monitor.upsert("budget_drift", "k", severity="warn")
    r["violations"] = 1
    assert health.monitor.upsert("budget_drift", "k",
                                 severity="info") is r
    assert r["severity"] == "warn"  # never demotes
    health.monitor.upsert("budget_drift", "k", severity="page")
    assert r["severity"] == "page"
    assert [x["_seq"] for x in health.monitor.since(mark0)] == [1]
    assert health.monitor.since(health.monitor.mark()) == []
    with pytest.raises(ValueError):
        health.monitor.upsert("nope", "k")
    with pytest.raises(ValueError):
        health.monitor.upsert("slo_burn", "k", severity="meh")
    health.monitor.reset()


def test_summarize_rows_actionable_rule():
    rows = [{"detector": "slo_burn", "severity": "page"},
            {"detector": "evidence_regression", "severity": "info",
             "verdict": "confirmed"},
            {"detector": "evidence_regression", "severity": "info",
             "verdict": "model_invalidated"}]
    s = health.summarize_rows(rows)
    assert s["findings"] == 3
    assert s["actionable"] == 2  # the page + the invalidation
    assert s["worst_severity"] == "page"
    assert s["by_detector"]["evidence_regression"] == 2


def test_report_grows_health_section(mesh):
    """The live report carries the sentinel's findings (the report
    surface of the sixth spine)."""
    from harp_tpu import report

    with telemetry.scope(True):
        for _ in range(health.TRIGGER_SUPERSTEPS):
            skew.record_execution("p", [10, 2, 2, 2], unit="u")
        row, _ = report.live_report()
        assert row["health"]["findings"] == 1
        text = report.render(row)
        assert "health (sentinel findings)" in text
        assert "skew_trigger" in text
