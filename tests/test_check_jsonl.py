"""scripts/check_jsonl.py — committed measurement files stay parseable and
provenance-stamped (the CPU-inversion guard, tier-1)."""

import json
import os
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "scripts"))

import check_jsonl  # noqa: E402


def test_committed_files_are_clean():
    """THE tier-1 gate: every committed BENCH_local / PROFILE_local /
    FLIP_DECISIONS line parses, and post-grandfather bench rows carry
    backend/date/commit."""
    errors = check_jsonl.check_repo(ROOT)
    assert errors == [], "\n".join(errors)


def test_unparseable_line_is_loud(tmp_path):
    p = tmp_path / "BENCH_local.jsonl"
    p.write_text('{"config": "x", "backend": "cpu"}\n'
                 "{'config': 'dictrepr'}\n")  # the teed dict-repr bug
    errors = check_jsonl.check_file(str(p))
    assert len(errors) == 1 and "unparseable" in errors[0]
    assert ":2:" in errors[0]


def test_new_bench_row_must_carry_provenance(tmp_path):
    rows = [
        {"config": "legacy_row", "iters_per_sec": 1.0},   # grandfathered
        {"config": "new_row", "iters_per_sec": 2.0},      # must be stamped
    ]
    p = tmp_path / "BENCH_local.jsonl"
    p.write_text("".join(json.dumps(r) + "\n" for r in rows))
    errors = check_jsonl.check_file(str(p), grandfathered=1,
                                    provenance=True)
    assert len(errors) == 1
    assert "new_row" in errors[0] and "backend" in errors[0]


def test_stamped_row_passes(tmp_path):
    row = {"config": "ok", "iters_per_sec": 2.0, "backend": "tpu",
           "date": "2026-08-04", "commit": "abc1234"}
    p = tmp_path / "BENCH_local.jsonl"
    p.write_text(json.dumps(row) + "\n")
    assert check_jsonl.check_file(str(p), provenance=True) == []


def test_non_bench_rows_need_only_parse(tmp_path):
    # verb-sweep and metric-headline rows have no "config": parse-only
    rows = [{"verb": "pull_sparse_sweep", "sec": 0.1},
            {"metric": "kmeans_iters_per_sec", "value": 1.0}]
    p = tmp_path / "rows.jsonl"
    p.write_text("".join(json.dumps(r) + "\n" for r in rows))
    assert check_jsonl.check_file(str(p), provenance=True) == []


def test_comm_row_quantized_verb_must_name_wire(tmp_path):
    """PR-2 gate: a CommLedger row for a quantized verb without a valid
    wire_dtype mis-scales every bytes-on-wire claim downstream."""
    rows = [
        {"kind": "comm", "verb": "rotate_quantized", "wire_dtype": "int8",
         "payload_bytes": 64},                               # fine
        {"kind": "comm", "verb": "rotate_quantized",
         "payload_bytes": 64},                               # missing wire
        {"kind": "comm", "verb": "regroup_quantized",
         "wire_dtype": "float16", "payload_bytes": 64},      # bogus wire
    ]
    p = tmp_path / "rows.jsonl"
    p.write_text("".join(json.dumps(r) + "\n" for r in rows))
    errors = check_jsonl.check_file(str(p))
    assert len(errors) == 2
    assert ":2:" in errors[0] and "wire_dtype" in errors[0]
    assert ":3:" in errors[1] and "float16" in errors[1]


def test_comm_row_exact_move_verb_must_not_claim_wire(tmp_path):
    rows = [
        {"kind": "comm", "verb": "rotate", "payload_bytes": 64},  # fine
        {"kind": "comm", "verb": "rotate", "wire_dtype": "int8",
         "payload_bytes": 64},                                    # bogus
        # allreduce legitimately records no wire (exact by default)
        {"kind": "comm", "verb": "allreduce", "payload_bytes": 64},
    ]
    p = tmp_path / "rows.jsonl"
    p.write_text("".join(json.dumps(r) + "\n" for r in rows))
    errors = check_jsonl.check_file(str(p))
    assert len(errors) == 1 and ":2:" in errors[0]
    assert "_quantized twin" in errors[0]


def test_comm_rows_checked_even_in_bench_files(tmp_path):
    """A telemetry export teed into BENCH_local still gets invariant 3."""
    row = {"kind": "comm", "verb": "regroup_quantized",
           "payload_bytes": 64}
    p = tmp_path / "BENCH_local.jsonl"
    p.write_text(json.dumps(row) + "\n")
    errors = check_jsonl.check_file(str(p), provenance=True)
    assert len(errors) == 1 and "wire_dtype" in errors[0]


def test_exported_ledger_rows_satisfy_the_checker(tmp_path):
    """Round-trip: what telemetry.export writes for the quantized and
    exact movement verbs must pass invariant 3 as-is."""
    import jax.numpy as jnp
    import numpy as np

    from harp_tpu.utils import telemetry

    with telemetry.scope(True):
        telemetry.ledger.record("rotate", np.zeros((4, 2), np.float32),
                                axis="workers")
        telemetry.ledger.record("rotate_quantized",
                                np.zeros((4, 2), np.float32),
                                axis="workers", wire_dtype=jnp.int8)
        telemetry.ledger.record("regroup_quantized",
                                np.zeros((4, 2), np.float32),
                                axis="workers", wire_dtype=jnp.bfloat16)
        p = tmp_path / "telemetry.jsonl"
        telemetry.export(str(p))
    assert check_jsonl.check_file(str(p)) == []


def test_flight_row_must_carry_provenance(tmp_path):
    """Invariant 4: a compile/transfer row without backend/date/commit is
    ambiguous evidence — a CPU-sim compile count must never read as relay
    evidence (the same inversion guard as the bench-row check)."""
    stamp = {"backend": "cpu", "date": "2026-08-04", "commit": "abc1234"}
    rows = [
        {"kind": "compile", "count": 1, "dur": 0.1, "total_s": 0.1,
         "span": "epoch", **stamp},                          # fine
        {"kind": "compile", "count": 2, "dur": 0.1, "total_s": 0.2},
        {"kind": "transfer", "op": "h2d", "bytes": 64, "calls": 1},
    ]
    p = tmp_path / "rows.jsonl"
    p.write_text("".join(json.dumps(r) + "\n" for r in rows))
    errors = check_jsonl.check_file(str(p))
    assert len(errors) == 2
    assert ":2:" in errors[0] and "provenance" in errors[0]
    assert ":3:" in errors[1] and "provenance" in errors[1]


def test_flight_row_counters_must_be_nonnegative_numbers(tmp_path):
    stamp = {"backend": "cpu", "date": "2026-08-04", "commit": "abc1234"}
    rows = [
        {"kind": "transfer", "op": "readback", "bytes": -4, "calls": 1,
         **stamp},
        {"kind": "compile", "count": "three", "dur": 0.1, "total_s": 0.1,
         **stamp},
    ]
    p = tmp_path / "rows.jsonl"
    p.write_text("".join(json.dumps(r) + "\n" for r in rows))
    errors = check_jsonl.check_file(str(p))
    assert len(errors) == 2
    assert "bytes=-4" in errors[0]
    assert "count='three'" in errors[1]


def test_compile_rows_must_be_monotone_within_a_file(tmp_path):
    """A cumulative compile counter that DECREASES down the file means two
    runs' exports were interleaved — every "N compiles this run" claim
    downstream would be wrong."""
    stamp = {"backend": "cpu", "date": "2026-08-04", "commit": "abc1234"}
    rows = [
        {"kind": "compile", "count": 1, "dur": 0.2, "total_s": 0.2, **stamp},
        {"kind": "compile", "count": 2, "dur": 0.1, "total_s": 0.3, **stamp},
        {"kind": "compile", "count": 1, "dur": 0.1, "total_s": 0.1, **stamp},
    ]
    p = tmp_path / "rows.jsonl"
    p.write_text("".join(json.dumps(r) + "\n" for r in rows))
    errors = check_jsonl.check_file(str(p))
    assert len(errors) == 2  # count AND total_s both decreased on row 3
    assert all(":3:" in e and "monotone" in e for e in errors)


def test_exported_flight_rows_satisfy_the_checker(tmp_path):
    """Round-trip: what flightrec.export_jsonl writes must pass invariant
    4 as-is (stamped, non-negative, monotone) — even teed into a bench
    file where provenance checking is on."""
    from harp_tpu.utils import flightrec, telemetry

    with telemetry.scope(True):
        flightrec.compile_watch.on_compile(0.25)
        flightrec.compile_watch.on_compile(0.05)
        flightrec.record_h2d(1024)
        flightrec.record_readback(4)
        p = tmp_path / "BENCH_local.jsonl"
        telemetry.export(str(p))
    assert check_jsonl.check_file(str(p), provenance=True) == []


def test_skew_row_invariants(tmp_path):
    """Invariant 5: skew rows carry the provenance stamp, per-worker
    counts sum to the global total, padding fraction lies in [0, 1]."""
    stamp = {"backend": "cpu", "date": "2026-08-04", "commit": "abc1234"}
    rows = [
        {"kind": "skew", "phase": "ok", "work": [3, 1], "total": 4,
         "padding_frac": 0.25, **stamp},                       # fine
        {"kind": "skew", "phase": "p", "work": [2, 2], "total": 5,
         **stamp},                                             # bad sum
        {"kind": "skew", "phase": "p", "work": [2, 2], "total": 4,
         "padding_frac": 1.5, **stamp},                        # bad pad
        {"kind": "skew", "phase": "p", "work": [1, 1], "total": 2},
        {"kind": "skew", "phase": "p", "work": "oops", "total": 1,
         **stamp},                                             # bad work
        {"kind": "skew", "phase": "p", "work": [-1, 2], "total": 1,
         **stamp},                                             # negative
    ]
    p = tmp_path / "rows.jsonl"
    p.write_text("".join(json.dumps(r) + "\n" for r in rows))
    errors = check_jsonl.check_file(str(p))
    assert len(errors) == 5
    assert ":2:" in errors[0] and "sum" in errors[0]
    assert ":3:" in errors[1] and "padding_frac" in errors[1]
    assert ":4:" in errors[2] and "provenance" in errors[2]
    assert ":5:" in errors[3] and "work" in errors[3]
    assert ":6:" in errors[4] and "negative" in errors[4]


def test_exported_skew_rows_satisfy_the_checker(tmp_path):
    """Round-trip: what skew.export_jsonl writes (via telemetry.export)
    must pass invariant 5 as-is — even teed into a bench file."""
    from harp_tpu.utils import skew, telemetry

    with telemetry.scope(True):
        skew.record_execution("lda.epochs", [5, 1, 1, 1], unit="tokens",
                              wall_s=0.25)
        skew.record_partition("lda.partition", [5, 1, 1, 1],
                              unit="tokens", padded_total=16)
        p = tmp_path / "BENCH_local.jsonl"
        telemetry.export(str(p))
    assert check_jsonl.check_file(str(p), provenance=True) == []


def test_cli_exit_codes(tmp_path):
    (tmp_path / "BENCH_local.jsonl").write_text("not json\n")
    assert check_jsonl.main(["--repo", str(tmp_path)]) == 1
    (tmp_path / "BENCH_local.jsonl").write_text("")
    assert check_jsonl.main(["--repo", str(tmp_path)]) == 0


def test_benchmark_json_rows_satisfy_the_checker(tmp_path):
    """The stamp the checker demands is exactly what benchmark_json
    emits — the two can never drift apart."""
    from harp_tpu.utils.metrics import benchmark_json

    p = tmp_path / "BENCH_local.jsonl"
    p.write_text(benchmark_json("fresh", {"iters_per_sec": 1.0}) + "\n")
    assert check_jsonl.check_file(str(p), provenance=True) == []


def test_lint_row_invariants(tmp_path):
    """Invariant 6: lint rows must be stamped, use registered rule ids,
    and carry non-negative integer counts."""
    rows = [
        # missing provenance entirely
        {"kind": "lint", "violations": 0, "per_rule": {}},
        # unregistered rule id in per_rule
        {"kind": "lint", "backend": "cpu", "date": "2026-08-04",
         "commit": "abc", "per_rule": {"HL999": 1}},
        # negative per-file count
        {"kind": "lint", "backend": "cpu", "date": "2026-08-04",
         "commit": "abc", "per_file": {"a.py": -1}},
    ]
    p = tmp_path / "BENCH_local.jsonl"
    p.write_text("".join(json.dumps(r) + "\n" for r in rows))
    errors = check_jsonl.check_file(str(p))
    assert len(errors) == 3
    assert ":1:" in errors[0] and "provenance" in errors[0]
    assert ":2:" in errors[1] and "HL999" in errors[1]
    assert ":3:" in errors[2] and "negative" in errors[2]


def test_lint_row_accepts_thread_rules_and_rejects_forgeries(tmp_path):
    """Invariant 6, Layer-5 extension (PR 20): the HL4xx thread rules
    are registered vocabulary — a row counting them passes, a forged
    neighbor id fails."""
    stamp = {"backend": "cpu", "date": "2026-08-06", "commit": "abc1234"}
    good = {"kind": "lint", "violations": 5, **stamp,
            "per_rule": {"HL401": 1, "HL402": 1, "HL403": 1,
                         "HL404": 1, "HL405": 1}}
    bad = {"kind": "lint", "violations": 1, **stamp,
           "per_rule": {"HL499": 1}}
    p = tmp_path / "BENCH_local.jsonl"
    p.write_text(json.dumps(good) + "\n" + json.dumps(bad) + "\n")
    errors = check_jsonl.check_file(str(p))
    assert len(errors) == 1
    assert ":2:" in errors[0] and "HL499" in errors[0]


def _sheet(**over):
    """A valid kmeans.fit byte sheet (the hand-computed Layer-4 shape),
    with per-test forgeries spliced in."""
    coll = {"site": "kmeans.py:324", "primitive": "psum",
            "verb": "allreduce", "axis": "workers",
            "wire_dtype": "float32", "per_shard_bytes": 1060,
            "calls_per_trace": 3, "amplification": 2, "dynamic": False,
            "path": "/shard_map/scan"}
    coll.update({k: v for k, v in over.items() if k in coll})
    sheet = {"collectives": [coll], "bytes_per_trace": 1060,
             "amplified_bytes": 2120, "donated_args": [],
             "donated_avals": []}
    sheet.update({k: v for k, v in over.items() if k in sheet})
    return sheet


def test_lint_byte_sheet_invariants(tmp_path):
    """Invariant 6, CommGraph extension: byte sheets must name
    registered programs/primitives/verbs and non-negative bytes —
    forged rows must each trip exactly their own violation."""
    stamp = {"backend": "cpu", "date": "2026-08-04", "commit": "abc1234"}
    base = {"kind": "lint", "violations": 0, **stamp}
    rows = [
        {**base, "byte_sheets": {"kmeans.fit": _sheet()}},       # fine
        {**base, "byte_sheets": {"notaprogram": _sheet()}},
        {**base, "byte_sheets": {
            "kmeans.fit": _sheet(primitive="send_recv")}},
        {**base, "byte_sheets": {
            "kmeans.fit": _sheet(verb="gossip")}},
        {**base, "byte_sheets": {
            "kmeans.fit": _sheet(bytes_per_trace=-5)}},
        {**base, "byte_sheets": {
            "kmeans.fit": _sheet(amplification=-1)}},
    ]
    p = tmp_path / "rows.jsonl"
    p.write_text("".join(json.dumps(r) + "\n" for r in rows))
    errors = check_jsonl.check_file(str(p))
    assert len(errors) == 5, errors
    assert ":2:" in errors[0] and "notaprogram" in errors[0]
    assert ":3:" in errors[1] and "send_recv" in errors[1]
    assert ":4:" in errors[2] and "gossip" in errors[2]
    assert ":5:" in errors[3] and "bytes_per_trace" in errors[3]
    assert ":6:" in errors[4] and "amplification" in errors[4]


def test_serve_row_invariants(tmp_path):
    """Invariant 7: serve rows must be stamped, percentiles monotone,
    qps positive, and steady_compiles exactly 0 — a serving-throughput
    claim that silently recompiled per batch is not serving evidence."""
    stamp = {"backend": "cpu", "date": "2026-08-04", "commit": "abc1234"}
    rows = [
        {"kind": "serve", "app": "kmeans", "qps": 100.0, "p50_ms": 1.0,
         "p95_ms": 2.0, "p99_ms": 3.0, "steady_compiles": 0,
         **stamp},                                          # fine
        {"kind": "serve", "qps": 100.0, "p50_ms": 1.0, "p95_ms": 2.0,
         "p99_ms": 3.0, "steady_compiles": 0},              # unstamped
        {"kind": "serve", "qps": 100.0, "p50_ms": 2.5, "p95_ms": 2.0,
         "p99_ms": 3.0, "steady_compiles": 0, **stamp},     # crossed
        {"kind": "serve", "qps": 0.0, "p50_ms": 1.0, "p95_ms": 2.0,
         "p99_ms": 3.0, "steady_compiles": 0, **stamp},     # qps <= 0
        {"kind": "serve", "qps": 100.0, "p50_ms": 1.0, "p95_ms": 2.0,
         "p99_ms": 3.0, "steady_compiles": 2, **stamp},     # compiled!
        {"kind": "serve", "qps": 100.0, "p50_ms": -1.0, "p95_ms": 2.0,
         "p99_ms": 3.0, "steady_compiles": 0, **stamp},     # negative
    ]
    p = tmp_path / "rows.jsonl"
    p.write_text("".join(json.dumps(r) + "\n" for r in rows))
    errors = check_jsonl.check_file(str(p))
    assert len(errors) == 5
    assert ":2:" in errors[0] and "provenance" in errors[0]
    assert ":3:" in errors[1] and "monotone" in errors[1]
    assert ":4:" in errors[2] and "qps" in errors[2]
    assert ":5:" in errors[3] and "steady_compiles" in errors[3]
    assert ":6:" in errors[4] and "p50_ms" in errors[4]


def test_sustained_serve_row_invariants(tmp_path):
    """Invariant 7, sustained extension: continuous-batching rows need
    offered_qps >= achieved_qps > 0 and non-negative queue-depth
    percentiles — a sustained claim without queue evidence cannot grade
    the padding-vs-latency knobs."""
    stamp = {"backend": "cpu", "date": "2026-08-04", "commit": "abc1234"}
    base = {"kind": "serve", "app": "kmeans", "qps": 100.0,
            "p50_ms": 1.0, "p95_ms": 2.0, "p99_ms": 3.0,
            "steady_compiles": 0, **stamp}
    qd = {"qdepth_p50": 3.0, "qdepth_p95": 9.0, "qdepth_p99": 12.0}
    rows = [
        {**base, "mode": "sustained", "offered_qps": 200.0,
         "achieved_qps": 100.0, **qd},                       # fine
        {**base, "offered_qps": 90.0, "achieved_qps": 100.0,
         **qd},                                              # ach > off
        {**base, "mode": "sustained", "offered_qps": 200.0,
         "achieved_qps": 0.0, **qd},                         # ach <= 0
        {**base, "offered_qps": 200.0, "achieved_qps": 100.0,
         "qdepth_p50": 3.0, "qdepth_p95": 9.0},              # missing p99
        {**base, "offered_qps": 200.0, "achieved_qps": 100.0,
         **{**qd, "qdepth_p95": -1.0}},                      # negative
        {**base, "mode": "sustained", **qd},                 # no qps pair
    ]
    p = tmp_path / "rows.jsonl"
    p.write_text("".join(json.dumps(r) + "\n" for r in rows))
    errors = check_jsonl.check_file(str(p))
    assert len(errors) == 5
    assert ":2:" in errors[0] and "offered_qps >= achieved_qps" in errors[0]
    assert ":3:" in errors[1] and "achieved" in errors[1]
    assert ":4:" in errors[2] and "qdepth_p99" in errors[2]
    assert ":5:" in errors[3] and "qdepth_p95" in errors[3]
    assert ":6:" in errors[4] and "offered" in errors[4]


def test_degraded_serve_row_invariants(tmp_path):
    """Invariant 9: fault-plane serve rows (PR 10) must balance their
    books — fractions in [0, 1], fault_retries a non-negative integer,
    and served + shed + failed == offered.  A row where requests vanish
    is not degradation evidence."""
    stamp = {"backend": "cpu", "date": "2026-08-04", "commit": "abc1234"}
    base = {"kind": "serve", "app": "kmeans", "qps": 100.0,
            "p50_ms": 1.0, "p95_ms": 2.0, "p99_ms": 3.0,
            "steady_compiles": 0, **stamp}
    deg = {"offered_requests": 100, "served_requests": 95,
           "shed_requests": 4, "failed_requests": 1,
           "shed_frac": 0.04, "deadline_miss_frac": 0.02,
           "fault_retries": 3}
    rows = [
        {**base, **deg},                                     # fine
        {**base, **deg, "shed_frac": 1.5},                   # frac > 1
        {**base, **deg, "deadline_miss_frac": -0.1},         # frac < 0
        {**base, **deg, "fault_retries": -2},                # negative
        {**base, **deg, "served_requests": 90},              # unbalanced
        {**base, "shed_frac": 0.0},                          # partial row
        {**base, **deg, "fault_retries": 2.5},               # non-integer
    ]
    p = tmp_path / "rows.jsonl"
    p.write_text("".join(json.dumps(r) + "\n" for r in rows))
    errors = check_jsonl.check_file(str(p))
    assert [e.split(":")[1] for e in errors] == ["2", "3", "4", "5",
                                                 "6", "6", "6", "6",
                                                 "6", "6", "7"]
    assert "shed_frac" in errors[0] and "[0, 1]" in errors[0]
    assert "deadline_miss_frac" in errors[1]
    assert "fault_retries" in errors[2]
    assert "exactly one of the three" in errors[3]
    # a partial degraded row is missing EVERY other book-keeping field
    assert sum("must" in e for e in errors[4:10]) == 6
    assert "fault_retries=2.5" in errors[10]


def test_sustained_bench_row_satisfies_the_checker(tmp_path, mesh):
    """Round-trip: benchmark_sustained through benchmark_json must pass
    the extended invariant 7 as-is in a bench file."""
    from harp_tpu.serve.bench import benchmark_sustained
    from harp_tpu.utils.metrics import benchmark_json

    res = benchmark_sustained(app="kmeans", n_requests=24,
                              rows_per_request=1, burst_admit=4,
                              ladder=(1, 8), offered_qps=2000.0,
                              state_shape={"k": 4, "d": 8})
    assert res["offered_qps"] >= res["achieved_qps"] > 0
    p = tmp_path / "BENCH_local.jsonl"
    p.write_text(benchmark_json("serve_kmeans_sustained", res) + "\n")
    assert check_jsonl.check_file(str(p), provenance=True) == []


def test_serve_bench_row_satisfies_the_checker(tmp_path, mesh):
    """Round-trip: what serve.bench emits through benchmark_json must
    pass invariant 7 as-is — even teed into a bench file."""
    from harp_tpu.serve.bench import benchmark
    from harp_tpu.utils.metrics import benchmark_json

    res = benchmark(app="kmeans", n_requests=12, rows_per_request=1,
                    burst=4, ladder=(1, 8),
                    state_shape={"k": 4, "d": 8})
    p = tmp_path / "BENCH_local.jsonl"
    p.write_text(benchmark_json("serve_kmeans", res) + "\n")
    assert check_jsonl.check_file(str(p), provenance=True) == []


def test_lint_cli_row_satisfies_the_checker(tmp_path, capsys):
    """Round-trip: the line `python -m harp_tpu lint --json` prints must
    pass invariant 6 as-is — even teed into a bench file."""
    from harp_tpu.analysis import cli as lint_cli

    lint_cli.main(["--json", "--layer", "ast"])
    line = capsys.readouterr().out.strip().splitlines()[-1]
    p = tmp_path / "BENCH_local.jsonl"
    p.write_text(line + "\n")
    assert check_jsonl.check_file(str(p), provenance=True) == []


def test_ingest_row_invariants(tmp_path):
    """Invariant 8: ingest rows must be stamped, overlap_efficiency in
    [0, 1], and host/point rates positive — a non-positive rate means
    the instrumented epoch loop never ran."""
    stamp = {"backend": "cpu", "date": "2026-08-04", "commit": "abc1234"}
    base = {"kind": "ingest", "config": "kmeans_ingest_ab_smoke",
            "overlap_efficiency": 0.97, "host_gb_per_sec": 4.2,
            "points_per_sec": 2.5e6}
    rows = [
        {**base, **stamp},                                   # fine
        base,                                                # unstamped
        {**base, "overlap_efficiency": 1.2, **stamp},        # oe > 1
        {**base, "host_gb_per_sec": 0.0, **stamp},           # rate <= 0
        {**base, "points_per_sec": -5.0, **stamp},           # negative
        {**base, "overlap_efficiency": None, **stamp},       # missing
    ]
    p = tmp_path / "rows.jsonl"
    p.write_text("".join(json.dumps(r) + "\n" for r in rows))
    errors = check_jsonl.check_file(str(p))
    assert len(errors) == 5
    assert ":2:" in errors[0] and "provenance" in errors[0]
    assert ":3:" in errors[1] and "overlap_efficiency" in errors[1]
    assert ":4:" in errors[2] and "host_gb_per_sec" in errors[2]
    assert ":5:" in errors[3] and "points_per_sec" in errors[3]
    assert ":6:" in errors[4] and "overlap_efficiency" in errors[4]


def test_ingest_bench_row_satisfies_the_checker(tmp_path, mesh):
    """Round-trip: benchmark_ingest through benchmark_json must pass
    invariant 8 as-is — even teed into a bench file."""
    import numpy as np

    from harp_tpu.models.kmeans_stream import benchmark_ingest
    from harp_tpu.utils.metrics import benchmark_json

    rng = np.random.default_rng(8)
    pts = rng.normal(size=(2048, 8)).astype(np.float16)
    f = tmp_path / "pts.npy"
    np.save(f, pts)
    res = benchmark_ingest(np.load(f, mmap_mode="r"), k=4, iters=2,
                           chunk_points=512, mesh=mesh,
                           disk_bytes=f.stat().st_size)
    p = tmp_path / "BENCH_local.jsonl"
    p.write_text(benchmark_json("kmeans_ingest", res) + "\n")
    assert check_jsonl.check_file(str(p), provenance=True) == []


# -- invariant 10: plan rows (PR 11) ----------------------------------------

def _plan_row(**over):
    """A minimal valid plan row; forge one field per test below."""
    site = {"site": "kmeans.py:346", "primitive": "psum",
            "verb": "allreduce", "schedule": "keep",
            "sheet_bytes": 2120, "predicted_bytes": 2120,
            "cost_s": 1e-7, "alternatives": {}, "candidates": {},
            "flip_candidate": None}
    row = {"kind": "plan", "config": "plan", "program": "kmeans.fit",
           "topology": "sim_ring_8", "rates_source": "declared",
           "sites": [site], "predicted_bytes_total": 2120,
           "flip_candidates": [], "backend": "cpu",
           "date": "2026-08-04", "commit": "abc1234"}
    row.update(over)
    return row


def _plan_errs(row):
    return check_jsonl._check_plan_row("t", 1, row)


def test_plan_row_valid_round_trip(tmp_path):
    p = tmp_path / "rows.jsonl"
    p.write_text(json.dumps(_plan_row()) + "\n")
    assert check_jsonl.check_file(str(p)) == []


def test_plan_row_requires_provenance():
    row = _plan_row()
    del row["backend"]
    assert any("provenance" in e for e in _plan_errs(row))


def test_plan_row_rejects_unknown_program_and_topology():
    assert any("unregistered program" in e
               for e in _plan_errs(_plan_row(program="made.up")))
    assert any("unknown topology" in e
               for e in _plan_errs(_plan_row(topology="v9000")))


def test_plan_row_rejects_unknown_and_non_keep_schedules():
    row = _plan_row()
    row["sites"][0]["schedule"] = "teleport"
    assert any("unknown schedule" in e for e in _plan_errs(row))
    # a non-"keep" CHOICE is a bypassed flip gate, even with coherent
    # bytes — the planner fails closed by contract
    row = _plan_row()
    row["sites"][0]["schedule"] = "wire_int8"
    row["sites"][0]["predicted_bytes"] = 530
    assert any("fails closed" in e for e in _plan_errs(row))


def test_plan_row_predicted_bytes_must_equal_sheet_scaling():
    # drifted keep prediction: the plan prices a program we do not run
    row = _plan_row()
    row["sites"][0]["predicted_bytes"] = 2121
    errs = _plan_errs(row)
    assert any("must equal the frozen scaling" in e for e in errs)
    # negative / non-int bytes are refused before the equality check
    row = _plan_row()
    row["sites"][0]["sheet_bytes"] = -5
    assert any("non-negative integer" in e for e in _plan_errs(row))


def test_plan_cli_rows_satisfy_the_checker(tmp_path, capsys, mesh):
    """Round-trip: python -m harp_tpu plan --json rows pass invariant
    10 as-is — even teed into a committed file."""
    from harp_tpu.plan import cli

    rc = cli.main(["--program", "mfsgd.epoch", "--json"])
    assert rc == 0
    line = capsys.readouterr().out.strip().splitlines()[-1]
    p = tmp_path / "rows.jsonl"
    p.write_text(line + "\n")
    assert check_jsonl.check_file(str(p)) == []


# ---------------------------------------------------------------------------
# Invariant 11: trace rows (PR 12)
# ---------------------------------------------------------------------------

_TSTAMP = {"backend": "cpu", "date": "2026-08-05", "commit": "abc1234"}


def _trace_rows():
    """A minimal complete 2-request timeline (1 served, 1 shed)."""
    return [
        {"kind": "trace", "ev": "event", "req": 1, "name": "arrival",
         "ts": 0.001, **_TSTAMP},
        {"kind": "trace", "ev": "event", "req": 2, "name": "arrival",
         "ts": 0.002, **_TSTAMP},
        {"kind": "trace", "ev": "event", "req": 2, "name": "shed",
         "ts": 0.002, "reason": "queue_full", **_TSTAMP},
        {"kind": "trace", "ev": "request", "req": 2, "ts": 0.002,
         "t0": 0.002, "outcome": "shed", "n_events": 2, **_TSTAMP},
        {"kind": "trace", "ev": "batch", "ts": 0.004, "seq": 0,
         "t0": 0.003, "rung": 8, "rows": 3, "padding_frac": 0.625,
         "members": [[1, 0, 3]], "events": [{"name": "form", "ts": 0.003}],
         **_TSTAMP},
        {"kind": "trace", "ev": "request", "req": 1, "ts": 0.004,
         "t0": 0.001, "outcome": "served", "n_events": 3, **_TSTAMP},
    ]


def _trace_errs(tmp_path, rows):
    p = tmp_path / "rows.jsonl"
    p.write_text("".join(json.dumps(r) + "\n" for r in rows))
    return check_jsonl.check_file(str(p))


def test_trace_rows_valid_round_trip(tmp_path):
    assert _trace_errs(tmp_path, _trace_rows()) == []


def test_trace_row_requires_provenance_and_known_shape(tmp_path):
    rows = _trace_rows()
    rows[0] = {k: v for k, v in rows[0].items() if k != "backend"}
    errs = _trace_errs(tmp_path, rows)
    assert any("missing provenance" in e and ":1:" in e for e in errs)
    rows = _trace_rows()
    rows[0]["ev"] = "wormhole"
    assert any("ev='wormhole'" in e for e in _trace_errs(tmp_path, rows))


def test_trace_rows_must_be_monotone(tmp_path):
    rows = _trace_rows()
    rows[2]["ts"] = 0.0005  # earlier than row 1's 0.001
    errs = _trace_errs(tmp_path, rows)
    assert any("decreased" in e and "monotone" in e for e in errs)
    rows = _trace_rows()
    rows[1]["ts"] = "later"
    assert any("non-negative number" in e
               for e in _trace_errs(tmp_path, rows))


def test_trace_request_spans_must_terminate(tmp_path):
    # drop request 1's terminal row: its events now dangle
    rows = [r for r in _trace_rows()
            if not (r["ev"] == "request" and r["req"] == 1)]
    errs = _trace_errs(tmp_path, rows)
    assert any("no terminated outcome row" in e and "[1]" in e
               for e in errs)
    # an unknown outcome is refused at the row
    rows = _trace_rows()
    rows[-1]["outcome"] = "vanished"
    assert any("outcome='vanished'" in e
               for e in _trace_errs(tmp_path, rows))


def test_trace_counts_reconcile_with_degraded_ledger(tmp_path):
    serve = {"kind": "serve", "app": "kmeans", "qps": 100.0,
             "p50_ms": 1.0, "p95_ms": 2.0, "p99_ms": 3.0,
             "steady_compiles": 0, "offered_requests": 2,
             "served_requests": 1, "shed_requests": 1,
             "failed_requests": 0, "shed_frac": 0.5,
             "deadline_miss_frac": 0.0, "fault_retries": 0, **_TSTAMP}
    assert _trace_errs(tmp_path, [serve] + _trace_rows()) == []
    # a ledger claiming different outcome totals must fail the file
    bad = dict(serve, served_requests=2, shed_requests=0)
    errs = _trace_errs(tmp_path, [bad] + _trace_rows())
    assert any("do not reconcile" in e for e in errs)


def test_trace_outcome_vocabulary_in_sync():
    """check_jsonl freezes the trace vocabularies standalone; drift
    from the live reqtrace module fails here."""
    from harp_tpu.utils import reqtrace

    assert tuple(reqtrace.OUTCOMES) == check_jsonl.KNOWN_TRACE_OUTCOMES


def test_exported_trace_rows_satisfy_the_checker(tmp_path, mesh):
    """Round-trip: a real continuous-plane run through
    telemetry.export passes invariant 11 as-is."""
    import numpy as np

    from harp_tpu.serve.engines import ENGINES
    from harp_tpu.serve.server import Server
    from harp_tpu.utils import telemetry

    with telemetry.scope(True):
        rng = np.random.default_rng(3)
        srv = Server("kmeans",
                     state=ENGINES["kmeans"].synthetic_state(rng, k=4, d=8),
                     mesh=mesh, ladder=(1, 8),
                     cache_dir=str(tmp_path / "aot"))
        srv.startup()
        r = srv.make_runner(max_queue_rows=4)
        r.submit("A", {"id": "A", "x": rng.normal(size=(3, 8)).tolist()},
                 now=0.001)
        r.submit("B", {"id": "B", "x": rng.normal(size=(3, 8)).tolist()},
                 now=0.002)
        r.step(0.003)
        r.step(0.004)
        p = tmp_path / "run.jsonl"
        telemetry.export(str(p))
    assert check_jsonl.check_file(str(p)) == []
    trace = [json.loads(ln) for ln in p.read_text().splitlines()
             if json.loads(ln).get("kind") == "trace"]
    assert sum(r.get("ev") == "request" for r in trace) == 2


def test_golden_trace_fixture_is_clean_and_loads():
    """The committed 2-request golden trace (tests/data) passes the
    checker — the fixture the trace CLI smoke drives."""
    p = os.path.join(os.path.dirname(__file__), "data",
                     "golden_trace.jsonl")
    assert check_jsonl.check_file(p) == []
    from harp_tpu.utils import reqtrace, telemetry

    rows = telemetry.load_rows(p)["trace"]
    s = reqtrace.summarize_rows(rows)
    assert (s["requests"], s["served"], s["shed"], s["failed"]) == \
        (2, 1, 1, 0)
    assert s["unterminated"] == []


# -- invariant 12: model rows (PR 13) ---------------------------------------

def _model_row(**over):
    """A minimal valid model row; forge one field per test below."""
    row = {"kind": "model", "program": "kmeans.fit", "config": None,
           "configs": ["kmeans", "kmeans_int8"],
           "topology": "v4_32", "rates_source": "declared",
           "metric": "program_runs_per_sec",
           "predicted_s": 0.0400001,
           "predicted_rate": 25.0,
           "bound": "overhead",
           "terms": {"compute_s": 0.0, "memory_s": 0.0,
                     "wire_s": 1e-7, "overhead_s": 0.04},
           "backend": "cpu", "date": "2026-08-05", "commit": "abc1234"}
    row.update(over)
    return row


def _model_errs(row):
    return check_jsonl._check_model_row("t", 1, row)


def test_model_row_valid_round_trip(tmp_path):
    p = tmp_path / "rows.jsonl"
    p.write_text(json.dumps(_model_row()) + "\n")
    assert check_jsonl.check_file(str(p)) == []


def test_model_row_requires_provenance():
    row = _model_row()
    del row["commit"]
    assert any("provenance" in e for e in _model_errs(row))


def test_model_row_needs_a_subject():
    # a prediction about nothing prices nothing
    row = _model_row(program=None, config=None, configs=[])
    assert any("neither a program nor a config" in e
               for e in _model_errs(row))


def test_model_row_rejects_unknown_program_and_config():
    assert any("unregistered program" in e
               for e in _model_errs(_model_row(program="made.up")))
    assert any("not in the sprint surface" in e
               for e in _model_errs(_model_row(config="warp_drive")))
    assert any("not in the sprint surface" in e
               for e in _model_errs(_model_row(configs=["kmeans", "nope"])))


def test_model_row_rejects_bad_vocabularies():
    assert any("rates_source" in e
               for e in _model_errs(_model_row(rates_source="vibes")))
    assert any("bound" in e
               for e in _model_errs(_model_row(bound="luck")))


def test_model_row_predicted_seconds_must_be_positive():
    for bad in (0, -1.0, None, "fast"):
        assert any("predicted_s" in e
                   for e in _model_errs(_model_row(predicted_s=bad))), bad


def test_model_row_terms_must_sum_to_total():
    row = _model_row(predicted_s=0.9)  # terms sum to 0.0400001
    assert any("must sum to the total" in e for e in _model_errs(row))
    # a missing or negative term is equally loud
    row = _model_row()
    del row["terms"]["wire_s"]
    assert any("terms" in e for e in _model_errs(row))
    row = _model_row()
    row["terms"]["wire_s"] = -1e-9
    assert any("terms" in e for e in _model_errs(row))


def test_model_row_bound_must_name_the_largest_term():
    row = _model_row(bound="compute")  # overhead dominates
    assert any("largest term" in e for e in _model_errs(row))


def test_model_vocabularies_in_sync_with_perfmodel():
    """The frozen invariant-12 vocabularies mirror harp_tpu.perfmodel
    (this file stays standalone; drift fails here, tier-1)."""
    from harp_tpu import perfmodel

    assert tuple(perfmodel.BOUNDS) == check_jsonl.KNOWN_MODEL_BOUNDS
    assert tuple(perfmodel.RATES_SOURCES) == \
        check_jsonl.KNOWN_MODEL_RATES_SOURCES


# -- invariant 13: health rows (PR 14) --------------------------------------

_HSTAMP = {"backend": "cpu", "date": "2026-08-05", "commit": "abc1234"}


def _health_row(**over):
    """A minimal valid slo_burn health row; forge one field per test."""
    row = {"kind": "health", "detector": "slo_burn", "severity": "warn",
           "tag": "serve.kmeans", "offered": 10, "served": 8, "shed": 2,
           "failed": 0, "fast_burn": 4.0, "slow_burn": 2.0,
           "breaches": 1, **_HSTAMP}
    row.update(over)
    return row


def _health_errs(row):
    return check_jsonl._check_health_row("t", 1, row)


def _skew_trigger_row(**plan_over):
    plan = {"phase": "p", "unit": "tokens",
            "moves": [{"id": "f1", "from": 0, "to": 2, "work": 12.0}],
            "ratio_before": 1.8, "ratio_after": 1.05,
            "work_after": [10.0, 10.0, 11.0, 9.0]}
    plan.update(plan_over)
    return _health_row(detector="skew_trigger", phase="p",
                       wasted_frac=0.42, supersteps=3, consecutive=3,
                       plan=plan)


def test_health_row_valid_round_trip(tmp_path):
    rows = [_health_row(), _skew_trigger_row(),
            _health_row(detector="budget_drift", violations=2,
                        worst="h2d_calls used 2 > budget 1"),
            _health_row(detector="evidence_regression", severity="info",
                        config="kmeans", verdict="confirmed",
                        measured=380.9, incumbent=381.2)]
    p = tmp_path / "rows.jsonl"
    p.write_text("".join(json.dumps(r) + "\n" for r in rows))
    assert check_jsonl.check_file(str(p), provenance=True) == []


def test_health_row_requires_provenance_and_vocabularies():
    row = _health_row()
    del row["backend"]
    assert any("provenance" in e for e in _health_errs(row))
    assert any("detector='gut_feeling'" in e
               for e in _health_errs(_health_row(detector="gut_feeling")))
    assert any("severity='mild'" in e
               for e in _health_errs(_health_row(severity="mild")))
    assert any("verdict='vibes'" in e
               for e in _health_errs(_health_row(verdict="vibes")))


def test_health_row_counts_and_ratios_nonnegative():
    assert any("shed=-1" in e
               for e in _health_errs(_health_row(shed=-1)))
    assert any("breaches=1.5" in e
               for e in _health_errs(_health_row(breaches=1.5)))
    assert any("fast_burn" in e
               for e in _health_errs(_health_row(fast_burn=-0.1)))
    assert any("wasted_frac" in e
               for e in _health_errs(_health_row(wasted_frac="lots")))


def test_evidence_regression_row_requires_verdict():
    row = _health_row(detector="evidence_regression", config="kmeans")
    assert any("verdict=None" in e for e in _health_errs(row))
    row["verdict"] = "model_invalidated"
    assert _health_errs(row) == []


def test_skew_trigger_row_requires_replayable_plan():
    assert _health_errs(_skew_trigger_row()) == []
    # no plan at all: the elastic hook has no payload
    row = _skew_trigger_row()
    del row["plan"]
    assert any("suggest_rebalance object" in e for e in _health_errs(row))
    # forged plan internals each trip their own violation
    assert any("worker index" in e for e in _health_errs(
        _skew_trigger_row(moves=[{"id": "f1", "from": -1, "to": 2,
                                  "work": 1.0}])))
    assert any("work=None" in e for e in _health_errs(
        _skew_trigger_row(moves=[{"id": "f1", "from": 0, "to": 2,
                                  "work": None}])))
    assert any("moves='nope'" in e
               for e in _health_errs(_skew_trigger_row(moves="nope")))
    assert any("ratio_after" in e for e in _health_errs(
        _skew_trigger_row(ratio_after=-2.0)))


def test_health_vocabularies_in_sync_with_health_module():
    """check_jsonl freezes the health vocabularies standalone; drift
    from the live harp_tpu.health module fails here (tier-1)."""
    from harp_tpu import health

    assert tuple(health.DETECTORS) == check_jsonl.KNOWN_HEALTH_DETECTORS
    assert tuple(health.SEVERITIES) == check_jsonl.KNOWN_HEALTH_SEVERITIES
    assert tuple(health.VERDICTS) == check_jsonl.KNOWN_HEALTH_VERDICTS


def test_exported_health_rows_satisfy_the_checker(tmp_path):
    """Round-trip: what the monitor exports (via telemetry.export) must
    pass invariant 13 as-is — even teed into a bench file."""
    from harp_tpu import health
    from harp_tpu.utils import skew, telemetry

    with telemetry.scope(True):
        for _ in range(health.TRIGGER_SUPERSTEPS):
            skew.record_partition(
                "files", [10, 1, 0, 1], unit="bytes",
                units=[[("a", 6), ("b", 4)], [("c", 1)], [], [("d", 1)]])
        health.monitor.observe_budget("serve.kmeans",
                                      [("h2d_calls", 2, 1)])
        p = tmp_path / "BENCH_local.jsonl"
        telemetry.export(str(p))
    assert check_jsonl.check_file(str(p), provenance=True) == []


def test_golden_health_fixture_is_clean_and_summarizes():
    """The committed golden health fixture (tests/data) passes the
    checker — the fixture the health CLI smoke drives."""
    p = os.path.join(os.path.dirname(__file__), "data",
                     "golden_health.jsonl")
    assert check_jsonl.check_file(p) == []
    from harp_tpu import health
    from harp_tpu.utils import telemetry

    rows = telemetry.load_rows(p)["health"]
    s = health.summarize_rows(rows)
    assert s["findings"] == 4
    assert s["worst_severity"] == "page"
    assert s["actionable"] == 3  # page + warn + warn; confirmed is info


# ---------------------------------------------------------------------------
# Invariant 14: elastic rows (PR 15)
# ---------------------------------------------------------------------------

_ESTAMP = {"backend": "cpu", "date": "2026-08-05", "commit": "abc1234"}


def _elastic_row(event="rebalance", **over):
    base = {
        "rebalance": {"kind": "elastic", "event": "rebalance",
                      "phase": "mfsgd.epochs", "n_workers": 8, "moves": 3,
                      "loads_before": [4000.0] + [150.0] * 7,
                      "loads_after": [640.0, 630.0] + [630.0] * 6,
                      "total": 5050.0, "wasted_frac_before": 0.84,
                      "wasted_frac_after": 0.02, "trigger_supersteps": 3,
                      **_ESTAMP},
        "shrink": {"kind": "elastic", "event": "shrink",
                   "phase": "mfsgd.epochs", "lost_worker": 3,
                   "site": "dispatch", "ordinal": 2,
                   "n_workers_before": 8, "n_workers_after": 7,
                   "capacity_frac": 0.875, **_ESTAMP},
        "resume": {"kind": "elastic", "event": "resume",
                   "phase": "mfsgd.epochs", "n_workers": 7, "from_step": 0,
                   "loads": [721.0] * 7, "total": 5047.0,
                   "wasted_frac": 0.0, "replayed_plan": True, **_ESTAMP},
    }[event]
    base = dict(base)
    base.update(over)
    return base


def _elastic_errs(row):
    return check_jsonl._check_elastic_row("t", 1, row)


def test_elastic_rows_valid_round_trip(tmp_path):
    # fix the resume loads to actually sum to total
    resume = _elastic_row("resume", loads=[721.0] * 7, total=5047.0)
    rows = [_elastic_row("rebalance",
                         loads_before=[4000.0] + [150.0] * 7,
                         loads_after=[631.25] * 8, total=5050.0),
            _elastic_row("shrink"), resume]
    p = tmp_path / "rows.jsonl"
    p.write_text("".join(json.dumps(r) + "\n" for r in rows))
    assert check_jsonl.check_file(str(p), provenance=True) == []


def test_elastic_row_requires_stamp_and_event_vocab():
    row = _elastic_row("shrink")
    del row["backend"]
    assert any("provenance" in e for e in _elastic_errs(row))
    grow = _elastic_row("shrink")
    grow["event"] = "grow"
    assert any("event='grow'" in e for e in _elastic_errs(grow))


def test_elastic_rebalance_row_forgeries_fire():
    ok = _elastic_row("rebalance",
                      loads_before=[4000.0] + [150.0] * 7,
                      loads_after=[631.25] * 8, total=5050.0)
    assert _elastic_errs(ok) == []
    # loads not summing to total
    assert any("conserve work" in e for e in _elastic_errs(
        _elastic_row("rebalance", loads_after=[1.0] * 8)))
    # loads without a total
    bad = _elastic_row("rebalance")
    del bad["total"]
    assert any("total" in e for e in _elastic_errs(bad))
    # negative / non-list loads
    assert any("non-negative" in e for e in _elastic_errs(
        _elastic_row("rebalance", loads_before=[-1.0] * 8,
                     total=-8.0)))
    assert any("non-empty list" in e for e in _elastic_errs(
        _elastic_row("rebalance", loads_before="heavy")))
    # a "rebalance" that made things worse
    assert any("worse" in e for e in _elastic_errs(
        _elastic_row("rebalance", wasted_frac_before=0.1,
                     wasted_frac_after=0.5,
                     loads_before=[631.25] * 8,
                     loads_after=[631.25] * 8)))
    # missing before/after evidence entirely
    nofrac = _elastic_row("rebalance", loads_before=[631.25] * 8,
                          loads_after=[631.25] * 8)
    del nofrac["wasted_frac_before"]
    assert any("before/after" in e.lower() or "before AND after" in e
               for e in _elastic_errs(nofrac))
    # fractions outside [0, 1]
    assert any("[0, 1]" in e for e in _elastic_errs(
        _elastic_row("rebalance", wasted_frac_after=1.5)))


def test_elastic_shrink_row_needs_strictly_fewer_survivors():
    assert _elastic_errs(_elastic_row("shrink")) == []
    assert any("survivor count" in e for e in _elastic_errs(
        _elastic_row("shrink", n_workers_after=8)))
    assert any("survivor count" in e for e in _elastic_errs(
        _elastic_row("shrink", n_workers_before=None)))
    assert any("lost_worker=-1" in e for e in _elastic_errs(
        _elastic_row("shrink", lost_worker=-1)))


def test_elastic_vocab_in_sync_with_elastic_module():
    import harp_tpu.elastic as E

    assert tuple(E.EVENTS) == check_jsonl.KNOWN_ELASTIC_EVENTS


# ---------------------------------------------------------------------------
# Invariant 15: profile attribution rows (PR 16)
# ---------------------------------------------------------------------------

_PSTAMP = {"backend": "cpu", "date": "2026-08-06", "commit": "abc1234"}


def _profile_row(**over):
    base = {
        "kind": "profile", "app": "lda", "program": "lda.epoch",
        "wall_s": 0.04, "reps": 4, "n_devices": 8,
        "terms": {"mxu_s": 0.001, "elementwise_s": 0.002,
                  "gather_dus_s": 0.0, "scatter_s": 0.0,
                  "wire_s": 0.033, "overhead_s": 0.004},
        "bound": "wire", "sum_rel_err": 0.02, "wire_bytes": 2308,
        "wire_sites": 3, "wire_unmatched": 0, "dispatches": 4,
        "dispatches_per_rep": 1, "dispatch_reconciled": True,
        "compiles_in_window": 0, "reconciled": True, **_PSTAMP}
    base.update(over)
    return base


def _profile_errs(row):
    return check_jsonl._check_profile_row("t", 1, row)


def test_profile_row_valid_round_trip(tmp_path):
    assert _profile_errs(_profile_row()) == []
    p = tmp_path / "PROFILE_attrib.jsonl"
    p.write_text(json.dumps(_profile_row()) + "\n")
    assert check_jsonl.check_file(str(p)) == []


def test_profile_row_requires_provenance_and_vocabularies():
    row = _profile_row()
    del row["backend"]
    assert any("provenance" in e for e in _profile_errs(row))
    assert any("app=" in e for e in _profile_errs(
        _profile_row(app="word2vec")))
    # program must be a registered lint driver, not free text
    assert any("unregistered program" in e for e in _profile_errs(
        _profile_row(program="lda.mystery")))


def test_profile_row_buckets_must_sum_to_wall():
    assert any("sum to" in e for e in _profile_errs(
        _profile_row(terms={"mxu_s": 0.001, "elementwise_s": 0.002,
                            "gather_dus_s": 0.0, "scatter_s": 0.0,
                            "wire_s": 0.01, "overhead_s": 0.004})))


def test_profile_row_rejects_unknown_bucket_name():
    bad = _profile_row()
    bad["terms"] = dict(bad["terms"])
    bad["terms"]["dma_s"] = bad["terms"].pop("wire_s")
    assert any("frozen mechanism" in e for e in _profile_errs(bad))


def test_profile_row_bound_must_name_the_largest_bucket():
    assert any("largest bucket" in e for e in _profile_errs(
        _profile_row(bound="mxu")))
    assert any("bound=" in e for e in _profile_errs(
        _profile_row(bound="hbm")))


def test_profile_row_fails_closed_on_reconciliation():
    # cross-check counters must be literally clean, not merely present
    assert any("exactly 0" in e for e in _profile_errs(
        _profile_row(compiles_in_window=1)))
    assert any("exactly 0" in e for e in _profile_errs(
        _profile_row(wire_unmatched=2)))
    assert any("dispatches=" in e for e in _profile_errs(
        _profile_row(dispatches=7)))
    assert any("sum_rel_err" in e for e in _profile_errs(
        _profile_row(sum_rel_err=0.9)))


def test_profile_vocabularies_in_sync_with_profile_module():
    """check_jsonl freezes the attribution vocabularies standalone;
    drift from the live harp_tpu.profile module fails here (tier-1)."""
    from harp_tpu.health import sentinel
    from harp_tpu.profile import attribution

    assert tuple(attribution.BUCKETS) == check_jsonl.KNOWN_PROFILE_BUCKETS
    assert tuple(attribution.PROFILE_APPS) == check_jsonl.KNOWN_PROFILE_APPS
    assert attribution.SUM_REL_TOL == check_jsonl.PROFILE_SUM_REL_TOL
    assert "profile_drift" in sentinel.DETECTORS


def test_golden_profile_fixture_is_clean_and_grades():
    """The committed golden profile fixture (tests/data) passes the
    checker, and the health grader reads it as drift-free against
    itself — the fixture the profile CLI smoke drives."""
    p = os.path.join(os.path.dirname(__file__), "data",
                     "golden_profile.jsonl")
    assert check_jsonl.check_file(p) == []
    import json as _json

    from harp_tpu.health import grade as HG

    rows = [_json.loads(l) for l in open(p)]
    committed = {r["app"]: r for r in rows}
    assert sorted(committed) == ["kmeans", "lda"]
    for r in rows:
        fresh = dict(r, terms=dict(r["terms"]))
        assert HG.grade_profile_row(fresh, ".", committed=committed) is None


def test_committed_profile_attribution_covers_every_app():
    """PROFILE_attrib.jsonl (the committed baseline the profile_drift
    detector grades against) carries one reconciled row per app in the
    frozen vocabulary — including the four PR-16 newly priced apps."""
    p = os.path.join(os.path.dirname(__file__), "..",
                     "PROFILE_attrib.jsonl")
    assert check_jsonl.check_file(p) == []
    rows = [json.loads(l) for l in open(p)]
    apps = {r["app"] for r in rows if r.get("kind") == "profile"}
    assert apps == set(check_jsonl.KNOWN_PROFILE_APPS)
    assert all(r["reconciled"] is True for r in rows)


# ---------------------------------------------------------------------------
# Invariant 16: steptrace rows (PR 18)
# ---------------------------------------------------------------------------

_TSTAMP = {"backend": "cpu", "date": "2026-08-06", "commit": "abc1234"}


def _st_flight(**over):
    fl = {"dispatches": 0, "readbacks": 0, "h2d_calls": 0, "compiles": 0}
    fl.update(over)
    return fl


def _st_rows():
    """A minimal valid forged timeline: one run, one completed span,
    one dispatch mark, one skew lane — internally reconciled."""
    fl = _st_flight(dispatches=1, readbacks=1)
    return [
        {"kind": "steptrace", "ev": "mark", "run": 1, "ts": 0.01,
         "source": "flight", "name": "dispatch", "seq": 0,
         "site": "kmeans.fit", **_TSTAMP},
        {"kind": "steptrace", "ev": "lane", "run": 1, "ts": 0.015,
         "seq": 0, "phase": "kmeans.fit", "work": [1.0] * 8,
         "unit": "points", **_TSTAMP},
        {"kind": "steptrace", "ev": "superstep", "run": 1, "seq": 0,
         "step": 0, "phase": "kmeans.fit", "outcome": "completed",
         "t0": 0.005, "ts": 0.02, "flight": fl, **_TSTAMP},
        {"kind": "steptrace", "ev": "run", "run": 1,
         "phase": "kmeans.fit", "t0": 0.0, "ts": 0.03, "supersteps": 1,
         "marks": 1, "lanes": 1,
         "outcomes": {"completed": 1, "faulted": 0, "rebalanced": 0,
                      "resumed": 0},
         "flight": dict(fl), "span_flight": dict(fl), **_TSTAMP},
    ]


def _st_check(rows, tmp_path, extra=()):
    p = tmp_path / "steptrace.jsonl"
    p.write_text("".join(json.dumps(r) + "\n"
                         for r in list(extra) + list(rows)))
    return check_jsonl.check_file(str(p), provenance=True)


def test_steptrace_rows_valid_round_trip(tmp_path):
    assert _st_check(_st_rows(), tmp_path) == []


def test_steptrace_row_requires_provenance_and_vocabularies(tmp_path):
    rows = _st_rows()
    del rows[0]["backend"]
    assert any("provenance" in e for e in _st_check(rows, tmp_path))
    rows = _st_rows()
    rows[0]["ev"] = "epoch"
    assert any("ev='epoch'" in e for e in _st_check(rows, tmp_path))
    rows = _st_rows()
    rows[0]["source"] = "vibes"
    assert any("source='vibes'" in e for e in _st_check(rows, tmp_path))


def test_steptrace_rows_must_be_monotone(tmp_path):
    rows = _st_rows()
    rows[1]["ts"] = 0.001  # lane stamped before the preceding mark
    assert any("monotone" in e for e in _st_check(rows, tmp_path))


def test_steptrace_every_run_must_terminate(tmp_path):
    rows = _st_rows()[:-1]  # drop the terminating run row
    assert any("no terminating run row" in e
               for e in _st_check(rows, tmp_path))
    # and a run row may appear exactly once
    rows = _st_rows() + [_st_rows()[-1]]
    assert any("duplicate steptrace run row" in e
               for e in _st_check(rows, tmp_path))


def test_steptrace_span_outcome_vocabulary_enforced(tmp_path):
    rows = _st_rows()
    rows[2]["outcome"] = "exploded"
    errs = _st_check(rows, tmp_path)
    assert any("outcome='exploded'" in e for e in errs)


def test_steptrace_run_summary_must_rederive(tmp_path):
    # claimed superstep count vs actual span rows
    rows = _st_rows()
    rows[-1]["supersteps"] = 2
    assert any("claims 2 superstep(s)" in e
               for e in _st_check(rows, tmp_path))
    # claimed outcome tally vs span outcomes
    rows = _st_rows()
    rows[-1]["outcomes"] = {"completed": 0, "faulted": 1,
                            "rebalanced": 0, "resumed": 0}
    assert any("do not match the run row's" in e
               for e in _st_check(rows, tmp_path))
    # span flight sums exceeding the run's own flight delta
    rows = _st_rows()
    rows[2]["flight"] = _st_flight(dispatches=3, readbacks=1)
    rows[-1]["span_flight"] = _st_flight(dispatches=3, readbacks=1)
    assert any("cannot own more ops than the run recorded" in e
               for e in _st_check(rows, tmp_path))


def test_steptrace_dispatch_marks_must_match_flight_exactly(tmp_path):
    # drop the dispatch mark but keep the run's flight delta at 1
    rows = [r for r in _st_rows()
            if not (r["ev"] == "mark" and r["name"] == "dispatch")]
    rows[-1]["marks"] = 0
    assert any("must agree EXACTLY" in e for e in _st_check(rows, tmp_path))


def test_steptrace_cannot_outclaim_the_transfer_ledger(tmp_path):
    """A timeline attributing more dispatches than the file's own
    kind:'transfer' rows recorded is forged."""
    transfer = {"kind": "transfer", "op": "dispatch", "calls": 0,
                "bytes": 0, "site": "forged", **_TSTAMP}
    errs = _st_check(_st_rows(), tmp_path, extra=[transfer])
    assert any("cannot own more dispatches" in e for e in errs)


def test_steptrace_elastic_marks_reconcile_event_for_event(tmp_path):
    # an elastic mark with no kind:'elastic' row
    rows = _st_rows()
    rows.insert(1, {"kind": "steptrace", "ev": "mark", "run": 1,
                    "ts": 0.012, "source": "elastic",
                    "name": "rebalance", "seq": 0, "phase": "kmeans.fit",
                    **_TSTAMP})
    rows[-1]["marks"] = 2
    assert any("one story" in e for e in _st_check(rows, tmp_path))
    # and the converse: a timeline-covered elastic row with no mark
    erow = _elastic_row("rebalance",
                        loads_before=[4000.0] + [150.0] * 7,
                        loads_after=[631.25] * 8, total=5050.0,
                        on_timeline=True)
    errs = _st_check(_st_rows(), tmp_path, extra=[erow])
    assert any("one story" in e for e in errs)
    # an UNCOVERED row (manual install outside any run) is legitimate
    erow_off = dict(erow, on_timeline=False)
    assert _st_check(_st_rows(), tmp_path, extra=[erow_off]) == []


def test_steptrace_health_marks_need_sentinel_rows(tmp_path):
    # a finding mark with no kind:'health' row in the file
    rows = _st_rows()
    rows.insert(1, {"kind": "steptrace", "ev": "mark", "run": 1,
                    "ts": 0.012, "source": "health", "name": "slo_burn",
                    "seq": 0, **_TSTAMP})
    rows[-1]["marks"] = 2
    assert any("must exist in the sentinel export" in e
               for e in _st_check(rows, tmp_path))
    # with the matching health row the same file is clean
    assert _st_check(rows, tmp_path, extra=[_health_row()]) == []


def test_steptrace_consume_mark_needs_consumed_trigger_row(tmp_path):
    consume = {"kind": "steptrace", "ev": "mark", "run": 1, "ts": 0.012,
               "source": "health", "name": "consume_skew_trigger",
               "seq": 0, "phase": "p", **_TSTAMP}
    rows = _st_rows()
    rows.insert(1, consume)
    rows[-1]["marks"] = 2
    # no skew_trigger row at all
    assert any("exactly-once handshake" in e
               for e in _st_check(rows, tmp_path))
    # a trigger row that was never consumed does not cover it either
    errs = _st_check(rows, tmp_path, extra=[_skew_trigger_row()])
    assert any("exactly-once handshake" in e for e in errs)
    # the consumed row closes the loop
    consumed = dict(_skew_trigger_row(), consumed=True)
    assert _st_check(rows, tmp_path, extra=[consumed]) == []


def test_steptrace_vocab_in_sync_with_steptrace_module():
    from harp_tpu.utils import steptrace as ST

    assert ST.EVS == check_jsonl.KNOWN_STEPTRACE_EVS
    assert ST.OUTCOMES == check_jsonl.KNOWN_STEPTRACE_OUTCOMES
    assert ST.SOURCES == check_jsonl.KNOWN_STEPTRACE_SOURCES
    assert ST.FLIGHT_KEYS == check_jsonl.KNOWN_STEPTRACE_FLIGHT_KEYS


def test_golden_steptrace_fixture_is_clean_and_summarizes():
    """The committed golden timeline fixture (tests/data) passes the
    checker — the fixture the timeline CLI smoke drives."""
    p = os.path.join(os.path.dirname(__file__), "data",
                     "golden_steptrace.jsonl")
    assert check_jsonl.check_file(p) == []
    from harp_tpu.utils import steptrace, telemetry

    rows = telemetry.load_rows(p)["steptrace"]
    s = steptrace.summarize_rows(rows)
    assert s["runs"] == 1 and s["unterminated"] == []
    assert s["supersteps"] >= 2 and s["dispatch_mismatch"] == []


# ---------------------------------------------------------------------------
# invariant 17: memory-ledger rows (PR 19)
# ---------------------------------------------------------------------------

def _mem_rows():
    """A minimal valid forged ledger: stage → donate → dispatch →
    output → restore → free → executable → vmem pass → summary,
    internally reconciled (the exact shape memrec.export_jsonl
    writes)."""
    return [
        {"kind": "memory", "ev": "buffer", "event": "staged", "buf": 1,
         "bytes": 1024, "label": "mesh.shard_array", "seq": 1,
         "live_bytes": 1024, "peak_bytes": 1024, **_TSTAMP},
        {"kind": "memory", "ev": "buffer", "event": "donated", "buf": 1,
         "bytes": 1024, "label": "mesh.shard_array", "seq": 2,
         "live_bytes": 0, "peak_bytes": 1024, **_TSTAMP},
        {"kind": "memory", "ev": "dispatch", "label": "serve.kmeans.b8",
         "seq": 3, "donated": [1], "donated_bytes": 1024,
         "live_bytes": 0, "peak_bytes": 1024, **_TSTAMP},
        {"kind": "memory", "ev": "buffer", "event": "output", "buf": 2,
         "bytes": 4, "label": "serve.kmeans.b8", "seq": 4,
         "live_bytes": 4, "peak_bytes": 1024, **_TSTAMP},
        {"kind": "memory", "ev": "buffer", "event": "restored", "buf": 0,
         "bytes": 4096, "label": "ckpt:step_1", "seq": 5,
         "live_bytes": 4, "peak_bytes": 1024, **_TSTAMP},
        {"kind": "memory", "ev": "buffer", "event": "freed", "buf": 2,
         "bytes": 4, "label": "serve.kmeans.b8", "seq": 6,
         "live_bytes": 0, "peak_bytes": 1024, **_TSTAMP},
        {"kind": "memory", "ev": "executable", "name": "serve.kmeans.b8",
         "seq": 7, "source": "compile", "argument_bytes": 256,
         "output_bytes": 256, "temp_bytes": 0,
         "generated_code_bytes": 0, "exec_hbm_bytes": 512, **_TSTAMP},
        {"kind": "memory", "ev": "vmem_check",
         "kernel": "kmeans.partials_int8", "seq": 8,
         "predicted_bytes": 1048576, "budget_bytes": 14680064,
         "fits": True, "refused": False, **_TSTAMP},
        {"kind": "memory", "ev": "summary", "seq": 9, "events": 8,
         "staged_bytes": 1024, "freed_bytes": 4, "donated_bytes": 1024,
         "peak_hbm_bytes": 1024, "live_hbm_bytes": 0,
         "hbm_bytes": 17179869184, "headroom_frac": 1.0,
         "executables": 1, "exec_hbm_bytes": 512, "vmem_checks": 1,
         "vmem_refusals": 0, **_TSTAMP},
    ]


def _mem_check(rows, tmp_path):
    p = tmp_path / "memory.jsonl"
    p.write_text("".join(json.dumps(r) + "\n" for r in rows))
    return check_jsonl.check_file(str(p), provenance=True)


def test_memory_rows_valid_round_trip(tmp_path):
    assert _mem_check(_mem_rows(), tmp_path) == []


def test_memory_row_requires_provenance_and_vocabularies(tmp_path):
    rows = _mem_rows()
    del rows[0]["backend"]
    assert any("provenance" in e for e in _mem_check(rows, tmp_path))
    rows = _mem_rows()
    rows[0]["ev"] = "malloc"
    assert any("ev='malloc'" in e for e in _mem_check(rows, tmp_path))
    rows = _mem_rows()
    rows[0]["event"] = "leaked"
    assert any("event='leaked'" in e for e in _mem_check(rows, tmp_path))
    rows = _mem_rows()
    rows[6]["source"] = "vibes"
    assert any("'compile' or 'cache'" in e
               for e in _mem_check(rows, tmp_path))


def test_memory_seq_must_strictly_increase(tmp_path):
    rows = _mem_rows()
    rows[1]["seq"] = 1  # replayed seq
    assert any("did not increase" in e for e in _mem_check(rows, tmp_path))
    rows = _mem_rows()
    rows[0]["bytes"] = -5
    assert any("non-negative" in e for e in _mem_check(rows, tmp_path))


def test_memory_watermark_must_rederive_exactly(tmp_path):
    # a forged peak the events cannot reproduce
    rows = _mem_rows()
    rows[0]["peak_bytes"] = 2048
    assert any("peak_bytes=2048 != derived 1024" in e
               for e in _mem_check(rows, tmp_path))
    # a forged live count on a buffer row
    rows = _mem_rows()
    rows[3]["live_bytes"] = 999
    assert any("re-derive from the event stream EXACTLY" in e
               for e in _mem_check(rows, tmp_path))
    # a summary asserting a peak the stream never reached
    rows = _mem_rows()
    rows[-1]["peak_hbm_bytes"] = 4096
    assert any("asserted, not measured" in e
               for e in _mem_check(rows, tmp_path))


def test_memory_donated_buffer_must_leave_live_set(tmp_path):
    # drop the donated buffer event: the dispatch row's claimed buffer
    # is then still live — the runtime twin of HL303 fires
    rows = [r for r in _mem_rows()
            if not (r.get("ev") == "buffer"
                    and r.get("event") == "donated")]
    errs = _mem_check(rows, tmp_path)
    assert any("still in the live set" in e and "HL303" in e
               for e in errs)
    # freeing a buffer that was never staged is equally forged
    rows = _mem_rows()
    rows[5]["buf"] = 77
    assert any("is not in the live set" in e
               for e in _mem_check(rows, tmp_path))


def test_memory_vmem_flags_must_follow_their_own_bytes(tmp_path):
    rows = _mem_rows()
    rows[7]["fits"] = False  # contradicts predicted <= budget
    errs = _mem_check(rows, tmp_path)
    assert any("contradicts predicted" in e for e in errs)
    rows = _mem_rows()
    rows[7]["refused"] = True  # refused must be the negation of fits
    assert any("negation of fits" in e for e in _mem_check(rows, tmp_path))


def test_memory_executable_components_must_sum(tmp_path):
    rows = _mem_rows()
    rows[6]["exec_hbm_bytes"] = 9999
    assert any("component sum" in e for e in _mem_check(rows, tmp_path))


def test_memory_export_must_terminate_in_one_summary(tmp_path):
    # no summary at all
    rows = _mem_rows()[:-1]
    assert any("no terminating summary" in e
               for e in _mem_check(rows, tmp_path))
    # a second summary
    rows = _mem_rows() + [dict(_mem_rows()[-1], seq=10)]
    assert any("second memory summary" in e
               for e in _mem_check(rows, tmp_path))
    # a late buffer event after the summary
    late = dict(_mem_rows()[0], seq=10)
    rows = _mem_rows() + [late]
    assert any("after the summary row" in e
               for e in _mem_check(rows, tmp_path))


def test_memory_headroom_must_be_computed(tmp_path):
    rows = _mem_rows()
    rows[-1]["headroom_frac"] = 0.5
    assert any("headroom must be computed" in e
               for e in _mem_check(rows, tmp_path))
    rows = _mem_rows()
    rows[-1]["hbm_bytes"] = 0
    assert any("positive integer" in e for e in _mem_check(rows, tmp_path))


def test_memory_vocab_in_sync_with_memrec_module():
    from harp_tpu.utils import memrec

    assert memrec.EVS == check_jsonl.KNOWN_MEMORY_EVS
    assert memrec.BUFFER_EVENTS == check_jsonl.KNOWN_MEMORY_EVENTS


def test_golden_memory_fixture_is_clean_and_summarizes():
    """The committed golden memory fixture (tests/data) passes the
    checker AND the module's own replay — the fixture the memory CLI
    smoke drives."""
    p = os.path.join(os.path.dirname(__file__), "data",
                     "golden_memory.jsonl")
    assert check_jsonl.check_file(p) == []
    from harp_tpu.utils import memrec, telemetry

    s = memrec.summarize_rows(telemetry.load_rows(p)["memory"])
    assert s["errors"] == []
    assert s["vmem_refusals"] == 1        # the walkthrough's refusal
    assert s["donated_bytes"] > 0         # the HL303 runtime twin
    assert s["executables"] == 1


# the derived evidence kinds that ship BOTH an offline validator
# (python -m harp_tpu trace/timeline/health/memory, profile --json)
# and a committed golden fixture; a new telemetry spine must join this
# tuple with its checker + fixture or the pin fails tier-1
GOLDEN_SPINE_KINDS = ("trace", "health", "profile", "steptrace",
                      "memory")


def test_meta_every_spine_kind_has_checker_and_golden_fixture():
    """Satellite 3 (PR 19): every spine kind with an offline CLI has a
    check_jsonl invariant (a ``_check_<kind>_row`` checker) AND a clean
    committed golden fixture under tests/data/ containing rows of that
    kind — a new spine cannot land half-pinned."""
    data = os.path.join(os.path.dirname(__file__), "data")
    goldens = sorted(f for f in os.listdir(data)
                     if f.startswith("golden_") and f.endswith(".jsonl"))
    assert goldens == sorted(f"golden_{k}.jsonl"
                             for k in GOLDEN_SPINE_KINDS)
    for kind in GOLDEN_SPINE_KINDS:
        checker = getattr(check_jsonl, f"_check_{kind}_row", None)
        assert callable(checker), f"no check_jsonl invariant for {kind}"
        p = os.path.join(data, f"golden_{kind}.jsonl")
        assert check_jsonl.check_file(p) == [], kind
        kinds_in_file = {json.loads(ln).get("kind")
                         for ln in open(p) if ln.strip()}
        assert kind in kinds_in_file, f"{p} holds no {kind} rows"
