"""scripts/check_jsonl.py — committed measurement files stay parseable and
provenance-stamped (the CPU-inversion guard, tier-1)."""

import json
import os
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "scripts"))

import check_jsonl  # noqa: E402


def test_committed_files_are_clean():
    """THE tier-1 gate: every committed BENCH_local / PROFILE_local /
    FLIP_DECISIONS line parses, and post-grandfather bench rows carry
    backend/date/commit."""
    errors = check_jsonl.check_repo(ROOT)
    assert errors == [], "\n".join(errors)


def test_unparseable_line_is_loud(tmp_path):
    p = tmp_path / "BENCH_local.jsonl"
    p.write_text('{"config": "x", "backend": "cpu"}\n'
                 "{'config': 'dictrepr'}\n")  # the teed dict-repr bug
    errors = check_jsonl.check_file(str(p))
    assert len(errors) == 1 and "unparseable" in errors[0]
    assert ":2:" in errors[0]


def test_new_bench_row_must_carry_provenance(tmp_path):
    rows = [
        {"config": "legacy_row", "iters_per_sec": 1.0},   # grandfathered
        {"config": "new_row", "iters_per_sec": 2.0},      # must be stamped
    ]
    p = tmp_path / "BENCH_local.jsonl"
    p.write_text("".join(json.dumps(r) + "\n" for r in rows))
    errors = check_jsonl.check_file(str(p), grandfathered=1,
                                    provenance=True)
    assert len(errors) == 1
    assert "new_row" in errors[0] and "backend" in errors[0]


def test_stamped_row_passes(tmp_path):
    row = {"config": "ok", "iters_per_sec": 2.0, "backend": "tpu",
           "date": "2026-08-04", "commit": "abc1234"}
    p = tmp_path / "BENCH_local.jsonl"
    p.write_text(json.dumps(row) + "\n")
    assert check_jsonl.check_file(str(p), provenance=True) == []


def test_non_bench_rows_need_only_parse(tmp_path):
    # verb-sweep and metric-headline rows have no "config": parse-only
    rows = [{"verb": "pull_sparse_sweep", "sec": 0.1},
            {"metric": "kmeans_iters_per_sec", "value": 1.0}]
    p = tmp_path / "rows.jsonl"
    p.write_text("".join(json.dumps(r) + "\n" for r in rows))
    assert check_jsonl.check_file(str(p), provenance=True) == []


def test_cli_exit_codes(tmp_path):
    (tmp_path / "BENCH_local.jsonl").write_text("not json\n")
    assert check_jsonl.main(["--repo", str(tmp_path)]) == 1
    (tmp_path / "BENCH_local.jsonl").write_text("")
    assert check_jsonl.main(["--repo", str(tmp_path)]) == 0


def test_benchmark_json_rows_satisfy_the_checker(tmp_path):
    """The stamp the checker demands is exactly what benchmark_json
    emits — the two can never drift apart."""
    from harp_tpu.utils.metrics import benchmark_json

    p = tmp_path / "BENCH_local.jsonl"
    p.write_text(benchmark_json("fresh", {"iters_per_sec": 1.0}) + "\n")
    assert check_jsonl.check_file(str(p), provenance=True) == []
