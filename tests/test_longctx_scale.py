"""Long-context graded-shape proofs — the same pin the 1B-point KMeans
(tests/test_kmeans_stream.py) and enwiki-1M LDA (tests/test_lda_scale.py)
programs have: the sequence-parallel attention programs must TRACE AND
LOWER at million-token sequence length on the 8-worker mesh, via
jax.ShapeDtypeStruct (zero host memory, no execution — that needs TPU).

Shapes follow the long-context regime the reference's scale story
implies (SURVEY.md §3.5 marks SP ❌ in Harp; ring/Ulysses here are the
beyond-reference long-context layer): 1M tokens, 8 KV heads × 128 head
dim, bf16 activations — per-worker live attention state is what ring
attention exists to bound.
"""

import pytest

import jax
import jax.numpy as jnp

from harp_tpu.ops.a2a_attention import make_a2a_attention_fn
from harp_tpu.ops.ring_attention import make_ring_attention_fn

B, S, H, HD = 1, 1_048_576, 8, 128  # 1M tokens, 8 heads × 128


def _sds(mesh, h=H):
    sh = mesh.sharding(mesh.spec(1, ndim=4))
    return [jax.ShapeDtypeStruct((B, S, h, HD), jnp.bfloat16, sharding=sh)
            for _ in range(3)]


@pytest.mark.parametrize("maker,name", [
    (make_ring_attention_fn, "ring"),
    (make_a2a_attention_fn, "a2a"),
])
def test_million_token_attention_lowers(mesh, maker, name):
    """Causal attention over a 1M-token sequence-sharded input lowers
    without executing; the collective (ppermute ring / all_to_all) is in
    the program, and activations stay bf16."""
    fn = maker(mesh, causal=True)
    text = fn.lower(*_sds(mesh)).as_text()
    assert "bf16" in text
    assert "while" in text                  # the ring/block loop lowered
    assert str(S // 8) in text              # per-worker sequence block
    if name == "ring":
        assert "collective_permute" in text
    else:
        assert "all_to_all" in text


def test_million_token_windowed_mqa_lowers(mesh):
    """The cheap long-context serving shape: sliding-window MQA (1 KV
    head) at 1M tokens — the window bounds work per step, MQA bounds KV
    bytes; both must survive lowering at true scale."""
    fn = make_ring_attention_fn(mesh, causal=True, window=4096)
    q = _sds(mesh)[0]
    kv = _sds(mesh, h=1)
    text = fn.lower(q, kv[0], kv[1]).as_text()
    assert "collective_permute" in text
