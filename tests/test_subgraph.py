"""Subgraph counting tests: exact DP vs brute force, unbiased estimates."""

import itertools
import math

import numpy as np
import pytest

from harp_tpu.models import subgraph as SG



def brute_force_rooted_colorful(edges, n, tpl, colors):
    """All maps φ: template→graph respecting edges, image colors distinct."""
    adj = set()
    for a, b in edges:
        adj.add((a, b))
        adj.add((b, a))
    s = len(tpl)
    count = 0
    for phi in itertools.product(range(n), repeat=s):
        if len({colors[v] for v in phi}) != s:
            continue
        ok = all((phi[i], phi[tpl[i]]) in adj for i in range(1, s))
        if ok:
            count += 1
    return count


def brute_force_unrooted(edges, n, tpl):
    """Exact template count: injective edge-respecting maps / |Aut(T)|."""
    adj = set()
    for a, b in edges:
        adj.add((a, b))
        adj.add((b, a))
    s = len(tpl)
    maps = 0
    for phi in itertools.permutations(range(n), s):
        if all((phi[i], phi[tpl[i]]) in adj for i in range(1, s)):
            maps += 1
    return maps / SG._count_automorphism_roots(tpl)


TINY_EDGES = [(0, 1), (1, 2), (2, 3), (3, 0), (0, 2), (4, 0), (5, 1), (4, 5)]
TINY_N = 8  # includes two isolated-ish vertices 6, 7


@pytest.mark.parametrize("tname", ["u3-path", "u3-star", "u5-path", "u5-star",
                                   "u5-tree"])
def test_dp_matches_brute_force_colorful(mesh, tname):
    tpl = SG.TEMPLATES[tname]
    s = len(tpl)
    rng = np.random.default_rng(0)
    colors = rng.integers(0, s, TINY_N).astype(np.int32)
    nbr, msk, dropped = SG.pad_csr(TINY_EDGES, TINY_N, 8)
    assert dropped == 0
    fn = SG.make_colorful_count_fn(tpl, s, mesh)
    out = float(np.asarray(fn(
        mesh.shard_array(nbr, 0), mesh.shard_array(msk, 0),
        mesh.shard_array(colors[None, :], 1),   # [trials=1, n]
    ))[0])
    expect = brute_force_rooted_colorful(TINY_EDGES, TINY_N, tpl, colors)
    assert out == expect, (tname, out, expect)


def test_automorphism_counts():
    assert SG._count_automorphism_roots(SG.TEMPLATES["u3-path"]) == 2   # path
    assert SG._count_automorphism_roots(SG.TEMPLATES["u3-star"]) == 2   # same tree
    assert SG._count_automorphism_roots(SG.TEMPLATES["u5-star"]) == 24  # 4! leaves
    assert SG._count_automorphism_roots(SG.TEMPLATES["u5-path"]) == 2


def test_estimator_unbiased_small(mesh):
    """Color-coding estimate over many trials ≈ exact count."""
    tpl = SG.TEMPLATES["u3-path"]
    exact = brute_force_unrooted(TINY_EDGES, TINY_N, tpl)
    cfg = SG.SubgraphConfig(template="u3-path", n_trials=200, seed=1, max_degree=8)
    est, trials, _ = SG.count_template(TINY_EDGES, TINY_N, cfg, mesh)
    assert exact > 0
    assert abs(est - exact) / exact < 0.2, (est, exact)


def test_degree_truncation_reported():
    edges = [(0, i) for i in range(1, 7)]
    _, _, dropped = SG.pad_csr(edges, 7, 4)
    assert dropped == 2  # vertex 0 has degree 6, cap 4


def test_u7_tree_runs_and_estimates(mesh):
    """The deepest template (u7-tree, 2^7 subset columns) runs end-to-end
    with batched trials and returns a sane nonnegative estimate."""
    rng = np.random.default_rng(3)
    n = 48
    edges = np.stack([rng.integers(0, n, 300), rng.integers(0, n, 300)], 1)
    est, trials, dropped = SG.count_template(
        edges, n, SG.SubgraphConfig(template="u7-tree", n_trials=3,
                                    trial_chunk=2, max_degree=24), mesh)
    assert len(trials) == 3 and np.isfinite(est) and est >= 0
