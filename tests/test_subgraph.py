"""Subgraph counting tests: exact DP vs brute force, unbiased estimates."""

import itertools
import math

import numpy as np
import pytest

from harp_tpu.models import subgraph as SG



def brute_force_rooted_colorful(edges, n, tpl, colors):
    """All maps φ: template→graph respecting edges, image colors distinct."""
    adj = set()
    for a, b in edges:
        adj.add((a, b))
        adj.add((b, a))
    s = len(tpl)
    count = 0
    for phi in itertools.product(range(n), repeat=s):
        if len({colors[v] for v in phi}) != s:
            continue
        ok = all((phi[i], phi[tpl[i]]) in adj for i in range(1, s))
        if ok:
            count += 1
    return count


def brute_force_unrooted(edges, n, tpl):
    """Exact template count: injective edge-respecting maps / |Aut(T)|."""
    adj = set()
    for a, b in edges:
        adj.add((a, b))
        adj.add((b, a))
    s = len(tpl)
    maps = 0
    for phi in itertools.permutations(range(n), s):
        if all((phi[i], phi[tpl[i]]) in adj for i in range(1, s)):
            maps += 1
    return maps / SG._count_automorphism_roots(tpl)


TINY_EDGES = [(0, 1), (1, 2), (2, 3), (3, 0), (0, 2), (4, 0), (5, 1), (4, 5)]
TINY_N = 8  # includes two isolated-ish vertices 6, 7


@pytest.mark.parametrize("tname", ["u3-path", "u3-star", "u5-path", "u5-star",
                                   "u5-tree"])
def test_dp_matches_brute_force_colorful(mesh, tname):
    tpl = SG.TEMPLATES[tname]
    s = len(tpl)
    rng = np.random.default_rng(0)
    colors = rng.integers(0, s, TINY_N).astype(np.int32)
    nbr, msk, overflow = SG.pad_csr(TINY_EDGES, TINY_N, 8)
    assert len(overflow) == 0
    o_nbr, o_row, o_msk = SG._partition_overflow(overflow, TINY_N,
                                                 mesh.num_workers)
    fn = SG.make_colorful_count_fn(tpl, s, mesh)
    out = float(np.asarray(fn(
        mesh.shard_array(nbr, 0), mesh.shard_array(msk, 0),
        mesh.shard_array(o_nbr, 0), mesh.shard_array(o_row, 0),
        mesh.shard_array(o_msk, 0),
        mesh.shard_array(colors[None, :], 1),   # [trials=1, n]
    ))[0])
    expect = brute_force_rooted_colorful(TINY_EDGES, TINY_N, tpl, colors)
    assert out == expect, (tname, out, expect)


@pytest.mark.parametrize("tname", ["u10-tree", "u12-tree"])
def test_deep_templates_exact_on_complete_graph(mesh, tname):
    """The 10/12-vertex templates (the deep end of the reference's
    ladder; 2^10/2^12 DP columns) against a CLOSED FORM no brute force
    can reach: on K_s with all-distinct colors, every injective map
    respects edges, so the rooted colorful count is exactly s!."""
    tpl = SG.TEMPLATES[tname]
    s = len(tpl)
    n = 16  # pad with isolated vertices so rows shard evenly over 8
    edges = [(a, b) for a in range(s) for b in range(a + 1, s)]
    colors = np.zeros(n, np.int32)
    colors[:s] = np.arange(s)  # distinct on K_s; isolated extras inert
    nbr, msk, overflow = SG.pad_csr(edges, n, s)
    assert len(overflow) == 0
    o_nbr, o_row, o_msk = SG._partition_overflow(overflow, n,
                                                 mesh.num_workers)
    fn = SG.make_colorful_count_fn(tpl, s, mesh)
    out = float(np.asarray(fn(
        mesh.shard_array(nbr, 0), mesh.shard_array(msk, 0),
        mesh.shard_array(o_nbr, 0), mesh.shard_array(o_row, 0),
        mesh.shard_array(o_msk, 0),
        mesh.shard_array(colors[None, :], 1),
    ))[0])
    assert out == math.factorial(s), (tname, out, math.factorial(s))


def test_automorphism_counts():
    assert SG._count_automorphism_roots(SG.TEMPLATES["u3-path"]) == 2   # path
    assert SG._count_automorphism_roots(SG.TEMPLATES["u3-star"]) == 2   # same tree
    assert SG._count_automorphism_roots(SG.TEMPLATES["u5-star"]) == 24  # 4! leaves
    assert SG._count_automorphism_roots(SG.TEMPLATES["u5-path"]) == 2


def test_estimator_unbiased_small(mesh):
    """Color-coding estimate over many trials ≈ exact count."""
    tpl = SG.TEMPLATES["u3-path"]
    exact = brute_force_unrooted(TINY_EDGES, TINY_N, tpl)
    cfg = SG.SubgraphConfig(template="u3-path", n_trials=200, seed=1, max_degree=8)
    est, trials, _ = SG.count_template(TINY_EDGES, TINY_N, cfg, mesh)
    assert exact > 0
    assert abs(est - exact) / exact < 0.2, (est, exact)


def test_degree_overflow_extracted_not_dropped():
    edges = [(0, i) for i in range(1, 7)]
    nbr, msk, overflow = SG.pad_csr(edges, 7, 4)
    assert len(overflow) == 2  # vertex 0 has degree 6, cap 4
    assert set(map(tuple, overflow)) == {(0, 5), (0, 6)}
    assert msk[0].sum() == 4  # dense path keeps the first cap entries


def test_low_degree_cap_exact_on_hub_graph(mesh):
    """A power-law-ish hub graph with max_degree far below the hub degree
    must count EXACTLY the same as an uncapped run — the overflow
    segment-sum path replaces the old truncation bias (round-1 VERDICT
    weak #4: dropped_edges biased estimates low)."""
    rng = np.random.default_rng(7)
    n = 40
    hub_edges = [(0, i) for i in range(1, n)]          # hub of degree 39
    rand_edges = [(int(a), int(b)) for a, b in
                  zip(rng.integers(1, n, 60), rng.integers(1, n, 60))]
    edges = hub_edges + rand_edges
    cfg_lo = SG.SubgraphConfig(template="u5-tree", n_trials=4, seed=5,
                               max_degree=4)
    cfg_hi = SG.SubgraphConfig(template="u5-tree", n_trials=4, seed=5,
                               max_degree=128)
    est_lo, trials_lo, ovf_lo = SG.count_template(edges, n, cfg_lo, mesh)
    est_hi, trials_hi, ovf_hi = SG.count_template(edges, n, cfg_hi, mesh)
    assert ovf_lo > 0 and ovf_hi == 0
    np.testing.assert_allclose(trials_lo, trials_hi, rtol=1e-5)


def test_u7_tree_runs_and_estimates(mesh):
    """The deepest template (u7-tree, 2^7 subset columns) runs end-to-end
    with batched trials and returns a sane nonnegative estimate."""
    rng = np.random.default_rng(3)
    n = 48
    edges = np.stack([rng.integers(0, n, 300), rng.integers(0, n, 300)], 1)
    est, trials, dropped = SG.count_template(
        edges, n, SG.SubgraphConfig(template="u7-tree", n_trials=3,
                                    trial_chunk=2, max_degree=24), mesh)
    assert len(trials) == 3 and np.isfinite(est) and est >= 0


def test_benchmark_powerlaw_graph(mesh):
    """The graded-scale graph generator (VERDICT r2 item 4): zipf-1.3
    sources concentrate edges on hubs, so the exact overflow path must
    carry real mass — overflow_share in (0, 1], nothing dropped, and the
    same seed reproduces the same graph (estimates match exactly)."""
    import pytest

    r1 = SG.benchmark(n_vertices=600, avg_degree=4, template="u3-path",
                      max_degree=4, graph="powerlaw", mesh=mesh, seed=7)
    r2 = SG.benchmark(n_vertices=600, avg_degree=4, template="u3-path",
                      max_degree=4, graph="powerlaw", mesh=mesh, seed=7)
    assert r1["dropped_edges"] == 0
    assert 0 < r1["overflow_share"] <= 1.0
    assert r1["overflow_edges"] == round(r1["overflow_share"] * 2 * 1200)
    assert r1["estimate"] == r2["estimate"]  # deterministic generation
    assert r1["graph"] == "powerlaw"
    # uniform graphs at the same degree stay under the cap far more often
    ru = SG.benchmark(n_vertices=600, avg_degree=4, template="u3-path",
                      max_degree=4, graph="uniform", mesh=mesh, seed=7)
    assert ru["overflow_share"] < r1["overflow_share"]
    with pytest.raises(ValueError, match="graph must be"):
        SG.benchmark(n_vertices=100, graph="smallworld", mesh=mesh)


def test_overflow_onehot_matches_segment(mesh):
    """The two exact overflow tails are the same math on different
    hardware paths: per-trial counts must agree to f32 tolerance on a
    hub-heavy graph where most adjacency rides the tail."""
    rng = np.random.default_rng(9)
    n = 64
    hub_edges = [(0, i) for i in range(1, n)]       # degree-63 hub
    hub2 = [(1, i) for i in range(2, 40)]           # second hub
    rand = [(int(a), int(b)) for a, b in
            zip(rng.integers(0, n, 120), rng.integers(0, n, 120))]
    edges = hub_edges + hub2 + rand
    res = {}
    for algo in ("segment", "onehot"):
        cfg = SG.SubgraphConfig(template="u5-tree", n_trials=4, seed=5,
                                max_degree=4, overflow_algo=algo,
                                overflow_row_tile=8,
                                overflow_entry_tile=16)
        est, trials, ovf = SG.count_template(edges, n, cfg, mesh)
        assert ovf > 0  # the tail really carries mass
        res[algo] = trials
    np.testing.assert_allclose(res["onehot"], res["segment"], rtol=1e-5)


def test_overflow_tiles_partitioner_exact():
    """Host tiling invariants: every overflow entry lands in exactly one
    tile slot, offsets stay inside the row window, padding is masked."""
    rng = np.random.default_rng(3)
    n_pad, nw, row_tile, entry_tile = 32, 4, 8, 4
    m = 37
    overflow = np.stack([rng.integers(0, n_pad, m),
                         rng.integers(0, n_pad, m)], 1).astype(np.int64)
    t_nbr, t_loc, t_msk, t_lo = SG._partition_overflow_tiles(
        overflow, n_pad, nw, row_tile, entry_tile)
    assert (t_msk.sum() == m)                     # every entry, once
    live = t_msk.reshape(-1) > 0
    assert (t_loc.reshape(-1)[live] < row_tile).all()
    assert (t_loc.reshape(-1)[~live] == row_tile).all()  # pad → zero row
    # reconstruct (local_row, nbr) multiset and compare with the input
    loc_rows = n_pad // nw
    NT = t_lo.shape[0] // nw
    rec = []
    for wt in range(nw * NT):
        w = wt // NT
        for e in range(t_nbr.shape[1]):
            if t_msk[wt, e] > 0:
                rec.append((w * loc_rows + t_lo[wt] + t_loc[wt, e],
                            t_nbr[wt, e]))
    want = sorted((int(r), int(c)) for r, c in overflow)
    assert sorted(rec) == want


def test_overflow_algo_validation():
    import pytest

    with pytest.raises(ValueError, match="overflow_algo"):
        SG.SubgraphConfig(overflow_algo="scatter")


@pytest.mark.parametrize("tname,k", [("u3-path", 5), ("u5-tree", 7)])
def test_dp_matches_brute_force_extra_colors(mesh, tname, k):
    """k > template size: the compact root table's support is ALL size-s
    subsets, summed — the branch the compact-table rewrite folded into
    one sum(-1); guards the support/ordering invariant it relies on."""
    tpl = SG.TEMPLATES[tname]
    rng = np.random.default_rng(2)
    colors = rng.integers(0, k, TINY_N).astype(np.int32)
    nbr, msk, overflow = SG.pad_csr(TINY_EDGES, TINY_N, 8)
    assert len(overflow) == 0
    o_nbr, o_row, o_msk = SG._partition_overflow(overflow, TINY_N,
                                                 mesh.num_workers)
    fn = SG.make_colorful_count_fn(tpl, k, mesh)
    out = float(np.asarray(fn(
        mesh.shard_array(nbr, 0), mesh.shard_array(msk, 0),
        mesh.shard_array(o_nbr, 0), mesh.shard_array(o_row, 0),
        mesh.shard_array(o_msk, 0),
        mesh.shard_array(colors[None, :], 1),
    ))[0])
    expect = brute_force_rooted_colorful(TINY_EDGES, TINY_N, tpl, colors)
    assert out == expect, (tname, k, out, expect)
