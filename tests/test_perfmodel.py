"""The predictive performance observatory (harp_tpu/perfmodel, PR 13).

Four contracts, all tier-1:

1. **Self-grading passes on the committed evidence** — the model's
   ranking agrees with every BENCH_local / FLIP_DECISIONS pair and
   SWEEP_pallas sweep it can price (a model edit that drifts from the
   measurements fails HERE, before it can mis-prune a relay sprint).
2. **Exported rows are invariant-12 evidence** — kind:"model" rows
   round-trip through scripts/check_jsonl.py, and the frozen
   vocabularies stay in sync.
3. **The kernel registry prices without fallbacks** — every registered
   kernel declares its work model, and the VMEM pre-sizer reproduces
   the tiles the 2026-08-01 window calibrated by hand.
4. **Sprint pruning respects the gates** — measure_all --predicted-top
   can never drop a JOINT/EXCLUSIVE partner or CONDITIONAL anchor its
   selection depends on (flip_decision's own tables are the source).
"""

import importlib.util
import json
import os
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "scripts"))

import check_jsonl  # noqa: E402
import flip_decision  # noqa: E402

from harp_tpu import perfmodel  # noqa: E402
from harp_tpu.perfmodel import grade as G  # noqa: E402
from harp_tpu.perfmodel import model as M  # noqa: E402


def _load_measure_all():
    spec = importlib.util.spec_from_file_location(
        "measure_all_pm", os.path.join(ROOT, "scripts", "measure_all.py"))
    ma = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ma)
    return ma


# -- 1. self-grading against the committed evidence -------------------------

def test_grading_passes_on_committed_evidence():
    """THE honesty gate: replay the model against every committed row
    it can price.  A disagreement ships the term breakdown in the
    failure, so a wrong prediction is diagnosable from the test log."""
    report = G.grade(ROOT)
    assert report["ok"], json.dumps(report["failures"], indent=2)
    # the evidence is rich enough to be a real gate, not a vacuous one:
    agreed = [p for p in report["pairs"] if p["status"] == "agrees"]
    assert len(agreed) >= 5, report["pairs"]
    assert len(report["sweeps"]) >= 3
    assert all(s["rho"] >= G.RANK_FLOOR for s in report["sweeps"])
    assert len(report["magnitude"]) >= 15  # priced committed rows


def test_grading_catches_an_inverted_model(monkeypatch):
    """Sabotage: invert one family's mechanism term (pretend the dense
    one-hot traffic is free) — the measured mfsgd_pallas FLIP must now
    disagree and flip ok to False (fail closed, like invariants 1-11)."""
    real = M.price

    def sabotaged(config, row=None, topo=None):
        p = real(config, row, topo)
        if config == "mfsgd":
            # dense suddenly prices as fast as the kernel
            return M.Price(p.config, p.metric, p.compute_s, 1e-12,
                           p.wire_s, p.overhead_s)
        return p

    monkeypatch.setattr(G, "price", sabotaged)
    report = G.grade(ROOT)
    assert not report["ok"]
    assert any("mfsgd_pallas" in f["what"] for f in report["failures"])


def test_measured_flips_are_never_predicted_losers():
    """Every measured FLIP verdict the model can price must be
    predicted at least even — pruning must never have dropped a
    measured winner (the costly failure mode)."""
    verdicts = G.flip_verdicts(os.path.join(ROOT, "FLIP_DECISIONS.jsonl"))
    bench = G.latest_tpu_rows(os.path.join(ROOT, "BENCH_local.jsonl"))
    checked = 0
    for name, v in verdicts.items():
        if not v.get("flip") or name not in M.CONFIG_MODELS:
            continue
        inc = G.FAMILY_PAIRS[name][0]
        shape = bench.get(inc)
        ratio = (M.price(inc, shape).predicted_s
                 / M.price(name, shape).predicted_s)
        assert ratio >= 1.0, (name, ratio)
        checked += 1
    assert checked >= 4  # mfsgd_pallas, lda_fast, lda_pallas, carry, fused


def test_sweep_points_match_their_committed_file():
    loaded = G.load_sweep_points(ROOT)
    assert loaded["errors"] == []


def test_family_pairs_mirror_flip_decision():
    """The grading table and flip_decision.CANDIDATES must tell one
    story about who competes with whom (and on which metric)."""
    for cand, (inc, metric, fb) in G.FAMILY_PAIRS.items():
        spec = flip_decision.CANDIDATES[cand]
        assert spec["incumbent"] == inc, cand
        assert spec["metric"] == metric, cand
        assert spec.get("metric_fallback") == fb, cand


def test_spearman():
    assert G.spearman([1, 2, 3], [10, 20, 30]) == 1.0
    assert G.spearman([1, 2, 3], [30, 20, 10]) == -1.0
    assert abs(G.spearman([1, 2, 3, 4], [1, 2, 4, 3]) - 0.8) < 1e-9


# -- 2. model rows through the checker --------------------------------------

def _topo():
    from harp_tpu.plan.topology import v4_32

    return v4_32()


def test_config_model_rows_are_invariant_12_clean(tmp_path):
    from harp_tpu.utils.flightrec import provenance_stamp

    p = tmp_path / "rows.jsonl"
    with open(p, "w") as f:
        for cfg in sorted(M.CONFIG_MODELS):
            row = M.model_row(M.price(cfg, None, _topo()), _topo(),
                              config=cfg)
            f.write(json.dumps({**row, **provenance_stamp()}) + "\n")
    assert check_jsonl.check_file(str(p)) == []


def test_program_row_from_a_sheet_is_invariant_12_clean(tmp_path):
    from harp_tpu.utils.flightrec import provenance_stamp

    sheet = {"collectives": [
        {"site": "kmeans.py:346", "primitive": "psum",
         "per_shard_bytes": 2120, "amplification": 2}]}
    price = M.price_sheet("kmeans.fit", sheet, _topo())
    assert price.wire_s > 0          # v4_32 has a real wire
    row = M.model_row(price, _topo(), program="kmeans.fit")
    assert row["configs"]            # the sprint configs that run it
    p = tmp_path / "rows.jsonl"
    p.write_text(json.dumps({**row, **provenance_stamp()}) + "\n")
    assert check_jsonl.check_file(str(p)) == []


def test_model_row_terms_sum_and_bound():
    row = M.model_row(M.price("lda", None, _topo()), _topo(),
                      config="lda")
    assert row["predicted_s"] > 0
    assert abs(sum(row["terms"].values()) - row["predicted_s"]) \
        <= 1e-9 * row["predicted_s"]
    assert row["bound"] == max(M.BOUNDS,
                               key=lambda b: row["terms"][f"{b}_s"])


def test_vocabulary_and_sprint_sync():
    """Frozen vocab pins: perfmodel <-> check_jsonl <-> measure_all."""
    ma = _load_measure_all()
    assert tuple(perfmodel.BOUNDS) == check_jsonl.KNOWN_MODEL_BOUNDS
    assert tuple(perfmodel.RATES_SOURCES) == \
        check_jsonl.KNOWN_MODEL_RATES_SOURCES
    assert set(check_jsonl.KNOWN_MODEL_CONFIGS) == set(ma.SPRINT_ORDER)
    # every priced config and every program-mapped config is runnable
    assert set(M.CONFIG_MODELS) <= set(ma.SPRINT_ORDER)
    for prog, cfgs in M.PROGRAM_CONFIGS.items():
        assert prog in check_jsonl.KNOWN_LINT_PROGRAMS, prog
        assert set(cfgs) <= set(ma.SPRINT_ORDER), prog
    # and the drivers registry maps completely (a new byte-sheeted
    # program must state its sprint configs, even as an explicit ())
    from harp_tpu.analysis.drivers import DRIVERS

    assert set(M.PROGRAM_CONFIGS) == set(DRIVERS)


def test_unpriceable_config_raises_keyerror():
    # subgraph became priceable in PR 16; kmeans_ingest (relay-tunnel
    # bound, priced by bench_ingest itself) remains deliberately out
    with pytest.raises(KeyError, match="unpriceable"):
        M.price("kmeans_ingest", None, _topo())


def test_wire_cost_is_the_planner_cost():
    """One wire oracle: the planner's site cost and the model's wire
    term are the same function (the Plan rows' cost column re-pointed
    at the shared model, PR 13)."""
    from harp_tpu.plan import planner

    topo = _topo()
    for sched in planner.SCHEDULES:
        for b in (0, 1, 1024, 999_983):
            assert planner._site_cost(topo, "psum", sched, b) == \
                M.wire_cost_s(topo, "psum", sched, b), (sched, b)


# -- 3. kernel registry work models + the VMEM pre-sizer --------------------

def test_every_registered_kernel_prices_without_fallback():
    """A kernel in KERNELS without a work model cannot exist (the
    registration signature requires the fields); this pins the other
    half: the declared numbers are sane (positive, VMEM under the
    16 MiB ceiling) for every entry — loudly, at lint/test time."""
    from harp_tpu.ops.kernel_registry import KERNEL_WORK, KERNELS

    assert set(KERNEL_WORK) == set(KERNELS)
    for name, work in KERNEL_WORK.items():
        for field in ("flops", "min_hbm_bytes", "vmem_bytes"):
            v = work[field]
            assert isinstance(v, int) and v > 0, (name, field, v)
        assert work["vmem_bytes"] <= 16 << 20, name


def test_registering_without_a_work_model_fails_loudly():
    from harp_tpu.ops.kernel_registry import register_kernel

    with pytest.raises(TypeError):
        register_kernel("bogus.kernel")(lambda: None)  # no work fields
    with pytest.raises(ValueError, match="work field"):
        register_kernel("bogus.kernel", flops=0, min_hbm_bytes=1,
                        vmem_bytes=1)(lambda: None)
    from harp_tpu.ops.kernel_registry import KERNELS

    assert "bogus.kernel" not in KERNELS


def test_presizer_reproduces_the_oom_calibrated_int8_tile():
    """The 2026-08-01 window found 8000 rows by OOM-probing on silicon;
    the pre-sizer must reproduce it offline from the kernel's own
    calibrated byte model (graded shape 1M x 300, k=100)."""
    out = perfmodel.presize("kmeans.partials_int8",
                            n=1_000_000, d=300, k=100)
    assert out["tile"] == 8000, out


def test_presizer_picks_the_swept_mfsgd_tile():
    """256x256 measured fastest (SWEEP_pallas 2026-08-01); the
    pre-sizer must pick it from the model, not from 'largest fits'
    (512 and 1024 fit VMEM too — and measured slower)."""
    out = perfmodel.presize("mfsgd.sgd_tile_update",
                            rank=64, n_items=26_744)
    assert out["tile"] == 256, out
    assert set(out["fits"]) >= {256, 512, 1024}


def test_presizer_refuses_an_unbudgeted_kernel():
    with pytest.raises(KeyError, match="pre-size"):
        perfmodel.presize("made.up_kernel")


def test_presizer_reports_vmem_wall():
    out = perfmodel.presize("mfsgd.sgd_tile_update",
                            rank=256, i_shard=200_000)
    assert out["tile"] is None and "budget" in out["reason"]


# -- PR 17: the three newly kernelized arms are presized OFFLINE (no
# silicon evidence yet) — these pins are the tiles the sprint will try
# FIRST, and the ranking rationale in the config comments cites them.

def test_presizer_picks_the_svm_sample_tile():
    """Whole-d resident w/x-tile: the grid-overhead term is monotone in
    1/tn, so the largest VMEM-fitting sample tile must win (8192 at the
    graded 500k x 128 f32 shape)."""
    out = perfmodel.presize("svm.kernel_row", n=500_000, d=128)
    assert out["tile"] == 8192, out
    assert set(out["fits"]) >= {8192, 4096, 2048}


def test_presizer_picks_the_wdamds_column_tile():
    """X (all N rows) stays resident; the column tile only bounds the
    delta/dist working set — largest fitting tile (128 at the graded
    4096-point shape) wins on the same 1/tn overhead argument."""
    out = perfmodel.presize("wdamds.smacof_dist",
                            n=4096, num_workers=8, dim=3)
    assert out["tile"] == 128, out
    assert set(out["fits"]) >= {128, 64, 32}


def test_presizer_reports_wdamds_vmem_wall():
    """At 200k points the resident [N, dim] + [tn, N] blocks cannot fit
    any lane-aligned tile — the pre-sizer must say so offline instead of
    letting the sprint discover it as a Mosaic OOM."""
    out = perfmodel.presize("wdamds.smacof_dist",
                            n=200_000, num_workers=8, dim=3)
    assert out["tile"] is None and "budget" in out["reason"]


def test_presizer_picks_the_rf_row_tile():
    out = perfmodel.presize("rf.hist_bins", n=200_000, f=64, n_bins=32,
                            n_classes=2, depth=6, num_workers=8)
    assert out["tile"] == 2048, out
    assert set(out["fits"]) >= {2048, 1024, 512}


# -- 4. sprint pruning respects the gates -----------------------------------

def test_gate_closure_never_drops_a_partner():
    """For EVERY candidate: selecting it alone must pull in all its
    JOINT partners, EXCLUSIVE partners, and CONDITIONAL anchors
    (recursively) — reusing flip_decision's own gate tables, so a new
    gate is automatically honored here."""
    ma = _load_measure_all()
    for cand in flip_decision.CANDIDATES:
        closed = ma.gate_closure({cand})
        for group in (flip_decision.JOINT_GATES
                      + flip_decision.EXCLUSIVE_GATES):
            if closed & set(group):
                assert set(group) <= closed, (cand, group)
        for name, (_, anchor) in flip_decision.CONDITIONAL_GATES.items():
            if name in closed:
                assert anchor in closed, (cand, name)


def test_predicted_only_is_ordered_and_gate_closed():
    ma = _load_measure_all()
    only, ranked, unpriced = ma.predicted_only(3, "v4_32")
    assert only == [c for c in ma.SPRINT_ORDER if c in only]  # order
    assert set(only) == ma.gate_closure(c for c, _ in ranked[:3])
    # rankings are real speedups over the committed evidence shapes
    assert all(s > 0 for _, s in ranked)
    # unpriceable candidates are reported, not silently dropped
    assert set(unpriced) <= set(flip_decision.CANDIDATES)
    for cand in unpriced:
        assert cand not in M.CONFIG_MODELS or \
            G.FAMILY_PAIRS[cand][0] not in M.CONFIG_MODELS


def test_predicted_top_cli_dry_run_binds(capsys):
    """The argparse surface: --predicted-top computes an --only list
    and --dry-run prints it without importing jax or benchmarking."""
    ma = _load_measure_all()
    ma.main(["--predicted-top", "2", "--dry-run", "--topology",
             "sim_ring_8"])
    out = capsys.readouterr()
    sel = json.loads(out.out.strip().splitlines()[-1])
    assert sel["dry_run"] is True
    meta = json.loads(out.err.strip().splitlines()[-1])
    assert meta["only"] == sel["would_run"]
    assert set(sel["would_run"]) == ma.gate_closure(
        c for c, _ in meta["ranking"][:2])


def test_predicted_top_conflicts_with_only():
    ma = _load_measure_all()
    with pytest.raises(SystemExit):
        ma.main(["--predicted-top", "2", "--only", "kmeans",
                 "--dry-run"])
