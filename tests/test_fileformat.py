"""L4 input-format tests — edu.iu.fileformat parity (SURVEY.md §3.1)."""

import numpy as np
import pytest

from harp_tpu import fileformat as ff


def _write(tmp_path, name, rows):
    p = tmp_path / name
    p.write_text("\n".join(",".join(str(v) for v in r) for r in rows) + "\n")
    return str(p)


def test_multi_file_splits_balanced_by_size(tmp_path):
    paths = []
    for i, n in enumerate([100, 1, 1, 1]):
        paths.append(_write(tmp_path, f"f{i}.csv", [[j, j] for j in range(n)]))
    splits = ff.multi_file_splits(paths, 2)
    assert len(splits) == 2
    assert sorted(sum(splits, [])) == sorted(paths)
    # the big file's worker should not also get all the small ones
    sizes = [sum(len(open(p).read()) for p in s) for s in splits]
    assert max(sizes) < sum(sizes)


def test_multi_file_splits_more_workers_than_files(tmp_path):
    p = _write(tmp_path, "only.csv", [[1, 2]])
    splits = ff.multi_file_splits([p], 4)
    assert sum(len(s) for s in splits) == 1
    assert len(splits) == 4


def test_single_file_splits_requires_match(tmp_path):
    ps = [_write(tmp_path, f"f{i}.csv", [[i]]) for i in range(3)]
    assert ff.single_file_splits(ps, 3) == [[p] for p in ps]
    with pytest.raises(ValueError):
        ff.single_file_splits(ps, 4)


def test_load_sharded_csv_roundtrip(tmp_path, mesh):
    rng = np.random.default_rng(0)
    all_rows = []
    paths = []
    for i in range(5):  # 5 files, uneven rows, over 8 workers
        rows = rng.normal(size=(3 + 2 * i, 4)).round(3)
        all_rows.append(rows)
        paths.append(_write(tmp_path, f"part{i}.csv", rows.tolist()))
    stacked, counts = ff.load_sharded_csv(str(tmp_path), mesh.num_workers)
    assert counts.sum() == sum(r.shape[0] for r in all_rows)
    rows_pad = stacked.shape[0] // mesh.num_workers
    assert rows_pad == counts.max()
    # every real row present exactly once; padding is zeros
    real = np.concatenate([
        stacked[w * rows_pad: w * rows_pad + c] for w, c in enumerate(counts)])
    want = np.concatenate(all_rows).astype(np.float32)
    got = sorted(map(tuple, real.round(3).tolist()))
    assert got == sorted(map(tuple, want.round(3).tolist()))
    # shardable on the mesh
    arr = mesh.shard_array(stacked)
    assert arr.shape == stacked.shape


def test_load_sharded_triples(tmp_path, mesh):
    lines = [(u, u % 3, float(u) / 2) for u in range(11)]
    for i in range(3):
        _write(tmp_path, f"r{i}.txt", [list(t) for t in lines[i::3]])
    (u, i_, v), counts = ff.load_sharded_triples(str(tmp_path), 4)
    assert counts.sum() == 11
    mask = u >= 0
    assert mask.sum() == 11
    got = sorted(zip(u[mask].tolist(), i_[mask].tolist(), v[mask].tolist()))
    assert got == sorted(lines)
    # padding convention: u = i = -1, v = 0
    assert np.all(i_[~mask] == -1) and np.all(v[~mask] == 0)


def test_load_sharded_csv_missing(tmp_path):
    with pytest.raises(FileNotFoundError):
        ff.load_sharded_csv(str(tmp_path / "nope*.csv"), 2)
