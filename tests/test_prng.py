"""utils/prng — raw key bits: bit-exact vs PRNGKey, no per-seed compiles.

The CLAUDE.md relay trap this pins: ``jax.random.PRNGKey(python_int)``
specializes on the int, so every fresh seed in a hot path paid a fresh
(~140 ms remote) compile.  The helper must be (a) bit-identical to
``PRNGKey``/``split(PRNGKey(...))`` — drivers switched to it mid-history,
so checkpointed RNG chains must resume unchanged — and (b) free of any
compile once the shape-specialized split program is warm.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from harp_tpu.utils import flightrec, prng, telemetry

# negative seeds follow two's complement; >32-bit seeds truncate in x32
# mode (the repo default) exactly like PRNGKey does
SEEDS = [0, 1, 42, 7_777_777, 2**31 - 1, -1, -5, 2**40 + 7]


@pytest.mark.parametrize("seed", SEEDS)
def test_key_bits_matches_prngkey(seed):
    assert np.array_equal(prng.key_bits(seed),
                          np.asarray(jax.random.PRNGKey(seed))), seed


@pytest.mark.parametrize("seed", [0, 3, -2, 2**40 + 7])
def test_split_keys_matches_split_of_prngkey(seed):
    want = np.asarray(jax.random.split(jax.random.PRNGKey(seed), 8))
    assert np.array_equal(prng.split_keys(seed, 8), want), seed


def test_key_bits_draws_match_typed_key():
    """normal() from the raw bits equals normal() from jax.random.key —
    the drivers that switched from typed keys (kmeans/mfsgd benchmark
    data generation) produce byte-identical datasets."""
    raw = jax.random.normal(jnp.asarray(prng.key_bits(9)), (16,))
    typed = jax.random.normal(jax.random.key(9), (16,))
    assert np.array_equal(np.asarray(raw), np.asarray(typed))


def test_split_keys_does_not_recompile_across_seeds(mesh):
    """The regression the helper exists for: after one warm call, new
    seeds must be compile-free (CompileWatch counts XLA backend
    compiles — the same counter the relay pays ~140 ms per tick on)."""
    if not flightrec.COMPILE_EVENTS_AVAILABLE:
        pytest.skip("this jax lacks the monitoring hook")
    with telemetry.scope():
        prng.split_keys(123, 8)  # warm: the one shape-keyed compile
        before = flightrec.compile_watch.count
        for seed in range(200, 220):
            prng.split_keys(seed, 8)
        assert flightrec.compile_watch.count == before, \
            "split_keys recompiled on a fresh seed"
