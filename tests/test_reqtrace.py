"""Request-level tracing (PR 12, harp_tpu/utils/reqtrace.py).

Pins, in order: the streaming log-bucket histograms' documented quantile
error and rolling expiry; zero-cost-when-disabled (the PR-3 contract);
complete span trees through the continuous serve plane (admission →
batch membership → dispatch → readback → outcome) with the flagship
per-batch budgets UNCHANGED while tracing is armed; the acceptance
criterion — a CPU-sim ``benchmark_sustained`` run under telemetry with
injected faults yields a Perfetto-loadable timeline whose request-span
outcomes reconcile EXACTLY with the invariant-9 row and whose
rolling-window p99 agrees with the exact percentile within the
documented bucket error; and the TCP plane's arrival-minted ids.
"""

import json
import math
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "scripts"))

import check_jsonl  # noqa: E402
from harp_tpu.serve.engines import ENGINES  # noqa: E402
from harp_tpu.serve.server import Server  # noqa: E402
from harp_tpu.utils import reqtrace, telemetry  # noqa: E402
from harp_tpu.utils.reqtrace import (LogHist, QUANTILE_REL_ERR,  # noqa: E402
                                     RollingWindow)


# ---------------------------------------------------------------------------
# Streaming histograms
# ---------------------------------------------------------------------------

def _rank_pct(xs, p):
    arr = sorted(xs)
    return arr[max(1, math.ceil(p / 100 * len(arr))) - 1]


def test_loghist_quantiles_within_documented_bucket_error():
    """The bound callers rely on: every quantile read is within
    QUANTILE_REL_ERR of the exact ceil-rank sample percentile, across
    three orders of magnitude of lognormal latencies."""
    rng = np.random.default_rng(0)
    xs = np.exp(rng.normal(1.0, 1.2, size=5000))  # ~0.1 .. ~100 ms
    h = LogHist()
    for v in xs:
        h.add(float(v))
    assert h.total == 5000
    for p in (10, 50, 90, 95, 99, 99.9):
        exact = _rank_pct(xs, p)
        got = h.quantile(p)
        assert abs(got - exact) <= QUANTILE_REL_ERR * exact, (p, got,
                                                             exact)


def test_loghist_zeros_and_empty():
    h = LogHist(lo=0.5)  # queue-depth shape: 0 is a real sample
    assert h.quantile(50) is None
    for v in (0, 0, 0, 4):
        h.add(v)
    assert h.quantile(50) == 0.0
    assert h.quantile(99) == pytest.approx(4.0, rel=QUANTILE_REL_ERR)


def test_loghist_memory_is_fixed():
    h = LogHist()
    for v in np.random.default_rng(1).exponential(5.0, size=20000):
        h.add(float(v))
    assert len(h.counts) == h.n + 1  # no retained samples, ever


def test_rolling_window_expires_old_samples():
    w = RollingWindow(window_s=6.0, subwindows=3)
    for t in (0.1, 0.2, 0.3):
        w.add_latency(t, 1000.0)  # old: 1 s latencies
    w.add_latency(10.0, 1.0)      # recent: 1 ms
    snap = w.snapshot(10.0)
    assert snap["samples"] == 1   # the 1 s samples expired with their
    assert snap["p99_ms"] == pytest.approx(1.0, rel=QUANTILE_REL_ERR)
    # sub-windows; only the live one remains
    assert snap["rel_err"] == round(QUANTILE_REL_ERR, 4)


# ---------------------------------------------------------------------------
# Zero-cost when disabled (PR-3 contract)
# ---------------------------------------------------------------------------

def test_tracer_is_zero_cost_when_disabled():
    with telemetry.scope(False):
        assert reqtrace.tracer.begin(0.0) is None
        reqtrace.tracer.event(1, "x", 0.0)
        reqtrace.tracer.end(1, "served", 0.0)
        reqtrace.tracer.batch(0, 0.0, rung=8, rows=3, members=[])
        reqtrace.tracer.mark("fault", "x", 0.0)
        assert reqtrace.tracer.summary() == {
            "requests": 0, "open": 0, "batches": 0,
            "served": 0, "shed": 0, "failed": 0}
        assert reqtrace.tracer.rows() == []


def test_untraced_continuous_run_records_nothing(mesh, tmp_path):
    """With telemetry off the serve plane runs exactly as before —
    no spans, no ids, no marks (begin returns None end to end)."""
    rng = np.random.default_rng(5)
    srv = Server("kmeans",
                 state=ENGINES["kmeans"].synthetic_state(rng, k=4, d=8),
                 mesh=mesh, ladder=(1, 8),
                 cache_dir=str(tmp_path / "aot"))
    srv.startup()
    r = srv.make_runner()
    r.submit(0, {"id": 0, "x": rng.normal(size=(3, 8)).tolist()},
             now=0.0)
    out = r.drain(0.01)
    assert len(out) == 1 and "result" in out[0][1]
    assert reqtrace.tracer.summary()["requests"] == 0


# ---------------------------------------------------------------------------
# Complete span trees + budgets unchanged with tracing armed
# ---------------------------------------------------------------------------

def test_continuous_plane_traces_complete_span_trees(mesh, tmp_path):
    """Every admitted request's span walks arrival → admit → batch →
    served with its batch membership recorded, batches carry dispatch
    <= readback, and the flagship per-batch budgets hold EXACTLY (one
    dispatch + one readback per batch, zero compiles) with tracing
    armed — tracing is host-side bookkeeping, never device work."""
    with telemetry.scope(True):
        rng = np.random.default_rng(6)
        srv = Server("kmeans",
                     state=ENGINES["kmeans"].synthetic_state(rng, k=4,
                                                             d=8),
                     mesh=mesh, ladder=(1, 8),
                     cache_dir=str(tmp_path / "aot"))
        srv.startup()
        srv.steady.reset()
        r = srv.make_runner(depth=2)
        t = 0.0
        for i in range(6):
            r.submit(i, {"id": i,
                         "x": rng.normal(size=(2, 8)).tolist()}, now=t)
            t += 0.001
            r.step(t)
        out = r.drain(t + 0.01)
        assert r.completed == 6
        r.verify_exact()  # budgets pinned with tracing ARMED

        tr = reqtrace.tracer
        assert tr.counts == {"served": 6, "shed": 0, "failed": 0}
        assert tr.summary()["open"] == 0  # every span terminated
        rows = tr.rows()
        ts = [row["ts"] for row in rows]
        assert ts == sorted(ts)  # causally ordered by construction
        # the request→batch join: every request's batch event names a
        # batch whose member list names it back
        batches = {row["seq"]: row for row in rows
                   if row["ev"] == "batch"}
        assert batches and len(batches) == r.dispatched
        for row in rows:
            if row["ev"] == "event" and row["name"] == "batch":
                b = batches[row["seq"]]
                assert any(m[0] == row["req"] for m in b["members"])
        for b in batches.values():
            evs = {e["name"]: e["ts"] for e in b["events"]}
            assert evs["form"] <= evs["dispatch"] <= evs["readback"]
            assert 0.0 <= b["padding_frac"] < 1.0


def test_deadline_shed_and_failure_spans_terminate(mesh, tmp_path):
    """The degraded paths terminate spans too: queue_full and deadline
    sheds end 'shed', exhausted retries end 'failed' with the batch's
    engine_failure event alongside."""
    from harp_tpu.utils.fault import FaultInjector

    with telemetry.scope(True):
        rng = np.random.default_rng(7)
        srv = Server("kmeans",
                     state=ENGINES["kmeans"].synthetic_state(rng, k=4,
                                                             d=8),
                     mesh=mesh, ladder=(1, 8),
                     cache_dir=str(tmp_path / "aot"))
        srv.startup()
        r = srv.make_runner(max_queue_rows=4, deadline_s=0.01,
                            max_retries=0)
        x = rng.normal(size=(3, 8)).tolist()
        r.submit("ok", {"id": "ok", "x": x}, now=0.0)
        out = r.submit("full", {"id": "full", "x": x}, now=0.001)
        assert out and out[0][1]["reason"] == "queue_full"
        # kill the only dispatch: retries exhausted -> engine failure
        inj = FaultInjector(seed=0, fail={"dispatch": (1,)})
        with inj.arm():
            r.step(0.002)
        assert r.engine_failures == 1
        tr = reqtrace.tracer
        assert tr.counts == {"served": 0, "shed": 1, "failed": 1}
        assert tr.batch_event_count("engine_failure") == 1
        # the injector's mark rode the unified timeline
        assert any(m["source"] == "fault" and m["site"] == "dispatch"
                   for m in tr.marks)


# ---------------------------------------------------------------------------
# The acceptance bench: chaos trace completeness + streaming percentiles
# ---------------------------------------------------------------------------

def _sustained_with_faults():
    from harp_tpu.serve.bench import benchmark_sustained

    return benchmark_sustained(
        app="kmeans", n_requests=96, rows_per_request=1, burst_admit=8,
        ladder=(1, 8, 32), state_shape={"k": 8, "d": 16},
        fault_rate=0.01, fault_seed=34,  # seed 34: a fault fires early
        deadline_ms=10_000.0, max_queue_rows=4096, max_retries=3)


def test_sustained_trace_reconciles_with_invariant9_ledger(mesh,
                                                           tmp_path):
    """THE acceptance pin: a sustained CPU-sim run under telemetry with
    injected faults yields (a) a trace whose shed/retry/failure events
    sum to the row's shed_frac / fault_retries / engine_failures
    EXACTLY, (b) rolling-window percentiles within the documented
    bucket error of the exact same-sample percentiles, (c) the flagship
    budgets pinned unchanged, and (d) a Perfetto-loadable, invariant-11
    clean timeline file."""
    with telemetry.scope(True):
        res = _sustained_with_faults()
        tr = reqtrace.tracer

        # (a) exact reconciliation — every offered request has exactly
        # one terminated span, and the degraded counters match the
        # trace's own event counts
        assert res["faults_injected"] >= 1  # chaos actually ran
        assert tr.counts["served"] == res["served_requests"]
        assert tr.counts["shed"] == res["shed_requests"]
        assert tr.counts["failed"] == res["failed_requests"]
        assert (tr.counts["served"] + tr.counts["shed"]
                + tr.counts["failed"]) == res["offered_requests"]
        assert tr.summary()["open"] == 0
        assert tr.batch_event_count("retry") == res["fault_retries"]
        assert tr.batch_event_count("engine_failure") == \
            res["engine_failures"]
        assert round(tr.counts["shed"] / res["offered_requests"], 6) == \
            res["shed_frac"]
        assert sum(1 for m in tr.marks if m["source"] == "fault") == \
            res["faults_injected"]

        # (b) streaming vs exact percentiles: same samples, same clock,
        # agreement bounded by the documented bucket error
        assert res["win_samples"] == res["served_requests"]
        assert res["win_rel_err"] == round(QUANTILE_REL_ERR, 4)
        for p in (50, 95, 99):
            win, exact = res[f"win_p{p}_ms"], res[f"runner_p{p}_ms"]
            assert abs(win - exact) <= QUANTILE_REL_ERR * exact + 1e-9, p

        # (c) flagship budgets pinned with tracing armed.  The staging
        # budget (PR 14: one put_input per batch window) counts EXACTLY
        # the retried windows — each injected fault forced one restage,
        # which is the budget-drift evidence, not a broken pipeline
        assert res["steady_compiles"] == 0
        assert res["budget_violations"] <= res["fault_retries"]
        assert res["health_budget_drift"] == res["budget_violations"]
        assert res["steady_dispatches"] == res["batches"]
        assert res["steady_readbacks"] == res["batches"]

        # (d) the exported timeline is invariant-11 clean and loads as
        # a Perfetto trace next to its invariant-9 row
        p = tmp_path / "timeline.jsonl"
        telemetry.export_timeline(str(p))
    rows = [json.loads(ln) for ln in p.read_text().splitlines()]
    assert rows and all(r["kind"] == "trace" for r in rows)
    with open(p, "a") as fh:  # the run's own bench row joins the file
        fh.write(json.dumps({**res, "kind": "serve", "app": "kmeans",
                             "backend": "cpu", "date": "2026-08-05",
                             "commit": "test"}) + "\n")
    assert check_jsonl.check_file(str(p)) == []
    perf = reqtrace.perfetto(rows)
    json.dumps(perf)  # loadable = serializable + well-formed events
    assert any(e.get("ph") == "X" for e in perf["traceEvents"])
    assert any(e.get("ph") == "i" and "fault" in e["name"]
               for e in perf["traceEvents"])


def test_sustained_row_unchanged_with_tracing_disabled(mesh):
    """Tracing off (telemetry disabled): the sustained bench still
    balances its books and records no spans — the serve plane's
    behavior does not depend on the tracer's presence.  (The bench
    enables telemetry internally for its CompileWatch evidence, so this
    drives the runner directly.)"""
    with telemetry.scope(False):  # reset collectors, telemetry OFF
        rng = np.random.default_rng(8)
        srv = Server("kmeans",
                     state=ENGINES["kmeans"].synthetic_state(rng, k=4,
                                                             d=8),
                     mesh=mesh, ladder=(1, 8))
        srv.startup()
        r = srv.make_runner()
        for i in range(4):
            r.submit(i, {"id": i,
                         "x": rng.normal(size=(2, 8)).tolist()},
                     now=0.001 * i)
            r.step(0.001 * i + 0.0005)
        r.drain(1.0)
        assert r.completed == 4
        assert reqtrace.tracer.summary()["requests"] == 0


# ---------------------------------------------------------------------------
# Transport: ids minted at socket arrival, delivery closes the chain
# ---------------------------------------------------------------------------

def test_tcp_plane_mints_ids_at_arrival_and_stamps_delivery(mesh,
                                                            tmp_path):
    import socket

    from harp_tpu.serve.transport import TCPFrontEnd

    with telemetry.scope(True):
        rng = np.random.default_rng(9)
        state = ENGINES["kmeans"].synthetic_state(rng, k=4, d=8)
        srv = Server("kmeans", state=state, mesh=mesh, ladder=(1, 8),
                     cache_dir=str(tmp_path / "aot"),
                     budget_action="warn")
        srv.startup()
        fe = TCPFrontEnd(srv, port=0).start_in_thread()
        s = socket.create_connection(("127.0.0.1", fe.port), timeout=60)
        f = s.makefile("rw")
        x = rng.normal(size=(2, 8)).astype(np.float32)
        for i in range(2):
            f.write(json.dumps({"id": i, "x": x.tolist()}) + "\n")
        f.flush()
        got = [json.loads(f.readline()) for _ in range(2)]
        assert all("result" in g for g in got)
        fe.shutdown()
        fe.join(60)
        s.close()

        tr = reqtrace.tracer
        assert tr.counts["served"] == 2 and tr.summary()["open"] == 0
        rows = tr.rows()
        arrivals = [r for r in rows if r["ev"] == "event"
                    and r["name"] == "arrival"]
        assert len(arrivals) == 2
        assert all(r.get("transport") == "tcp" for r in arrivals)
        # delivery events landed after the spans served
        delivers = [r for r in rows if r["ev"] == "event"
                    and r["name"] == "deliver"]
        assert len(delivers) == 2
        # the runner's live stats carry the rolling window
        win = fe.runner.stats()["window"]
        assert win["rel_err"] == round(QUANTILE_REL_ERR, 4)
        assert win["samples"] >= 0


# ---------------------------------------------------------------------------
# The timeline merge + report section
# ---------------------------------------------------------------------------

def test_export_timeline_merges_spines_in_order(mesh, tmp_path):
    """Spans and fault marks fold into the trace timeline; aggregate
    spines (comm/transfer) ride summary rows at the tail; the whole
    file is monotone and invariant-11 clean."""
    with telemetry.scope(True):
        with telemetry.span("phase_a"):
            pass
        rid = reqtrace.tracer.begin(0.001)
        reqtrace.tracer.end(rid, "served", 0.002)
        p = tmp_path / "t.jsonl"
        telemetry.export_timeline(str(p))
    rows = [json.loads(ln) for ln in p.read_text().splitlines()]
    assert check_jsonl.check_file(str(p)) == []
    kinds = {(r["ev"], r.get("source")) for r in rows}
    assert ("mark", "span") in kinds
    assert ("request", None) in kinds
    ts = [r["ts"] for r in rows]
    assert ts == sorted(ts)


def test_report_carries_request_section(mesh):
    from harp_tpu import report

    with telemetry.scope(True):
        rid = reqtrace.tracer.begin(0.0)
        reqtrace.tracer.end(rid, "served", 0.003)
        rid2 = reqtrace.tracer.begin(0.001)
        reqtrace.tracer.end(rid2, "shed", 0.002)
        row, _ = report.live_report()
        text = report.render(row)
    assert row["requests"]["served"] == 1
    assert row["requests"]["shed"] == 1
    assert "requests (trace): 2 — 1 served / 1 shed / 0 failed" in text
