"""Classic-stats suite: every algorithm vs its numpy reference."""

import numpy as np
import pytest

from harp_tpu.models import stats as S


@pytest.fixture
def data():
    rng = np.random.default_rng(0)
    return rng.normal(size=(333, 12)).astype(np.float32)  # non-divisible rows


def test_moments(mesh, data):
    m = S.moments(data, mesh)
    assert m["n"] == 333
    np.testing.assert_allclose(m["mean"], data.mean(0), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(m["variance"], data.var(0), rtol=1e-3, atol=1e-5)
    np.testing.assert_allclose(m["min"], data.min(0), rtol=1e-6)
    np.testing.assert_allclose(m["max"], data.max(0), rtol=1e-6)


def test_covariance(mesh, data):
    mean, cov = S.covariance(data, mesh)
    np.testing.assert_allclose(mean, data.mean(0), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(cov, np.cov(data.T, bias=True), rtol=1e-3, atol=1e-4)


def test_pca(mesh, data):
    comps, ev = S.pca(data, n_components=3, mesh=mesh)
    cov = np.cov(data.T, bias=True)
    evals = np.sort(np.linalg.eigvalsh(cov))[::-1]
    np.testing.assert_allclose(ev, evals[:3], rtol=1e-3)
    # components are eigenvectors: cov @ v ≈ λ v
    for i in range(3):
        np.testing.assert_allclose(cov @ comps[i], ev[i] * comps[i],
                                   rtol=2e-2, atol=2e-3)


def test_naive_bayes(mesh):
    rng = np.random.default_rng(1)
    # multinomial-ish counts with class-dependent feature rates
    n, d, c = 400, 10, 3
    rates = rng.uniform(0.5, 3.0, size=(c, d))
    y = rng.integers(0, c, n).astype(np.int32)
    x = rng.poisson(rates[y]).astype(np.float32)
    model = S.naive_bayes_fit(x, y, c, mesh=mesh)
    pred = S.naive_bayes_predict(model, x)
    assert (pred == y).mean() > 0.7


def test_linear_regression(mesh):
    rng = np.random.default_rng(2)
    x = rng.normal(size=(500, 8)).astype(np.float32)
    true_beta = rng.normal(size=8).astype(np.float32)
    y = x @ true_beta + 2.5 + 0.01 * rng.normal(size=500).astype(np.float32)
    beta, intercept = S.linear_regression(x, y, mesh=mesh)
    np.testing.assert_allclose(beta, true_beta, atol=5e-3)
    assert abs(intercept - 2.5) < 1e-2


def test_ridge_shrinks(mesh):
    rng = np.random.default_rng(3)
    x = rng.normal(size=(64, 8)).astype(np.float32)
    y = x @ rng.normal(size=8).astype(np.float32)
    b0, _ = S.linear_regression(x, y, mesh=mesh)
    b1, _ = S.ridge_regression(x, y, l2=100.0, mesh=mesh)
    assert np.linalg.norm(b1) < np.linalg.norm(b0)


def test_tsqr(mesh):
    rng = np.random.default_rng(4)
    x = rng.normal(size=(256, 16)).astype(np.float32)
    q, r = S.tsqr(x, mesh)
    np.testing.assert_allclose(q @ r, x, rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(q.T @ q, np.eye(16), atol=1e-4)
    assert np.allclose(r, np.triu(r))  # R upper triangular


def test_svd(mesh):
    rng = np.random.default_rng(5)
    x = rng.normal(size=(256, 16)).astype(np.float32)
    u, s, vt = S.svd(x, mesh)
    np.testing.assert_allclose(u @ np.diag(s) @ vt, x, rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(s, np.linalg.svd(x, compute_uv=False), rtol=1e-4)


def test_als_converges(mesh):
    rng = np.random.default_rng(6)
    n_users, n_items, rank = 96, 40, 4
    Wt = rng.normal(size=(n_users, rank)).astype(np.float32)
    Ht = rng.normal(size=(n_items, rank)).astype(np.float32)
    u = rng.integers(0, n_users, 3000).astype(np.int32)
    i = rng.integers(0, n_items, 3000).astype(np.int32)
    v = (Wt[u] * Ht[i]).sum(-1).astype(np.float32)
    W, H, hist = S.als(u, i, v, n_users, n_items, rank=6, reg=0.01,
                       iters=8, mesh=mesh)
    assert hist[-1] < 0.2 * hist[0], hist
