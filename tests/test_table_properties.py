"""Property tests for KVTable: random op sequences vs a model dict.

The interesting invariant is the count-weighted AVG merge: merging
pre-combined tables in ANY grouping must equal combining all raw
contributions directly (associativity Harp's ValCombiner relies on).
"""

import numpy as np
from hypothesis import given, settings, strategies as st

from harp_tpu.parallel.collective import Combiner
from harp_tpu.table import KVTable, kv_allreduce

keys_st = st.integers(-4, 4)
vals_st = st.floats(-100, 100, allow_nan=False, allow_infinity=False,
                    width=32)
pairs_st = st.lists(st.tuples(keys_st, vals_st), min_size=1, max_size=40)
ops_st = st.sampled_from([Combiner.ADD, Combiner.MAX, Combiner.MIN,
                          Combiner.AVG])

_NUMPY_OP = {
    Combiner.ADD: np.sum,
    Combiner.MAX: np.max,
    Combiner.MIN: np.min,
    Combiner.AVG: np.mean,
}


@settings(max_examples=60, deadline=None)
@given(pairs=pairs_st, op=ops_st)
def test_kvtable_add_matches_numpy_reduction(pairs, op):
    t = KVTable(op, dtype=np.float64)
    model = {}
    for k, v in pairs:
        t.add(k, v)
        model.setdefault(k, []).append(v)
    assert t.keys() == sorted(model)
    for k, contributions in model.items():
        np.testing.assert_allclose(float(t.get(k)),
                                   _NUMPY_OP[op](contributions),
                                   rtol=1e-9, atol=1e-9)


@settings(max_examples=60, deadline=None)
@given(pairs=pairs_st, op=ops_st, n_splits=st.integers(1, 5))
def test_kv_merge_grouping_invariance(pairs, op, n_splits):
    """Splitting the contribution stream across worker tables and merging
    gives the same result as one table seeing every raw contribution."""
    direct = KVTable(op, dtype=np.float64)
    for k, v in pairs:
        direct.add(k, v)

    workers = [KVTable(op, dtype=np.float64) for _ in range(n_splits)]
    for i, (k, v) in enumerate(pairs):
        workers[i % n_splits].add(k, v)
    merged = kv_allreduce(workers[0], worker_tables=workers[1:])

    assert merged.keys() == direct.keys()
    for k in direct.keys():
        np.testing.assert_allclose(float(merged.get(k)), float(direct.get(k)),
                                   rtol=1e-9, atol=1e-9)


@settings(max_examples=30, deadline=None)
@given(pairs=pairs_st, op=ops_st)
def test_kvtable_array_roundtrip_preserves_state(pairs, op):
    """to_arrays → from_arrays(counts=...) reproduces values AND merge
    behavior (counts carry the AVG weights)."""
    t = KVTable(op, dtype=np.float64)
    for k, v in pairs:
        t.add(k, v)
    keys, vals, counts = t.to_arrays()
    t2 = KVTable.from_arrays(keys, vals, op, dtype=np.float64, counts=counts)
    for k in t.keys():
        np.testing.assert_allclose(float(t2.get(k)), float(t.get(k)))
    # the restored table must merge identically to the original
    other = KVTable(op, dtype=np.float64)
    other.add(0, 7.0)
    a = kv_allreduce(t, worker_tables=[other])
    b = kv_allreduce(t2, worker_tables=[other])
    for k in a.keys():
        np.testing.assert_allclose(float(a.get(k)), float(b.get(k)),
                                   rtol=1e-12)
