"""Property tests for KVTable: random op sequences vs a model dict.

The interesting invariant is the count-weighted AVG merge: merging
pre-combined tables in ANY grouping must equal combining all raw
contributions directly (associativity Harp's ValCombiner relies on).
"""

import numpy as np
import pytest

pytest.importorskip("hypothesis", reason="property tests need hypothesis")
from hypothesis import given, settings, strategies as st

from harp_tpu.parallel.collective import Combiner
from harp_tpu.table import KVTable, kv_allreduce

keys_st = st.integers(-4, 4)
vals_st = st.floats(-100, 100, allow_nan=False, allow_infinity=False,
                    width=32)
pairs_st = st.lists(st.tuples(keys_st, vals_st), min_size=1, max_size=40)
ops_st = st.sampled_from([Combiner.ADD, Combiner.MAX, Combiner.MIN,
                          Combiner.AVG])

_NUMPY_OP = {
    Combiner.ADD: np.sum,
    Combiner.MAX: np.max,
    Combiner.MIN: np.min,
    Combiner.AVG: np.mean,
}


@settings(max_examples=60, deadline=None)
@given(pairs=pairs_st, op=ops_st)
def test_kvtable_add_matches_numpy_reduction(pairs, op):
    t = KVTable(op, dtype=np.float64)
    model = {}
    for k, v in pairs:
        t.add(k, v)
        model.setdefault(k, []).append(v)
    assert t.keys() == sorted(model)
    for k, contributions in model.items():
        np.testing.assert_allclose(float(t.get(k)),
                                   _NUMPY_OP[op](contributions),
                                   rtol=1e-9, atol=1e-9)


@settings(max_examples=60, deadline=None)
@given(pairs=pairs_st, op=ops_st, n_splits=st.integers(1, 5))
def test_kv_merge_grouping_invariance(pairs, op, n_splits):
    """Splitting the contribution stream across worker tables and merging
    gives the same result as one table seeing every raw contribution."""
    direct = KVTable(op, dtype=np.float64)
    for k, v in pairs:
        direct.add(k, v)

    workers = [KVTable(op, dtype=np.float64) for _ in range(n_splits)]
    for i, (k, v) in enumerate(pairs):
        workers[i % n_splits].add(k, v)
    merged = kv_allreduce(workers[0], worker_tables=workers[1:])

    assert merged.keys() == direct.keys()
    for k in direct.keys():
        np.testing.assert_allclose(float(merged.get(k)), float(direct.get(k)),
                                   rtol=1e-9, atol=1e-9)


@settings(max_examples=30, deadline=None)
@given(pairs=pairs_st, op=ops_st)
def test_kvtable_array_roundtrip_preserves_state(pairs, op):
    """to_arrays → from_arrays(counts=...) reproduces values AND merge
    behavior (counts carry the AVG weights)."""
    t = KVTable(op, dtype=np.float64)
    for k, v in pairs:
        t.add(k, v)
    keys, vals, counts = t.to_arrays()
    t2 = KVTable.from_arrays(keys, vals, op, dtype=np.float64, counts=counts)
    for k in t.keys():
        np.testing.assert_allclose(float(t2.get(k)), float(t.get(k)))
    # the restored table must merge identically to the original
    other = KVTable(op, dtype=np.float64)
    other.add(0, 7.0)
    a = kv_allreduce(t, worker_tables=[other])
    b = kv_allreduce(t2, worker_tables=[other])
    for k in a.keys():
        np.testing.assert_allclose(float(a.get(k)), float(b.get(k)),
                                   rtol=1e-12)


# ---------------------------------------------------------------------------
# Sparse pull/push properties: random ids/capacities vs an exact numpy
# model INCLUDING the deterministic drop rule (per-(worker, owner)
# arrival order, capacity slots each).
# ---------------------------------------------------------------------------

import jax
from jax.sharding import PartitionSpec as P

from harp_tpu.table import pull_rows_sparse, push_rows_sparse

_N = 8           # workers
_RPW = 4         # table rows per worker
_M = 6           # requests per worker
_D = 3

ids_st = st.lists(st.integers(0, _N * _RPW - 1), min_size=_N * _M,
                  max_size=_N * _M)
cap_st = st.integers(1, _M)
# allow_subnormal=False: XLA flushes f32 denormals to zero (FTZ) while
# numpy keeps them — a float-semantics artifact, not a verb property
table_st = st.lists(st.floats(-100, 100, allow_nan=False,
                              allow_infinity=False, width=32,
                              allow_subnormal=False),
                    min_size=_N * _RPW * _D, max_size=_N * _RPW * _D)

_sparse_cache: dict = {}


def _fns(mesh, capacity):
    if capacity not in _sparse_cache:
        pull = jax.jit(mesh.shard_map(
            lambda t, i: pull_rows_sparse(t, i, capacity=capacity),
            in_specs=(mesh.spec(0), mesh.spec(0)),
            out_specs=(mesh.spec(0), mesh.spec(0), P())))
        push = jax.jit(mesh.shard_map(
            lambda t, i, dv: push_rows_sparse(t, i, dv, capacity=capacity),
            in_specs=(mesh.spec(0),) * 3,
            out_specs=(mesh.spec(0), P())))
        _sparse_cache[capacity] = (pull, push)
    return _sparse_cache[capacity]


def _model_keep(ids, capacity):
    """The deterministic drop rule: per (worker, owning-destination)
    arrival order, ``capacity`` slots each."""
    keep = np.zeros(ids.shape, bool)
    for w in range(_N):
        counts: dict = {}
        for j in range(_M):
            dest = ids[w * _M + j] // _RPW
            c = counts.get(dest, 0)
            keep[w * _M + j] = c < capacity
            counts[dest] = c + 1
    return keep


@settings(max_examples=25, deadline=None)
@given(ids=ids_st, cap=cap_st, tvals=table_st)
def test_pull_rows_sparse_property(mesh, ids, cap, tvals):
    ids = np.asarray(ids, np.int32)
    table = np.asarray(tvals, np.float32).reshape(_N * _RPW, _D)
    pull, _ = _fns(mesh, cap)
    rows, ok, dropped = pull(table, ids)
    keep = _model_keep(ids, cap)
    np.testing.assert_array_equal(np.asarray(ok), keep)
    assert int(dropped) == int((~keep).sum())
    rows = np.asarray(rows)
    np.testing.assert_allclose(rows[keep], table[ids[keep]])
    np.testing.assert_allclose(rows[~keep], 0.0)


@settings(max_examples=25, deadline=None)
@given(ids=ids_st, cap=cap_st)
def test_push_rows_sparse_property(mesh, ids, cap):
    ids = np.asarray(ids, np.int32)
    table = np.zeros((_N * _RPW, _D), np.float32)
    deltas = (np.arange(_N * _M * _D, dtype=np.float32)
              .reshape(_N * _M, _D) / 7.0)
    _, push = _fns(mesh, cap)
    new_table, dropped = push(table, ids, deltas)
    keep = _model_keep(ids, cap)
    assert int(dropped) == int((~keep).sum())
    expect = np.zeros_like(table)
    np.add.at(expect, ids[keep], deltas[keep])
    np.testing.assert_allclose(np.asarray(new_table), expect, rtol=1e-6,
                               atol=1e-6)


def _model_keep_dedup(ids, capacity):
    """Exact dedup drop rule: per worker, DISTINCT ids request in
    ASCENDING order (the sort inside _dedup_plan), capacity slots per
    owner; every token of a kept id is ok.  Returns (token keep mask,
    total distinct-id drops)."""
    keep_tok = np.zeros(ids.shape, bool)
    distinct_drops = 0
    for w in range(_N):
        chunk = ids[w * _M:(w + 1) * _M]
        counts: dict = {}
        kept = set()
        for u in np.unique(chunk):          # ascending
            dest = int(u) // _RPW
            c = counts.get(dest, 0)
            if c < capacity:
                kept.add(int(u))
            else:
                distinct_drops += 1
            counts[dest] = c + 1
        keep_tok[w * _M:(w + 1) * _M] = [int(x) in kept for x in chunk]
    return keep_tok, distinct_drops


_dedup_cache: dict = {}


def _dedup_fns(mesh, capacity):
    from harp_tpu.table import pull_rows_sparse_dedup, push_rows_sparse_dedup

    if capacity not in _dedup_cache:
        pull = jax.jit(mesh.shard_map(
            lambda t, i: pull_rows_sparse_dedup(t, i, capacity=capacity),
            in_specs=(mesh.spec(0), mesh.spec(0)),
            out_specs=(mesh.spec(0), mesh.spec(0), P())))
        push = jax.jit(mesh.shard_map(
            lambda t, i, dv: push_rows_sparse_dedup(t, i, dv,
                                                    capacity=capacity),
            in_specs=(mesh.spec(0),) * 3,
            out_specs=(mesh.spec(0), P())))
        _dedup_cache[capacity] = (pull, push)
    return _dedup_cache[capacity]


@settings(max_examples=25, deadline=None)
@given(ids=ids_st, cap=cap_st, tvals=table_st)
def test_pull_rows_sparse_dedup_property(mesh, ids, cap, tvals):
    ids = np.asarray(ids, np.int32)
    table = np.asarray(tvals, np.float32).reshape(_N * _RPW, _D)
    pull, _ = _dedup_fns(mesh, cap)
    rows, ok, dropped = pull(table, ids)
    keep, distinct_drops = _model_keep_dedup(ids, cap)
    np.testing.assert_array_equal(np.asarray(ok), keep)
    assert int(dropped) == distinct_drops   # counted per DISTINCT id
    rows = np.asarray(rows)
    np.testing.assert_allclose(rows[keep], table[ids[keep]])
    np.testing.assert_allclose(rows[~keep], 0.0)


@settings(max_examples=25, deadline=None)
@given(ids=ids_st, cap=cap_st)
def test_push_rows_sparse_dedup_property(mesh, ids, cap):
    ids = np.asarray(ids, np.int32)
    table = np.zeros((_N * _RPW, _D), np.float32)
    # integer deltas: the pre-summed dedup push must be EXACTLY np.add.at
    deltas = ((np.arange(_N * _M * _D) % 13) - 6).astype(
        np.float32).reshape(_N * _M, _D)
    _, push = _dedup_fns(mesh, cap)
    new_table, dropped = push(table, ids, deltas)
    keep, distinct_drops = _model_keep_dedup(ids, cap)
    assert int(dropped) == distinct_drops
    expect = np.zeros_like(table)
    np.add.at(expect, ids[keep], deltas[keep])
    np.testing.assert_array_equal(np.asarray(new_table), expect)


# ---------------------------------------------------------------------------
# Native CSV parser property: the hand-rolled C++ float scanner must
# round-trip arbitrary f32 values written at full precision, agreeing
# with numpy's parse to 1 ulp (the scanner accumulates in double and
# rounds once, so exact equality is not guaranteed for long mantissas).
# ---------------------------------------------------------------------------

from harp_tpu.native.build import load_native
from harp_tpu.native.datasource import CSVStream

f32_st = st.floats(allow_nan=False, allow_infinity=False, width=32,
                   allow_subnormal=False)


@settings(max_examples=30, deadline=None)
@given(rows=st.lists(st.tuples(f32_st, f32_st, f32_st), min_size=2,
                     max_size=8),
       sep=st.sampled_from([",", " ", "\t", ", "]),
       fmt=st.sampled_from(["{:.9e}", "{:.17g}", "{:g}", "{:.6f}"]))
def test_native_csv_parser_roundtrip_property(tmp_path_factory, rows, sep,
                                              fmt):
    if load_native() is None:
        import pytest

        pytest.skip("no native lib")
    vals = np.asarray(rows, np.float32)
    p = tmp_path_factory.mktemp("csvprop") / "v.csv"
    with open(p, "w") as f:
        for row in vals:
            f.write(sep.join(fmt.format(float(v)) for v in row) + "\n")
    # what numpy parses from the same text (the fallback's semantics)
    expect = np.loadtxt(str(p), dtype=np.float64,
                        delimiter=None if sep != "," and sep != ", " else ",",
                        ndmin=2).astype(np.float32)
    with CSVStream(str(p), chunk_rows=4) as stream:
        got = np.concatenate(list(stream), 0)
    assert got.shape == expect.shape
    # agreement to 1 ulp of the numpy-parsed value (spacing at f32 max
    # overflows to inf — a permissive bound there, which is fine)
    with np.errstate(over="ignore"):
        ulp = np.spacing(np.abs(expect).astype(np.float32)) + 1e-45
    assert (np.abs(got - expect) <= ulp).all(), (got, expect)


# ---------------------------------------------------------------------------
# libsvm parser property: native and Python-fallback parses must agree on
# random sparse data across formats (same contract as the CSV parser —
# behavior must not depend on g++ availability).
# ---------------------------------------------------------------------------


@settings(max_examples=20, deadline=None)
@given(rows=st.lists(
    st.tuples(f32_st,                       # label
              st.lists(st.tuples(st.integers(1, 30), f32_st),
                       min_size=0, max_size=6)),    # (idx, val) pairs
    min_size=1, max_size=6),
    fmt=st.sampled_from(["{:.9e}", "{:.17g}", "{:g}"]))
def test_libsvm_native_matches_fallback_property(tmp_path_factory, rows,
                                                 fmt):
    import harp_tpu.native.build as B
    from harp_tpu.native.datasource import load_libsvm

    if load_native() is None:
        import pytest

        pytest.skip("no native lib")
    p = tmp_path_factory.mktemp("svmprop") / "d.svm"
    with open(p, "w") as f:
        for label, pairs in rows:
            # ascending indices per line (the format's contract)
            pairs = sorted({i: v for i, v in pairs}.items())
            toks = [fmt.format(float(label))] + [
                f"{i}:{fmt.format(float(v))}" for i, v in pairs]
            f.write(" ".join(toks) + "\n")

    native = load_libsvm(str(p))
    saved = (B._LIB, B._TRIED)
    try:
        B._LIB, B._TRIED = None, True   # force the fallback
        fallback = load_libsvm(str(p))
    finally:
        B._LIB, B._TRIED = saved
    for a, b, name in zip(native, fallback,
                          ("labels", "indptr", "indices", "values", "nf")):
        with np.errstate(over="ignore"):
            ulp = (np.spacing(np.abs(np.asarray(a, np.float64))
                              .astype(np.float32)) + 1e-45
                   if name in ("labels", "values") else 0)
        assert np.all(np.abs(np.asarray(a, np.float64)
                             - np.asarray(b, np.float64)) <= ulp), \
            (name, a, b)
