"""Superstep flightpath (harp_tpu/utils/steptrace, PR 18) — one causal
training-plane timeline across all six spines.

Evidence layers, all on the 8-worker CPU sim:

1. span mechanics: runs/supersteps terminate in ``finally`` with the
   frozen outcome vocabulary; reentrant entries are no-ops (outermost
   wins); marks outside a run are dropped, not orphaned;
2. THE chaos drill (ISSUE 18 acceptance): a seeded transient fault, a
   fired-and-consumed skew rebalance, and a permanent worker loss in
   ONE elastic run produce ONE timeline — every span terminated, every
   abnormal termination carrying its cause as an adjacent mark, the
   export invariant-16 clean (which reconciles it against the elastic
   ledger, the health sentinel, and the TransferLedger), and the
   Perfetto conversion loadable (trace-event shape, no NaNs);
3. the healthy control: the same driver on a balanced corpus shows
   zero abnormal terminations;
4. the PR-3 contract: with telemetry off the tracer stays empty and
   traced results are bit-identical; with tracing ARMED the flagship
   flight budgets (1 dispatch / 1 stacked readback / 0 steady
   compiles) pass UNCHANGED — the timeline is an observer, never a
   participant.
"""

import json
import os
import sys

import numpy as np
import pytest

from harp_tpu import health
from harp_tpu.elastic import ledger as eledger
from harp_tpu.utils import flightrec, steptrace, telemetry
from harp_tpu.utils.fault import FaultInjector

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "scripts"))

import check_jsonl  # noqa: E402


# ---------------------------------------------------------------------------
# span mechanics
# ---------------------------------------------------------------------------

def test_vocab_sync_with_check_jsonl():
    """The frozen invariant-16 vocabularies must mirror the module's —
    drift fails tier-1 (the sync-pin pattern of invariants 11/13/14)."""
    assert check_jsonl.KNOWN_STEPTRACE_EVS == steptrace.EVS
    assert check_jsonl.KNOWN_STEPTRACE_OUTCOMES == steptrace.OUTCOMES
    assert check_jsonl.KNOWN_STEPTRACE_SOURCES == steptrace.SOURCES
    assert (check_jsonl.KNOWN_STEPTRACE_FLIGHT_KEYS
            == steptrace.FLIGHT_KEYS)
    # the flight keys must stay a subset of what flightrec can delta
    assert set(steptrace.FLIGHT_KEYS) <= set(flightrec.snapshot())


def test_run_and_superstep_rows_reconcile():
    with telemetry.scope(True):
        with steptrace.run("unit.phase"):
            for i in range(3):
                with steptrace.superstep("unit.phase", i):
                    steptrace.tracer.mark("wire", "allreduce",
                                          site="unit.py:1")
        rows = steptrace.tracer.rows()
    spans = [r for r in rows if r["ev"] == "superstep"]
    runs = [r for r in rows if r["ev"] == "run"]
    assert len(spans) == 3 and len(runs) == 1
    assert [s["outcome"] for s in spans] == ["completed"] * 3
    assert [s["seq"] for s in spans] == [0, 1, 2]
    assert runs[0]["supersteps"] == 3
    assert runs[0]["outcomes"]["completed"] == 3
    assert runs[0]["marks"] == 3
    # ts-monotone by construction (spans close before the run row)
    ts = [r["ts"] for r in rows]
    assert ts == sorted(ts)


def test_exception_terminates_span_faulted_and_propagates():
    with telemetry.scope(True):
        with pytest.raises(RuntimeError):
            with steptrace.run("unit.phase"):
                with steptrace.superstep("unit.phase", 0):
                    raise RuntimeError("boom")
        rows = steptrace.tracer.rows()
    spans = [r for r in rows if r["ev"] == "superstep"]
    runs = [r for r in rows if r["ev"] == "run"]
    assert spans[0]["outcome"] == "faulted"
    # the run row still terminates (finally) — no unterminated run even
    # when the driver dies
    assert len(runs) == 1 and runs[0]["outcomes"]["faulted"] == 1


def test_reentrant_run_and_superstep_are_noops():
    """kmeans.fit inside elastic_fit (or any nested driver) must not
    double-count: the outermost run/span wins."""
    with telemetry.scope(True):
        with steptrace.run("outer"):
            with steptrace.run("inner"):          # no-op
                with steptrace.superstep("outer", 0):
                    with steptrace.superstep("inner", 99):  # no-op
                        pass
        rows = steptrace.tracer.rows()
    runs = [r for r in rows if r["ev"] == "run"]
    spans = [r for r in rows if r["ev"] == "superstep"]
    assert len(runs) == 1 and runs[0]["phase"] == "outer"
    assert len(spans) == 1 and spans[0]["step"] == 0


def test_marks_outside_a_run_are_dropped():
    with telemetry.scope(True):
        steptrace.tracer.mark("wire", "allreduce", site="unit.py:1")
        steptrace.tracer.on_elastic("rebalance", "unit.phase")
        assert steptrace.tracer.rows() == []


# ---------------------------------------------------------------------------
# THE chaos drill — ISSUE 18 acceptance
# ---------------------------------------------------------------------------

def _skewed_ratings(rng):
    hot = rng.integers(0, 16, 4000)
    cold = rng.integers(16, 64, 1000)
    users = np.concatenate([hot, cold])
    rng.shuffle(users)
    items = rng.integers(0, 48, users.shape[0])
    vals = rng.normal(size=users.shape[0]).astype(np.float32)
    return users, items, vals


def _assert_perfetto_loadable(doc):
    """Chrome Trace Event JSON shape: serializable, M/X/i phases only,
    X spans with non-negative µs durations."""
    json.dumps(doc)  # round-trippable
    assert set(doc) == {"traceEvents", "displayTimeUnit"}
    for e in doc["traceEvents"]:
        assert e["ph"] in ("M", "X", "i")
        if e["ph"] == "X":
            assert e["dur"] >= 0 and e["ts"] >= 0


def test_chaos_drill_one_timeline(mesh, tmp_path):
    """Transient fault + fired rebalance + permanent worker loss in ONE
    run -> one invariant-16-clean, Perfetto-loadable timeline whose
    spans reconcile with the elastic/health rows."""
    from harp_tpu.elastic.apps import MFSGDElastic, elastic_fit
    from harp_tpu.models.mfsgd import MFSGDConfig

    users, items, vals = _skewed_ratings(np.random.default_rng(0))
    cfg = MFSGDConfig(rank=4, algo="dense", u_tile=8, i_tile=8,
                      entry_cap=64)
    ck = str(tmp_path / "ck")
    with telemetry.scope(True):
        # dispatch ordinal 5 = a transient mid-epoch-3 (retry absorbs),
        # ordinal 7 = permanent loss of worker 3 (elastic shrink); the
        # skewed corpus fires the skew trigger at superstep 3
        inj = FaultInjector(seed=0, fail={"dispatch": (5,)},
                            permanent={"dispatch": (7,)}, lost_worker=3)
        ad = MFSGDElastic(64, 48, cfg, mesh, 0, users=users, items=items,
                          vals=vals, packs_per_worker=8,
                          max_worker_loss=1)
        elastic_fit(ad, 6, ck, ckpt_every=1, fault=inj)
        assert inj.permanent_fired and ad.losses == 1
        elastic_events = [r["event"] for r in eledger.ledger.rows]
        assert elastic_events == ["rebalance", "resume", "shrink",
                                  "resume"]
        rows = steptrace.tracer.rows()
        p = tmp_path / "chaos.jsonl"
        telemetry.export(str(p))
    # ONE run; every span terminated; each chaos mode on the timeline
    runs = [r for r in rows if r["ev"] == "run"]
    assert len(runs) == 1
    rn = runs[0]
    spans = [r for r in rows if r["ev"] == "superstep"]
    assert len(spans) == rn["supersteps"]
    outcomes = [s["outcome"] for s in spans]
    assert outcomes.count("rebalanced") == 1
    assert outcomes.count("faulted") == 2       # transient + permanent
    assert outcomes.count("resumed") == 2       # restart + post-shrink
    # cause-adjacency: the faulted spans carry the injector's marks
    marks = [r for r in rows if r["ev"] == "mark"]
    fault_marks = [m for m in marks if m["source"] == "fault"]
    assert {m["name"] for m in fault_marks} == {"injected_fail",
                                                "injected_permanent"}
    assert {m["seq"] for m in fault_marks} == {
        s["seq"] for s in spans if s["outcome"] == "faulted"}
    # the timeline's elastic marks mirror the ledger event-for-event
    assert [m["name"] for m in marks if m["source"] == "elastic"] \
        == elastic_events
    # the actuation pair: trigger finding + exactly-once consume
    health_marks = {m["name"] for m in marks if m["source"] == "health"}
    assert {"skew_trigger", "consume_skew_trigger"} <= health_marks
    # two-spine dispatch reconciliation, exact
    n_dispatch_marks = sum(1 for m in marks
                           if (m["source"], m["name"])
                           == ("flight", "dispatch"))
    assert n_dispatch_marks == rn["flight"]["dispatches"]
    # the full export passes invariant 16 (plus 13/14's own checks)
    assert check_jsonl.check_file(str(p), provenance=True) == []
    _assert_perfetto_loadable(steptrace.perfetto(rows))
    summary = steptrace.summarize_rows(rows)
    assert summary["unterminated"] == []
    assert summary["dispatch_mismatch"] == []
    assert summary["faulted"] == 2 and summary["rebalanced"] == 1


def test_healthy_control_zero_abnormal_terminations(mesh, tmp_path):
    """Balanced corpus, no injector: every span completes, no fault or
    elastic marks, and the export is invariant-16 clean."""
    from harp_tpu.elastic.apps import MFSGDElastic, elastic_fit
    from harp_tpu.models.mfsgd import MFSGDConfig

    rng = np.random.default_rng(5)
    users = rng.integers(0, 64, 1500)
    items = rng.integers(0, 48, 1500)
    vals = rng.normal(size=1500).astype(np.float32)
    cfg = MFSGDConfig(rank=4, algo="dense", u_tile=8, i_tile=8,
                      entry_cap=64)
    with telemetry.scope(True):
        ad = MFSGDElastic(64, 48, cfg, mesh, 0, users=users, items=items,
                          vals=vals)
        elastic_fit(ad, 3)
        rows = steptrace.tracer.rows()
        p = tmp_path / "healthy.jsonl"
        telemetry.export(str(p))
    runs = [r for r in rows if r["ev"] == "run"]
    assert len(runs) == 1
    assert runs[0]["outcomes"] == {"completed": 3, "faulted": 0,
                                   "rebalanced": 0, "resumed": 0}
    assert not any(r["ev"] == "mark"
                   and r["source"] in ("fault", "elastic")
                   for r in rows)
    # one lane per superstep (skew.record_execution fires per epoch)
    assert runs[0]["lanes"] == 3
    assert check_jsonl.check_file(str(p), provenance=True) == []


def test_kmeans_fit_is_one_single_dispatch_superstep(mesh):
    """The whole-run-in-one-jit discipline reads literally off the
    timeline: kmeans.fit is one run, one span, flight dispatches=1."""
    from harp_tpu.models import kmeans

    pts = np.random.default_rng(0).normal(size=(256, 8)) \
        .astype(np.float32)
    with telemetry.scope(True):
        kmeans.fit(pts, k=4, iters=3, mesh=mesh, seed=0)
        rows = steptrace.tracer.rows()
    runs = [r for r in rows if r["ev"] == "run"]
    spans = [r for r in rows if r["ev"] == "superstep"]
    assert len(runs) == 1 and runs[0]["phase"] == "kmeans.fit"
    assert len(spans) == 1
    assert spans[0]["flight"]["dispatches"] == 1
    assert spans[0]["flight"]["readbacks"] == 2  # inertia + centroids
    lanes = [r for r in rows if r["ev"] == "lane"]
    assert len(lanes) == 1 and len(lanes[0]["work"]) == mesh.num_workers


# ---------------------------------------------------------------------------
# the PR-3 contract: zero-cost off, zero-flight-cost armed
# ---------------------------------------------------------------------------

def test_zero_cost_with_telemetry_off(mesh):
    """With telemetry off the tracer must stay EMPTY through a full
    instrumented driver run — and the result must be bit-identical to
    the traced run (the observer never participates)."""
    from harp_tpu.models import kmeans

    pts = np.random.default_rng(0).normal(size=(256, 8)) \
        .astype(np.float32)
    steptrace.reset()
    c_off, inertia_off = kmeans.fit(pts, k=4, iters=3, mesh=mesh, seed=0)
    assert steptrace.tracer.rows() == []
    assert steptrace.tracer._run is None
    with telemetry.scope(True):
        c_on, inertia_on = kmeans.fit(pts, k=4, iters=3, mesh=mesh,
                                      seed=0)
        assert steptrace.tracer.rows() != []
    np.testing.assert_array_equal(np.asarray(c_off), np.asarray(c_on))
    assert inertia_off == inertia_on


def test_flagship_budget_pins_unchanged_with_tracing_armed(mesh):
    """The PR-3/PR-17 flagship budget — 1 dispatch, 1 stacked readback,
    0 steady compiles, 0 H2D — must hold bit-for-bit INSIDE an armed
    steptrace run: tracing adds marks, never flight traffic."""
    import harp_tpu.models.mfsgd as MF

    cfg = MF.MFSGDConfig(rank=4, algo="dense", u_tile=8, i_tile=8,
                         entry_cap=32)
    with telemetry.scope():
        m = MF.MFSGD(64, 48, cfg, mesh, seed=3)
        u, i, v = MF.synthetic_ratings(64, 48, 600, rank=4, seed=3)
        m.set_ratings(u, i, v)
        m.train_epoch()       # warmup
        m.compile_epochs(3)
        m.train_epochs(3)     # steady (stacked-readback ops compiled)
        with steptrace.run("mfsgd.epochs"):
            with flightrec.budget(compiles=0, dispatches=1, readbacks=1,
                                  h2d_bytes=0,
                                  tag="mfsgd.train_epochs.traced") as b:
                with steptrace.superstep("mfsgd.epochs", 0):
                    m.train_epochs(3)
            assert b.spent()["dispatches"] == 1
            assert b.spent()["readbacks"] == 1
        rows = steptrace.tracer.rows()
    spans = [r for r in rows if r["ev"] == "superstep"]
    assert spans[-1]["flight"] == {"dispatches": 1, "readbacks": 1,
                                   "h2d_calls": 0, "compiles": 0}


def test_export_timeline_merges_steptrace_rows(mesh, tmp_path):
    """export_timeline must append the steptrace spine so ONE file
    holds the whole training-plane story (the merge the timeline CLI
    reads)."""
    from harp_tpu.models import kmeans

    pts = np.random.default_rng(0).normal(size=(128, 4)) \
        .astype(np.float32)
    p = tmp_path / "merged.jsonl"
    with telemetry.scope(True):
        kmeans.fit(pts, k=4, iters=2, mesh=mesh, seed=0)
        telemetry.export_timeline(str(p))
    kinds = {json.loads(line).get("kind") for line in open(p)}
    assert "steptrace" in kinds
    loaded = telemetry.load_rows(str(p))
    assert steptrace.summarize_rows(
        loaded["steptrace"])["unterminated"] == []
