"""Worker script for the multi-process jax.distributed tests (not a pytest module).

Launched by tests/test_multiprocess.py as ``python multiproc_worker.py
<process_id> <port> [num_processes] [local_devices]``.  Validates the
multi-host code paths without TPU hardware: ``init_distributed``
bootstrap, a mesh spanning processes, and EVERY collective family
crossing a real process boundary (Gloo on CPU — the DCN stand-in):
allreduce, regroup / all_to_all, dense push/pull, the sparse
request/serve pull/push, the host-side ``kv_allreduce`` union, full
MF-SGD / LDA epochs, ZeRO-1 optimizer steps (sharded state asserted per
process, trajectory == replicated adam), and a tensor-parallel MLP step
on a 2-D mesh whose model axis crosses the process link.

``local_devices > 1`` is the POD-SHAPED topology (VERDICT r2 item 6): a
v4-32 is N processes × M chips, where intra-process (ICI stand-in) and
inter-process (DCN stand-in) links coexist in ONE mesh — the launcher
sets ``--xla_force_host_platform_device_count=M`` per process, and every
check below validates each process's M addressable shards against the
globally-expected array, so block layouts that happen to be right only
at one-device-per-process cannot pass silently.
"""

import os
import sys

proc_id = int(sys.argv[1])
port = sys.argv[2]
n_procs = int(sys.argv[3]) if len(sys.argv) > 3 else 2
local_devices = int(sys.argv[4]) if len(sys.argv) > 4 else 1

if local_devices > 1:
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               f" --xla_force_host_platform_device_count={local_devices}")

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from harp_tpu import Int2IntKVTable, WorkerMesh, init_distributed, kv_allreduce
from harp_tpu.parallel import collective as C

init_distributed(f"127.0.0.1:{port}", num_processes=n_procs,
                 process_id=proc_id)
assert jax.process_count() == n_procs, jax.process_count()
assert jax.local_device_count() == local_devices, jax.local_device_count()

import numpy as np

mesh = WorkerMesh()
nw = mesh.num_workers
assert nw == n_procs * local_devices, (nw, n_procs, local_devices)


def check_global(arr, expected, rtol=1e-7, atol=0.0):
    """Validate every shard THIS process can address against the expected
    global array — works for any sharding and any devices-per-process."""
    expected = np.asarray(expected)
    for sh in arr.addressable_shards:
        np.testing.assert_allclose(np.asarray(sh.data), expected[sh.index],
                                   rtol=rtol, atol=atol)


# device collective across the process boundary
op = C.host_op(mesh, C.allreduce, in_dim=0, out_dim=0)
x = np.arange(2 * nw, dtype=np.float32).reshape(nw, 2)
check_global(op(x), np.tile(x.sum(0), (nw, 1)))

# regroup / all_to_all across the boundary: worker w sends block j of
# its [nw] vector to worker j; worker w ends holding every peer's block w
rg = C.host_op(mesh, C.regroup, in_dim=0, out_dim=0)
xr = (np.arange(nw)[:, None] * 10 + np.arange(nw)[None, :]).astype(
    np.float32).reshape(-1)  # worker w holds [10w+0 .. 10w+(nw-1)]
check_global(rg(xr),
             (np.arange(nw)[None, :] * 10
              + np.arange(nw)[:, None]).astype(np.float32).reshape(-1))

# dense push (psum_scatter: combined owner shards) and pull (all_gather)
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def pushpull_prog(contrib):
    mine = C.push(contrib)          # [rows/nw, d] owner block, summed
    full = C.pull(mine)             # re-materialized [rows, d]
    return mine, full


pp = jax.jit(mesh.shard_map(
    pushpull_prog, in_specs=(P(),), out_specs=(mesh.spec(0), P())))
contrib = np.arange(nw * 3, dtype=np.float32).reshape(nw, 3)
mine, full = pp(contrib)
check_global(mine, contrib * nw)
check_global(full, contrib * nw)

# sparse request/serve pull + push: two all_to_alls cross the boundary
from harp_tpu.table import pull_rows_sparse, push_rows_sparse


def sparse_prog(shard, ids):
    rows, ok, dropped = pull_rows_sparse(shard, ids, capacity=2)
    new_shard, pdrop = push_rows_sparse(
        shard, ids, jnp.ones((ids.shape[0],) + shard.shape[1:],
                             shard.dtype), capacity=2)
    return rows, ok, dropped, new_shard, pdrop


sp = jax.jit(mesh.shard_map(
    sparse_prog, in_specs=(mesh.spec(0), mesh.spec(0)),
    out_specs=(mesh.spec(0), mesh.spec(0), P(), mesh.spec(0), P())))
table = np.arange(nw * 2 * 3, dtype=np.float32).reshape(nw * 2, 3)
# every worker asks for row 0 (owner 0) and its right neighbor's first row
ids = np.stack([np.zeros(nw, np.int64),
                ((np.arange(nw) + 1) % nw) * 2], 1).reshape(-1)
rows, ok, dropped, new_tab, pdrop = sp(table, ids.astype(np.int32))
assert int(np.asarray(dropped)) == 0 and int(np.asarray(pdrop)) == 0
check_global(rows, table[ids])
check_global(ok, np.ones(2 * nw, bool))
exp = table.copy()
np.add.at(exp, ids, 1.0)
check_global(new_tab, exp)

# host-side KV union across processes
t = Int2IntKVTable()
t.add(proc_id, 1)        # unique key per process
t.add(100, proc_id + 1)  # shared: combined 1+2
u = kv_allreduce(t)
assert u.keys() == list(range(n_procs)) + [100], u.keys()
assert int(u.get(100)) == sum(range(1, n_procs + 1)), u.get(100)

# a full dense MF-SGD rotation epoch spanning the process boundary: the
# ring ppermute of H half-slices and the loss allreduce cross the
# process link (and, pod-shaped, the intra-process segments too)
from harp_tpu.models import mfsgd as MF

u_ids, i_ids, vals = MF.synthetic_ratings(32, 24, 400, rank=3, seed=0)
model = MF.MFSGD(32, 24, MF.MFSGDConfig(rank=4, u_tile=8, i_tile=8,
                                        entry_cap=32, lr=0.05),
                 mesh, seed=0)
model.set_ratings(u_ids, i_ids, vals)
r1 = model.train_epoch()
rs = model.train_epochs(3)
assert np.isfinite(r1) and rs[-1] < r1, (r1, rs)

# the fused-kernel algo (interpret-mode pallas off-TPU) through the same
# cross-process rotation: scalar-prefetch grids + scratch under
# shard_map with a process-boundary mesh must match the dense result
model_p = MF.MFSGD(32, 24, MF.MFSGDConfig(rank=4, algo="pallas", u_tile=8,
                                          i_tile=8, entry_cap=32, lr=0.05,
                                          compute_dtype=jnp.float32),
                   mesh, seed=0)
model_p.set_ratings(u_ids, i_ids, vals)
rp = model_p.train_epoch()
model_d = MF.MFSGD(32, 24, MF.MFSGDConfig(rank=4, u_tile=8, i_tile=8,
                                          entry_cap=32, lr=0.05,
                                          compute_dtype=jnp.float32),
                   mesh, seed=0)
model_d.set_ratings(u_ids, i_ids, vals)
rd = model_d.train_epoch()
assert abs(rp - rd) < 1e-5, (rp, rd)

# LDA pull/push epoch across the boundary: the word-topic table is
# row-sharded over the WHOLE mesh, so chunk pull/push request/serve
# round trips cross both intra- and inter-process links
from harp_tpu.models.lda import LDA, LDAConfig, synthetic_corpus

dl, wl = synthetic_corpus(n_docs=8 * nw, vocab_size=8 * nw,
                          n_topics_true=2, tokens_per_doc=8, seed=0)
lda = LDA(8 * nw, 8 * nw, LDAConfig(n_topics=4, algo="pushpull", chunk=16),
          mesh, seed=0)
lda.set_tokens(dl, wl)
for _ in range(3):
    lda.sample_epoch()
assert lda.last_dropped == 0  # default pull_cap: zero drops guaranteed
# multi-host: a process can only read its own shards — check the
# replicated Nk (global topic totals must still equal the token count)
Nk = np.asarray(lda.Nk.addressable_shards[0].data)
np.testing.assert_allclose(Nk.sum(), lda.n_tokens)
local_Nwk = np.asarray(lda.Nwk.addressable_shards[0].data)
assert (local_Nwk >= 0).all() and np.isfinite(local_Nwk).all()

# sharded ingest: each process streams ONLY its own split
# (fit_streaming_local — Harp's HDFS-split model); the result must match
# a straight-line numpy Lloyd on the concatenated dataset
from harp_tpu.models.kmeans_stream import fit_streaming_local

rng = np.random.RandomState(7)
full = (rng.randn(64 * n_procs, 6).astype(np.float32)
        + (np.arange(64 * n_procs)[:, None] % 4) * 5.0)
mine_slice = full[proc_id * 64:(proc_id + 1) * 64]   # THIS process's split
c0 = full[:4].copy()
c_got, inertia_got = fit_streaming_local(mine_slice, k=4, iters=4,
                                         chunk_points=40, mesh=mesh,
                                         init=c0)


def np_lloyd(pts, c, iters):
    c = c.copy()
    for _ in range(iters):
        d2 = ((pts[:, None, :] - c[None]) ** 2).sum(-1)
        a = d2.argmin(1)
        last_inertia = float(d2[np.arange(len(pts)), a].sum())
        for j in range(len(c)):
            if (a == j).any():
                c[j] = pts[a == j].mean(0)
    return c, last_inertia


c_ref, inertia_ref = np_lloyd(full, c0, 4)
np.testing.assert_allclose(c_got, c_ref, rtol=1e-3, atol=1e-3)
assert abs(inertia_got - inertia_ref) < 1e-3 * abs(inertia_ref)

# pod-shaped only: one rotate step around the mixed ICI/DCN ring —
# worker w's block must land on worker (w+1) % nw regardless of which
# segments are intra- vs inter-process
rot = C.host_op(mesh, C.rotate, in_dim=0, out_dim=0)
xrot = np.arange(nw, dtype=np.float32).reshape(nw, 1)
check_global(rot(xrot), np.roll(xrot, 1, axis=0))

# ZeRO-1 optimizer steps across the process boundary (VERDICT r3 item 7):
# the gradient push (psum_scatter) + param pull (all_gather) cross the
# process link, each process holds ONLY its 1/nw optimizer-state shards,
# and the loss trajectory must equal the replicated-adam trainer's
from harp_tpu.models.mlp import MLPConfig, MLPTrainer, synthetic_mnist

xz, yz = synthetic_mnist(n=4 * nw, d=8, classes=4, seed=1)
zcfg = dict(sizes=(8, 16, 4), optimizer="adam")
tr_z = MLPTrainer(MLPConfig(zero1=True, **zcfg), mesh, seed=0)
tr_r = MLPTrainer(MLPConfig(**zcfg), mesh, seed=0)
losses_z = [tr_z.train_batch(xz, yz)[0] for _ in range(3)]
losses_r = [tr_r.train_batch(xz, yz)[0] for _ in range(3)]
np.testing.assert_allclose(losses_z, losses_r, rtol=1e-5, atol=1e-6)
import jax.tree_util as jtu

vec_leaves = [lf for lf in jtu.tree_leaves(tr_z.opt_state) if lf.ndim > 0]
assert vec_leaves, "adam zero1 state must have vector leaves"
for lf in vec_leaves:
    # TRUE sharding per process: local_devices shards of 1/nw each, at
    # distinct offsets — a silently replicated state fails here
    shards = lf.addressable_shards
    assert len(shards) == local_devices, (len(shards), local_devices)
    starts = set()
    for sh in shards:
        assert sh.data.shape[0] == lf.shape[0] // nw, (
            sh.data.shape, lf.shape, nw)
        starts.add(sh.index[0].start or 0)
    assert len(starts) == local_devices, starts
# adam's first moment is nonzero after real steps — the sharded state is
# actually being updated, not dead weight
mu_max = max(float(np.abs(np.asarray(sh.data)).max())
             for sh in vec_leaves[0].addressable_shards)
assert mu_max > 0.0

# tensor parallel across the boundary: a 2-D (data x model) mesh whose
# model axis spans real process links; first-step loss must match the
# data-parallel trainer (GSPMD numerics == explicit-verb numerics)
from harp_tpu.models.mlp import TPMLPTrainer
from harp_tpu.parallel.mesh import mesh_2d

n_model = next(d for d in (4, 2, 1) if nw % d == 0)
tp = TPMLPTrainer(MLPConfig(sizes=(8, 16, 4)),
                  mesh_2d(nw // n_model, n_model), seed=0)
dp = MLPTrainer(MLPConfig(sizes=(8, 16, 4)), mesh, seed=0)
tp_loss, tp_acc = tp.train_batch(xz, yz)
dp_loss, dp_acc = dp.train_batch(xz, yz)
assert abs(tp_loss - dp_loss) < 1e-4, (tp_loss, dp_loss)
assert abs(tp_acc - dp_acc) < 1e-6, (tp_acc, dp_acc)

# --- VERDICT r4 item 6: the remaining parallelism strategies cross the
# same real process boundary the verbs/ZeRO-1/TP already do ---

# pipeline parallelism: one GPipe loss+grad step — activations hop the
# stage ring via rotate/ppermute, so every microbatch crosses the
# process link (and intra-process segments, pod-shaped) S+M-1 times;
# loss AND per-stage grads must match the serial host chain rule
from harp_tpu.parallel.pipeline import pipeline_loss_and_grads

PW, PMB, PM = 8, 2, 3  # width, microbatch, n_microbatches
pp_rng = np.random.default_rng(40)
pp_params = {"w": (pp_rng.normal(size=(nw, PW, PW)) * 0.5).astype(np.float32),
             "b": (pp_rng.normal(size=(nw, PW)) * 0.1).astype(np.float32)}
px = pp_rng.normal(size=(PM, PMB, PW)).astype(np.float32)
pt = pp_rng.normal(size=(PM, PMB, PW)).astype(np.float32)


def pp_stage(params, h):
    return jax.nn.tanh(h @ params["w"] + params["b"])


def pp_loss(outs, targets):
    return ((outs - targets) ** 2).mean()


pp_fn = jax.jit(mesh.shard_map(
    lambda p, xx, tt: pipeline_loss_and_grads(
        pp_stage, pp_loss, jax.tree_util.tree_map(lambda a: a[0], p),
        xx, tt),
    in_specs=({"w": mesh.spec(0), "b": mesh.spec(0)}, P(), P()),
    out_specs=(P(), {"w": mesh.spec(0), "b": mesh.spec(0)})))
pp_l, pp_g = pp_fn(pp_params, px, pt)


def pp_serial_loss(p):
    outs = []
    for i in range(PM):
        h = jnp.asarray(px[i])
        for s in range(nw):
            h = pp_stage({"w": p["w"][s], "b": p["b"][s]}, h)
        outs.append(h)
    return pp_loss(jnp.stack(outs), jnp.asarray(pt))


pp_ref_l, pp_ref_g = jax.value_and_grad(pp_serial_loss)(
    jax.tree_util.tree_map(jnp.asarray, pp_params))
lz = np.asarray(pp_l.addressable_shards[0].data)
assert abs(float(lz) - float(pp_ref_l)) < 1e-5, (lz, pp_ref_l)
# shard_map concatenated per-stage grads along dim 0 (see test_pipeline)
check_global(pp_g["w"], np.asarray(pp_ref_g["w"]).reshape(nw * PW, PW),
             rtol=1e-4, atol=1e-6)
check_global(pp_g["b"], np.asarray(pp_ref_g["b"]).reshape(nw * PW),
             rtol=1e-4, atol=1e-6)

# expert-parallel MoE: the regroup (all_to_all) dispatch + inverse
# exchange cross the process link; capacity sized so nothing drops
from harp_tpu.ops.moe import moe_ffn, reference_moe

MD, MH = 8, 16
moe_rng = np.random.default_rng(41)
moe_w = {"gate": moe_rng.normal(size=(MD, nw)).astype(np.float32),
         "w1": (moe_rng.normal(size=(nw, MD, MH)) * 0.5).astype(np.float32),
         "b1": (moe_rng.normal(size=(nw, MH)) * 0.1).astype(np.float32),
         "w2": (moe_rng.normal(size=(nw, MH, MD)) * 0.5).astype(np.float32),
         "b2": (moe_rng.normal(size=(nw, MD)) * 0.1).astype(np.float32)}
mx = moe_rng.normal(size=(nw * 8, MD)).astype(np.float32)
moe_fn = jax.jit(mesh.shard_map(
    lambda xx, wt: moe_ffn(xx, wt["gate"], wt["w1"][0], wt["b1"][0],
                           wt["w2"][0], wt["b2"][0], capacity=8),
    in_specs=(mesh.spec(0),
              {"gate": P(), "w1": mesh.spec(0), "b1": mesh.spec(0),
               "w2": mesh.spec(0), "b2": mesh.spec(0)}),
    out_specs=(mesh.spec(0), P())))
my, mdrop = moe_fn(mx, moe_w)
assert int(np.asarray(mdrop.addressable_shards[0].data)) == 0
moe_ref = reference_moe(mx, moe_w["gate"], moe_w["w1"], moe_w["b1"],
                        moe_w["w2"], moe_w["b2"], 8, nw)
check_global(my, np.asarray(moe_ref), rtol=2e-4, atol=2e-5)

# ring attention (causal): the K/V ring ppermute crosses the process
# link every block step; online-softmax result must match full attention
from harp_tpu.ops.flash_attention import reference_attention
from harp_tpu.ops.ring_attention import make_ring_attention_fn

ab, ah, ad = 2, 2, 8
an = 8 * nw  # sequence sharded over the whole mesh
at_rng = np.random.default_rng(42)
aq, ak, av = (at_rng.normal(size=(ab, an, ah, ad)).astype(np.float32)
              for _ in range(3))
a_out = make_ring_attention_fn(mesh, causal=True)(aq, ak, av)
qf = jnp.asarray(aq).transpose(0, 2, 1, 3).reshape(ab * ah, an, ad)
kf = jnp.asarray(ak).transpose(0, 2, 1, 3).reshape(ab * ah, an, ad)
vf = jnp.asarray(av).transpose(0, 2, 1, 3).reshape(ab * ah, an, ad)
a_ref = np.asarray(reference_attention(qf, kf, vf, causal=True))
a_ref = a_ref.reshape(ab, ah, an, ad).transpose(0, 2, 1, 3)
for sh in a_out.addressable_shards:
    np.testing.assert_allclose(np.asarray(sh.data), a_ref[sh.index],
                               rtol=2e-4, atol=2e-5)

print(f"proc {proc_id}: MULTIPROC OK", flush=True)
