"""Worker script for the two-process jax.distributed test (not a pytest module).

Launched by tests/test_multiprocess.py as ``python multiproc_worker.py
<process_id> <port>``.  Validates the multi-host code paths without TPU
hardware: ``init_distributed`` bootstrap, a mesh spanning processes, a
device collective crossing the process boundary (Gloo on CPU — the DCN
stand-in), and ``kv_allreduce``'s host-side cross-process union.
"""

import os
import sys

proc_id = int(sys.argv[1])
port = sys.argv[2]

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from harp_tpu import Int2IntKVTable, WorkerMesh, init_distributed, kv_allreduce
from harp_tpu.parallel import collective as C

init_distributed(f"127.0.0.1:{port}", num_processes=2, process_id=proc_id)
assert jax.process_count() == 2, jax.process_count()

import numpy as np

mesh = WorkerMesh()  # 2 devices, one per process
assert mesh.num_workers == 2

# device collective across the process boundary; in multi-process each
# host reads only its addressable shard of the global result
op = C.host_op(mesh, C.allreduce, in_dim=0, out_dim=0)
x = np.arange(4, dtype=np.float32).reshape(2, 2)
out = op(x)
local = np.asarray(out.addressable_shards[0].data)
np.testing.assert_allclose(local, x.sum(0)[None, :])

# host-side KV union across processes
t = Int2IntKVTable()
t.add(proc_id, 1)        # unique key per process
t.add(100, proc_id + 1)  # shared: combined 1+2
u = kv_allreduce(t)
assert u.keys() == [0, 1, 100], u.keys()
assert int(u.get(100)) == 3, u.get(100)

# a full dense MF-SGD rotation epoch spanning the process boundary: the
# ring ppermute of H half-slices and the loss allreduce both cross DCN
# (Gloo stand-in); every process feeds identical global inputs and reads
# back the replicated RMSE
from harp_tpu.models import mfsgd as MF

u_ids, i_ids, vals = MF.synthetic_ratings(32, 24, 400, rank=3, seed=0)
model = MF.MFSGD(32, 24, MF.MFSGDConfig(rank=4, u_tile=8, i_tile=8,
                                        entry_cap=32, lr=0.05),
                 mesh, seed=0)
model.set_ratings(u_ids, i_ids, vals)
r1 = model.train_epoch()
rs = model.train_epochs(3)
assert np.isfinite(r1) and rs[-1] < r1, (r1, rs)

print(f"proc {proc_id}: MULTIPROC OK", flush=True)
