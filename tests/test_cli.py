"""L8 launcher tests — unified CLI dispatcher."""

import harp_tpu.__main__ as cli


def test_list(capsys):
    assert cli.main(["--list"]) == 0
    out = capsys.readouterr().out
    for app in ("kmeans", "mfsgd", "lda", "mlp", "subgraph", "rf", "bench"):
        assert app in out


def test_unknown_app(capsys):
    assert cli.main(["nosuchapp"]) == 2
    assert "unknown app" in capsys.readouterr().err


def test_dispatch_kmeans_smoke(capsys):
    rc = cli.main(["kmeans", "--n", "512", "--d", "8", "--k", "4",
                   "--iters", "3", "--bench"])
    assert rc == 0
    assert "iters_per_sec" in capsys.readouterr().out


def test_dispatch_stats_smoke(capsys):
    rc = cli.main(["stats", "pca", "--n", "512", "--d", "8"])
    assert rc == 0
    assert "top5_evals" in capsys.readouterr().out


def test_stats_all_algos_run(capsys):
    """Every daal_* launcher equivalent dispatches and prints a result."""
    from harp_tpu.models import stats

    for algo in ("cov", "moments", "naive", "linreg", "ridge",
                 "qr", "svd", "als"):
        stats.main([algo, "--n", "512", "--d", "8"])
        assert algo.replace("qr", "tsqr").replace(
            "naive", "naive_bayes") in capsys.readouterr().out


def test_dispatch_kmeans_stream_split_glob(capsys, tmp_path):
    """--input with a glob of split files runs the per-worker file-stream
    path (the HDFS-split input shape) and prints one JSON line."""
    import json

    import numpy as np

    rng = np.random.default_rng(0)
    for i in range(3):
        np.savetxt(tmp_path / f"part_{i}.csv",
                   rng.normal(size=(50 + 20 * i, 4)).astype(np.float32),
                   fmt="%.5f", delimiter=",")
    rc = cli.main(["kmeans-stream", "--input", str(tmp_path / "part_*.csv"),
                   "--k", "3", "--iters", "2", "--chunk", "32"])
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["files"] == 3 and np.isfinite(rec["inertia"])
    # numeric schema even for split input (jsonl consumers do arithmetic)
    assert rec["n"] == 50 + 70 + 90 and rec["d"] == 4


def test_dispatch_svm_libsvm_file(capsys, tmp_path):
    """The reference's native input format trains end-to-end via the CLI
    (sparse ELL path, labels mapped from arbitrary binary values)."""
    import numpy as np

    rng = np.random.default_rng(0)
    lines = []
    for _ in range(64):
        x1, x2 = rng.normal(size=2)
        label = 2 if x1 + x2 > 0 else 1  # 1/2-labeled, as UCI files often are
        lines.append(f"{label} 1:{x1:.4f} 2:{x2:.4f}")
    p = tmp_path / "train.svm"
    p.write_text("\n".join(lines) + "\n")
    rc = cli.main(["svm", "--libsvm", str(p)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "train_acc" in out
    import json as _json

    acc = _json.loads(out.strip().splitlines()[-1])["train_acc"]
    assert acc > 0.85  # separable-ish data must actually train


def test_svm_sparse_matches_dense(mesh):
    """fit_sparse on an ELL view of dense data == fit on the dense data."""
    import numpy as np

    from harp_tpu.models.svm import SVM, SVMConfig

    rng = np.random.default_rng(1)
    n, d = 128, 8
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = np.sign(x @ rng.normal(size=d) + 1e-3).astype(np.float32)
    cfg = SVMConfig(inner_steps=50, outer_rounds=2, sv_per_worker=8)
    dense = SVM(cfg, mesh).fit(x, y)
    # every entry stored: ELL == dense data, so the models must agree
    ids = np.tile(np.arange(d, dtype=np.int32), (n, 1))
    ones = np.ones((n, d), np.float32)
    sparse = SVM(cfg, mesh).fit_sparse(ids, x, ones, y, d)
    np.testing.assert_allclose(sparse.w, dense.w, rtol=1e-4, atol=1e-6)
    assert abs(sparse.b - dense.b) < 1e-4


def test_svm_libsvm_rejects_bad_inputs(tmp_path):
    import pytest

    from harp_tpu.models import svm as S

    p = tmp_path / "zb.svm"
    p.write_text("1 0:1.0 2:2.0\n2 1:1.0\n")  # 0-based indices
    with pytest.raises(SystemExit, match="zero-based"):
        S.main(["--libsvm", str(p)])

    p2 = tmp_path / "multi.svm"
    p2.write_text("1 1:1.0\n2 1:2.0\n3 1:3.0\n")
    with pytest.raises(SystemExit, match="2 label values"):
        S.main(["--libsvm", str(p2)])


def test_dispatch_lda_ckpt_resume(capsys, tmp_path, monkeypatch):
    """LDA CLI trains with checkpoints; a rerun RESUMES (zero epochs run)."""
    from harp_tpu.models.lda import LDA

    calls = []
    orig = LDA.sample_epoch
    monkeypatch.setattr(LDA, "sample_epoch",
                        lambda self: (calls.append(1), orig(self))[1])

    args = ["lda", "--docs", "16", "--vocab", "16", "--topics", "2",
            "--tokens-per-doc", "4", "--epochs", "2",
            "--d-tile", "8", "--w-tile", "8", "--entry-cap", "16",
            "--ckpt-dir", str(tmp_path / "c")]
    assert cli.main(args) == 0
    first = capsys.readouterr().out
    assert "log_likelihood" in first
    assert len(calls) == 2  # both epochs trained

    calls.clear()
    assert cli.main(args) == 0
    second = capsys.readouterr().out
    assert len(calls) == 0  # resumed from the checkpoint: nothing re-ran
    assert first == second  # and the restored chain state is identical


def test_dispatch_kmeans_ckpt_resume_cli(capsys, tmp_path):
    """kmeans grows the driver --ckpt-dir/--ckpt-every/--resume wiring
    (PR 10): a run checkpoints in chunks; a rerun with --resume picks up
    the finished run (nothing re-runs) and reports the SAME inertia —
    and the continuation across a 'process restart' is bit-identical to
    an uninterrupted run in a fresh dir."""
    import json

    import numpy as np

    from harp_tpu.utils.checkpoint import CheckpointManager

    args = ["kmeans", "--n", "256", "--d", "8", "--k", "4", "--iters",
            "6", "--ckpt-every", "2"]
    a = str(tmp_path / "a")
    assert cli.main(args + ["--ckpt-dir", a]) == 0
    row1 = json.loads(capsys.readouterr().out.splitlines()[-1])
    assert row1["resumed_from"] is None
    mgr = CheckpointManager(a)
    assert mgr.latest_step() == 2  # 3 chunks of 2 iterations

    assert cli.main(args + ["--ckpt-dir", a, "--resume"]) == 0
    row2 = json.loads(capsys.readouterr().out.splitlines()[-1])
    assert row2["resumed_from"] == 2
    assert row2["inertia"] == row1["inertia"]
    _, s1 = mgr.restore_latest()
    assert np.asarray(s1["centroids"]).shape == (4, 8)
    assert np.isfinite(np.asarray(s1["centroids"])).all()


def test_resume_flag_contract_across_drivers(tmp_path):
    """--resume without --ckpt-dir, or against an empty dir, fails
    loudly on every driver that grew it (a mistyped dir must not
    silently retrain from epoch 0)."""
    import pytest

    for argv in (
        ["kmeans", "--resume"],
        ["mfsgd", "--resume", "--epochs", "1"],
        ["lda", "--resume", "--epochs", "1"],
    ):
        with pytest.raises(SystemExit, match="requires --ckpt-dir"):
            cli.main(argv)
    empty = str(tmp_path / "nothing-here")
    with pytest.raises(SystemExit, match="no checkpoints"):
        cli.main(["mfsgd", "--resume", "--ckpt-dir", empty,
                  "--epochs", "1"])


def test_dispatch_mfsgd_resume_cli_bit_identical(capsys, tmp_path):
    """mfsgd --resume end to end: train 2 of 4 epochs, then finish the
    run under --resume from a fresh driver; the final checkpointed
    factors are BIT-identical to one uninterrupted 4-epoch run."""
    import json

    import numpy as np

    from harp_tpu.utils.checkpoint import CheckpointManager

    base = ["mfsgd", "--users", "32", "--items", "24", "--nnz", "300",
            "--rank", "4", "--algo", "dense", "--u-tile", "8",
            "--i-tile", "8", "--entry-cap", "32", "--ckpt-every", "2"]
    a, b = str(tmp_path / "a"), str(tmp_path / "b")
    assert cli.main(base + ["--epochs", "4", "--ckpt-dir", a]) == 0
    capsys.readouterr()

    assert cli.main(base + ["--epochs", "2", "--ckpt-dir", b]) == 0
    capsys.readouterr()
    assert cli.main(base + ["--epochs", "4", "--ckpt-dir", b,
                            "--resume"]) == 0
    row = json.loads(capsys.readouterr().out.splitlines()[-1])
    assert row["resumed_from"] == 1  # epochs 0-1 were already done
    assert row["epochs_run"] == 2    # only 2-3 ran under --resume

    _, sa = CheckpointManager(a).restore_latest()
    _, sb = CheckpointManager(b).restore_latest()
    np.testing.assert_array_equal(np.asarray(sa["W"]),
                                  np.asarray(sb["W"]))
    np.testing.assert_array_equal(np.asarray(sa["H"]),
                                  np.asarray(sb["H"]))


def test_dispatch_file_inputs(capsys, tmp_path):
    """kmeans/mfsgd/lda consume input files like the Harp apps' HDFS paths."""
    import numpy as np

    rng = np.random.default_rng(0)
    # kmeans: two CSV shards via a glob
    for j in range(2):
        np.savetxt(tmp_path / f"pts{j}.csv",
                   rng.normal(size=(64, 4)).astype(np.float32), delimiter=",")
    assert cli.main(["kmeans", "--input", str(tmp_path / "pts*.csv"),
                     "--k", "2", "--iters", "2"]) == 0
    out = capsys.readouterr().out
    assert '"n": 128' in out and "inertia" in out

    # mfsgd: rating triples, dims inferred from ids
    lines = [f"{rng.integers(0, 24)} {rng.integers(0, 16)} {rng.normal():.3f}"
             for _ in range(300)]
    (tmp_path / "r.txt").write_text("\n".join(lines) + "\n")
    assert cli.main(["mfsgd", "--input", str(tmp_path / "r.txt"),
                     "--rank", "4", "--epochs", "2",
                     "--u-tile", "8", "--i-tile", "8"]) == 0
    out = capsys.readouterr().out
    assert '"nnz": 300' in out and "rmse_final" in out

    # lda: doc-word tokens with a count column (expanded)
    tok = ["0 1 2", "0 3 1", "1 2 3", "2 0 1"]
    (tmp_path / "tok.txt").write_text("\n".join(tok) + "\n")
    assert cli.main(["lda", "--input", str(tmp_path / "tok.txt"),
                     "--topics", "2", "--d-tile", "8", "--w-tile", "8",
                     "--epochs", "2",
                     "--ckpt-dir", str(tmp_path / "lc")]) == 0
    out = capsys.readouterr().out
    assert "log_likelihood" in out

    # zero matches → clear SystemExit, not a concatenate traceback
    import pytest

    with pytest.raises(SystemExit, match="no input files"):
        cli.main(["kmeans", "--input", str(tmp_path / "nope*.csv")])
    with pytest.raises(SystemExit, match="no input files"):
        cli.main(["mfsgd", "--input", str(tmp_path / "nope*.txt")])

    # an empty shard among real ones is skipped, not a concat crash
    (tmp_path / "pts_empty.csv").write_text("")
    assert cli.main(["kmeans", "--input", str(tmp_path / "pts*.csv"),
                     "--k", "2", "--iters", "1"]) == 0
    assert '"n": 128' in capsys.readouterr().out

    # rating files without a rating column are refused (a silent all-zero
    # fit would look like success)
    (tmp_path / "pairs.txt").write_text("0 1\n2 3\n")
    with pytest.raises(SystemExit, match="no rating column"):
        cli.main(["mfsgd", "--input", str(tmp_path / "pairs.txt")])

    # negative ids are refused
    (tmp_path / "neg.txt").write_text("-1 2 3.0\n0 1 1.0\n")
    with pytest.raises(SystemExit, match="negative"):
        cli.main(["mfsgd", "--input", str(tmp_path / "neg.txt")])

    # ragged rows are refused (a short row would read as a fabricated 0.0)
    (tmp_path / "ragged.txt").write_text("0 1 4.5\n2 3\n")
    with pytest.raises(SystemExit, match="disagree on column count"):
        cli.main(["mfsgd", "--input", str(tmp_path / "ragged.txt")])


def test_lda_explicit_zero_counts_dropped(capsys, tmp_path):
    """'doc word 0' means absent (dropped); bare pairs mean one token."""
    import pytest

    (tmp_path / "z.txt").write_text("0 1 2\n0 2 0\n1 0 1\n")
    assert cli.main(["lda", "--input", str(tmp_path / "z.txt"),
                     "--topics", "2", "--algo", "scatter", "--chunk", "8",
                     "--epochs", "1"]) == 0
    capsys.readouterr()

    (tmp_path / "allz.txt").write_text("0 1 0\n1 2 0\n")
    with pytest.raises(SystemExit, match="all token counts are zero"):
        cli.main(["lda", "--input", str(tmp_path / "allz.txt"),
                  "--topics", "2", "--algo", "scatter", "--chunk", "8",
                  "--epochs", "1"])


def test_triples_two_column_fallback_matches_native(tmp_path, monkeypatch):
    """Bare 'doc word' rows (no count) load identically on both paths."""
    import numpy as np

    import harp_tpu.native.datasource as ds

    p = tmp_path / "two.txt"
    p.write_text("0 1\n2 3\n")
    native = ds.load_triples(str(p))
    monkeypatch.setattr(ds, "load_native", lambda: None)
    fallback = ds.load_triples(str(p))
    for a, b in zip(native, fallback):
        np.testing.assert_allclose(a, b)
    np.testing.assert_array_equal(native[2], [0.0, 0.0])


def test_measure_all_script_smoke(tmp_path):
    """The L8 measurement script runs a subset and writes JSONL."""
    import json
    import os
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = tmp_path / "res.jsonl"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = ""  # let the script's process pick CPU via conftest-style forcing
    code = (
        "import os\n"
        "os.environ['XLA_FLAGS'] = os.environ.get('XLA_FLAGS','') + "
        "' --xla_force_host_platform_device_count=8'\n"
        "import jax\n"
        "jax.config.update('jax_platforms','cpu')\n"
        f"import sys; sys.argv = ['m','--smoke','--only','kmeans','--out',{str(out)!r}]\n"
        f"import runpy; runpy.run_path({os.path.join(root,'scripts','measure_all.py')!r},"
        " run_name='__main__')\n"
    )
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=240, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    recs = [json.loads(l) for l in out.read_text().splitlines()]
    assert recs and recs[0]["config"] == "kmeans"
    assert "iters_per_sec" in recs[0] and "error" not in recs[0]


def test_measure_all_full_mode_kwargs_bind(monkeypatch):
    """Every FULL-shape sweep config must CONSTRUCT correctly with no
    relay: the lambdas' kwargs are bound against the real benchmark
    signatures via stubs, so a typo'd/removed kwarg (or a config name
    missing from SPRINT_ORDER) fails HERE — not twenty minutes into a
    scarce TPU window.  Smoke mode only ever validates the smoke shapes;
    this is the full-mode twin."""
    import importlib.util
    import inspect
    import os

    spec = importlib.util.spec_from_file_location(
        "measure_all_bind", os.path.join(
            os.path.dirname(__file__), "..", "scripts", "measure_all.py"))
    ma = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ma)

    from harp_tpu.models import (kmeans, kmeans_stream, lda, mfsgd, mlp,
                                 rf, subgraph, svm, wdamds)
    from harp_tpu.utils import roofline

    def stubbed(mod, attr):
        sig = inspect.signature(getattr(mod, attr))

        def stub(**kw):
            sig.bind(**kw)  # TypeError on any kwarg the real fn rejects
            return {"stub": 1.0}

        monkeypatch.setattr(mod, attr, stub)

    for mod in (kmeans, lda, mfsgd, mlp, rf, subgraph, svm, wdamds):
        stubbed(mod, "benchmark")
    stubbed(kmeans_stream, "benchmark_streaming")
    from harp_tpu.serve import bench as serve_bench

    stubbed(serve_bench, "benchmark")
    stubbed(serve_bench, "benchmark_sustained")
    monkeypatch.setattr(ma, "_bench_ingest",
                        lambda smoke, quantize=None: {"stub": 1.0})
    monkeypatch.setattr(roofline, "annotate", lambda name, res: res)

    rows = list(ma.run_all(smoke=False, only=None))
    bad = [r for r in rows if "error" in r]
    assert not bad, bad  # a binding failure shows up as the error row
    assert [r["config"] for r in rows] == ma.SPRINT_ORDER

    # PR 13: the perfmodel-pruned selection binds through the same
    # machinery — the --predicted-top list is a valid --only list whose
    # full-shape lambdas construct (and stays gate-closed, so a pruned
    # sprint can still print verdicts)
    only, ranked, _ = ma.predicted_only(4, "v4_32")
    assert only and set(only) == ma.gate_closure(
        c for c, _ in ranked[:4])
    pruned = list(ma.run_all(smoke=False, only=only))
    assert [r["config"] for r in pruned] == only
    assert not [r for r in pruned if "error" in r]


def test_dispatch_bench_smoke(capsys):
    rc = cli.main(["bench", "--verbs", "allreduce", "rotate",
                   "--min-kb", "1024", "--max-mb", "1", "--reps", "2"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "allreduce" in out


def test_stats_file_inputs(capsys, tmp_path):
    """The daal_* stats launchers consume CSV/triple files like HDFS paths."""
    import numpy as np

    from harp_tpu.models import stats

    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 5)).astype(np.float32)
    np.savetxt(tmp_path / "m.csv", x, delimiter=",")
    stats.main(["pca", "--input", str(tmp_path / "m.csv")])
    assert "top5_evals" in capsys.readouterr().out

    # supervised: last column is the target
    w = rng.normal(size=4).astype(np.float32)
    xy = np.concatenate([x[:, :4], (x[:, :4] @ w)[:, None]], 1)
    np.savetxt(tmp_path / "xy.csv", xy, delimiter=",")
    stats.main(["linreg", "--input", str(tmp_path / "xy.csv")])
    out = capsys.readouterr().out
    assert "fit_rmse" in out
    import json as _json

    assert _json.loads(out.strip().splitlines()[-1])["fit_rmse"] < 1e-2

    # naive bayes with integer labels in the last column
    labels = rng.integers(0, 3, 64).astype(np.float32)
    nb = np.concatenate([np.abs(x[:, :4]), labels[:, None]], 1)
    np.savetxt(tmp_path / "nb.csv", nb, delimiter=",")
    stats.main(["naive", "--input", str(tmp_path / "nb.csv")])
    assert "train_acc" in capsys.readouterr().out

    # als reads rating triples
    (tmp_path / "r.txt").write_text(
        "\n".join(f"{rng.integers(0, 12)} {rng.integers(0, 8)} "
                  f"{rng.normal():.3f}" for _ in range(200)) + "\n")
    stats.main(["als", "--input", str(tmp_path / "r.txt")])
    assert "rmse_history" in capsys.readouterr().out

    # single-column file for a supervised algo is refused
    np.savetxt(tmp_path / "one.csv", x[:, :1], delimiter=",")
    import pytest

    with pytest.raises(SystemExit, match=">= 2 columns"):
        stats.main(["ridge", "--input", str(tmp_path / "one.csv")])


def test_stats_file_inputs_validation(tmp_path):
    import numpy as np
    import pytest

    from harp_tpu.models import stats

    (tmp_path / "neg.txt").write_text("-1 2 3.0\n0 1 1.0\n")
    with pytest.raises(SystemExit, match="negative user/item ids"):
        stats.main(["als", "--input", str(tmp_path / "neg.txt")])

    rng = np.random.default_rng(0)
    x = np.abs(rng.normal(size=(32, 3))).astype(np.float32)
    frac = np.concatenate([x, rng.normal(size=(32, 1)).astype(np.float32)], 1)
    np.savetxt(tmp_path / "frac.csv", frac, delimiter=",")
    with pytest.raises(SystemExit, match="must be integers"):
        stats.main(["naive", "--input", str(tmp_path / "frac.csv")])

    big = np.concatenate([x, np.full((32, 1), 1e6, np.float32)], 1)
    np.savetxt(tmp_path / "big.csv", big, delimiter=",")
    with pytest.raises(SystemExit, match="regression target"):
        stats.main(["naive", "--input", str(tmp_path / "big.csv")])


def test_dispatch_trace_cli_smoke(capsys, tmp_path):
    """python -m harp_tpu trace (PR 12): the committed golden 2-request
    fixture summarizes clean (exit 0) in human and JSON modes, exports
    a loadable Perfetto trace.json, and the failure exits are honest —
    1 for an incomplete trace, 2 for an unreadable file."""
    import json
    import os

    golden = os.path.join(os.path.dirname(__file__), "data",
                          "golden_trace.jsonl")
    assert cli.main(["trace", golden]) == 0
    out = capsys.readouterr().out
    assert "1 served / 1 shed / 0 failed" in out
    assert "[shed]" in out and "queue_full" in out  # the shed walkthrough

    pf = tmp_path / "trace.json"
    assert cli.main(["trace", golden, "--json",
                     "--perfetto", str(pf)]) == 0
    row = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert (row["requests"], row["served"], row["shed"]) == (2, 1, 1)
    assert row["unterminated"] == []
    assert all(k in row for k in ("backend", "date", "commit"))
    perf = json.loads(pf.read_text())
    assert perf["traceEvents"] and all(
        "ph" in e and "name" in e for e in perf["traceEvents"])
    assert any(e["ph"] == "X" for e in perf["traceEvents"])

    # incomplete trace (events with no terminal row) exits 1
    lines = [ln for ln in open(golden)
             if '"ev": "request"' not in ln or '"req": 1' not in ln]
    bad = tmp_path / "bad.jsonl"
    bad.write_text("".join(lines))
    assert cli.main(["trace", str(bad)]) == 1
    assert "unterminated" in capsys.readouterr().err

    # unreadable input exits 2
    assert cli.main(["trace", str(tmp_path / "nope.jsonl")]) == 2


def test_dispatch_timeline_cli_smoke(capsys, tmp_path):
    """python -m harp_tpu timeline (PR 18): the committed golden
    2-superstep fixture summarizes clean (exit 0) in human and JSON
    modes, exports a loadable Perfetto trace.json, and the failure
    exits are honest — 1 for an unterminated timeline, 2 for an
    unreadable file."""
    import json
    import os

    golden = os.path.join(os.path.dirname(__file__), "data",
                          "golden_steptrace.jsonl")
    assert cli.main(["timeline", golden]) == 0
    out = capsys.readouterr().out
    assert "1 run(s), 2 superstep(s)" in out
    assert "2 completed / 0 faulted / 0 rebalanced / 0 resumed" in out
    assert "[mfsgd.epochs]" in out          # the run header
    assert "flight:dispatch" in out         # a threaded spine mark

    pf = tmp_path / "trace.json"
    assert cli.main(["timeline", golden, "--json",
                     "--perfetto", str(pf)]) == 0
    row = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert (row["runs"], row["supersteps"], row["completed"]) == (1, 2, 2)
    assert row["unterminated"] == [] and row["dispatch_mismatch"] == []
    assert all(k in row for k in ("backend", "date", "commit"))
    perf = json.loads(pf.read_text())
    assert perf["traceEvents"] and all(
        "ph" in e and "name" in e for e in perf["traceEvents"])
    assert any(e["ph"] == "X" for e in perf["traceEvents"])

    # a timeline whose run row was lost (killed mid-export) exits 1
    lines = [ln for ln in open(golden) if '"ev": "run"' not in ln]
    bad = tmp_path / "bad.jsonl"
    bad.write_text("".join(lines))
    assert cli.main(["timeline", str(bad)]) == 1
    assert "unterminated" in capsys.readouterr().err

    # unreadable input exits 2
    assert cli.main(["timeline", str(tmp_path / "nope.jsonl")]) == 2


def test_dispatch_health_cli_smoke(capsys, tmp_path):
    """python -m harp_tpu health (PR 14): the committed golden fixture
    summarizes with exit 1 (actionable findings), a healthy file exits
    0, an unreadable one exits 2, and --json emits one stamped line."""
    import json
    import os

    golden = os.path.join(os.path.dirname(__file__), "data",
                          "golden_health.jsonl")
    assert cli.main(["health", golden]) == 1  # page + warns: actionable
    out = capsys.readouterr().out
    assert "4 finding(s), 3 actionable" in out
    assert "slo_burn" in out and "skew_trigger" in out
    assert "budget_drift" in out and "evidence_regression" in out
    assert "ratio 1.72 -> 1.05" in out  # the inline rebalance plan

    assert cli.main(["health", golden, "--json"]) == 1
    row = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert row["findings"] == 4 and row["worst_severity"] == "page"
    assert all(k in row for k in ("backend", "date", "commit"))

    # a healthy file (info-only findings, no config rows) exits 0
    ok = tmp_path / "ok.jsonl"
    ok.write_text(json.dumps(
        {"kind": "health", "detector": "evidence_regression",
         "severity": "info", "config": "kmeans", "verdict": "confirmed",
         "backend": "cpu", "date": "2026-08-05",
         "commit": "x"}) + "\n")
    assert cli.main(["health", str(ok)]) == 0
    assert "no findings" not in capsys.readouterr().out  # 1 info row

    # unreadable input exits 2
    assert cli.main(["health", str(tmp_path / "nope.jsonl")]) == 2
    assert "cannot read" in capsys.readouterr().err


def test_health_cli_grades_fresh_bench_rows(capsys, tmp_path,
                                            monkeypatch):
    """The grader half: a sprint output file with a regressed fresh row
    (vs a committed incumbent in --repo) exits 1 and names the verdict;
    --no-grade-bench turns the same file healthy."""
    import json

    from harp_tpu import health

    health.monitor.reset()
    repo = tmp_path / "repo"
    repo.mkdir()
    (repo / "BENCH_local.jsonl").write_text(json.dumps(
        {"config": "rf", "trees_per_sec": 10.0, "backend": "tpu",
         "date": "2026-08-01", "commit": "abc1234"}) + "\n")
    fresh = tmp_path / "sprint.jsonl"
    fresh.write_text(json.dumps(
        {"config": "rf", "trees_per_sec": 5.0, "backend": "tpu",
         "date": "2026-08-05", "commit": "def5678"}) + "\n")
    assert cli.main(["health", str(fresh), "--repo", str(repo),
                     "--json"]) == 1
    row = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert row["graded_configs"] == 1 and row["actionable"] == 1
    health.monitor.reset()
    assert cli.main(["health", str(fresh), "--repo", str(repo),
                     "--no-grade-bench"]) == 0
    capsys.readouterr()
    health.monitor.reset()


def test_health_cli_grade_model_emits_checker_clean_row(capsys):
    """--grade-model on the real repo: the committed evidence grades
    clean (tier-1 pins perfmodel.grade ok), the CLI exits 0, and the
    one emitted kind:'health' row passes invariant 13 — the line
    measure_on_relay.sh tees into the evidence file."""
    import json
    import os
    import sys

    from harp_tpu import health

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "scripts"))
    import check_jsonl

    health.monitor.reset()
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    assert cli.main(["health", "--grade-model", "--repo", root]) == 0
    line = capsys.readouterr().out.strip().splitlines()[-1]
    row = json.loads(line)
    assert row["kind"] == "health"
    assert row["detector"] == "evidence_regression"
    assert row["verdict"] == "confirmed"
    assert check_jsonl._check_health_row("t", 1, row) == []
    health.monitor.reset()


def test_dispatch_profile_cli_smoke(capsys, monkeypatch):
    """python -m harp_tpu profile (PR 16): a real single-app capture
    emits one invariant-15-clean kind:'profile' row under --json (the
    PROFILE_attrib.jsonl regeneration path), --all iterates the frozen
    app vocabulary, an unknown app exits 2, and any unreconciled row
    exits 1."""
    import json
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "scripts"))
    import check_jsonl

    assert cli.main(["profile", "kmeans", "--json"]) == 0
    row = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert row["kind"] == "profile" and row["app"] == "kmeans"
    assert check_jsonl._check_profile_row("t", 1, row) == []

    # human rendering names the bound and the reconciliation verdict
    assert cli.main(["profile", "kmeans"]) == 0
    out = capsys.readouterr().out
    assert "bound=" in out and "[ok]" in out

    # unknown app exits 2 and lists the vocabulary; no app exits 2
    assert cli.main(["profile", "word2vec"]) == 2
    assert "unknown app" in capsys.readouterr().err
    assert cli.main(["profile"]) == 2
    capsys.readouterr()

    # --all iterates every registered app (capture stubbed so the smoke
    # stays in seconds); an unreconciled row turns exit 0 into 1
    from harp_tpu.profile import attribution

    golden = os.path.join(os.path.dirname(__file__), "data",
                          "golden_profile.jsonl")
    template = json.loads(open(golden).readline())
    calls = []

    def fake_capture(app, reps=4):
        calls.append(app)
        return dict(template, app=app)

    monkeypatch.setattr(attribution, "capture", fake_capture)
    assert cli.main(["profile", "--all", "--json"]) == 0
    lines = capsys.readouterr().out.strip().splitlines()
    assert calls == list(attribution.PROFILE_APPS)
    assert len(lines) == len(attribution.PROFILE_APPS)

    monkeypatch.setattr(
        attribution, "capture",
        lambda app, reps=4: dict(template, app=app, reconciled=False))
    assert cli.main(["profile", "kmeans"]) == 1
    assert "FAILED" in capsys.readouterr().out


def test_health_cli_grades_profile_rows(capsys, tmp_path):
    """PR-16 satellite: a fresh kind:'profile' row whose bound flipped
    vs the committed PROFILE_attrib.jsonl baseline is a warn-severity
    profile_drift finding (exit 1); the committed baseline grades
    drift-free against itself (exit 0)."""
    import json
    import os

    from harp_tpu import health

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    committed = os.path.join(root, "PROFILE_attrib.jsonl")
    health.monitor.reset()
    assert cli.main(["health", committed, "--repo", root]) == 0
    capsys.readouterr()

    rows = [json.loads(l) for l in open(committed)]
    r = next(x for x in rows if x["app"] == "lda")
    t = dict(r["terms"])
    t["mxu_s"], t["wire_s"] = t["mxu_s"] + t["wire_s"], 0.0
    drifted = tmp_path / "drifted.jsonl"
    drifted.write_text(json.dumps(dict(r, terms=t, bound="mxu")) + "\n")
    health.monitor.reset()
    assert cli.main(["health", str(drifted), "--repo", root]) == 1
    out = capsys.readouterr().out
    assert "profile_drift" in out and "FLIPPED" in out
    health.monitor.reset()


def test_elastic_cli_knobs_bind_without_executing(capsys, monkeypatch):
    """PR-15 satellite: --elastic / --max-worker-loss on the mfsgd /
    lda / kmeans-stream apps forward into the elastic fit entries.
    Each entry is stubbed with a signature-binding stub (the
    measure_all full-mode pattern), so a typo'd or removed kwarg in the
    CLI wiring fails HERE — without training anything."""
    import inspect

    import harp_tpu.elastic.apps as EA

    calls = []

    def stubbed(attr):
        real = getattr(EA, attr)
        sig = inspect.signature(real)

        class _Ad:
            losses = 0

            class mesh:
                num_workers = 8

            def metric(self):
                return 1.0

        def stub(*a, **kw):
            sig.bind(*a, **kw)  # TypeError on any rejected kwarg
            calls.append(attr)
            return _Ad()

        monkeypatch.setattr(EA, attr, stub)

    for attr in ("mfsgd_elastic_fit", "lda_elastic_fit",
                 "kmeans_stream_elastic_fit"):
        stubbed(attr)

    assert cli.main(["mfsgd", "--elastic", "--users", "32", "--items",
                     "16", "--nnz", "64", "--epochs", "1",
                     "--max-worker-loss", "1"]) == 0
    assert "mfsgd_elastic_cli" in capsys.readouterr().out
    assert cli.main(["lda", "--elastic", "--docs", "16", "--vocab",
                     "16", "--topics", "2", "--tokens-per-doc", "4",
                     "--epochs", "1"]) == 0
    assert "lda_elastic_cli" in capsys.readouterr().out
    assert cli.main(["kmeans-stream", "--elastic", "--n", "64", "--d",
                     "4", "--k", "2", "--iters", "1"]) == 0
    assert "kmeans_stream_elastic_cli" in capsys.readouterr().out
    assert calls == ["mfsgd_elastic_fit", "lda_elastic_fit",
                     "kmeans_stream_elastic_fit"]

    # --elastic refuses file inputs loudly (no silent non-elastic fit)
    import pytest

    with pytest.raises(SystemExit, match="synthetic"):
        cli.main(["mfsgd", "--elastic", "--input", "nope.txt"])


def test_elastic_cli_kmeans_stream_smoke(capsys, tmp_path):
    """One real end-to-end elastic CLI run (the cheapest app): prints a
    JSON row with the elastic fields."""
    import json

    rc = cli.main(["kmeans-stream", "--elastic", "--n", "256", "--d",
                   "4", "--k", "3", "--iters", "2",
                   "--ckpt-dir", str(tmp_path / "ck")])
    assert rc == 0
    import numpy as np

    row = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert row["config"] == "kmeans_stream_elastic_cli"
    assert row["n_workers"] == 8 and row["worker_losses"] == 0
    assert np.isfinite(row["inertia"])


def test_dispatch_memory_cli_smoke(capsys, tmp_path):
    """python -m harp_tpu memory (PR 19): the committed golden ledger
    fixture summarizes clean (exit 0) in human and JSON modes, an
    unterminated export exits 1, an unreadable file exits 2."""
    import json
    import os

    golden = os.path.join(os.path.dirname(__file__), "data",
                          "golden_memory.jsonl")
    assert cli.main(["memory", golden]) == 0
    out = capsys.readouterr().out
    assert "9 buffer event(s)" in out and "2 dispatch(es)" in out
    assert "peak HBM" in out and "headroom" in out
    assert "vmem checks 1 (1 refused)" in out    # the refusal evidence

    assert cli.main(["memory", golden, "--json"]) == 0
    row = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert row["errors"] == []
    assert row["peak_hbm_bytes"] == 1056772
    assert row["vmem_refusals"] == 1 and row["donated_bytes"] == 16384

    # an export whose summary row was lost (killed mid-write) exits 1
    lines = [ln for ln in open(golden) if '"ev": "summary"' not in ln]
    bad = tmp_path / "bad.jsonl"
    bad.write_text("".join(lines))
    assert cli.main(["memory", str(bad)]) == 1
    assert "unterminated" in capsys.readouterr().err

    # unreadable input exits 2
    assert cli.main(["memory", str(tmp_path / "nope.jsonl")]) == 2
    assert "unreadable" in capsys.readouterr().err
