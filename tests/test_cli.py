"""L8 launcher tests — unified CLI dispatcher."""

import harp_tpu.__main__ as cli


def test_list(capsys):
    assert cli.main(["--list"]) == 0
    out = capsys.readouterr().out
    for app in ("kmeans", "mfsgd", "lda", "mlp", "subgraph", "rf", "bench"):
        assert app in out


def test_unknown_app(capsys):
    assert cli.main(["nosuchapp"]) == 2
    assert "unknown app" in capsys.readouterr().err


def test_dispatch_kmeans_smoke(capsys):
    rc = cli.main(["kmeans", "--n", "512", "--d", "8", "--k", "4",
                   "--iters", "3", "--bench"])
    assert rc == 0
    assert "iters_per_sec" in capsys.readouterr().out


def test_dispatch_stats_smoke(capsys):
    rc = cli.main(["stats", "pca", "--n", "512", "--d", "8"])
    assert rc == 0
    assert "top5_evals" in capsys.readouterr().out


def test_stats_all_algos_run(capsys):
    """Every daal_* launcher equivalent dispatches and prints a result."""
    from harp_tpu.models import stats

    for algo in ("cov", "moments", "naive", "linreg", "ridge",
                 "qr", "svd", "als"):
        stats.main([algo, "--n", "512", "--d", "8"])
        assert algo.replace("qr", "tsqr").replace(
            "naive", "naive_bayes") in capsys.readouterr().out


def test_dispatch_bench_smoke(capsys):
    rc = cli.main(["bench", "--verbs", "allreduce", "rotate",
                   "--min-kb", "1024", "--max-mb", "1", "--reps", "2"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "allreduce" in out
