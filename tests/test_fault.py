"""Fault-injection / recovery / sanitizer tests (SURVEY.md §6)."""

import jax.numpy as jnp
import numpy as np
import pytest

from harp_tpu.utils.checkpoint import CheckpointManager
from harp_tpu.utils.fault import FaultInjector, WorkerFailure, run_with_recovery
from harp_tpu.utils.check import assert_finite, checked_jit


def _driver(tmp_path, fail_at=(), max_restarts=3, n_iters=20, ckpt_every=4):
    ckpt = CheckpointManager(str(tmp_path / "ckpt"))
    trace = []

    def step(i, state):
        trace.append(i)
        return {"acc": state["acc"] + jnp.float32(i)}

    state = run_with_recovery(
        lambda: {"acc": jnp.float32(0.0)}, step, n_iters, ckpt,
        ckpt_every=ckpt_every, max_restarts=max_restarts,
        fault=FaultInjector(fail_at))
    return state, trace


def test_recovery_clean_run(tmp_path):
    state, trace = _driver(tmp_path)
    assert trace == list(range(20))
    assert float(state["acc"]) == sum(range(20))


def test_recovery_resumes_from_checkpoint(tmp_path):
    state, trace = _driver(tmp_path, fail_at=(10,))
    # failed at 10 → restart from ckpt at step 7 (every 4 → steps 3, 7)
    assert trace[:11] == list(range(10)) + [8]
    assert float(state["acc"]) == sum(range(20))  # exact despite replay


def test_recovery_restart_from_scratch_before_first_ckpt(tmp_path):
    state, trace = _driver(tmp_path, fail_at=(2,))
    assert trace[:3] == [0, 1, 0]  # no checkpoint yet → iteration 0
    assert float(state["acc"]) == sum(range(20))


def test_recovery_gives_up(tmp_path):
    with pytest.raises(WorkerFailure):
        _driver(tmp_path, fail_at=(5, 6, 7, 8), max_restarts=2)


def test_fault_injector_fires_once():
    fi = FaultInjector(fail_at=(3,))
    with pytest.raises(WorkerFailure):
        fi.check(3)
    fi.check(3)  # transient: second pass over the same iteration is clean
    assert fi.fired == [3]


def test_checked_jit_clean():
    fn = checked_jit(lambda x: jnp.sqrt(x).sum())
    assert float(fn(jnp.ones(4))) == 4.0


def test_checked_jit_catches_nan():
    fn = checked_jit(lambda x: jnp.log(x) / x)
    with pytest.raises(Exception, match="nan"):
        fn(jnp.float32(-1.0))


def test_checked_jit_catches_oob():
    fn = checked_jit(lambda x, i: x[i])
    with pytest.raises(Exception, match="out-of-bounds|index"):
        fn(jnp.arange(4.0), jnp.int32(9))


def test_assert_finite_user_check():
    def prog(x):
        assert_finite({"x": x}, "model")
        return x * 2

    fn = checked_jit(prog)
    np.testing.assert_allclose(np.asarray(fn(jnp.ones(3))), 2 * np.ones(3))
    with pytest.raises(Exception, match="model"):
        fn(jnp.array([1.0, jnp.inf, 3.0]))
