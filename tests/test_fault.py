"""Fault-injection / recovery / sanitizer tests (SURVEY.md §6).

PR 10 adds the fault plane proper: the seeded site-schedule injector on
the flightrec observer hooks (deterministic chaos), the crash-atomic
checkpoint layout with damaged-checkpoint fallback, and the pinned
kill/resume contract — an injector-killed epoch loop, restarted from the
latest checkpoint, reproduces the uninterrupted run's final params
bit-identically.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from harp_tpu.utils import flightrec, telemetry
from harp_tpu.utils.checkpoint import CheckpointManager
from harp_tpu.utils.fault import (FaultInjector, InjectedFault,
                                  WorkerFailure, resolve_resume,
                                  run_with_recovery)
from harp_tpu.utils.check import assert_finite, checked_jit


def _driver(tmp_path, fail_at=(), max_restarts=3, n_iters=20, ckpt_every=4):
    ckpt = CheckpointManager(str(tmp_path / "ckpt"))
    trace = []

    def step(i, state):
        trace.append(i)
        return {"acc": state["acc"] + jnp.float32(i)}

    state = run_with_recovery(
        lambda: {"acc": jnp.float32(0.0)}, step, n_iters, ckpt,
        ckpt_every=ckpt_every, max_restarts=max_restarts,
        fault=FaultInjector(fail_at))
    return state, trace


def test_recovery_clean_run(tmp_path):
    state, trace = _driver(tmp_path)
    assert trace == list(range(20))
    assert float(state["acc"]) == sum(range(20))


def test_recovery_resumes_from_checkpoint(tmp_path):
    state, trace = _driver(tmp_path, fail_at=(10,))
    # failed at 10 → restart from ckpt at step 7 (every 4 → steps 3, 7)
    assert trace[:11] == list(range(10)) + [8]
    assert float(state["acc"]) == sum(range(20))  # exact despite replay


def test_recovery_restart_from_scratch_before_first_ckpt(tmp_path):
    state, trace = _driver(tmp_path, fail_at=(2,))
    assert trace[:3] == [0, 1, 0]  # no checkpoint yet → iteration 0
    assert float(state["acc"]) == sum(range(20))


def test_recovery_gives_up(tmp_path):
    with pytest.raises(WorkerFailure):
        _driver(tmp_path, fail_at=(5, 6, 7, 8), max_restarts=2)


@pytest.mark.parametrize("algo", ["dense", "scatter"])
def test_mfsgd_fit_checkpoint_resume(mesh, tmp_path, algo):
    """The MF-SGD driver survives an injected crash and a process 'restart'
    — for BOTH update algos (recovery interacts with each epoch fn)."""
    from harp_tpu.models import mfsgd as MF

    rng = np.random.default_rng(0)
    nnz = 400
    u = rng.integers(0, 32, nnz).astype(np.int32)
    i = rng.integers(0, 24, nnz).astype(np.int32)
    v = rng.normal(size=nnz).astype(np.float32)

    def make_model():
        m = MF.MFSGD(32, 24, MF.MFSGDConfig(rank=4, algo=algo, chunk=64,
                                            u_tile=8, i_tile=8, entry_cap=32),
                     mesh=mesh)
        m.set_ratings(u, i, v)
        return m

    ckpt = str(tmp_path / "mf")
    # crash at epoch 3 (after the epoch-2 checkpoint with ckpt_every=2):
    # recovery restarts in-process and completes all 6 epochs
    model = make_model()
    rmses = model.fit(6, ckpt, ckpt_every=2, fault=FaultInjector(fail_at=(3,)))
    assert len(rmses) >= 6  # all epochs ran (pre-crash ones included)
    mgr = CheckpointManager(ckpt)
    assert mgr.latest_step() == 5

    # a fresh driver pointing at the same dir resumes, not restarts —
    # and must INSTALL the restored factors even though no epoch runs
    model2 = make_model()
    more = model2.fit(6, ckpt, ckpt_every=2)
    assert more == []  # epochs 0..5 already done — nothing to run
    np.testing.assert_allclose(np.asarray(model2.W), np.asarray(model.W),
                               rtol=1e-6)

    # crash BEFORE the first checkpoint: recovery must restart from the
    # initial factors, not the crash-time ones (no double-applied epochs)
    model3 = make_model()
    w_init = np.asarray(model3.W).copy()
    clean = make_model()  # same seed → same init
    clean_rmses = clean.fit(3)
    ckpt2 = str(tmp_path / "mf2")
    rmses3 = model3.fit(3, ckpt2, ckpt_every=100,
                        fault=FaultInjector(fail_at=(2,)))
    np.testing.assert_allclose(np.asarray(model3.W), np.asarray(clean.W),
                               rtol=1e-5)
    assert not np.allclose(np.asarray(model3.W), w_init)  # it did train
    # crash at epoch 2 → epochs 0,1 ran, then the full clean trajectory
    # replays from the entry snapshot: the tail must match the clean run
    np.testing.assert_allclose(rmses3[-3:], clean_rmses, rtol=1e-5)
    np.testing.assert_allclose(rmses3[:2], clean_rmses[:2], rtol=1e-5)

    # fault injection without a checkpoint dir must refuse, not no-op
    with pytest.raises(ValueError, match="ckpt_dir"):
        make_model().fit(2, fault=FaultInjector(fail_at=(1,)))


@pytest.mark.parametrize("algo", ["dense", "scatter"])
def test_lda_fit_checkpoint_resume(mesh, tmp_path, algo):
    """LDA sampling recovers from a crash on the same chain as a clean run."""
    from harp_tpu.models import lda as L

    def make_model():
        m = L.LDA(16, 24, L.LDAConfig(n_topics=4, algo=algo, chunk=32,
                                      d_tile=8, w_tile=8, entry_cap=16),
                  mesh=mesh, seed=1)
        d, w = L.synthetic_corpus(16, 24, 2, tokens_per_doc=8, seed=1)
        m.set_tokens(d, w)
        return m

    clean = make_model()
    clean.fit(4)

    ckpt = str(tmp_path / "lda")
    model = make_model()
    model.fit(4, ckpt, ckpt_every=2, fault=FaultInjector(fail_at=(3,)))
    # keys are checkpointed, so the recovered chain == the clean chain
    np.testing.assert_array_equal(np.asarray(model.z_grid),
                                  np.asarray(clean.z_grid))
    np.testing.assert_allclose(np.asarray(model.Nwk), np.asarray(clean.Nwk))


def test_fault_injector_fires_once():
    fi = FaultInjector(fail_at=(3,))
    with pytest.raises(WorkerFailure):
        fi.check(3)
    fi.check(3)  # transient: second pass over the same iteration is clean
    assert fi.fired == [3]


# ---------------------------------------------------------------------------
# Seeded site-schedule chaos (PR 10)
# ---------------------------------------------------------------------------

def _drive_site(inj, site, n):
    """Feed ``n`` events into one site, collecting fired ordinals."""
    fired = []
    for _ in range(n):
        try:
            inj.on_event(site)
        except InjectedFault as e:
            fired.append(e.ordinal)
    return fired


def test_injector_seeded_schedule_is_reproducible():
    """Same seed + same event sequence → the same faults, exactly."""
    a = _drive_site(FaultInjector(seed=11, fail={"dispatch": 0.3}),
                    "dispatch", 50)
    b = _drive_site(FaultInjector(seed=11, fail={"dispatch": 0.3}),
                    "dispatch", 50)
    c = _drive_site(FaultInjector(seed=12, fail={"dispatch": 0.3}),
                    "dispatch", 50)
    assert a == b
    assert 0 < len(a) < 50  # a rate schedule fails some, not all
    assert a != c  # and the seed is what pins it


def test_injector_ordinal_schedule_and_counters():
    inj = FaultInjector(fail={"readback": (2, 4)})
    assert _drive_site(inj, "readback", 5) == [2, 4]
    assert inj.seen["readback"] == 5
    assert inj.injected["readback"] == 2
    assert inj.events == [("readback", 2), ("readback", 4)]
    assert inj.counters()["injected"]["dispatch"] == 0


def test_injector_max_faults_bounds_total():
    inj = FaultInjector(fail={"dispatch": 1.0}, max_faults=3)
    assert _drive_site(inj, "dispatch", 10) == [1, 2, 3]


def test_injector_delay_schedule_counts():
    inj = FaultInjector(delay={"h2d": (1,)}, delay_s=0.0)
    inj.on_event("h2d")
    inj.on_event("h2d")
    assert inj.delayed["h2d"] == 1
    assert inj.injected["h2d"] == 0  # delays never raise


def test_injector_rejects_unknown_site():
    with pytest.raises(ValueError, match="unknown fault site"):
        FaultInjector(fail={"dispacth": 0.1})


def test_injector_armed_kills_tracked_dispatch():
    """Armed on the dispatch site, the injector fails the scheduled
    tracked invocation BEFORE it launches (the wrapped fn never runs)
    and leaves the dispatch counter exact: failed attempts don't count."""
    calls = []
    fn = flightrec.track(lambda x: calls.append(x) or x + 1, "t")
    inj = FaultInjector(fail={"dispatch": (2,)})
    with telemetry.scope(True):
        with inj.arm():
            assert fn(1) == 2
            with pytest.raises(InjectedFault, match="dispatch"):
                fn(10)
            assert fn(2) == 3
        assert flightrec.transfers.dispatches == 2  # the launched ones
    assert calls == [1, 2]  # the killed attempt never reached the fn


def test_injector_ckpt_write_site_crashes_mid_save(tmp_path):
    """An injected ckpt_write fault models crash-mid-write: the save
    dies BEFORE any byte lands, so the checkpoint set on disk is exactly
    the pre-crash one (atomicity makes the crash unobservable)."""
    mgr = CheckpointManager(str(tmp_path / "c"))
    mgr.save(0, {"x": np.arange(3.0)})
    inj = FaultInjector(fail={"ckpt_write": (1,)})
    with inj.arm():
        with pytest.raises(InjectedFault, match="ckpt_write"):
            mgr.save(1, {"x": np.arange(3.0) + 1})
    assert mgr.steps() == [0]  # no partial step_1 appeared
    step, state = mgr.restore_latest()
    assert step == 0
    np.testing.assert_array_equal(state["x"], np.arange(3.0))


def test_injector_disabled_is_zero_cost(mesh):
    """The PR-3 zero-cost contract, for the injector: an armed-but-empty
    injector changes NOTHING — the traced epoch program is bit-identical
    (jaxpr equality), the numeric result identical, no observer remains
    registered afterwards, and an unarmed injector costs literally one
    falsy check (the observer lists stay empty)."""
    import jax

    import harp_tpu.models.mfsgd as MF

    def build_and_run():
        cfg = MF.MFSGDConfig(rank=4, algo="dense", u_tile=8, i_tile=8,
                             entry_cap=32)
        m = MF.MFSGD(64, 48, cfg, mesh, seed=3)
        u, i, v = MF.synthetic_ratings(64, 48, 600, rank=4, seed=3)
        m.set_ratings(u, i, v)
        rmse = m.train_epoch()
        jaxpr = str(jax.make_jaxpr(m._epoch_fn.__wrapped__)(
            m.W, m.H, *m._blocks))
        return rmse, jaxpr

    rmse_off, jaxpr_off = build_and_run()
    inj = FaultInjector(seed=0)  # no schedules: arm registers nothing
    with inj.arm():
        assert not flightrec._DISPATCH_OBSERVERS
        assert not flightrec._H2D_OBSERVERS
        assert not flightrec._CKPT_WRITE_OBSERVERS
        rmse_on, jaxpr_on = build_and_run()
    assert rmse_on == rmse_off
    assert jaxpr_on == jaxpr_off
    assert sum(inj.seen.values()) == 0
    # a SCHEDULED site registers only itself, and unregisters on exit
    with FaultInjector(fail={"dispatch": (99,)}).arm():
        assert len(flightrec._DISPATCH_OBSERVERS) == 1
        assert not flightrec._READBACK_OBSERVERS
    assert not flightrec._DISPATCH_OBSERVERS


# ---------------------------------------------------------------------------
# The pinned kill/resume contract (PR 10 acceptance)
# ---------------------------------------------------------------------------

def test_mfsgd_injector_kill_then_resume_is_bit_identical(mesh, tmp_path):
    """THE acceptance pin: a seeded FaultInjector kills the mfsgd epoch
    loop mid-run (max_restarts=0 — a process death, not an in-process
    recovery); a FRESH driver pointing at the same checkpoint dir (the
    CLI ``--resume`` path) completes the run, and the final factors are
    BIT-identical to the uninterrupted run's — not rtol-close: the
    checkpoint round trip is exact and the replayed epochs are the same
    compiled program over the same operands."""
    from harp_tpu.models import mfsgd as MF

    rng = np.random.default_rng(0)
    u = rng.integers(0, 32, 400).astype(np.int32)
    i = rng.integers(0, 24, 400).astype(np.int32)
    v = rng.normal(size=400).astype(np.float32)

    def make_model():
        m = MF.MFSGD(32, 24, MF.MFSGDConfig(rank=4, algo="dense", u_tile=8,
                                            i_tile=8, entry_cap=32),
                     mesh=mesh)
        m.set_ratings(u, i, v)
        return m

    clean = make_model()
    clean.fit(6)  # the uninterrupted reference

    ckpt = str(tmp_path / "kill")
    crashed = make_model()
    # epoch dispatches are the only tracked dispatches inside fit();
    # ordinal 4 = epoch index 3, after the ckpt_every=2 save at epoch 1
    inj = FaultInjector(seed=7, fail={"dispatch": (4,)})
    with inj.arm():
        with pytest.raises(InjectedFault, match="dispatch"):
            crashed.fit(6, ckpt, ckpt_every=2, max_restarts=0)
    assert inj.injected["dispatch"] == 1
    mgr = CheckpointManager(ckpt)
    assert mgr.latest_step() == 1  # epochs 0-1 checkpointed, 2 ran, 3 died

    resumed = make_model()  # fresh driver == restarted process
    assert resolve_resume(ckpt, True) == 1  # the CLI --resume gate
    resumed.fit(6, ckpt, ckpt_every=2)
    np.testing.assert_array_equal(np.asarray(resumed.W),
                                  np.asarray(clean.W))
    np.testing.assert_array_equal(np.asarray(resumed.H),
                                  np.asarray(clean.H))


def test_kmeans_fit_ckpt_crash_resume_bit_identical(mesh, tmp_path):
    """kmeans grows the same driver contract (PR 10): the chunked ckpt
    path resumes a killed run bit-identically to its own uninterrupted
    twin, and reports the final inertia even when the resume has no
    chunks left to run."""
    from harp_tpu.models import kmeans as KM

    rng = np.random.default_rng(5)
    pts = rng.normal(size=(128, 6)).astype(np.float32)

    c_clean, in_clean = KM.fit(pts, k=4, iters=6, mesh=mesh, seed=0,
                               ckpt_dir=str(tmp_path / "clean"),
                               ckpt_every=2)
    crashed_dir = str(tmp_path / "crash")
    with pytest.raises(WorkerFailure):
        # chunk index 2 (iterations 4-5) dies; chunks 0-1 checkpointed;
        # max_restarts=0 = the process is gone
        KM.fit(pts, k=4, iters=6, mesh=mesh, seed=0, ckpt_dir=crashed_dir,
               ckpt_every=2, max_restarts=0,
               fault=FaultInjector(fail_at=(2,)))
    assert CheckpointManager(crashed_dir).latest_step() == 1

    c_res, in_res = KM.fit(pts, k=4, iters=6, mesh=mesh, seed=0,
                           ckpt_dir=crashed_dir, ckpt_every=2)
    np.testing.assert_array_equal(c_res, c_clean)
    assert in_res == in_clean

    # resume with nothing left still reports the checkpointed inertia
    c_again, in_again = KM.fit(pts, k=4, iters=6, mesh=mesh, seed=0,
                               ckpt_dir=crashed_dir, ckpt_every=2)
    np.testing.assert_array_equal(c_again, c_clean)
    assert in_again == in_clean

    # fault without a ckpt dir is refused on this driver too
    with pytest.raises(ValueError, match="ckpt_dir"):
        KM.fit(pts, k=4, iters=2, mesh=mesh,
               fault=FaultInjector(fail_at=(1,)))


# ---------------------------------------------------------------------------
# Crash-atomic checkpoints + damaged-checkpoint fallback (satellite)
# ---------------------------------------------------------------------------

def test_checkpoint_save_is_atomic_no_tmp_left(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "c"))
    mgr.save(3, {"x": np.arange(4.0)})
    names = sorted(n for n in (tmp_path / "c").iterdir())
    assert [n.name for n in names] == ["step_000000000003"]  # no tmp.*


def test_checkpoint_truncated_newest_falls_back(tmp_path):
    """Satellite pin: damage the NEWEST checkpoint (truncate its files);
    restore_latest warns and restores the previous step instead — and
    run_with_recovery's restore(None) path rides the same fallback."""
    import shutil

    mgr = CheckpointManager(str(tmp_path / "c"))
    mgr.save(1, {"x": np.arange(3.0)})
    mgr.save(2, {"x": np.arange(3.0) + 10})
    newest = tmp_path / "c" / "step_000000000002"
    # truncate: gut the directory contents but leave the dir (the shape
    # a torn copy / partial delete leaves behind)
    for child in newest.iterdir():
        (shutil.rmtree(child) if child.is_dir() else child.unlink())
    with pytest.warns(RuntimeWarning, match="falling back"):
        step, state = mgr.restore_latest()
    assert step == 1
    np.testing.assert_array_equal(state["x"], np.arange(3.0))
    with pytest.warns(RuntimeWarning, match="falling back"):
        step2, _ = mgr.restore(None)
    assert step2 == 1


def test_checkpoint_all_damaged_raises_filenotfound(tmp_path):
    import shutil

    mgr = CheckpointManager(str(tmp_path / "c"))
    mgr.save(1, {"x": np.arange(3.0)})
    for child in (tmp_path / "c" / "step_000000000001").iterdir():
        (shutil.rmtree(child) if child.is_dir() else child.unlink())
    with pytest.warns(RuntimeWarning):
        with pytest.raises(FileNotFoundError, match="no restorable"):
            mgr.restore_latest()


def test_resolve_resume_contract(tmp_path):
    assert resolve_resume(None, False) is None
    assert resolve_resume(str(tmp_path / "x"), False) is None
    with pytest.raises(SystemExit, match="requires --ckpt-dir"):
        resolve_resume(None, True)
    empty = str(tmp_path / "empty")
    with pytest.raises(SystemExit, match="no checkpoints"):
        resolve_resume(empty, True)
    mgr = CheckpointManager(str(tmp_path / "full"))
    mgr.save(4, {"x": np.arange(2.0)})
    assert resolve_resume(str(tmp_path / "full"), True) == 4


def test_checked_jit_clean():
    fn = checked_jit(lambda x: jnp.sqrt(x).sum())
    assert float(fn(jnp.ones(4))) == 4.0


def test_checked_jit_catches_nan():
    fn = checked_jit(lambda x: jnp.log(x) / x)
    with pytest.raises(Exception, match="nan"):
        fn(jnp.float32(-1.0))


def test_checked_jit_catches_oob():
    fn = checked_jit(lambda x, i: x[i])
    with pytest.raises(Exception, match="out-of-bounds|index"):
        fn(jnp.arange(4.0), jnp.int32(9))


def test_assert_finite_user_check():
    def prog(x):
        assert_finite({"x": x}, "model")
        return x * 2

    fn = checked_jit(prog)
    np.testing.assert_allclose(np.asarray(fn(jnp.ones(3))), 2 * np.ones(3))
    with pytest.raises(Exception, match="model"):
        fn(jnp.array([1.0, jnp.inf, 3.0]))


def test_mlp_fit_ckpt_checkpoint_resume(mesh, tmp_path):
    """MLP epoch training survives an injected crash; a fresh driver
    resumes from the checkpoint with params AND optimizer state."""
    import jax

    from harp_tpu.models import mlp as M

    x, y = M.synthetic_mnist(n=256, d=16, classes=4, seed=0)

    def make():
        return M.MLPTrainer(M.MLPConfig(sizes=(16, 32, 4), lr=0.05,
                                        optimizer="momentum"), mesh, seed=0)

    ckpt = str(tmp_path / "mlp")
    t1 = make()
    hist = t1.fit_ckpt(x, y, 6, ckpt, batch_size=32, ckpt_every=2,
                       fault=FaultInjector(fail_at=(3,)))
    assert len(hist) >= 6
    mgr = CheckpointManager(ckpt)
    assert mgr.latest_step() == 5

    # fresh driver on the same dir: resumes (nothing re-runs), installs state
    t2 = make()
    more = t2.fit_ckpt(x, y, 6, ckpt, batch_size=32, ckpt_every=2)
    assert more == []
    for a, b in zip(jax.tree.leaves(t1.params), jax.tree.leaves(t2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)

    # fault injection without a checkpoint dir is refused
    import pytest

    with pytest.raises(ValueError, match="ckpt_dir"):
        make().fit_ckpt(x, y, 2, None, fault=FaultInjector(fail_at=(1,)))


def test_ccd_fit_checkpoint_resume(mesh, tmp_path):
    """CCD gets the same recovery contract as MF-SGD/LDA: crash-recovery
    reproduces the clean run, resume installs restored factors, and a
    mismatched-rank checkpoint refuses."""
    from harp_tpu.models import ccd as CC
    from harp_tpu.models.mfsgd import synthetic_ratings

    u, i, v = synthetic_ratings(32, 24, 400, rank=3, seed=0)

    def make_model(rank=4):
        m = CC.CCD(32, 24, CC.CCDConfig(rank=rank), mesh, seed=0)
        m.set_ratings(u, i, v)
        return m

    clean = make_model()
    clean_rmses = clean.fit(4)
    assert clean_rmses[-1] < clean_rmses[0]

    ckpt = str(tmp_path / "ccd")
    crashed = make_model()
    rmses = crashed.fit(4, ckpt, ckpt_every=2,
                        fault=FaultInjector(fail_at=(3,)))
    assert len(rmses) >= 4
    np.testing.assert_allclose(np.asarray(crashed.W), np.asarray(clean.W),
                               rtol=1e-5, atol=1e-6)

    resumed = make_model()
    assert resumed.fit(4, ckpt, ckpt_every=2) == []  # nothing left to run
    np.testing.assert_allclose(np.asarray(resumed.H), np.asarray(crashed.H),
                               rtol=1e-6)

    with pytest.raises(ValueError, match="refusing to resume"):
        make_model(rank=8).fit(4, ckpt, ckpt_every=2)


# ---------------------------------------------------------------------------
# Permanent-fault site (PR 15)
# ---------------------------------------------------------------------------

def test_permanent_exact_ordinal_fires_once_and_reproduces():
    """The permanent schedule honors the fail= contract's exact
    1-based ordinals (the worker-loss drill pin), fires AT MOST once,
    and replays identically for the same seed + event sequence."""
    from harp_tpu.utils.fault import FaultInjector, PermanentWorkerLoss

    def run():
        inj = FaultInjector(seed=3, permanent={"dispatch": (4,)},
                            lost_worker=2)
        fired = []
        for i in range(1, 9):
            try:
                inj.on_event("dispatch")
            except PermanentWorkerLoss as e:
                fired.append((e.site, e.ordinal, e.worker))
        return fired, inj

    fired, inj = run()
    assert fired == [("dispatch", 4, 2)]  # exactly once, at ordinal 4
    assert inj.permanent_fired and inj.injected["dispatch"] == 1
    assert run()[0] == fired  # seeded reproducibility


def test_permanent_probability_spec_is_seed_reproducible():
    from harp_tpu.utils.fault import FaultInjector, PermanentWorkerLoss

    def first_fire(seed):
        inj = FaultInjector(seed=seed, permanent={"dispatch": 0.3},
                            lost_worker=1)
        for i in range(1, 64):
            try:
                inj.on_event("dispatch")
            except PermanentWorkerLoss as e:
                return e.ordinal
        return None

    a = first_fire(7)
    assert a is not None and a == first_fire(7)
    assert {first_fire(s) for s in range(5)} != {a}  # seed matters


def test_permanent_spec_validation():
    from harp_tpu.utils.fault import FaultInjector

    with pytest.raises(ValueError, match="unknown fault site"):
        FaultInjector(permanent={"nope": (1,)}, lost_worker=0)
    with pytest.raises(ValueError, match="lost_worker"):
        FaultInjector(permanent={"dispatch": (1,)})


def test_permanent_is_not_a_transient_injected_fault():
    """Serve retry layers classify InjectedFault as transient; a
    permanent loss must never match that except clause."""
    from harp_tpu.utils.fault import (InjectedFault, PermanentWorkerLoss,
                                      WorkerFailure)

    e = PermanentWorkerLoss("dispatch", 2, 5)
    assert isinstance(e, WorkerFailure)
    assert not isinstance(e, InjectedFault)
    assert e.worker == 5


def test_run_with_recovery_reraises_permanent_without_handler(tmp_path):
    """Without on_permanent, a permanent loss must NOT burn restarts in
    a same-mesh crash loop — it re-raises immediately."""
    from harp_tpu.utils.checkpoint import CheckpointManager
    from harp_tpu.utils.fault import (PermanentWorkerLoss,
                                      run_with_recovery)

    calls = []

    def step(i, state):
        calls.append(i)
        raise PermanentWorkerLoss("dispatch", i + 1, 0)

    mgr = CheckpointManager(str(tmp_path / "ck"))
    with pytest.raises(PermanentWorkerLoss):
        run_with_recovery(lambda: 0, step, 3, mgr, max_restarts=3)
    assert calls == [0]  # no retry happened


def test_run_with_recovery_on_permanent_resumes(tmp_path):
    """With a handler, the loop resumes from the latest checkpoint and
    permanent losses do not consume max_restarts."""
    from harp_tpu.utils.checkpoint import CheckpointManager
    from harp_tpu.utils.fault import (PermanentWorkerLoss,
                                      run_with_recovery)

    handled = []
    fire = {"armed": True}

    def step(i, state):
        if i == 1 and fire["armed"]:
            fire["armed"] = False
            raise PermanentWorkerLoss("dispatch", 2, 4)
        return state + 1

    mgr = CheckpointManager(str(tmp_path / "ck"))
    out = run_with_recovery(lambda: 0, step, 3, mgr, ckpt_every=1,
                            max_restarts=0,  # a plain restart would raise
                            on_permanent=handled.append)
    assert out == 3
    assert [e.worker for e in handled] == [4]
