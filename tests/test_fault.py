"""Fault-injection / recovery / sanitizer tests (SURVEY.md §6)."""

import jax.numpy as jnp
import numpy as np
import pytest

from harp_tpu.utils.checkpoint import CheckpointManager
from harp_tpu.utils.fault import FaultInjector, WorkerFailure, run_with_recovery
from harp_tpu.utils.check import assert_finite, checked_jit


def _driver(tmp_path, fail_at=(), max_restarts=3, n_iters=20, ckpt_every=4):
    ckpt = CheckpointManager(str(tmp_path / "ckpt"))
    trace = []

    def step(i, state):
        trace.append(i)
        return {"acc": state["acc"] + jnp.float32(i)}

    state = run_with_recovery(
        lambda: {"acc": jnp.float32(0.0)}, step, n_iters, ckpt,
        ckpt_every=ckpt_every, max_restarts=max_restarts,
        fault=FaultInjector(fail_at))
    return state, trace


def test_recovery_clean_run(tmp_path):
    state, trace = _driver(tmp_path)
    assert trace == list(range(20))
    assert float(state["acc"]) == sum(range(20))


def test_recovery_resumes_from_checkpoint(tmp_path):
    state, trace = _driver(tmp_path, fail_at=(10,))
    # failed at 10 → restart from ckpt at step 7 (every 4 → steps 3, 7)
    assert trace[:11] == list(range(10)) + [8]
    assert float(state["acc"]) == sum(range(20))  # exact despite replay


def test_recovery_restart_from_scratch_before_first_ckpt(tmp_path):
    state, trace = _driver(tmp_path, fail_at=(2,))
    assert trace[:3] == [0, 1, 0]  # no checkpoint yet → iteration 0
    assert float(state["acc"]) == sum(range(20))


def test_recovery_gives_up(tmp_path):
    with pytest.raises(WorkerFailure):
        _driver(tmp_path, fail_at=(5, 6, 7, 8), max_restarts=2)


@pytest.mark.parametrize("algo", ["dense", "scatter"])
def test_mfsgd_fit_checkpoint_resume(mesh, tmp_path, algo):
    """The MF-SGD driver survives an injected crash and a process 'restart'
    — for BOTH update algos (recovery interacts with each epoch fn)."""
    from harp_tpu.models import mfsgd as MF

    rng = np.random.default_rng(0)
    nnz = 400
    u = rng.integers(0, 32, nnz).astype(np.int32)
    i = rng.integers(0, 24, nnz).astype(np.int32)
    v = rng.normal(size=nnz).astype(np.float32)

    def make_model():
        m = MF.MFSGD(32, 24, MF.MFSGDConfig(rank=4, algo=algo, chunk=64,
                                            u_tile=8, i_tile=8, entry_cap=32),
                     mesh=mesh)
        m.set_ratings(u, i, v)
        return m

    ckpt = str(tmp_path / "mf")
    # crash at epoch 3 (after the epoch-2 checkpoint with ckpt_every=2):
    # recovery restarts in-process and completes all 6 epochs
    model = make_model()
    rmses = model.fit(6, ckpt, ckpt_every=2, fault=FaultInjector(fail_at=(3,)))
    assert len(rmses) >= 6  # all epochs ran (pre-crash ones included)
    mgr = CheckpointManager(ckpt)
    assert mgr.latest_step() == 5

    # a fresh driver pointing at the same dir resumes, not restarts —
    # and must INSTALL the restored factors even though no epoch runs
    model2 = make_model()
    more = model2.fit(6, ckpt, ckpt_every=2)
    assert more == []  # epochs 0..5 already done — nothing to run
    np.testing.assert_allclose(np.asarray(model2.W), np.asarray(model.W),
                               rtol=1e-6)

    # crash BEFORE the first checkpoint: recovery must restart from the
    # initial factors, not the crash-time ones (no double-applied epochs)
    model3 = make_model()
    w_init = np.asarray(model3.W).copy()
    clean = make_model()  # same seed → same init
    clean_rmses = clean.fit(3)
    ckpt2 = str(tmp_path / "mf2")
    rmses3 = model3.fit(3, ckpt2, ckpt_every=100,
                        fault=FaultInjector(fail_at=(2,)))
    np.testing.assert_allclose(np.asarray(model3.W), np.asarray(clean.W),
                               rtol=1e-5)
    assert not np.allclose(np.asarray(model3.W), w_init)  # it did train
    # crash at epoch 2 → epochs 0,1 ran, then the full clean trajectory
    # replays from the entry snapshot: the tail must match the clean run
    np.testing.assert_allclose(rmses3[-3:], clean_rmses, rtol=1e-5)
    np.testing.assert_allclose(rmses3[:2], clean_rmses[:2], rtol=1e-5)

    # fault injection without a checkpoint dir must refuse, not no-op
    with pytest.raises(ValueError, match="ckpt_dir"):
        make_model().fit(2, fault=FaultInjector(fail_at=(1,)))


@pytest.mark.parametrize("algo", ["dense", "scatter"])
def test_lda_fit_checkpoint_resume(mesh, tmp_path, algo):
    """LDA sampling recovers from a crash on the same chain as a clean run."""
    from harp_tpu.models import lda as L

    def make_model():
        m = L.LDA(16, 24, L.LDAConfig(n_topics=4, algo=algo, chunk=32,
                                      d_tile=8, w_tile=8, entry_cap=16),
                  mesh=mesh, seed=1)
        d, w = L.synthetic_corpus(16, 24, 2, tokens_per_doc=8, seed=1)
        m.set_tokens(d, w)
        return m

    clean = make_model()
    clean.fit(4)

    ckpt = str(tmp_path / "lda")
    model = make_model()
    model.fit(4, ckpt, ckpt_every=2, fault=FaultInjector(fail_at=(3,)))
    # keys are checkpointed, so the recovered chain == the clean chain
    np.testing.assert_array_equal(np.asarray(model.z_grid),
                                  np.asarray(clean.z_grid))
    np.testing.assert_allclose(np.asarray(model.Nwk), np.asarray(clean.Nwk))


def test_fault_injector_fires_once():
    fi = FaultInjector(fail_at=(3,))
    with pytest.raises(WorkerFailure):
        fi.check(3)
    fi.check(3)  # transient: second pass over the same iteration is clean
    assert fi.fired == [3]


def test_checked_jit_clean():
    fn = checked_jit(lambda x: jnp.sqrt(x).sum())
    assert float(fn(jnp.ones(4))) == 4.0


def test_checked_jit_catches_nan():
    fn = checked_jit(lambda x: jnp.log(x) / x)
    with pytest.raises(Exception, match="nan"):
        fn(jnp.float32(-1.0))


def test_checked_jit_catches_oob():
    fn = checked_jit(lambda x, i: x[i])
    with pytest.raises(Exception, match="out-of-bounds|index"):
        fn(jnp.arange(4.0), jnp.int32(9))


def test_assert_finite_user_check():
    def prog(x):
        assert_finite({"x": x}, "model")
        return x * 2

    fn = checked_jit(prog)
    np.testing.assert_allclose(np.asarray(fn(jnp.ones(3))), 2 * np.ones(3))
    with pytest.raises(Exception, match="model"):
        fn(jnp.array([1.0, jnp.inf, 3.0]))


def test_mlp_fit_ckpt_checkpoint_resume(mesh, tmp_path):
    """MLP epoch training survives an injected crash; a fresh driver
    resumes from the checkpoint with params AND optimizer state."""
    import jax

    from harp_tpu.models import mlp as M

    x, y = M.synthetic_mnist(n=256, d=16, classes=4, seed=0)

    def make():
        return M.MLPTrainer(M.MLPConfig(sizes=(16, 32, 4), lr=0.05,
                                        optimizer="momentum"), mesh, seed=0)

    ckpt = str(tmp_path / "mlp")
    t1 = make()
    hist = t1.fit_ckpt(x, y, 6, ckpt, batch_size=32, ckpt_every=2,
                       fault=FaultInjector(fail_at=(3,)))
    assert len(hist) >= 6
    mgr = CheckpointManager(ckpt)
    assert mgr.latest_step() == 5

    # fresh driver on the same dir: resumes (nothing re-runs), installs state
    t2 = make()
    more = t2.fit_ckpt(x, y, 6, ckpt, batch_size=32, ckpt_every=2)
    assert more == []
    for a, b in zip(jax.tree.leaves(t1.params), jax.tree.leaves(t2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)

    # fault injection without a checkpoint dir is refused
    import pytest

    with pytest.raises(ValueError, match="ckpt_dir"):
        make().fit_ckpt(x, y, 2, None, fault=FaultInjector(fail_at=(1,)))


def test_ccd_fit_checkpoint_resume(mesh, tmp_path):
    """CCD gets the same recovery contract as MF-SGD/LDA: crash-recovery
    reproduces the clean run, resume installs restored factors, and a
    mismatched-rank checkpoint refuses."""
    from harp_tpu.models import ccd as CC
    from harp_tpu.models.mfsgd import synthetic_ratings

    u, i, v = synthetic_ratings(32, 24, 400, rank=3, seed=0)

    def make_model(rank=4):
        m = CC.CCD(32, 24, CC.CCDConfig(rank=rank), mesh, seed=0)
        m.set_ratings(u, i, v)
        return m

    clean = make_model()
    clean_rmses = clean.fit(4)
    assert clean_rmses[-1] < clean_rmses[0]

    ckpt = str(tmp_path / "ccd")
    crashed = make_model()
    rmses = crashed.fit(4, ckpt, ckpt_every=2,
                        fault=FaultInjector(fail_at=(3,)))
    assert len(rmses) >= 4
    np.testing.assert_allclose(np.asarray(crashed.W), np.asarray(clean.W),
                               rtol=1e-5, atol=1e-6)

    resumed = make_model()
    assert resumed.fit(4, ckpt, ckpt_every=2) == []  # nothing left to run
    np.testing.assert_allclose(np.asarray(resumed.H), np.asarray(crashed.H),
                               rtol=1e-6)

    with pytest.raises(ValueError, match="refusing to resume"):
        make_model(rank=8).fit(4, ckpt, ckpt_every=2)
